"""Numerics observatory (monitor/numerics.py): streaming per-op tensor
statistics behind PADDLE_TPU_NUMERICS, the chunk-sampling cadence, EMA
drift early warnings, calibration tables, the sentinel drift rule, the
int8 KV page path, and the flight/run-ledger embeds."""

import json
import math
import os
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.monitor import device as dev
from paddle_tpu.monitor import metrics as mx
from paddle_tpu.monitor import numerics as num


@pytest.fixture(autouse=True)
def _fresh_numerics():
    num.reset()
    yield
    num.reset()


def _scale_prog(factor=2.0):
    """data -> scale -> mean: one obviously-attributable floating op."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        h = fluid.layers.scale(x, scale=factor)
        out = fluid.layers.mean(h)
    return main, startup, out


def _label_for(op_type):
    labels = [k for k in num.snapshot() if k.endswith(":" + op_type)]
    assert len(labels) == 1, (op_type, sorted(num.snapshot()))
    return labels[0]


# -- env knob parsing ---------------------------------------------------------

def test_stats_level_parsing(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_NUMERICS", raising=False)
    assert num.stats_level() == 0
    for raw, want in (("0", 0), ("1", 1), ("2", 2), ("7", 2), ("-3", 0),
                      ("true", 1), ("junk", 0)):
        monkeypatch.setenv("PADDLE_TPU_NUMERICS", raw)
        assert num.stats_level() == want, raw


def test_stats_every_parsing(monkeypatch):
    monkeypatch.delenv(num.EVERY_ENV_KEY, raising=False)
    assert num.stats_every() == num.DEFAULT_EVERY
    for raw, want in (("1", 1), ("0", 1), ("-2", 1), ("7", 7),
                      ("junk", num.DEFAULT_EVERY)):
        monkeypatch.setenv(num.EVERY_ENV_KEY, raw)
        assert num.stats_every() == want, raw


# -- level 0: the off path ----------------------------------------------------

def test_level0_bit_identity(monkeypatch):
    """Arming then disarming the observatory must leave the computation
    bit-identical — off/armed plans live side by side in the plan cache
    (stats joins the plan key), so disarming never reuses an armed step."""
    main, startup, out = _scale_prog()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x = np.linspace(-1, 1, 8).astype("float32").reshape(2, 4)
    monkeypatch.delenv("PADDLE_TPU_NUMERICS", raising=False)
    r_unset, = exe.run(main, feed={"x": x}, fetch_list=[out])
    assert not num.snapshot(), "level 0 folded stats"
    monkeypatch.setenv("PADDLE_TPU_NUMERICS", "1")
    monkeypatch.setenv(num.EVERY_ENV_KEY, "1")
    r_armed, = exe.run(main, feed={"x": x}, fetch_list=[out])
    assert num.snapshot(), "armed run folded no stats"
    monkeypatch.setenv("PADDLE_TPU_NUMERICS", "0")
    r_off, = exe.run(main, feed={"x": x}, fetch_list=[out])
    assert np.asarray(r_unset).tobytes() == np.asarray(r_off).tobytes()
    np.testing.assert_allclose(np.asarray(r_unset), np.asarray(r_armed),
                               rtol=1e-6)


# -- armed stats: parity against numpy ---------------------------------------

def test_armed_stats_match_numpy(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_NUMERICS", "1")
    monkeypatch.setenv(num.EVERY_ENV_KEY, "1")
    main, startup, out = _scale_prog(factor=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x = np.array([[0.0, -1.5, 0.25, 3.0],
                  [2.0, 0.0, -0.5, 1.0]], "float32")
    exe.run(main, feed={"x": x}, fetch_list=[out])
    ref = (2.0 * x).astype(np.float64)
    st = num.snapshot()[_label_for("scale")]
    assert st["count"] == ref.size
    np.testing.assert_allclose(st["absmax"], np.abs(ref).max(), rtol=1e-6)
    np.testing.assert_allclose(st["mean"], ref.mean(), rtol=1e-5)
    np.testing.assert_allclose(st["rms"], np.sqrt((ref ** 2).mean()),
                               rtol=1e-5)
    assert st["zero_frac"] == pytest.approx((ref == 0).mean())
    assert st["overflow_frac"] == 0.0 and st["subnormal_frac"] == 0.0
    assert st["driver"] == "run"
    # fp32 dtype ceiling rode the layout into the drift detector's hands
    assert st["dtype_max"] == pytest.approx(float(np.finfo(np.float32).max))
    # mean op: one element, |mean(2x)|
    st_mean = num.snapshot()[_label_for("mean")]
    assert st_mean["count"] == 1
    np.testing.assert_allclose(st_mean["absmax"], abs(ref.mean()), rtol=1e-5)
    # the registry mirror carries the same numbers
    snap = mx.snapshot()
    key = "numerics/%s/absmax" % _label_for("scale")
    assert snap[key]["value"] == pytest.approx(np.abs(ref).max(), rel=1e-6)


def test_near_overflow_and_zero_fractions(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_NUMERICS", "1")
    monkeypatch.setenv(num.EVERY_ENV_KEY, "1")
    main, startup, out = _scale_prog(factor=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    big = num.OVERFLOW_FRACTION * float(np.finfo(np.float32).max) * 2.0
    x = np.array([[big, 0.0, 0.0, 1.0]], "float32")
    exe.run(main, feed={"x": x}, fetch_list=[out])
    st = num.snapshot()[_label_for("scale")]
    assert st["overflow_frac"] == pytest.approx(0.25)
    assert st["zero_frac"] == pytest.approx(0.5)


# -- chunk sampling -----------------------------------------------------------

def test_chunk_sampling_every(monkeypatch):
    """PADDLE_TPU_NUMERICS_EVERY=3: chunks 0,3,6 run the stats variant —
    7 runs fold 3 chunks. run() keeps a per-program chunk counter."""
    monkeypatch.setenv("PADDLE_TPU_NUMERICS", "1")
    monkeypatch.setenv(num.EVERY_ENV_KEY, "3")
    main, startup, out = _scale_prog()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x = np.ones((2, 4), "float32")
    before = mx.counter("numerics/chunks").value
    for _ in range(7):
        exe.run(main, feed={"x": x}, fetch_list=[out])
    folded = mx.counter("numerics/chunks").value - before
    assert folded == 3, folded
    st = num.snapshot()[_label_for("scale")]
    assert st["chunks"] == 3


def test_run_steps_always_observed(monkeypatch):
    """run_steps resolves ONE plan for the whole stream, so sampling
    would freeze the decision arbitrarily — armed run_steps chunks are
    always the stats variant regardless of the cadence."""
    monkeypatch.setenv("PADDLE_TPU_NUMERICS", "1")
    monkeypatch.setenv(num.EVERY_ENV_KEY, "1000")
    main, startup, out = _scale_prog()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x = np.ones((2, 4), "float32")
    before = mx.counter("numerics/chunks").value
    feeds = iter([{"x": x}] * 4)
    exe.run_steps(main, feeds, steps=4, fetch_list=[out], fetch_every=4)
    assert mx.counter("numerics/chunks").value - before >= 1
    st = num.snapshot()[_label_for("scale")]
    assert st["driver"] == "run_steps"
    # the fused chunk folded its per-step rows into ONE chunk aggregate
    # (one EMA tick per chunk): counts sum across the 4 fused steps
    assert st["count"] == 4 * x.size


# -- stat-row algebra ---------------------------------------------------------

def test_merge_stat_rows():
    import jax.numpy as jnp

    a = jnp.asarray([4.0, 1.0, 2.0, 3.0, 0.0, 1.0, 8.0])
    b = jnp.asarray([2.0, 5.0, 2.0, 1.0, 2.0, 0.0, 8.0])
    m = np.asarray(num.merge_stat_rows(a, b))
    assert m[0] == 4.0                      # absmax: max
    np.testing.assert_allclose(m[1:], np.asarray(a)[1:] + np.asarray(b)[1:])


def test_accumulate_never_raises_on_garbage():
    num.accumulate(np.zeros((2, 3)), [])          # wrong row width
    num.accumulate("not an array", [])            # not an array
    num.accumulate(np.zeros((1, num.NUM_STATS)), [])  # placeholder row
    assert not num.snapshot()


# -- drift detection ----------------------------------------------------------

def _feed_ramp(absmaxes, fmax=1e4, label="7:scale"):
    """Drive accumulate() with synthetic single-op chunks whose absmax
    follows ``absmaxes`` — the EMA sees one tick per call."""
    for am in absmaxes:
        row = np.array([[am, am, am * am, 0.0, 0.0, 0.0, 4.0]], np.float32)
        num.accumulate(row, [(label, ("out",), fmax)])


def test_drift_warns_on_overflow_ramp():
    with warnings.catch_warnings(record=True) as got:
        warnings.simplefilter("always")
        _feed_ramp([2.0 ** k for k in range(1, 9)])  # doubling every chunk
    drift = [w for w in got if issubclass(w.category,
                                          num.NumericsDriftWarning)]
    assert drift, "no NumericsDriftWarning on a doubling absmax ramp"
    w = drift[0].message
    assert w.label == "7:scale"
    assert w.kind == "trending-toward-overflow"
    assert w.chunks_to_overflow is not None and w.chunks_to_overflow <= 8.0
    events = num.drain_drift_events()
    assert events and events[0]["op"] == "7:scale"
    assert events[0]["kind"] == "trending-toward-overflow"
    assert not num.drain_drift_events(), "drain did not clear"


def test_drift_warns_on_collapse_and_steady_is_silent():
    with warnings.catch_warnings(record=True) as got:
        warnings.simplefilter("always")
        _feed_ramp([1.0] * 8)                  # steady: silence
    assert not [w for w in got
                if issubclass(w.category, num.NumericsDriftWarning)]
    assert not num.drain_drift_events()
    with warnings.catch_warnings(record=True) as got:
        warnings.simplefilter("always")
        _feed_ramp([1.0, 1.0, 0.0])            # live range went dark
    ev = num.drain_drift_events()
    assert ev and ev[0]["kind"] == "collapsed-to-zero"


def test_drift_event_reaches_flight_ring(monkeypatch, tmp_path):
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setattr(dev, "_flight", None, raising=False)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        _feed_ramp([2.0 ** k for k in range(1, 9)])
    fr = dev.flight_recorder()
    assert fr is not None
    path = fr.dump("test")
    with open(path) as f:
        doc = json.load(f)
    evs = [e for e in doc["entries"] if e.get("event") == "numerics_drift"]
    assert evs, "drift event missing from the flight ring"
    assert evs[0]["op"] == "7:scale"
    assert evs[0]["drift_kind"] == "trending-toward-overflow"


def test_sentinel_drift_rule():
    from paddle_tpu.reliability import DivergenceSentinel

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        _feed_ramp([2.0 ** k for k in range(1, 9)])
    rows = [(np.float32(0.5),)]
    # drift disarmed: the queued event is ignored (and stays queued)
    s0 = DivergenceSentinel(drift=False)
    assert s0.check_rows(rows, []) is None
    sen = DivergenceSentinel(drift=True)
    trip = sen.check_rows(rows, [])
    assert trip is not None and trip.rule == "drift"
    assert trip.named_op == "7:scale"
    assert "trending-toward-overflow" in trip.reason
    # the drain consumed the queue: a clean chunk does not re-trip
    assert sen.check_rows(rows, []) is None


# -- calibration tables -------------------------------------------------------

def test_calibration_roundtrip_and_running_max(tmp_path):
    tbl = str(tmp_path / "calib.json")
    assert num.record_calibration("fp0", "3", "matmul", 2.0, path=tbl) == tbl
    assert num.lookup_amax("fp0", "3", "matmul", path=tbl) == 2.0
    # merge is a running max: smaller re-records don't shrink the grid
    num.record_calibration("fp0", "3", "matmul", 1.0, path=tbl)
    assert num.lookup_amax("fp0", "3", "matmul", path=tbl) == 2.0
    num.record_calibration("fp0", "3", "matmul", 8.0, path=tbl)
    assert num.lookup_amax("fp0", "3", "matmul", path=tbl) == 8.0
    assert num.lookup_scale("fp0", "3", "matmul", path=tbl) == \
        pytest.approx(8.0 / 127.0)
    # the persisted document is the parameterized tune-table format
    with open(tbl) as f:
        doc = json.load(f)
    assert doc["format"] == num.FORMAT


def test_calibration_lookups_never_raise(tmp_path):
    assert num.lookup_amax("fp0", "0", "x", path=str(tmp_path / "no.json")) \
        is None
    bad = tmp_path / "bad.json"
    bad.write_text("{ not json")
    assert num.lookup_amax("fp0", "0", "x", path=str(bad)) is None
    assert num.lookup_scale("fp0", "0", "x", path=str(bad)) is None
    assert num.kv_scale("fp0", path=str(bad)) is None


def test_level2_run_publishes_calibration(monkeypatch, tmp_path):
    tbl = str(tmp_path / "calib.json")
    monkeypatch.setenv("PADDLE_TPU_NUMERICS", "2")
    monkeypatch.setenv(num.EVERY_ENV_KEY, "1")
    monkeypatch.setenv("PADDLE_TPU_NUMERICS_TABLE", tbl)
    main, startup, out = _scale_prog(factor=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x = np.full((2, 4), 1.5, "float32")
    exe.run(main, feed={"x": x}, fetch_list=[out])
    assert os.path.exists(tbl), "level 2 run published no table"
    fp = dev.program_fingerprint(main)
    slot, _, typ = _label_for("scale").partition(":")
    assert num.lookup_amax(fp, slot, typ, path=tbl) == pytest.approx(3.0)
    assert num.lookup_scale(fp, slot, typ, path=tbl) == \
        pytest.approx(3.0 / 127.0)


def test_kv_fingerprint_and_scale_gate(tmp_path):
    tbl = str(tmp_path / "calib.json")
    fp = num.kv_fingerprint(2, 4, 16, "float32")
    assert fp == num.kv_fingerprint(2, 4, 16, "float32")   # stable
    assert fp != num.kv_fingerprint(2, 4, 32, "float32")   # geometry-keyed
    assert num.kv_scale(fp, path=tbl) is None              # uncalibrated
    num.record_calibration(fp, "kv", "k", 4.0, path=tbl)
    assert num.kv_scale(fp, path=tbl) is None              # half missing
    num.record_kv_calibration(fp, 4.0, 2.0, path=tbl)
    ks, vs = num.kv_scale(fp, path=tbl)
    assert ks == pytest.approx(4.0 / 127.0)
    assert vs == pytest.approx(2.0 / 127.0)


# -- int8 KV pages ------------------------------------------------------------

def test_int8_kv_cache_parity_and_bytes():
    from paddle_tpu.serving.kv_cache import Int8PagedKVCache, PagedKVCache

    geom = dict(n_layer=1, n_head=2, d_head=4, slots=2, max_ctx=16,
                page_size=4, num_pages=8)
    rng = np.random.RandomState(0)
    kv = rng.randn(2, 8, 2, 4).astype("float32")  # [seq,.. ] per slot
    amax = float(np.abs(kv).max())
    fp = PagedKVCache(**geom)
    i8 = Int8PagedKVCache(k_scale=amax / 127.0, v_scale=amax / 127.0, **geom)
    sf, si = fp.init_state(), i8.init_state()
    dest = fp.prompt_dest([0, 1])
    for st, ops in ((sf, fp), (si, i8)):
        st.update(ops.write_prompt(st, 0, kv[0], kv[1], dest, 8))
        st["pt"] = st["pt"].at[0].set(dest)
    kf, vf = (np.asarray(t) for t in fp.context(sf, 0))
    ki, vi = (np.asarray(t) for t in i8.context(si, 0))
    # symmetric int8 on a calibrated grid: error bounded by half a step
    step = amax / 127.0
    assert np.max(np.abs(kf - ki)) <= 0.5 * step + 1e-6
    assert np.max(np.abs(vf - vi)) <= 0.5 * step + 1e-6
    assert i8.cache_bytes(si) < fp.cache_bytes(sf) // 2
    with pytest.raises(ValueError):
        Int8PagedKVCache(k_scale=0.0, v_scale=1.0, **geom)


def test_engine_int8_gate_degrades_without_calibration(monkeypatch,
                                                       tmp_path):
    from paddle_tpu import serving
    from paddle_tpu.models import decoder_lm

    monkeypatch.setenv("PADDLE_TPU_NUMERICS_TABLE",
                       str(tmp_path / "calib.json"))
    cfg = decoder_lm.DecoderConfig(vocab_size=16, n_layer=1, d_model=8,
                                   n_head=1, max_seq=16)
    model = decoder_lm.DecoderLM(cfg, seed=0)
    eng = serving.ServingEngine(model, serving.ServingConfig(
        slots=1, page_size=8, max_seq=16, kv_dtype="int8"))
    try:
        assert eng.cache_ops.layout == "paged", \
            "uncalibrated int8 request must fall back to fp pages"
    finally:
        eng.close()
    # calibrate, and the SAME config comes up quantized
    mc = model.cfg
    num.record_kv_calibration(
        num.kv_fingerprint(mc.n_layer, mc.n_head, mc.d_head, mc.dtype),
        2.0, 2.0, path=str(tmp_path / "calib.json"))
    eng2 = serving.ServingEngine(model, serving.ServingConfig(
        slots=1, page_size=8, max_seq=16, kv_dtype="int8"))
    try:
        assert eng2.cache_ops.layout == "paged-int8"
        assert eng2.stats()["kv_dtype"] == "int8"
    finally:
        eng2.close()


# -- embeds -------------------------------------------------------------------

def test_runlog_embed(monkeypatch, tmp_path):
    from paddle_tpu.monitor import runlog

    monkeypatch.setenv("PADDLE_TPU_RUN_LEDGER", str(tmp_path / "led.jsonl"))
    monkeypatch.setenv("PADDLE_TPU_NUMERICS", "0")
    _feed_ramp([1.0])
    rec = runlog.record_run("bench", {"cfg": {"m": 1.0}})
    assert "numerics_last" not in rec, "level 0 record embedded stats"
    monkeypatch.setenv("PADDLE_TPU_NUMERICS", "1")
    rec = runlog.record_run("bench", {"cfg": {"m": 1.0}})
    assert rec["numerics_last"]["7:scale"]["absmax"] == 1.0
    on_disk = runlog.read_ledger(str(tmp_path / "led.jsonl"))
    assert "numerics_last" in on_disk[-1]


def test_flight_dump_embed(monkeypatch, tmp_path):
    monkeypatch.setenv("PADDLE_TPU_NUMERICS", "1")
    _feed_ramp([1.0])
    fr = dev.FlightRecorder(str(tmp_path))
    path = fr.dump("test")
    with open(path) as f:
        doc = json.load(f)
    assert doc["numerics_last"]["7:scale"]["absmax"] == 1.0
