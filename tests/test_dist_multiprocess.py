"""Multi-process distributed training tests without a real cluster
(reference: test_dist_base.py — trainers as localhost subprocesses,
dist losses asserted against local losses; check_with_place :216).
"""

import os
import re
import subprocess
import sys

import pytest

_RUNNER = os.path.join(os.path.dirname(__file__), "dist_runner.py")


def _launch(pid, n, port, extra_env=None, local_devices=2):
    env = dict(os.environ)
    env.pop("PYTEST_CURRENT_TEST", None)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=%d" % local_devices
    env["PADDLE_TRAINER_ID"] = str(pid)
    env["PADDLE_TRAINERS_NUM"] = str(n)
    env["PADDLE_TRAINER_ENDPOINTS"] = ",".join(
        "127.0.0.1:%d" % (port + i) for i in range(n))
    env.update(extra_env or {})
    return subprocess.Popen([sys.executable, _RUNNER], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                            text=True)


def _losses_from(out: str, pid: int):
    m = re.search(r"DIST_LOSSES:%d:([\d.,\-e]+)" % pid, out)
    assert m, "runner %d produced no losses; output:\n%s" % (pid, out)
    return [float(v) for v in m.group(1).split(",")]


def _run_cluster(n, port, extra_env=None, local_devices=2, timeout=300):
    procs = [_launch(i, n, port, extra_env, local_devices) for i in range(n)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
            assert p.returncode == 0, out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return [_losses_from(out, i) for i, out in enumerate(outs)]


def test_two_process_data_parallel_matches_single():
    # single-process reference run
    (single,) = _run_cluster(1, 23450)

    # two processes over one global mesh (reference: _run_cluster :344)
    l0, l1 = _run_cluster(2, 23460)
    assert l0 == l1, (l0, l1)  # same replicated loss on both processes

    for s, d in zip(single, l0):
        assert abs(s - d) < 1e-4, (single, l0)
    assert l0[-1] < l0[0], l0  # learnable fixed batch => loss must fall


def test_two_process_dp_tp_mesh():
    """dp×tp composed across processes: 2 procs × 2 local devices = a
    {'data': 2, 'model': 2} global mesh. Losses must be replicated across
    processes, match the single-process run, and decrease."""
    env = {"DIST_MODE": "dp_tp"}
    (single,) = _run_cluster(1, 23470, extra_env=env, local_devices=4)

    l0, l1 = _run_cluster(2, 23480, extra_env=env)
    assert l0 == l1, (l0, l1)
    for s, d in zip(single, l0):
        assert abs(s - d) < 1e-4, (single, l0)
    assert l0[-1] < l0[0], l0


def test_four_process_data_parallel():
    """4 trainers × 1 local device — the reference's 2-pserver/2-trainer
    scale, all-collective (NCCL2-mode analog)."""
    (single,) = _run_cluster(1, 23490, local_devices=4)
    ls = _run_cluster(4, 23500, local_devices=1)
    for l in ls[1:]:
        assert l == ls[0], ls
    for s, d in zip(single, ls[0]):
        assert abs(s - d) < 1e-4, (single, ls[0])
    assert ls[0][-1] < ls[0][0], ls[0]
