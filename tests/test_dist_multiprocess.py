"""Multi-process distributed training tests without a real cluster
(reference: test_dist_base.py — trainers as localhost subprocesses,
dist losses asserted against local losses; check_with_place :216).
"""

import os
import re
import subprocess
import sys

import pytest

_RUNNER = os.path.join(os.path.dirname(__file__), "dist_runner.py")


def _launch(pid, n, port, extra_env=None, local_devices=2):
    env = dict(os.environ)
    env.pop("PYTEST_CURRENT_TEST", None)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=%d" % local_devices
    env["PADDLE_TRAINER_ID"] = str(pid)
    env["PADDLE_TRAINERS_NUM"] = str(n)
    env["PADDLE_TRAINER_ENDPOINTS"] = ",".join(
        "127.0.0.1:%d" % (port + i) for i in range(n))
    env.update(extra_env or {})
    return subprocess.Popen([sys.executable, _RUNNER], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                            text=True)


def _losses_from(out: str, pid: int):
    m = re.search(r"DIST_LOSSES:%d:([\d.,\-e]+)" % pid, out)
    assert m, "runner %d produced no losses; output:\n%s" % (pid, out)
    return [float(v) for v in m.group(1).split(",")]


def _run_cluster(n, port, extra_env=None, local_devices=2, timeout=300):
    procs = [_launch(i, n, port, extra_env, local_devices) for i in range(n)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
            assert p.returncode == 0, out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return [_losses_from(out, i) for i, out in enumerate(outs)]


def test_two_process_data_parallel_matches_single():
    # single-process reference run
    (single,) = _run_cluster(1, 23450)

    # two processes over one global mesh (reference: _run_cluster :344)
    l0, l1 = _run_cluster(2, 23460)
    assert l0 == l1, (l0, l1)  # same replicated loss on both processes

    for s, d in zip(single, l0):
        assert abs(s - d) < 1e-4, (single, l0)
    assert l0[-1] < l0[0], l0  # learnable fixed batch => loss must fall


def test_two_process_dp_tp_mesh():
    """dp×tp composed across processes: 2 procs × 2 local devices = a
    {'data': 2, 'model': 2} global mesh. Losses must be replicated across
    processes, match the single-process run, and decrease."""
    env = {"DIST_MODE": "dp_tp"}
    (single,) = _run_cluster(1, 23470, extra_env=env, local_devices=4)

    l0, l1 = _run_cluster(2, 23480, extra_env=env)
    assert l0 == l1, (l0, l1)
    for s, d in zip(single, l0):
        assert abs(s - d) < 1e-4, (single, l0)
    assert l0[-1] < l0[0], l0


def test_four_process_data_parallel():
    """4 trainers × 1 local device — the reference's 2-pserver/2-trainer
    scale, all-collective (NCCL2-mode analog)."""
    (single,) = _run_cluster(1, 23490, local_devices=4)
    ls = _run_cluster(4, 23500, local_devices=1)
    for l in ls[1:]:
        assert l == ls[0], ls
    for s, d in zip(single, ls[0]):
        assert abs(s - d) < 1e-4, (single, ls[0])
    assert ls[0][-1] < ls[0][0], ls[0]


# -- the multi-process crash drill (ISSUE 7) ----------------------------------

def _crash_cluster(n, ckpt_dir, hb_dir, extra=None, timeout=120):
    """Launch an n-rank crash-mode cluster; returns [(returncode, out)]."""
    env = {"DIST_MODE": "crash", "DIST_STEPS": "6", "DIST_HB_TIMEOUT": "4",
           "DIST_CKPT_DIR": ckpt_dir, "DIST_HB_DIR": hb_dir}
    env.update(extra or {})
    procs = [_launch(i, n, 23510, env, local_devices=1) for i in range(n)]
    results = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            results.append((p.returncode, out))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return results


def _step_losses(out, pid):
    """{global step: loss-bits-hex} from a rank's DIST_STEP lines."""
    return {int(s): h for p, s, h in re.findall(
        r"DIST_STEP:(\d+):(\d+):([0-9a-f]{8})", out) if int(p) == pid}


def test_crash_drill_kill_one_trainer_then_resume(tmp_path):
    """Kill rank 0 (the checkpointer) mid-run with SIGKILL: the survivor
    must exit with a clean DIST_PEER_LOST diagnostic and the marked
    EXIT_PEER_LOST code instead of hanging; a restart-all must resume from
    the last published checkpoint and reproduce the uninterrupted loss
    trajectory bit-for-bit."""
    # uninterrupted reference: both ranks complete and agree per step
    ref = _crash_cluster(2, str(tmp_path / "ref_ck"), str(tmp_path / "ref_hb"))
    assert [rc for rc, _ in ref] == [0, 0], ref
    ref_losses = _step_losses(ref[0][1], 0)
    assert sorted(ref_losses) == list(range(6)), ref_losses
    assert ref_losses == _step_losses(ref[1][1], 1), "replication parity"

    # crashed run: rank 0 SIGKILLs itself before step 3
    ck = str(tmp_path / "ck")
    crashed = _crash_cluster(
        2, ck, str(tmp_path / "hb1"),
        extra={"DIST_KILL_RANK": "0", "DIST_KILL_AT_STEP": "3"})
    rc0, out0 = crashed[0]
    rc1, out1 = crashed[1]
    assert rc0 == -9, (rc0, out0)  # hard kill, no cleanup
    # the survivor exits with the marked code + diagnostic, not a hang
    assert rc1 == 43, (rc1, out1)
    assert "DIST_PEER_LOST:rank=1:lost=0" in out1, out1
    surv = _step_losses(out1, 1)
    assert all(surv[s] == ref_losses[s] for s in surv), (surv, ref_losses)
    # rank 0 published checkpoints for steps 1..3 before dying
    assert _step_losses(out0, 0) == {s: ref_losses[s] for s in range(3)}

    # restart-all: resume from the last published serial (step 3), finish,
    # and match the uninterrupted trajectory bit-for-bit
    resumed = _crash_cluster(2, ck, str(tmp_path / "hb2"),
                             extra={"DIST_RESUME": "1"})
    assert [rc for rc, _ in resumed] == [0, 0], resumed
    for pid, (_, out) in enumerate(resumed):
        assert ("DIST_RESUMED:%d:3" % pid) in out, out
        got = _step_losses(out, pid)
        assert sorted(got) == [3, 4, 5], got
        assert got == {s: ref_losses[s] for s in (3, 4, 5)}, \
            "resumed trajectory diverged from the uninterrupted run"
