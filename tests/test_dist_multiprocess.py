"""Multi-process distributed training test without a real cluster
(reference: test_dist_base.py — 2 trainers as localhost subprocesses,
dist losses asserted against local losses).
"""

import os
import re
import subprocess
import sys

import pytest

_RUNNER = os.path.join(os.path.dirname(__file__), "dist_runner.py")


def _launch(pid, n, port, extra_env=None):
    env = dict(os.environ)
    env.pop("PYTEST_CURRENT_TEST", None)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PADDLE_TRAINER_ID"] = str(pid)
    env["PADDLE_TRAINERS_NUM"] = str(n)
    env["PADDLE_TRAINER_ENDPOINTS"] = ",".join(
        "127.0.0.1:%d" % (port + i) for i in range(n))
    env.update(extra_env or {})
    return subprocess.Popen([sys.executable, _RUNNER], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                            text=True)


def _losses_from(out: str, pid: int):
    m = re.search(r"DIST_LOSSES:%d:([\d.,\-e]+)" % pid, out)
    assert m, "runner %d produced no losses; output:\n%s" % (pid, out)
    return [float(v) for v in m.group(1).split(",")]


def test_two_process_data_parallel_matches_single():
    # single-process reference run
    p = _launch(0, 1, 23450)
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0, out
    single = _losses_from(out, 0)

    # two processes over one global mesh (reference: _run_cluster :344)
    p0 = _launch(0, 2, 23460)
    p1 = _launch(1, 2, 23460)
    out0, _ = p0.communicate(timeout=300)
    out1, _ = p1.communicate(timeout=300)
    assert p0.returncode == 0, out0
    assert p1.returncode == 0, out1
    l0 = _losses_from(out0, 0)
    l1 = _losses_from(out1, 1)
    assert l0 == l1, (l0, l1)  # same replicated loss on both processes

    for s, d in zip(single, l0):
        assert abs(s - d) < 1e-4, (single, l0)
    assert l0[-1] < l0[0]
