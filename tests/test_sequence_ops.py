"""Sequence op tests over padded+Length representation (mirrors the
reference's sequence_ops/ test files: test_sequence_pool.py,
test_sequence_reverse.py, test_sequence_softmax_op.py, ...)."""

import numpy as np
import pytest

from paddle_tpu.testing import check_output, run_op


@pytest.fixture
def r():
    return np.random.RandomState(3)


def test_sequence_mask():
    length = np.array([2, 0, 3], "int64")
    want = np.array([[1, 1, 0], [0, 0, 0], [1, 1, 1]], "float32")
    check_output("sequence_mask", {"X": length}, {"Y": want},
                 attrs={"maxlen": 3, "out_dtype": "float32"})


def test_sequence_pool_all_types(r):
    x = r.randn(3, 4, 2).astype("float32")
    length = np.array([2, 4, 1], "int64")
    m = (np.arange(4)[None, :] < length[:, None]).astype("float32")[..., None]
    xm = x * m
    check_output("sequence_pool", {"X": x, "Length": length},
                 {"Out": xm.sum(1)}, attrs={"pooltype": "sum"}, atol=1e-5)
    check_output("sequence_pool", {"X": x, "Length": length},
                 {"Out": xm.sum(1) / length[:, None]},
                 attrs={"pooltype": "average"}, atol=1e-5)
    check_output("sequence_pool", {"X": x, "Length": length},
                 {"Out": xm.sum(1) / np.sqrt(length[:, None])},
                 attrs={"pooltype": "sqrt"}, atol=1e-5)
    want_max = np.where(m > 0, x, -np.inf).max(1)
    check_output("sequence_pool", {"X": x, "Length": length},
                 {"Out": want_max}, attrs={"pooltype": "max"}, atol=1e-5)
    want_last = x[np.arange(3), length - 1]
    check_output("sequence_pool", {"X": x, "Length": length},
                 {"Out": want_last}, attrs={"pooltype": "last"}, atol=1e-6)
    check_output("sequence_pool", {"X": x, "Length": length},
                 {"Out": x[:, 0]}, attrs={"pooltype": "first"}, atol=1e-6)


def test_sequence_softmax_masks_padding(r):
    x = r.randn(2, 4).astype("float32")
    length = np.array([3, 2], "int64")
    out = np.asarray(run_op("sequence_softmax", {"X": x, "Length": length}, ["Out"])["Out"])
    np.testing.assert_allclose(out.sum(1), [1.0, 1.0], atol=1e-5)
    assert out[0, 3] == 0 and out[1, 2] == 0 and out[1, 3] == 0
    e = np.exp(x[0, :3] - x[0, :3].max())
    np.testing.assert_allclose(out[0, :3], e / e.sum(), atol=1e-5)


def test_sequence_reverse(r):
    x = np.arange(12).reshape(2, 6).astype("float32")
    length = np.array([4, 6], "int64")
    out = np.asarray(run_op("sequence_reverse", {"X": x, "Length": length}, ["Y"])["Y"])
    np.testing.assert_array_equal(out[0], [3, 2, 1, 0, 4, 5])
    np.testing.assert_array_equal(out[1], [11, 10, 9, 8, 7, 6])


def test_sequence_pad_unpad(r):
    x = r.randn(2, 3, 2).astype("float32")
    length = np.array([2, 3], "int64")
    out = run_op("sequence_pad",
                 {"X": x, "Length": length, "PadValue": np.array(9.0, "float32")},
                 ["Out", "Length"], attrs={"padded_length": 5})
    got = np.asarray(out["Out"])
    assert got.shape == (2, 5, 2)
    np.testing.assert_allclose(got[0, :2], x[0, :2])
    assert (got[0, 2:] == 9.0).all() and (got[1, 3:] == 9.0).all()

    up = np.asarray(run_op("sequence_unpad", {"X": x, "Length": length}, ["Out"])["Out"])
    assert (up[0, 2:] == 0).all()
    np.testing.assert_allclose(up[1], x[1])


def test_sequence_erase_and_enumerate():
    x = np.array([[1, 2, 3, 2, 5], [2, 2, 2, 4, 0]], "int64")
    out = run_op("sequence_erase", {"X": x}, ["Out", "Length"], attrs={"tokens": [2]})
    got = np.asarray(out["Out"])
    np.testing.assert_array_equal(got[0], [1, 3, 5, 0, 0])
    np.testing.assert_array_equal(got[1], [4, 0, 0, 0, 0])
    np.testing.assert_array_equal(np.asarray(out["Length"]), [3, 2])

    e = np.asarray(run_op("sequence_enumerate", {"X": x}, ["Out"],
                          attrs={"win_size": 2, "pad_value": 0})["Out"])
    np.testing.assert_array_equal(e[0, 0], [1, 2])
    np.testing.assert_array_equal(e[0, 4], [5, 0])


def test_sequence_slice_scatter(r):
    x = np.arange(20).reshape(2, 10).astype("float32")
    out = np.asarray(run_op("sequence_slice",
                            {"X": x, "Offset": np.array([2, 5], "int64"),
                             "Length": np.array([3, 2], "int64")},
                            ["Out"], attrs={"out_maxlen": 4})["Out"])
    np.testing.assert_array_equal(out[0], [2, 3, 4, 0])
    np.testing.assert_array_equal(out[1], [15, 16, 0, 0])

    base = np.zeros((2, 5), "float32")
    ids = np.array([[1, 3], [0, 0]], "int64")
    upd = np.array([[1.0, 2.0], [5.0, 7.0]], "float32")
    got = np.asarray(run_op("sequence_scatter",
                            {"X": base, "Ids": ids, "Updates": upd}, ["Out"])["Out"])
    np.testing.assert_array_equal(got[0], [0, 1, 0, 2, 0])
    np.testing.assert_array_equal(got[1], [12, 0, 0, 0, 0])


def test_im2sequence_and_row_conv(r):
    x = r.randn(1, 2, 4, 4).astype("float32")
    out = np.asarray(run_op("im2sequence", {"X": x}, ["Out"],
                            attrs={"kernels": [2, 2], "strides": [1, 1]})["Out"])
    assert out.shape == (1, 9, 8)
    # first patch contains the 2x2 window of both channels
    patch0 = set(np.round(out[0, 0], 5).tolist())
    want0 = set(np.round(x[0, :, :2, :2].reshape(-1), 5).tolist())
    assert patch0 == want0

    seq = r.randn(2, 5, 3).astype("float32")
    w = r.randn(3, 3).astype("float32")
    got = np.asarray(run_op("row_conv", {"X": seq, "Filter": w}, ["Out"])["Out"])
    want = np.zeros_like(seq)
    for k in range(3):
        shifted = np.pad(seq, [(0, 0), (0, k), (0, 0)])[:, k:k + 5]
        want += shifted * w[k][None, None, :]
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_sequence_layers_in_program(rng):
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[6, 4], append_batch_size=True)
        length = fluid.layers.data("len", shape=[], dtype="int64")
        pooled = fluid.layers.sequence_pool(x, "average", length=length)
        out = fluid.layers.fc(pooled, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(3, 6, 4).astype("float32")
    ls = np.array([6, 2, 4], "int64")
    got, = exe.run(main, feed={"x": xs, "len": ls}, fetch_list=[out])
    assert got.shape == (3, 2) and np.isfinite(got).all()
