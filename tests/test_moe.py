"""Expert-parallel Switch-MoE tests on the virtual mesh: sharded execution
matches the unsharded dense computation of the same routing; gradients flow;
capacity drops overflow tokens."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel import make_switch_ffn, switch_moe


def _mesh(n, axis="expert"):
    return Mesh(np.array(jax.devices()[:n]), (axis,))


def _dense_reference(x, gate_w, params, fn, capacity):
    """Independent GShard-style one-hot dispatch (the round-2 formulation) —
    same routing semantics as the sort-based production path."""
    b, t, d = x.shape
    flat = x.reshape(-1, d)
    gate_logits = flat @ gate_w
    e = gate_logits.shape[-1]
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0
    pos_in_expert = jnp.sum(pos * onehot, axis=1)
    keep = pos_in_expert < capacity
    gate = jnp.sum(probs * onehot, axis=1) * keep
    slot = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), capacity,
                          dtype=jnp.float32)
    dispatch = onehot[:, :, None] * slot[:, None, :] * keep[:, None, None]
    combine = dispatch * gate[:, None, None]
    aux = e * jnp.sum(jnp.mean(onehot, axis=0) * jnp.mean(probs, axis=0))
    buf = jnp.einsum("nd,nec->ecd", flat.astype(jnp.float32), dispatch)
    out = jax.vmap(fn)(params, buf.astype(x.dtype))
    y = jnp.einsum("ecd,nec->nd", out.astype(jnp.float32), combine)
    return y.reshape(b, t, d).astype(x.dtype), aux


def test_switch_moe_matches_dense(rng):
    e, d, dff, b, t = 4, 8, 16, 2, 12
    mesh = _mesh(4)
    init, fn = make_switch_ffn(d, dff)
    params = init(jax.random.PRNGKey(0), e)
    gate_w = jnp.asarray(rng.randn(d, e).astype("float32") * 0.5)
    x = jnp.asarray(rng.randn(b, t, d).astype("float32"))
    cap = max(1, int(1.25 * b * t / e))
    y, aux = jax.jit(lambda xx: switch_moe(xx, gate_w, params, fn, mesh))(x)
    y_ref, aux_ref = _dense_reference(x, gate_w, params, fn, cap)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


def test_switch_moe_grads_and_sharded_params(rng):
    e, d, dff, b, t = 4, 8, 16, 2, 8
    mesh = _mesh(4)
    init, fn = make_switch_ffn(d, dff)
    params = init(jax.random.PRNGKey(1), e)
    sh = NamedSharding(mesh, P("expert"))
    params = jax.tree.map(lambda p: jax.device_put(p, sh), params)
    gate_w = jnp.asarray(rng.randn(d, e).astype("float32") * 0.5)
    x = jnp.asarray(rng.randn(b, t, d).astype("float32"))

    def loss(p, gw):
        y, aux = switch_moe(x, gw, p, fn, mesh)
        return jnp.mean(y ** 2) + 0.01 * aux

    g_p, g_gw = jax.jit(jax.grad(loss, argnums=(0, 1)))(params, gate_w)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g_p))
    assert np.isfinite(np.asarray(g_gw)).all()
    # router must receive gradient through the combine weights
    assert float(jnp.abs(g_gw).sum()) > 0


def test_switch_moe_capacity_drops(rng):
    """With capacity 1 and all tokens preferring one expert, overflow tokens
    output zeros (Switch drop semantics)."""
    e, d, dff, b, t = 2, 4, 8, 1, 6
    mesh = _mesh(2)
    init, fn = make_switch_ffn(d, dff)
    params = init(jax.random.PRNGKey(2), e)
    # gate forces expert 0 for every token
    gate_w = jnp.zeros((d, e)).at[:, 0].set(10.0)
    x = jnp.asarray(np.ones((b, t, d), "float32"))
    y, _ = jax.jit(lambda xx: switch_moe(xx, gate_w, params, fn, mesh,
                                         capacity_factor=1.0 / e * 1.0))(x)
    # capacity = int(1/e * n / e)... compute real: capacity_factor*n/e
    # here: (0.5 * 6 / 2)=1 → only 1 token served, rest dropped to zeros
    nonzero_rows = int((np.abs(np.asarray(y).reshape(t, d)).sum(-1) > 1e-6).sum())
    assert nonzero_rows == 1, nonzero_rows


def test_switch_moe_composes_with_data_axis(rng):
    """The docstring's dp×ep claim: batch sharded over 'data', experts over
    'expert', on a 2-axis mesh — same numbers as the single-axis run."""
    from jax.sharding import NamedSharding

    e, d, dff, b, t = 4, 8, 16, 4, 8
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh2 = Mesh(devs, ("data", "expert"))
    init, fn = make_switch_ffn(d, dff)
    params = init(jax.random.PRNGKey(0), e)
    gate_w = jnp.asarray(rng.randn(d, e).astype("float32") * 0.5)
    x = jnp.asarray(rng.randn(b, t, d).astype("float32"))

    # single-device reference (no sharding at all)
    mesh1 = Mesh(np.array(jax.devices()[:1]), ("expert",))
    y_ref, aux_ref = jax.jit(lambda xx: switch_moe(xx, gate_w, params, fn,
                                                   mesh1))(x)

    ps = jax.tree.map(
        lambda p: jax.device_put(p, NamedSharding(mesh2, P("expert"))), params)
    xs = jax.device_put(x, NamedSharding(mesh2, P("data")))
    y, aux = jax.jit(lambda xx: switch_moe(xx, gate_w, ps, fn, mesh2))(xs)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)
