"""Tiny-config convergence/run smokes for the five workload families
(SURVEY.md §4 tier 3 — book tests / parallel-executor model tests)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.models import deepfm as deepfm_mod
from paddle_tpu.models import mnist as mnist_mod
from paddle_tpu.models import resnet as resnet_mod
from paddle_tpu.models import transformer as tfm_mod


def test_lenet_mnist_runs_and_learns(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[1, 28, 28])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        logits, loss, acc = mnist_mod.lenet5(img, label, class_num=4)
        fluid.optimizer.Adam(2e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    # separable synthetic images: class k has bright quadrant k
    n = 128
    ys = rng.randint(0, 4, n)
    xs = rng.randn(n, 1, 28, 28).astype("float32") * 0.1
    for i, y in enumerate(ys):
        r, c = divmod(int(y), 2)
        xs[i, 0, r * 14 : r * 14 + 14, c * 14 : c * 14 + 14] += 1.0
    losses = []
    for _ in range(6):
        for i in range(0, n, 32):
            l, = exe.run(main, feed={"img": xs[i:i+32], "label": ys[i:i+32].reshape(-1, 1).astype("int64")},
                         fetch_list=[loss])
            losses.append(float(l))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_resnet_cifar_tiny_step(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 16, 16])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        logits, loss, acc = resnet_mod.resnet_cifar10(img, label, depth=18, class_num=10)
        fluid.optimizer.Momentum(0.01, 0.9).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(8, 3, 16, 16).astype("float32")
    ys = rng.randint(0, 10, (8, 1)).astype("int64")
    l1, = exe.run(main, feed={"img": xs, "label": ys}, fetch_list=[loss])
    l2, = exe.run(main, feed={"img": xs, "label": ys}, fetch_list=[loss])
    assert np.isfinite(l1).all() and np.isfinite(l2).all()
    # BN running stats must have moved off their init values
    mean0 = fluid.global_scope().as_numpy(
        [n for n in fluid.global_scope().local_var_names() if n.endswith(".mean_0")][0]
    )
    assert np.abs(mean0).sum() > 0


def test_transformer_tiny_learns(rng):
    main, startup = fluid.Program(), fluid.Program()
    B, S, V = 8, 16, 32
    with fluid.program_guard(main, startup):
        src = fluid.layers.data("src", shape=[S], dtype="int64", append_batch_size=True)
        trg = fluid.layers.data("trg", shape=[S], dtype="int64", append_batch_size=True)
        lbl = fluid.layers.data("lbl", shape=[S, 1], dtype="int64")
        smask = fluid.layers.data("smask", shape=[S], dtype="float32")
        tmask = fluid.layers.data("tmask", shape=[S], dtype="float32")
        logits, loss = tfm_mod.transformer(
            src, trg, lbl, smask, tmask, src_vocab_size=V, trg_vocab_size=V,
            max_length=S, n_layer=2, n_head=2, d_model=32, d_inner=64,
            dropout_rate=0.0, label_smooth_eps=0.0)
        fluid.optimizer.Adam(1e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    # copy task: target = source shifted
    src_np = rng.randint(2, V, (B, S)).astype("int64")
    feed = {
        "src": src_np,
        "trg": np.concatenate([np.ones((B, 1), "int64"), src_np[:, :-1]], axis=1),
        "lbl": src_np.reshape(B, S, 1),
        "smask": np.ones((B, S), "float32"),
        "tmask": np.ones((B, S), "float32"),
    }
    losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0]) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_bert_tiny_pretrain_step(rng):
    main, startup = fluid.Program(), fluid.Program()
    B, S, V = 4, 16, 64
    n_mask = 3
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[S], dtype="int64")
        pos = fluid.layers.data("pos", shape=[S], dtype="int64")
        sent = fluid.layers.data("sent", shape=[S], dtype="int64")
        mask = fluid.layers.data("mask", shape=[S], dtype="float32")
        mpos = fluid.layers.data("mpos", shape=[n_mask], dtype="int64")
        mlbl = fluid.layers.data("mlbl", shape=[1], dtype="int64")
        nsp = fluid.layers.data("nsp", shape=[1], dtype="int64")
        total, mlm_loss, nsp_loss = tfm_mod.bert_pretrain(
            ids, pos, sent, mask, mpos, mlbl, nsp, vocab_size=V,
            max_position=S, n_layer=2, n_head=2, d_model=32, d_inner=64,
            dropout_rate=0.0)
        fluid.optimizer.Adam(1e-3).minimize(total)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {
        "ids": rng.randint(0, V, (B, S)).astype("int64"),
        "pos": np.tile(np.arange(S), (B, 1)).astype("int64"),
        "sent": np.zeros((B, S), "int64"),
        "mask": np.ones((B, S), "float32"),
        "mpos": (np.arange(B)[:, None] * S + np.arange(n_mask)).astype("int64"),
        "mlbl": rng.randint(0, V, (B * n_mask, 1)).astype("int64"),
        "nsp": rng.randint(0, 2, (B, 1)).astype("int64"),
    }
    t1 = float(exe.run(main, feed=feed, fetch_list=[total])[0])
    t2 = float(exe.run(main, feed=feed, fetch_list=[total])[0])
    assert np.isfinite([t1, t2]).all()
    assert t2 < t1


def test_deepfm_learns_and_auc_moves(rng):
    main, startup = fluid.Program(), fluid.Program()
    F, DIM = 8, 100
    with fluid.program_guard(main, startup):
        sp = fluid.layers.data("sp", shape=[F], dtype="int64")
        dn = fluid.layers.data("dn", shape=[4], dtype="float32")
        lbl = fluid.layers.data("lbl", shape=[1], dtype="int64")
        predict, loss, auc_var = deepfm_mod.deepfm(
            sp, dn, lbl, sparse_feature_dim=DIM, embedding_size=4,
            num_fields=F, layer_sizes=(16, 16))
        fluid.optimizer.Adam(5e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    n = 256
    ids = rng.randint(0, DIM, (n, F)).astype("int64")
    dense = rng.randn(n, 4).astype("float32")
    # label determined by first sparse field parity (embedding-learnable)
    y = (ids[:, 0] % 2).astype("int64").reshape(-1, 1)
    losses, aucs = [], []
    for _ in range(8):
        for i in range(0, n, 64):
            l, a = exe.run(main, feed={"sp": ids[i:i+64], "dn": dense[i:i+64],
                                       "lbl": y[i:i+64]}, fetch_list=[loss, auc_var])
            losses.append(float(l)); aucs.append(float(a))
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    assert aucs[-1] > 0.55


def test_vgg16_tiny_step(rng):
    """VGG-16 config (reference benchmark/fluid/models/vgg.py) runs a train
    step on a tiny input and the loss is finite and decreases."""
    from paddle_tpu.models.vgg import vgg16

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 32, 32])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        loss, _ = vgg16(img, label, class_num=10)
        fluid.optimizer.Adam(1e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"img": rng.randn(4, 3, 32, 32).astype("float32"),
            "label": rng.randint(0, 10, (4, 1)).astype("int64")}
    l0 = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
    for _ in range(4):
        l1 = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
    assert np.isfinite(l1) and l1 < l0, (l0, l1)


def test_stacked_lstm_sentiment_learns(rng):
    """stacked_dynamic_lstm config (reference benchmark model): learns a
    token-presence sentiment rule."""
    from paddle_tpu.models.stacked_lstm import stacked_lstm_net

    vocab, t = 200, 12
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        words = fluid.layers.data("words", shape=[t], dtype="int64")
        length = fluid.layers.data("length", shape=[], dtype="int64")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        loss, acc = stacked_lstm_net(words, length, label, dict_dim=vocab,
                                     emb_dim=32, hid_dim=32, stacked_num=2)
        fluid.optimizer.Adam(5e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    n = 64
    ys = rng.randint(0, 2, n)
    ws = rng.randint(5, vocab, (n, t)).astype("int64")
    ws[ys == 1, 0] = 3  # sentiment marker token
    lens = rng.randint(6, t + 1, n).astype("int64")
    feed = {"words": ws, "length": lens, "label": ys.reshape(-1, 1).astype("int64")}
    losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
              for _ in range(12)]
    assert losses[-1] < losses[0] * 0.7, losses


def test_se_resnext_tiny_step(rng):
    """SE-ResNeXt config (reference benchmark/fluid/models/se_resnext.py)
    runs a train step on tiny shapes with finite decreasing loss."""
    from paddle_tpu.models.se_resnext import se_resnext

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 32, 32])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        loss, _ = se_resnext(img, label, class_num=10, layers_cfg=(1, 1),
                             cardinality=8, base_filters=(32, 64))
        fluid.optimizer.Adam(1e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"img": rng.randn(4, 3, 32, 32).astype("float32"),
            "label": rng.randint(0, 10, (4, 1)).astype("int64")}
    l0 = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
    for _ in range(4):
        l1 = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
    assert np.isfinite(l1) and l1 < l0, (l0, l1)


def test_machine_translation_model_module(rng):
    """The zoo's named seq_to_seq_net config trains to decreasing loss."""
    from paddle_tpu.models.machine_translation import seq_to_seq_net

    B, TS, TT, V = 6, 8, 7, 40
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 2
    with fluid.program_guard(main, startup):
        src = fluid.layers.data("src", shape=[TS], dtype="int64")
        src_len = fluid.layers.data("src_len", shape=[], dtype="int64")
        trg = fluid.layers.data("trg", shape=[TT], dtype="int64")
        trg_len = fluid.layers.data("trg_len", shape=[], dtype="int64")
        labels = fluid.layers.data("labels", shape=[TT, 1], dtype="int64")
        loss, _ = seq_to_seq_net(src, src_len, trg, trg_len, labels, V,
                                 embedding_dim=12, encoder_size=12,
                                 decoder_size=12)
        fluid.optimizer.Adam(5e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"src": rng.randint(0, V, (B, TS)).astype("int64"),
            "src_len": rng.randint(3, TS + 1, (B,)).astype("int64"),
            "trg": rng.randint(0, V, (B, TT)).astype("int64"),
            "trg_len": rng.randint(2, TT + 1, (B,)).astype("int64")}
    feed["labels"] = np.roll(feed["trg"], -1, axis=1)[..., None].astype("int64")
    losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
              for _ in range(30)]
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_word2vec_ngram_learns(rng):
    """Book test tail (ref tests/book/test_word2vec.py): the 4-gram LM fits
    a deterministic next-word rule."""
    from paddle_tpu.models import word2vec as w2v

    V = 30
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        words = [fluid.layers.data(n, shape=[1], dtype="int64")
                 for n in ("firstw", "secondw", "thirdw", "forthw", "nextw")]
        avg_cost, predict = w2v.word2vec_ngram(*words, dict_size=V,
                                               embed_size=16, hidden_size=64)
        fluid.optimizer.Adam(5e-3).minimize(avg_cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    n = 256
    ctx = rng.randint(0, V, (n, 4)).astype("int64")
    nxt = ((ctx[:, 0] + ctx[:, 1]) % V).reshape(-1, 1).astype("int64")
    losses = []
    for _ in range(30):
        for i in range(0, n, 64):
            feed = {nm: ctx[i:i+64, j:j+1] for j, nm in
                    enumerate(("firstw", "secondw", "thirdw", "forthw"))}
            feed["nextw"] = nxt[i:i+64]
            l, = exe.run(main, feed=feed, fetch_list=[avg_cost])
            losses.append(float(l))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_label_semantic_roles_crf_train_and_decode(rng):
    """Book test tail (ref tests/book/test_label_semantic_roles.py):
    db_lstm + linear_chain_crf trains, then crf_decoding infers with the
    same 'crfw' transitions."""
    from paddle_tpu.models import semantic_roles as srl

    B, T, L = 4, 6, 5
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        names = ("word", "predicate", "ctx_n2", "ctx_n1", "ctx_0",
                 "ctx_p1", "ctx_p2", "mark")
        feats = [fluid.layers.data(n, shape=[T], dtype="int64") for n in names]
        target = fluid.layers.data("target", shape=[T], dtype="int64")
        length = fluid.layers.data("length", shape=[], dtype="int64")
        feature_out = srl.db_lstm(*feats, length=length, word_dict_len=20,
                                  pred_dict_len=8, label_dict_len=L,
                                  word_dim=8, hidden_dim=8, depth=2)
        avg_cost = srl.srl_train_net(feature_out, target, length=length)
        decode = srl.srl_decode(feature_out, length=length)
        fluid.optimizer.SGD(0.05).minimize(avg_cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    # tags follow the word id (learnable mapping)
    words = rng.randint(0, 20, (B, T)).astype("int64")
    feed = {n: words if n == "word" else
            rng.randint(0, 8 if n == "predicate" else 2 if n == "mark" else 20,
                        (B, T)).astype("int64")
            for n in names}
    feed["target"] = (words % L).astype("int64")
    feed["length"] = np.full((B,), T, "int64")
    losses = []
    for _ in range(15):
        l, = exe.run(main, feed=feed, fetch_list=[avg_cost])
        losses.append(float(l))
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    path, = exe.run(main, feed=feed, fetch_list=[decode])
    assert path.shape == (B, T)
    assert path.min() >= 0 and path.max() < L


def test_recommender_system_learns(rng):
    """Book test tail (ref tests/book/test_recommender_system.py): two-tower
    cosine model regresses synthetic ratings."""
    from paddle_tpu.models import recommender as rec

    B = 64
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        uid = fluid.layers.data("user_id", shape=[1], dtype="int64")
        gender = fluid.layers.data("gender_id", shape=[1], dtype="int64")
        age = fluid.layers.data("age_id", shape=[1], dtype="int64")
        job = fluid.layers.data("job_id", shape=[1], dtype="int64")
        mov = fluid.layers.data("movie_id", shape=[1], dtype="int64")
        cat = fluid.layers.data("category_id", shape=[3], dtype="int64")
        title = fluid.layers.data("movie_title", shape=[4], dtype="int64")
        rating = fluid.layers.data("score", shape=[1], dtype="float32")
        usr = rec.usr_combined_features(uid, gender, age, job, usr_dict_size=20)
        movf = rec.mov_combined_features(mov, cat, title, mov_dict_size=30,
                                         title_dict_size=50)
        scale_infer, avg_cost = rec.inference_program(usr, movf, rating)
        fluid.optimizer.Adam(2e-3).minimize(avg_cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    n = 256
    data = {
        "user_id": rng.randint(0, 20, (n, 1)).astype("int64"),
        "gender_id": rng.randint(0, 2, (n, 1)).astype("int64"),
        "age_id": rng.randint(0, 7, (n, 1)).astype("int64"),
        "job_id": rng.randint(0, 21, (n, 1)).astype("int64"),
        "movie_id": rng.randint(0, 30, (n, 1)).astype("int64"),
        "category_id": rng.randint(0, 18, (n, 3)).astype("int64"),
        "movie_title": rng.randint(0, 50, (n, 4)).astype("int64"),
    }
    # rating depends on user/movie id parity — learnable structure
    score = (3.0 + ((data["user_id"] + data["movie_id"]) % 2) * 1.5)
    data["score"] = score.astype("float32")
    losses = []
    for _ in range(20):
        for i in range(0, n, B):
            feed = {k: v[i:i+B] for k, v in data.items()}
            l, = exe.run(main, feed=feed, fetch_list=[avg_cost])
            losses.append(float(l))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_bert_named_configs_and_tiny_convergence(rng):
    """models/bert.py named configs: bert_base builds the canonical graph
    (param shapes checked, no execution); bert_tiny pretrain CONVERGES."""
    from paddle_tpu.models import bert as bert_mod

    # graph-construction check for the named BERT-base config
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[16], dtype="int64")
        pos = fluid.layers.data("pos", shape=[16], dtype="int64")
        sent = fluid.layers.data("sent", shape=[16], dtype="int64")
        mask = fluid.layers.data("mask", shape=[16], dtype="float32")
        seq, pooled = bert_mod.bert_base(ids, pos, sent, mask, max_position=16)
        assert seq.shape[-1] == 768 and pooled.shape[-1] == 768
        we = main.global_block.var("word_embedding")
        assert tuple(we.shape) == (30522, 768)
        n_attn = sum(1 for op in main.global_block.ops
                     if op.type == "scaled_dot_product_attention")
        assert n_attn == 12

    # tiny pretrain convergence
    B, S, V, n_mask = 4, 16, 64, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[S], dtype="int64")
        pos = fluid.layers.data("pos", shape=[S], dtype="int64")
        sent = fluid.layers.data("sent", shape=[S], dtype="int64")
        mask = fluid.layers.data("mask", shape=[S], dtype="float32")
        mpos = fluid.layers.data("mpos", shape=[n_mask], dtype="int64")
        mlbl = fluid.layers.data("mlbl", shape=[1], dtype="int64")
        nsp = fluid.layers.data("nsp", shape=[1], dtype="int64")
        total, mlm_loss, nsp_loss = bert_mod.bert_pretrain(
            ids, pos, sent, mask, mpos, mlbl, nsp,
            **dict(bert_mod.BERT_TINY_CONFIG, max_position=S, dropout_rate=0.0))
        fluid.optimizer.Adam(2e-3).minimize(total)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {
        "ids": rng.randint(0, V, (B, S)).astype("int64"),
        "pos": np.tile(np.arange(S), (B, 1)).astype("int64"),
        "sent": np.zeros((B, S), "int64"),
        "mask": np.ones((B, S), "float32"),
        "mpos": (np.arange(B)[:, None] * S + np.arange(n_mask)).astype("int64"),
        "mlbl": rng.randint(0, V, (B * n_mask, 1)).astype("int64"),
        "nsp": rng.randint(0, 2, (B, 1)).astype("int64"),
    }
    losses = [float(exe.run(main, feed=feed, fetch_list=[total])[0])
              for _ in range(25)]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_resnet_nhwc_matches_nchw(rng):
    """data_format='NHWC' (the TPU-native channels-last layout) must be
    numerically identical to NCHW through training steps."""
    def run(fmt):
        with fluid.unique_name.guard():
            with fluid.scope_guard(fluid.Scope()):
                main, startup = fluid.Program(), fluid.Program()
                main.random_seed = startup.random_seed = 7
                with fluid.program_guard(main, startup):
                    img = fluid.layers.data("img", shape=[3, 16, 16])
                    label = fluid.layers.data("label", shape=[1], dtype="int64")
                    logits, loss, acc = resnet_mod.resnet(
                        img, label, depth=18, class_num=10, data_format=fmt)
                    fluid.optimizer.Momentum(0.1, 0.9).minimize(loss)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                r = np.random.RandomState(0)
                feed = {"img": r.randn(4, 3, 16, 16).astype("float32"),
                        "label": r.randint(0, 10, (4, 1)).astype("int64")}
                return [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
                        for _ in range(3)]

    # layout changes fp32 reduction order; drift compounds over train steps
    np.testing.assert_allclose(run("NCHW"), run("NHWC"), rtol=5e-3, atol=1e-3)


def test_causal_lm_shapes_and_train_step(rng):
    """causal_lm: logits shape, loss finite, and one train step runs."""
    b, s, v = 2, 16, 64
    with fluid.unique_name.guard(), fluid.scope_guard(fluid.Scope()):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data("ids", shape=[s], dtype="int64")
            lbl = fluid.layers.data("lbl", shape=[s, 1], dtype="int64")
            logits, loss = tfm_mod.causal_lm(ids, lbl, vocab_size=v, max_length=s,
                                         n_layer=2, n_head=2, d_model=32,
                                         d_inner=64)
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        assert tuple(logits.shape) == (-1, s, v)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {"ids": rng.randint(0, v, (b, s)).astype("int64"),
                "lbl": rng.randint(0, v, (b, s, 1)).astype("int64")}
        l0, = exe.run(main, feed=feed, fetch_list=[loss])
        for _ in range(12):
            l1, = exe.run(main, feed=feed, fetch_list=[loss])
        assert np.isfinite(l0).all() and np.isfinite(l1).all()
        assert float(l1) < float(l0), "causal_lm loss did not decrease"
