"""Speculative decoding tests (ISSUE 17 tentpole coverage): the n-gram
drafter's lookup rules, the speculation-knob grammar, the accept/reject
residual-sampling identity, verify-window attention vs plain decode, and
the engine-level invariants — greedy AND sampled speculative streams are
bit-identical to plain decode, and rejected draft tokens never leak pages
across any retirement path (finished, timeout, decode-failure, drain)."""

import numpy as np
import pytest

from paddle_tpu import serving
from paddle_tpu.models import decoder_lm
from paddle_tpu.ops import attention_ops
from paddle_tpu.serving.speculative import (SPEC_K_CAP, NGramDrafter,
                                            make_drafter, parse_speculation,
                                            residual_sample)

_MODEL = None


def get_model():
    """One tiny decoder shared across tests (init cost, not compile cost —
    each engine still AOT-compiles its own step functions)."""
    global _MODEL
    if _MODEL is None:
        cfg = decoder_lm.DecoderConfig(vocab_size=64, n_layer=2, d_model=32,
                                       n_head=2, max_seq=64)
        _MODEL = decoder_lm.DecoderLM(cfg, seed=0)
    return _MODEL


def small_config(**kw):
    kw.setdefault("slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_seq", 64)
    kw.setdefault("prompt_buckets", (16,))
    return serving.ServingConfig(**kw)


def rep_prompts(rng, n=4, motif=3, reps=4, vocab=64):
    """Repetitive prompts (a short motif repeated) — the n-gram drafter's
    best case, so acceptance-dependent assertions aren't vacuous."""
    return [list(rng.randint(0, vocab, motif)) * reps for _ in range(n)]


def assert_balanced(eng, label):
    assert eng.pool.num_used == 0, "%s leaked pages" % label
    assert eng.page_accounting_ok(), label


def spec_counters():
    from paddle_tpu.monitor import metrics as mx
    snap = mx.snapshot()
    return {n: float(snap.get(n, {}).get("value", 0.0))
            for n in ("serving/spec_proposed_tokens",
                      "serving/spec_accepted_tokens",
                      "serving/spec_rejected_tokens",
                      "serving/decode_dispatches")}


# -- n-gram drafter -----------------------------------------------------------

class TestNGramDrafter:
    def test_trailing_ngram_continuation(self):
        d = NGramDrafter(max_n=3, min_n=1)
        # suffix [1, 2] previously occurred at the start; propose what
        # followed it there
        assert d.propose([1, 2, 3, 4, 1, 2], 3) == [3, 4, 1]

    def test_longest_ngram_wins(self):
        d = NGramDrafter(max_n=3, min_n=1)
        # the trailing TRIgram [1, 2, 3] matches at index 1 — its
        # continuation (9) wins over any shorter-suffix match elsewhere
        h = [5, 1, 2, 3, 9, 7, 1, 2, 3]
        assert d.propose(h, 2)[:1] == [9]

    def test_rightmost_prior_occurrence_wins(self):
        d = NGramDrafter(max_n=2, min_n=2)
        # suffix [1, 2] occurs at 0 (-> 7) and at 3 (-> 8): most recent wins
        assert d.propose([1, 2, 7, 1, 2, 8, 1, 2], 1) == [8]

    def test_no_match_and_degenerate_inputs_are_empty(self):
        d = NGramDrafter()
        assert d.propose([1, 2, 3, 4, 5, 6], 4) == []   # no repeated n-gram
        assert d.propose([1, 2, 3, 4], 0) == []         # k == 0
        assert d.propose([7], 4) == []                  # history too short
        assert d.propose([], 4) == []

    def test_draft_capped_at_k(self):
        d = NGramDrafter(max_n=2, min_n=1)
        h = [1, 2, 3, 4, 5, 6, 1, 2]
        assert d.propose(h, 2) == [3, 4]
        assert len(d.propose(h, 8)) <= 8

    def test_factory_and_validation(self):
        assert make_drafter("ngram").kind == "ngram"
        with pytest.raises(ValueError):
            make_drafter("oracle")
        with pytest.raises(ValueError):
            NGramDrafter(max_n=1, min_n=2)


# -- knob grammar -------------------------------------------------------------

def test_parse_speculation_grammar():
    assert parse_speculation(None) is None
    for off in ("", "0", "off", "none", "false", "no", 0):
        assert parse_speculation(off) == 0
    assert parse_speculation("auto") == "auto"
    assert parse_speculation("AUTO") == "auto"
    assert parse_speculation(3) == 3
    assert parse_speculation("5") == 5
    assert parse_speculation(64) == SPEC_K_CAP
    with pytest.raises(ValueError):
        parse_speculation(-1)
    with pytest.raises(ValueError):
        parse_speculation("-2")


# -- residual sampling --------------------------------------------------------

def test_residual_sample_marginal_is_exactly_target(rng):
    """The Leviathan guarantee: draft from q, accept with min(1, p/q),
    resample the residual on reject — the emitted marginal is p."""
    v, n = 8, 30000
    p = rng.dirichlet(np.ones(v))
    q = rng.dirichlet(np.ones(v))
    drafts = rng.choice(v, size=n, p=q)
    u1, u2 = rng.rand(n), rng.rand(n)
    toks = np.zeros(n, np.int64)
    acc = np.zeros(n, bool)
    for i in range(n):
        toks[i], acc[i] = residual_sample(p, q, drafts[i], u1[i], u2[i])
    assert acc.any() and (~acc).any(), "need both branches exercised"
    # acceptance rate is sum_t min(p_t, q_t)
    assert abs(acc.mean() - np.minimum(p, q).sum()) < 0.02
    hist = np.bincount(toks, minlength=v) / n
    assert np.max(np.abs(hist - p)) < 0.02

def test_residual_sample_edge_cases():
    p = np.array([0.5, 0.5, 0.0, 0.0])
    q = np.array([0.0, 0.0, 0.5, 0.5])
    # draft has q-mass zero -> must reject into the residual (= p here)
    tok, acc = residual_sample(p, q, 0, 0.0, 0.6)
    assert not acc and tok == 1
    # q == p: acceptance is certain for any u_accept < 1
    tok, acc = residual_sample(p, p, 1, 0.999, 0.0)
    assert acc and tok == 1


# -- verify-window attention --------------------------------------------------

def test_verify_attention_w1_matches_decode_attention(rng):
    b, l, h, d = 3, 12, 2, 8
    q = rng.randn(b, 1, h, d).astype(np.float32)
    ck = rng.randn(b, l, h, d).astype(np.float32)
    cv = rng.randn(b, l, h, d).astype(np.float32)
    ctx_len = np.array([4, 12, 7], np.int32)
    got = np.asarray(attention_ops.verify_attention(q, ck, cv, ctx_len,
                                                    sm_scale=0.5))
    want = np.asarray(attention_ops.decode_attention(q[:, 0], ck, cv, ctx_len,
                                                     sm_scale=0.5))
    # same masking, same softmax, same neg_inf constant; XLA batches the
    # window einsum differently, so equality is numerical, not bitwise —
    # TOKEN bit-parity is the engine-level tests' job
    np.testing.assert_allclose(got[:, 0], want, rtol=1e-6, atol=1e-6)


def test_verify_attention_rows_are_causally_ragged(rng):
    """Window row j attends to exactly ctx_len + j positions — i.e. each
    row reproduces a plain decode step at its own logical position."""
    b, l, h, d, w = 2, 16, 2, 8, 3
    q = rng.randn(b, w, h, d).astype(np.float32)
    ck = rng.randn(b, l, h, d).astype(np.float32)
    cv = rng.randn(b, l, h, d).astype(np.float32)
    ctx_len = np.array([5, 9], np.int32)
    got = np.asarray(attention_ops.verify_attention(q, ck, cv, ctx_len))
    for j in range(w):
        row = np.asarray(attention_ops.decode_attention(
            q[:, j], ck, cv, ctx_len + j))
        np.testing.assert_allclose(got[:, j], row, rtol=1e-6, atol=1e-6)


# -- engine: bit parity -------------------------------------------------------

def _drive(stream, spec, cfg_kw=None, **submit_kw):
    eng = serving.ServingEngine(get_model(), small_config(**(cfg_kw or {})))
    reqs = [eng.submit(p, m, speculation=spec, **submit_kw)
            for p, m in stream]
    eng.run()
    assert_balanced(eng, "spec=%r" % (spec,))
    toks = [list(r.tokens_out) for r in reqs]
    states = [r.state for r in reqs]
    eng.close()
    assert all(s == "finished" for s in states), states
    return toks


def test_greedy_speculative_bit_parity(rng):
    stream = [(p, 12) for p in rep_prompts(rng, n=5)]
    c0 = spec_counters()
    spec = _drive(stream, 4)
    c1 = spec_counters()
    plain = _drive(stream, 0)
    assert spec == plain, "speculative greedy stream diverged from decode"
    accepted = c1["serving/spec_accepted_tokens"] - \
        c0["serving/spec_accepted_tokens"]
    proposed = c1["serving/spec_proposed_tokens"] - \
        c0["serving/spec_proposed_tokens"]
    assert accepted > 0, "repetitive stream accepted nothing — vacuous parity"
    assert proposed >= accepted


def test_speculation_saves_dispatches_on_repetitive_stream(rng):
    stream = [(p, 14) for p in rep_prompts(rng, n=4)]
    c0 = spec_counters()
    _drive(stream, 4)
    c1 = spec_counters()
    _drive(stream, 0)
    c2 = spec_counters()
    d_spec = c1["serving/decode_dispatches"] - c0["serving/decode_dispatches"]
    d_plain = c2["serving/decode_dispatches"] - c1["serving/decode_dispatches"]
    assert d_spec < d_plain, \
        "the whole point: fewer dispatches for the same tokens (%d vs %d)" \
        % (d_spec, d_plain)


def test_sampled_speculative_bit_parity(rng):
    """The (seed, position)-keyed sampler makes even SAMPLED speculative
    decode bit-identical to plain decode — stronger than the distribution
    match the accept/reject math alone would promise."""
    stream = [(p, 10) for p in rep_prompts(rng, n=4)]
    for temp, top_k in ((0.8, 0), (1.2, 5)):
        spec = _drive(stream, 4, temperature=temp, top_k=top_k, seed=17)
        plain = _drive(stream, 0, temperature=temp, top_k=top_k, seed=17)
        assert spec == plain, "sampled divergence at T=%s top_k=%d" \
            % (temp, top_k)


def test_sampled_speculative_histogram_matches_plain(rng):
    """Belt and braces on top of bit-parity: the emitted token histogram
    over many sampled requests is identical between the two paths."""
    stream = [(p, 8) for p in rep_prompts(rng, n=6)]
    spec = _drive(stream, 3, temperature=1.0, top_k=0, seed=5)
    plain = _drive(stream, 0, temperature=1.0, top_k=0, seed=5)
    h_spec = np.bincount(np.concatenate([np.asarray(t) for t in spec]),
                         minlength=64)
    h_plain = np.bincount(np.concatenate([np.asarray(t) for t in plain]),
                          minlength=64)
    assert np.array_equal(h_spec, h_plain)


def test_mixed_speculation_per_request(rng):
    """Speculating and non-speculating requests share ticks; each still
    emits its own plain-decode stream."""
    prompts = rep_prompts(rng, n=4)
    eng = serving.ServingEngine(get_model(), small_config())
    reqs = [eng.submit(p, 10, speculation=(4 if i % 2 == 0 else 0))
            for i, p in enumerate(prompts)]
    eng.run()
    assert_balanced(eng, "mixed")
    mixed = [list(r.tokens_out) for r in reqs]
    eng.close()
    assert mixed == _drive([(p, 10) for p in prompts], 0)


def test_greedy_parity_includes_captured_logits(rng):
    stream = [(p, 8) for p in rep_prompts(rng, n=3)]

    def capture(spec):
        eng = serving.ServingEngine(get_model(),
                                    small_config(collect_logits=True))
        reqs = [eng.submit(p, m, speculation=spec) for p, m in stream]
        eng.run()
        rows = [[np.asarray(x) for x in eng.captured_logits(r)]
                for r in reqs]
        toks = [list(r.tokens_out) for r in reqs]
        eng.close()
        return toks, rows

    t_spec, l_spec = capture(4)
    t_plain, l_plain = capture(0)
    assert t_spec == t_plain
    for rs, rp in zip(l_spec, l_plain):
        assert len(rs) == len(rp)
        for a, b in zip(rs, rp):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


# -- engine: page accounting across every retirement path ---------------------

def test_spec_page_accounting_every_retirement_path(rng):
    from paddle_tpu.reliability import FaultPlan, faults

    prompts = rep_prompts(rng, n=3)

    # 1. normal finish (covered again here so the four paths sit together)
    eng = serving.ServingEngine(get_model(), small_config())
    for p in prompts:
        eng.submit(p, 8, speculation=4)
    eng.run()
    assert_balanced(eng, "finished")
    eng.close()

    # 2. deadline timeout mid-speculation: rejected draft tokens must not
    # strand the pages the verify window touched
    eng_t = serving.ServingEngine(get_model(), small_config())
    r_dead = eng_t.submit(prompts[0], 32, deadline_s=0.0, speculation=4)
    r_live = eng_t.submit(prompts[1], 4, speculation=4)
    eng_t.run(max_steps=100)
    assert r_dead.state == "timeout"
    assert r_live.state == "finished"
    assert_balanced(eng_t, "timeout")
    eng_t.close()

    # 3. injected decode failure: the failed request's pages come back
    eng_f = serving.ServingEngine(get_model(),
                                  small_config(decode_retries=0))
    plan = FaultPlan([faults.FaultSpec("serving.decode", "fatal", at=1)])
    with plan:
        r_a = eng_f.submit(prompts[0], 6, speculation=4)
        r_b = eng_f.submit(prompts[1], 6, speculation=4)
        eng_f.run(max_steps=100)
    assert r_a.state == "failed" and not r_a.pages
    assert r_b.state in ("failed", "finished")
    assert_balanced(eng_f, "decode-failure")
    # engine survives for fresh speculative traffic
    r_after = eng_f.submit(prompts[2], 4, speculation=4)
    eng_f.run(max_steps=100)
    assert r_after.state == "finished"
    assert_balanced(eng_f, "post-failure")
    eng_f.close()

    # 4. drain with speculative requests still in flight
    eng_d = serving.ServingEngine(get_model(), small_config())
    for p in prompts:
        eng_d.submit(p, 30, speculation=4)
    eng_d.step()
    eng_d.drain(timeout_s=0.0)
    assert_balanced(eng_d, "drain")
    eng_d.close()


# -- engine: layout / kernel orthogonality ------------------------------------

def test_int8_kv_speculative_matches_int8_plain(monkeypatch, tmp_path, rng):
    """Speculation is orthogonal to KV quantization: int8+spec emits the
    int8 plain-decode stream (compare like with like — int8 vs fp drift
    is test_numerics' business, not ours)."""
    from paddle_tpu.monitor import numerics as num

    tbl = str(tmp_path / "calib.json")
    monkeypatch.setenv("PADDLE_TPU_NUMERICS_TABLE", tbl)
    mc = get_model().cfg
    num.record_kv_calibration(
        num.kv_fingerprint(mc.n_layer, mc.n_head, mc.d_head, mc.dtype),
        4.0, 4.0, path=tbl)
    stream = [(p, 8) for p in rep_prompts(rng, n=3)]

    def drive_int8(spec):
        eng = serving.ServingEngine(get_model(),
                                    small_config(kv_dtype="int8"))
        assert eng.cache_ops.layout == "paged-int8"
        reqs = [eng.submit(p, m, speculation=spec) for p, m in stream]
        eng.run()
        assert_balanced(eng, "int8 spec=%r" % (spec,))
        toks = [list(r.tokens_out) for r in reqs]
        eng.close()
        return toks

    assert drive_int8(4) == drive_int8(0)


def test_decode_verify_kernel_interpret_matches_gather(rng):
    """The fused verify dispatch rides the paged kernel via B*W pseudo-slot
    flattening; in interpret mode it must emit the gather path's stream."""
    from paddle_tpu.flags import set_flag

    stream = [(p, 10) for p in rep_prompts(rng, n=3)]

    def drive_flag(mode):
        set_flag("paged_attention_kernel", mode)
        try:
            return _drive(stream, 4)
        finally:
            set_flag("paged_attention_kernel", "auto")

    assert drive_flag("interpret") == drive_flag("off")


# -- engine: config + stats surface -------------------------------------------

def test_speculation_info_and_stats_surface(rng):
    eng = serving.ServingEngine(get_model(),
                                small_config(speculation=3))
    k, kind, src = eng.speculation_info()
    assert (k, kind, src) == (3, "ngram", "explicit")
    st = eng.stats()
    assert st["speculation"] == 3
    assert st["spec_drafter"] == "ngram"
    assert st["speculation_source"] == "explicit"
    eng.close()

    eng_off = serving.ServingEngine(get_model(), small_config())
    assert eng_off.speculation_info()[0] == 0
    eng_off.close()

    eng_auto = serving.ServingEngine(get_model(),
                                     small_config(speculation="auto"))
    k, kind, src = eng_auto.speculation_info()
    assert k >= 1 and kind == "ngram"
    assert src in ("tuned", "shipped", "default")
    eng_auto.close()


def test_bad_speculation_rejected_at_submit(rng):
    eng = serving.ServingEngine(get_model(), small_config())
    with pytest.raises(ValueError):
        eng.submit([1, 2, 3], 4, speculation=-2)
    with pytest.raises(ValueError):
        eng.submit([1, 2, 3], 4, speculation="fast")
    eng.run()
    assert_balanced(eng, "rejected submits")
    eng.close()
