"""Pallas row-wise sparse-update kernel parity (interpret mode on CPU).

The kernel (ops/pallas_kernels/sparse_adam.py) replaces the three XLA
scatter fusions of the SelectedRows Adam path (benchmarks/SPARSE_PROFILE.md
§1) with one batched row-DMA pass. Contract: bit-for-bit the same update
semantics as the scatter formulation — duplicate ids merged by
``core/sparse.merge_rows`` upstream, merge-padding ids (== V) dropped like
an OOB scatter, ``padding_idx`` rows carried through the normal lazy-Adam
moment decay.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.sparse import merge_rows
from paddle_tpu.flags import set_flag
from paddle_tpu.ops.pallas_kernels import sparse_adam_rows, sparse_sgd_rows


@pytest.fixture(autouse=True)
def _restore_flag():
    yield
    set_flag("sparse_update_kernel", "auto")


def _merged(rng, vocab, dim, n):
    ids = rng.randint(0, vocab, (n,)).astype(np.int32)
    ids[: n // 4] = ids[n // 4 : n // 2]  # duplicates exercise merge_rows
    rows = rng.randn(n, dim).astype(np.float32)
    return merge_rows(jnp.asarray(ids), jnp.asarray(rows), vocab)


def test_kernel_adam_matches_scatter(rng):
    vocab, dim = 500, 10
    uniq, merged = _merged(rng, vocab, dim, 64)
    p = jnp.asarray(rng.randn(vocab, dim).astype(np.float32))
    m = jnp.asarray(rng.randn(vocab, dim).astype(np.float32) * 0.1)
    v = jnp.asarray(np.abs(rng.randn(vocab, dim)).astype(np.float32) * 0.1)
    b1, b2, eps, lr_t = 0.9, 0.999, 1e-8, 0.01

    m_rows = b1 * m[uniq] + (1 - b1) * merged
    v_rows = b2 * v[uniq] + (1 - b2) * jnp.square(merged)
    ref_p = p.at[uniq].add(-(lr_t * m_rows / (jnp.sqrt(v_rows) + eps)))
    ref_m = m.at[uniq].add(m_rows - m[uniq])
    ref_v = v.at[uniq].add(v_rows - v[uniq])

    k_p, k_m, k_v = sparse_adam_rows(p, m, v, uniq, merged, lr_t,
                                     b1, b2, eps, interpret=True)
    np.testing.assert_allclose(ref_p, k_p, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(ref_m, k_m, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(ref_v, k_v, rtol=1e-6, atol=1e-6)
    # untouched rows must be bit-identical (aliased, never copied)
    touched = np.zeros(vocab, bool)
    touched[np.asarray(uniq)[np.asarray(uniq) < vocab]] = True
    np.testing.assert_array_equal(np.asarray(p)[~touched],
                                  np.asarray(k_p)[~touched])


def test_kernel_sgd_matches_scatter(rng):
    vocab, dim = 300, 7  # dim deliberately not lane-aligned
    uniq, merged = _merged(rng, vocab, dim, 40)
    p = jnp.asarray(rng.randn(vocab, dim).astype(np.float32))
    ref = p.at[uniq].add(-0.3 * merged)
    out = sparse_sgd_rows(p, uniq, merged, 0.3, interpret=True)
    np.testing.assert_allclose(ref, out, rtol=1e-6, atol=1e-6)


def test_kernel_drops_merge_padding(rng):
    """All-padding tail (few distinct ids in a big batch): rows past the
    distinct count carry id == V and must leave the table untouched."""
    vocab, dim = 100, 10
    ids = np.full((32,), 7, np.int32)  # ONE distinct id, 31 pad slots
    rows = rng.randn(32, dim).astype(np.float32)
    uniq, merged = merge_rows(jnp.asarray(ids), jnp.asarray(rows), vocab)
    p = jnp.asarray(rng.randn(vocab, dim).astype(np.float32))
    out = sparse_sgd_rows(p, uniq, merged, 1.0, interpret=True)
    expect = np.asarray(p).copy()
    expect[7] -= rows.sum(0)
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)


def _build(vocab, dim, optimizer, padding_idx=None):
    from paddle_tpu.core import unique_name

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with unique_name.guard(), fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[4], dtype="int64")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(ids, size=[vocab, dim], is_sparse=True,
                                     padding_idx=padding_idx)
        flat = fluid.layers.reshape(emb, [-1, 4 * dim])
        logits = fluid.layers.fc(flat, size=2)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        optimizer().minimize(loss)
    return main, startup, loss


@pytest.mark.parametrize("opt", ["adam", "sgd"])
def test_end_to_end_kernel_vs_scatter(rng, opt):
    """FLAGS_sparse_update_kernel=interpret drives the whole training step
    through the kernel; losses and every persistable (params + moments)
    must track the scatter path. Includes a padding_idx row in the batch
    (zero grad rows still get lazy moment decay — both paths agree)."""
    vocab, dim = 200, 10
    make = {
        "adam": lambda: fluid.optimizer.Adam(learning_rate=0.05),
        "sgd": lambda: fluid.optimizer.SGD(learning_rate=0.5),
    }[opt]
    ids_np = rng.randint(0, vocab, (24, 4)).astype("int64")
    ids_np[:6] = ids_np[6:12]   # duplicates
    ids_np[0, 0] = 3            # the padding_idx row
    feed = {"ids": ids_np, "label": (ids_np[:, :1] % 2).astype("int64")}
    results = {}
    for mode in ("off", "interpret"):
        set_flag("sparse_update_kernel", mode)
        main, startup, loss = _build(vocab, dim, make, padding_idx=3)
        scope = fluid.core.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
            losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
                      for _ in range(4)]
            params = {
                n: np.asarray(scope.find_var(n))
                for n in sorted(s.name for s in main.list_vars()
                                if s.persistable)
                if scope.find_var(n) is not None
                and "learning_rate" not in n
            }
        results[mode] = (losses, params)
    l_ref, p_ref = results["off"]
    l_k, p_k = results["interpret"]
    np.testing.assert_allclose(l_ref, l_k, rtol=1e-4)
    assert set(p_ref) == set(p_k)
    for n in p_ref:
        np.testing.assert_allclose(p_ref[n], p_k[n], rtol=1e-4, atol=1e-6,
                                   err_msg=n)


@pytest.mark.parametrize("mode", ["off", "interpret"])
def test_masked_negative_ids_never_touch_row0(rng, mode):
    """ids < 0 are the masked-feature convention (lookup output zeroed);
    the grad path maps them to the merge invalid index (== V) so the
    row-wise update DROPS them — row 0 must stay bit-identical, not decay
    its Adam moments every step."""
    set_flag("sparse_update_kernel", mode)
    vocab, dim = 50, 10
    main, startup, loss = _build(
        vocab, dim, lambda: fluid.optimizer.Adam(learning_rate=0.1))
    ids_np = rng.randint(1, vocab, (16, 4)).astype("int64")
    ids_np[:, 0] = -1  # a masked column every step
    feed = {"ids": ids_np, "label": (ids_np[:, 1:2] % 2).astype("int64")}
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        table0 = {n: np.asarray(scope.find_var(n))[0].copy()
                  for n in scope.vars
                  if getattr(scope.find_var(n), "shape", None) == (vocab, dim)}
        assert table0
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[loss])
        for n, before in table0.items():
            np.testing.assert_array_equal(
                before, np.asarray(scope.find_var(n))[0], err_msg=n)


def test_selftest_entry():
    """The CI smoke (`python -m paddle_tpu.ops.pallas_kernels.sparse_adam
    --selftest`, ROADMAP fast smokes) must stay green."""
    from paddle_tpu.ops.pallas_kernels import sparse_adam

    assert sparse_adam._selftest() == 0
