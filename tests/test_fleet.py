"""Fleet subsystem tests: prefix-cache hashing/LRU/poisoning, the frame
protocol (JSON + binary page-payload frames), router exactly-once
accounting under kill/restart, cross-process telemetry aggregation
(ISSUE 15 tentpole coverage), and the disaggregation plane — KV-page
serialization round-trips on every cache layout, cross-replica prefix
shipping, and scale-down migration (ISSUE 18). Router tests run on
in-process sim engines — the process-worker path is covered by
tools/fleet_bench and tools/chaos_drill (smoke gates)."""

import io
import os
import subprocess
import sys

import pytest

from paddle_tpu.fleet import (FleetBackpressure, FleetConfig, FleetRequest,
                              PrefixCache, Router, SimConfig, SimEngine,
                              aggregate_telemetry, prefix_key)
from paddle_tpu.fleet import metrics as fm
from paddle_tpu.fleet.protocol import (MAX_FRAME, Binary, FrameReader,
                                       pack_pages, read_frame, send_frame,
                                       send_binary_frame, unpack_pages)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- prefix_key ---------------------------------------------------------------
class TestPrefixKey:
    def test_deterministic_and_order_sensitive(self):
        assert prefix_key([1, 2, 3]) == prefix_key([1, 2, 3])
        assert prefix_key([1, 2, 3]) != prefix_key([3, 2, 1])
        assert prefix_key([1, 2]) != prefix_key([1, 2, 3])
        # numpy ints and Python ints hash identically
        import numpy as np

        assert prefix_key(np.array([5, 6, 7])) == prefix_key([5, 6, 7])

    def test_stable_across_processes(self):
        """The router and its worker replicas MUST derive the same key
        from the same tokens — Python hash() is salted per process, so
        this would fail if prefix_key ever leaned on it."""
        toks = list(range(40, 72))
        out = subprocess.run(
            [sys.executable, "-c",
             "from paddle_tpu.fleet.prefix_cache import prefix_key;"
             "print(prefix_key(range(40, 72)))"],
            cwd=_REPO, env=dict(os.environ, JAX_PLATFORMS="cpu",
                                PYTHONHASHSEED="12345"),
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == prefix_key(toks)


# -- PrefixCache (host bookkeeping) -------------------------------------------
class TestPrefixCache:
    def test_cacheable_len_keeps_a_remainder_token(self):
        c = PrefixCache(page_budget=8, page_size=8)
        # a prompt that exactly fills pages still leaves >= 1 token out
        assert c.cacheable_len(16) == 8
        assert c.cacheable_len(17) == 16
        assert c.cacheable_len(8) == 0
        assert c.cacheable_len(3) == 0

    def test_insert_lookup_longest_match(self):
        c = PrefixCache(page_budget=8, page_size=4)
        base = list(range(100, 112))  # 12 tokens = 3 pages
        ok, evicted = c.insert(base[:4], [0])
        assert ok and not evicted
        ok, _ = c.insert(base[:8], [1, 2])
        assert ok
        # longest page-aligned prefix wins: 12-token prompt -> 8-token hit
        hit = c.lookup(base + [999])
        assert hit is not None and hit.tokens == tuple(base[:8])
        assert hit.pages == [1, 2]
        # shorter prompt falls back to the 4-token entry
        hit = c.lookup(base[:6])
        assert hit is not None and hit.tokens == tuple(base[:4])
        # different tokens with the same length miss entirely
        assert c.lookup([7] * 12) is None

    def test_refusals_keep_ownership_with_caller(self):
        c = PrefixCache(page_budget=2, page_size=4)
        assert c.insert([1, 2, 3, 4], [10]) == (True, [])
        # duplicate: refused, nothing evicted
        assert c.insert([1, 2, 3, 4], [11]) == (False, [])
        # token/page length mismatch: refused
        assert c.insert([1, 2, 3], [12]) == (False, [])
        # larger than the whole budget: refused even against an empty LRU
        assert c.insert(list(range(12)), [13, 14, 15]) == (False, [])
        assert c.pages_held == 1

    def test_lru_eviction_returns_pages(self):
        c = PrefixCache(page_budget=2, page_size=4)
        c.insert([1, 2, 3, 4], [10])
        c.insert([5, 6, 7, 8], [11])
        # touch the first entry so the SECOND is LRU
        assert c.lookup([1, 2, 3, 4, 9]) is not None
        ok, evicted = c.insert([9, 10, 11, 12], [12])
        assert ok and evicted == [11], "LRU order ignored recency"
        assert c.pages_held == 2 and len(c) == 2

    def test_flush_returns_every_owned_page(self):
        c = PrefixCache(page_budget=4, page_size=4)
        c.insert([1, 2, 3, 4], [10])
        c.insert([5, 6, 7, 8], [11, 12][:1])
        assert sorted(c.flush()) == [10, 11]
        assert c.pages_held == 0 and len(c) == 0 and c.flush() == []

    def test_counters_tick(self):
        h0, m0 = fm.PREFIX_HITS.value, fm.PREFIX_MISSES.value
        i0, e0 = fm.PREFIX_INSERTS.value, fm.PREFIX_EVICTIONS.value
        c = PrefixCache(page_budget=1, page_size=4)
        c.insert([1, 2, 3, 4], [0])
        assert c.lookup([1, 2, 3, 4, 5]) is not None
        assert c.lookup([9, 9, 9, 9, 9]) is None
        c.insert([5, 6, 7, 8], [1])  # evicts the first
        assert fm.PREFIX_HITS.value == h0 + 1
        assert fm.PREFIX_MISSES.value == m0 + 1
        assert fm.PREFIX_INSERTS.value == i0 + 2
        assert fm.PREFIX_EVICTIONS.value == e0 + 1


# -- frame protocol -----------------------------------------------------------
class TestProtocol:
    def test_round_trip(self):
        buf = io.BytesIO()
        docs = [{"op": "submit", "id": 3, "prompt": [1, 2, 3]},
                {"ev": "result", "tokens": list(range(100)),
                 "error": None, "unicode": "påge"}]
        for d in docs:
            send_frame(buf, d)
        buf.seek(0)
        assert [read_frame(buf) for _ in docs] == docs
        assert read_frame(buf) is None  # clean EOF

    def test_torn_frame_is_eof_not_garbage(self):
        buf = io.BytesIO()
        send_frame(buf, {"a": 1})
        data = buf.getvalue()
        for cut in (1, 3, 5, len(data) - 1):  # mid-header and mid-payload
            assert read_frame(io.BytesIO(data[:cut])) is None

    def test_oversized_frame_rejected(self):
        buf = io.BytesIO((MAX_FRAME + 1).to_bytes(4, "big") + b"x")
        with pytest.raises(ValueError):
            read_frame(buf)

    def test_reader_reassembles_split_writes(self):
        r, w = os.pipe()
        try:
            os.set_blocking(r, False)
            reader = FrameReader(r)
            buf = io.BytesIO()
            send_frame(buf, {"n": 1})
            send_frame(buf, {"n": 2})
            data = buf.getvalue()
            got = []
            for i in range(0, len(data), 3):  # drip 3 bytes at a time
                os.write(w, data[i:i + 3])
                got.extend(reader.drain())
            assert got == [{"n": 1}, {"n": 2}]
            os.close(w)
            assert reader.drain() == [] and reader.eof
        finally:
            os.close(r)


# -- binary page-payload frames (ISSUE 18) ------------------------------------
class TestBinaryFrames:
    def test_mixed_json_and_binary_round_trip(self):
        """Binary frames interleave with JSON on the same stream; the
        length-word top bit tells them apart, bytes come back verbatim."""
        buf = io.BytesIO()
        payload = bytes(range(256)) * 7
        send_frame(buf, {"op": "submit", "id": 1})
        send_binary_frame(buf, payload)
        send_frame(buf, {"ev": "result", "id": 1})
        buf.seek(0)
        assert read_frame(buf) == {"op": "submit", "id": 1}
        got = read_frame(buf)
        assert isinstance(got, Binary) and got.payload == payload
        assert read_frame(buf) == {"ev": "result", "id": 1}
        assert read_frame(buf) is None

    def test_torn_binary_frame_is_eof_not_garbage(self):
        buf = io.BytesIO()
        send_binary_frame(buf, b"kvpagebytes" * 100)
        data = buf.getvalue()
        for cut in (1, 3, 4, 10, len(data) - 1):
            assert read_frame(io.BytesIO(data[:cut])) is None

    def test_oversized_binary_rejected_both_ends(self):
        """The sender refuses before poisoning the stream; a reader that
        sees an oversize binary length word raises the same typed error
        (both sides treat it as a corrupt stream, not a big payload)."""
        with pytest.raises(ValueError, match="exceeds MAX_FRAME"):
            send_binary_frame(io.BytesIO(), b"\0" * (MAX_FRAME + 1))
        word = (0x80000000 | (MAX_FRAME + 1)).to_bytes(4, "big")
        with pytest.raises(ValueError, match="exceeds MAX_FRAME"):
            read_frame(io.BytesIO(word + b"x"))
        r, w = os.pipe()
        try:
            os.set_blocking(r, False)
            reader = FrameReader(r)
            os.write(w, word + b"x")
            with pytest.raises(ValueError, match="exceeds MAX_FRAME"):
                reader.drain()
        finally:
            os.close(r)
            os.close(w)

    def test_reader_reassembles_split_binary_writes(self):
        """A binary frame dripped through a pipe in small chunks (the
        kernel tears large page payloads across reads) reassembles into
        one Binary; the torn tail stays buffered between drains."""
        r, w = os.pipe()
        try:
            os.set_blocking(r, False)
            reader = FrameReader(r)
            buf = io.BytesIO()
            send_frame(buf, {"n": 1})
            send_binary_frame(buf, bytes(range(251)) * 5)
            send_frame(buf, {"n": 2})
            data = buf.getvalue()
            got = []
            for i in range(0, len(data), 7):
                os.write(w, data[i:i + 7])
                got.extend(reader.drain())
            assert len(got) == 3
            assert got[0] == {"n": 1} and got[2] == {"n": 2}
            assert isinstance(got[1], Binary)
            assert got[1].payload == bytes(range(251)) * 5
            os.close(w)
            assert reader.drain() == [] and reader.eof
        finally:
            os.close(r)

    def test_pack_unpack_pages_round_trip(self):
        meta = {"ev": "pages", "xid": 7, "layout": "paged", "n_pages": 2}
        blobs = [b"k" * 1000, b"v" * 1000, b"", b"\x00\xff" * 8]
        meta2, blobs2 = unpack_pages(pack_pages(meta, blobs))
        assert blobs2 == blobs
        assert {k: meta2[k] for k in meta} == meta
        assert meta2["blob_lens"] == [1000, 1000, 0, 16]

    def test_torn_page_payload_raises_typed_error(self):
        payload = pack_pages({"ev": "pages", "xid": 1}, [b"abc" * 64])
        for cut in (2, 6, len(payload) - 1):
            with pytest.raises(ValueError, match="torn page payload"):
                unpack_pages(payload[:cut])
        with pytest.raises(ValueError, match="torn page payload"):
            unpack_pages(payload + b"extra")


# -- router over in-process sims ----------------------------------------------
def _sim_router(n=2, slots=2, **kw):
    kw.setdefault("affinity", "round_robin")
    return Router(FleetConfig(
        replicas=n, mode="inprocess",
        engine_factory=lambda i: SimEngine(SimConfig(slots=slots)), **kw))


class TestRouter:
    def test_exactly_once_and_seed_pinning(self):
        router = _sim_router()
        frs = [router.submit([1, i], 4) for i in range(8)]
        assert all(f.seed is not None for f in frs), \
            "unseeded requests cannot replay deterministically"
        assert router.wait_all(20.0)
        acc = router.accounting()
        assert len(acc) == 8 and set(acc.values()) == {"finished"}
        assert all(len(f.tokens) == 4 for f in frs)
        router.close()

    def test_backpressure_is_typed_not_silent(self):
        router = _sim_router(n=1, max_queue=2, max_outstanding=1)
        router.submit([1], 4)
        router.submit([2], 4)
        with pytest.raises(FleetBackpressure):
            router.submit([3], 4)
        assert router.wait_all(20.0)
        router.close()
        with pytest.raises(FleetBackpressure):
            router.submit([4], 4)  # closed router rejects loudly too

    def test_kill_requeues_and_replays_bit_identical(self):
        req0 = fm.REQUEUED.value
        router = _sim_router(n=2, slots=1)
        frs = [router.submit([3, 3, i], 6, temperature=0.9)
               for i in range(6)]
        for _ in range(2):
            router.pump()
        router._replicas[1].kill()
        assert router.wait_all(20.0)
        assert set(router.accounting().values()) == {"finished"}
        assert fm.REQUEUED.value > req0
        twin = _sim_router(n=1, slots=1)
        frs_t = [twin.submit([3, 3, i], 6, temperature=0.9)
                 for i in range(6)]
        assert twin.wait_all(20.0)
        assert [f.tokens for f in frs] == [f.tokens for f in frs_t]
        router.close()
        twin.close()

    def test_requeue_limit_fails_loudly(self):
        """A request that keeps landing on dying replicas must become
        FAILED — never retry forever, never vanish."""
        router = _sim_router(n=1, slots=1, requeue_limit=1,
                             auto_restart=False)
        fr = router.submit([1, 2], 4)
        router.pump()
        router._replicas[0].kill()
        # manual respawn/kill cycle: each pump requeues, each kill burns
        # one attempt
        for _ in range(4):
            router.pump()
            if fr.terminal:
                break
            router._respawn(0)
            router.pump()
            router._replicas[0].kill()
        assert fr.state == "failed", fr.state
        assert router.accounting()[fr.id] == "failed"
        router.close()

    def test_rolling_restart_rejects_nothing(self):
        router = _sim_router(n=2)
        frs = [router.submit([2, i], 5) for i in range(6)]
        for _ in range(2):
            router.pump()
        router.rolling_restart(10.0)
        assert router.wait_all(20.0)
        acc = router.accounting()
        assert "rejected" not in acc.values(), acc
        assert all(f.state == "finished" for f in frs)
        router.close()

    def test_degraded_replica_gets_no_new_traffic(self):
        engines = {}

        def factory(i):
            engines[i] = SimEngine(SimConfig(slots=2))
            return engines[i]

        router = Router(FleetConfig(replicas=2, mode="inprocess",
                                    affinity="round_robin",
                                    engine_factory=factory))
        engines[0].force_degraded = True
        frs = [router.submit([4, i], 3) for i in range(6)]
        assert router.wait_all(20.0)
        assert all(f.state == "finished" for f in frs)
        assert all(f.last_replica == 1 for f in frs), \
            [f.last_replica for f in frs]
        router.close()

    def test_drain_terminates_everything_exactly_once(self):
        router = _sim_router(n=2)
        frs = [router.submit([6, i], 4) for i in range(5)]
        router.drain()
        states = {f.state for f in frs}
        assert states <= {"finished", "rejected"}, states
        acc = router.accounting()
        assert len(acc) == 5 and all(v in ("finished", "rejected")
                                     for v in acc.values())

    def test_fleet_request_doc_round_trips_the_wire_fields(self):
        fr = FleetRequest(7, [1, 2, 3], 5, temperature=0.5, top_k=3,
                          seed=42)
        d = fr.doc()
        assert d["id"] == 7 and d["prompt"] == [1, 2, 3]
        assert d["max_new_tokens"] == 5 and d["seed"] == 42
        import json

        assert json.loads(json.dumps(d)) == d  # frame-protocol safe

    def test_fleet_request_doc_carries_speculation(self):
        """The per-request speculation override rides the wire frame —
        parsed at the router (so a bad value fails at submit, not on a
        replica), JSON-safe in every accepted form."""
        import json

        assert FleetRequest(8, [1, 2], 4,
                            speculation="auto").doc()["speculation"] == "auto"
        assert FleetRequest(9, [1], 4).doc()["speculation"] is None
        assert FleetRequest(10, [1], 4,
                            speculation="off").doc()["speculation"] == 0
        d = FleetRequest(11, [1], 4, speculation=64).doc()
        assert isinstance(d["speculation"], int)  # capped, still an int
        assert json.loads(json.dumps(d)) == d
        with pytest.raises(ValueError):
            FleetRequest(12, [1], 4, speculation=-3)

    def test_sim_replica_accepts_speculative_submits(self):
        """Sim engines ignore speculation but must accept the doc field —
        a fleet mixing sim and real replicas routes the same wire form to
        both."""
        router = _sim_router(n=1)
        fr = router.submit([5, 5, 5], 4, speculation="auto")
        assert router.wait_all(20.0)
        assert fr.state == "finished" and len(fr.tokens) == 4
        router.close()


# -- telemetry aggregation ----------------------------------------------------
class TestAggregateTelemetry:
    def test_merges_replica_rings(self, tmp_path):
        from paddle_tpu.monitor import metrics as mx
        from paddle_tpu.monitor import telemetry

        mx.enable()
        base = str(tmp_path / "fleet")
        for i in range(3):
            d = os.path.join(base, "replica_%d" % i)
            os.makedirs(d)
            exp = telemetry.TelemetryExporter(d, interval_s=999.0)
            mx.counter("test/fleet_agg").inc(i + 1)
            exp.tick()
            exp.stop()
        agg = aggregate_telemetry(base)
        assert sorted(agg) == ["replica_0", "replica_1", "replica_2"]
        for v in agg.values():
            assert v["samples"] >= 1 and "last" in v

    def test_empty_base_is_empty_not_fatal(self, tmp_path):
        assert aggregate_telemetry(str(tmp_path)) == {}
        assert aggregate_telemetry(str(tmp_path / "nonexistent")) == {}

    def test_degenerate_rings_flag_not_throw(self, tmp_path):
        """The three ways a replica's ring goes wrong — never ticked,
        crashed mid-append, never appeared — each yield a flagged entry,
        never an exception, never a silent hole."""
        base = str(tmp_path / "fleet")
        os.makedirs(os.path.join(base, "replica_0"))  # spawned, no tick yet
        d1 = os.path.join(base, "replica_1")          # torn tail only
        os.makedirs(d1)
        with open(os.path.join(d1, "telemetry_123_0.jsonl"), "w") as f:
            f.write('{"schema": "paddle_tpu.telemetry/v1", "seq": 1, "tr')
        agg = aggregate_telemetry(base, expected=[0, 1, 2])
        assert agg["replica_0"]["flag"] == "no complete samples"
        assert agg["replica_1"]["flag"] == "no complete samples"
        assert agg["replica_2"]["flag"] == "ring dir missing"
        assert all(v["samples"] == 0 for v in agg.values())

    def test_missing_base_with_expected_flags_every_replica(self, tmp_path):
        agg = aggregate_telemetry(str(tmp_path / "never_made"), expected=[0, 1])
        assert sorted(agg) == ["replica_0", "replica_1"]
        assert all(v["flag"] == "ring dir missing" for v in agg.values())

    def test_numeric_replica_order(self, tmp_path):
        base = str(tmp_path / "fleet")
        for i in (0, 1, 2, 10):
            os.makedirs(os.path.join(base, "replica_%d" % i))
        assert list(aggregate_telemetry(base)) == [
            "replica_0", "replica_1", "replica_2", "replica_10"]


# -- fleet event log ----------------------------------------------------------
class TestFleetEventLog:
    def test_round_trip_skips_torn_tail(self, tmp_path):
        from paddle_tpu.fleet.events import FleetEventLog, read_events

        p = str(tmp_path / "events.jsonl")
        log = FleetEventLog(p)
        assert log.armed
        log.emit("spawn", replica=0)
        log.emit("kill_detected", replica=0, lost=2)
        log.close()
        with open(p, "a") as f:
            f.write('{"kind": "torn')  # crash mid-append
        evs = read_events(p)
        assert [e["kind"] for e in evs] == ["spawn", "kill_detected"]
        assert len({e["run_id"] for e in evs}) == 1
        kills = read_events(p, kind="kill_detected")
        assert len(kills) == 1 and kills[0]["lost"] == 2

    def test_unwritable_path_disarms_never_raises(self, tmp_path):
        from paddle_tpu.fleet.events import FleetEventLog

        bad = os.path.join(str(tmp_path / "file_not_dir"), "x", "e.jsonl")
        with open(str(tmp_path / "file_not_dir"), "w") as f:
            f.write("occupied")
        log = FleetEventLog(bad)
        assert not log.armed
        assert log.emit("spawn", replica=0) is None  # no-op, no raise


# -- fleet SLO plane ----------------------------------------------------------
class TestFleetSLO:
    def test_merge_fleet_docs_sums_deltas(self):
        from paddle_tpu.fleet.slo import merge_fleet_docs

        docs = [
            {"t": 10.0, "dt_s": 2.0,
             "metrics": {"g": {"type": "gauge", "value": 2.0}},
             "deltas": {"counters": {"c": 1.0}, "gauges": {"g": 2.0},
                        "histograms": {"h": {"count": 2, "sum": 10.0,
                                             "buckets": {"5": 2}}}}},
            {"t": 11.0, "dt_s": 3.0,
             "metrics": {"g": {"type": "gauge", "value": 3.0}},
             "deltas": {"counters": {"c": 2.0}, "gauges": {"g": 3.0},
                        "histograms": {"h": {"count": 1, "sum": 7.0,
                                             "buckets": {"10": 1}}}}},
        ]
        s = merge_fleet_docs(docs, seq=1)
        assert s.counter_delta("c") == 3.0
        assert s.gauge_value("g") == 5.0  # queue depths ADD across a fleet
        h = s.histogram_delta("h")
        assert h["count"] == 3 and h["sum"] == 17.0
        assert h["buckets"] == {"5": 2, "10": 1}
        assert s.dt_s == 3.0  # widest window, not the sum

    def test_breach_fires_both_scopes_and_cursor_dedupes(self, tmp_path):
        import json

        from paddle_tpu.fleet.slo import FleetSLO
        from paddle_tpu.monitor.slo import parse_slos

        base = str(tmp_path)
        d = os.path.join(base, "replica_0")
        os.makedirs(d)
        doc = {"schema": "paddle_tpu.telemetry/v1", "seq": 1, "pid": 1,
               "t": 1.0, "dt_s": 1.0,
               "metrics": {"fleet/queue_depth": {"type": "gauge",
                                                 "value": 9.0}},
               "deltas": {"counters": {}, "histograms": {},
                          "gauges": {"fleet/queue_depth": 9.0}}}
        with open(os.path.join(d, "telemetry_1_0.jsonl"), "w") as f:
            f.write(json.dumps(doc) + "\n")
        hits = []
        slo = FleetSLO(
            parse_slos("fleet/queue_depth<=5"),
            on_replica_breach=lambda i, b: hits.append(("replica", i)),
            on_fleet_breach=lambda b: hits.append(("fleet",)))
        out = slo.evaluate(base, [0])
        assert out["replica"].get(0) and out["fleet"]
        assert ("replica", 0) in hits and ("fleet",) in hits
        # per-(replica, pid) seq cursor: the same sample never
        # re-evaluates on the next pass
        hits.clear()
        assert slo.evaluate(base, [0]) == {"replica": {}, "fleet": []}
        assert not hits


# -- fleet trace: orphan closure + in-process round trip ----------------------
class TestFleetTrace:
    def test_close_orphans_synthesizes_tagged_closures(self):
        from paddle_tpu.fleet import trace as ftrace

        spans = [
            {"name": "submitted", "cat": "fleet", "ts_us": 0, "dur_us": 0,
             "pid": 1, "tid": -1, "track": ftrace.QUEUE_TRACK,
             "args": {"trace_id": "t1"}},
            {"name": "queued", "cat": "fleet", "ts_us": 0, "dur_us": 5,
             "pid": 1, "tid": -1, "track": ftrace.QUEUE_TRACK,
             "args": {"trace_id": "t1", "attempt": 1}},
            # a dispatch whose attempt never closed and a request with no
            # terminal: what a SIGKILLed ROUTER would leave behind
            {"name": "dispatch", "cat": "fleet", "ts_us": 5, "dur_us": 0,
             "pid": 1, "tid": -2, "track": "replica 0",
             "args": {"trace_id": "t1", "attempt": 1}},
            {"name": "drain", "cat": "fleet", "ts_us": 0, "dur_us": 100,
             "pid": 1, "tid": -3, "track": ftrace.LIFECYCLE_TRACK,
             "args": {}},
        ]
        out, n = ftrace.close_orphans(spans)
        assert n == 2
        synth = [s for s in out if (s.get("args") or {}).get("synthetic")]
        att = next(s for s in synth if s["name"] == "attempt 1")
        assert att["args"]["killed"] and att["dur_us"] >= 1
        term = next(s for s in synth if s["name"] == "failed")
        assert term["dur_us"] == 0
        # the validator runs the same closure pass itself on raw spans
        digests = ftrace.validate_fleet_spans(spans)
        assert digests["t1"]["synthetic"]
        assert digests["t1"]["state"] == "failed"
        assert digests["_meta"]["synthetic_closures"] == 2

    def test_inprocess_router_trace_validates(self, tmp_path):
        """A traced in-process fleet round trip: the router's own spans
        alone form a validating request tree (submitted -> queued ->
        dispatch -> attempt 1 -> terminal), zero synthetic closures."""
        from paddle_tpu.fleet import trace as ftrace

        trace_dir = str(tmp_path / "trace")
        router = Router(FleetConfig(
            replicas=2, mode="inprocess", affinity="round_robin",
            engine_factory=lambda i: SimEngine(SimConfig(slots=2)),
            trace_dir=trace_dir))
        frs = [router.submit([1, i], 4) for i in range(5)]
        assert router.wait_all(20.0)
        router.close()
        spans, manifest, problems = ftrace.load_fragments(trace_dir)
        assert not problems and manifest.get("run_id")
        digests = ftrace.validate_fleet_spans(spans)
        meta = digests.pop("_meta")
        assert meta["requests"] == 5
        assert meta["synthetic_closures"] == 0
        assert all(d["state"] == "finished" and d["attempts"] == [1]
                   for d in digests.values())
        trace_ids = {f.trace_id for f in frs}
        assert set(digests) == trace_ids


# -- speculative requests through the fleet (real engines) --------------------
class TestFleetSpeculative:
    @staticmethod
    def _real_router(model, n=2):
        from paddle_tpu import serving

        def factory(i):
            return serving.ServingEngine(model, serving.ServingConfig(
                slots=2, page_size=8, max_seq=64))

        return Router(FleetConfig(replicas=n, mode="inprocess",
                                  affinity="round_robin",
                                  engine_factory=factory))

    def test_kill_replays_speculative_bit_identical(self, tiny_model):
        """A speculative request stranded by a killed replica must
        requeue and replay BIT-identically to an unkilled twin: greedy
        draft-verify emits the same (seed, position)-keyed stream as
        plain decode, so the fleet's replay invariant holds unchanged
        even when the respawned replica re-runs the whole request."""
        import numpy as np

        rng = np.random.RandomState(5)
        prompts = [list(rng.randint(0, 64, 3)) * 4 for _ in range(4)]
        req0 = fm.REQUEUED.value
        router = self._real_router(tiny_model)
        frs = [router.submit(p, 6, speculation=4) for p in prompts]
        for _ in range(2):
            router.pump()
        router._replicas[1].kill()
        assert router.wait_all(120.0)
        assert set(router.accounting().values()) == {"finished"}
        assert fm.REQUEUED.value > req0, "the kill stranded nothing"
        router.close()
        twin = self._real_router(tiny_model, n=1)
        frs_t = [twin.submit(p, 6, speculation=4) for p in prompts]
        assert twin.wait_all(120.0)
        twin.close()
        assert [f.tokens for f in frs] == [f.tokens for f in frs_t], \
            "a requeued speculative replay diverged from its unkilled twin"

    def test_speculative_verify_spans_nest_in_decode_windows(
            self, tiny_model, tmp_path):
        """Trace-validator leg for the speculation/autopsy join: a traced
        speculative fleet run must emit verify-tagged decode spans
        (phase=verify, accepted <= proposed accounting) that nest inside
        BOTH the request's serving lifetime span and the fleet attempt
        (dispatch) window — the containment the phase ledger relies on to
        attribute verify windows per request."""
        import numpy as np

        from paddle_tpu import serving
        from paddle_tpu.fleet import trace as ftrace
        from paddle_tpu.serving import trace as svtrace

        trace_dir = str(tmp_path / "trace")

        def factory(i):
            return serving.ServingEngine(tiny_model, serving.ServingConfig(
                slots=2, page_size=8, max_seq=64))

        # ONE replica: two traced in-process engines would collide on the
        # shared "serving slot <k>" virtual tracks
        router = Router(FleetConfig(replicas=1, mode="inprocess",
                                    affinity="round_robin",
                                    engine_factory=factory,
                                    trace_dir=trace_dir))
        rng = np.random.RandomState(7)
        prompts = [list(rng.randint(0, 64, 3)) * 4 for _ in range(3)]
        frs = [router.submit(p, 6, speculation=4) for p in prompts]
        assert router.wait_all(120.0)
        router.close()

        spans, manifest, problems = ftrace.load_fragments(trace_dir)
        assert not problems and manifest.get("run_id")
        digests = ftrace.validate_fleet_spans(spans)
        assert digests.pop("_meta")["synthetic_closures"] == 0
        # the serving-cat schedule is well-nested across the merged stream
        svtrace.assert_well_nested(spans)

        verify = [s for s in spans
                  if s.get("cat") == "serving" and s["name"] == "decode"
                  and (s.get("args") or {}).get("phase") == "verify"]
        assert verify, "speculative run emitted no verify-tagged spans"
        for s in verify:
            a = s["args"]
            assert a.get("verify") is True, a
            assert 0 <= a["accepted"] <= a["proposed"], a
            assert a.get("window", 0) >= 1, a
        assert sum(s["args"]["proposed"] for s in verify) > 0

        life = {(s.get("args") or {}).get("trace_id"):
                (s["ts_us"], s["ts_us"] + s["dur_us"])
                for s in spans
                if s.get("cat") == "serving" and s["name"].startswith("req ")}
        attempts = {((s.get("args") or {}).get("trace_id"),
                     (s.get("args") or {}).get("attempt")):
                    (s["ts_us"], s["ts_us"] + s["dur_us"])
                    for s in spans
                    if s.get("cat") == "fleet"
                    and s["name"].startswith("attempt ")}
        seen = set()
        for s in verify:
            a = s["args"]
            tid = a["trace_id"]
            seen.add(tid)
            lo, hi = s["ts_us"], s["ts_us"] + s["dur_us"]
            llo, lhi = life[tid]
            assert llo <= lo and hi <= lhi, \
                "verify span [%d,%d] escapes lifetime [%d,%d] of %s" \
                % (lo, hi, llo, lhi, tid)
            alo, ahi = attempts[(tid, a.get("attempt", 1))]
            assert alo <= lo and hi <= ahi, \
                "verify span [%d,%d] escapes attempt window [%d,%d] of %s" \
                % (lo, hi, alo, ahi, tid)
        assert seen == {f.trace_id for f in frs}, \
            "some speculative request decoded without a verify window"


# -- engine-level prefix cache (real model) -----------------------------------
@pytest.fixture(scope="module")
def tiny_model():
    from paddle_tpu.models import decoder_lm

    cfg = decoder_lm.DecoderConfig(vocab_size=64, n_layer=1, d_model=16,
                                   n_head=2, max_seq=64)
    return decoder_lm.DecoderLM(cfg, seed=3)


def _prefix_engine(model, pages=8):
    from paddle_tpu import serving

    return serving.ServingEngine(model, serving.ServingConfig(
        slots=2, page_size=8, max_seq=64, num_pages=32,
        prefix_cache_pages=pages))


class TestEnginePrefixCache:
    def test_config_validates_budget(self, tiny_model):
        from paddle_tpu import serving

        with pytest.raises(ValueError):
            serving.ServingConfig(slots=2, page_size=8, max_seq=64,
                                  num_pages=16, prefix_cache_pages=16)

    def test_hit_skips_prefill_and_matches_cold_stream(self, tiny_model):
        from paddle_tpu.serving import metrics as sm

        sys_prompt = list(range(1, 18))  # 17 tokens: 2 full pages cached
        eng = _prefix_engine(tiny_model)
        p0 = sm.PREFILL_COUNT.value
        h0 = fm.PREFIX_HITS.value
        r1 = eng.submit(sys_prompt + [30], 5, temperature=0.8, seed=11)
        eng.run()
        r2 = eng.submit(sys_prompt + [30], 5, temperature=0.8, seed=11)
        eng.run()
        assert r1.state == r2.state == "finished"
        assert list(r2.tokens_out) == list(r1.tokens_out), \
            "a prefix hit changed the sampled stream"
        assert fm.PREFIX_HITS.value == h0 + 1
        assert sm.PREFILL_COUNT.value == p0 + 1, \
            "the warm request still dispatched a full prefill"
        assert eng.page_accounting_ok()
        eng.drain(10.0)
        assert eng.pool.num_used == 0, "prefix pages leaked through drain"

    def test_failed_request_never_donates(self, tiny_model):
        from paddle_tpu.reliability import FaultPlan, faults

        eng = _prefix_engine(tiny_model)
        pk0 = fm.PREFIX_POISONED_SKIPPED.value
        plan = FaultPlan([faults.FaultSpec("serving.decode", "fatal",
                                           at=1, times=1)])
        with plan:
            bad = eng.submit(list(range(1, 18)), 5)
            eng.run(max_steps=50)
        assert bad.state == "failed"
        assert fm.PREFIX_POISONED_SKIPPED.value > pk0
        assert len(eng.prefix_cache) == 0, \
            "a FAILED request's pages entered the prefix cache"
        assert eng.page_accounting_ok() and eng.pool.num_used == 0
        # the poisoned prefix is structurally unservable: a fresh request
        # with the same prompt misses and re-prefills cleanly
        h0 = fm.PREFIX_HITS.value
        good = eng.submit(list(range(1, 18)), 3, seed=5)
        eng.run()
        assert good.state == "finished" and fm.PREFIX_HITS.value == h0
        eng.drain(10.0)

    def test_accounting_includes_cache_owned_pages(self, tiny_model):
        eng = _prefix_engine(tiny_model)
        r = eng.submit(list(range(1, 18)), 3, seed=9)
        eng.run()
        assert r.state == "finished"
        assert eng.prefix_cache.pages_held == 2
        assert eng.pool.num_used == 2, "donated pages were double-freed"
        assert eng.page_accounting_ok()
        eng.drain(10.0)
        assert eng.pool.num_used == 0


# -- KV-page serialization (ISSUE 18: every layout round-trips or refuses) ----
class TestPagePayloadLayouts:
    @staticmethod
    def _fp_cache():
        import jax.numpy as jnp

        from paddle_tpu.serving.kv_cache import PagedKVCache

        return PagedKVCache(n_layer=2, n_head=2, d_head=4, slots=2,
                            max_ctx=32, page_size=8, num_pages=6,
                            dtype=jnp.float32)

    @staticmethod
    def _fill(cache, state, seed):
        import jax.numpy as jnp
        import numpy as np

        rng = np.random.RandomState(seed)
        shp = state["k"].shape
        return {**state,
                "k": jnp.asarray(rng.randn(*shp).astype(state["k"].dtype)
                                 if state["k"].dtype != np.int8 else
                                 rng.randint(-127, 128, shp, np.int8)),
                "v": jnp.asarray(rng.randn(*shp).astype(state["v"].dtype)
                                 if state["v"].dtype != np.int8 else
                                 rng.randint(-127, 128, shp, np.int8))}

    def test_fp_paged_round_trip_bit_exact(self):
        """fp pages exported from one pool and imported into DIFFERENT
        page ids of another re-export the exact same bytes — raw C-order
        rows, no float formatting anywhere in the path."""
        src = self._fp_cache()
        s_state = self._fill(src, src.init_state(), seed=1)
        meta, blobs = src.export_pages(s_state, [1, 3])
        assert meta["n_pages"] == 2 and len(blobs) == 2
        dst = self._fp_cache()
        d_state = self._fill(dst, dst.init_state(), seed=2)  # noisy pool
        d_state = dst.import_pages(d_state, [4, 2], meta, blobs)
        meta2, blobs2 = dst.export_pages(d_state, [4, 2])
        assert blobs2 == blobs, "fp page bytes mutated in transit"
        assert {k: meta2[k] for k in meta} == meta

    def test_int8_paged_round_trip_carries_scales(self):
        """int8 pages travel with their per-page fp32 scale columns; the
        importer's own constructor scales never touch imported pages, so
        the re-export is bit-exact including the scales."""
        import jax.numpy as jnp
        import numpy as np

        from paddle_tpu.serving.kv_cache import Int8PagedKVCache

        def mk(ks, vs):
            return Int8PagedKVCache(n_layer=2, n_head=2, d_head=4, slots=2,
                                    max_ctx=32, page_size=8, num_pages=6,
                                    k_scale=ks, v_scale=vs)

        src = mk(0.125, 0.25)
        s_state = self._fill(src, src.init_state(), seed=3)
        # vary the per-page scale columns so the test catches a payload
        # that ships the constructor scalar instead of the page columns
        rng = np.random.RandomState(4)
        s_state = {**s_state,
                   "ks": jnp.asarray(rng.rand(2, 6).astype(np.float32) + .1),
                   "vs": jnp.asarray(rng.rand(2, 6).astype(np.float32) + .1)}
        meta, blobs = src.export_pages(s_state, [0, 5])
        assert len(blobs) == 4, "int8 payload must carry ks/vs columns"
        dst = mk(1.0, 1.0)  # different calibration on purpose
        d_state = dst.import_pages(dst.init_state(), [2, 4], meta, blobs)
        meta2, blobs2 = dst.export_pages(d_state, [2, 4])
        assert blobs2 == blobs, "int8 pages or scale columns mutated"
        got_ks = np.asarray(d_state["ks"][:, [2, 4]])
        want_ks = np.asarray(s_state["ks"][:, [0, 5]])
        assert np.array_equal(got_ks, want_ks), \
            "imported pages dequantize with the wrong scales"

    def test_contiguous_layout_refuses_typed(self):
        """The dense layout has no addressable page unit: both directions
        refuse with ValueError — callers surface 'migration unsupported',
        never a crash or a silent wrong-shape blob."""
        from paddle_tpu.serving.kv_cache import ContiguousKVCache

        cache = ContiguousKVCache(n_layer=1, n_head=2, d_head=4, slots=2,
                                  max_ctx=16)
        state = cache.init_state()
        with pytest.raises(ValueError, match="no pages to export"):
            cache.export_pages(state, [0])
        with pytest.raises(ValueError, match="no pages to import"):
            cache.import_pages(state, [0], {"layout": "contiguous"}, [b""])

    def test_import_refuses_geometry_mismatch(self):
        """Every mismatch is a typed ValueError BEFORE any pool write:
        wrong page_size, wrong blob count, wrong page count, short blobs."""
        import jax.numpy as jnp

        from paddle_tpu.serving.kv_cache import PagedKVCache

        src = self._fp_cache()
        state = self._fill(src, src.init_state(), seed=5)
        meta, blobs = src.export_pages(state, [1, 3])
        other = PagedKVCache(n_layer=2, n_head=2, d_head=4, slots=2,
                             max_ctx=32, page_size=16, num_pages=3,
                             dtype=jnp.float32)
        with pytest.raises(ValueError, match="geometry mismatch"):
            other.import_pages(other.init_state(), [1], meta, blobs)
        dst = self._fp_cache()
        with pytest.raises(ValueError, match="blobs"):
            dst.import_pages(dst.init_state(), [1, 3], meta, blobs[:1])
        with pytest.raises(ValueError, match="pages"):
            dst.import_pages(dst.init_state(), [1], meta, blobs)
        with pytest.raises(ValueError, match="bytes"):
            dst.import_pages(dst.init_state(), [1, 3], meta,
                             [blobs[0][:-4], blobs[1]])

    def test_engine_export_ingest_serves_bit_identical(self, tiny_model):
        """Engine-level round trip: a prefix prefilled on engine A,
        shipped as (meta, blobs), and ingested by engine B serves the
        same request on B as a RESUME — zero prefill dispatches, the
        sampled stream bit-identical, page accounting intact on both."""
        from paddle_tpu.serving import metrics as sm

        prompt = list(range(1, 18))  # 16 cached tokens = 2 pages of 8
        eng_a = _prefix_engine(tiny_model)
        eng_b = _prefix_engine(tiny_model)
        r1 = eng_a.submit(prompt, 5, temperature=0.8, seed=11)
        eng_a.run()
        assert r1.state == "finished"
        exported = eng_a.export_prefix_pages(prompt[:16])
        assert exported is not None, "donated prefix not exportable"
        meta, blobs = exported
        assert eng_b.ingest_prefix_pages(prompt[:16], meta, blobs)
        assert eng_b.page_accounting_ok()
        p0 = sm.PREFILL_COUNT.value
        r2 = eng_b.submit(prompt, 5, temperature=0.8, seed=11)
        eng_b.run()
        assert r2.state == "finished"
        assert list(r2.tokens_out) == list(r1.tokens_out), \
            "shipped pages changed the sampled stream"
        assert sm.PREFILL_COUNT.value == p0, \
            "the ingested prefix did not spare the prefill dispatch"
        # re-export from B: the bytes survived the hop bit-exact
        meta_b, blobs_b = eng_b.export_prefix_pages(prompt[:16])
        assert blobs_b == blobs
        for eng in (eng_a, eng_b):
            assert eng.page_accounting_ok()
            eng.drain(10.0)
            assert eng.pool.num_used == 0, "pages leaked through drain"

    def test_ingest_refusals_leak_nothing(self, tiny_model):
        """Every ingest refusal frees its reservation first: bad token
        count, geometry mismatch, duplicate ingest — pool usage is
        unchanged and page accounting holds after each."""
        prompt = list(range(1, 18))
        eng_a = _prefix_engine(tiny_model)
        eng_b = _prefix_engine(tiny_model)
        r = eng_a.submit(prompt, 3, seed=2)
        eng_a.run()
        assert r.state == "finished"
        meta, blobs = eng_a.export_prefix_pages(prompt[:16])
        used0 = eng_b.pool.num_used
        # token count disagrees with n_pages * page_size
        assert not eng_b.ingest_prefix_pages(prompt[:12], meta, blobs)
        # geometry lie: n_pages beyond the payload
        bad = dict(meta, n_pages=3)
        assert not eng_b.ingest_prefix_pages(prompt[:16] + [77] * 8,
                                             bad, blobs)
        assert eng_b.pool.num_used == used0 and eng_b.page_accounting_ok()
        assert eng_b.ingest_prefix_pages(prompt[:16], meta, blobs)
        # duplicate ingest: no-op success, no second reservation
        used1 = eng_b.pool.num_used
        assert eng_b.ingest_prefix_pages(prompt[:16], meta, blobs)
        assert eng_b.pool.num_used == used1
        eng_a.drain(10.0)
        eng_b.drain(10.0)


# -- disaggregation plane over in-process sims (ISSUE 18) ---------------------
def _disagg_router(roles, n_decode_slots=2, **kw):
    kw.setdefault("affinity", "round_robin")
    return Router(FleetConfig(
        roles=roles, mode="inprocess", page_size=16,
        engine_factory=lambda i: SimEngine(
            SimConfig(slots=n_decode_slots, page_size=16)), **kw))


class TestDisaggRouter:
    def test_prefill_replicas_serve_no_user_requests(self):
        """1-prefill/2-decode fleet: long prompts prefill on the prefill
        replica and decode elsewhere; the streams match a uniform twin
        bit-for-bit and the pages actually migrated."""
        mc0 = fm.MIGRATIONS_COMPLETED.value
        router = _disagg_router("1:2")
        prompts = [[100 + i * 50 + t for t in range(33)] for i in range(4)]
        frs = [router.submit(p, 4, temperature=0.5, seed=40 + i)
               for i, p in enumerate(prompts)]
        assert router.wait_all(30.0)
        acc = router.accounting()
        assert len(acc) == 4 and set(acc.values()) == {"finished"}, acc
        assert all(f.last_replica != 0 for f in frs), \
            "a user request decoded on the prefill replica"
        assert fm.MIGRATIONS_COMPLETED.value > mc0, "nothing migrated"
        snap = router.snapshot()
        assert snap["roles"]["prefill"] == 1
        assert snap["migration"]["active"] == 0
        router.close()
        twin = _sim_router(n=1)
        frs_t = [twin.submit(p, 4, temperature=0.5, seed=40 + i)
                 for i, p in enumerate(prompts)]
        assert twin.wait_all(30.0)
        twin.close()
        assert [f.tokens for f in frs] == [f.tokens for f in frs_t], \
            "disaggregated decode diverged from the uniform twin"

    def test_remote_prefix_hit_ships_across_replicas(self):
        """Uniform fleet with the fleet-wide index armed: a prefix owned
        by replica A serves an identical request forced onto replica B by
        shipping the pages — remote hit counted, stream unchanged."""
        h0 = fm.REMOTE_HITS.value
        router = Router(FleetConfig(
            replicas=2, mode="inprocess", affinity="round_robin",
            page_size=16, fleet_prefix=True,
            engine_factory=lambda i: SimEngine(
                SimConfig(slots=2, page_size=16))))
        prompt = [7 * t % 97 for t in range(33)]
        f1 = router.submit(prompt, 4, temperature=0.5, seed=9)
        assert router.wait_all(30.0)
        owner = f1.last_replica
        router._replicas[owner].accepting = False
        f2 = router.submit(prompt, 4, temperature=0.5, seed=9)
        assert router.wait_all(30.0)
        assert f2.state == "finished" and f2.last_replica == 1 - owner
        assert f2.tokens == f1.tokens, \
            "the remote prefix hit changed the stream"
        assert fm.REMOTE_HITS.value > h0
        router.close()

    def test_failed_migration_falls_back_cold_exactly_once(self):
        """Kill the DESTINATION while pages are in flight toward it: the
        migration fails closed, the carried request blows its no-migrate
        fuse and re-prefills cold — one terminal outcome, same stream."""
        mf0 = fm.MIGRATIONS_FAILED.value
        router = Router(FleetConfig(
            replicas=2, mode="inprocess", affinity="round_robin",
            page_size=16, fleet_prefix=True,
            engine_factory=lambda i: SimEngine(
                SimConfig(slots=2, page_size=16))))
        prompt = [5 * t % 89 for t in range(33)]
        f1 = router.submit(prompt, 4, temperature=0.5, seed=13)
        assert router.wait_all(30.0)
        owner = f1.last_replica
        router._replicas[owner].accepting = False
        f2 = router.submit(prompt, 4, temperature=0.5, seed=13)
        deadline = 200
        while not router._migrations and deadline:
            router.pump()
            deadline -= 1
        assert router._migrations, "no migration started"
        router._replicas[1 - owner].kill()
        assert router.wait_all(30.0)
        acc = router.accounting()
        assert acc[f2.id] == "finished", acc
        assert list(acc.values()).count("finished") == len(acc), acc
        assert fm.MIGRATIONS_FAILED.value > mf0, \
            "the dead owner's migration did not fail closed"
        assert f2.no_migrate, "the failed request can retry migration"
        assert f2.tokens == f1.tokens, "the cold fallback changed tokens"
        router.close()

    def test_manual_rebalance_moves_ownership(self):
        """rebalance() is a MOVE: the pages ship to the destination and
        are evicted at the source, and the fleet index re-points the
        prefix at its new owner."""
        mc0 = fm.MIGRATIONS_COMPLETED.value
        router = Router(FleetConfig(
            replicas=2, mode="inprocess", affinity="round_robin",
            page_size=16, fleet_prefix=True,
            engine_factory=lambda i: SimEngine(
                SimConfig(slots=2, page_size=16))))
        prompt = [3 * t % 83 for t in range(33)]
        f1 = router.submit(prompt, 3, temperature=0.5, seed=21)
        assert router.wait_all(30.0)
        owner = f1.last_replica
        key, ent = next(iter(router._prefix_index.items()))
        assert ent["owners"] == {owner}
        xid = router.rebalance(owner, 1 - owner, ent["tokens"])
        assert xid is not None
        for _ in range(200):
            if not router._migrations:
                break
            router.pump()
        assert not router._migrations, "rebalance never resolved"
        assert fm.MIGRATIONS_COMPLETED.value > mc0
        assert router._prefix_index[key]["owners"] == {1 - owner}, \
            "ownership did not move with the pages"
        router.close()

    def test_scale_down_migrates_and_retires(self):
        """scale_down drains a live replica: in-flight work re-lands
        elsewhere, every request reaches one terminal outcome, and the
        victim takes no further traffic."""
        router = _sim_router(n=3)
        frs = [router.submit([9, 9, 9, i], 6, temperature=0.4, seed=60 + i)
               for i in range(6)]
        for _ in range(2):
            router.pump()
        out = router.scale_down(1)
        assert out["replica"] == 1
        assert router.wait_all(30.0)
        acc = router.accounting()
        assert len(acc) == 6 and set(acc.values()) == {"finished"}, acc
        snap = router.snapshot()
        victim = next(r for r in snap["replicas"]
                      if r["name"] == "replica-1")
        assert victim["retired"] and not victim["alive"]
        router.close()
        # the retired slot stays down: nothing respawns it afterwards
        assert not router._replicas[1].alive
