"""Fleet subsystem tests: prefix-cache hashing/LRU/poisoning, the frame
protocol, router exactly-once accounting under kill/restart, and
cross-process telemetry aggregation (ISSUE 15 tentpole coverage). Router
tests run on in-process sim engines — the process-worker path is covered
by tools/fleet_bench and tools/chaos_drill (smoke gates)."""

import io
import os
import subprocess
import sys

import pytest

from paddle_tpu.fleet import (FleetBackpressure, FleetConfig, FleetRequest,
                              PrefixCache, Router, SimConfig, SimEngine,
                              aggregate_telemetry, prefix_key)
from paddle_tpu.fleet import metrics as fm
from paddle_tpu.fleet.protocol import MAX_FRAME, FrameReader, read_frame, \
    send_frame

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- prefix_key ---------------------------------------------------------------
class TestPrefixKey:
    def test_deterministic_and_order_sensitive(self):
        assert prefix_key([1, 2, 3]) == prefix_key([1, 2, 3])
        assert prefix_key([1, 2, 3]) != prefix_key([3, 2, 1])
        assert prefix_key([1, 2]) != prefix_key([1, 2, 3])
        # numpy ints and Python ints hash identically
        import numpy as np

        assert prefix_key(np.array([5, 6, 7])) == prefix_key([5, 6, 7])

    def test_stable_across_processes(self):
        """The router and its worker replicas MUST derive the same key
        from the same tokens — Python hash() is salted per process, so
        this would fail if prefix_key ever leaned on it."""
        toks = list(range(40, 72))
        out = subprocess.run(
            [sys.executable, "-c",
             "from paddle_tpu.fleet.prefix_cache import prefix_key;"
             "print(prefix_key(range(40, 72)))"],
            cwd=_REPO, env=dict(os.environ, JAX_PLATFORMS="cpu",
                                PYTHONHASHSEED="12345"),
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == prefix_key(toks)


# -- PrefixCache (host bookkeeping) -------------------------------------------
class TestPrefixCache:
    def test_cacheable_len_keeps_a_remainder_token(self):
        c = PrefixCache(page_budget=8, page_size=8)
        # a prompt that exactly fills pages still leaves >= 1 token out
        assert c.cacheable_len(16) == 8
        assert c.cacheable_len(17) == 16
        assert c.cacheable_len(8) == 0
        assert c.cacheable_len(3) == 0

    def test_insert_lookup_longest_match(self):
        c = PrefixCache(page_budget=8, page_size=4)
        base = list(range(100, 112))  # 12 tokens = 3 pages
        ok, evicted = c.insert(base[:4], [0])
        assert ok and not evicted
        ok, _ = c.insert(base[:8], [1, 2])
        assert ok
        # longest page-aligned prefix wins: 12-token prompt -> 8-token hit
        hit = c.lookup(base + [999])
        assert hit is not None and hit.tokens == tuple(base[:8])
        assert hit.pages == [1, 2]
        # shorter prompt falls back to the 4-token entry
        hit = c.lookup(base[:6])
        assert hit is not None and hit.tokens == tuple(base[:4])
        # different tokens with the same length miss entirely
        assert c.lookup([7] * 12) is None

    def test_refusals_keep_ownership_with_caller(self):
        c = PrefixCache(page_budget=2, page_size=4)
        assert c.insert([1, 2, 3, 4], [10]) == (True, [])
        # duplicate: refused, nothing evicted
        assert c.insert([1, 2, 3, 4], [11]) == (False, [])
        # token/page length mismatch: refused
        assert c.insert([1, 2, 3], [12]) == (False, [])
        # larger than the whole budget: refused even against an empty LRU
        assert c.insert(list(range(12)), [13, 14, 15]) == (False, [])
        assert c.pages_held == 1

    def test_lru_eviction_returns_pages(self):
        c = PrefixCache(page_budget=2, page_size=4)
        c.insert([1, 2, 3, 4], [10])
        c.insert([5, 6, 7, 8], [11])
        # touch the first entry so the SECOND is LRU
        assert c.lookup([1, 2, 3, 4, 9]) is not None
        ok, evicted = c.insert([9, 10, 11, 12], [12])
        assert ok and evicted == [11], "LRU order ignored recency"
        assert c.pages_held == 2 and len(c) == 2

    def test_flush_returns_every_owned_page(self):
        c = PrefixCache(page_budget=4, page_size=4)
        c.insert([1, 2, 3, 4], [10])
        c.insert([5, 6, 7, 8], [11, 12][:1])
        assert sorted(c.flush()) == [10, 11]
        assert c.pages_held == 0 and len(c) == 0 and c.flush() == []

    def test_counters_tick(self):
        h0, m0 = fm.PREFIX_HITS.value, fm.PREFIX_MISSES.value
        i0, e0 = fm.PREFIX_INSERTS.value, fm.PREFIX_EVICTIONS.value
        c = PrefixCache(page_budget=1, page_size=4)
        c.insert([1, 2, 3, 4], [0])
        assert c.lookup([1, 2, 3, 4, 5]) is not None
        assert c.lookup([9, 9, 9, 9, 9]) is None
        c.insert([5, 6, 7, 8], [1])  # evicts the first
        assert fm.PREFIX_HITS.value == h0 + 1
        assert fm.PREFIX_MISSES.value == m0 + 1
        assert fm.PREFIX_INSERTS.value == i0 + 2
        assert fm.PREFIX_EVICTIONS.value == e0 + 1


# -- frame protocol -----------------------------------------------------------
class TestProtocol:
    def test_round_trip(self):
        buf = io.BytesIO()
        docs = [{"op": "submit", "id": 3, "prompt": [1, 2, 3]},
                {"ev": "result", "tokens": list(range(100)),
                 "error": None, "unicode": "påge"}]
        for d in docs:
            send_frame(buf, d)
        buf.seek(0)
        assert [read_frame(buf) for _ in docs] == docs
        assert read_frame(buf) is None  # clean EOF

    def test_torn_frame_is_eof_not_garbage(self):
        buf = io.BytesIO()
        send_frame(buf, {"a": 1})
        data = buf.getvalue()
        for cut in (1, 3, 5, len(data) - 1):  # mid-header and mid-payload
            assert read_frame(io.BytesIO(data[:cut])) is None

    def test_oversized_frame_rejected(self):
        buf = io.BytesIO((MAX_FRAME + 1).to_bytes(4, "big") + b"x")
        with pytest.raises(ValueError):
            read_frame(buf)

    def test_reader_reassembles_split_writes(self):
        r, w = os.pipe()
        try:
            os.set_blocking(r, False)
            reader = FrameReader(r)
            buf = io.BytesIO()
            send_frame(buf, {"n": 1})
            send_frame(buf, {"n": 2})
            data = buf.getvalue()
            got = []
            for i in range(0, len(data), 3):  # drip 3 bytes at a time
                os.write(w, data[i:i + 3])
                got.extend(reader.drain())
            assert got == [{"n": 1}, {"n": 2}]
            os.close(w)
            assert reader.drain() == [] and reader.eof
        finally:
            os.close(r)


# -- router over in-process sims ----------------------------------------------
def _sim_router(n=2, slots=2, **kw):
    kw.setdefault("affinity", "round_robin")
    return Router(FleetConfig(
        replicas=n, mode="inprocess",
        engine_factory=lambda i: SimEngine(SimConfig(slots=slots)), **kw))


class TestRouter:
    def test_exactly_once_and_seed_pinning(self):
        router = _sim_router()
        frs = [router.submit([1, i], 4) for i in range(8)]
        assert all(f.seed is not None for f in frs), \
            "unseeded requests cannot replay deterministically"
        assert router.wait_all(20.0)
        acc = router.accounting()
        assert len(acc) == 8 and set(acc.values()) == {"finished"}
        assert all(len(f.tokens) == 4 for f in frs)
        router.close()

    def test_backpressure_is_typed_not_silent(self):
        router = _sim_router(n=1, max_queue=2, max_outstanding=1)
        router.submit([1], 4)
        router.submit([2], 4)
        with pytest.raises(FleetBackpressure):
            router.submit([3], 4)
        assert router.wait_all(20.0)
        router.close()
        with pytest.raises(FleetBackpressure):
            router.submit([4], 4)  # closed router rejects loudly too

    def test_kill_requeues_and_replays_bit_identical(self):
        req0 = fm.REQUEUED.value
        router = _sim_router(n=2, slots=1)
        frs = [router.submit([3, 3, i], 6, temperature=0.9)
               for i in range(6)]
        for _ in range(2):
            router.pump()
        router._replicas[1].kill()
        assert router.wait_all(20.0)
        assert set(router.accounting().values()) == {"finished"}
        assert fm.REQUEUED.value > req0
        twin = _sim_router(n=1, slots=1)
        frs_t = [twin.submit([3, 3, i], 6, temperature=0.9)
                 for i in range(6)]
        assert twin.wait_all(20.0)
        assert [f.tokens for f in frs] == [f.tokens for f in frs_t]
        router.close()
        twin.close()

    def test_requeue_limit_fails_loudly(self):
        """A request that keeps landing on dying replicas must become
        FAILED — never retry forever, never vanish."""
        router = _sim_router(n=1, slots=1, requeue_limit=1,
                             auto_restart=False)
        fr = router.submit([1, 2], 4)
        router.pump()
        router._replicas[0].kill()
        # manual respawn/kill cycle: each pump requeues, each kill burns
        # one attempt
        for _ in range(4):
            router.pump()
            if fr.terminal:
                break
            router._respawn(0)
            router.pump()
            router._replicas[0].kill()
        assert fr.state == "failed", fr.state
        assert router.accounting()[fr.id] == "failed"
        router.close()

    def test_rolling_restart_rejects_nothing(self):
        router = _sim_router(n=2)
        frs = [router.submit([2, i], 5) for i in range(6)]
        for _ in range(2):
            router.pump()
        router.rolling_restart(10.0)
        assert router.wait_all(20.0)
        acc = router.accounting()
        assert "rejected" not in acc.values(), acc
        assert all(f.state == "finished" for f in frs)
        router.close()

    def test_degraded_replica_gets_no_new_traffic(self):
        engines = {}

        def factory(i):
            engines[i] = SimEngine(SimConfig(slots=2))
            return engines[i]

        router = Router(FleetConfig(replicas=2, mode="inprocess",
                                    affinity="round_robin",
                                    engine_factory=factory))
        engines[0].force_degraded = True
        frs = [router.submit([4, i], 3) for i in range(6)]
        assert router.wait_all(20.0)
        assert all(f.state == "finished" for f in frs)
        assert all(f.last_replica == 1 for f in frs), \
            [f.last_replica for f in frs]
        router.close()

    def test_drain_terminates_everything_exactly_once(self):
        router = _sim_router(n=2)
        frs = [router.submit([6, i], 4) for i in range(5)]
        router.drain()
        states = {f.state for f in frs}
        assert states <= {"finished", "rejected"}, states
        acc = router.accounting()
        assert len(acc) == 5 and all(v in ("finished", "rejected")
                                     for v in acc.values())

    def test_fleet_request_doc_round_trips_the_wire_fields(self):
        fr = FleetRequest(7, [1, 2, 3], 5, temperature=0.5, top_k=3,
                          seed=42)
        d = fr.doc()
        assert d["id"] == 7 and d["prompt"] == [1, 2, 3]
        assert d["max_new_tokens"] == 5 and d["seed"] == 42
        import json

        assert json.loads(json.dumps(d)) == d  # frame-protocol safe

    def test_fleet_request_doc_carries_speculation(self):
        """The per-request speculation override rides the wire frame —
        parsed at the router (so a bad value fails at submit, not on a
        replica), JSON-safe in every accepted form."""
        import json

        assert FleetRequest(8, [1, 2], 4,
                            speculation="auto").doc()["speculation"] == "auto"
        assert FleetRequest(9, [1], 4).doc()["speculation"] is None
        assert FleetRequest(10, [1], 4,
                            speculation="off").doc()["speculation"] == 0
        d = FleetRequest(11, [1], 4, speculation=64).doc()
        assert isinstance(d["speculation"], int)  # capped, still an int
        assert json.loads(json.dumps(d)) == d
        with pytest.raises(ValueError):
            FleetRequest(12, [1], 4, speculation=-3)

    def test_sim_replica_accepts_speculative_submits(self):
        """Sim engines ignore speculation but must accept the doc field —
        a fleet mixing sim and real replicas routes the same wire form to
        both."""
        router = _sim_router(n=1)
        fr = router.submit([5, 5, 5], 4, speculation="auto")
        assert router.wait_all(20.0)
        assert fr.state == "finished" and len(fr.tokens) == 4
        router.close()


# -- telemetry aggregation ----------------------------------------------------
class TestAggregateTelemetry:
    def test_merges_replica_rings(self, tmp_path):
        from paddle_tpu.monitor import metrics as mx
        from paddle_tpu.monitor import telemetry

        mx.enable()
        base = str(tmp_path / "fleet")
        for i in range(3):
            d = os.path.join(base, "replica_%d" % i)
            os.makedirs(d)
            exp = telemetry.TelemetryExporter(d, interval_s=999.0)
            mx.counter("test/fleet_agg").inc(i + 1)
            exp.tick()
            exp.stop()
        agg = aggregate_telemetry(base)
        assert sorted(agg) == ["replica_0", "replica_1", "replica_2"]
        for v in agg.values():
            assert v["samples"] >= 1 and "last" in v

    def test_empty_base_is_empty_not_fatal(self, tmp_path):
        assert aggregate_telemetry(str(tmp_path)) == {}
        assert aggregate_telemetry(str(tmp_path / "nonexistent")) == {}

    def test_degenerate_rings_flag_not_throw(self, tmp_path):
        """The three ways a replica's ring goes wrong — never ticked,
        crashed mid-append, never appeared — each yield a flagged entry,
        never an exception, never a silent hole."""
        base = str(tmp_path / "fleet")
        os.makedirs(os.path.join(base, "replica_0"))  # spawned, no tick yet
        d1 = os.path.join(base, "replica_1")          # torn tail only
        os.makedirs(d1)
        with open(os.path.join(d1, "telemetry_123_0.jsonl"), "w") as f:
            f.write('{"schema": "paddle_tpu.telemetry/v1", "seq": 1, "tr')
        agg = aggregate_telemetry(base, expected=[0, 1, 2])
        assert agg["replica_0"]["flag"] == "no complete samples"
        assert agg["replica_1"]["flag"] == "no complete samples"
        assert agg["replica_2"]["flag"] == "ring dir missing"
        assert all(v["samples"] == 0 for v in agg.values())

    def test_missing_base_with_expected_flags_every_replica(self, tmp_path):
        agg = aggregate_telemetry(str(tmp_path / "never_made"), expected=[0, 1])
        assert sorted(agg) == ["replica_0", "replica_1"]
        assert all(v["flag"] == "ring dir missing" for v in agg.values())

    def test_numeric_replica_order(self, tmp_path):
        base = str(tmp_path / "fleet")
        for i in (0, 1, 2, 10):
            os.makedirs(os.path.join(base, "replica_%d" % i))
        assert list(aggregate_telemetry(base)) == [
            "replica_0", "replica_1", "replica_2", "replica_10"]


# -- fleet event log ----------------------------------------------------------
class TestFleetEventLog:
    def test_round_trip_skips_torn_tail(self, tmp_path):
        from paddle_tpu.fleet.events import FleetEventLog, read_events

        p = str(tmp_path / "events.jsonl")
        log = FleetEventLog(p)
        assert log.armed
        log.emit("spawn", replica=0)
        log.emit("kill_detected", replica=0, lost=2)
        log.close()
        with open(p, "a") as f:
            f.write('{"kind": "torn')  # crash mid-append
        evs = read_events(p)
        assert [e["kind"] for e in evs] == ["spawn", "kill_detected"]
        assert len({e["run_id"] for e in evs}) == 1
        kills = read_events(p, kind="kill_detected")
        assert len(kills) == 1 and kills[0]["lost"] == 2

    def test_unwritable_path_disarms_never_raises(self, tmp_path):
        from paddle_tpu.fleet.events import FleetEventLog

        bad = os.path.join(str(tmp_path / "file_not_dir"), "x", "e.jsonl")
        with open(str(tmp_path / "file_not_dir"), "w") as f:
            f.write("occupied")
        log = FleetEventLog(bad)
        assert not log.armed
        assert log.emit("spawn", replica=0) is None  # no-op, no raise


# -- fleet SLO plane ----------------------------------------------------------
class TestFleetSLO:
    def test_merge_fleet_docs_sums_deltas(self):
        from paddle_tpu.fleet.slo import merge_fleet_docs

        docs = [
            {"t": 10.0, "dt_s": 2.0,
             "metrics": {"g": {"type": "gauge", "value": 2.0}},
             "deltas": {"counters": {"c": 1.0}, "gauges": {"g": 2.0},
                        "histograms": {"h": {"count": 2, "sum": 10.0,
                                             "buckets": {"5": 2}}}}},
            {"t": 11.0, "dt_s": 3.0,
             "metrics": {"g": {"type": "gauge", "value": 3.0}},
             "deltas": {"counters": {"c": 2.0}, "gauges": {"g": 3.0},
                        "histograms": {"h": {"count": 1, "sum": 7.0,
                                             "buckets": {"10": 1}}}}},
        ]
        s = merge_fleet_docs(docs, seq=1)
        assert s.counter_delta("c") == 3.0
        assert s.gauge_value("g") == 5.0  # queue depths ADD across a fleet
        h = s.histogram_delta("h")
        assert h["count"] == 3 and h["sum"] == 17.0
        assert h["buckets"] == {"5": 2, "10": 1}
        assert s.dt_s == 3.0  # widest window, not the sum

    def test_breach_fires_both_scopes_and_cursor_dedupes(self, tmp_path):
        import json

        from paddle_tpu.fleet.slo import FleetSLO
        from paddle_tpu.monitor.slo import parse_slos

        base = str(tmp_path)
        d = os.path.join(base, "replica_0")
        os.makedirs(d)
        doc = {"schema": "paddle_tpu.telemetry/v1", "seq": 1, "pid": 1,
               "t": 1.0, "dt_s": 1.0,
               "metrics": {"fleet/queue_depth": {"type": "gauge",
                                                 "value": 9.0}},
               "deltas": {"counters": {}, "histograms": {},
                          "gauges": {"fleet/queue_depth": 9.0}}}
        with open(os.path.join(d, "telemetry_1_0.jsonl"), "w") as f:
            f.write(json.dumps(doc) + "\n")
        hits = []
        slo = FleetSLO(
            parse_slos("fleet/queue_depth<=5"),
            on_replica_breach=lambda i, b: hits.append(("replica", i)),
            on_fleet_breach=lambda b: hits.append(("fleet",)))
        out = slo.evaluate(base, [0])
        assert out["replica"].get(0) and out["fleet"]
        assert ("replica", 0) in hits and ("fleet",) in hits
        # per-(replica, pid) seq cursor: the same sample never
        # re-evaluates on the next pass
        hits.clear()
        assert slo.evaluate(base, [0]) == {"replica": {}, "fleet": []}
        assert not hits


# -- fleet trace: orphan closure + in-process round trip ----------------------
class TestFleetTrace:
    def test_close_orphans_synthesizes_tagged_closures(self):
        from paddle_tpu.fleet import trace as ftrace

        spans = [
            {"name": "submitted", "cat": "fleet", "ts_us": 0, "dur_us": 0,
             "pid": 1, "tid": -1, "track": ftrace.QUEUE_TRACK,
             "args": {"trace_id": "t1"}},
            {"name": "queued", "cat": "fleet", "ts_us": 0, "dur_us": 5,
             "pid": 1, "tid": -1, "track": ftrace.QUEUE_TRACK,
             "args": {"trace_id": "t1", "attempt": 1}},
            # a dispatch whose attempt never closed and a request with no
            # terminal: what a SIGKILLed ROUTER would leave behind
            {"name": "dispatch", "cat": "fleet", "ts_us": 5, "dur_us": 0,
             "pid": 1, "tid": -2, "track": "replica 0",
             "args": {"trace_id": "t1", "attempt": 1}},
            {"name": "drain", "cat": "fleet", "ts_us": 0, "dur_us": 100,
             "pid": 1, "tid": -3, "track": ftrace.LIFECYCLE_TRACK,
             "args": {}},
        ]
        out, n = ftrace.close_orphans(spans)
        assert n == 2
        synth = [s for s in out if (s.get("args") or {}).get("synthetic")]
        att = next(s for s in synth if s["name"] == "attempt 1")
        assert att["args"]["killed"] and att["dur_us"] >= 1
        term = next(s for s in synth if s["name"] == "failed")
        assert term["dur_us"] == 0
        # the validator runs the same closure pass itself on raw spans
        digests = ftrace.validate_fleet_spans(spans)
        assert digests["t1"]["synthetic"]
        assert digests["t1"]["state"] == "failed"
        assert digests["_meta"]["synthetic_closures"] == 2

    def test_inprocess_router_trace_validates(self, tmp_path):
        """A traced in-process fleet round trip: the router's own spans
        alone form a validating request tree (submitted -> queued ->
        dispatch -> attempt 1 -> terminal), zero synthetic closures."""
        from paddle_tpu.fleet import trace as ftrace

        trace_dir = str(tmp_path / "trace")
        router = Router(FleetConfig(
            replicas=2, mode="inprocess", affinity="round_robin",
            engine_factory=lambda i: SimEngine(SimConfig(slots=2)),
            trace_dir=trace_dir))
        frs = [router.submit([1, i], 4) for i in range(5)]
        assert router.wait_all(20.0)
        router.close()
        spans, manifest, problems = ftrace.load_fragments(trace_dir)
        assert not problems and manifest.get("run_id")
        digests = ftrace.validate_fleet_spans(spans)
        meta = digests.pop("_meta")
        assert meta["requests"] == 5
        assert meta["synthetic_closures"] == 0
        assert all(d["state"] == "finished" and d["attempts"] == [1]
                   for d in digests.values())
        trace_ids = {f.trace_id for f in frs}
        assert set(digests) == trace_ids


# -- speculative requests through the fleet (real engines) --------------------
class TestFleetSpeculative:
    @staticmethod
    def _real_router(model, n=2):
        from paddle_tpu import serving

        def factory(i):
            return serving.ServingEngine(model, serving.ServingConfig(
                slots=2, page_size=8, max_seq=64))

        return Router(FleetConfig(replicas=n, mode="inprocess",
                                  affinity="round_robin",
                                  engine_factory=factory))

    def test_kill_replays_speculative_bit_identical(self, tiny_model):
        """A speculative request stranded by a killed replica must
        requeue and replay BIT-identically to an unkilled twin: greedy
        draft-verify emits the same (seed, position)-keyed stream as
        plain decode, so the fleet's replay invariant holds unchanged
        even when the respawned replica re-runs the whole request."""
        import numpy as np

        rng = np.random.RandomState(5)
        prompts = [list(rng.randint(0, 64, 3)) * 4 for _ in range(4)]
        req0 = fm.REQUEUED.value
        router = self._real_router(tiny_model)
        frs = [router.submit(p, 6, speculation=4) for p in prompts]
        for _ in range(2):
            router.pump()
        router._replicas[1].kill()
        assert router.wait_all(120.0)
        assert set(router.accounting().values()) == {"finished"}
        assert fm.REQUEUED.value > req0, "the kill stranded nothing"
        router.close()
        twin = self._real_router(tiny_model, n=1)
        frs_t = [twin.submit(p, 6, speculation=4) for p in prompts]
        assert twin.wait_all(120.0)
        twin.close()
        assert [f.tokens for f in frs] == [f.tokens for f in frs_t], \
            "a requeued speculative replay diverged from its unkilled twin"


# -- engine-level prefix cache (real model) -----------------------------------
@pytest.fixture(scope="module")
def tiny_model():
    from paddle_tpu.models import decoder_lm

    cfg = decoder_lm.DecoderConfig(vocab_size=64, n_layer=1, d_model=16,
                                   n_head=2, max_seq=64)
    return decoder_lm.DecoderLM(cfg, seed=3)


def _prefix_engine(model, pages=8):
    from paddle_tpu import serving

    return serving.ServingEngine(model, serving.ServingConfig(
        slots=2, page_size=8, max_seq=64, num_pages=32,
        prefix_cache_pages=pages))


class TestEnginePrefixCache:
    def test_config_validates_budget(self, tiny_model):
        from paddle_tpu import serving

        with pytest.raises(ValueError):
            serving.ServingConfig(slots=2, page_size=8, max_seq=64,
                                  num_pages=16, prefix_cache_pages=16)

    def test_hit_skips_prefill_and_matches_cold_stream(self, tiny_model):
        from paddle_tpu.serving import metrics as sm

        sys_prompt = list(range(1, 18))  # 17 tokens: 2 full pages cached
        eng = _prefix_engine(tiny_model)
        p0 = sm.PREFILL_COUNT.value
        h0 = fm.PREFIX_HITS.value
        r1 = eng.submit(sys_prompt + [30], 5, temperature=0.8, seed=11)
        eng.run()
        r2 = eng.submit(sys_prompt + [30], 5, temperature=0.8, seed=11)
        eng.run()
        assert r1.state == r2.state == "finished"
        assert list(r2.tokens_out) == list(r1.tokens_out), \
            "a prefix hit changed the sampled stream"
        assert fm.PREFIX_HITS.value == h0 + 1
        assert sm.PREFILL_COUNT.value == p0 + 1, \
            "the warm request still dispatched a full prefill"
        assert eng.page_accounting_ok()
        eng.drain(10.0)
        assert eng.pool.num_used == 0, "prefix pages leaked through drain"

    def test_failed_request_never_donates(self, tiny_model):
        from paddle_tpu.reliability import FaultPlan, faults

        eng = _prefix_engine(tiny_model)
        pk0 = fm.PREFIX_POISONED_SKIPPED.value
        plan = FaultPlan([faults.FaultSpec("serving.decode", "fatal",
                                           at=1, times=1)])
        with plan:
            bad = eng.submit(list(range(1, 18)), 5)
            eng.run(max_steps=50)
        assert bad.state == "failed"
        assert fm.PREFIX_POISONED_SKIPPED.value > pk0
        assert len(eng.prefix_cache) == 0, \
            "a FAILED request's pages entered the prefix cache"
        assert eng.page_accounting_ok() and eng.pool.num_used == 0
        # the poisoned prefix is structurally unservable: a fresh request
        # with the same prompt misses and re-prefills cleanly
        h0 = fm.PREFIX_HITS.value
        good = eng.submit(list(range(1, 18)), 3, seed=5)
        eng.run()
        assert good.state == "finished" and fm.PREFIX_HITS.value == h0
        eng.drain(10.0)

    def test_accounting_includes_cache_owned_pages(self, tiny_model):
        eng = _prefix_engine(tiny_model)
        r = eng.submit(list(range(1, 18)), 3, seed=9)
        eng.run()
        assert r.state == "finished"
        assert eng.prefix_cache.pages_held == 2
        assert eng.pool.num_used == 2, "donated pages were double-freed"
        assert eng.page_accounting_ok()
        eng.drain(10.0)
        assert eng.pool.num_used == 0
