"""Subprocess entry for the SIGKILL exactly-once drill in
test_data_pipeline.py.

Runs ``run_supervised`` over a ``CheckpointableReader`` (the reader is
created FRESH each invocation — zero caller-side ``feed_source(start)``
logic; the supervisor restores its position from the checkpoint payload).
Usage::

    python data_runner.py <shard_dir> <checkpoint_dir> <total_steps>

Environment:
  DATA_KILL_AT_STEP  SIGKILL *this* process right after the chunk ending
                     at that global step commits — a hard crash with no
                     checkpoint-on-exit, the worst-case kill the
                     exactly-once ledger must survive.

Prints one ``LEDGER:<step>:<id,id,...>`` line per committed step (flushed
BEFORE the kill check so the parent sees the final pre-crash commit), one
``SUP_STEP:<step>:<loss-bits-hex>`` per step at exit, and
``SUP_RESUMED:<start>`` when a checkpoint was restored.
"""

import os
import signal
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np


def main():
    shard_dir, ckpt_dir, total = sys.argv[1], sys.argv[2], int(sys.argv[3])
    kill_at = int(os.environ.get("DATA_KILL_AT_STEP", "-1"))

    import paddle_tpu as fluid
    from paddle_tpu import data
    from paddle_tpu.reliability import run_supervised

    paths = sorted(os.path.join(shard_dir, f)
                   for f in os.listdir(shard_dir) if f.endswith(".txt"))

    def parse(line):
        t = line.split()
        return {"x": np.asarray([float(v) for v in t[:8]], np.float32),
                "y": np.asarray([int(t[8])], np.int64)}

    reader = data.CheckpointableReader(
        paths, parse, batch_size=4,
        schema=[data.FieldSpec("x", (8,), np.float32),
                data.FieldSpec("y", (1,), np.int64)],
        epochs=1)

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 77
    with fluid.program_guard(main_prog, startup):
        x = fluid.layers.data("x", shape=[8])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        h = fluid.layers.dropout(h, dropout_prob=0.3)
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    def on_chunk(step0, rows):
        ids = reader.last_batch_ids(len(rows))
        for i, batch in enumerate(ids):
            print("LEDGER:%d:%s" % (step0 + i, ",".join(batch)), flush=True)
        if 0 <= kill_at < step0 + len(rows):
            os.kill(os.getpid(), signal.SIGKILL)  # no cleanup, no checkpoint

    res = run_supervised(
        exe, main_prog, reader, total, [loss],
        checkpoint_dir=ckpt_dir, fetch_every=2, checkpoint_every_steps=2,
        backoff_s=0.0, exit_on_preempt=False, on_chunk=on_chunk)
    if res.resumed:
        print("SUP_RESUMED:%d" % res.start_step)
    for i, row in enumerate(res.losses):
        print("SUP_STEP:%d:%s"
              % (res.start_step + i, np.float32(row[0]).tobytes().hex()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
