"""Structured-loss tests: CTC against torch.nn.functional.ctc_loss,
linear-chain CRF against brute-force enumeration, Viterbi against brute
force, hsigmoid against a manual bit-code walk, NCE/sample_logits
training sanity."""

import itertools

import numpy as np
import pytest

import paddle_tpu as fluid


def _run(fetch, feed):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feed, fetch_list=fetch if isinstance(fetch, list) else [fetch])


# -- CTC ----------------------------------------------------------------------


def test_warpctc_matches_torch(rng):
    torch = pytest.importorskip("torch")
    b, t, c, l = 3, 12, 6, 4
    logits = rng.randn(b, t, c).astype("float32")
    labels = rng.randint(1, c, (b, l)).astype("int32")
    in_lens = np.array([12, 10, 7], "int32")
    lab_lens = np.array([4, 3, 2], "int32")

    x = fluid.layers.data("x", shape=[t, c])
    y = fluid.layers.data("y", shape=[l], dtype="int32")
    il = fluid.layers.data("il", shape=[], dtype="int32")
    ll = fluid.layers.data("ll", shape=[], dtype="int32")
    loss = fluid.layers.warpctc(x, y, blank=0, input_length=il, label_length=ll)
    got, = _run(loss, {"x": logits, "y": labels, "il": in_lens, "ll": lab_lens})

    tl = torch.nn.functional.ctc_loss(
        torch.log_softmax(torch.tensor(logits).permute(1, 0, 2), -1),
        torch.tensor(labels.astype("int64")),
        torch.tensor(in_lens.astype("int64")), torch.tensor(lab_lens.astype("int64")),
        blank=0, reduction="none")
    np.testing.assert_allclose(got[:, 0], tl.numpy(), rtol=1e-4, atol=1e-4)


def test_warpctc_gradient_flows(rng):
    b, t, c, l = 2, 8, 5, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[t, c])
        y = fluid.layers.data("y", shape=[l], dtype="int32")
        h = fluid.layers.fc(x, size=c, num_flatten_dims=2)
        loss = fluid.layers.mean(fluid.layers.warpctc(h, y, blank=0))
        fluid.optimizer.Adam(2e-2).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": rng.randn(b, t, c).astype("float32"),
            "y": rng.randint(1, c, (b, l)).astype("int32")}
    losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0]) for _ in range(15)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_ctc_greedy_decoder(rng):
    # probs crafted so argmax path is [1,1,0,2,2,0,3] -> collapse to [1,2,3]
    path = np.array([1, 1, 0, 2, 2, 0, 3])
    t, c = len(path), 4
    probs = np.full((1, t, c), 0.1, "float32")
    probs[0, np.arange(t), path] = 0.9
    x = fluid.layers.data("x", shape=[t, c])
    out, ln = fluid.layers.ctc_greedy_decoder(x, blank=0)
    o, n = _run([out, ln], {"x": probs})
    assert int(n[0]) == 3
    np.testing.assert_array_equal(o[0, :3], [1, 2, 3])
    assert (o[0, 3:] == -1).all()


# -- CRF ----------------------------------------------------------------------


def _np_crf_nll(emission, transition, label, length):
    """Brute-force -(path_score - logZ) per sequence."""
    d = emission.shape[-1]
    start, stop, trans = transition[0], transition[1], transition[2:]
    out = []
    for em, lab, ln in zip(emission, label, length):
        em = em[:ln]
        lab = lab[:ln]
        gold = start[lab[0]] + em[0, lab[0]] + stop[lab[-1]]
        for k in range(1, ln):
            gold += trans[lab[k - 1], lab[k]] + em[k, lab[k]]
        z = -np.inf
        for seq in itertools.product(range(d), repeat=ln):
            s = start[seq[0]] + em[0, seq[0]] + stop[seq[-1]]
            for k in range(1, ln):
                s += trans[seq[k - 1], seq[k]] + em[k, seq[k]]
            z = np.logaddexp(z, s)
        out.append(-(gold - z))
    return np.array(out, "float32")


def test_linear_chain_crf_matches_bruteforce(rng):
    b, t, d = 3, 4, 3
    emission = rng.randn(b, t, d).astype("float32")
    transition = (rng.randn(d + 2, d) * 0.5).astype("float32")
    label = rng.randint(0, d, (b, t)).astype("int64")
    length = np.array([4, 3, 2], "int32")

    em = fluid.layers.data("em", shape=[t, d])
    lb = fluid.layers.data("lb", shape=[t], dtype="int64")
    ln = fluid.layers.data("ln", shape=[], dtype="int32")
    ll = fluid.layers.linear_chain_crf(
        em, lb, param_attr=fluid.ParamAttr(
            name="crf_w", initializer=fluid.initializer.NumpyArrayInitializer(transition)),
        length=ln)
    got, = _run(ll, {"em": emission, "lb": label, "ln": length})
    exp = _np_crf_nll(emission, transition, label, length)
    np.testing.assert_allclose(got[:, 0], exp, rtol=1e-4, atol=1e-4)


def test_crf_decoding_matches_bruteforce(rng):
    b, t, d = 2, 4, 3
    emission = rng.randn(b, t, d).astype("float32")
    transition = (rng.randn(d + 2, d) * 0.5).astype("float32")
    length = np.array([4, 3], "int32")

    em = fluid.layers.data("em", shape=[t, d])
    ln = fluid.layers.data("ln", shape=[], dtype="int32")
    attr = fluid.ParamAttr(
        name="crf_w2", initializer=fluid.initializer.NumpyArrayInitializer(transition))
    lb = fluid.layers.data("lb", shape=[t], dtype="int64")
    _ = fluid.layers.linear_chain_crf(em, lb, param_attr=attr, length=ln)
    path = fluid.layers.crf_decoding(em, attr, length=ln)
    got, = _run(path, {"em": emission, "ln": length,
                       "lb": np.zeros((b, t), "int64")})

    start, stop, trans = transition[0], transition[1], transition[2:]
    for i in range(b):
        best, best_seq = -np.inf, None
        for seq in itertools.product(range(d), repeat=int(length[i])):
            s = start[seq[0]] + emission[i, 0, seq[0]] + stop[seq[-1]]
            for k in range(1, len(seq)):
                s += trans[seq[k - 1], seq[k]] + emission[i, k, seq[k]]
            if s > best:
                best, best_seq = s, seq
        np.testing.assert_array_equal(got[i, :length[i]], best_seq)
        assert (got[i, length[i]:] == 0).all()


def test_crf_trains_sequence_tagging(rng):
    """label_semantic_roles-style smoke: emissions + CRF train to lower cost."""
    b, t, d = 8, 6, 4
    xs = rng.randn(b, t, 8).astype("float32")
    # learnable rule: tag = argmax of first 4 features
    ys = xs[..., :4].argmax(-1).astype("int64")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[t, 8])
        y = fluid.layers.data("y", shape=[t], dtype="int64")
        em = fluid.layers.fc(x, size=d, num_flatten_dims=2)
        cost = fluid.layers.mean(fluid.layers.linear_chain_crf(em, y))
        fluid.optimizer.Adam(5e-2).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = [float(exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[cost])[0])
              for _ in range(20)]
    assert losses[-1] < losses[0] * 0.7, losses


# -- hsigmoid -----------------------------------------------------------------


def _np_hsigmoid(x, w, b, label, c):
    out = np.zeros(len(x), "float32")
    for i in range(len(x)):
        code = int(label[i]) + c
        length = code.bit_length() - 1
        for bit in range(length):
            idx = (code >> (bit + 1)) - 1
            tgt = float((code >> bit) & 1)
            logit = x[i] @ w[idx] + b[idx]
            out[i] += max(logit, 0) - logit * tgt + np.log1p(np.exp(-abs(logit)))
    return out


def test_hsigmoid_matches_manual(rng):
    bsz, d, c = 5, 6, 7
    xs = rng.randn(bsz, d).astype("float32")
    w0 = rng.randn(c - 1, d).astype("float32") * 0.3
    b0 = rng.randn(c - 1).astype("float32") * 0.1
    ys = rng.randint(0, c, (bsz, 1)).astype("int64")
    x = fluid.layers.data("x", shape=[d])
    y = fluid.layers.data("y", shape=[1], dtype="int64")
    out = fluid.layers.hsigmoid(
        x, y, c,
        param_attr=fluid.ParamAttr(
            name="hs_w", initializer=fluid.initializer.NumpyArrayInitializer(w0)),
        bias_attr=fluid.ParamAttr(
            name="hs_b", initializer=fluid.initializer.NumpyArrayInitializer(b0)))
    got, = _run(out, {"x": xs, "y": ys})
    np.testing.assert_allclose(got[:, 0], _np_hsigmoid(xs, w0, b0, ys[:, 0], c),
                               rtol=1e-4, atol=1e-5)


def test_hsigmoid_trains(rng):
    bsz, d, c = 32, 8, 10
    xs = rng.randn(bsz, d).astype("float32")
    ys = (xs[:, :1] > 0).astype("int64")  # separable 2-of-10 classes
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[d])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        cost = fluid.layers.mean(fluid.layers.hsigmoid(x, y, c))
        fluid.optimizer.Adam(5e-2).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = [float(exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[cost])[0])
              for _ in range(20)]
    assert losses[-1] < losses[0] * 0.5, losses


# -- NCE / sample_logits ------------------------------------------------------


def test_nce_trains_and_eval_deterministic(rng):
    bsz, d, c = 16, 8, 20
    xs = rng.randn(bsz, d).astype("float32")
    ys = rng.randint(0, c, (bsz, 1)).astype("int64")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[d])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        cost = fluid.layers.mean(
            fluid.layers.nce(x, y, num_total_classes=c, num_neg_samples=5))
        fluid.optimizer.Adam(5e-2).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": xs, "y": ys}
    losses = [float(exe.run(main, feed=feed, fetch_list=[cost])[0]) for _ in range(15)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_sample_logits_shapes_and_correction(rng):
    bsz, c, nt, s = 4, 50, 1, 8
    logits = rng.randn(bsz, c).astype("float32")
    labels = rng.randint(0, c, (bsz, nt)).astype("int32")
    lg = fluid.layers.data("lg", shape=[c])
    lb = fluid.layers.data("lb", shape=[nt], dtype="int32")
    s_logits, s_labels = fluid.layers.sample_logits(lg, lb, num_samples=s)
    o, l = _run([s_logits, s_labels], {"lg": logits, "lb": labels})
    assert o.shape == (bsz, nt + s)
    np.testing.assert_array_equal(l, np.zeros((bsz, nt), "int64"))
    assert np.isfinite(o[:, :nt]).all()
