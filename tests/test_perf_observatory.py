"""Performance observatory tests: run ledger (monitor.runlog), noise-aware
regression verdicts (monitor.regress), step-time attribution
(monitor.stepstats), and the P99 satellite columns. All series are seeded
and synthetic — no wall-clock timing in any assertion."""

import json
import os

import pytest

from paddle_tpu.monitor import metrics as mx
from paddle_tpu.monitor import regress, runlog, stepstats


@pytest.fixture(autouse=True)
def _metrics_on():
    mx.enable()
    mx.reset()
    yield


@pytest.fixture
def ledger_env(tmp_path, monkeypatch):
    path = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("PADDLE_TPU_RUN_LEDGER", path)
    monkeypatch.setattr(runlog, "_ledger", None)
    yield path
    runlog._ledger = None


def _rec(config, metrics, seq, kind="perf_gate"):
    return {"schema": runlog.RUN_SCHEMA, "run_id": "rtest-%d" % seq,
            "t": float(seq), "kind": kind, "configs": {config: metrics}}


# -- run ledger ---------------------------------------------------------------

def test_record_run_round_trips_provenance(ledger_env):
    rec = runlog.record_run("bench", {"cfg": {"step_ms_p50": 12.5}},
                            extra={"note": "t"})
    assert rec["ledger_path"] == ledger_env
    back = runlog.read_ledger(ledger_env)
    assert len(back) == 1
    got = back[0]
    assert got["run_id"] == runlog.run_id() == rec["run_id"]
    assert got["kind"] == "bench"
    assert got["configs"] == {"cfg": {"step_ms_p50": 12.5}}
    assert got["extra"] == {"note": "t"}
    prov = got["provenance"]
    # every provenance section present (values may degrade to None)
    for key in ("git", "device_kind", "opt_level", "jax", "env"):
        assert key in prov, key
    assert "sha" in prov["git"]
    assert prov["env"].get("PADDLE_TPU_RUN_LEDGER") == ledger_env
    assert mx.snapshot()["runlog/records"]["value"] >= 1


def test_ledger_rotation_keeps_bounded_files(tmp_path):
    path = str(tmp_path / "led.jsonl")
    led = runlog.RunLedger(path, rotate_records=2, keep_files=2)
    for i in range(7):
        led.append(_rec("c", {"step_ms_p50": float(i)}, i))
    # rotate@2 keep@2 (live + 1 shard): bounded on disk, newest preserved
    names = sorted(os.listdir(str(tmp_path)))
    assert len(names) == 2, names
    back = runlog.read_ledger(path)
    assert [r["configs"]["c"]["step_ms_p50"] for r in back] == [4.0, 5.0, 6.0]
    assert mx.snapshot()["runlog/rotations"]["value"] >= 1


def test_read_ledger_skips_torn_tail_and_foreign_schema(tmp_path):
    path = str(tmp_path / "led.jsonl")
    led = runlog.RunLedger(path)
    led.append(_rec("c", {"step_ms_p50": 1.0}, 0))
    led.append(_rec("c", {"step_ms_p50": 2.0}, 1))
    with open(path, "a") as f:
        f.write(json.dumps({"schema": "someone_else/v1", "x": 1}) + "\n")
        f.write('{"schema": "paddle_tpu.runlog/v1", "run_id": "torn')
    back = runlog.read_ledger(path)
    assert [r["run_id"] for r in back] == ["rtest-0", "rtest-1"]


def test_ledger_write_error_disables_once(tmp_path):
    led = runlog.RunLedger(str(tmp_path / "noexist" / "x" / "led.jsonl"))
    # make the parent un-creatable by occupying it with a FILE
    blocker = str(tmp_path / "noexist")
    with open(blocker, "w") as f:
        f.write("x")
    assert led.append(_rec("c", {}, 0)) is None
    assert led.disabled
    assert led.append(_rec("c", {}, 1)) is None  # no raise, stays disabled
    assert mx.snapshot()["runlog/write_errors"]["value"] >= 1


def test_record_run_without_ledger_still_returns_record(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_RUN_LEDGER", raising=False)
    monkeypatch.setattr(runlog, "_ledger", None)
    rec = runlog.record_run("bench", {"cfg": {"eps": 1.0}})
    assert rec["ledger_path"] is None and rec["run_id"] == runlog.run_id()
    info = runlog.tail_info()
    assert info == {"run_id": runlog.run_id()}


# -- regression detection -----------------------------------------------------

BASE = [10.0, 10.2, 9.8, 10.1, 9.9, 10.0, 10.3, 9.7]


def test_injected_step_time_regression_is_regressed():
    history = [_rec("tfm", {"step_ms_p50": v}, i) for i, v in enumerate(BASE)]
    head = _rec("tfm", {"step_ms_p50": 13.0}, 99)  # 1.3x slower
    verdicts = regress.compare_run(head, history)
    assert len(verdicts) == 1
    v = verdicts[0]
    assert v.verdict == regress.REGRESSED
    assert v.config == "tfm" and v.metric == "step_ms_p50"
    assert v.n_baseline == len(BASE)
    assert v.delta_frac == pytest.approx(0.3, abs=0.02)


def test_throughput_direction_down_is_regressed_up_is_improved():
    history = [_rec("tfm", {"examples_per_sec": 100 * v}, i)
               for i, v in enumerate(BASE)]
    down = regress.compare_run(
        _rec("tfm", {"examples_per_sec": 770.0}, 99), history)
    assert down[0].verdict == regress.REGRESSED
    up = regress.compare_run(
        _rec("tfm", {"examples_per_sec": 1300.0}, 99), history)
    assert up[0].verdict == regress.IMPROVED


def test_noisy_but_flat_series_is_not_regressed():
    noisy = [9.6, 10.4, 9.8, 10.2, 10.0, 9.7, 10.3, 10.1]
    history = [_rec("tfm", {"step_ms_p50": v}, i)
               for i, v in enumerate(noisy)]
    verdicts = regress.compare_run(
        _rec("tfm", {"step_ms_p50": 10.05}, 99), history)
    assert verdicts[0].verdict == regress.NEUTRAL
    # a wobble inside the MAD-widened band stays NEUTRAL too
    verdicts = regress.compare_run(
        _rec("tfm", {"step_ms_p50": 10.9}, 99), history)
    assert verdicts[0].verdict == regress.NEUTRAL


def test_three_sample_ledger_is_insufficient_data():
    history = [_rec("tfm", {"step_ms_p50": v}, i)
               for i, v in enumerate([10.0, 10.1, 9.9])]
    verdicts = regress.compare_run(
        _rec("tfm", {"step_ms_p50": 13.0}, 99), history)
    assert verdicts[0].verdict == regress.INSUFFICIENT_DATA
    # and an empty baseline likewise
    verdicts = regress.compare_run(_rec("tfm", {"step_ms_p50": 13.0}, 99), [])
    assert verdicts[0].verdict == regress.INSUFFICIENT_DATA


def test_unknown_direction_metrics_are_skipped():
    history = [_rec("tfm", {"mystery_number": v}, i)
               for i, v in enumerate(BASE)]
    verdicts = regress.compare_run(
        _rec("tfm", {"mystery_number": 130.0}, 99), history)
    assert verdicts == []
    assert regress.metric_direction("examples_per_sec") == 1
    assert regress.metric_direction("latency_p99_ms") == -1
    assert regress.metric_direction("mystery_number") == 0


def test_check_verdicts_ticks_counter_and_fires_hook():
    history = [_rec("tfm", {"step_ms_p50": v}, i) for i, v in enumerate(BASE)]
    verdicts = regress.compare_run(
        _rec("tfm", {"step_ms_p50": 13.0}, 99), history)
    before = mx.snapshot()["perf/regressions"]["value"]
    hits = []
    regressed = regress.check_verdicts(verdicts, on_regression=hits.append)
    assert [v.metric for v in regressed] == ["step_ms_p50"]
    assert hits == regressed
    assert mx.snapshot()["perf/regressions"]["value"] == before + 1
    doc = regressed[0].to_doc()
    assert doc["verdict"] == regress.REGRESSED and doc["config"] == "tfm"


def test_baseline_window_trails():
    # old slow epoch must age out of the trailing window
    history = [_rec("tfm", {"step_ms_p50": 20.0}, i) for i in range(10)]
    history += [_rec("tfm", {"step_ms_p50": v}, 10 + i)
                for i, v in enumerate(BASE)]
    series = regress.baseline_series(history, "tfm", "step_ms_p50", window=8)
    assert series == BASE


# -- step-time attribution ----------------------------------------------------

def test_attribute_labels_input_bound_with_feed_wait_dominant():
    bd = stepstats.attribute(
        {"host_ms": 1.0, "input_ms": 8.0, "compute_ms": 2.0},
        step_ms=11.0)
    assert bd["bound"] == "input" and bd["dominant"] == "input_ms"
    assert "prefetch" in bd["hint"]
    assert stepstats.render(bd, "probe").startswith("probe: input-bound")


def test_attribute_residual_compute_on_peakless_hardware():
    bd = stepstats.attribute({"host_ms": 1.0, "input_ms": 2.0}, step_ms=10.0)
    assert bd["compute_is_residual"] and bd["terms"]["compute_ms"] == 7.0
    assert bd["bound"] == "compute"


def test_collect_terms_from_snapshot_with_peaks():
    snap = {
        "device_profile/flops": {"type": "gauge", "value": 1e9},
        "device_profile/bytes_accessed": {"type": "gauge", "value": 8e6},
        "collectives/ppermute/bytes": {"type": "counter", "value": 4e6},
        "collectives/ppermute/calls": {"type": "counter", "value": 2},
        "collectives/ppermute/sp/bytes": {"type": "counter", "value": 4e6},
        "data/prefetch_wait_ms": {"type": "histogram", "count": 4,
                                  "sum": 2.0},
    }
    peaks = {"flops": 1e12, "hbm_gbps": 8.0, "ici_gbps": 4.0}
    terms = stepstats.collect_terms(snap, host_ms=0.25, peaks=peaks)
    assert terms["compute_ms"] == pytest.approx(1.0)
    assert terms["memory_ms"] == pytest.approx(1.0)
    # axis-qualified collectives counters must not double count
    assert terms["comms_ms"] == pytest.approx(1.0)
    assert terms["input_ms"] == pytest.approx(0.5)
    assert terms["host_ms"] == 0.25
    bd = stepstats.attribute(terms, step_ms=4.0)
    assert bd["bound"] in ("compute", "comms")
    assert "attributed_frac" in bd


def test_attribute_with_nothing_measured():
    bd = stepstats.attribute({})
    assert bd["bound"] == "unknown" and bd["dominant"] is None


# -- P99 satellites -----------------------------------------------------------

def test_histogram_snapshot_has_p99():
    h = mx.histogram("perf_obs/p99_hist")
    for v in range(1, 101):
        h.observe(float(v))
    snap = h.snapshot()
    assert snap["p95"] <= snap["p99"] <= snap["max"]
    assert "p99=" in mx.to_text()


def test_step_profiler_table_has_p99_column():
    from paddle_tpu.profiler import StepProfiler

    prof = StepProfiler()
    for _ in range(5):
        with prof.step("train"):
            pass
    table = prof.summary()
    header, row = table.splitlines()[0], table.splitlines()[1]
    assert "P99(ms)" in header
    # alignment: header columns and row columns line up count-wise
    assert len(header.split()) == len(row.split())


def test_step_logger_summary_has_p99(monkeypatch):
    from paddle_tpu.monitor.step_logger import StepLogger

    sl = StepLogger(every_n=1000)
    t = [0.0]

    def fake_clock():
        t[0] += 0.01
        return t[0]

    monkeypatch.setattr("paddle_tpu.monitor.step_logger.time.perf_counter",
                        fake_clock)
    for _ in range(10):
        sl.step(examples=4)
    s = sl.summary()
    assert "p99" in s["step_time_ms"]
    assert s["step_time_ms"]["p99"] >= s["step_time_ms"]["p50"]


def test_dump_metrics_table_renders_p99():
    from tools.dump_metrics import format_snapshot

    h = mx.histogram("perf_obs/fmt_hist")
    h.observe(5.0)
    out = format_snapshot(mx.snapshot())
    assert "p99=" in out


# -- flight-dump join keys ----------------------------------------------------

def test_flight_dump_embeds_run_id_and_telemetry_delta(tmp_path, monkeypatch):
    from paddle_tpu.monitor import telemetry
    from paddle_tpu.monitor.device import FlightRecorder

    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path / "tel"))
    handle = telemetry.acquire()
    try:
        mx.counter("perf_obs/flight_evt").inc(3)
        telemetry.force_tick()
        fr = FlightRecorder(str(tmp_path / "flight"))
        fr.record_event("test_evt", detail=1)
        path = fr.dump("test")
        with open(path) as f:
            doc = json.load(f)
        assert doc["run_id"] == runlog.run_id()
        assert doc["telemetry_last"]["seq"] >= 1
        assert doc["telemetry_last"]["deltas"]["counters"][
            "perf_obs/flight_evt"] == 3
    finally:
        telemetry.release(handle)
