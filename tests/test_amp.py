"""Mixed-precision tests: bf16 forward with fp32 master weights."""

import jax.numpy as jnp
import numpy as np

import paddle_tpu as fluid


def _build():
    x = fluid.layers.data("x", shape=[16])
    y = fluid.layers.data("y", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=32, act="relu")
    logits = fluid.layers.fc(h, size=4)
    loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
    return h, logits, loss


def test_amp_trains_and_keeps_fp32_masters(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        h, logits, loss = _build()
        opt = fluid.amp.decorate(fluid.optimizer.Adam(1e-2))
        opt.minimize(loss)
    assert main._amp_dtype == "bfloat16"
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(64, 16).astype("float32")
    ys = rng.randint(0, 4, (64, 1)).astype("int64")
    losses, acts = [], None
    for _ in range(20):
        l, a = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss, h],
                       return_numpy=False)
        losses.append(float(np.asarray(l)))
    # forward activations are bf16; master weights in scope stay fp32
    assert a.dtype == jnp.bfloat16
    w = fluid.global_scope().find_var("fc_0.w_0")
    assert w.dtype == jnp.float32
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_amp_loss_close_to_fp32(rng):
    xs = rng.randn(32, 16).astype("float32")
    ys = rng.randint(0, 4, (32, 1)).astype("int64")

    def run(use_amp):
        with fluid.unique_name.guard():
            with fluid.scope_guard(fluid.Scope()):
                main, startup = fluid.Program(), fluid.Program()
                main.random_seed = startup.random_seed = 3
                with fluid.program_guard(main, startup):
                    h, logits, loss = _build()
                    opt = fluid.optimizer.SGD(0.05)
                    if use_amp:
                        opt = fluid.amp.decorate(opt)
                    opt.minimize(loss)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                return [float(exe.run(main, feed={"x": xs, "y": ys},
                                      fetch_list=[loss])[0]) for _ in range(5)]

    fp32 = run(False)
    bf16 = run(True)
    np.testing.assert_allclose(fp32, bf16, rtol=0.05, atol=0.02)


def test_amp_eval_does_not_degrade_fp32_state(rng):
    """A forward-only (eval/fetch) run under AMP must not write bf16 copies
    of params or BN stats back into the scope (ADVICE r1: executor.py:119)."""
    with fluid.scope_guard(fluid.Scope()):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            h, logits, loss = _build()
            fluid.amp.decorate(fluid.optimizer.Adam(1e-2)).minimize(loss)
        infer = main.clone(for_test=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        w_name = [p.name for p in main.all_parameters() if p.name.endswith("w_0")][0]
        before = np.asarray(fluid.global_scope().find_var(w_name))
        assert before.dtype == np.float32
        xs = rng.randn(8, 16).astype("float32")
        ys = rng.randint(0, 4, (8, 1)).astype("int64")
        # eval-only run (no backward): fetch logits from the cloned program
        exe.run(infer, feed={"x": xs, "y": ys}, fetch_list=[loss])
        after_var = fluid.global_scope().find_var(w_name)
        after = np.asarray(after_var)
        assert after.dtype == np.float32, "fp32 master degraded to %s" % after.dtype
        np.testing.assert_array_equal(before, after)


def test_amp_static_loss_scaling_matches_unscaled(rng):
    xs = rng.randn(32, 16).astype("float32")
    ys = rng.randint(0, 4, (32, 1)).astype("int64")

    def run(scale):
        with fluid.unique_name.guard():
            with fluid.scope_guard(fluid.Scope()):
                main, startup = fluid.Program(), fluid.Program()
                main.random_seed = startup.random_seed = 3
                with fluid.program_guard(main, startup):
                    h, logits, loss = _build()
                    opt = fluid.amp.decorate(fluid.optimizer.SGD(0.05),
                                             init_loss_scaling=scale)
                    opt.minimize(loss)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                return [float(exe.run(main, feed={"x": xs, "y": ys},
                                      fetch_list=[loss])[0]) for _ in range(5)]

    np.testing.assert_allclose(run(1.0), run(128.0), rtol=0.02, atol=0.01)
