"""Native RecordIO round-trip + corruption detection (mirrors reference
recordio tests: recordio/chunk_test.cc, scanner_test.cc)."""

import os

import numpy as np
import pytest

from paddle_tpu import recordio


def test_roundtrip_bytes(tmp_path):
    path = str(tmp_path / "data.rio")
    records = [b"hello", b"", b"x" * 100000, bytes(range(256))]
    with recordio.Writer(path) as w:
        for r in records:
            w.write(r)
    got = list(recordio.Scanner(path))
    assert got == records


def test_roundtrip_many_chunks(tmp_path):
    path = str(tmp_path / "many.rio")
    records = [("example-%d" % i).encode() for i in range(5000)]  # >1 chunk
    with recordio.Writer(path) as w:
        for r in records:
            w.write(r)
    assert list(recordio.Scanner(path)) == records


def test_pickle_examples_and_reader_pipeline(tmp_path, rng):
    import pickle

    from paddle_tpu import reader as R

    path = str(tmp_path / "examples.rio")
    examples = [(rng.randn(4).astype("float32"), int(i % 3)) for i in range(100)]
    # pickle is opt-in: structured objects need an explicit serializer
    with pytest.raises(TypeError):
        recordio.write_records(path, examples)
    n = recordio.write_records(path, examples, serializer=pickle.dumps)
    assert n == 100
    r = recordio.recordio_reader(path, deserializer=pickle.loads)
    batches = list(R.batch(r, 32)())
    assert len(batches) == 4 and len(batches[0]) == 32
    np.testing.assert_array_equal(batches[0][0][0], examples[0][0])


def test_raw_bytes_default(tmp_path):
    path = str(tmp_path / "raw.rio")
    recs = [b"a", b"bb", b"ccc"]
    assert recordio.write_records(path, recs) == 3
    assert list(recordio.read_records(path)) == recs


def test_corruption_detected(tmp_path):
    path = str(tmp_path / "corrupt.rio")
    with recordio.Writer(path) as w:
        for i in range(10):
            w.write(b"payload-%d" % i)
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF  # flip a payload bit
    open(path, "wb").write(bytes(data))
    with pytest.raises(recordio.RecordIOCorruptError):
        list(recordio.Scanner(path))


def test_tampered_len_table_detected(tmp_path):
    """The CRC covers the payload only; an inflated record_len entry must
    still be rejected (sum(lens) != payload_len) instead of reading past the
    payload buffer."""
    path = str(tmp_path / "tamper.rio")
    with recordio.Writer(path) as w:
        for i in range(4):
            w.write(b"record-%d" % i)
    data = bytearray(open(path, "rb").read())
    # layout: magic(4) n(4) plen(8) crc(4) lens(4*n) payload — inflate lens[0]
    import struct

    (l0,) = struct.unpack_from("<I", data, 20)
    struct.pack_into("<I", data, 20, l0 + 1000)
    open(path, "wb").write(bytes(data))
    with pytest.raises(recordio.RecordIOCorruptError):
        list(recordio.Scanner(path))
