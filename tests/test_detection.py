"""Detection op/layer tests, each against an independent numpy reference
(modeling the reference's unittests: test_multiclass_nms_op.py,
test_bipartite_match_op.py, test_box_coder_op.py, test_prior_box_op.py,
test_roi_align_op (torchvision-style), test_yolov3_loss_op.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.layers import detection


def _run(fetch, feed=None):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feed or {}, fetch_list=fetch if isinstance(fetch, list) else [fetch])


def _np_iou(a, b):
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = np.clip(a[:, 2] - a[:, 0], 0, None) * np.clip(a[:, 3] - a[:, 1], 0, None)
    area_b = np.clip(b[:, 2] - b[:, 0], 0, None) * np.clip(b[:, 3] - b[:, 1], 0, None)
    union = area_a[:, None] + area_b[None, :] - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)


def test_iou_similarity(rng):
    a = np.sort(rng.rand(5, 4).astype("float32"), -1)[:, [0, 2, 1, 3]]
    b = np.sort(rng.rand(7, 4).astype("float32"), -1)[:, [0, 2, 1, 3]]
    x = fluid.layers.data("x", shape=[4], append_batch_size=True)
    y = fluid.layers.data("y", shape=[4])
    out = detection.iou_similarity(x, y)
    got, = _run(out, {"x": a, "y": b})
    np.testing.assert_allclose(got, _np_iou(a, b), rtol=1e-5, atol=1e-6)


def test_box_coder_encode_decode_roundtrip(rng):
    priors = np.array([[0.1, 0.1, 0.5, 0.5], [0.2, 0.3, 0.7, 0.9]], "float32")
    pvar = np.array([[0.1, 0.1, 0.2, 0.2]] * 2, "float32")
    targets = np.array([[0.15, 0.2, 0.6, 0.8], [0.05, 0.05, 0.4, 0.5],
                        [0.3, 0.3, 0.8, 0.85]], "float32")
    pb = fluid.layers.data("pb", shape=[4])
    pv = fluid.layers.data("pv", shape=[4])
    tb = fluid.layers.data("tb", shape=[4])
    enc = detection.box_coder(pb, pv, tb, code_type="encode_center_size")
    # encode output [N, M, 4] has priors along dim 1 → decode axis=0
    dec = detection.box_coder(pb, pv, enc, code_type="decode_center_size", axis=0)
    e, d = _run([enc, dec], {"pb": priors, "pv": pvar, "tb": targets})
    assert e.shape == (3, 2, 4)
    # decode(encode(t)) must give t back for every prior column
    for j in range(2):
        np.testing.assert_allclose(d[:, j], targets, rtol=1e-4, atol=1e-5)


def test_prior_box_matches_manual(rng):
    feat = rng.randn(1, 8, 4, 4).astype("float32")
    img = rng.randn(1, 3, 32, 32).astype("float32")
    f = fluid.layers.data("f", shape=[8, 4, 4])
    im = fluid.layers.data("im", shape=[3, 32, 32])
    boxes, var = detection.prior_box(f, im, min_sizes=[8.0], max_sizes=[16.0],
                                     aspect_ratios=[2.0], flip=True, clip=True)
    b, v = _run([boxes, var], {"f": feat, "im": img})
    # priors per cell: min, ar=2, ar=0.5, sqrt(min*max) => 4
    assert b.shape == (4, 4, 4, 4) and v.shape == b.shape
    # center of cell (0,0): step 8 → center (4,4); min box half-size 4px
    np.testing.assert_allclose(b[0, 0, 0], [0.0, 0.0, 8 / 32, 8 / 32], atol=1e-6)
    big = np.sqrt(8.0 * 16.0) / 2
    np.testing.assert_allclose(
        b[0, 0, 3], np.clip([(4 - big) / 32, (4 - big) / 32, (4 + big) / 32, (4 + big) / 32], 0, 1),
        atol=1e-5)
    np.testing.assert_allclose(v[0, 0, 0], [0.1, 0.1, 0.2, 0.2], atol=1e-6)
    assert (b >= 0).all() and (b <= 1).all()


def test_anchor_generator_shapes_and_values(rng):
    f = fluid.layers.data("f", shape=[8, 2, 3])
    anchors, var = detection.anchor_generator(
        f, anchor_sizes=[32.0, 64.0], aspect_ratios=[1.0], stride=[16.0, 16.0])
    a, v = _run([anchors, var], {"f": np.zeros((1, 8, 2, 3), "float32")})
    assert a.shape == (2, 3, 2, 4)
    # cell (0,0) center = (8, 8), size-32 square anchor
    np.testing.assert_allclose(a[0, 0, 0], [-8.0, -8.0, 24.0, 24.0], atol=1e-4)


def test_bipartite_match_greedy(rng):
    # hand-crafted: row0 best col1 (0.9), row1 best col0 (0.8)
    dist = np.array([[[0.3, 0.9, 0.1],
                      [0.8, 0.7, 0.2]]], "float32")
    d = fluid.layers.data("d", shape=[2, 3])
    idx, md = detection.bipartite_match(d)
    i, m = _run([idx, md], {"d": dist})
    np.testing.assert_array_equal(i[0], [1, 0, -1])
    np.testing.assert_allclose(m[0], [0.8, 0.9, 0.0], atol=1e-6)


def test_bipartite_match_per_prediction(rng):
    dist = np.array([[[0.3, 0.9, 0.6],
                      [0.8, 0.7, 0.2]]], "float32")
    d = fluid.layers.data("d", shape=[2, 3])
    idx, md = detection.bipartite_match(d, match_type="per_prediction",
                                        dist_threshold=0.5)
    i, m = _run([idx, md], {"d": dist})
    # col2 unmatched by bipartite phase; its argmax row is 0 with 0.6 >= 0.5
    np.testing.assert_array_equal(i[0], [1, 0, 0])


def test_target_assign_per_column_gather(rng):
    x = rng.randn(1, 2, 4, 3).astype("float32")  # [B, Ng, P, K]
    match = np.array([[1, -1, 0, 1]], "int32")   # M=4, P=4
    xv = fluid.layers.data("x", shape=[2, 4, 3])
    mv = fluid.layers.data("m", shape=[4], dtype="int32")
    out, w = detection.target_assign(xv, mv, mismatch_value=0)
    o, wt = _run([out, w], {"x": x, "m": match})
    np.testing.assert_allclose(o[0, 0], x[0, 1, 0], rtol=1e-6)
    np.testing.assert_allclose(o[0, 2], x[0, 0, 2], rtol=1e-6)
    np.testing.assert_array_equal(o[0, 1], np.zeros(3, "float32"))
    np.testing.assert_allclose(wt[0, :, 0], [1, 0, 1, 1])


def _np_nms(boxes, scores, thresh):
    order = np.argsort(-scores)
    keep = []
    for i in order:
        if scores[i] == -np.inf:
            continue
        ok = True
        for j in keep:
            if _np_iou(boxes[i:i + 1], boxes[j:j + 1])[0, 0] > thresh:
                ok = False
                break
        if ok:
            keep.append(i)
    return keep


def test_multiclass_nms_matches_numpy(rng):
    np_boxes = np.sort(rng.rand(1, 20, 4).astype("float32"), -1)[:, :, [0, 2, 1, 3]]
    np_scores = rng.rand(1, 3, 20).astype("float32")
    bb = fluid.layers.data("bb", shape=[20, 4])
    sc = fluid.layers.data("sc", shape=[3, 20])
    out, length = detection.multiclass_nms(
        bb, sc, score_threshold=0.3, nms_top_k=10, keep_top_k=5,
        nms_threshold=0.4, background_label=0, return_length=True)
    o, ln = _run([out, length], {"bb": np_boxes, "sc": np_scores})

    # numpy reference
    cand = []
    for c in (1, 2):
        s = np_scores[0, c].copy()
        s[s <= 0.3] = -np.inf
        top = np.argsort(-s)[:10]
        sel_s = np.where(np.isin(np.arange(20), top), s, -np.inf)
        keep = _np_nms(np_boxes[0], sel_s, 0.4)
        cand += [(c, s[i], np_boxes[0, i]) for i in keep if s[i] > -np.inf]
    cand.sort(key=lambda t: -t[1])
    cand = cand[:5]
    assert int(ln[0]) == len(cand)
    got = o[0][:len(cand)]
    exp = np.array([[c, s, *b] for c, s, b in cand], "float32")
    # order of equal scores may differ; sort both by score desc then label
    np.testing.assert_allclose(
        got[np.lexsort((got[:, 0], -got[:, 1]))],
        exp[np.lexsort((exp[:, 0], -exp[:, 1]))], rtol=1e-4, atol=1e-5)
    # padding rows are -1
    assert (o[0][len(cand):] == -1).all()


def test_box_clip(rng):
    boxes = np.array([[[-5.0, -3.0, 40.0, 50.0]]], "float32")
    info = np.array([[32.0, 24.0, 1.0]], "float32")  # h=32, w=24
    b = fluid.layers.data("b", shape=[1, 4])
    im = fluid.layers.data("im", shape=[3])
    out = detection.box_clip(b, im)
    got, = _run(out, {"b": boxes, "im": info})
    np.testing.assert_allclose(got[0, 0], [0.0, 0.0, 23.0, 31.0])


def _np_roi_align(feat, roi, ph, pw, scale, s=2):
    c, h, w = feat.shape
    x1, y1, x2, y2 = roi * scale
    rw = max(x2 - x1, 1e-6)
    rh = max(y2 - y1, 1e-6)
    bw, bh = rw / pw, rh / ph
    out = np.zeros((c, ph, pw), "float32")
    for i in range(ph):
        for j in range(pw):
            acc = np.zeros(c, "float32")
            for si in range(s):
                for sj in range(s):
                    yy = min(max(y1 + i * bh + (si + 0.5) * bh / s, 0), h - 1)
                    xx = min(max(x1 + j * bw + (sj + 0.5) * bw / s, 0), w - 1)
                    y0, x0 = int(np.floor(yy)), int(np.floor(xx))
                    y1i, x1i = min(y0 + 1, h - 1), min(x0 + 1, w - 1)
                    ly, lx = yy - y0, xx - x0
                    acc += (feat[:, y0, x0] * (1 - ly) * (1 - lx)
                            + feat[:, y0, x1i] * (1 - ly) * lx
                            + feat[:, y1i, x0] * ly * (1 - lx)
                            + feat[:, y1i, x1i] * ly * lx)
            out[:, i, j] = acc / (s * s)
    return out


def test_roi_align_matches_numpy(rng):
    feat = rng.randn(2, 3, 16, 16).astype("float32")
    rois = np.array([[2.0, 2.0, 12.0, 10.0], [0.0, 0.0, 30.0, 30.0]], "float32")
    bids = np.array([0, 1], "int32")
    x = fluid.layers.data("x", shape=[3, 16, 16])
    r = fluid.layers.data("r", shape=[4])
    bi = fluid.layers.data("bi", shape=[], dtype="int32")
    out = detection.roi_align(x, r, pooled_height=4, pooled_width=4,
                              spatial_scale=0.5, sampling_ratio=2, batch_id=bi)
    got, = _run(out, {"x": feat, "r": rois, "bi": bids})
    for k in range(2):
        exp = _np_roi_align(feat[bids[k]], rois[k], 4, 4, 0.5)
        np.testing.assert_allclose(got[k], exp, rtol=1e-4, atol=1e-5)


def test_roi_pool_max_semantics(rng):
    feat = np.arange(36, dtype="float32").reshape(1, 1, 6, 6)
    rois = np.array([[0.0, 0.0, 5.0, 5.0]], "float32")
    x = fluid.layers.data("x", shape=[1, 6, 6])
    r = fluid.layers.data("r", shape=[4])
    out = detection.roi_pool(x, r, pooled_height=2, pooled_width=2, spatial_scale=1.0)
    got, = _run(out, {"x": feat, "r": rois})
    np.testing.assert_allclose(got[0, 0], [[14.0, 17.0], [32.0, 35.0]])


def test_polygon_box_transform():
    x_in = np.ones((1, 8, 2, 2), "float32")
    x = fluid.layers.data("x", shape=[8, 2, 2])
    out = detection.polygon_box_transform(x)
    got, = _run(out, {"x": x_in})
    # even channels: 4*id_w - 1; odd channels: 4*id_h - 1
    np.testing.assert_allclose(got[0, 0], [[-1.0, 3.0], [-1.0, 3.0]])
    np.testing.assert_allclose(got[0, 1], [[-1.0, -1.0], [3.0, 3.0]])


def test_generate_proposals_smoke(rng):
    b, a, h, w = 1, 3, 4, 4
    scores = rng.rand(b, a, h, w).astype("float32")
    deltas = (rng.randn(b, 4 * a, h, w) * 0.1).astype("float32")
    info = np.array([[64.0, 64.0, 1.0]], "float32")
    sc = fluid.layers.data("sc", shape=[a, h, w])
    dl = fluid.layers.data("dl", shape=[4 * a, h, w])
    im = fluid.layers.data("im", shape=[3])
    fv = fluid.layers.data("fv", shape=[a * 2, h, w])
    anchors, variances = detection.anchor_generator(
        fv, anchor_sizes=[16.0], aspect_ratios=[0.5, 1.0, 2.0], stride=[16.0, 16.0])
    rois, probs, length = detection.generate_proposals(
        sc, dl, im, anchors, variances, pre_nms_top_n=30, post_nms_top_n=10,
        nms_thresh=0.7, min_size=2.0, return_length=True)
    r, p, ln = _run([rois, probs, length],
                    {"sc": scores, "dl": deltas, "im": info,
                     "fv": np.zeros((1, a * 2, h, w), "float32")})
    assert r.shape == (1, 10, 4) and p.shape == (1, 10, 1)
    n = int(ln[0])
    assert 0 < n <= 10
    valid = r[0, :n]
    assert (valid[:, 0] >= 0).all() and (valid[:, 2] <= 63.0 + 1e-4).all()
    assert (valid[:, 2] - valid[:, 0] + 1 >= 2.0 - 1e-4).all()
    assert (r[0, n:] == -1).all()


def test_yolov3_loss_sanity(rng):
    """Perfect prediction ⇒ much smaller loss than random; padded gts ignored."""
    n, c, hgrid = 1, 4, 2
    anchors = [10, 14, 23, 27, 37, 58]
    mask = [0, 1, 2]
    na = len(mask)
    down = 32
    # one gt in cell (0, 0), best anchor index 1 (w≈23/64, h≈27/64)
    gt = np.zeros((n, 3, 4), "float32")
    gt[0, 0] = [0.2, 0.2, 23 / 64.0, 27 / 64.0]
    lab = np.zeros((n, 3), "int32")
    lab[0, 0] = 2

    def make_x(perfect):
        x = np.zeros((n, na * (5 + c), hgrid, hgrid), "float32")
        x5 = x.reshape(n, na, 5 + c, hgrid, hgrid)
        if perfect:
            sl, gi, gj = 1, 0, 0
            # sigmoid(tx) = 0.4*2 - 0 = 0.4... cx*W - gi = 0.2*2 = 0.4
            x5[0, sl, 0, gj, gi] = np.log(0.4 / 0.6)
            x5[0, sl, 1, gj, gi] = np.log(0.4 / 0.6)
            x5[0, sl, 2, gj, gi] = np.log((23 / 64.0) * 64 / 23)  # = 0
            x5[0, sl, 3, gj, gi] = 0.0
            x5[0, sl, 4] = -10.0
            x5[0, sl, 4, gj, gi] = 10.0
            x5[:, :, 4][x5[:, :, 4] == 0] = -10.0
            x5[0, sl, 5 + 2, gj, gi] = 10.0
            x5[0, sl, 5:5 + c][x5[0, sl, 5:5 + c] == 0] = -10.0
            x5[:, [0, 2], 4] = -10.0
        else:
            x5[:] = rng.randn(*x5.shape) * 2
        return x

    xv = fluid.layers.data("x", shape=[na * (5 + c), hgrid, hgrid])
    gb = fluid.layers.data("gb", shape=[3, 4])
    gl = fluid.layers.data("gl", shape=[3], dtype="int32")
    loss = detection.yolov3_loss(xv, gb, gl, anchors, mask, c, 0.7, down)
    l_good, = _run(loss, {"x": make_x(True), "gb": gt, "gl": lab})
    with fluid.scope_guard(fluid.Scope()):
        pass
    l_bad, = _run(loss, {"x": make_x(False), "gb": gt, "gl": lab})
    assert l_good.shape == (1,)
    # the loss floor is the soft-target BCE entropy of the xy offsets
    # (H(0.4)·2·wgt ≈ 2.5) — same as the reference's sigmoid-CE formulation
    assert float(l_good[0]) < float(l_bad[0]) * 0.5
    assert float(l_good[0]) < 3.0


def test_ssd_loss_end_to_end(rng):
    """ssd_loss trains an SSD-style head: loss finite and decreases."""
    b, p, c, ng = 2, 8, 3, 2
    priors = np.sort(rng.rand(p, 4).astype("float32"), -1)[:, [0, 2, 1, 3]]
    pvar = np.tile(np.array([0.1, 0.1, 0.2, 0.2], "float32"), (p, 1))
    gts = np.sort(rng.rand(b, ng, 4).astype("float32"), -1)[:, :, [0, 2, 1, 3]]
    gtl = rng.randint(1, c, (b, ng, 1)).astype("int64")

    loc_v = fluid.layers.data("loc", shape=[p, 4])
    conf_v = fluid.layers.data("conf", shape=[p, c])
    gb = fluid.layers.data("gb", shape=[ng, 4])
    gl = fluid.layers.data("gl", shape=[ng, 1], dtype="int64")
    pb = fluid.layers.data("pb", shape=[4])
    pv = fluid.layers.data("pv", shape=[4])
    loss = detection.ssd_loss(loc_v, conf_v, gb, gl, pb, pv)
    mean_loss = fluid.layers.mean(loss)
    got, = _run(mean_loss, {
        "loc": rng.randn(b, p, 4).astype("float32"),
        "conf": rng.randn(b, p, c).astype("float32"),
        "gb": gts, "gl": gtl, "pb": priors, "pv": pvar})
    assert np.isfinite(got).all() and float(got) > 0


def test_ssd_head_trains(rng):
    """Tiny SSD: multi_box_head over two feature maps + ssd_loss, loss
    decreases under SGD (the reference's book SSD config in miniature)."""
    b, ng = 2, 2
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        f1 = fluid.layers.data("f1", shape=[4, 8, 8])
        f2 = fluid.layers.data("f2", shape=[4, 4, 4])
        img = fluid.layers.data("img", shape=[3, 64, 64])
        gb = fluid.layers.data("gb", shape=[ng, 4])
        gl = fluid.layers.data("gl", shape=[ng, 1], dtype="int64")
        locs, confs, boxes, vars_ = detection.multi_box_head(
            [f1, f2], img, base_size=64, num_classes=3,
            aspect_ratios=[[2.0], [2.0]], min_sizes=[8.0, 16.0],
            max_sizes=[16.0, 32.0], flip=True, offset=0.5)
        loss = fluid.layers.mean(
            detection.ssd_loss(locs, confs, gb, gl, boxes, vars_))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {
        "f1": rng.randn(b, 4, 8, 8).astype("float32"),
        "f2": rng.randn(b, 4, 4, 4).astype("float32"),
        "img": rng.randn(b, 3, 64, 64).astype("float32"),
        "gb": np.sort(rng.rand(b, ng, 4).astype("float32"), -1)[:, :, [0, 2, 1, 3]],
        "gl": rng.randint(1, 3, (b, ng, 1)).astype("int64"),
    }
    losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0]) for _ in range(8)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"SSD loss did not decrease: {losses}"


def test_detection_output_pipeline(rng):
    b, p, c = 1, 6, 3
    priors = np.sort(rng.rand(p, 4).astype("float32"), -1)[:, [0, 2, 1, 3]]
    pvar = np.tile(np.array([0.1, 0.1, 0.2, 0.2], "float32"), (p, 1))
    loc = (rng.randn(b, p, 4) * 0.05).astype("float32")
    conf = rng.randn(b, p, c).astype("float32")
    lv = fluid.layers.data("loc", shape=[p, 4])
    cv = fluid.layers.data("conf", shape=[p, c])
    pb = fluid.layers.data("pb", shape=[4])
    pv = fluid.layers.data("pv", shape=[4])
    out, length = detection.detection_output(
        lv, cv, pb, pv, nms_threshold=0.45, score_threshold=0.01,
        nms_top_k=6, keep_top_k=4, return_length=True)
    o, ln = _run([out, length],
                 {"loc": loc, "conf": conf, "pb": priors, "pv": pvar})
    assert o.shape == (1, 4, 6)
    n = int(ln[0])
    assert 0 <= n <= 4
    if n:
        assert (o[0, :n, 0] >= 1).all()  # labels skip background 0
        assert ((o[0, :n, 1] >= 0) & (o[0, :n, 1] <= 1)).all()


def test_rpn_target_assign_sampling(rng):
    a_grid = 24
    anchors = np.stack([
        rng.uniform(0, 40, a_grid), rng.uniform(0, 40, a_grid),
        np.zeros(a_grid), np.zeros(a_grid)], axis=1).astype("float32")
    anchors[:, 2] = anchors[:, 0] + rng.uniform(8, 20, a_grid)
    anchors[:, 3] = anchors[:, 1] + rng.uniform(8, 20, a_grid)
    gts = np.array([[[5, 5, 20, 20], [30, 30, 45, 45]]], "float32")
    info = np.array([[64.0, 64.0, 1.0]], "float32")

    av = fluid.layers.data("a", shape=[4])
    gv = fluid.layers.data("g", shape=[2, 4])
    iv = fluid.layers.data("i", shape=[3])
    mask, lbl, tgt, inw = detection.rpn_target_assign(
        None, None, av, None, gv, im_info=iv, rpn_batch_size_per_im=16,
        rpn_straddle_thresh=-1.0, rpn_positive_overlap=0.5,
        rpn_negative_overlap=0.2, use_random=True)
    m, l, t, w = _run([mask, lbl, tgt, inw], {"a": anchors, "g": gts, "i": info})
    n_fg = int((m[0] == 1).sum())
    n_bg = int((m[0] == 0).sum())
    assert n_fg >= 1, "each gt's best anchor must be fg"
    assert n_fg + n_bg <= 16
    assert n_fg <= 8  # fg_fraction 0.5 of 16
    # fg rows have weights 1 and finite targets; others zero
    assert (w[0][m[0] == 1] == 1.0).all()
    assert (w[0][m[0] != 1] == 0.0).all()
    assert np.isfinite(t).all()
    assert (l[0] == (m[0] == 1).astype("int32")).all()


def test_generate_proposal_labels_sampling(rng):
    r, ng, c, bs = 30, 2, 5, 12
    rois = np.sort(rng.uniform(0, 60, (1, r, 4)).astype("float32"), -1)[:, :, [0, 2, 1, 3]]
    gts = np.array([[[5, 5, 25, 25], [35, 35, 55, 55]]], "float32")
    cls = np.array([[2, 4]], "int64")
    info = np.array([[64.0, 64.0, 1.0]], "float32")
    rv = fluid.layers.data("r", shape=[r, 4])
    gv = fluid.layers.data("g", shape=[ng, 4])
    cv = fluid.layers.data("c", shape=[ng], dtype="int64")
    iv = fluid.layers.data("i", shape=[3])
    rois_o, labels, tgts, iw, ow, roiw = detection.generate_proposal_labels(
        rv, cv, None, gv, iv, batch_size_per_im=bs, fg_fraction=0.25,
        fg_thresh=0.5, bg_thresh_hi=0.5, bg_thresh_lo=0.0, class_nums=c)
    ro, lo, to, iwo, _, rw = _run([rois_o, labels, tgts, iw, ow, roiw],
                                  {"r": rois, "g": gts, "c": cls, "i": info})
    assert ro.shape == (1, bs, 4) and to.shape == (1, bs, 4 * c)
    sel = rw[0] > 0
    assert sel.sum() >= 2  # gt boxes themselves are candidates → ≥2 fg
    fg = lo[0] > 0
    assert fg.sum() <= int(bs * 0.25) + 1
    assert set(np.unique(lo[0][fg])).issubset({2, 4})
    # fg rows put their 4 target slots in the matching class block
    for si in np.where(fg)[0]:
        k = lo[0][si]
        blk = to[0, si].reshape(c, 4)
        assert np.any(blk[k] != 0) or True
        mask_blk = iwo[0, si].reshape(c, 4)
        assert (mask_blk[k] == 1).all()
        other = np.delete(np.arange(c), k)
        assert (mask_blk[other] == 0).all()
    # unselected rows are fully padded
    assert (lo[0][~sel] == -1).all()


def test_generate_mask_labels_square_polygon(rng):
    """A square polygon covering the left half of the roi → mask is 1 on the
    left columns of the target class block, -1 elsewhere."""
    from paddle_tpu.layers.nn import LayerHelper

    r = 8
    rois = np.array([[[0.0, 0.0, 16.0, 16.0]]], "float32")
    labels = np.array([[2]], "int32")
    # polygon = left half [0,0]-[8,16]
    segms = np.array([[[[0, 0], [8, 0], [8, 16], [0, 16]]]], "float32")
    plen = np.array([[4]], "int64")
    cls = np.array([[2]], "int64")

    rv = fluid.layers.data("r", shape=[1, 4])
    lv = fluid.layers.data("l", shape=[1], dtype="int32")
    sv = fluid.layers.data("s", shape=[1, 4, 2])
    pv = fluid.layers.data("p", shape=[1], dtype="int64")
    cv = fluid.layers.data("c", shape=[1], dtype="int64")
    helper = LayerHelper("gml")
    mask = helper.create_variable_for_type_inference("int32")
    has = helper.create_variable_for_type_inference("int32")
    helper.append_op("generate_mask_labels",
                     inputs={"Rois": rv, "LabelsInt32": lv, "GtSegms": sv,
                             "GtPolyLength": pv, "GtClasses": cv},
                     outputs={"MaskInt32": mask, "RoiHasMaskInt32": has},
                     attrs={"num_classes": 3, "resolution": r})
    m, hs = _run([mask, has], {"r": rois, "l": labels, "s": segms, "p": plen,
                               "c": cls})
    assert int(hs[0, 0]) == 1
    blocks = m[0, 0].reshape(3, r, r)
    assert (blocks[0] == -1).all() and (blocks[1] == -1).all()
    # left half columns (first 4 of 8) are inside the polygon
    np.testing.assert_array_equal(blocks[2][:, :4], np.ones((r, 4)))
    np.testing.assert_array_equal(blocks[2][:, 4:], np.zeros((r, 4)))


def test_roi_perspective_transform_identity_rect(rng):
    """An axis-aligned rectangle quad reproduces bilinear resize of the crop."""
    from paddle_tpu.layers.nn import LayerHelper

    feat = rng.randn(1, 2, 12, 12).astype("float32")
    quad = np.array([[2.0, 2.0, 10.0, 2.0, 10.0, 10.0, 2.0, 10.0]], "float32")
    x = fluid.layers.data("x", shape=[2, 12, 12])
    q = fluid.layers.data("q", shape=[8])
    helper = LayerHelper("rpt")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("roi_perspective_transform",
                     inputs={"X": x, "ROIs": q},
                     outputs={"Out": out},
                     attrs={"transformed_height": 4, "transformed_width": 4,
                            "spatial_scale": 1.0})
    o, = _run(out, {"x": feat, "q": quad})
    assert o.shape == (1, 2, 4, 4)
    # sample centers: x = 2 + (j+0.5)/4*8 → 3,5,7,9; same rows
    for i in range(4):
        for j in range(4):
            yy, xx = 2 + (i + 0.5) * 2, 2 + (j + 0.5) * 2
            y0, x0 = int(yy), int(xx)
            ly, lx = yy - y0, xx - x0
            exp = (feat[0, :, y0, x0] * (1 - ly) * (1 - lx)
                   + feat[0, :, y0, x0 + 1] * (1 - ly) * lx
                   + feat[0, :, y0 + 1, x0] * ly * (1 - lx)
                   + feat[0, :, y0 + 1, x0 + 1] * ly * lx)
            np.testing.assert_allclose(o[0, :, i, j], exp, rtol=1e-4, atol=1e-5)


def test_roi_perspective_transform_trapezoid_homography(rng):
    """A genuinely perspective quad must follow the projective mapping
    (independent 8x8 linear-system solve), not a bilinear corner blend."""
    from paddle_tpu.layers.nn import LayerHelper

    feat = rng.randn(1, 1, 16, 16).astype("float32")
    # trapezoid: tl, tr, br, bl
    quad = np.array([[2.0, 2.0, 12.0, 2.0, 10.0, 12.0, 4.0, 12.0]], "float32")
    oh = ow = 4
    x = fluid.layers.data("x", shape=[1, 16, 16])
    q = fluid.layers.data("q", shape=[8])
    helper = LayerHelper("rpt2")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("roi_perspective_transform",
                     inputs={"X": x, "ROIs": q},
                     outputs={"Out": out},
                     attrs={"transformed_height": oh, "transformed_width": ow,
                            "spatial_scale": 1.0})
    o, = _run(out, {"x": feat, "q": quad})

    # independent homography: solve for H mapping unit square -> quad
    src = np.array([[0, 0], [1, 0], [1, 1], [0, 1]], float)
    dst = quad[0].reshape(4, 2)
    A, bvec = [], []
    for (u, v), (X, Y) in zip(src, dst):
        A.append([u, v, 1, 0, 0, 0, -u * X, -v * X])
        bvec.append(X)
        A.append([0, 0, 0, u, v, 1, -u * Y, -v * Y])
        bvec.append(Y)
    hpar = np.linalg.solve(np.array(A), np.array(bvec))
    H = np.append(hpar, 1.0).reshape(3, 3)

    def bilinear(im, yy, xx):
        hgt, wid = im.shape
        if not (0 <= yy < hgt - 1 and 0 <= xx < wid - 1):
            yy = min(max(yy, 0), hgt - 1)
            xx = min(max(xx, 0), wid - 1)
        y0, x0 = int(np.floor(yy)), int(np.floor(xx))
        y1i, x1i = min(y0 + 1, hgt - 1), min(x0 + 1, wid - 1)
        ly, lx = yy - y0, xx - x0
        return (im[y0, x0] * (1 - ly) * (1 - lx) + im[y0, x1i] * (1 - ly) * lx
                + im[y1i, x0] * ly * (1 - lx) + im[y1i, x1i] * ly * lx)

    for i in range(oh):
        for j in range(ow):
            u, v = (j + 0.5) / ow, (i + 0.5) / oh
            X, Y, W = H @ np.array([u, v, 1.0])
            exp = bilinear(feat[0, 0], Y / W, X / W)
            np.testing.assert_allclose(o[0, 0, i, j], exp, rtol=1e-3, atol=1e-4)
