"""Elastic checkpoint/resume tests (SURVEY §5.3): rotating serials, atomic
writes (partial checkpoints skipped), and preemption-resume producing
bit-identical training to an uninterrupted run."""

import os

import numpy as np

import paddle_tpu as fluid


def _build(dim=8, classes=3):
    x = fluid.layers.data("x", shape=[dim])
    y = fluid.layers.data("y", shape=[1], dtype="int64")
    logits = fluid.layers.fc(x, size=classes, param_attr=fluid.ParamAttr(name="w"),
                             bias_attr=fluid.ParamAttr(name="b"))
    loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
    fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
    return loss


def _data(rng, n=64, dim=8, classes=3):
    xs = rng.randn(n, dim).astype("float32")
    ys = rng.randint(0, classes, (n, 1)).astype("int64")
    return xs, ys


def test_checkpoint_rotation_and_serials(tmp_path, rng):
    ckpt = str(tmp_path / "ck")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs, ys = _data(rng)
    for step in range(5):
        exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        fluid.io.save_checkpoint(exe, ckpt, main, trainer_args={"step": step},
                                 max_num_checkpoints=3)
    names = sorted(os.listdir(ckpt))
    assert names == ["checkpoint_2", "checkpoint_3", "checkpoint_4"], names
    args = fluid.io.load_checkpoint(exe, ckpt, main)
    assert args["step"] == 4


def test_resume_matches_uninterrupted(tmp_path, rng):
    xs, ys = _data(rng)
    ckpt = str(tmp_path / "ck")

    def fresh():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 90210
        with fluid.program_guard(main, startup):
            loss = _build()
        return main, startup, loss

    # uninterrupted: 10 steps
    main, startup, loss = fresh()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(10):
            full = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        w_full = fluid.global_scope().as_numpy("w")

    # interrupted at step 5 + resume in a brand-new scope ("new process")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for step in range(5):
            exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        fluid.io.save_checkpoint(exe, ckpt, main, trainer_args={"step": 5})
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)  # re-init (wrong weights) — then restore
        args = fluid.io.load_checkpoint(exe, ckpt, main)
        assert args["step"] == 5
        for _ in range(5):
            resumed = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        w_res = fluid.global_scope().as_numpy("w")
    np.testing.assert_allclose(w_res, w_full, rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(float(resumed[0]), float(full[0]), rtol=1e-6)


def test_partial_checkpoint_skipped(tmp_path, rng):
    """A checkpoint dir without the _SUCCESS marker (preempted mid-save)
    must be ignored in favour of the previous complete one."""
    ckpt = str(tmp_path / "ck")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs, ys = _data(rng)
    exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    fluid.io.save_checkpoint(exe, ckpt, main, trainer_args={"step": 0})
    good_w = fluid.global_scope().as_numpy("w")
    # simulate a torn write: newer serial without _SUCCESS
    torn = os.path.join(ckpt, "checkpoint_1")
    os.makedirs(torn)
    with open(os.path.join(torn, "garbage"), "w") as f:
        f.write("x")
    exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])  # drift weights
    args = fluid.io.load_checkpoint(exe, ckpt, main)
    assert args["step"] == 0
    np.testing.assert_allclose(fluid.global_scope().as_numpy("w"), good_w)


def test_no_checkpoint_returns_none(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    assert fluid.io.load_checkpoint(exe, str(tmp_path / "nope"), main) is None
    fluid.io.clean_checkpoint(str(tmp_path / "nope"))  # no-op, no raise
