"""Quantization tests: fake-quant op numerics vs numpy, STE gradients, and
the QuantizeTranspiler QAT → freeze → int8 pipeline end to end (reference:
contrib/tests/test_quantize_transpiler.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.contrib import QuantizeTranspiler


def _run(fetch, feed):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feed, fetch_list=fetch if isinstance(fetch, list) else [fetch])


def test_fake_quantize_abs_max_numerics(rng):
    x_np = (rng.randn(4, 6) * 3).astype("float32")
    x = fluid.layers.data("x", shape=[6])
    helper = fluid.layers.nn.LayerHelper("q")
    out = helper.create_variable_for_type_inference("float32")
    scale = helper.create_variable_for_type_inference("float32")
    helper.append_op("fake_quantize_abs_max", inputs={"X": x},
                     outputs={"Out": out, "OutScale": scale},
                     attrs={"bit_length": 8})
    o, s = _run([out, scale], {"x": x_np})
    exp_scale = np.max(np.abs(x_np))
    np.testing.assert_allclose(s[0], exp_scale, rtol=1e-6)
    np.testing.assert_allclose(o, np.round(x_np / exp_scale * 127), atol=1e-4)


def test_fake_quant_dequant_roundtrip_error_bounded(rng):
    x_np = (rng.randn(8, 8)).astype("float32")
    x = fluid.layers.data("x", shape=[8])
    helper = fluid.layers.nn.LayerHelper("q")
    q = helper.create_variable_for_type_inference("float32")
    scale = helper.create_variable_for_type_inference("float32")
    dq = helper.create_variable_for_type_inference("float32")
    helper.append_op("fake_quantize_abs_max", inputs={"X": x},
                     outputs={"Out": q, "OutScale": scale}, attrs={"bit_length": 8})
    helper.append_op("fake_dequantize_max_abs", inputs={"X": q, "Scale": scale},
                     outputs={"Out": dq}, attrs={"max_range": 127.0})
    o, = _run(dq, {"x": x_np})
    # max error = scale/127/2
    bound = np.max(np.abs(x_np)) / 127.0
    assert np.max(np.abs(o - x_np)) <= bound


def test_ste_gradient_identity(rng):
    """Quant→dequant pair must pass gradients straight through (STE)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.quantize_ops import quantize_abs_max

    x = jnp.asarray(rng.randn(5, 5).astype("float32"))

    def f(v):
        q, s = quantize_abs_max(v, 8)
        return jnp.sum(q * (jax.lax.stop_gradient(s) / 127.0) * 2.0)

    g = jax.grad(f)(x)
    np.testing.assert_allclose(g, np.full((5, 5), 2.0), rtol=1e-5)


@pytest.mark.parametrize("act_type", ["abs_max", "moving_average_abs_max", "range_abs_max"])
def test_qat_training_converges(rng, act_type):
    """QAT-transpiled MLP trains to decreasing loss; quant ops are present."""
    dim, classes = 16, 4
    centers = rng.randn(classes, dim).astype("float32") * 3
    ys = rng.randint(0, classes, 128)
    xs = (centers[ys] + rng.randn(128, dim) * 0.3).astype("float32")
    ys = ys.reshape(-1, 1).astype("int64")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[dim])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=32, act="relu")
        logits = fluid.layers.fc(h, size=classes)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
        t = QuantizeTranspiler(activation_quantize_type=act_type)
        t.training_transpile(main, startup)
        fluid.optimizer.Adam(1e-2).minimize(loss)

    qops = [op.type for b in main.blocks for op in b.ops
            if op.type.startswith("fake_quantize")]
    assert len(qops) >= 4, f"quant ops not inserted: {qops}"

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = [float(exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])[0])
              for _ in range(20)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses


def test_qat_freeze_and_int8(rng):
    """freeze_program: weights land on the int grid, inference stays close
    to the QAT model; convert_to_int8 stores int8 arrays."""
    dim, classes = 8, 3
    xs = rng.randn(32, dim).astype("float32")
    ys = rng.randint(0, classes, (32, 1)).astype("int64")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[dim])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        logits = fluid.layers.fc(x, size=classes, param_attr=fluid.ParamAttr(name="w"))
        sm = fluid.layers.softmax(logits)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(sm, y))
        t = QuantizeTranspiler()
        t.training_transpile(main, startup)
        test_program = main.clone(for_test=True)
        fluid.optimizer.SGD(0.05).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    for _ in range(5):
        exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    qat_out, = exe.run(test_program, feed={"x": xs, "y": ys}, fetch_list=[sm])

    t.freeze_program(test_program)
    frozen_w = fluid.global_scope().as_numpy("w")
    assert np.all(np.abs(frozen_w - np.round(frozen_w)) < 1e-5), "weights not on int grid"
    assert np.max(np.abs(frozen_w)) <= 127
    frozen_out, = exe.run(test_program, feed={"x": xs, "y": ys}, fetch_list=[sm.name + ".dequantized"]) \
        if False else exe.run(test_program, feed={"x": xs, "y": ys}, fetch_list=[sm])
    np.testing.assert_allclose(frozen_out, qat_out, atol=5e-2)

    converted = t.convert_to_int8(test_program)
    assert "w" in converted
    assert fluid.global_scope().as_numpy("w").dtype == np.int8


class TestPostTrainingCalibration:
    """VERDICT r3 #7 (ref contrib/int8_inference/utility.py): calibrate a
    TRAINED fp32 program with a calibration reader, emit the int8 program
    via the freeze machinery, and stay within tolerance of fp32."""

    def _train_fp32(self, rng):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[16])
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, size=32, act="relu")
            logits = fluid.layers.fc(h, size=4)
            prob = fluid.layers.softmax(logits)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            test_prog = main.clone(for_test=True)
            fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        w = rng.randn(16, 4)
        xs = rng.randn(256, 16).astype("float32")
        ys = np.argmax(xs @ w, axis=1).reshape(-1, 1).astype("int64")
        for i in range(30):
            exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        return exe, test_prog, prob, xs, ys

    @pytest.mark.parametrize("algo", ["abs_max", "KL"])
    def test_calibrate_freeze_predict(self, rng, algo):
        from paddle_tpu.contrib.int8_inference import Calibrator

        exe, test_prog, prob, xs, ys = self._train_fp32(rng)
        fp32_prob, = exe.run(test_prog, feed={"x": xs[:64], "y": ys[:64]},
                             fetch_list=[prob], return_numpy=True)

        calib = Calibrator(test_prog, exe, algo=algo)
        for i in range(0, 256, 64):
            calib.sample_data({"x": xs[i:i + 64], "y": ys[i:i + 64]})
        qprog = calib.calibrate()

        q_prob, = exe.run(qprog, feed={"x": xs[:64], "y": ys[:64]},
                          fetch_list=[prob], return_numpy=True)
        # int8 predictions track fp32: same argmax on nearly every row and
        # close probabilities
        agree = (np.argmax(q_prob, 1) == np.argmax(fp32_prob, 1)).mean()
        assert agree >= 0.95, "argmax agreement %.3f" % agree
        assert np.max(np.abs(q_prob - fp32_prob)) < 0.15

        # the weights really sit on the int grid after freeze
        from paddle_tpu.contrib.quantize.quantize_transpiler import QuantizeTranspiler

        conv = QuantizeTranspiler().convert_to_int8(qprog)
        assert conv, "no weights converted to int8 storage"
        w0 = np.asarray(fluid.global_scope().find_var(conv[0]))
        assert w0.dtype == np.int8

    def test_calibrator_requires_samples(self, rng):
        from paddle_tpu.contrib.int8_inference import Calibrator

        exe, test_prog, prob, xs, ys = self._train_fp32(rng)
        with pytest.raises(RuntimeError, match="sample_data"):
            Calibrator(test_prog, exe).calibrate()

    def test_save_int8_model(self, rng, tmp_path):
        from paddle_tpu.contrib.int8_inference import Calibrator

        exe, test_prog, prob, xs, ys = self._train_fp32(rng)
        calib = Calibrator(test_prog, exe, algo="abs_max")
        calib.sample_data({"x": xs[:64], "y": ys[:64]})
        out_dir = str(tmp_path / "int8_model")
        calib.save_int8_model(out_dir, ["x"], [prob])
        import os

        assert os.path.isdir(out_dir) and os.listdir(out_dir)
        # the saved model loads and predicts
        with fluid.scope_guard(fluid.core.Scope()):
            exe2 = fluid.Executor(fluid.CPUPlace())
            prog, feeds, fetches = fluid.io.load_inference_model(out_dir, exe2)
            out, = exe2.run(prog, feed={feeds[0]: xs[:8]}, fetch_list=fetches,
                            return_numpy=True)
            assert out.shape == (8, 4)
            assert np.all(np.isfinite(out))
