"""Pipeline parallelism tests on the virtual CPU mesh: GPipe forward and
fwd+bwd parity against the plain sequential composition of stages."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from paddle_tpu.parallel import gpipe, pipeline_step, stack_stage_params


def _mesh(n, axis="pipe"):
    devs = np.array(jax.devices()[:n])
    return Mesh(devs, (axis,))


def _stage(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _make(rng, s=4, d=8):
    per_stage = [(jnp.asarray(rng.randn(d, d).astype("float32") * 0.4),
                  jnp.asarray(rng.randn(d).astype("float32") * 0.1))
                 for _ in range(s)]
    return per_stage, stack_stage_params(per_stage)


def test_gpipe_forward_matches_sequential(rng):
    s, m, mb, d = 4, 6, 3, 8
    per_stage, stacked = _make(rng, s, d)
    x = jnp.asarray(rng.randn(m, mb, d).astype("float32"))
    mesh = _mesh(s)
    fwd = gpipe(_stage, mesh, "pipe")
    got = jax.jit(fwd)(stacked, x)

    exp = x
    for p in per_stage:
        exp = _stage(p, exp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exp), rtol=2e-5, atol=2e-5)


def test_gpipe_gradients_match_sequential(rng):
    s, m, mb, d = 4, 5, 2, 8
    per_stage, stacked = _make(rng, s, d)
    x = jnp.asarray(rng.randn(m, mb, d).astype("float32"))
    y = jnp.asarray(rng.randn(m, mb, d).astype("float32"))
    mesh = _mesh(s)

    def loss_fn(outs, labels):
        return jnp.mean((outs - labels) ** 2)

    step = jax.jit(pipeline_step(_stage, loss_fn, mesh, "pipe"))
    loss_p, grads_p = step(stacked, x, y)

    def seq_loss(st):
        h = x
        for i in range(s):
            h = _stage(jax.tree.map(lambda a: a[i], st), h)
        return loss_fn(h, y)

    loss_s, grads_s = jax.value_and_grad(seq_loss)(stacked)
    np.testing.assert_allclose(float(loss_p), float(loss_s), rtol=2e-5)
    for gp, gs in zip(jax.tree.leaves(grads_p), jax.tree.leaves(grads_s)):
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gs),
                                   rtol=5e-4, atol=1e-5)


def test_gpipe_trains(rng):
    """A 4-stage pipelined MLP fits a random mapping — end-to-end SGD."""
    s, m, mb, d = 4, 4, 4, 8
    per_stage, stacked = _make(rng, s, d)
    x = jnp.asarray(rng.randn(m, mb, d).astype("float32"))
    y = jnp.asarray((rng.randn(m, mb, d) * 0.3).astype("float32"))
    mesh = _mesh(s)
    step = jax.jit(pipeline_step(_stage, lambda o, l: jnp.mean((o - l) ** 2),
                                 mesh, "pipe"))
    params = stacked
    losses = []
    for _ in range(25):
        loss, grads = step(params, x, y)
        params = jax.tree.map(lambda p, g: p - 0.3 * g, params, grads)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses


def test_gpipe_with_params_sharded_on_mesh(rng):
    """Stage params placed with the pipe sharding still give correct results
    (each device holds only its stage — the memory-scaling contract)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    s, m, mb, d = 4, 4, 2, 8
    per_stage, stacked = _make(rng, s, d)
    mesh = _mesh(s)
    sh = NamedSharding(mesh, P("pipe"))
    stacked = jax.tree.map(lambda p: jax.device_put(p, sh), stacked)
    x = jnp.asarray(rng.randn(m, mb, d).astype("float32"))
    fwd = gpipe(_stage, mesh, "pipe")
    got = jax.jit(fwd)(stacked, x)
    exp = x
    for p in per_stage:
        exp = _stage(p, exp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exp), rtol=2e-5, atol=2e-5)


def test_gpipe_activations_sharded_not_replicated(rng):
    """The memory contract (VERDICT r2 weak #3): microbatch slabs and
    outputs are sharded over the pipe axis — no device materializes the
    full [M, mb, ...] batch."""
    s, m, mb, d = 4, 8, 4, 8
    per_stage, stacked = _make(rng, s, d)
    mesh = _mesh(s)
    x = jnp.asarray(rng.randn(m, mb, d).astype("float32"))
    fwd = gpipe(_stage, mesh, "pipe")
    got = jax.jit(fwd)(stacked, x)
    # outputs come back sharded on the M axis: each device owns M/S slabs
    assert len(got.sharding.device_set) == s
    shard = got.addressable_shards[0].data
    assert shard.shape[0] == m // s, (shard.shape, got.shape)
    # and per-device bytes are 1/S of the full activation batch
    full = got.size * got.dtype.itemsize
    per_dev = shard.size * shard.dtype.itemsize
    assert per_dev * s == full
