"""Round-4 op tail: proximal_gd / proximal_adagrad (reference:
operators/optimizers/proximal_gd_op.h, proximal_adagrad_op.h) and
positive_negative_pair (reference: operators/positive_negative_pair_op.h),
checked OpTest-style against numpy oracles ported from the reference's own
unit tests (test_proximal_gd_op.py, test_positive_negative_pair_op.py)."""

import itertools

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.testing.op_test import check_output, run_op


@pytest.fixture
def r():
    return np.random.RandomState(7)


def _soft(prox, lr, l1, l2):
    if l1 > 0:
        return (np.sign(prox) * np.maximum(np.abs(prox) - lr * l1, 0.0)
                / (1.0 + lr * l2))
    return prox / (1.0 + lr * l2)


@pytest.mark.parametrize("l1,l2", [(0.0, 0.0), (0.1, 0.2), (0.3, 0.0)])
def test_proximal_gd(r, l1, l2):
    p = r.randn(5, 3).astype("float32")
    g = r.randn(5, 3).astype("float32")
    lr = np.array([0.05], "float32")
    want = _soft(p - 0.05 * g, 0.05, l1, l2).astype("float32")
    check_output("proximal_gd",
                 {"Param": p, "Grad": g, "LearningRate": lr},
                 {"ParamOut": want}, attrs={"l1": l1, "l2": l2}, atol=1e-6)


@pytest.mark.parametrize("l1,l2", [(0.0, 0.0), (0.1, 0.2)])
def test_proximal_adagrad(r, l1, l2):
    p = r.randn(4, 2).astype("float32")
    g = r.randn(4, 2).astype("float32")
    m = np.abs(r.randn(4, 2)).astype("float32") + 0.1
    lr = np.array([0.05], "float32")
    m_new = m + g * g
    want = _soft(p - 0.05 * g / np.sqrt(m_new), 0.05, l1, l2).astype("float32")
    check_output("proximal_adagrad",
                 {"Param": p, "Grad": g, "Moment": m, "LearningRate": lr},
                 {"ParamOut": want, "MomentOut": m_new},
                 attrs={"l1": l1, "l2": l2}, atol=1e-6)


def test_proximal_adagrad_sparse_rows_only(r):
    """The sparse variant must update exactly the touched rows."""
    from paddle_tpu.core.sparse import SparseGrad

    vocab, dim = 10, 4
    p = r.randn(vocab, dim).astype("float32")
    m = np.abs(r.randn(vocab, dim)).astype("float32") + 0.1
    ids = np.array([2, 7, 2], "int64")          # duplicate id accumulates
    rows = r.randn(3, dim).astype("float32")
    lr = np.array([0.1], "float32")

    g = SparseGrad(ids=np.asarray(ids), rows=np.asarray(rows))
    out = run_op("proximal_adagrad",
                 {"Param": p, "Grad": g, "Moment": m, "LearningRate": lr},
                 ["ParamOut", "MomentOut"], attrs={"l1": 0.1, "l2": 0.05})

    dense_g = np.zeros_like(p)
    np.add.at(dense_g, ids, rows)
    m_new = m.copy()
    want = p.copy()
    for i in np.unique(ids):
        m_new[i] = m[i] + dense_g[i] ** 2
        prox = p[i] - 0.1 * dense_g[i] / np.sqrt(m_new[i])
        want[i] = _soft(prox, 0.1, 0.1, 0.05)
    np.testing.assert_allclose(np.asarray(out["ParamOut"]), want, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out["MomentOut"]), m_new, atol=1e-5)
    # untouched rows identical
    untouched = [i for i in range(vocab) if i not in ids]
    np.testing.assert_array_equal(
        np.asarray(out["ParamOut"])[untouched], p[untouched])


def test_proximal_optimizers_end_to_end(r):
    """Both optimizers minimize a separable toy problem; L1 drives some
    weights exactly to zero (the point of the proximal step)."""
    for make in (lambda: fluid.optimizer.ProximalGD(
                     0.5, l1_regularization_strength=0.01),
                 lambda: fluid.optimizer.ProximalAdagrad(
                     0.5, l1_regularization_strength=0.01)):
        with fluid.unique_name.guard(), fluid.scope_guard(fluid.core.Scope()):
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("x", shape=[8])
                y = fluid.layers.data("y", shape=[1])
                pred = fluid.layers.fc(x, size=1)
                loss = fluid.layers.mean(fluid.layers.square(pred - y))
                make().minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            xs = r.randn(64, 8).astype("float32")
            # only the first feature matters -> L1 should zero the rest
            ys = (2.0 * xs[:, :1]).astype("float32")
            losses = [float(exe.run(main, feed={"x": xs, "y": ys},
                                    fetch_list=[loss])[0]) for _ in range(40)]
            assert losses[-1] < losses[0] * 0.2, losses


def _pnpair_oracle(score, label, query, column=-1, weight=None):
    """Ported from the reference's own oracle
    (tests/unittests/test_positive_negative_pair_op.py:24)."""
    predictions = {}
    n = label.shape[0]
    if weight is None:
        weight = np.ones((n, 1), "float32")
    for s, l, q, w in zip(score, label, query, weight):
        predictions.setdefault(q[0], []).append((s[column], l[0], w[0]))
    pos = neg = neu = 0.0
    for ranks in predictions.values():
        for e1, e2 in itertools.combinations(ranks, 2):
            (s1, l1, w1), (s2, l2, w2) = e1, e2
            if l1 == l2:
                continue
            w = (w1 + w2) * 0.5
            if s1 == s2:
                neu += w
            elif (s1 - s2) * (l1 - l2) > 0:
                pos += w
            else:
                neg += w
    return pos, neg, neu


def test_positive_negative_pair(r):
    n, width, n_query = 24, 3, 4
    score = r.rand(n, width).astype("float32")
    label = r.randint(0, 3, (n, 1)).astype("float32")
    query = np.asarray([[i % n_query] for i in range(n)], "int64")
    pos, neg, neu = _pnpair_oracle(score, label, query)
    out = run_op("positive_negative_pair",
                 {"Score": score, "Label": label, "QueryID": query},
                 ["PositivePair", "NegativePair", "NeutralPair"])
    np.testing.assert_allclose(np.asarray(out["PositivePair"]), [pos], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["NegativePair"]), [neg], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["NeutralPair"]), [neu], atol=1e-6)


def test_positive_negative_pair_weighted_ties_accum(r):
    n = 12
    score = np.round(r.rand(n, 2), 1).astype("float32")  # force some ties
    label = r.randint(0, 2, (n, 1)).astype("float32")
    query = r.randint(0, 3, (n, 1)).astype("int64")
    weight = r.rand(n, 1).astype("float32")
    pos, neg, neu = _pnpair_oracle(score, label, query, column=0,
                                   weight=weight)
    acc = np.array([1.5], "float32")
    out = run_op("positive_negative_pair",
                 {"Score": score, "Label": label, "QueryID": query,
                  "Weight": weight,
                  "AccumulatePositivePair": acc,
                  "AccumulateNegativePair": acc,
                  "AccumulateNeutralPair": acc},
                 ["PositivePair", "NegativePair", "NeutralPair"],
                 attrs={"column": 0})
    np.testing.assert_allclose(np.asarray(out["PositivePair"]), [pos + 1.5], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["NegativePair"]), [neg + 1.5], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["NeutralPair"]), [neu + 1.5], rtol=1e-5)
