"""The v0 gate (SURVEY.md §7 stage 2): MNIST-style MLP trains to convergence.

Mirrors the reference's book test
(python/paddle/fluid/tests/book/test_recognize_digits.py) with synthetic
separable data standing in for MNIST downloads (zero egress).
"""

import numpy as np

import paddle_tpu as fluid


def _synthetic_mnist(rng, n=512, dim=64, classes=10):
    """Linearly-separable clusters — a convergence smoke without downloads."""
    centers = rng.randn(classes, dim).astype("float32") * 3.0
    ys = rng.randint(0, classes, size=n)
    xs = centers[ys] + rng.randn(n, dim).astype("float32") * 0.5
    return xs.astype("float32"), ys.reshape(n, 1).astype("int64")


def _build_mlp(dim=64, classes=10):
    img = fluid.layers.data("img", shape=[dim])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(img, size=128, act="relu")
    h = fluid.layers.fc(h, size=64, act="relu")
    logits = fluid.layers.fc(h, size=classes)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label)
    )
    acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
    return img, label, loss, acc


def test_mnist_mlp_converges(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img, label, loss, acc = _build_mlp()
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

    xs, ys = _synthetic_mnist(rng)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    batch = 64
    first_loss = None
    last_loss = last_acc = None
    for epoch in range(6):
        for i in range(0, len(xs), batch):
            feed = {"img": xs[i : i + batch], "label": ys[i : i + batch]}
            last_loss, last_acc = exe.run(main, feed=feed, fetch_list=[loss, acc])
            if first_loss is None:
                first_loss = float(last_loss)
    assert float(last_loss) < 0.25, f"did not converge: {first_loss} -> {float(last_loss)}"
    assert float(last_acc) > 0.9
    assert float(first_loss) > float(last_loss)


def test_mnist_mlp_sgd_and_momentum(rng):
    for make_opt in (
        lambda: fluid.optimizer.SGD(learning_rate=0.1),
        lambda: fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9),
    ):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                img, label, loss, acc = _build_mlp()
                make_opt().minimize(loss)
        xs, ys = _synthetic_mnist(rng, n=256)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = []
        for epoch in range(4):
            for i in range(0, len(xs), 64):
                feed = {"img": xs[i : i + 64], "label": ys[i : i + 64]}
                (l,) = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(l))
        assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]


def test_inference_clone_matches_train_forward(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(img, size=16, act="relu")
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, label))
        test_prog = main.clone(for_test=True)
        fluid.optimizer.SGD(0.0).minimize(loss)  # lr=0 → params frozen

    xs = rng.randn(16, 8).astype("float32")
    ys = rng.randint(0, 4, size=(16, 1)).astype("int64")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    train_logits, = exe.run(main, feed={"img": xs, "label": ys}, fetch_list=[logits])
    infer_logits, = exe.run(test_prog, feed={"img": xs, "label": ys}, fetch_list=[logits])
    np.testing.assert_allclose(train_logits, infer_logits, rtol=1e-5, atol=1e-5)
