"""Serving subsystem tests: scheduler churn invariants, paged-vs-contiguous
KV-cache bit parity, ragged-vs-padded logit parity, page-pool backpressure
and flight-recorder capture (ISSUE 6 tentpole coverage)."""

import json
import os

import numpy as np
import pytest

from paddle_tpu import serving
from paddle_tpu.models import decoder_lm
from paddle_tpu.serving.page_pool import PagePoolExhausted
from paddle_tpu.serving.request import Request

_MODEL = None


def get_model():
    """One tiny decoder shared across tests (init cost, not compile cost —
    each engine still AOT-compiles its own step functions)."""
    global _MODEL
    if _MODEL is None:
        cfg = decoder_lm.DecoderConfig(vocab_size=64, n_layer=2, d_model=32,
                                       n_head=2, max_seq=64)
        _MODEL = decoder_lm.DecoderLM(cfg, seed=0)
    return _MODEL


def make_stream(n, rng, max_prompt=16, max_new=8, vocab=64):
    return [(list(rng.randint(0, vocab, int(rng.randint(3, max_prompt + 1)))),
             int(rng.randint(2, max_new + 1))) for _ in range(n)]


def small_config(**kw):
    kw.setdefault("slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_seq", 64)
    kw.setdefault("prompt_buckets", (16,))
    return serving.ServingConfig(**kw)


# -- scheduler ---------------------------------------------------------------

def test_scheduler_admit_retire_invariants_under_churn(rng):
    sched = serving.Scheduler(n_slots=3, max_queue=100)
    submitted, running, finished = [], {}, []
    for step in range(200):
        op = rng.randint(0, 3)
        if op == 0:
            r = sched.submit(Request([1, 2], max_new_tokens=2))
            submitted.append(r)
        elif op == 1 and sched.peek() is not None and sched.admissible_slots():
            slot = sched.admissible_slots()[rng.randint(
                0, len(sched.admissible_slots()))]
            r = sched.admit(slot)
            # FIFO: the admitted request is the oldest not-yet-started one
            expect = next(q for q in submitted
                          if q not in running.values() and q not in finished)
            assert r is expect, "admission broke FIFO order"
            assert r.slot == slot and r.state == "running"
            running[slot] = r
        elif op == 2 and running:
            slot = list(running)[rng.randint(0, len(running))]
            r = sched.retire(slot)
            assert r is running.pop(slot)
            assert r.state == "finished" and r.slot is None
            finished.append(r)
        # core invariants, every step
        assert sched.occupancy == len(running)
        assert sched.queue_depth == len(submitted) - len(running) - len(finished)
        assert {r.slot for r in sched.running()} == set(running)
    # every request is in exactly one place
    assert len(submitted) == sched.queue_depth + len(running) + len(finished)


def test_scheduler_bounded_queue_and_slot_errors():
    sched = serving.Scheduler(n_slots=1, max_queue=2)
    sched.submit(Request([1], 1))
    sched.submit(Request([1], 1))
    with pytest.raises(serving.BackpressureError):
        sched.submit(Request([1], 1))
    sched.admit(0)
    with pytest.raises(ValueError):
        sched.admit(0)  # double occupancy
    sched.retire(0)
    with pytest.raises(ValueError):
        sched.retire(0)  # empty slot


def test_scheduler_static_mode_admits_only_full_drain():
    sched = serving.Scheduler(n_slots=2, continuous=False)
    for _ in range(3):
        sched.submit(Request([1], 1))
    assert sched.admissible_slots() == [0, 1]
    sched.admit(0)
    # one slot busy -> static policy refuses the other
    assert sched.admissible_slots() == []
    sched.retire(0)
    assert sched.admissible_slots() == [0, 1]


# -- page pool ---------------------------------------------------------------

def test_page_pool_accounting_and_atomic_exhaustion():
    pool = serving.PagePool(num_pages=8, page_size=16)
    assert pool.pages_needed(1) == 1 and pool.pages_needed(16) == 1
    assert pool.pages_needed(17) == 2
    a = pool.alloc(5)
    assert pool.num_used == 5 and abs(pool.utilization - 5 / 8) < 1e-9
    with pytest.raises(PagePoolExhausted):
        pool.alloc(4)  # atomic: nothing taken
    assert pool.num_free == 3
    assert isinstance(PagePoolExhausted("x"), serving.BackpressureError)
    pool.free(a)
    assert pool.num_used == 0
    with pytest.raises(ValueError):
        pool.free([a[0]])  # double free
    b = pool.alloc(8)
    assert sorted(b) == list(range(8))


# -- decode parity -----------------------------------------------------------

def drive_stream(stream, **cfg_kw):
    eng = serving.ServingEngine(get_model(), small_config(**cfg_kw))
    reqs = [eng.submit(p, m) for p, m in stream]
    done = eng.run()
    assert len(done) == len(reqs)
    return eng, reqs


def test_paged_vs_contiguous_bit_parity(rng):
    """The paged gather decode must be BIT-identical to the contiguous
    reference cache on the same request stream — tokens and logits."""
    stream = make_stream(8, rng)
    e1, r1 = drive_stream(stream, paged=True, collect_logits=True)
    e2, r2 = drive_stream(stream, paged=False, collect_logits=True)
    for a, b in zip(r1, r2):
        assert a.tokens_out == b.tokens_out
        la, lb = e1.captured_logits(a), e2.captured_logits(b)
        assert len(la) == len(lb) == len(a.tokens_out)
        for x, y in zip(la, lb):
            assert np.array_equal(x, y), "paged logits diverged bitwise"


def test_ragged_vs_padded_full_recompute_logit_parity(rng):
    """Bucket-padded prefill + incremental paged decode at mixed lengths
    must match the O(S^2) full-recompute reference on the unpadded
    prompt: same greedy tokens, logits to float tolerance."""
    model = get_model()
    stream = make_stream(4, rng)
    eng, reqs = drive_stream(stream, paged=True, collect_logits=True)
    for req in reqs:
        toks, logits = decoder_lm.reference_decode(
            model.params, model.cfg, req.prompt, req.max_new_tokens)
        assert req.tokens_out == toks
        for got, want in zip(eng.captured_logits(req), logits):
            np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_decode_fuse_token_parity(rng):
    """Fusing k decode steps into one dispatched scan (the run_steps
    analog) must not change any emitted token."""
    stream = make_stream(6, rng)
    _, r1 = drive_stream(stream, decode_fuse=1)
    _, r4 = drive_stream(stream, decode_fuse=4)
    for a, b in zip(r1, r4):
        assert a.tokens_out == b.tokens_out


def test_static_wave_mode_drains(rng):
    stream = make_stream(6, rng)
    _, reqs = drive_stream(stream, paged=False, continuous=False)
    assert all(r.state == "finished" for r in reqs)
    assert all(len(r.tokens_out) == r.max_new_tokens for r in reqs)


# -- backpressure + observability --------------------------------------------

def test_pool_exhaustion_queues_not_crashes(rng, monkeypatch, tmp_path):
    """An undersized page pool must degrade to queueing (admission
    backpressure) and still drain; the flight recorder captures the
    pressure event with the in-flight batch."""
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
    from paddle_tpu.monitor import device as _dev, metrics as mx

    blocked0 = mx.snapshot()["serving/admission_blocked_on_pages"]["value"]
    # 4 slots but pages for only ~1.5 in-flight worst-case requests
    eng = serving.ServingEngine(get_model(), small_config(num_pages=3))
    reqs = [eng.submit(list(rng.randint(0, 64, 12)), 8) for _ in range(4)]
    saw_queued_while_running = False
    guard = 0
    while not eng.scheduler.idle():
        eng.step()
        if eng.scheduler.queue_depth and eng.scheduler.occupancy:
            saw_queued_while_running = True
        guard += 1
        assert guard < 200, "engine failed to drain under page pressure"
    assert all(r.state == "finished" for r in reqs)
    assert all(len(r.tokens_out) == r.max_new_tokens for r in reqs)
    assert saw_queued_while_running, "pool never actually backpressured"
    assert mx.snapshot()["serving/admission_blocked_on_pages"]["value"] \
        > blocked0
    assert eng.pool.num_used == 0
    fr = _dev.flight_recorder()
    events = [e for e in fr._entries
              if e.get("event") == "serving_admission_blocked"]
    assert events, "flight recorder missed the backpressure event"
    assert "batch" in events[-1] and events[-1]["need_pages"] > 0


def test_flight_recorder_captures_batch_on_decode_failure(
        rng, monkeypatch, tmp_path):
    """A decode failure is flight-dumped AND absorbed (ISSUE 7): the batch
    is FAILED with pages reclaimed, and the engine survives — fail_fast
    restores the old raise-through behavior for debugging."""
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
    eng = serving.ServingEngine(get_model(), small_config())
    req = eng.submit(list(rng.randint(0, 64, 8)), 4)

    def boom(*a, **kw):
        raise RuntimeError("injected decode failure")

    eng._decode_exe[eng.cfg.decode_fuse] = boom
    done = eng.step()  # absorbed, not raised
    assert [r.id for r in done] == [req.id] and req.state == "failed"
    assert req.error and not req.pages and eng.pool.num_used == 0
    assert eng.health()["status"] == "degraded"
    dumps = [f for f in os.listdir(str(tmp_path)) if f.startswith("flight_")]
    assert dumps, "no flight dump written"
    with open(os.path.join(str(tmp_path), sorted(dumps)[-1])) as f:
        doc = json.load(f)
    assert doc["reason"] == "serving.decode"
    batches = [e for e in doc["entries"]
               if e.get("event") == "serving_inflight_batch"]
    assert batches, "dump missing the in-flight batch spec"
    spec = batches[-1]
    assert spec["slots"] and spec["slots"][0]["prompt_len"] == 8
    assert spec["layout"] == "paged"

    # fail_fast: the old contract, raise through after the dump
    eng2 = serving.ServingEngine(get_model(), small_config(fail_fast=True))
    eng2.submit(list(rng.randint(0, 64, 8)), 4)
    eng2._decode_exe[eng2.cfg.decode_fuse] = boom
    with pytest.raises(RuntimeError, match="injected decode failure"):
        eng2.step()


def test_submit_validation_and_immediate_finish(rng):
    eng = serving.ServingEngine(get_model(), small_config())
    with pytest.raises(ValueError):
        eng.submit(list(range(17)), 4)       # beyond largest bucket
    with pytest.raises(ValueError):
        eng.submit([1, 2, 3], 62)            # prompt+max_new > max_seq
    # max_new_tokens=1 finishes at prefill without touching a decode slot
    req = eng.submit(list(rng.randint(0, 64, 8)), 1)
    done = eng.run()
    assert [r.id for r in done] == [req.id]
    assert len(req.tokens_out) == 1 and req.state == "finished"
    assert eng.scheduler.idle() and eng.pool.num_used == 0


def test_eos_stops_generation(rng):
    """With eos_id set to the model's (fixed-point) greedy token, requests
    stop at the first emission instead of running out max_new_tokens."""
    model = get_model()
    prompt = list(rng.randint(0, 64, 8))
    toks, _ = decoder_lm.reference_decode(model.params, model.cfg, prompt, 1)
    eng = serving.ServingEngine(model, small_config(eos_id=toks[0]))
    req = eng.submit(prompt, 8)
    eng.run()
    assert req.state == "finished"
    assert len(req.tokens_out) == 1 and req.tokens_out[0] == toks[0]


def test_graceful_drain_finishes_inflight_rejects_new(rng):
    """ISSUE 10 satellite: drain stops admitting (typed DrainingError on
    submit, queued requests shed REJECTED), finishes the in-flight
    requests, reclaims every page and closes the engine."""
    eng = serving.ServingEngine(get_model(), small_config(slots=2))
    reqs = [eng.submit(list(rng.randint(0, 64, 8)), 4) for _ in range(4)]
    eng.step()  # admit two into slots, two remain queued
    assert eng.scheduler.occupancy == 2 and eng.scheduler.queue_depth == 2
    eng.request_drain()
    with pytest.raises(serving.DrainingError):
        eng.submit([1, 2, 3], 2)
    summary = eng.drain(timeout_s=30.0)
    assert summary == {"finished": 2, "timed_out": 0, "failed": 0,
                       "rejected": 2}, summary
    states = sorted(r.state for r in reqs)
    assert states == ["finished", "finished", "rejected", "rejected"]
    assert eng.pool.num_used == 0 and eng.page_accounting_ok()
    assert eng._closed and eng.last_drain == summary
    # rejected requests never held slots or pages
    for r in reqs:
        if r.state == "rejected":
            assert not r.pages and r.slot is None


def test_drain_timeout_cuts_stragglers_loose(rng):
    """A drain past its budget retires the stragglers TIMEOUT — pages come
    back, the engine still closes (never hangs a rollout)."""
    eng = serving.ServingEngine(get_model(), small_config(slots=2))
    r1 = eng.submit(list(rng.randint(0, 64, 8)), 8)
    eng.step()
    assert r1.state == "running"
    summary = eng.drain(timeout_s=0.0)  # budget already spent
    assert summary["timed_out"] == 1 and r1.state == "timeout"
    assert not r1.pages and eng.pool.num_used == 0
    assert eng._closed


def test_drain_interrupts_run_loop(rng):
    """request_drain mid-run (the SIGTERM handler's path): the drive loop
    flips into drain at the next cycle instead of tearing down."""
    eng = serving.ServingEngine(get_model(), small_config(slots=2))
    reqs = [eng.submit(list(rng.randint(0, 64, 8)), 6) for _ in range(2)]
    eng.step()
    eng.request_drain()
    eng.run(max_steps=100)
    assert eng.last_drain is not None and eng.last_drain["finished"] == 2
    assert all(r.state == "finished" for r in reqs)
    assert eng._closed


def test_drain_is_idempotent(rng):
    """Double drain: the second call returns the recorded summary without
    re-running the shed/step loop (fleet respawn paths drain replicas
    that may already have drained themselves)."""
    eng = serving.ServingEngine(get_model(), small_config(slots=2))
    reqs = [eng.submit(list(rng.randint(0, 64, 8)), 4) for _ in range(2)]
    eng.step()  # admit into slots so drain FINISHES them (not shed)
    s1 = eng.drain(timeout_s=10.0)
    assert all(r.state == "finished" for r in reqs)
    s2 = eng.drain(timeout_s=10.0)
    assert s2 is s1, "a second drain re-ran instead of replaying"
    assert eng.last_drain is s1 and eng._closed
    assert eng.pool.num_used == 0


def test_drain_is_reentrant(rng):
    """A nested drain (signal handler / monitor thread firing while the
    drain decode loop runs) returns an in-progress snapshot instead of
    re-entering — and must NOT be recorded as the final summary."""
    eng = serving.ServingEngine(get_model(), small_config(slots=2))
    [eng.submit(list(rng.randint(0, 64, 8)), 4) for _ in range(2)]
    eng.step()  # admit into slots so the drain loop has work to step
    nested = []
    real_step = eng.step

    def step_and_reenter():
        nested.append(eng.drain())
        return real_step()

    eng.step = step_and_reenter
    summary = eng.drain(timeout_s=10.0)
    assert nested, "drain loop never stepped"
    for snap in nested:
        assert snap is not summary, "nested drain leaked the live summary"
        assert snap.get("finished", 0) <= summary["finished"]
    assert eng.last_drain is summary and summary["finished"] == 2


def test_close_is_idempotent_and_drain_after_close(rng):
    eng = serving.ServingEngine(get_model(), small_config(slots=2))
    r = eng.submit(list(rng.randint(0, 64, 8)), 3)
    eng.run()
    assert r.state == "finished"
    eng.close()
    eng.close()  # second close: no-op, no error
    assert eng._closed
    # drain on a closed-but-never-drained engine still produces a summary
    # exactly once (nothing in flight: all zeros) and stays idempotent
    s1 = eng.drain(timeout_s=1.0)
    assert s1["finished"] == 0 and s1["rejected"] == 0
    assert eng.drain() is s1
