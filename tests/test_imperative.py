"""Dygraph (imperative) mode tests.

Mirrors the reference's imperative suite
(python/paddle/fluid/tests/unittests/test_imperative_basic.py,
test_imperative_optimizer.py): Layer/parameter mechanics, eager autograd,
optimizer parity with static mode, and an MNIST-style MLP trained to
convergence in dygraph.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import imperative
from paddle_tpu.imperative import F, to_variable


def _synthetic(rng, n=256, dim=32, classes=10):
    centers = rng.randn(classes, dim).astype("float32") * 3.0
    ys = rng.randint(0, classes, size=n)
    xs = centers[ys] + rng.randn(n, dim).astype("float32") * 0.5
    return xs.astype("float32"), ys.reshape(n, 1).astype("int64")


class MLP(imperative.Layer):
    def __init__(self, name_scope, dim=32, classes=10):
        super().__init__(name_scope)
        self._fc1 = imperative.FC(self.full_name(), 64, act="relu")
        self._fc2 = imperative.FC(self.full_name(), classes)

    def forward(self, x):
        return self._fc2(self._fc1(x))


def test_to_variable_roundtrip_and_guard():
    assert not imperative.enabled()
    with imperative.guard():
        assert imperative.enabled()
        x = to_variable(np.arange(6, dtype="float32").reshape(2, 3))
        assert x.shape == (2, 3)
        assert x.dtype == "float32"
        np.testing.assert_array_equal(x.numpy(), np.arange(6).reshape(2, 3))
    assert not imperative.enabled()
    with pytest.raises(RuntimeError):
        to_variable(np.zeros(3))


def test_eager_autograd_matches_analytic():
    with imperative.guard():
        x = to_variable(np.array([[1.0, 2.0], [3.0, 4.0]], dtype="float32"))
        y = x * x + 2.0 * x  # dy/dx = 2x + 2
        loss = F.mean(y)
        loss._backward()
        expect = (2.0 * x.numpy() + 2.0) / x.numpy().size
        np.testing.assert_allclose(x.gradient(), expect, rtol=1e-6)


def test_grad_accumulates_across_uses():
    with imperative.guard():
        x = to_variable(np.ones((3,), dtype="float32"))
        y = x * 3.0
        z = x * 5.0
        loss = F.reduce_sum(y + z)
        loss.backward()
        np.testing.assert_allclose(x.gradient(), np.full(3, 8.0), rtol=1e-6)


def test_stop_gradient_blocks_flow():
    with imperative.guard():
        x = to_variable(np.ones((3,), dtype="float32"))
        w = to_variable(np.ones((3,), dtype="float32"))
        w.stop_gradient = True
        loss = F.reduce_sum(x * w)
        loss.backward()
        assert x.gradient() is not None
        assert w.gradient() is None


def test_layer_parameter_registry():
    with imperative.guard():
        mlp = MLP("mlp")
        mlp(to_variable(np.zeros((4, 32), dtype="float32")))  # builds lazy FCs
        params = mlp.parameters()
        assert len(params) == 4  # 2 FCs × (w, b)
        assert len(mlp.sublayers()) == 2
        assert all(p.persistable for p in params)
        # clear_gradients wipes accumulated grads
        loss = F.mean(mlp(to_variable(np.ones((4, 32), dtype="float32"))))
        loss.backward()
        assert any(p.gradient() is not None for p in params)
        mlp.clear_gradients()
        assert all(p.gradient() is None for p in params)


def test_pylayer_custom_op():
    class Square(imperative.PyLayer):
        @staticmethod
        def forward(x):
            return x * x

    with imperative.guard():
        x = to_variable(np.array([2.0, 3.0], dtype="float32"))
        y = Square.apply(x)
        F.reduce_sum(y).backward()
        np.testing.assert_allclose(x.gradient(), [4.0, 6.0], rtol=1e-6)


def test_imperative_mnist_mlp_converges(rng):
    xs, ys = _synthetic(rng)
    with imperative.guard(seed=7):
        mlp = MLP("mlp")
        opt = fluid.optimizer.Adam(learning_rate=1e-2)
        batch = 64
        first = last = None
        for epoch in range(4):
            for i in range(0, len(xs), batch):
                img = to_variable(xs[i:i + batch])
                label = to_variable(ys[i:i + batch])
                label.stop_gradient = True
                loss = F.mean(F.softmax_with_cross_entropy(mlp(img), label))
                loss._backward()
                opt.minimize(loss)
                mlp.clear_gradients()
                if first is None:
                    first = float(loss.numpy())
                last = float(loss.numpy())
    assert last < 0.3, f"dygraph MLP did not converge: {first} -> {last}"
    assert last < first


def test_imperative_sgd_matches_static(rng):
    """One SGD step on identical weights/grads must match static mode."""
    dim, classes = 8, 3
    xs = rng.randn(16, dim).astype("float32")
    ys = rng.randint(0, classes, size=(16, 1)).astype("int64")
    w0 = rng.randn(dim, classes).astype("float32") * 0.1
    b0 = np.zeros(classes, dtype="float32")

    # -- static
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[dim])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        logits = fluid.layers.fc(
            img, size=classes,
            param_attr=fluid.ParamAttr(
                name="w", initializer=fluid.initializer.NumpyArrayInitializer(w0)),
            bias_attr=fluid.ParamAttr(
                name="b", initializer=fluid.initializer.NumpyArrayInitializer(b0)))
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    static_loss, = exe.run(main, feed={"img": xs, "label": ys}, fetch_list=[loss])
    static_w = fluid.global_scope().as_numpy("w")

    # -- imperative
    with imperative.guard():
        fc = imperative.FC("fc", classes)
        fc(to_variable(xs))  # build
        fc.weight.value = jnp.asarray(w0)
        fc.bias.value = jnp.asarray(b0)
        label = to_variable(ys)
        label.stop_gradient = True
        dloss = F.mean(F.softmax_with_cross_entropy(fc(to_variable(xs)), label))
        dloss._backward()
        fluid.optimizer.SGD(learning_rate=0.1).minimize(dloss)
        np.testing.assert_allclose(float(dloss.numpy()), float(static_loss), rtol=1e-5)
        np.testing.assert_allclose(fc.weight.numpy(), static_w, rtol=1e-5, atol=1e-6)


def test_imperative_conv_pool_bn_smoke(rng):
    x = rng.randn(2, 3, 16, 16).astype("float32")
    with imperative.guard():
        conv = imperative.Conv2D("conv", num_channels=3, num_filters=8,
                                 filter_size=3, padding=1, act="relu")
        pool = imperative.Pool2D("pool", pool_size=2, pool_stride=2)
        bn = imperative.BatchNorm("bn", num_channels=8)
        emb = imperative.Embedding("emb", size=[50, 6])

        out = pool(conv(to_variable(x)))
        assert out.shape == (2, 8, 8, 8)
        mean_before = bn._mean.numpy().copy()
        out = bn(out)
        assert not np.allclose(bn._mean.numpy(), mean_before), "BN stats must update"
        ids = to_variable(rng.randint(0, 50, size=(4, 7)).astype("int64"))
        e = emb(ids)
        assert e.shape == (4, 7, 6)
        loss = F.mean(out) + F.mean(e)
        loss.backward()
        assert conv.weight.gradient() is not None
        assert emb.weight.gradient() is not None


def test_double_backward_does_not_compound():
    """Repeated backward accumulates into leaves linearly, never compounds
    through stale intermediate cotangents."""
    with imperative.guard():
        x = to_variable(np.ones((3,), dtype="float32"))
        loss = F.reduce_sum((x * 2.0) * 3.0)
        loss.backward()
        np.testing.assert_allclose(x.gradient(), np.full(3, 6.0))
        loss.backward()
        np.testing.assert_allclose(x.gradient(), np.full(3, 12.0))


def test_adamw_decays_lamb_differs():
    """AdamW's weight decay must actually apply in dygraph (not degrade to
    Adam), and Lamb must take its own path."""
    w0 = np.full((4, 4), 2.0, dtype="float32")

    def one_step(make_opt):
        with imperative.guard():
            fc = imperative.FC("fc", 4, bias_attr=False)
            fc(to_variable(np.ones((2, 4), dtype="float32")))
            fc.weight.value = jnp.asarray(w0)
            loss = F.mean(fc(to_variable(np.ones((2, 4), dtype="float32"))))
            loss.backward()
            make_opt().minimize(loss)
            return fc.weight.numpy()

    adam = one_step(lambda: fluid.optimizer.Adam(learning_rate=0.1))
    adamw = one_step(lambda: fluid.optimizer.AdamW(learning_rate=0.1, weight_decay=0.5))
    lamb = one_step(lambda: fluid.optimizer.Lamb(learning_rate=0.1))
    assert not np.allclose(adam, adamw), "AdamW must differ from Adam (weight decay)"
    assert not np.allclose(adam, lamb), "Lamb must differ from Adam (trust ratio)"
    assert adamw.mean() < adam.mean(), "decay must pull weights toward zero"


def test_optimizer_cannot_switch_modes(rng):
    with imperative.guard():
        x = to_variable(np.ones((2, 4), dtype="float32"))
        fc = imperative.FC("fc", 2)
        loss = F.mean(fc(x))
        loss.backward()
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[4])
        static_loss = fluid.layers.mean(fluid.layers.fc(img, size=2))
        with pytest.raises(RuntimeError, match="imperative"):
            opt.minimize(static_loss)


def test_bn_stats_are_not_parameters():
    with imperative.guard():
        bn = imperative.BatchNorm("bn", num_channels=4)
        names = sorted(p.name for p in bn.parameters())
        assert len(names) == 2, f"BN must expose scale+bias only, got {names}"


def test_bn_eval_mode_uses_running_stats(rng):
    """Layer.eval() must switch BN to running stats and freeze them."""
    x = rng.randn(8, 4, 6, 6).astype("float32") * 3 + 1
    with imperative.guard():
        bn = imperative.BatchNorm("bn", num_channels=4)
        bn(to_variable(x))  # one train step seeds running stats
        mean_after_train = bn._mean.numpy().copy()
        bn.eval()
        out = bn(to_variable(x))
        np.testing.assert_array_equal(bn._mean.numpy(), mean_after_train)
        # eval output must use running stats, not batch stats (batch stats
        # would give per-channel mean ~0)
        ch_mean = np.abs(out.numpy().mean(axis=(0, 2, 3))).max()
        assert ch_mean > 0.05, "eval-mode BN normalized with batch statistics"
        bn.train()
        bn(to_variable(x))
        assert not np.allclose(bn._mean.numpy(), mean_after_train)


def test_embedding_negative_padding_idx_masks_grad(rng):
    with imperative.guard():
        emb = imperative.Embedding("emb", size=[10, 3], padding_idx=-1)
        ids = to_variable(np.array([[9, 1]], dtype="int64"))
        loss = F.mean(emb(ids))
        loss.backward()
        g = emb.weight.gradient()
        np.testing.assert_array_equal(g[9], np.zeros(3, "float32"))
        assert np.abs(g[1]).sum() > 0


def test_imperative_adam_state_persists(rng):
    """Accumulators (moments) must persist across minimize calls."""
    with imperative.guard():
        x = to_variable(np.ones((4, 8), dtype="float32"))
        fc = imperative.FC("fc", 4)
        opt = fluid.optimizer.Adam(learning_rate=1e-2)
        losses = []
        for _ in range(3):
            loss = F.mean(F.square(fc(x)))
            loss.backward()
            opt.minimize(loss)
            fc.clear_gradients()
            losses.append(float(loss.numpy()))
        accs = opt._accumulators["moment1"]
        assert len(accs) == 2  # w and b
        assert losses[-1] < losses[0]


def test_save_load_dygraph_roundtrip(rng, tmp_path):
    from paddle_tpu.imperative import load_dygraph, save_dygraph

    path = str(tmp_path / "model")
    x = np.ones((2, 32), dtype="float32")
    with imperative.guard():
        m1 = MLP("mlp")
        m1(to_variable(x))
        out1 = m1(to_variable(x)).numpy()
        save_dygraph(m1, path)
    with imperative.guard():
        m2 = MLP("mlp")
        m2(to_variable(x))  # build (different random init)
        # unique_name.guard() resets per imperative.guard(), so names match
        m2.set_state(load_dygraph(path))
        out2 = m2(to_variable(x)).numpy()
    np.testing.assert_allclose(out1, out2, rtol=1e-6)
    # strict mode flags shape mismatches loudly
    with imperative.guard():
        m3 = MLP("mlp", dim=16)
        m3(to_variable(np.ones((2, 16), dtype="float32")))
        with pytest.raises((ValueError, KeyError)):
            m3.set_state(load_dygraph(path))


def test_imperative_jit_parity_and_speedup():
    """VERDICT r3 #8: imperative.jit compiles a dygraph Layer's forward to
    one XLA executable — numerics identical to eager, and the per-op
    interpretation tax (>=10x on a small MLP loop) is gone."""
    import time

    import paddle_tpu.imperative as imp

    with imp.guard(seed=3):
        class MLP(imp.Layer):
            def __init__(self):
                super().__init__("mlp")
                self.fc1 = imp.FC("fc1", 64, act="relu")
                self.fc2 = imp.FC("fc2", 64, act="relu")
                self.fc3 = imp.FC("fc3", 8)

            def forward(self, x):
                return self.fc3(self.fc2(self.fc1(x)))

        mlp = MLP()
        x = imp.to_variable(np.random.RandomState(0).randn(16, 32).astype("float32"))
        want = mlp(x).numpy()

        fast = imp.jit(mlp)
        got = fast(x)
        np.testing.assert_allclose(got.numpy(), want, rtol=1e-5, atol=1e-6)

        # param updates flow without retracing
        p0 = mlp.parameters()[0]
        p0.value = p0.value + 1.0
        np.testing.assert_allclose(fast(x).numpy(), mlp(x).numpy(),
                                   rtol=1e-5, atol=1e-5)

        n = 30
        jnp_ready = fast(x).numpy()  # warm cache

        t0 = time.perf_counter()
        for _ in range(n):
            out = mlp(x)
        out.numpy()
        t_eager = time.perf_counter() - t0

        t0 = time.perf_counter()
        for _ in range(n):
            out = fast(x)
        out.numpy()
        t_jit = time.perf_counter() - t0
        # >=3x not >=10x: wall-clock ratios are flaky on loaded CI hosts
        # (ADVICE r4); the honest TPU number (47x) is recorded in README
        assert t_eager / t_jit >= 3, (
            "jit speedup only %.1fx (eager %.1fms vs jit %.1fms)"
            % (t_eager / t_jit, t_eager * 1e3, t_jit * 1e3))


def test_jit_train_loss_parity_with_eager(rng):
    """jit_train's compiled step must follow the same loss trajectory as the
    plain eager train loop (same seed, same data, same optimizer)."""
    xs, ys = _synthetic(rng, n=128)

    def train(compiled, n_steps=8):
        with imperative.guard(seed=11):
            mlp = MLP("mlp")
            opt = fluid.optimizer.Adam(learning_rate=1e-2)

            def loss_fn(img, lbl):
                return F.mean(F.softmax_with_cross_entropy(mlp(img), lbl))

            losses = []
            if compiled:
                step = imperative.jit_train(loss_fn, mlp, opt)
                for i in range(n_steps):
                    losses.append(float(step(xs, ys).numpy()))
            else:
                for i in range(n_steps):
                    img, lbl = to_variable(xs), to_variable(ys)
                    lbl.stop_gradient = True
                    loss = loss_fn(img, lbl)
                    loss._backward()
                    opt.minimize(loss)
                    mlp.clear_gradients()
                    losses.append(float(loss.numpy()))
            return losses

    eager = train(False)
    jitted = train(True)
    assert jitted[-1] < jitted[0], "jit_train did not reduce the loss"
    # identical math (the model has no dropout, so RNG derivation aside the
    # trajectories must agree to float tolerance)
    np.testing.assert_allclose(eager, jitted, rtol=2e-4, atol=2e-5)


def test_jit_train_speedup_and_param_update(rng):
    import time

    xs, ys = _synthetic(rng, n=64)
    with imperative.guard(seed=3):
        mlp = MLP("mlp")
        opt = fluid.optimizer.SGD(learning_rate=0.1)

        def loss_fn(img, lbl):
            return F.mean(F.softmax_with_cross_entropy(mlp(img), lbl))

        step = imperative.jit_train(loss_fn, mlp, opt)
        step(xs, ys)   # eager warmup step
        w_before = np.array(mlp._fc1.parameters()[0].numpy())
        step(xs, ys)   # compiled
        w_after = np.array(mlp._fc1.parameters()[0].numpy())
        assert not np.allclose(w_before, w_after), "params not updated"

        n = 30
        t0 = time.perf_counter()
        for _ in range(n):
            out = step(xs, ys)
        out.numpy()
        t_jit = time.perf_counter() - t0

        t0 = time.perf_counter()
        for _ in range(n):
            img, lbl = to_variable(xs), to_variable(ys)
            lbl.stop_gradient = True
            loss = loss_fn(img, lbl)
            loss._backward()
            opt.minimize(loss)
            mlp.clear_gradients()
        loss.numpy()
        t_eager = time.perf_counter() - t0
        # >=3x not >=10x: wall-clock ratios are flaky on loaded CI hosts
        # (ADVICE r4); the honest TPU number is recorded in README
        assert t_eager / t_jit >= 3, (
            "jit_train speedup only %.1fx (eager %.1fms vs jit %.1fms)"
            % (t_eager / t_jit, t_eager * 1e3, t_jit * 1e3))


def test_jit_train_carries_batchnorm_stats(rng):
    """jit_train must thread non-trainable state (BN running stats) through
    the compiled step: stats keep moving, and no tracer leaks into them."""
    xs = rng.randn(64, 4, 6, 6).astype("float32")
    ys = rng.randint(0, 3, (64, 1)).astype("int64")

    class ConvBN(imperative.Layer):
        def __init__(self, name_scope):
            super().__init__(name_scope)
            self._conv = imperative.Conv2D(self.full_name(), 4, 8, 3)
            self._bn = imperative.BatchNorm(self.full_name(), 8, act="relu")
            self._fc = imperative.FC(self.full_name(), 3)

        def forward(self, x):
            return self._fc(self._bn(self._conv(x)))

    with imperative.guard(seed=5):
        net = ConvBN("cbn")
        opt = fluid.optimizer.SGD(learning_rate=0.05)

        def loss_fn(img, lbl):
            return F.mean(F.softmax_with_cross_entropy(net(img), lbl))

        step = imperative.jit_train(loss_fn, net, opt)
        step(xs, ys)                       # eager warmup
        mean1 = np.array(net._bn._mean.numpy())
        step(xs, ys)                       # compiled
        mean2 = np.array(net._bn._mean.numpy())   # must not raise (tracer leak)
        step(xs, ys)
        mean3 = np.array(net._bn._mean.numpy())
        assert not np.allclose(mean1, mean2), "BN stats frozen under jit_train"
        assert not np.allclose(mean2, mean3)


def test_jit_train_rejects_same_tape_mixing(rng):
    """VERDICT demand 8: mixing jit_train's compiled step with a manual
    backward() on the same tape used to silently drop/double-count the
    eager gradients — it must be a hard error, recoverable by
    clear_gradients()."""
    xs, ys = _synthetic(rng, n=32)
    with imperative.guard(seed=13):
        mlp = MLP("mlp")
        opt = fluid.optimizer.SGD(learning_rate=0.1)

        def loss_fn(img, lbl):
            return F.mean(F.softmax_with_cross_entropy(mlp(img), lbl))

        step = imperative.jit_train(loss_fn, mlp, opt)
        step(xs, ys)   # eager warmup
        step(xs, ys)   # compiled
        # manual backward on the same parameters -> pending eager grads
        img, lbl = to_variable(xs), to_variable(ys)
        lbl.stop_gradient = True
        loss_fn(img, lbl)._backward()
        with pytest.raises(RuntimeError, match="manual backward"):
            step(xs, ys)
        mlp.clear_gradients()
        out = step(xs, ys)  # recovers once the tape is cleared
        assert np.isfinite(out.numpy()).all()
