"""Fault injection + crash-safe training/serving tests (ISSUE 7).

Covers: the FaultPlan grammar and classification oracle, the subprocess
SIGTERM kill/resume drill (bit-identical loss trajectory), the injected-NaN
fault driving the CHECK_NUMERICS=2 watchdog end-to-end, run_steps' typed
feed errors, checkpoint durability satellites (torn-restore fallback,
trainer-0-only rotation), and the serving page-accounting invariant across
every retirement path (EOS / max_new / timeout / decode failure)."""

import os
import re
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.reliability import (FaultPlan, InjectedResourceExhausted,
                                    TransientFault, classify, faults)

_RUNNER = os.path.join(os.path.dirname(__file__), "reliability_runner.py")


# -- fault plan framework -----------------------------------------------------

def test_fault_plan_grammar_roundtrip():
    plan = FaultPlan.parse(
        "executor.dispatch@2=transient:3;serving.decode@1=latency:1:25;"
        "io.save_checkpoint@4=fatal")
    assert [s.site for s in plan.specs] == [
        "executor.dispatch", "serving.decode", "io.save_checkpoint"]
    assert plan.specs[0].times == 3
    assert plan.specs[1].ms == 25.0
    # visit counting: fires on visits [at, at+times)
    assert plan.poll("executor.dispatch") is None
    for _ in range(3):
        assert plan.poll("executor.dispatch").kind == "transient"
    assert plan.poll("executor.dispatch") is None
    assert plan.fired == 3 and plan.hits("executor.dispatch") == 5


def test_fault_plan_rejects_bad_entries():
    for bad in ("nonsense", "bogus.site@1=transient",
                "executor.dispatch@0=transient",
                "executor.dispatch@1=made_up_kind"):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)


def test_env_fault_plan_and_fast_path(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_FAULT_PLAN", raising=False)
    faults.clear()
    assert faults.current_plan() is None
    assert faults.poll("executor.dispatch") is None  # the no-plan fast path
    monkeypatch.setenv("PADDLE_TPU_FAULT_PLAN",
                       "executor.compile@1=transient")
    plan = faults.current_plan()
    assert plan is not None and plan.specs[0].site == "executor.compile"
    assert faults.current_plan() is plan  # cached per env value
    with pytest.raises(TransientFault):
        faults.fire("executor.compile")


def test_probabilistic_specs_are_seed_deterministic():
    """FaultSpec(p=...) fires per-visit from the plan's seeded RNG — the
    same seed replays the same firing schedule (the 'seedable' contract)."""
    def schedule(seed):
        plan = FaultPlan([faults.FaultSpec("executor.dispatch", "transient",
                                           p=0.5)], seed=seed)
        return [plan.poll("executor.dispatch") is not None
                for _ in range(32)]

    a, b = schedule(7), schedule(7)
    assert a == b, "same seed must replay the same schedule"
    assert any(a) and not all(a), a  # p=0.5 over 32 visits: mixed outcomes
    assert schedule(8) != a  # and the seed actually matters


def test_classify_oracle():
    from paddle_tpu.serving import BackpressureError, PagePoolExhausted

    assert classify(TransientFault("x")) == "transient"
    assert classify(InjectedResourceExhausted("RESOURCE_EXHAUSTED")) == "fatal"
    assert classify(BackpressureError("full")) == "backpressure"
    assert classify(PagePoolExhausted("no pages")) == "backpressure"
    assert classify(RuntimeError("UNAVAILABLE: connection reset")) == \
        "transient"
    assert classify(KeyboardInterrupt()) == "preemption"
    assert classify(ValueError("shape mismatch")) == "fatal"


# -- the subprocess kill/resume drill -----------------------------------------

def _run_runner(ckpt, total=10, fault_plan=None, timeout=120):
    env = dict(os.environ)
    env.pop("PYTEST_CURRENT_TEST", None)
    env.pop("PADDLE_TPU_FAULT_PLAN", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    if fault_plan:
        env["PADDLE_TPU_FAULT_PLAN"] = fault_plan
    p = subprocess.run([sys.executable, _RUNNER, ckpt, str(total)], env=env,
                       stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                       text=True, timeout=timeout)
    losses = {int(s): h for s, h in
              re.findall(r"SUP_STEP:(\d+):([0-9a-f]{8})", p.stdout)}
    return p, losses


def test_sigterm_kill_resume_bit_identical(tmp_path):
    """SIGTERM mid-run_supervised (delivered through the real signal path
    by the fault plan's preempt kind): marked exit code 42, rotating
    checkpoint written; a restart resumes and the stitched loss trajectory
    is BIT-identical to an uninterrupted run — dropout masks included."""
    ref, ref_losses = _run_runner(str(tmp_path / "ref"))
    assert ref.returncode == 0, ref.stdout
    assert sorted(ref_losses) == list(range(10)), ref.stdout

    ck = str(tmp_path / "ck")
    first, first_losses = _run_runner(
        ck, fault_plan="executor.dispatch@3=preempt")
    assert first.returncode == 42, first.stdout  # EXIT_PREEMPTED
    # the SIGTERM lands mid-run; the in-flight fused chunk (2 steps) still
    # completes, so the covered prefix is a non-empty even-length range
    k = len(first_losses)
    assert 0 < k < 10 and k % 2 == 0, first.stdout
    assert sorted(first_losses) == list(range(k)), first.stdout
    assert "SUP_RESUMED" not in first.stdout

    second, second_losses = _run_runner(ck)
    assert second.returncode == 0, second.stdout
    assert ("SUP_RESUMED:%d" % k) in second.stdout, second.stdout
    assert sorted(second_losses) == list(range(k, 10)), second.stdout

    stitched = dict(first_losses)
    stitched.update(second_losses)
    assert stitched == ref_losses, \
        "kill/resume trajectory diverged from the uninterrupted run"


def test_supervisor_transient_retry_inprocess(tmp_path):
    from paddle_tpu.reliability import run_supervised

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        loss = fluid.layers.mean(fluid.layers.fc(x, size=3))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    def source(start):
        def gen():
            s = start
            while True:
                r = np.random.RandomState(s)
                yield {"x": r.randn(4, 4).astype("float32")}
                s += 1
        return gen()

    plan = FaultPlan([faults.FaultSpec("executor.dispatch", "transient",
                                       at=2, times=2)])
    with plan:
        res = run_supervised(exe, main, source, 6, [loss],
                             checkpoint_dir=str(tmp_path / "ck"),
                             fetch_every=2, backoff_s=0.0,
                             exit_on_preempt=False)
    assert res.steps_done == 6 and res.retries == 2, res

    # a fatal fault re-raises after recording the supervisor event
    plan = FaultPlan([faults.FaultSpec("executor.dispatch", "fatal", at=1)])
    with plan:
        with pytest.raises(faults.InjectedFault):
            run_supervised(exe, main, source, 2, [loss],
                           checkpoint_dir=str(tmp_path / "ck2"),
                           exit_on_preempt=False)


# -- injected NaN -> numerics watchdog ----------------------------------------

def test_injected_nan_watchdog_names_originating_op(monkeypatch):
    """The 'nan' fault poisons a feed; the CHECK_NUMERICS=2 guarded step
    must attribute the first non-finite output to the originating op by
    <slot>:<type> — the full watchdog path driven end-to-end by a fault."""
    from paddle_tpu.core.enforce import EnforceNotMet

    monkeypatch.setenv("PADDLE_TPU_CHECK_NUMERICS", "2")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        loss = fluid.layers.mean(fluid.layers.fc(x, size=3, act="relu"))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = np.ones((2, 4), "float32")
    exe.run(main, feed={"x": xs}, fetch_list=[loss])  # clean step
    # the plan is installed AFTER the clean step, so the poisoned run is
    # its first executor.dispatch visit
    plan = FaultPlan([faults.FaultSpec("executor.dispatch", "nan", at=1)])
    with plan:
        with pytest.raises(EnforceNotMet,
                           match=r"first produced by op \d+:\w+"):
            exe.run(main, feed={"x": xs}, fetch_list=[loss])
    # (no "recovery" run: the poisoned step's NaN grads corrupted the
    # optimizer state — catching exactly that is the watchdog's job; the
    # production answer is the supervisor's checkpoint-and-restore)


# -- run_steps typed feed errors ----------------------------------------------

def test_run_steps_feed_failure_is_typed_and_flight_recorded(
        monkeypatch, tmp_path):
    from paddle_tpu.executor import FeedError

    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        loss = fluid.layers.mean(fluid.layers.fc(x, size=3))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    def feeds():
        yield {"x": np.ones((2, 4), "float32")}
        raise RuntimeError("data pipeline exploded")

    with pytest.raises(FeedError, match=r"global step 1 \(position 1 of the "
                                        r"current 2-step chunk\).*data "
                                        r"pipeline exploded"):
        exe.run_steps(main, feeds(), steps=4, fetch_list=[loss],
                      fetch_every=2)
    dumps = [f for f in os.listdir(str(tmp_path)) if f.startswith("flight_")]
    assert dumps, "feed failure was not flight-recorded"


# -- checkpoint durability satellites -----------------------------------------

def _ckpt_model():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        logits = fluid.layers.fc(x, size=3,
                                 param_attr=fluid.ParamAttr(name="w"),
                                 bias_attr=fluid.ParamAttr(name="b"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _one_step(exe, main, loss, rng):
    exe.run(main, feed={"x": rng.randn(4, 4).astype("float32"),
                        "y": rng.randint(0, 3, (4, 1)).astype("int64")},
            fetch_list=[loss])


def test_torn_restore_falls_back_to_previous_serial(tmp_path, rng):
    """A truncated tensor file inside a _SUCCESS checkpoint must not raise
    mid-restore — load_checkpoint logs, falls back to the previous serial,
    and the scope ends fully consistent with it."""
    ck = str(tmp_path / "ck")
    main, startup, loss = _ckpt_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    _one_step(exe, main, loss, rng)
    fluid.io.save_checkpoint(exe, ck, main, trainer_args={"step": 1})
    w_good = fluid.global_scope().as_numpy("w").copy()
    _one_step(exe, main, loss, rng)
    fluid.io.save_checkpoint(exe, ck, main, trainer_args={"step": 2})
    # corrupt the NEWEST serial's tensor payload (truncation = torn write
    # that survived into a _SUCCESS-marked dir, e.g. lost page cache)
    newest = os.path.join(ck, "checkpoint_1", "w.npy")
    with open(newest, "wb") as f:
        f.write(b"\x93NUMPY")  # magic only: unreadable header
    _one_step(exe, main, loss, rng)  # drift the live weights
    args = fluid.io.load_checkpoint(exe, ck, main)
    assert args["step"] == 1, args  # fell back to serial 0
    np.testing.assert_array_equal(fluid.global_scope().as_numpy("w"), w_good)

    # every serial torn -> a hard, named error (never a silent fresh start)
    oldest = os.path.join(ck, "checkpoint_0", "w.npy")
    with open(oldest, "wb") as f:
        f.write(b"\x93NUMPY")
    with pytest.raises(RuntimeError, match="no readable checkpoint"):
        fluid.io.load_checkpoint(exe, ck, main)


def test_rotation_only_by_trainer_zero(tmp_path, rng):
    """Non-zero trainers never rotate (concurrent savers can't race-delete
    each other's serials); trainer 0 still enforces max_num_checkpoints."""
    ck = str(tmp_path / "ck")
    main, startup, loss = _ckpt_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    _one_step(exe, main, loss, rng)
    for step in range(4):
        fluid.io.save_checkpoint(exe, ck, main, trainer_id=1,
                                 trainer_args={"step": step},
                                 max_num_checkpoints=2)
    names = sorted(n for n in os.listdir(ck) if n.startswith("checkpoint_"))
    assert len(names) == 4, names  # trainer 1 rotated nothing
    fluid.io.save_checkpoint(exe, ck, main, trainer_id=0,
                             trainer_args={"step": 4},
                             max_num_checkpoints=2)
    names = sorted(n for n in os.listdir(ck) if n.startswith("checkpoint_"))
    assert names == ["checkpoint_3", "checkpoint_4"], names


def test_injected_save_fault_leaves_unpublished_tmp(tmp_path, rng):
    """A fault during save (post-payload, pre-publish) must leave only an
    unpublished .tmp dir — the resume path skips it cleanly."""
    ck = str(tmp_path / "ck")
    main, startup, loss = _ckpt_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    _one_step(exe, main, loss, rng)
    fluid.io.save_checkpoint(exe, ck, main, trainer_args={"step": 1})
    plan = FaultPlan([faults.FaultSpec("io.save_checkpoint", "fatal", at=1)])
    with plan:
        with pytest.raises(faults.InjectedFault):
            fluid.io.save_checkpoint(exe, ck, main, trainer_args={"step": 2})
    tmps = [n for n in os.listdir(ck) if n.startswith("checkpoint_1.tmp")]
    assert tmps, os.listdir(ck)  # staged but never published
    assert not os.path.isdir(os.path.join(ck, "checkpoint_1"))
    args = fluid.io.load_checkpoint(exe, ck, main)
    assert args["step"] == 1, args  # the torn tmp was never a candidate


# -- serving page accounting across every retirement path ---------------------

def test_serving_page_accounting_every_retirement_path(rng):
    from paddle_tpu import serving
    from paddle_tpu.models import decoder_lm

    cfg = decoder_lm.DecoderConfig(vocab_size=64, n_layer=1, d_model=16,
                                   n_head=2, max_seq=32)
    model = decoder_lm.DecoderLM(cfg, seed=0)

    def fresh(**kw):
        return serving.ServingEngine(model, serving.ServingConfig(
            slots=2, page_size=8, max_seq=32, **kw))

    def assert_balanced(eng, label):
        assert eng.pool.num_used == 0, "%s leaked pages" % label
        assert eng.page_accounting_ok(), label

    # 1. max_new retirement (and the immediate-finish prefill path)
    eng = fresh()
    r_full = eng.submit(list(rng.randint(0, 64, 6)), 4)
    r_one = eng.submit(list(rng.randint(0, 64, 6)), 1)
    eng.run(max_steps=100)
    assert r_full.state == "finished" and r_one.state == "finished"
    assert_balanced(eng, "max_new")
    # EOS retirement: replay a prompt with eos_id set to a token the greedy
    # decode deterministically emits mid-generation
    tok_mid = r_full.tokens_out[1]
    eng_eos = fresh(eos_id=int(tok_mid))
    r_eos = eng_eos.submit(list(r_full.prompt), 4)
    eng_eos.run(max_steps=100)
    assert r_eos.state == "finished"
    assert len(r_eos.tokens_out) < 4, "EOS did not stop generation early"
    assert_balanced(eng_eos, "eos")

    # 2. timeout retirement, queued AND running
    eng_t = fresh()
    r_q = eng_t.submit(list(rng.randint(0, 64, 6)), 4, deadline_s=0.0)
    r_r = eng_t.submit(list(rng.randint(0, 64, 6)), 4)
    eng_t.run(max_steps=100)
    assert r_q.state == "timeout" and not r_q.pages
    assert r_r.state == "finished"
    assert_balanced(eng_t, "timeout")

    # 3. decode-failure retirement: pages reclaimed, engine keeps serving
    eng_f = fresh(decode_retries=0)
    plan = FaultPlan([faults.FaultSpec("serving.decode", "fatal", at=1)])
    with plan:
        r_a = eng_f.submit(list(rng.randint(0, 64, 6)), 4)
        r_b = eng_f.submit(list(rng.randint(0, 64, 6)), 4)
        done = eng_f.run(max_steps=100)
    assert r_a.state == "failed" and r_a.error and not r_a.pages
    assert r_b.state in ("failed", "finished")
    assert len(done) == 2, done
    assert_balanced(eng_f, "decode-failure")
    # and the engine is still alive for new traffic
    r_after = eng_f.submit(list(rng.randint(0, 64, 6)), 3)
    eng_f.run(max_steps=100)
    assert r_after.state == "finished"
    assert_balanced(eng_f, "post-failure traffic")
    assert eng_f.health()["status"] == "ok"
