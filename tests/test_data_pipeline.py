"""Exactly-once, corruption-tolerant data pipeline + divergence sentinel
(ISSUE 10).

Covers: CheckpointableReader position round-trip (exactly-once across a
state_dict/load_state_dict boundary), typed corrupt-record quarantine with
per-record reasons, the bounded corrupt-rate -> DataCorruptionError
contract, prefetch state consistency (the wrapper's state is the
consumer's, not the worker's read-ahead), MultiSlot/AsyncExecutor feed
parity, reader-fed run_supervised resume with zero caller bookkeeping
(in-process preempt + subprocess SIGKILL, both asserting the record-id
ledger), checkpoint torn-restore with the new data-reader payload (model
and reader fall back to the SAME serial), the divergence sentinel
(NaN-window rollback healing bit-identical to a never-poisoned twin,
spike rule, trip budget, repeat-trip fatality, watchdog op naming), and
the supervisor's seeded-jitter backoff schedule."""

import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import data
from paddle_tpu.reliability import (DivergenceSentinel, FaultPlan,
                                    SentinelFatal, backoff_schedule, faults,
                                    run_supervised)

_RUNNER = os.path.join(os.path.dirname(__file__), "data_runner.py")


# -- shard helpers ------------------------------------------------------------

def _write_shards(dirname, n, n_shards=2, poison=(), seed_base=6000):
    """Shards of ``8 floats + 1 int label`` records; indices in ``poison``
    get all-NaN features (parseable + schema-valid — numerically toxic)."""
    os.makedirs(dirname, exist_ok=True)
    paths, idx = [], 0
    per = n // n_shards
    for si in range(n_shards):
        p = os.path.join(dirname, "shard_%d.txt" % si)
        with open(p, "w") as f:
            for _ in range(per):
                r = np.random.RandomState(seed_base + idx)
                x = np.full(8, np.nan) if idx in poison else r.randn(8)
                f.write(" ".join("%r" % float(v) for v in x)
                        + " %d\n" % r.randint(0, 4))
                idx += 1
        paths.append(p)
    return paths


def _parse(line):
    t = line.split()
    return {"x": np.asarray([float(v) for v in t[:8]], np.float32),
            "y": np.asarray([int(t[8])], np.int64)}


_SCHEMA = [data.FieldSpec("x", (8,), np.float32),
           data.FieldSpec("y", (1,), np.int64)]


def _reader(paths, batch_size=4, **kw):
    kw.setdefault("epochs", 1)
    return data.CheckpointableReader(paths, _parse, batch_size,
                                     schema=_SCHEMA, **kw)


# -- reader core --------------------------------------------------------------

def test_reader_position_roundtrip_exactly_once(tmp_path):
    paths = _write_shards(str(tmp_path), 24)
    ref = list(_reader(paths))
    r1 = _reader(paths)
    head = [next(r1) for _ in range(2)]
    state = r1.state_dict()
    tail1 = list(r1)
    r2 = _reader(paths)
    r2.load_state_dict(state)
    tail2 = list(r2)
    assert len(head) + len(tail1) == len(ref) == 6
    for a, b in zip(tail1, tail2):
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])
    # the restored reader's ledger continues exactly where the state says
    assert r2.state_dict()["records_read"] == 24
    # a different shard set refuses the state (silent skew prevention)
    other = _write_shards(str(tmp_path / "other"), 24)
    os.rename(other[0], other[0].replace("shard_0", "renamed_0"))
    r3 = data.CheckpointableReader(
        sorted(os.path.join(str(tmp_path / "other"), f)
               for f in os.listdir(str(tmp_path / "other"))),
        _parse, 4, schema=_SCHEMA, epochs=1)
    with pytest.raises(ValueError, match="different records"):
        r3.load_state_dict(state)


def test_corrupt_records_quarantined_with_reasons(tmp_path):
    p = os.path.join(str(tmp_path), "bad_0.txt")
    with open(p, "w") as f:
        f.write(" ".join(["0.1"] * 8) + " 1\n")      # good
        f.write("not numbers at all\n")               # parse failure
        f.write(" ".join(["0.2"] * 4) + " 1\n")      # wrong width (shape)
        f.write(" ".join(["0.3"] * 8) + " 2\n")      # good
        f.write(" ".join(["0.4"] * 8) + " 0\n")      # good
        f.write(" ".join(["0.5"] * 8) + " 3\n")      # good
    q = os.path.join(str(tmp_path), "quarantine.jsonl")
    r = _reader([p], batch_size=2, quarantine_path=q,
                max_corrupt_rate=0.9, corrupt_check_min=1)
    batches = list(r)
    assert len(batches) == 2 and r.records_corrupt == 2
    rows = [json.loads(ln) for ln in open(q)]
    assert [row["id"] for row in rows] == ["bad_0.txt#1", "bad_0.txt#2"]
    assert all("parse" in row["reason"] for row in rows)
    # quarantined ids persist into the skip set and the state dict
    assert r.quarantined_ids() == ["bad_0.txt#1", "bad_0.txt#2"]
    assert sorted(r.state_dict()["skip_ids"]) == r.quarantined_ids()


def test_corrupt_rate_bound_raises_typed(tmp_path):
    p = os.path.join(str(tmp_path), "mostly_bad_0.txt")
    with open(p, "w") as f:
        for i in range(20):
            f.write("garbage\n" if i % 2 else
                    " ".join(["0.1"] * 8) + " 1\n")
    r = _reader([p], batch_size=2, max_corrupt_rate=0.1, corrupt_check_min=4)
    with pytest.raises(data.DataCorruptionError, match="exceeds the"):
        list(r)


def test_prefetch_preserves_checkpoint_contract(tmp_path):
    paths = _write_shards(str(tmp_path), 32)
    ref = list(_reader(paths))
    pf = _reader(paths).prefetch(capacity=3)
    got = [next(pf) for _ in range(3)]
    state = pf.state_dict()  # position of the LAST YIELDED batch only
    assert state["records_read"] == 12, state
    # a fresh reader restored from the prefetcher's state continues in step
    r2 = _reader(paths)
    r2.load_state_dict(state)
    rest = list(r2)
    assert len(got) + len(rest) == len(ref)
    for a, b in zip(got + rest, ref):
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])
    pf.stop()
    # quarantine through the wrapper rewinds the worker's read-ahead: the
    # NEXT batches skip the named records exactly as an unwrapped reader
    pf2 = _reader(paths).prefetch(capacity=2)
    next(pf2)
    ids_next = ["shard_0.txt#4", "shard_0.txt#5"]
    pf2.quarantine(ids_next, "test window")
    after = next(pf2)
    r3 = _reader(paths)
    [next(r3)]
    r3.quarantine(ids_next, "test window")
    expect = next(r3)
    for k in after:
        np.testing.assert_array_equal(after[k], expect[k])
    pf2.stop()


def test_multislot_asyncexecutor_feed_parity(tmp_path):
    from paddle_tpu.async_executor import (_batch_to_feed,
                                           _parse_multislot_line)

    paths = data.write_ctr_shards(str(tmp_path), 12, n_shards=1,
                                  num_fields=5, dense_dim=3, vocab=100)
    slots = data.ctr_slots(num_fields=5, dense_dim=3)
    reader = data.MultiSlotTextReader(paths, slots, batch_size=4, epochs=1)
    ref_batches = []
    batch = []
    for line in open(paths[0]):
        batch.append(_parse_multislot_line(line.strip(), slots))
        if len(batch) == 4:
            ref_batches.append(_batch_to_feed(batch, slots))
            batch = []
    got = list(reader)
    assert len(got) == len(ref_batches) == 3
    for a, b in zip(got, ref_batches):
        assert sorted(a) == sorted(b)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])


def test_ctr_reader_feeds_deepfm(tmp_path):
    from paddle_tpu.models import deepfm as dfm

    paths = data.write_ctr_shards(str(tmp_path), 16, n_shards=2,
                                  num_fields=4, dense_dim=3, vocab=50)
    reader = data.CTRMultiSlotReader(paths, batch_size=8, num_fields=4,
                                     dense_dim=3, vocab=50, epochs=1)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[4], dtype="int64")
        dense = fluid.layers.data("dense", shape=[3])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        _, loss, _ = dfm.deepfm(ids, dense, label, sparse_feature_dim=50,
                                embedding_size=4, num_fields=4,
                                layer_sizes=(8,), is_sparse=False)
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    # composes with DevicePrefetcher: parse-ahead -> H2D-ahead -> run_steps
    from paddle_tpu.reader import DevicePrefetcher

    with DevicePrefetcher(reader.prefetch(2), capacity=2) as feeds:
        rows = exe.run_steps(main, feeds, steps=2, fetch_list=[loss],
                             fetch_every=2)
    assert len(rows) == 2 and all(np.isfinite(r[0]).all() for r in rows)


# -- supervised integration: exactly-once with zero caller bookkeeping --------

def _build_train():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 1234
    with fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8])
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, size=16, act="relu")
            h = fluid.layers.dropout(h, dropout_prob=0.3)
            logits = fluid.layers.fc(h, size=4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
    return main, startup, loss


def _supervised(ckpt, reader, plan=None, total=8, sentinel=None,
                ledger=None):
    main, startup, loss = _build_train()
    exe = fluid.Executor(fluid.CPUPlace())

    def on_chunk(step0, rows):
        if ledger is not None:
            for i, ids in enumerate(reader.last_batch_ids(len(rows))):
                ledger[step0 + i] = ids

    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with (plan if plan is not None else FaultPlan([])):
            return run_supervised(
                exe, main, reader, total, [loss], checkpoint_dir=ckpt,
                fetch_every=2, checkpoint_every_steps=2, backoff_s=0.0,
                exit_on_preempt=False, sentinel=sentinel, on_chunk=on_chunk)


def _bits(v):
    return np.float32(v).tobytes().hex()


def test_supervised_reader_preempt_resume_exactly_once(tmp_path):
    paths = _write_shards(str(tmp_path / "shards"), 40)
    ref_led = {}
    ref = _supervised(str(tmp_path / "ref"), _reader(paths), ledger=ref_led)
    assert ref.steps_done == 8

    ck = str(tmp_path / "ck")
    led1, led2 = {}, {}
    plan = FaultPlan([faults.FaultSpec("executor.dispatch", "preempt", at=2)])
    first = _supervised(ck, _reader(paths), plan, ledger=led1)
    assert first.preempted and first.steps_done == 4
    # the resume uses a FRESH reader object: the supervisor restores its
    # position from the checkpoint payload, no feed_source(start) anywhere
    second = _supervised(ck, _reader(paths), ledger=led2)
    assert second.resumed and second.start_step == 4
    assert second.steps_done == 8 and not second.preempted

    stitched = dict(led1)
    stitched.update(led2)
    consumed = [rid for s in sorted(stitched) for rid in stitched[s]]
    assert sorted(stitched) == list(range(8))
    assert len(consumed) == len(set(consumed)) == 32
    assert stitched == ref_led
    sb = [_bits(r[0]) for r in first.losses] + \
         [_bits(r[0]) for r in second.losses]
    assert sb == [_bits(r[0]) for r in ref.losses]


def _run_data_runner(shards, ckpt, total=8, kill_at=None, timeout=120):
    env = dict(os.environ)
    env.pop("PYTEST_CURRENT_TEST", None)
    env.pop("PADDLE_TPU_FAULT_PLAN", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    if kill_at is not None:
        env["DATA_KILL_AT_STEP"] = str(kill_at)
    else:
        env.pop("DATA_KILL_AT_STEP", None)
    p = subprocess.run([sys.executable, _RUNNER, shards, ckpt, str(total)],
                       env=env, stdout=subprocess.PIPE,
                       stderr=subprocess.STDOUT, text=True, timeout=timeout)
    ledger = {int(s): ids.split(",") for s, ids in
              re.findall(r"LEDGER:(\d+):(\S+)", p.stdout)}
    losses = {int(s): h for s, h in
              re.findall(r"SUP_STEP:(\d+):([0-9a-f]{8})", p.stdout)}
    return p, ledger, losses


def test_sigkill_resume_record_ledger_exactly_once(tmp_path):
    """SIGKILL (no checkpoint-on-exit, no cleanup) mid-run + auto-resume:
    the latest-wins stitched per-step ledger consumes every record exactly
    once and matches an uninterrupted twin — acceptance drill 2."""
    shards = str(tmp_path / "shards")
    _write_shards(shards, 40)
    ref_p, ref_led, ref_losses = _run_data_runner(
        shards, str(tmp_path / "ref"))
    assert ref_p.returncode == 0, ref_p.stdout
    assert sorted(ref_led) == list(range(8))

    ck = str(tmp_path / "ck")
    first_p, led1, _ = _run_data_runner(shards, ck, kill_at=5)
    assert first_p.returncode == -9, first_p.stdout  # died to SIGKILL
    assert sorted(led1) == list(range(6)), first_p.stdout

    second_p, led2, second_losses = _run_data_runner(shards, ck)
    assert second_p.returncode == 0, second_p.stdout
    m = re.search(r"SUP_RESUMED:(\d+)", second_p.stdout)
    assert m, second_p.stdout
    resume_at = int(m.group(1))
    assert 0 < resume_at <= 5  # last durable checkpoint before the kill

    stitched = dict(led1)
    stitched.update(led2)  # re-executed steps: the resumed life wins
    consumed = [rid for s in sorted(stitched) for rid in stitched[s]]
    assert sorted(stitched) == list(range(8))
    assert len(consumed) == len(set(consumed)) == 32, \
        "records lost or double-consumed across the SIGKILL boundary"
    assert stitched == ref_led, "ledger differs from the uninterrupted twin"
    # and the resumed losses are bit-identical to the twin's
    for s, h in second_losses.items():
        assert ref_losses[s] == h, "step %d loss diverged" % s


def test_torn_restore_reader_state_matches_model(tmp_path):
    """Satellite: newest serial torn (payload unreadable though _SUCCESS
    exists) -> load falls back to the previous serial, and the reader
    resumes from THAT serial's position — model and data can't skew."""
    paths = _write_shards(str(tmp_path / "shards"), 40)
    ck = str(tmp_path / "ck")
    reader = _reader(paths)
    res = _supervised(ck, reader, total=6)
    assert res.steps_done == 6 and res.checkpoints_written >= 2
    serials = sorted(int(n.split("_")[1]) for n in os.listdir(ck)
                     if n.startswith("checkpoint_"))
    newest = os.path.join(ck, "checkpoint_%d" % serials[-1])
    prev = os.path.join(ck, "checkpoint_%d" % serials[-2])
    prev_args = json.load(open(os.path.join(prev, "trainer_args.json")))
    # corrupt the newest payload (torn write that survived _SUCCESS)
    victims = [f for f in os.listdir(newest) if f.endswith(".npy")]
    with open(os.path.join(newest, victims[0]), "wb") as f:
        f.write(b"\x93NUMPY")
    fresh = _reader(paths)
    resumed = _supervised(ck, fresh, total=6)
    assert resumed.resumed
    assert resumed.start_step == prev_args["step"], \
        "model fell back but not to the serial the reader resumed from"
    # the reader position restored == the position stored WITH that serial
    assert fresh.state_dict()["records_read"] == 6 * 4  # ran to step 6
    ledger_start = prev_args["data_reader"]["records_read"]
    assert ledger_start == prev_args["step"] * 4, prev_args


# -- the divergence sentinel --------------------------------------------------

def test_sentinel_nan_window_heals_bit_identical(tmp_path):
    """Acceptance drill 1 (pytest twin of the chaos_drill leg): poisoned
    window -> trip, rollback, quarantine, resume past it; final losses
    bit-identical to a twin that never saw the poisoned records."""
    poison = set(range(16, 24))  # steps 4-5 at batch 4: one fused chunk
    d_p = str(tmp_path / "poison")
    paths = _write_shards(d_p, 40, poison=poison)
    d_c = str(tmp_path / "clean")
    os.makedirs(d_c)
    clean, idx = [], 0
    for p in paths:
        q = os.path.join(d_c, os.path.basename(p))
        with open(q, "w") as f:
            for line in open(p):
                if idx not in poison:
                    f.write(line)
                idx += 1
        clean.append(q)
    qfile = str(tmp_path / "quarantine.jsonl")
    sent = DivergenceSentinel(nan=True, max_trips=2)
    healed = _supervised(str(tmp_path / "ck_h"),
                         _reader(paths, quarantine_path=qfile),
                         sentinel=sent)
    assert healed.steps_done == 8 and healed.rollbacks == 1
    assert [t.rule for t in healed.trips] == ["nan"]
    assert healed.records_quarantined == 8
    rows = [json.loads(ln) for ln in open(qfile)]
    assert sorted(r["id"] for r in rows) == \
        sorted("shard_%d.txt#%d" % (i // 20, i % 20) for i in poison)
    twin = _supervised(str(tmp_path / "ck_t"), _reader(clean))
    assert [_bits(r[0]) for r in healed.losses] == \
        [_bits(r[0]) for r in twin.losses]


def test_sentinel_spike_rule_and_budget(tmp_path):
    sent = DivergenceSentinel(nan=False, spike_z=3.0, spike_window=16,
                              spike_min_history=4, max_trips=2)
    hist = [1.0, 1.01, 0.99, 1.0, 1.02, 0.98]
    trip = sent.check_rows([[np.float32(50.0)]], hist)
    assert trip is not None and trip.rule == "spike"
    assert sent.check_rows([[np.float32(1.0)]], hist) is None
    # budget: trips at DISTINCT steps beyond max_trips -> fatal
    sent.register_trip(4, trip)
    t2 = sent.check_rows([[np.float32(60.0)]], hist)
    sent.register_trip(8, t2)
    t3 = sent.check_rows([[np.float32(70.0)]], hist)
    with pytest.raises(SentinelFatal, match="budget exhausted"):
        sent.register_trip(12, t3)


def test_sentinel_repeat_trip_same_step_fatal():
    sent = DivergenceSentinel(max_trips=10)
    t1 = sent.check_rows([[np.float32(np.nan)]], [])
    assert t1 is not None and t1.rule == "nan"
    sent.register_trip(6, t1)
    t2 = sent.check_rows([[np.float32(np.nan)]], [])
    with pytest.raises(SentinelFatal, match="REPEAT trip at step 6"):
        sent.register_trip(6, t2)


def test_sentinel_watchdog_exception_names_op(monkeypatch, tmp_path):
    """With CHECK_NUMERICS=2 the guarded step raises the typed watchdog
    error; the sentinel maps it to a nan trip CARRYING the <slot>:<type>
    op name, and a repeat trip surfaces it in the SentinelFatal."""
    monkeypatch.setenv("PADDLE_TPU_CHECK_NUMERICS", "2")
    poison = set(range(8, 16))  # steps 2-3
    paths = _write_shards(str(tmp_path / "shards"), 40, poison=poison)
    sent = DivergenceSentinel(nan=True, max_trips=3)
    healed = _supervised(str(tmp_path / "ck"),
                         _reader(paths, quarantine_path=str(
                             tmp_path / "q.jsonl")),
                         sentinel=sent)
    assert healed.steps_done == 8 and healed.rollbacks == 1
    trip = healed.trips[0]
    assert trip.rule == "nan" and trip.named_op is not None
    assert re.match(r"\d+:\w+", trip.named_op), trip.named_op


def test_sentinel_rollback_flight_recorded(monkeypatch, tmp_path):
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path / "flight"))
    poison = set(range(16, 24))
    paths = _write_shards(str(tmp_path / "shards"), 40, poison=poison)
    sent = DivergenceSentinel(nan=True, max_trips=2)
    _supervised(str(tmp_path / "ck"), _reader(paths), sentinel=sent)
    # the trip event is in the ring; force a dump through a fatal twin:
    # replaying the SAME poisoned stream WITHOUT quarantine support would
    # be contrived — instead assert the ring recorded the trip by dumping
    from paddle_tpu.monitor import device as dev

    fr = dev.flight_recorder()
    assert fr is not None
    path = fr.dump("test", None)
    doc = json.load(open(path))
    events = [e for e in doc["entries"] if e.get("event") == "sentinel_trip"]
    assert events and events[0]["rolled_back_to"] == 4
    assert events[0]["quarantined"] == 8


# -- jittered backoff satellite ----------------------------------------------

def test_backoff_schedule_seeded_jitter_reproducible():
    a = backoff_schedule(0.1, 4, seed=7)
    b = backoff_schedule(0.1, 4, seed=7)
    assert a == b, "same seed must reproduce the same schedule"
    c = backoff_schedule(0.1, 4, seed=8)
    assert a != c, "seed must actually vary the jitter"
    # exponential envelope with jitter in [0.5, 1.0) of the pure schedule
    for i, s in enumerate(a):
        pure = 0.1 * (2 ** i)
        assert 0.5 * pure <= s < pure
    # the supervisor derives its seed from the active fault plan
    plan = FaultPlan([], seed=7)
    with plan:
        assert faults.current_plan().seed == 7
