"""Ragged paged-attention Pallas decode kernel + device-side sampled
decoding (ISSUE 13 tentpole coverage).

Kernel parity runs in Pallas interpret mode on CPU against the XLA
page-gather + ``decode_attention`` reference — same tolerance discipline
as the sparse_adam kernel tests (rtol/atol 1e-6 on live rows, BIT-exact
indifference to garbage beyond ``ctx_len``). Engine-level tests arm
``FLAGS_paged_attention_kernel=interpret`` and assert the full serving
stack emits the same token streams either way, that ``temperature=0`` is
bit-identical to greedy, that seeded sampling is invariant to
``decode_fuse`` width, and that top-k can never select outside the top-k
set.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import serving
from paddle_tpu.flags import set_flag
from paddle_tpu.models import decoder_lm
from paddle_tpu.ops.pallas_kernels import paged_attention as pa

_MODEL = None


def get_model():
    global _MODEL
    if _MODEL is None:
        cfg = decoder_lm.DecoderConfig(vocab_size=64, n_layer=2, d_model=32,
                                       n_head=2, max_seq=64)
        _MODEL = decoder_lm.DecoderLM(cfg, seed=0)
    return _MODEL


@pytest.fixture(autouse=True)
def _restore_flag():
    yield
    set_flag("paged_attention_kernel", "auto")


def make_pool(rng, slots, pages_per_slot, num_pages, page_size, h, d):
    """Synthetic one-layer paged KV pool + a permuted page table, the
    layout PagedKVCache hands the kernel."""
    k = rng.randn(num_pages * page_size, h, d).astype(np.float32)
    v = rng.randn(num_pages * page_size, h, d).astype(np.float32)
    pt = np.stack([rng.permutation(num_pages)[:pages_per_slot]
                   for _ in range(slots)]).astype(np.int32)
    q = rng.randn(slots, h, d).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(pt)


# -- kernel parity (interpret mode) ------------------------------------------

def test_kernel_matches_gather_at_ragged_lengths(rng):
    slots, h, d, ps, pps = 5, 2, 16, 8, 8
    q, k, v, pt = make_pool(rng, slots, pps, 24, ps, h, d)
    ctx = jnp.asarray([1, 7, 8, 33, 64], jnp.int32)  # ragged, page-straddling
    want = pa.gather_reference(q, k, v, pt, ctx, ps, sm_scale=0.25)
    for bp in (1, 3, 4, None):  # incl. non-divisor + tuned-table default
        got = pa.paged_decode_attention(q, k, v, pt, ctx, page_size=ps,
                                        sm_scale=0.25, block_pages=bp,
                                        interpret=True)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6,
                                   err_msg="block_pages=%r" % (bp,))


def test_garbage_pages_move_no_output_bit(rng):
    """Pages beyond ctx_len belong to OTHER requests (or are stale) — the
    kernel must ignore them EXACTLY, not approximately: trashing every
    invalid row with large finite values moves no output bit."""
    slots, h, d, ps, pps = 4, 2, 8, 8, 4
    q, k, v, pt = make_pool(rng, slots, pps, 12, ps, h, d)
    ctx = jnp.asarray([3, 8, 17, 29], jnp.int32)
    clean = pa.paged_decode_attention(q, k, v, pt, ctx, page_size=ps,
                                      block_pages=2, interpret=True)
    kp, vp = np.asarray(k).copy(), np.asarray(v).copy()
    used = np.zeros(kp.shape[0], bool)
    for s in range(slots):
        n = int(ctx[s])
        for j in range(pps):
            row0 = int(pt[s, j]) * ps
            live = max(0, min(ps, n - j * ps))
            used[row0:row0 + live] = True
    kp[~used], vp[~used] = 1e4, -1e4
    got = pa.paged_decode_attention(q, jnp.asarray(kp), jnp.asarray(vp), pt,
                                    ctx, page_size=ps, block_pages=2,
                                    interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(clean))


# -- engine-level parity + sampling ------------------------------------------

def _serve(stream, flag_mode, decode_fuse=1, **submit_kw):
    """Drive one engine over ``stream`` with the kernel flag pinned to
    ``flag_mode``; returns ([tokens_out per request], engine stats)."""
    set_flag("paged_attention_kernel", flag_mode)
    try:
        eng = serving.ServingEngine(get_model(), serving.ServingConfig(
            slots=2, page_size=8, max_seq=64, prompt_buckets=(16,),
            decode_fuse=decode_fuse))
        reqs = [eng.submit(p, m, **submit_kw) for p, m in stream]
        eng.run()
        stats = eng.stats()
        eng.close()
        return [list(r.tokens_out) for r in reqs], stats
    finally:
        set_flag("paged_attention_kernel", "auto")


def test_engine_kernel_vs_gather_token_parity(rng):
    stream = [(list(rng.randint(0, 64, int(n))), 6) for n in (3, 9, 14)]
    got, stats = _serve(stream, "interpret")
    want, base_stats = _serve(stream, "off")
    assert got == want, "kernel decode diverged from the gather path"
    assert stats["decode_kernel"] == "paged"
    assert stats["decode_kernel_source"] in ("tuned", "shipped", "default")
    assert base_stats["decode_kernel"] == "gather"
    assert base_stats["decode_kernel_source"] == "n/a"


def test_temperature_zero_bit_identical_to_greedy(rng):
    stream = [(list(rng.randint(0, 64, int(n))), 8) for n in (4, 11)]
    greedy, _ = _serve(stream, "off")
    # explicit temperature=0 (with sampling params set) must stay greedy
    t0, _ = _serve(stream, "off", temperature=0.0, top_k=5, seed=123)
    assert t0 == greedy, "temperature=0 is not bit-identical to greedy"


def test_seeded_sampling_invariant_to_decode_fuse(rng):
    """The RNG is keyed per (seed, absolute position), not per dispatch —
    a request's stream must not depend on how many decode steps the
    engine fuses into one lax.scan chunk."""
    stream = [(list(rng.randint(0, 64, int(n))), 8) for n in (5, 12, 7)]
    kw = dict(temperature=0.8, top_k=5, seed=4242)
    f1, _ = _serve(stream, "off", decode_fuse=1, **kw)
    f4, _ = _serve(stream, "off", decode_fuse=4, **kw)
    assert f1 == f4, "sampled stream depends on decode_fuse width"
    greedy, _ = _serve(stream, "off")
    assert f1 != greedy, "temperature=0.8 never diverged from greedy"


def test_top_k_never_selects_outside_top_k(rng):
    k = 3
    set_flag("paged_attention_kernel", "off")
    eng = serving.ServingEngine(get_model(), serving.ServingConfig(
        slots=2, page_size=8, max_seq=64, prompt_buckets=(16,),
        collect_logits=True))
    reqs = [eng.submit(list(rng.randint(0, 64, n)), 8,
                       temperature=1.5, top_k=k, seed=7 + n)
            for n in (4, 10)]
    eng.run()
    checked = 0
    for r in reqs:
        rows = eng.captured_logits(r)
        assert len(rows) == len(r.tokens_out), (len(rows), len(r.tokens_out))
        for tok, row in zip(r.tokens_out, rows):
            top = np.argsort(np.asarray(row, np.float32))[-k:]
            assert tok in top, "token %d outside top-%d set %s" % (
                tok, k, top)
            checked += 1
    eng.close()
    assert checked >= 16


def test_sampled_requests_mix_with_greedy_in_one_batch(rng):
    """Per-request sampling params ride slot state — one continuous batch
    serves greedy and sampled requests side by side, and the greedy ones
    match a pure-greedy run exactly."""
    prompts = [list(rng.randint(0, 64, n)) for n in (6, 6, 9)]
    set_flag("paged_attention_kernel", "off")
    eng = serving.ServingEngine(get_model(), serving.ServingConfig(
        slots=2, page_size=8, max_seq=64, prompt_buckets=(16,)))
    r_greedy = eng.submit(prompts[0], 8)
    r_sampled = eng.submit(prompts[1], 8, temperature=0.9, seed=99)
    r_greedy2 = eng.submit(prompts[2], 8)
    eng.run()
    eng.close()
    pure, _ = _serve([(prompts[0], 8), (prompts[2], 8)], "off")
    assert list(r_greedy.tokens_out) == pure[0]
    assert list(r_greedy2.tokens_out) == pure[1]
    assert len(r_sampled.tokens_out) == 8
