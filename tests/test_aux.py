"""Aux subsystem tests: DataFeeder, reader decorators, metrics, flags,
debugger, datasets, prefetcher (SURVEY §5 parity)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import metrics as M
from paddle_tpu import reader as R


def test_reader_decorators_compose():
    def r():
        return iter(range(10))

    batches = list(R.batch(r, 3)())
    assert batches == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
    assert list(R.batch(r, 3, drop_last=True)()) == [[0, 1, 2], [3, 4, 5], [6, 7, 8]]
    assert sorted(R.shuffle(r, 5, seed=0)()) == list(range(10))
    assert list(R.firstn(r, 4)()) == [0, 1, 2, 3]
    doubled = R.map_readers(lambda x: 2 * x, r)
    assert list(doubled()) == [0, 2, 4, 6, 8, 10, 12, 14, 16, 18]
    assert list(R.chain(r, r)()) == list(range(10)) * 2
    assert sorted(R.buffered(r, 2)()) == list(range(10))
    assert sorted(R.xmap_readers(lambda x: x + 1, r, 2, 4)()) == list(range(1, 11))
    assert list(R.xmap_readers(lambda x: x + 1, r, 2, 4, order=True)()) == list(range(1, 11))


def test_data_feeder_batches_and_pads():
    main = fluid.Program()
    with fluid.program_guard(main):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
    feeder = fluid.DataFeeder([x, y])
    feed = feeder.feed([(np.ones(4, "float32"), 3), (np.zeros(4, "float32"), 1)])
    assert feed["x"].shape == (2, 4)
    assert feed["y"].shape == (2, 1) and feed["y"].dtype == np.int64

    seq = fluid.layers.data("s", shape=[-1], dtype="int64", append_batch_size=True)
    f2 = fluid.DataFeeder([seq], pad_sequences=True, emit_masks=True)
    feed = f2.feed([(np.array([1, 2, 3]),), (np.array([5]),)])
    assert feed["s"].shape == (2, 3)
    np.testing.assert_array_equal(feed["s_mask"], [[1, 1, 1], [1, 0, 0]])


def test_metrics_accumulators():
    acc = M.Accuracy()
    acc.update(0.5, 10)
    acc.update(1.0, 10)
    assert abs(acc.eval() - 0.75) < 1e-9

    auc = M.Auc(num_thresholds=255)
    preds = np.array([[0.9, 0.1], [0.1, 0.9], [0.2, 0.8], [0.7, 0.3]])
    labels = np.array([0, 1, 1, 0])
    auc.update(preds, labels)
    assert auc.eval() == 1.0  # perfectly separable

    p = M.Precision(); p.update([1, 1, 0], [1, 0, 0])
    assert abs(p.eval() - 0.5) < 1e-9
    r = M.Recall(); r.update([1, 0, 0], [1, 1, 0])
    assert abs(r.eval() - 0.5) < 1e-9


def test_flags_env_and_nan_check(rng, monkeypatch):
    assert fluid.get_flag("check_nan_inf") is False
    fluid.set_flag("check_nan_inf", True)
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[2])
            out = fluid.layers.log(x)  # log of negative → nan
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        with pytest.raises(RuntimeError, match="check_nan_inf"):
            exe.run(main, feed={"x": np.array([[-1.0, 1.0]], "float32")},
                    fetch_list=[out])
    finally:
        fluid.set_flag("check_nan_inf", False)


def test_debugger_and_datasets(tmp_path):
    main = fluid.Program()
    with fluid.program_guard(main):
        x = fluid.layers.data("x", shape=[2])
        y = fluid.layers.fc(x, size=3)
    text = fluid.debugger.pprint_program_codes(main)
    assert "mul" in text and "var x" in text
    dot = fluid.debugger.draw_block_graphviz(main.global_block,
                                             str(tmp_path / "g.dot"))
    assert "digraph" in open(dot).read()

    ex = next(fluid.dataset.mnist.train()())
    assert ex[0].shape == (784,) and 0 <= ex[1] < 10
    ex = next(fluid.dataset.cifar.train10()())
    assert ex[0].shape == (3, 32, 32)
    ex = next(fluid.dataset.uci_housing.train()())
    assert ex[0].shape == (13,) and ex[1].shape == (1,)


def test_device_prefetcher_yields_device_arrays():
    feeds = [{"x": np.ones((2, 2), "float32") * i} for i in range(5)]
    got = list(R.DevicePrefetcher(iter(feeds), capacity=2))
    assert len(got) == 5
    import jax

    assert isinstance(got[0]["x"], jax.Array)
    np.testing.assert_array_equal(np.asarray(got[3]["x"]), feeds[3]["x"])


def test_detection_map_metric():
    """DetectionMAP vs a hand-computed single-class case."""
    from paddle_tpu.metrics import DetectionMAP

    m = DetectionMAP(overlap_threshold=0.5, ap_version="integral")
    # 2 gts; 3 detections: hit(0.9), miss(0.8), hit(0.7)
    gt = np.array([[[1, 0, 0, 10, 10], [1, 20, 20, 30, 30]]], "float32")
    det = np.array([[[1, 0.9, 0, 0, 10, 10],
                     [1, 0.8, 50, 50, 60, 60],
                     [1, 0.7, 20, 20, 30, 30],
                     [-1, -1, -1, -1, -1, -1]]], "float32")
    m.update(det, [3], gt)
    # precisions at recalls: r=.5 p=1.0; r=1.0 p=2/3 → AP = .5*1 + .5*2/3
    assert abs(m.eval() - (0.5 + 0.5 * 2 / 3)) < 1e-6
    m11 = DetectionMAP(overlap_threshold=0.5, ap_version="11point")
    m11.update(det, [3], gt)
    # max precision ≥ each recall threshold: 1.0 for t<=0.5 (6 pts), 2/3 above
    assert abs(m11.eval() - (6 * 1.0 + 5 * 2 / 3) / 11) < 1e-6


def test_nets_composites(rng):
    """fluid.nets helpers compose and run (reference: nets.py)."""
    import paddle_tpu as fluid
    from paddle_tpu import nets

    img = fluid.layers.data("img", shape=[3, 16, 16])
    seq = fluid.layers.data("seq", shape=[10, 8])
    ln = fluid.layers.data("ln", shape=[], dtype="int64")

    cp = nets.simple_img_conv_pool(img, num_filters=4, filter_size=3,
                                   pool_size=2, pool_stride=2, act="relu")
    grp = nets.img_conv_group(img, conv_num_filter=[4, 4], pool_size=2,
                              conv_act="relu", conv_with_batchnorm=True)
    sc = nets.sequence_conv_pool(seq, num_filters=6, filter_size=3, length=ln)
    g = nets.glu(fluid.layers.fc(img, size=8), dim=-1)
    att = nets.scaled_dot_product_attention(seq, seq, seq, num_heads=2)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    outs = exe.run(feed={
        "img": rng.randn(2, 3, 16, 16).astype("float32"),
        "seq": rng.randn(2, 10, 8).astype("float32"),
        "ln": np.array([10, 7], "int64"),
    }, fetch_list=[cp, grp, sc, g, att])
    assert outs[0].shape == (2, 4, 7, 7)
    assert outs[1].shape[1] == 4
    assert outs[2].shape == (2, 6)
    assert outs[3].shape == (2, 4)
    assert outs[4].shape == (2, 10, 8)
    assert all(np.isfinite(o).all() for o in outs)


def test_step_profiler_table(rng):
    import re
    import time as _t

    from paddle_tpu.profiler import StepProfiler

    prof = StepProfiler()
    for _ in range(3):
        with prof.step("train"):
            _t.sleep(0.002)
    with prof.step("eval"):
        _t.sleep(0.001)
    table = prof.summary()
    assert re.search(r"train\s+3\s+", table)
    assert re.search(r"eval\s+1\s+", table)
    assert "Ave(ms)" in table


def test_contrib_memory_usage_and_op_freq(rng):
    """contrib.memory_usage / op_freq_statistic (reference:
    contrib/memory_usage_calc.py:46, contrib/op_frequence.py)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        h = fluid.layers.fc(x, size=16, act="relu")
        y = fluid.layers.fc(h, size=4)
    lo, hi, unit = fluid.contrib.memory_usage(main, batch_size=32)
    assert unit in ("B", "KB", "MB") and 0 < lo < hi
    lo2, hi2, _ = fluid.contrib.memory_usage(main, batch_size=64)
    assert hi2 > hi  # scales with batch
    with pytest.raises(ValueError):
        fluid.contrib.memory_usage(main, batch_size=0)
    with pytest.raises(TypeError):
        fluid.contrib.memory_usage("nope", 8)

    uni, adj = fluid.contrib.op_freq_statistic(main)
    assert uni.get("mul", 0) >= 2 and uni.get("relu", 0) == 1
    assert any("->" in k for k in adj)
