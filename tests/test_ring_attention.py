"""Ring attention vs full attention parity on the 8-device CPU mesh
(forward + gradients, causal + non-causal, with dp×sp mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.ops.attention_ops import sdpa
from paddle_tpu.parallel.mesh import create_mesh
from paddle_tpu.parallel.ring_attention import ring_attention


@pytest.fixture
def qkv():
    r = np.random.RandomState(0)
    shape = (2, 2, 32, 8)  # [B, H, S, D], S divisible by sp=4
    return tuple(jnp.asarray(r.randn(*shape).astype("float32")) for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full_attention(qkv, causal):
    q, k, v = qkv
    mesh = create_mesh({"sp": 4})
    scale = q.shape[-1] ** -0.5

    want = sdpa(q, k, v, causal=causal, sm_scale=scale)

    @jax.jit
    def run(q, k, v):
        return ring_attention(q, k, v, mesh, causal=causal, sm_scale=scale)

    got = run(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4)


def test_ring_attention_grads_match(qkv):
    q, k, v = qkv
    mesh = create_mesh({"sp": 4})
    scale = q.shape[-1] ** -0.5

    def loss_full(q, k, v):
        return jnp.sum(sdpa(q, k, v, causal=True, sm_scale=scale) ** 2)

    @jax.jit
    def loss_ring_grads(q, k, v):
        def f(q, k, v):
            return jnp.sum(ring_attention(q, k, v, mesh, causal=True,
                                          sm_scale=scale) ** 2)

        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_ring = loss_ring_grads(q, k, v)
    for gf, gr in zip(g_full, g_ring):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   atol=5e-4, rtol=1e-3)


def test_ring_attention_dp_sp_mesh(qkv):
    """Combined data×sequence parallel mesh."""
    q, k, v = qkv
    mesh = create_mesh({"data": 2, "sp": 4})
    scale = q.shape[-1] ** -0.5
    want = sdpa(q, k, v, causal=False, sm_scale=scale)

    sh = NamedSharding(mesh, P("data", None, "sp", None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))

    @jax.jit
    def run(q, k, v):
        return ring_attention(q, k, v, mesh, causal=False, sm_scale=scale)

    got = run(qs, ks, vs)
    assert len(got.sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4)


def test_ring_attention_op_fallback_without_sp(qkv):
    """The graph op degrades to fused attention when no sp axis exists."""
    import paddle_tpu as fluid
    from paddle_tpu.testing import run_op

    q, k, v = (np.asarray(x) for x in qkv)
    scale = q.shape[-1] ** -0.5
    got = run_op("ring_attention", {"Q": q, "K": k, "V": v}, ["Out"],
                 attrs={"causal": True, "sm_scale": scale})["Out"]
    want = sdpa(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True,
                sm_scale=scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_ring_flash_block_gate(monkeypatch):
    """Flash blocks only on TPU, 128-aligned shards, above the crossover."""
    import importlib

    ra = importlib.import_module("paddle_tpu.parallel.ring_attention")

    q32 = jnp.zeros((1, 2, 4096, 64), jnp.float32)
    # off-TPU: never
    assert not ra._use_flash_blocks(q32, 4096)
    monkeypatch.setattr("paddle_tpu.ops.attention_ops._on_tpu", lambda: True)
    if ra._block_sizes_for(4096):
        from paddle_tpu.ops.attention_ops import _flash_fn

        if _flash_fn()[0] is not None:
            assert ra._use_flash_blocks(q32, 4096)
            assert not ra._use_flash_blocks(q32, 1024)   # below crossover
            assert not ra._use_flash_blocks(q32, 2100)   # not 128-aligned
            qi = jnp.zeros((1, 2, 4096, 64), jnp.int32)
            assert not ra._use_flash_blocks(qi, 4096)    # wrong dtype


def test_ring_blockwise_residuals_are_linear_in_s():
    """The custom VJP must not save per-step score blocks: residuals are
    (q, k, v, out, lse) only — O(S_local), not O(S_local^2)."""
    from paddle_tpu.parallel.ring_attention import _ring_blockwise_fwd

    b, h, s, d = 1, 2, 64, 16
    q = jnp.ones((b, h, s, d), jnp.float32)
    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(4), ("sp",))

    def local(q, k, v):
        return _ring_blockwise_fwd("sp", True, 0.25, False, q, k, v)

    from paddle_tpu.parallel._compat import shard_map

    out, res = shard_map(
        local, mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=(P(None, None, "sp", None),
                   (P(None, None, "sp", None),) * 4 + (P(None, None, "sp"),)))(q, q, q)
    assert out.shape == q.shape
    q_r, k_r, v_r, out_r, lse_r = res
    assert lse_r.shape == (b, h, s)          # O(S) softmax stats
    for r in (q_r, k_r, v_r, out_r):
        assert r.shape == q.shape            # no [*, S, S] buffer saved
