"""Beam search tests: single-step op vs numpy, backtrack decode vs numpy,
TensorArray ops, and a full While-loop GRU decode matching a numpy beam
search on identical weights.

Reference tests: operators/beam_search_op_test.cc,
beam_search_decode_op_test.cc, test_beam_search_op.py.
"""

import numpy as np
import pytest

import paddle_tpu as fluid


def np_beam_step(pre_ids, pre_scores, logp, end_id):
    B, K, V = logp.shape
    total = pre_scores[..., None] + logp
    for b in range(B):
        for k in range(K):
            if pre_ids[b, k] == end_id:
                total[b, k, :] = -1e9
                total[b, k, end_id] = pre_scores[b, k]
    flat = total.reshape(B, K * V)
    idx = np.argsort(-flat, axis=1)[:, :K]
    scores = np.take_along_axis(flat, idx, axis=1)
    return (idx % V).astype("int64"), scores, (idx // V).astype("int64")


def test_beam_search_op_matches_numpy(rng):
    B, K, V = 2, 3, 7
    pre_ids_np = np.array([[1, 2, 0], [4, 0, 5]], "int64")  # some finished (0)
    pre_scores_np = rng.randn(B, K).astype("float32")
    logp_np = np.log(rng.dirichlet(np.ones(V), size=(B, K)).astype("float32"))

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        pre_ids = fluid.layers.data("pre_ids", [B, K], "int64",
                                    append_batch_size=False)
        pre_scores = fluid.layers.data("pre_scores", [B, K], "float32",
                                       append_batch_size=False)
        logp = fluid.layers.data("logp", [B, K, V], "float32",
                                 append_batch_size=False)
        sid, ssc, par = fluid.layers.beam_search(
            pre_ids, pre_scores, None, logp, beam_size=K, end_id=0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got_ids, got_scores, got_par = exe.run(
        main, feed={"pre_ids": pre_ids_np, "pre_scores": pre_scores_np,
                    "logp": logp_np}, fetch_list=[sid, ssc, par])
    ref_ids, ref_scores, ref_par = np_beam_step(
        pre_ids_np, pre_scores_np.astype("float64"), logp_np.astype("float64"), 0)
    np.testing.assert_allclose(got_scores, ref_scores, rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(got_ids, ref_ids)
    np.testing.assert_array_equal(got_par, ref_par)


def test_tensor_array_write_read_length(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [2, 3], append_batch_size=False)
        i0 = fluid.layers.fill_constant([1], "int64", 0)
        i1 = fluid.layers.fill_constant([1], "int64", 1)
        arr = fluid.layers.array_write(x, i0, capacity=4)
        arr = fluid.layers.array_write(x * 2.0, i1, array=arr)
        r0 = fluid.layers.array_read(arr, i0)
        r1 = fluid.layers.array_read(arr, i1)
        n = fluid.layers.array_length(arr)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x_np = rng.randn(2, 3).astype("float32")
    r0v, r1v, nv = exe.run(main, feed={"x": x_np}, fetch_list=[r0, r1, n])
    np.testing.assert_allclose(r0v, x_np, rtol=1e-6)
    np.testing.assert_allclose(r1v, 2 * x_np, rtol=1e-6)
    assert nv[0] == 2


def np_full_beam_search(emb, w_in, b_in, w_gru, w_out, b_out, B, K, bos,
                        end_id, max_len):
    """Greedy numpy GRU-cell beam search mirroring the program in
    test_while_loop_beam_decode (origin_mode=False gates [u|r|c])."""
    V, E = emb.shape
    H = w_gru.shape[0]

    def sigmoid(v):
        return 1 / (1 + np.exp(-v))

    pre_ids = np.full((B, K), bos, "int64")
    pre_scores = np.tile(np.array([0.0] + [-1e9] * (K - 1)), (B, 1))
    state = np.zeros((B * K, H))
    ids_hist, par_hist = [], []
    for _t in range(max_len):
        x = emb[pre_ids.reshape(-1)] @ w_in + b_in  # [B*K, 3H]
        h_prev = state
        ur = sigmoid(x[:, :2 * H] + h_prev @ w_gru[:, :2 * H])
        u, r = ur[:, :H], ur[:, H:]
        c = np.tanh(x[:, 2 * H:] + (r * h_prev) @ w_gru[:, 2 * H:])
        h = u * c + (1 - u) * h_prev
        logits = h @ w_out + b_out
        logp = logits - np.log(np.exp(logits - logits.max(-1, keepdims=True)
                                      ).sum(-1, keepdims=True)) - logits.max(-1, keepdims=True)
        sid, ssc, par = np_beam_step(pre_ids, pre_scores, logp.reshape(B, K, -1),
                                     end_id)
        ids_hist.append(sid)
        par_hist.append(par)
        state = h.reshape(B, K, H)[np.arange(B)[:, None], par].reshape(B * K, H)
        pre_ids, pre_scores = sid, ssc
    # backtrack
    T = max_len
    seqs = np.zeros((B, K, T), "int64")
    cur = np.tile(np.arange(K), (B, 1))
    for t in range(T - 1, -1, -1):
        seqs[:, :, t] = ids_hist[t][np.arange(B)[:, None], cur]
        cur = par_hist[t][np.arange(B)[:, None], cur]
    return seqs, pre_scores


def test_while_loop_beam_decode_matches_numpy(rng):
    """Full decode loop: While + beam_search + TensorArrays on a tiny GRU LM,
    exact match against the numpy reference using identical weights."""
    B, K, V, E, H, max_len = 2, 3, 11, 6, 8, 5
    bos, eos = 1, 0

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        pre_ids = fluid.layers.assign(np.full((B, K), bos, "int64"))
        pre_scores = fluid.layers.assign(
            np.tile(np.array([0.0] + [-1e9] * (K - 1), "float32"), (B, 1)))
        state = fluid.layers.assign(np.zeros((B * K, H), "float32"))
        offset = fluid.layers.assign(
            (np.arange(B)[:, None] * K).astype("int64"))  # [B,1]

        i = fluid.layers.fill_constant([1], "int64", 0)
        n = fluid.layers.fill_constant([1], "int64", max_len)
        zero = fluid.layers.fill_constant([1], "int64", 0)
        ids_arr = fluid.layers.array_write(
            fluid.layers.assign(np.zeros((B, K), "int64")), zero,
            capacity=max_len)
        par_arr = fluid.layers.array_write(
            fluid.layers.assign(np.zeros((B, K), "int64")), zero,
            capacity=max_len)
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond)
        with w.block():
            emb = fluid.layers.embedding(
                pre_ids, size=[V, E],
                param_attr=fluid.ParamAttr(name="emb_w"))
            emb_flat = fluid.layers.reshape(emb, [B * K, E])
            gates = fluid.layers.fc(
                emb_flat, size=3 * H,
                param_attr=fluid.ParamAttr(name="in_w"),
                bias_attr=fluid.ParamAttr(name="in_b"))
            h, _, _ = fluid.layers.gru_unit(
                gates, state, size=3 * H,
                param_attr=fluid.ParamAttr(name="gru_w"),
                bias_attr=fluid.ParamAttr(name="gru_b",
                                          initializer=fluid.initializer.Constant(0.0)))
            logits = fluid.layers.fc(
                h, size=V, param_attr=fluid.ParamAttr(name="out_w"),
                bias_attr=fluid.ParamAttr(name="out_b"))
            logp = fluid.layers.reshape(
                fluid.layers.log_softmax(logits), [B, K, V])
            sid, ssc, par = fluid.layers.beam_search(
                pre_ids, pre_scores, None, logp, beam_size=K, end_id=eos)
            flat_par = fluid.layers.reshape(
                fluid.layers.elementwise_add(par, offset), [B * K, 1])
            new_state = fluid.layers.gather(h, flat_par)
            fluid.layers.array_write(sid, i, array=ids_arr)
            fluid.layers.array_write(par, i, array=par_arr)
            fluid.layers.assign(sid, pre_ids)
            fluid.layers.assign(ssc, pre_scores)
            fluid.layers.assign(new_state, state)
            fluid.layers.increment(i, in_place=True)
            fluid.layers.less_than(i, n, cond=cond)
        sent_ids, sent_scores = fluid.layers.beam_search_decode(
            ids_arr, pre_scores, beam_size=K, end_id=eos, parents=par_arr)

    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        got_ids, got_scores = exe.run(main, feed={},
                                      fetch_list=[sent_ids, sent_scores])
        g = fluid.global_scope()
        emb_w = np.asarray(g.find_var("emb_w")).astype("float64")
        in_w = np.asarray(g.find_var("in_w")).astype("float64")
        in_b = np.asarray(g.find_var("in_b")).astype("float64").reshape(-1)
        gru_w = np.asarray(g.find_var("gru_w")).astype("float64")
        out_w = np.asarray(g.find_var("out_w")).astype("float64")
        out_b = np.asarray(g.find_var("out_b")).astype("float64").reshape(-1)
    ref_ids, ref_scores = np_full_beam_search(
        emb_w, in_w, in_b, gru_w, out_w, out_b, B, K, bos, eos, max_len)
    np.testing.assert_array_equal(got_ids, ref_ids)
    np.testing.assert_allclose(got_scores, ref_scores, rtol=1e-4, atol=1e-4)
