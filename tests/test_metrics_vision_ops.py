"""Tests for metric ops (edit_distance vs python Levenshtein, chunk_eval vs
a hand-built IOB case, precision_recall vs sklearn-style numpy math), the
vision tail (spp/unpool/grid_sampler/psroi_pool), and host ops
(print/py_func)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def _run(fetch, feed):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feed, fetch_list=fetch if isinstance(fetch, list) else [fetch])


# -- edit_distance ------------------------------------------------------------


def _lev(a, b):
    d = np.arange(len(b) + 1, dtype=float)
    for i, ca in enumerate(a):
        prev = d.copy()
        d[0] = i + 1
        for j, cb in enumerate(b):
            d[j + 1] = min(prev[j + 1] + 1, d[j] + 1, prev[j] + (ca != cb))
    return d[len(b)]


def test_edit_distance_matches_python(rng):
    b, lh, lr = 4, 7, 6
    hyps = rng.randint(1, 5, (b, lh)).astype("int64")
    refs = rng.randint(1, 5, (b, lr)).astype("int64")
    hl = np.array([7, 5, 3, 0], "int64")
    rl = np.array([6, 6, 2, 4], "int64")
    h = fluid.layers.data("h", shape=[lh], dtype="int64")
    r = fluid.layers.data("r", shape=[lr], dtype="int64")
    hlv = fluid.layers.data("hl", shape=[], dtype="int64")
    rlv = fluid.layers.data("rl", shape=[], dtype="int64")
    out, seq_num = fluid.layers.edit_distance(
        h, r, normalized=False, input_length=hlv, label_length=rlv)
    got, n = _run([out, seq_num], {"h": hyps, "r": refs, "hl": hl, "rl": rl})
    exp = [_lev(hyps[i, :hl[i]].tolist(), refs[i, :rl[i]].tolist()) for i in range(b)]
    np.testing.assert_allclose(got[:, 0], exp)
    assert int(n[0]) == b


# -- chunk_eval ---------------------------------------------------------------


def test_chunk_eval_iob(rng):
    # IOB, 2 chunk types. tags: B=0 I=1 → label = type*2 + tag; O = 2*2=4
    # label:  [B0 I0 O  B1 I1 I1 O  B0]  → chunks: (0,1,t0), (3,5,t1), (7,7,t0)
    # infer:  [B0 I0 O  B1 O  I1 O  B0]  → chunks: (0,1,t0), (3,3,t1), (5,5,t1), (7,7,t0)
    lab = np.array([[0, 1, 4, 2, 3, 3, 4, 0]], "int64")
    inf = np.array([[0, 1, 4, 2, 4, 3, 4, 0]], "int64")
    iv = fluid.layers.data("i", shape=[8], dtype="int64")
    lv = fluid.layers.data("l", shape=[8], dtype="int64")
    p, r, f1, ni, nl, nc = fluid.layers.chunk_eval(
        iv, lv, chunk_scheme="IOB", num_chunk_types=2)
    pv, rv, fv, niv, nlv, ncv = _run([p, r, f1, ni, nl, nc], {"i": inf, "l": lab})
    assert int(niv[0]) == 4 and int(nlv[0]) == 3 and int(ncv[0]) == 2
    np.testing.assert_allclose(pv[0], 2 / 4, rtol=1e-6)
    np.testing.assert_allclose(rv[0], 2 / 3, rtol=1e-6)
    np.testing.assert_allclose(fv[0], 2 * (0.5 * 2 / 3) / (0.5 + 2 / 3), rtol=1e-6)


def test_chunk_eval_iobes_with_length(rng):
    # IOBES: B=0 I=1 E=2 S=3; 1 type → O = 4
    lab = np.array([[0, 1, 2, 4, 3, 0, 2, 0]], "int64")  # BIE O S BE (+pad)
    inf = lab.copy()
    ln = np.array([7], "int64")
    iv = fluid.layers.data("i", shape=[8], dtype="int64")
    lv = fluid.layers.data("l", shape=[8], dtype="int64")
    lnv = fluid.layers.data("ln", shape=[], dtype="int64")
    p, r, f1, ni, nl, nc = fluid.layers.chunk_eval(
        iv, lv, chunk_scheme="IOBES", num_chunk_types=1, seq_length=lnv)
    pv, rv, fv, niv, nlv, ncv = _run([p, r, f1, ni, nl, nc],
                                     {"i": inf, "l": lab, "ln": ln})
    # chunks in first 7: BIE(0-2), S(4), BE(5-6) = 3; perfect match
    assert int(niv[0]) == 3 and int(nlv[0]) == 3 and int(ncv[0]) == 3
    assert pv[0] == 1.0 and rv[0] == 1.0 and fv[0] == 1.0


# -- precision_recall ---------------------------------------------------------


def test_precision_recall_op(rng):
    c, b = 3, 12
    idx = rng.randint(0, c, (b, 1)).astype("int64")
    lab = rng.randint(0, c, (b, 1)).astype("int64")
    iv = fluid.layers.data("i", shape=[1], dtype="int64")
    lv = fluid.layers.data("l", shape=[1], dtype="int64")
    helper = fluid.layers.nn.LayerHelper("pr")
    bm = helper.create_variable_for_type_inference("float32")
    am = helper.create_variable_for_type_inference("float32")
    st = helper.create_variable_for_type_inference("float32")
    helper.append_op("precision_recall", inputs={"Indices": iv, "Labels": lv},
                     outputs={"BatchMetrics": bm, "AccumMetrics": am,
                              "AccumStatesInfo": st},
                     attrs={"class_number": c})
    bmv, stv = _run([bm, st], {"i": idx, "l": lab})[0:2]

    # numpy reference
    tp = np.array([np.sum((idx[:, 0] == k) & (lab[:, 0] == k)) for k in range(c)], float)
    fp = np.array([np.sum((idx[:, 0] == k) & (lab[:, 0] != k)) for k in range(c)], float)
    fn = np.array([np.sum((idx[:, 0] != k) & (lab[:, 0] == k)) for k in range(c)], float)
    prec = np.where(tp + fp > 0, tp / np.maximum(tp + fp, 1e-12), 0)
    rec = np.where(tp + fn > 0, tp / np.maximum(tp + fn, 1e-12), 0)
    np.testing.assert_allclose(bmv[0], prec.mean(), rtol=1e-5)
    np.testing.assert_allclose(bmv[1], rec.mean(), rtol=1e-5)
    micro_p = tp.sum() / max(tp.sum() + fp.sum(), 1e-12)
    np.testing.assert_allclose(bmv[3], micro_p, rtol=1e-5)
    np.testing.assert_allclose(stv[:, 0], tp)


# -- vision tail --------------------------------------------------------------


def test_spp_shapes_and_values(rng):
    x_np = rng.randn(2, 3, 8, 8).astype("float32")
    x = fluid.layers.data("x", shape=[3, 8, 8])
    out = fluid.layers.spp(x, pyramid_height=2, pool_type="max")
    o, = _run(out, {"x": x_np})
    assert o.shape == (2, 3 * (1 + 4))
    np.testing.assert_allclose(o[:, :3], x_np.max(axis=(2, 3)), rtol=1e-6)
    np.testing.assert_allclose(o[0, 3], x_np[0, 0, :4, :4].max(), rtol=1e-6)


def test_max_pool_with_index_and_unpool_roundtrip(rng):
    x_np = rng.randn(1, 2, 4, 4).astype("float32")
    x = fluid.layers.data("x", shape=[2, 4, 4])
    out, mask = fluid.layers.max_pool2d_with_index(x, ksize=[2, 2])
    restored = fluid.layers.unpool(out, mask, ksize=[2, 2])
    o, m, u = _run([out, mask, restored], {"x": x_np})
    np.testing.assert_allclose(o[0, 0], x_np[0, 0].reshape(2, 2, 2, 2).max(axis=(1, 3)))
    # unpool scatters each max back to its original position
    assert u.shape == x_np.shape
    for ci in range(2):
        for i in range(2):
            for j in range(2):
                flat = m[0, ci, i, j]
                assert u[0, ci].reshape(-1)[flat] == o[0, ci, i, j]
    assert np.count_nonzero(u) <= 8


def test_grid_sampler_identity(rng):
    n, c, h, w = 1, 2, 5, 5
    x_np = rng.randn(n, c, h, w).astype("float32")
    ys, xs = np.meshgrid(np.linspace(-1, 1, h), np.linspace(-1, 1, w), indexing="ij")
    grid_np = np.stack([xs, ys], -1)[None].astype("float32")
    x = fluid.layers.data("x", shape=[c, h, w])
    g = fluid.layers.data("g", shape=[h, w, 2])
    out = fluid.layers.grid_sampler(x, g)
    o, = _run(out, {"x": x_np, "g": grid_np})
    np.testing.assert_allclose(o, x_np, rtol=1e-5, atol=1e-5)


def test_psroi_pool_shapes(rng):
    oc, ph, pw = 3, 2, 2
    x_np = rng.randn(1, oc * ph * pw, 8, 8).astype("float32")
    rois_np = np.array([[0.0, 0.0, 7.0, 7.0]], "float32")
    x = fluid.layers.data("x", shape=[oc * ph * pw, 8, 8])
    r = fluid.layers.data("r", shape=[4])
    out = fluid.layers.psroi_pool(x, r, oc, 1.0, ph, pw)
    o, = _run(out, {"x": x_np, "r": rois_np})
    assert o.shape == (1, oc, ph, pw)
    # bin (0,0) of output channel 0 averages channel 0 over the top-left
    np.testing.assert_allclose(o[0, 0, 0, 0], x_np[0, 0, :4, :4].mean(), rtol=1e-5)


# -- host ops -----------------------------------------------------------------


def test_print_op_passthrough(rng, capfd):
    x_np = rng.randn(2, 3).astype("float32")
    x = fluid.layers.data("x", shape=[3])
    out = fluid.layers.Print(x, message="dbg:", summarize=3)
    y = fluid.layers.scale(out, scale=2.0)
    o, = _run(y, {"x": x_np})
    np.testing.assert_allclose(o, x_np * 2, rtol=1e-6)


def test_py_func_forward_and_backward(rng):
    x_np = rng.randn(4, 3).astype("float32")

    def forward(a):
        return np.tanh(a)

    def backward(a, g):
        return g * (1 - np.tanh(a) ** 2)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3], stop_gradient=False)
        helper = fluid.layers.nn.LayerHelper("pf")
        out = helper.create_variable_for_type_inference("float32")
        out.shape = (4, 3)
        fluid.layers.py_func(forward, x, out, backward_func=backward)
        loss = fluid.layers.mean(out)
        grads = fluid.backward.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    o, = exe.run(main, feed={"x": x_np}, fetch_list=[out])
    np.testing.assert_allclose(o, np.tanh(x_np), rtol=1e-5)
