"""Mesh-sharded CTR embedding path (CPU 8-device dryrun).

The parameter-server replacement at DeepFM scale: [V, D] tables + their
Adam moments row-sharded over ``model`` (parallel.sharded_embedding
``is_sparse=True``), gradients rows-only per shard through
``core.sparse.sharded_rows_update`` (replicated exchange by default, the
explicit ``all_to_all`` id exchange behind FLAGS_ctr_alltoall_update), and
shard-by-shard table init (ops/tensor_ops._run_init) — the mechanism that
lets V=1e8 instantiate where the single-device fill RESOURCE_EXHAUSTs
(BENCH_r05).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import parallel
from paddle_tpu.flags import set_flag

V, D, F = 64, 8, 4
MESH_AXES = {"data": 2, "model": 4}


@pytest.fixture(autouse=True)
def _restore_flags():
    yield
    set_flag("ctr_alltoall_update", False)


def _build_deepfm(sharding_axis):
    from paddle_tpu.core import unique_name
    from paddle_tpu.models import deepfm as dfm

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with unique_name.guard(), fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[F], dtype="int64")
        dense = fluid.layers.data("dense", shape=[3])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        _, loss, _ = dfm.deepfm(ids, dense, label, sparse_feature_dim=V,
                                embedding_size=D, num_fields=F,
                                layer_sizes=(16,), is_sparse=True,
                                sharding_axis=sharding_axis)
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def _feed(rng):
    return {"ids": rng.randint(0, V, (16, F)).astype("int64"),
            "dense": rng.rand(16, 3).astype("float32"),
            "label": rng.randint(0, 2, (16, 1)).astype("int64")}


def _run(sharding_axis, feed, steps=3):
    main, startup, loss = _build_deepfm(sharding_axis)
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        if sharding_axis:
            mesh = parallel.create_mesh(dict(MESH_AXES))
            with parallel.mesh_guard(mesh):
                exe.run(startup)
            prog = fluid.CompiledProgram(main).with_mesh(
                dict(MESH_AXES), loss_name=loss.name)
        else:
            exe.run(startup)
            prog = main
        losses = [float(exe.run(prog, feed=feed, fetch_list=[loss])[0])
                  for _ in range(steps)]
        vars_ = dict(scope.vars)
    return losses, vars_


def test_sharded_deepfm_loss_parity(rng):
    """Sharded tables + shard-local rows-only Adam == single device, and
    param + both moments live at V/n rows per device."""
    feed = _feed(rng)
    single, _ = _run(None, feed)
    shard, svars = _run("model", feed)
    np.testing.assert_allclose(single, shard, rtol=1e-4, atol=1e-5)
    checked = 0
    for n, v in svars.items():
        if getattr(v, "shape", None) == (V, D) or (
                "sparse_emb" in n and hasattr(v, "sharding")):
            if not hasattr(v, "sharding") or v.ndim != 2:
                continue
            assert v.sharding.shard_shape(v.shape)[0] == v.shape[0] // 4, n
            checked += 1
    # table + moment1 + moment2 for both emb and w1
    assert checked >= 3, sorted(svars)


def test_sharded_deepfm_alltoall_parity(rng):
    """FLAGS_ctr_alltoall_update: the explicit PS-style all_to_all id/row
    exchange produces the same training trajectory."""
    feed = _feed(rng)
    single, _ = _run(None, feed)
    set_flag("ctr_alltoall_update", True)
    shard, _ = _run("model", feed)
    np.testing.assert_allclose(single, shard, rtol=1e-4, atol=1e-5)


def test_sharded_update_through_kernel_parity(rng):
    """The two tentpole halves compose: with the kernel gate on, the
    sharded branch runs the row-DMA kernel per shard inside shard_map on
    the local [V/n, D] slices — trajectory must still match single-device."""
    from paddle_tpu.flags import set_flag as _set

    feed = _feed(rng)
    single, _ = _run(None, feed)
    _set("sparse_update_kernel", "interpret")
    try:
        shard, _ = _run("model", feed)
    finally:
        _set("sparse_update_kernel", "auto")
    np.testing.assert_allclose(single, shard, rtol=1e-4, atol=1e-5)


def test_route_rows_to_shards_exact(rng):
    """Unit test of the all_to_all router: scatter-add through the routed
    (ids, rows) == global scatter-add, nothing dropped."""
    from paddle_tpu.core.sparse import sharded_rows_update

    n_dev = 4
    mesh = jax.sharding.Mesh(np.asarray(jax.devices()[:n_dev]), ("model",))
    vocab, dim, n = 32, 4, 16
    ids = jnp.asarray(rng.randint(0, vocab, (n,)).astype(np.int32))
    rows = jnp.asarray(rng.randn(n, dim).astype(np.float32))
    # globally-merged unique ids are the contract (duplicates merged first)
    from paddle_tpu.core.sparse import merge_rows

    uniq, merged = merge_rows(ids, rows, vocab)
    table = jnp.zeros((vocab, dim), jnp.float32)

    def upd(tabs, lid, rows_l):
        (t,) = tabs
        return (t.at[lid].add(rows_l),)

    for alltoall in (False, True):
        (out,) = sharded_rows_update((table,), uniq, merged, upd, mesh,
                                     "model", alltoall=alltoall)
        ref = table.at[ids].add(rows)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)


def test_shard_by_shard_init_bit_identical(rng):
    """The annotated startup init under mesh_guard materializes per-shard
    and must equal the unsharded init bit-for-bit (partitionable threefry:
    the random stream is sharding-invariant)."""
    from paddle_tpu.core import unique_name

    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        with unique_name.guard(), fluid.program_guard(main, startup):
            ids = fluid.layers.data("ids", shape=[2], dtype="int64")
            parallel.sharded_embedding(ids, size=[V, D], is_sparse=True)
        return startup

    scope1 = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope1):
        exe.run(build())
        name = list(scope1.vars)[0]
        t1 = np.asarray(scope1.find_var(name))

    scope2 = fluid.core.Scope()
    mesh = parallel.create_mesh(dict(MESH_AXES))
    with fluid.scope_guard(scope2):
        with parallel.mesh_guard(mesh):
            fluid.Executor(fluid.CPUPlace()).run(build())
        tv = scope2.find_var(list(scope2.vars)[0])
        assert tv.sharding.shard_shape(tv.shape)[0] == V // 4
        t2 = np.asarray(tv)
    np.testing.assert_array_equal(t1, t2)


def test_oom_hint_names_the_escape_hatches():
    """RESOURCE_EXHAUSTED during a fill_constant init must come back as an
    EnforceNotMet naming the requested bytes and the is_sparse /
    sharded_embedding fixes (the BENCH_r05 V=1e8 failure mode)."""
    from paddle_tpu.core import unique_name
    from paddle_tpu.core.enforce import wrap_op_error

    main, startup = fluid.Program(), fluid.Program()
    with unique_name.guard(), fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[2], dtype="int64")
        fluid.layers.embedding(ids, size=[int(1e8), 10], is_sparse=True)
    fill = next(op for op in startup.global_block.ops
                if op.type in ("fill_constant", "uniform_random",
                               "gaussian_random",
                               "truncated_gaussian_random"))
    err = wrap_op_error(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying to "
                     "allocate 4000000000 bytes."), fill, 0)
    msg = str(err)
    assert "4.00 GB" in msg, msg
    assert "is_sparse=True" in msg
    assert "sharded_embedding" in msg
    # a non-OOM failure stays hint-free
    assert "hint:" not in str(wrap_op_error(ValueError("bad"), fill, 0))
