"""Sparse-gradient (SelectedRows-equivalent) tests.

Reference contract: ``framework/selected_rows.h:32`` — an embedding gradient
is (rows, value-block), and sparse optimizer kernels
(``operators/optimizers/sgd_op.h`` SelectedRows branch, ``adam_op.h`` lazy
mode) update only the touched rows. The TPU-native encoding is
``core/sparse.py``'s (ids, rows) pair threaded through jax.grad as "virtual
rows", so the O(V*D) dense scatter-add never exists in the XLA graph.

The scale test asserts that structurally: with vocab V=100k the compiled
training step's total FLOPs stay far below one full-table elementwise pass
(V*D), while the dense path pays >= 2*V*D just in the SGD update.
"""

import jax
import numpy as np
import pytest

import paddle_tpu as fluid


def _build(vocab, dim, is_sparse, optimizer):
    from paddle_tpu.core import unique_name

    main, startup = fluid.Program(), fluid.Program()
    with unique_name.guard(), fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[1], dtype="int64")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(ids, size=[vocab, dim], is_sparse=is_sparse)
        logits = fluid.layers.fc(emb, size=2)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        optimizer().minimize(loss)
    return main, startup, loss


def _batch(rng, vocab, n=32):
    ids = rng.randint(0, vocab, size=(n, 1)).astype("int64")
    # duplicates exercise merge_rows' duplicate-id accumulation
    ids[: n // 4] = ids[n // 4 : n // 2]
    label = (ids % 2).astype("int64")
    return {"ids": ids, "label": label}


def _step_flops(exe, feed):
    """Total FLOPs of the last-compiled training step, via XLA cost analysis."""
    compiled = list(exe._cache.values())[-1]
    scope = fluid.global_scope()
    state = {
        n: scope.find_var(n)
        for n in compiled.state_names
        if scope.find_var(n) is not None
    }
    feeds = {k: np.asarray(v) for k, v in feed.items()}
    cost = compiled.fn.lower(state, feeds, np.uint32(0)).compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    return float(cost["flops"])


def test_sparse_sgd_matches_dense_exactly(rng):
    """Row-wise SGD on merged duplicate ids is exact => identical params."""
    vocab, dim = 1000, 16
    results = {}
    for is_sparse in (False, True):
        main, startup, loss = _build(
            vocab, dim, is_sparse, lambda: fluid.optimizer.SGD(learning_rate=0.5))
        main.random_seed = startup.random_seed = 7
        scope = fluid.core.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
            r = np.random.RandomState(0)
            losses = []
            for _ in range(4):
                (l,) = exe.run(main, feed=_batch(r, vocab), fetch_list=[loss])
                losses.append(float(l))
            params = {
                n: np.asarray(scope.find_var(n))
                for n in sorted(s.name for s in main.list_vars() if s.persistable)
                if scope.find_var(n) is not None and "learning_rate" not in n
            }
        results[is_sparse] = (losses, params)

    l_dense, p_dense = results[False]
    l_sparse, p_sparse = results[True]
    np.testing.assert_allclose(l_dense, l_sparse, rtol=1e-5)
    assert set(p_dense) == set(p_sparse)
    for n in p_dense:
        np.testing.assert_allclose(p_dense[n], p_sparse[n], rtol=2e-5, atol=1e-6)


def test_sparse_lazy_adam_trains(rng):
    """Lazy-mode Adam (rows-only moment updates) still learns the task."""
    vocab, dim = 5000, 16
    main, startup, loss = _build(
        vocab, dim, True, lambda: fluid.optimizer.Adam(learning_rate=0.05))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    r = np.random.RandomState(1)
    feed = _batch(r, vocab, n=128)  # fixed batch — learnable
    losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0]) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5, losses


@pytest.mark.parametrize("opt", ["sgd", "adam"])
def test_sparse_grad_never_densifies_at_scale(opt):
    """V=100k: the whole step must cost far less than one dense table pass.

    Dense mode pays >= 2*V*D FLOPs in the elementwise update alone (more for
    adam's moments); the sparse path touches only the N looked-up rows, so
    total step FLOPs stay well under V*D. This is the jaxpr/HLO-level proof
    that no full-table scatter/elementwise ever materializes.
    """
    vocab, dim = 100_000, 64
    table_pass = vocab * dim  # FLOPs of ONE elementwise pass over the table
    make = {
        "sgd": lambda: fluid.optimizer.SGD(learning_rate=0.1),
        "adam": lambda: fluid.optimizer.Adam(learning_rate=1e-3),
    }[opt]
    flops = {}
    for is_sparse in (True, False):
        main, startup, loss = _build(vocab, dim, is_sparse, make)
        scope = fluid.core.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        r = np.random.RandomState(2)
        feed = _batch(r, vocab)
        with fluid.scope_guard(scope):
            exe.run(startup)
            exe.run(main, feed=feed, fetch_list=[loss])
            flops[is_sparse] = _step_flops(exe, feed)
    assert flops[True] < table_pass, (
        "sparse step cost %.0f >= one table pass %.0f — grad densified"
        % (flops[True], table_pass))
    assert flops[False] > table_pass, (
        "dense yardstick unexpectedly cheap (%.0f)" % flops[False])
    assert flops[True] < flops[False] / 4
