"""fluid.gradients / calc_gradient (reference: backward.py:613).

Covers the VERDICT round-2 gap: arbitrary targets/inputs, target_gradients
seeding, no_grad_set, multiple calls per program (GAN two-loss), and the
double-grad idiom (gradients of gradients).
"""

import numpy as np

import paddle_tpu as fluid


def _run(main, startup, feed, fetch):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=fetch)


def test_gradients_wrt_feed_var(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.reduce_sum(fluid.layers.square(x))
        (gx,) = fluid.gradients(y, x)
    xs = rng.randn(3, 4).astype("float32")
    (g,) = _run(main, startup, {"x": xs}, [gx])
    np.testing.assert_allclose(g, 2 * xs, rtol=1e-5)


def test_gradients_of_intermediate_cuts_graph(rng):
    # d y / d h treats h as an independent leaf: dy/dh = 2h, regardless of
    # h's own producer (h = 3x).
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        h = fluid.layers.scale(x, scale=3.0)
        y = fluid.layers.reduce_sum(fluid.layers.square(h))
        (gh,) = fluid.gradients(y, h)
    xs = rng.randn(2, 4).astype("float32")
    (g,) = _run(main, startup, {"x": xs}, [gh])
    np.testing.assert_allclose(g, 2 * 3.0 * xs, rtol=1e-5)


def test_gradients_wrt_parameter(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        out = fluid.layers.fc(x, size=2, bias_attr=False)
        loss = fluid.layers.reduce_sum(out)
        w = main.all_parameters()[0]
        (gw,) = fluid.gradients(loss, w)
    xs = rng.randn(5, 4).astype("float32")
    (g,) = _run(main, startup, {"x": xs}, [gw])
    # d sum(x @ W) / d W = sum_rows(x) broadcast over output cols
    expect = np.tile(xs.sum(0, keepdims=True).T, (1, 2))
    np.testing.assert_allclose(g, expect, rtol=1e-4)


def test_target_gradients_seed(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        seed = fluid.layers.data("seed", shape=[4])
        y = fluid.layers.square(x)  # elementwise target, same shape as seed
        (gx,) = fluid.gradients(y, x, target_gradients=seed)
    xs = rng.randn(2, 4).astype("float32")
    ss = rng.randn(2, 4).astype("float32")
    (g,) = _run(main, startup, {"x": xs, "seed": ss}, [gx])
    np.testing.assert_allclose(g, 2 * xs * ss, rtol=1e-5)


def test_no_grad_set_blocks_flow(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        a = fluid.layers.scale(x, scale=2.0)  # path 1 (blocked)
        b = fluid.layers.scale(x, scale=5.0)  # path 2
        y = fluid.layers.reduce_sum(a + b)
        (gx,) = fluid.gradients(y, x, no_grad_set={a.name})
    xs = rng.randn(2, 4).astype("float32")
    (g,) = _run(main, startup, {"x": xs}, [gx])
    np.testing.assert_allclose(g, np.full_like(xs, 5.0), rtol=1e-5)


def test_two_losses_gan_style(rng):
    # Two independent gradients() calls on one program — per-loss grads of a
    # shared input, as a GAN script computes d/g losses wrt shared fakes.
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        loss_a = fluid.layers.reduce_sum(fluid.layers.square(x))
        (ga,) = fluid.gradients(loss_a, x)
        loss_b = fluid.layers.reduce_sum(fluid.layers.scale(x, scale=7.0))
        (gb,) = fluid.gradients(loss_b, x)
        assert ga.name != gb.name  # second call must not collide on x@GRAD
    xs = rng.randn(3, 4).astype("float32")
    a, b = _run(main, startup, {"x": xs}, [ga, gb])
    np.testing.assert_allclose(a, 2 * xs, rtol=1e-5)
    np.testing.assert_allclose(b, np.full_like(xs, 7.0), rtol=1e-5)


def test_double_grad(rng):
    # y = sum(x^3); g = dy/dx = 3x^2; z = sum(g^2) = sum(9 x^4);
    # dz/dx = 36 x^3 — the WGAN-GP gradient-penalty idiom.
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.reduce_sum(fluid.layers.pow(x, factor=3.0))
        (g1,) = fluid.gradients(y, x)
        z = fluid.layers.reduce_sum(fluid.layers.square(g1))
        (g2,) = fluid.gradients(z, x)
    xs = np.abs(rng.randn(2, 4)).astype("float32") + 0.5
    (g,) = _run(main, startup, {"x": xs}, [g2])
    np.testing.assert_allclose(g, 36 * xs**3, rtol=1e-4)


def test_gradients_after_minimize(rng):
    # gradients() on a program that already built its training tail: the
    # backward slice must skip backward_marker + optimizer ops (round-3
    # review finding — this used to KeyError on the optimizer-rewritten
    # param names).
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        out = fluid.layers.fc(x, size=1, bias_attr=False)
        loss = fluid.layers.mean(fluid.layers.square(out))
        fluid.optimizer.SGD(learning_rate=0.0).minimize(loss)
        (gx,) = fluid.gradients(loss, x)
    xs = rng.randn(6, 4).astype("float32")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    w = np.asarray(fluid.global_scope().find_var(main.all_parameters()[0].name))
    (g,) = exe.run(main, feed={"x": xs}, fetch_list=[gx])
    expect = 2.0 / len(xs) * (xs @ w) @ w.T
    np.testing.assert_allclose(g, expect, rtol=1e-4)


def test_gradients_then_minimize_no_alias(rng):
    # gradients() claims W@GRAD first; append_backward must rename its own
    # grad var instead of silently overwriting the fetched one.
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        out = fluid.layers.fc(x, size=1, bias_attr=False)
        w = main.all_parameters()[0]
        aux = fluid.layers.reduce_sum(out)          # d aux / d W = sum_rows(x)
        (gw_aux,) = fluid.gradients(aux, w)
        loss = fluid.layers.mean(fluid.layers.square(out))
        fluid.optimizer.SGD(learning_rate=0.0).minimize(loss)
    xs = rng.randn(6, 4).astype("float32")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (g,) = exe.run(main, feed={"x": xs}, fetch_list=[gw_aux])
    np.testing.assert_allclose(g, xs.sum(0, keepdims=True).T, rtol=1e-4)


def test_gradients_duplicate_inputs(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.reduce_sum(fluid.layers.square(x))
        g1, g2 = fluid.gradients(y, [x, x])
        assert g1 is g2  # duplicates share one leaf/grad
    xs = rng.randn(2, 4).astype("float32")
    (g,) = _run(main, startup, {"x": xs}, [g1])
    np.testing.assert_allclose(g, 2 * xs, rtol=1e-5)


def test_gradients_int_input_rejected():
    import pytest

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        idx = fluid.layers.data("idx", shape=[1], dtype="int64")
        y = fluid.layers.reduce_sum(fluid.layers.cast(idx, "float32"))
        with pytest.raises(TypeError, match="non-differentiable"):
            fluid.gradients(y, idx)


def test_calc_gradient_alias():
    assert fluid.backward.calc_gradient is fluid.backward.gradients
