"""Flash-vs-composed block-path parity for ring attention (ADVICE r4).

The ring's flash path feeds the vendored Pallas FA2 kernels per block and
relies on the p = exp(logits - m)/l contract (passing m=lse, l=1 must yield
exact global probabilities in the backward). These tests execute the REAL
vendored kernel bodies in Pallas interpret mode on CPU and assert forward
(o, l, m) and backward (dq, dk, dv) agreement with the composed reference
on identical inputs — so a change to the vendored kernels that breaks the
contract fails CI without TPU hardware.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import importlib

from paddle_tpu.ops.pallas_kernels import flash_attention as fa

# the package re-exports the ring_attention FUNCTION under the module's name
ra = importlib.import_module("paddle_tpu.parallel.ring_attention")


@pytest.fixture(autouse=True)
def _interpret_kernels():
    fa.INTERPRET = True
    yield
    fa.INTERPRET = False


def _mk(rng, b=1, h=2, s=128, d=64, dtype=jnp.float32):
    def t():
        return jnp.asarray(rng.randn(b, h, s, d).astype("float32"), dtype)

    return t(), t(), t()


@pytest.mark.parametrize("causal", [False, True])
def test_block_fwd_flash_matches_ref(rng, causal):
    q, k, v = _mk(rng)
    o_f, l_f, m_f = ra._block_fwd_flash(q, k, v, causal, 0.25)
    o_r, l_r, m_r = ra._block_fwd_ref(q, k, v, causal, 0.25)
    np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_r),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(m_f), np.asarray(m_r),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(l_f), np.asarray(l_r),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_block_bwd_flash_matches_ref(rng, causal):
    q, k, v = _mk(rng)
    sm_scale = 0.25
    # global stats from the reference forward (the bwd contract consumes the
    # GLOBAL lse; any self-consistent source works for parity)
    o, l, m = ra._block_fwd_ref(q, k, v, causal, sm_scale)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    do = jnp.asarray(np.random.RandomState(7).randn(*q.shape), q.dtype)
    di = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)
    dq_f, dk_f, dv_f = ra._block_bwd_flash(q, k, v, lse, do, di, causal,
                                           sm_scale)
    dq_r, dk_r, dv_r = ra._block_bwd_ref(q, k, v, lse, do, di, causal,
                                         sm_scale)
    for a, b, nm in ((dq_f, dq_r, "dq"), (dk_f, dk_r, "dk"),
                     (dv_f, dv_r, "dv")):
        np.testing.assert_allclose(np.asarray(a, dtype="float32"),
                                   np.asarray(b, dtype="float32"),
                                   rtol=2e-4, atol=2e-4, err_msg=nm)


def test_vendored_kernels_are_project_owned():
    """sdpa and ring attention must import the vendored module, not JAX's."""
    import paddle_tpu.ops.attention_ops as ao

    flash, _ = ao._flash_fn()
    if flash is None:
        pytest.skip("pallas unavailable")
    assert "paddle_tpu" in flash.__module__
