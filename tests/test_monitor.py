"""paddle_tpu.monitor tests: registry semantics, span nesting + Chrome-trace
schema, executor cache-hit/miss wiring, reader queue gauges, and the
satellite fixes (vlog %-literal, profiler reset/percentiles, dump_metrics
round-trip)."""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.monitor import metrics as mx
from paddle_tpu.monitor import tracer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_registry():
    mx.enable()
    mx.reset()
    tracer.clear_spans()
    yield
    mx.enable()
    mx.reset()
    tracer.clear_spans()


# -- registry -----------------------------------------------------------------

def test_counter_gauge_histogram_basics():
    c = mx.counter("t/counter")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5

    g = mx.gauge("t/gauge")
    g.set(10)
    g.inc(5)
    g.dec(1)
    assert g.value == 14

    h = mx.histogram("t/hist", buckets=[1, 10, 100])
    for v in (0.5, 5, 50, 500):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 4
    assert snap["sum"] == 555.5
    assert snap["min"] == 0.5 and snap["max"] == 500
    assert snap["buckets"] == {"le_1": 1, "le_10": 1, "le_100": 1, "le_inf": 1}
    assert 0 < snap["p50"] <= 50
    assert snap["p95"] <= 500


def test_registry_get_or_create_and_kind_conflict():
    assert mx.counter("t/same") is mx.counter("t/same")
    with pytest.raises(TypeError):
        mx.gauge("t/same")


def test_histogram_bucket_conflict_raises():
    h = mx.histogram("t/buckets", buckets=[1, 2, 4])
    assert mx.histogram("t/buckets") is h  # no buckets = don't care
    assert mx.histogram("t/buckets", buckets=[4, 2, 1]) is h  # order-insensitive
    with pytest.raises(ValueError):
        mx.histogram("t/buckets", buckets=[1, 2, 8])


def test_log_buckets_geometry():
    import math

    b = mx.log_buckets(1e-3, 1e3, per_decade=3)
    assert b[0] == 1e-3 and b[-1] == 1e3
    assert all(y > x for x, y in zip(b, b[1:]))
    # interior bounds are geometric: adjacent ratios ~ 10^(1/3)
    for x, y in zip(b[:-2], b[1:-1]):
        assert abs(math.log10(y / x) - 1.0 / 3.0) < 0.02, (x, y)
    # one bucket per decade lands exactly on the powers of ten
    assert list(mx.log_buckets(1e-2, 1e2, per_decade=1)) == [
        0.01, 0.1, 1.0, 10.0, 100.0]
    # a hi that is not on the grid is still included as the last bound
    assert mx.log_buckets(1.0, 50.0, per_decade=1)[-1] == 50.0


def test_log_buckets_rejects_bad_ranges():
    for lo, hi in ((0.0, 1.0), (-1.0, 1.0), (1.0, 1.0), (2.0, 1.0)):
        with pytest.raises(ValueError):
            mx.log_buckets(lo, hi)
    with pytest.raises(ValueError):
        mx.log_buckets(1.0, 10.0, per_decade=0)


def test_histogram_percentile_overflow_clamps_to_top_edge():
    # every sample past the top finite bound: the percentile rank lands
    # in the +Inf overflow bucket. The histogram must answer with the
    # top finite edge (honest lower bound, same convention as the
    # telemetry-side _bucket_percentile) — NOT extrapolate toward max,
    # which used to report a fabricated value between top edge and max.
    h = mx.histogram("t/overflow", buckets=[1, 2, 4])
    for v in (50.0, 400.0, 6000.0):
        h.observe(v)
    assert h.percentile(50) == 4.0
    assert h.percentile(99) == 4.0
    assert h._overflow_warned  # one-time vlog fired

    # mixed population: ranks inside finite buckets are untouched,
    # only the overflow tail clamps
    m = mx.histogram("t/overflow_mixed", buckets=[1, 2, 4])
    for v in (0.5, 0.6, 0.7, 1000.0):
        m.observe(v)
    assert m.percentile(50) <= 1.0
    assert m.percentile(99) == 4.0

    # reset() re-arms the one-time warning with the rest of the state
    h.reset()
    assert not h._overflow_warned
    h.observe(99.0)
    assert h.percentile(99) == 4.0
    assert h._overflow_warned


def test_log_bucketed_histogram_counts():
    h = mx.histogram("t/log_hist",
                     buckets=mx.log_buckets(1e-2, 1e2, per_decade=1))
    for v in (0.005, 0.05, 5.0, 500.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 4
    assert snap["buckets"]["le_0.01"] == 1   # below lo folds into lo
    assert snap["buckets"]["le_0.1"] == 1
    assert snap["buckets"]["le_10"] == 1
    assert snap["buckets"]["le_inf"] == 1    # past hi overflows


def test_tracer_span_cap(monkeypatch):
    monkeypatch.setattr(tracer, "_max_spans", 3)
    tracer.start_tracing()
    for i in range(6):
        tracer.instant("cap/%d" % i)
    tracer.stop_tracing()
    assert len(tracer.get_spans()) == 3
    assert tracer._dropped == 3
    tracer.clear_spans()
    assert tracer._dropped == 0


def test_disabled_is_inert_and_reset_keeps_handles():
    c = mx.counter("t/toggle")
    c.inc(2)
    mx.disable()
    c.inc(100)
    mx.gauge("t/toggle_g").set(9)
    mx.histogram("t/toggle_h").observe(1)
    assert not mx.enabled()
    mx.enable()
    assert c.value == 2
    assert mx.gauge("t/toggle_g").value == 0
    assert mx.histogram("t/toggle_h").count == 0

    mx.reset()
    assert c.value == 0
    c.inc(7)  # same handle still registered and live
    assert mx.snapshot()["t/toggle"]["value"] == 7


def test_snapshot_json_and_text_roundtrip():
    mx.counter("t/js").inc(3)
    mx.histogram("t/jh").observe(2.0)
    doc = json.loads(mx.to_json())
    assert doc["t/js"]["value"] == 3
    assert doc["t/jh"]["count"] == 1
    txt = mx.to_text()
    assert "t/js" in txt and "t/jh" in txt


def test_thread_safety_under_contention():
    c = mx.counter("t/mt")
    h = mx.histogram("t/mt_h", buckets=[10])

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(1)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert h.count == 8000


# -- tracer -------------------------------------------------------------------

def test_span_nesting_and_chrome_schema():
    tracer.start_tracing()
    with tracer.span("outer", args={"k": "v"}):
        with tracer.span("inner"):
            pass
        with tracer.span("inner2"):
            pass
    spans = tracer.stop_tracing()
    by_name = {s["name"]: s for s in spans}
    assert by_name["inner"]["depth"] == by_name["outer"]["depth"] + 1
    assert by_name["inner2"]["depth"] == by_name["outer"]["depth"] + 1
    # children temporally contained in the parent
    o = by_name["outer"]
    for child in ("inner", "inner2"):
        s = by_name[child]
        assert s["ts_us"] >= o["ts_us"]
        assert s["ts_us"] + s["dur_us"] <= o["ts_us"] + o["dur_us"]

    doc = tracer.to_chrome_trace(spans)
    assert "traceEvents" in doc and isinstance(doc["traceEvents"], list)
    complete = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in complete} == {"outer", "inner", "inner2"}
    for e in complete:
        assert {"name", "cat", "ts", "dur", "pid", "tid"} <= set(e)
    assert any(e["ph"] == "M" for e in doc["traceEvents"])  # metadata present
    assert by_name["outer"]["args"] == {"k": "v"}


def test_spans_raw_file_chrome_roundtrip(tmp_path):
    tracer.start_tracing()
    with tracer.span("rt/a"):
        with tracer.span("rt/b"):
            pass
    spans = tracer.stop_tracing()
    raw = tmp_path / "spans.json"
    chrome = tmp_path / "trace.json"
    tracer.save_spans(str(raw), spans)
    assert tracer.load_spans(str(raw)) == spans
    tracer.save_chrome_trace(str(chrome), spans)
    back = tracer.load_spans(str(chrome))  # chrome -> spans round-trip
    assert {s["name"] for s in back} == {"rt/a", "rt/b"}
    assert sorted(s["dur_us"] for s in back) == sorted(s["dur_us"] for s in spans)


def test_inactive_tracer_records_nothing():
    assert not tracer.active()
    with tracer.span("ghost"):
        pass
    assert tracer.get_spans() == []


def test_trace_file_env_autostart(tmp_path):
    """PADDLE_TPU_TRACE_FILE=... writes a loadable Chrome trace at exit.
    The tracer module is stdlib-only, so the subprocess loads it standalone
    (no jax import) and stays fast."""
    out = tmp_path / "trace.json"
    code = (
        "import importlib.util\n"
        "spec = importlib.util.spec_from_file_location('t', %r)\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        "assert m.active()\n"
        "with m.span('auto/outer'):\n"
        "    with m.span('auto/inner'):\n"
        "        pass\n"
    ) % os.path.join(REPO, "paddle_tpu", "monitor", "tracer.py")
    env = dict(os.environ, PADDLE_TPU_TRACE_FILE=str(out))
    subprocess.run([sys.executable, "-c", code], check=True, env=env,
                   timeout=60)
    doc = json.loads(out.read_text())
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert names == {"auto/outer", "auto/inner"}


# -- executor wiring ----------------------------------------------------------

def _mlp_program(dim=6, classes=3):
    x = fluid.layers.data("x", shape=[dim])
    y = fluid.layers.data("y", shape=[1], dtype="int64")
    logits = fluid.layers.fc(x, size=classes)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def test_executor_cache_hit_miss_counters(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = _mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    mx.reset()
    feed8 = {"x": rng.randn(8, 6).astype("float32"),
             "y": rng.randint(0, 3, (8, 1)).astype("int64")}
    exe.run(main, feed=feed8, fetch_list=[loss])
    snap = mx.snapshot()
    assert snap["executor/cache_miss"]["value"] == 1
    assert snap["executor/cache_hit"]["value"] == 0
    assert snap["executor/compile_time_ms"]["count"] == 1

    # same feed signature -> hit, and a steady-state step-time observation
    exe.run(main, feed=feed8, fetch_list=[loss])
    snap = mx.snapshot()
    assert snap["executor/cache_hit"]["value"] == 1
    assert snap["executor/cache_miss"]["value"] == 1
    assert snap["executor/step_time_ms"]["count"] == 1
    assert snap["executor/step_time_ms"]["sum"] > 0

    # different batch shape -> new specialization -> miss
    feed16 = {"x": rng.randn(16, 6).astype("float32"),
              "y": rng.randint(0, 3, (16, 1)).astype("int64")}
    exe.run(main, feed=feed16, fetch_list=[loss])
    snap = mx.snapshot()
    assert snap["executor/cache_miss"]["value"] == 2
    assert snap["executor/cache_hit"]["value"] == 1

    assert snap["executor/runs"]["value"] == 3
    # per row: 6 f32 features + 1 label canonicalized to int32 = 28 bytes
    assert snap["executor/feed_bytes"]["value"] == (8 + 8 + 16) * (6 * 4 + 4)
    assert snap["executor/fetch_bytes"]["value"] == 3 * 4  # three f32 scalars


def test_executor_disabled_metrics_stay_zero(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = _mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    mx.reset()
    mx.disable()
    feed = {"x": rng.randn(4, 6).astype("float32"),
            "y": rng.randint(0, 3, (4, 1)).astype("int64")}
    out, = exe.run(main, feed=feed, fetch_list=[loss])
    mx.enable()
    snap = mx.snapshot()
    assert np.isfinite(out).all()  # run itself unaffected
    assert snap["executor/runs"]["value"] == 0
    assert snap["executor/cache_miss"]["value"] == 0
    assert snap["executor/step_time_ms"]["count"] == 0


def test_executor_step_spans_when_tracing(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = _mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": rng.randn(4, 6).astype("float32"),
            "y": rng.randint(0, 3, (4, 1)).astype("int64")}
    tracer.start_tracing()
    exe.run(main, feed=feed, fetch_list=[loss])
    exe.run(main, feed=feed, fetch_list=[loss])
    spans = tracer.stop_tracing()
    names = [s["name"] for s in spans]
    assert "executor/trace_setup" in names
    assert "executor/compile_and_step" in names
    assert "executor/step" in names


def test_grad_norm_gauge_opt_in(rng, monkeypatch, tmp_path):
    monkeypatch.setenv("PADDLE_TPU_GRAD_NORM", "1")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = _mlp_program()
    probe = main.global_block.var(monitor.GRAD_NORM_VAR)
    assert not probe.persistable  # a per-step probe, never model state
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    mx.reset()
    feed = {"x": rng.randn(8, 6).astype("float32"),
            "y": rng.randint(0, 3, (8, 1)).astype("int64")}
    out, = exe.run(main, feed=feed, fetch_list=[loss])
    assert out.size == 1  # the hidden extra fetch never reaches the caller
    assert mx.snapshot()["optimizer/grad_global_norm"]["value"] > 0
    # the probe must not break program caching
    exe.run(main, feed=feed, fetch_list=[loss])
    assert mx.snapshot()["executor/cache_hit"]["value"] == 1
    # ...nor checkpointing: the probe var stays out of save_persistables
    fluid.io.save_persistables(exe, str(tmp_path / "ckpt"), main)
    fluid.io.load_persistables(exe, str(tmp_path / "ckpt"), main)
    exe.run(main, feed=feed, fetch_list=[loss])


# -- reader wiring ------------------------------------------------------------

def test_py_reader_queue_depth_and_wait(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader = fluid.layers.py_reader(
            capacity=8, shapes=[[-1, 4], [-1, 1]],
            dtypes=["float32", "int64"], name="mon_reader")
        img, label = fluid.layers.read_file(reader)
        logits = fluid.layers.fc(img, size=2)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    batches = [(rng.randn(4, 4).astype("float32"),
                rng.randint(0, 2, (4, 1)).astype("int64")) for _ in range(5)]
    reader.decorate_tensor_provider(lambda: iter(batches))

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    mx.reset()
    reader.start()
    n = 0
    with pytest.raises(fluid.EOFException):
        while True:
            exe.run(main, fetch_list=[loss])
            n += 1
    reader.reset()
    assert n == 5
    snap = mx.snapshot()
    assert snap["reader/batches"]["value"] == 5
    assert snap["reader/wait_time_ms"]["count"] == 5
    assert snap["reader/queue_depth"]["set"] is True


def test_device_prefetcher_gauges(rng):
    from paddle_tpu.reader.prefetcher import DevicePrefetcher

    feeds = [{"a": rng.randn(2, 3).astype("float32")} for _ in range(4)]
    mx.reset()
    got = list(DevicePrefetcher(iter(feeds), capacity=2))
    assert len(got) == 4
    snap = mx.snapshot()
    assert snap["prefetcher/h2d_ms"]["count"] == 4
    assert snap["prefetcher/wait_time_ms"]["count"] == 5  # 4 batches + END
    assert snap["prefetcher/queue_depth"]["set"] is True


# -- step logger --------------------------------------------------------------

def test_step_logger_summary_and_lines(caplog):
    import logging

    slog = monitor.StepLogger(every_n=2, name="t")
    with caplog.at_level(logging.INFO, logger="paddle_tpu.monitor"):
        for i in range(6):
            slog.step(loss=float(10 - i), examples=32)
    assert len([r for r in caplog.records if "[t] step" in r.message]) == 3
    s = slog.summary()
    assert s["steps"] == 6
    assert s["examples"] == 6 * 32
    assert s["last_loss"] == 5.0
    assert "p50" in s["step_time_ms"] and "p95" in s["step_time_ms"]


def test_step_logger_reset_clears_pending_loss():
    slog = monitor.StepLogger(every_n=100, name="t2")
    slog.step(loss=5.0, examples=1)
    slog.reset()
    slog.step(examples=1)  # no loss observed since reset
    assert "last_loss" not in slog.summary()


def test_instant_events_survive_chrome_roundtrip(tmp_path):
    tracer.start_tracing()
    with tracer.span("ri/span"):
        tracer.instant("ri/marker", args={"n": 1})
    spans = tracer.stop_tracing()
    chrome = tmp_path / "trace.json"
    tracer.save_chrome_trace(str(chrome), spans)
    back = tracer.load_spans(str(chrome))
    assert {s["name"] for s in back} == {"ri/span", "ri/marker"}
    marker = next(s for s in back if s["name"] == "ri/marker")
    assert marker["dur_us"] == 0 and marker["args"] == {"n": 1}


# -- satellites ---------------------------------------------------------------

def test_vlog_literal_percent_and_cached_level(caplog, monkeypatch):
    import logging

    from paddle_tpu import log as plog

    plog.set_vlog_level(2)
    try:
        with caplog.at_level(logging.INFO, logger="paddle_tpu"):
            plog.vlog(1, "reached 100% of quota")  # raised ValueError before
            plog.vlog(1, "step %d of %d", 3, 7)
            plog.vlog(3, "above level — suppressed")
        assert plog.vlog_level() == 2
        # cached: changing the env alone must NOT alter the parsed level
        monkeypatch.setenv("GLOG_v", "9")
        assert plog.vlog_level() == 2
    finally:
        plog.set_vlog_level(None)
    msgs = [r.message for r in caplog.records]
    assert "[VLOG1] reached 100% of quota" in msgs
    assert "[VLOG1] step 3 of 7" in msgs
    assert not any("suppressed" in m for m in msgs)


def test_reset_profiler_clears_default_step_profiler():
    prof = fluid.profiler.default_step_profiler()
    with prof.step("warm"):
        pass
    assert "warm" in prof.summary()
    fluid.profiler.reset_profiler()
    assert "warm" not in fluid.profiler.default_step_profiler().summary()


def test_step_profiler_percentile_columns():
    prof = fluid.profiler.StepProfiler()
    for _ in range(10):
        with prof.step("s"):
            pass
    table = prof.summary()
    assert "P50(ms)" in table and "P95(ms)" in table


def test_dump_metrics_cli_roundtrip(tmp_path):
    from tools import dump_metrics

    tracer.start_tracing()
    with tracer.span("cli/a"):
        pass
    spans = tracer.stop_tracing()
    raw = tmp_path / "spans.json"
    chrome = tmp_path / "trace.json"
    tracer.save_spans(str(raw), spans)
    assert dump_metrics.main(["--to-chrome", str(raw), str(chrome)]) == 0
    doc = json.loads(chrome.read_text())
    dump_metrics.validate_chrome_trace(doc)
    # idempotent: a Chrome trace converts to itself
    chrome2 = tmp_path / "trace2.json"
    assert dump_metrics.main(["--to-chrome", str(chrome), str(chrome2)]) == 0
    assert ({e["name"] for e in json.loads(chrome2.read_text())["traceEvents"]
             if e["ph"] == "X"}
            == {s["name"] for s in spans})

    snap_file = tmp_path / "snap.json"
    mx.counter("cli/c").inc(4)
    snap_file.write_text(mx.to_json())
    assert dump_metrics.main([str(snap_file)]) == 0


def test_dump_metrics_selftest():
    from tools import dump_metrics

    assert dump_metrics.selftest() == 0
