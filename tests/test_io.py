"""Checkpoint/resume + inference-model round-trip tests
(mirrors reference tests/book save/reload pattern and test_dist_save_load.py)."""

import numpy as np

import paddle_tpu as fluid


def _build(seed=0):
    x = fluid.layers.data("x", shape=[8])
    y = fluid.layers.data("y", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=16, act="relu")
    logits = fluid.layers.fc(h, size=4)
    loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
    return x, y, logits, loss


def test_save_load_persistables_resume(tmp_path, rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x, y, logits, loss = _build()
        fluid.optimizer.Adam(1e-2).minimize(loss)

    xs = rng.randn(32, 8).astype("float32")
    ys = rng.randint(0, 4, (32, 1)).astype("int64")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    for _ in range(3):
        exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])

    ckpt = str(tmp_path / "ckpt")
    fluid.io.save_persistables(exe, ckpt, main_program=main)

    # Continue training from the checkpoint in a FRESH scope: losses must
    # match continuing in the original scope (exact resume incl. Adam state).
    ref_losses = []
    import copy

    saved_scope_vars = {k: np.asarray(v) for k, v in fluid.global_scope().vars.items()}
    for _ in range(3):
        (l,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        ref_losses.append(float(l))

    with fluid.scope_guard(fluid.Scope()):
        exe2 = fluid.Executor(fluid.CPUPlace())
        fluid.io.load_persistables(exe2, ckpt, main_program=main)
        resumed_losses = []
        for _ in range(3):
            (l,) = exe2.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
            resumed_losses.append(float(l))
    np.testing.assert_allclose(ref_losses, resumed_losses, rtol=1e-5, atol=1e-6)


def test_save_load_combined_file(tmp_path, rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x, y, logits, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    ckpt = str(tmp_path / "combined")
    fluid.io.save_params(exe, ckpt, main_program=main, filename="all_params")
    w_before = fluid.global_scope().as_numpy("fc_0.w_0")
    with fluid.scope_guard(fluid.Scope()):
        fluid.io.load_params(exe, ckpt, main_program=main, filename="all_params")
        w_after = fluid.global_scope().as_numpy("fc_0.w_0")
    np.testing.assert_array_equal(w_before, w_after)


def test_save_load_inference_model(tmp_path, rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x, y, logits, loss = _build()
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(8, 8).astype("float32")
    ys = rng.randint(0, 4, (8, 1)).astype("int64")
    exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    expected, = exe.run(main.clone(for_test=True), feed={"x": xs, "y": ys},
                        fetch_list=[logits])

    model_dir = str(tmp_path / "model")
    fluid.io.save_inference_model(model_dir, ["x"], [logits], exe, main_program=main)

    with fluid.scope_guard(fluid.Scope()):
        exe2 = fluid.Executor(fluid.CPUPlace())
        prog, feed_names, fetch_names = fluid.io.load_inference_model(model_dir, exe2)
        assert feed_names == ["x"]
        got, = exe2.run(prog, feed={"x": xs}, fetch_list=fetch_names)
    np.testing.assert_allclose(expected, got, rtol=1e-5, atol=1e-6)
    # pruned program must not contain label/loss ops
    types = [op.type for op in prog.global_block.ops]
    assert "softmax_with_cross_entropy" not in types
    assert "sgd" not in types


def test_load_vars_migrates_split_qkv(tmp_path, rng):
    """Checkpoints from builds that stored q/k/v projections separately load
    into the r5 merged-qkv layout (concat on axis 1 at load time)."""
    import os

    d_model, n_head, seq = 16, 2, 8
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[seq, d_model], dtype="float32")
        out = fluid.layers.attention.multi_head_attention(
            x, None, None, None, d_model // n_head, d_model // n_head,
            d_model, n_head, is_test=True, name="mha")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(2, seq, d_model).astype("float32")
    want, = exe.run(main, feed={"x": xs}, fetch_list=[out])

    ckpt = str(tmp_path / "old_ckpt")
    fluid.io.save_params(exe, ckpt, main_program=main)
    # rewrite the merged qkv weight as the OLD three-way split layout
    import json

    with open(os.path.join(ckpt, "__index__.json")) as f:
        index = json.load(f)
    qkv_names = [n for n in index["vars"] if "_qkv" in n]
    assert qkv_names, "expected a merged qkv parameter in %s" % index["vars"]
    for n in qkv_names:
        path = os.path.join(ckpt, n.replace("/", "__") + ".npy")
        w = np.load(path)
        os.remove(path)
        for i, suffix in enumerate(("_q", "_k", "_v")):
            part = w[:, i * d_model:(i + 1) * d_model]
            np.save(os.path.join(
                ckpt, n.replace("_qkv", suffix, 1).replace("/", "__") + ".npy"),
                part)
        index["vars"] = [m for m in index["vars"] if m != n] + [
            n.replace("_qkv", s, 1) for s in ("_q", "_k", "_v")]
    with open(os.path.join(ckpt, "__index__.json"), "w") as f:
        json.dump(index, f)

    with fluid.scope_guard(fluid.Scope()):
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(startup)
        fluid.io.load_params(exe2, ckpt, main_program=main)
        got, = exe2.run(main, feed={"x": xs}, fetch_list=[out])
    np.testing.assert_allclose(want, got, rtol=1e-5, atol=1e-6)
