"""Checkpoint/resume + inference-model round-trip tests
(mirrors reference tests/book save/reload pattern and test_dist_save_load.py)."""

import numpy as np

import paddle_tpu as fluid


def _build(seed=0):
    x = fluid.layers.data("x", shape=[8])
    y = fluid.layers.data("y", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=16, act="relu")
    logits = fluid.layers.fc(h, size=4)
    loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
    return x, y, logits, loss


def test_save_load_persistables_resume(tmp_path, rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x, y, logits, loss = _build()
        fluid.optimizer.Adam(1e-2).minimize(loss)

    xs = rng.randn(32, 8).astype("float32")
    ys = rng.randint(0, 4, (32, 1)).astype("int64")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    for _ in range(3):
        exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])

    ckpt = str(tmp_path / "ckpt")
    fluid.io.save_persistables(exe, ckpt, main_program=main)

    # Continue training from the checkpoint in a FRESH scope: losses must
    # match continuing in the original scope (exact resume incl. Adam state).
    ref_losses = []
    import copy

    saved_scope_vars = {k: np.asarray(v) for k, v in fluid.global_scope().vars.items()}
    for _ in range(3):
        (l,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        ref_losses.append(float(l))

    with fluid.scope_guard(fluid.Scope()):
        exe2 = fluid.Executor(fluid.CPUPlace())
        fluid.io.load_persistables(exe2, ckpt, main_program=main)
        resumed_losses = []
        for _ in range(3):
            (l,) = exe2.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
            resumed_losses.append(float(l))
    np.testing.assert_allclose(ref_losses, resumed_losses, rtol=1e-5, atol=1e-6)


def test_save_load_combined_file(tmp_path, rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x, y, logits, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    ckpt = str(tmp_path / "combined")
    fluid.io.save_params(exe, ckpt, main_program=main, filename="all_params")
    w_before = fluid.global_scope().as_numpy("fc_0.w_0")
    with fluid.scope_guard(fluid.Scope()):
        fluid.io.load_params(exe, ckpt, main_program=main, filename="all_params")
        w_after = fluid.global_scope().as_numpy("fc_0.w_0")
    np.testing.assert_array_equal(w_before, w_after)


def test_save_load_inference_model(tmp_path, rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x, y, logits, loss = _build()
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(8, 8).astype("float32")
    ys = rng.randint(0, 4, (8, 1)).astype("int64")
    exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    expected, = exe.run(main.clone(for_test=True), feed={"x": xs, "y": ys},
                        fetch_list=[logits])

    model_dir = str(tmp_path / "model")
    fluid.io.save_inference_model(model_dir, ["x"], [logits], exe, main_program=main)

    with fluid.scope_guard(fluid.Scope()):
        exe2 = fluid.Executor(fluid.CPUPlace())
        prog, feed_names, fetch_names = fluid.io.load_inference_model(model_dir, exe2)
        assert feed_names == ["x"]
        got, = exe2.run(prog, feed={"x": xs}, fetch_list=fetch_names)
    np.testing.assert_allclose(expected, got, rtol=1e-5, atol=1e-6)
    # pruned program must not contain label/loss ops
    types = [op.type for op in prog.global_block.ops]
    assert "softmax_with_cross_entropy" not in types
    assert "sgd" not in types
