"""Inference stack tests: predictor API + StableHLO export round-trip
(mirrors reference inference/tests/api/analyzer_*_tester.cc output-parity
pattern, minus model downloads)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import inference


def _train_and_save(tmp_path, rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        logits = fluid.layers.fc(h, size=4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(logits, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(16, 8).astype("float32")
    ys = rng.randint(0, 4, (16, 1)).astype("int64")
    exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    model_dir = str(tmp_path / "model")
    fluid.io.save_inference_model(model_dir, ["x"], [logits], exe, main_program=main)
    want, = exe.run(main.clone(for_test=True), feed={"x": xs, "y": ys},
                    fetch_list=[logits])
    return model_dir, xs, want, main


def test_predictor_run_positional(tmp_path, rng):
    model_dir, xs, want, _ = _train_and_save(tmp_path, rng)
    config = inference.AnalysisConfig(model_dir)
    predictor = inference.create_predictor(config)
    assert predictor.get_input_names() == ["x"]
    out, = predictor.run([xs])
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)


def test_predictor_handle_api(tmp_path, rng):
    model_dir, xs, want, _ = _train_and_save(tmp_path, rng)
    predictor = inference.create_predictor(inference.AnalysisConfig(model_dir))
    h = predictor.get_input_handle("x")
    h.copy_from_cpu(xs)
    predictor.run()
    out_h = predictor.get_output_handle(predictor.get_output_names()[0])
    np.testing.assert_allclose(out_h.copy_to_cpu(), want, rtol=1e-5, atol=1e-6)


def test_stablehlo_export_roundtrip(tmp_path, rng):
    model_dir, xs, want, main = _train_and_save(tmp_path, rng)
    art_dir = str(tmp_path / "hlo")
    fetch = main.clone(for_test=True)
    logits_name = None
    # find the softmax output fetched earlier: reuse save_inference_model names
    predictor = inference.create_predictor(inference.AnalysisConfig(model_dir))
    fetch_names = predictor.get_output_names()

    inference.export_stablehlo(
        art_dir, ["x"], fetch_names, {"x": xs},
        program=predictor._program, scope=predictor._scope)
    mod = inference.load_stablehlo(art_dir)
    out, = mod.run({"x": xs})
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)
    # batch polymorphism: different batch size runs without re-export
    out2, = mod.run({"x": xs[:3]})
    np.testing.assert_allclose(out2, want[:3], rtol=1e-5, atol=1e-6)
