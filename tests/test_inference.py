"""Inference stack tests: predictor API + StableHLO export round-trip
(mirrors reference inference/tests/api/analyzer_*_tester.cc output-parity
pattern, minus model downloads)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import inference


def _train_and_save(tmp_path, rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        logits = fluid.layers.fc(h, size=4, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(logits, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(16, 8).astype("float32")
    ys = rng.randint(0, 4, (16, 1)).astype("int64")
    exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    model_dir = str(tmp_path / "model")
    fluid.io.save_inference_model(model_dir, ["x"], [logits], exe, main_program=main)
    want, = exe.run(main.clone(for_test=True), feed={"x": xs, "y": ys},
                    fetch_list=[logits])
    return model_dir, xs, want, main


def test_predictor_run_positional(tmp_path, rng):
    model_dir, xs, want, _ = _train_and_save(tmp_path, rng)
    config = inference.AnalysisConfig(model_dir)
    predictor = inference.create_predictor(config)
    assert predictor.get_input_names() == ["x"]
    out, = predictor.run([xs])
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)


def test_predictor_handle_api(tmp_path, rng):
    model_dir, xs, want, _ = _train_and_save(tmp_path, rng)
    predictor = inference.create_predictor(inference.AnalysisConfig(model_dir))
    h = predictor.get_input_handle("x")
    h.copy_from_cpu(xs)
    predictor.run()
    out_h = predictor.get_output_handle(predictor.get_output_names()[0])
    np.testing.assert_allclose(out_h.copy_to_cpu(), want, rtol=1e-5, atol=1e-6)


def test_stablehlo_export_roundtrip(tmp_path, rng):
    model_dir, xs, want, main = _train_and_save(tmp_path, rng)
    art_dir = str(tmp_path / "hlo")
    fetch = main.clone(for_test=True)
    logits_name = None
    # find the softmax output fetched earlier: reuse save_inference_model names
    predictor = inference.create_predictor(inference.AnalysisConfig(model_dir))
    fetch_names = predictor.get_output_names()

    inference.export_stablehlo(
        art_dir, ["x"], fetch_names, {"x": xs},
        program=predictor._program, scope=predictor._scope)
    mod = inference.load_stablehlo(art_dir)
    out, = mod.run({"x": xs})
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)
    # batch polymorphism: different batch size runs without re-export
    out2, = mod.run({"x": xs[:3]})
    np.testing.assert_allclose(out2, want[:3], rtol=1e-5, atol=1e-6)


def test_predictor_batch_bucketing_bounds_compile_cache(tmp_path, rng):
    """Varying client batch sizes must round up to power-of-two buckets:
    bit-correct sliced outputs, O(log max_batch) compiled specializations
    instead of one per unique batch."""
    model_dir, xs, want, _ = _train_and_save(tmp_path, rng)
    predictor = inference.create_predictor(inference.AnalysisConfig(model_dir))
    for b in (3, 5, 6, 7):
        out, = predictor.run([xs[:b]])
        assert out.shape[0] == b, "padded rows leaked into the output"
        np.testing.assert_allclose(out, want[:b], rtol=1e-5, atol=1e-6)
    # 3 -> bucket 4; 5,6,7 -> bucket 8: two specializations, not four
    assert len(predictor._exe._cache) == 2

    config = inference.AnalysisConfig(model_dir)
    config.switch_batch_bucketing(False)
    exact = inference.create_predictor(config)
    for b in (3, 5, 6, 7):
        out, = exact.run([xs[:b]])
        np.testing.assert_allclose(out, want[:b], rtol=1e-5, atol=1e-6)
    assert len(exact._exe._cache) == 4  # the unbounded-growth failure mode


def test_iohandle_reshape_validates_against_staged(tmp_path, rng):
    model_dir, xs, _, _ = _train_and_save(tmp_path, rng)
    predictor = inference.create_predictor(inference.AnalysisConfig(model_dir))
    h = predictor.get_input_handle("x")
    h.copy_from_cpu(xs[:4])
    with pytest.raises(ValueError, match="conflicts with already-staged"):
        h.reshape([8, 8])
    h.reshape([4, 8])  # matching declaration is fine
    with pytest.raises(ValueError, match="declared"):
        h.copy_from_cpu(xs[:2])  # violates the declared shape
    out_h = predictor.get_output_handle(predictor.get_output_names()[0])
    with pytest.raises(ValueError, match="input handles"):
        out_h.reshape([4, 4])


def test_iohandle_reuse_across_runs(tmp_path, rng):
    """run() consumes the staged inputs, so the standard per-iteration
    reshape()+copy_from_cpu() pattern works at a DIFFERENT batch next
    iteration instead of colliding with the previous one's shapes."""
    model_dir, xs, want, _ = _train_and_save(tmp_path, rng)
    predictor = inference.create_predictor(inference.AnalysisConfig(model_dir))
    h = predictor.get_input_handle("x")
    out_name = predictor.get_output_names()[0]
    for b in (4, 2, 7):
        h.reshape([b, 8])
        h.copy_from_cpu(xs[:b])
        predictor.run()
        got = predictor.get_output_handle(out_name).copy_to_cpu()
        assert got.shape[0] == b
        np.testing.assert_allclose(got, want[:b], rtol=1e-5, atol=1e-6)


def test_bucketing_batch_reduced_fetch_stays_exact(tmp_path, rng):
    """A fetch that reduces over the batch dim must not silently average
    padded rows in — bucketing falls back to an exact-shape run."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        out = fluid.layers.fc(x, size=4, act="softmax")
        m = fluid.layers.mean(out)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    model_dir = str(tmp_path / "redmodel")
    fluid.io.save_inference_model(model_dir, ["x"], [out, m], exe,
                                  main_program=main)
    xs = rng.randn(5, 8).astype("float32")
    want_out, want_m = exe.run(main.clone(for_test=True), feed={"x": xs},
                               fetch_list=[out, m])
    predictor = inference.create_predictor(inference.AnalysisConfig(model_dir))
    got_out, got_m = predictor.run([xs])
    assert got_out.shape[0] == 5
    np.testing.assert_allclose(got_out, want_out, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_m, want_m, rtol=1e-5, atol=1e-6)
