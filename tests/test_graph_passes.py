"""Default trace-time optimizer (paddle_tpu.passes): DCE, constant folding,
CSE, fused-kernel pattern rewrites, the PADDLE_TPU_OPT_LEVEL gates, and the
Executor/CompiledProgram wiring (ISSUE 3).

The load-bearing invariants:
  * optimized programs are CLONES — the source program is never mutated;
  * losses are bit-identical to PADDLE_TPU_OPT_LEVEL=0, dropout RNG
    included (RNG-slot stamping, passes/analysis.py);
  * re-running a pass on a cache hit is a bug — the optimization is
    memoized per (program version, fetch set) and the dispatch-plan cache
    keys on the optimized clone.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.core.pass_framework import PassBuilder, PassError, get_pass
from paddle_tpu.passes.pipeline import maybe_optimize, optimize_program


def _count_ops(program, op_type):
    return sum(1 for op in program.global_block.ops if op.type == op_type)


def _op_types(program):
    return [op.type for op in program.global_block.ops]


def _counter(name):
    snap = monitor.snapshot()
    return snap.get(name, {}).get("value", 0.0)


def _mlp_with_baggage(dropout=0.0):
    """MLP whose program carries typical train-loop baggage: an unfetched
    accuracy branch, a constant chain, and a duplicated subexpression."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=24, act="relu")
        if dropout:
            h = fluid.layers.dropout(
                h, dropout, dropout_implementation="upscale_in_train")
        logits = fluid.layers.fc(h, size=10)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.layers.accuracy(fluid.layers.softmax(logits), y)
        c = fluid.layers.fill_constant([1], "float32", 2.0)
        fluid.layers.scale(c, scale=3.0)
        a = fluid.layers.scale(h, scale=2.0)
        b = fluid.layers.scale(h, scale=2.0)
        fluid.layers.elementwise_add(a, b)
        fluid.optimizer.Adam(1e-3).minimize(loss)
    return main, startup, loss


def _feed(rng, n=8):
    return {"x": rng.randn(n, 16).astype("float32"),
            "y": rng.randint(0, 10, (n, 1)).astype("int64")}


# -- individual passes --------------------------------------------------------


def test_dce_sheds_unfetched_branches_and_keeps_persistables(rng):
    main, startup, loss = _mlp_with_baggage()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    opt = optimize_program(main, (loss.name,), fluid.global_scope())
    # metrics branch, constant chain and duplicate subexpression all gone
    assert _count_ops(opt, "accuracy") == 0
    assert _count_ops(opt, "top_k") == 0
    assert _count_ops(opt, "fill_constant") == 0
    assert len(opt.global_block.ops) < len(main.global_block.ops)
    # source untouched, params + optimizer state still persistable
    assert _count_ops(main, "accuracy") == 1
    src_persist = {v.name for v in main.list_vars() if v.persistable}
    opt_persist = {v.name for v in opt.list_vars() if v.persistable}
    assert src_persist == opt_persist


def test_constant_folding_replaces_chain_with_single_constant(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        c = fluid.layers.fill_constant([4], "float32", 2.0)
        c = fluid.layers.scale(c, scale=3.0, bias=1.0)
        out = fluid.layers.elementwise_add(x, c)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(2, 4).astype("float32")
    (want,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
    opt = optimize_program(main, (out.name,), fluid.global_scope())
    # chain collapsed: exactly one constant producer + the consumer add
    assert _count_ops(opt, "scale") == 0
    consts = (_count_ops(opt, "fill_constant")
              + _count_ops(opt, "assign_value"))
    assert consts == 1
    (got,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
    np.testing.assert_array_equal(want, got)
    np.testing.assert_allclose(got, xs + 7.0, rtol=1e-6)


def test_constant_folding_keeps_persistable_initializers(rng):
    """Startup fill_constant writes a param — externally visible, must
    survive folding (the executor flows it to the scope)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        fluid.layers.fc(x, size=3)
    opt = optimize_program(startup, (), fluid.global_scope())
    assert len(opt.global_block.ops) == len(startup.global_block.ops)


def test_cse_merges_duplicate_subexpressions(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        a = fluid.layers.scale(x, scale=2.0)
        b = fluid.layers.scale(x, scale=2.0)   # duplicate
        c = fluid.layers.scale(x, scale=5.0)   # different attrs: kept
        out = fluid.layers.elementwise_add(fluid.layers.elementwise_add(a, b), c)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(2, 4).astype("float32")
    (want,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
    opt = optimize_program(main, (out.name,), fluid.global_scope())
    assert _count_ops(opt, "scale") == 2
    (got,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
    np.testing.assert_array_equal(want, got)
    np.testing.assert_allclose(got, xs * 9.0, rtol=1e-6)


def test_cse_alias_dies_on_redefinition(rng):
    """A merged-away name that is later REDEFINED must stop aliasing:
    downstream readers need the new definition, not the first occurrence."""
    from paddle_tpu.passes.cse import CommonSubexpressionEliminationPass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        a = fluid.layers.scale(x, scale=2.0)
        b = fluid.layers.scale(x, scale=2.0)   # dup: aliased to a...
        blk = main.global_block
        blk.append_op("scale", inputs={"X": x}, outputs={"Out": b},
                      attrs={"scale": 5.0, "bias": 0.0,
                             "bias_after_scale": True})  # ...then redefined
        c = fluid.layers.relu(b)
        out = fluid.layers.elementwise_add(a, c)
    CommonSubexpressionEliminationPass().apply(main)
    relu = next(o for o in main.global_block.ops if o.type == "relu")
    assert relu.inputs["X"] == [b.name]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (got,) = exe.run(main, feed={"x": np.ones((1, 4), "float32")},
                     fetch_list=[out])
    np.testing.assert_allclose(got, np.full((1, 4), 7.0))  # 2x + relu(5x)


def test_build_time_pipeline_keeps_fetchable_leaves(rng):
    """The CompiledProgram build path runs the pipeline with NO fetch info;
    constant chains and duplicate leaves must stay fetchable at run time."""
    from paddle_tpu.core.pass_framework import FunctionPass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        loss = fluid.layers.mean(fluid.layers.fc(x, size=3))
        d = fluid.layers.scale(
            fluid.layers.fill_constant([1], "float32", 2.0), scale=0.5)
    bs = fluid.compiler.BuildStrategy()
    bs.pass_builder().append_pass(FunctionPass("noop", lambda p, s: None))
    cp = fluid.compiler.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, build_strategy=bs)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    lv, dv = exe.run(cp, feed={"x": np.ones((8, 4), "float32")},
                     fetch_list=[loss, d])
    assert float(np.asarray(dv).ravel()[0]) == pytest.approx(1.0)


def test_cse_respects_redefinition(rng):
    """An op whose output is clobbered between two identical computations
    must NOT serve as the CSE source for the later one."""
    from paddle_tpu.passes.cse import CommonSubexpressionEliminationPass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        a = fluid.layers.scale(x, scale=2.0)
        blk = main.global_block
        # clobber a's var with a different value, then recompute scale(x, 2)
        blk.append_op("scale", inputs={"X": x}, outputs={"Out": a},
                      attrs={"scale": 7.0, "bias": 0.0,
                             "bias_after_scale": True})
        b = fluid.layers.scale(x, scale=2.0)
        out = fluid.layers.elementwise_add(a, b)
    p = CommonSubexpressionEliminationPass()
    p.apply(main)
    # the third scale cannot be merged into the (clobbered) first
    assert _count_ops(main, "scale") == 3
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(2, 4).astype("float32")
    (got,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
    np.testing.assert_allclose(got, xs * 9.0, rtol=1e-6)


# -- fused-kernel pattern rewrites --------------------------------------------


def test_softmax_xent_fuse_rewrite_and_parity(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        logits = fluid.layers.fc(x, size=5)
        probs = fluid.layers.softmax(logits)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(probs, y))
        fluid.optimizer.SGD(0.5).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = _feed(rng)
    feed["x"] = feed["x"][:, :8]
    feed["y"] = np.clip(feed["y"], 0, 4)
    opt = maybe_optimize(main, (loss.name,), fluid.global_scope())
    assert _count_ops(opt, "softmax_with_cross_entropy") == 1
    assert _count_ops(opt, "softmax") == 0
    assert _count_ops(opt, "cross_entropy") == 0
    # composed numerics at level 0 vs fused at level 1 agree closely (the
    # fused op is the numerically superior formulation, not bit-equal)
    losses = []
    for _ in range(4):
        lv, = exe.run(main, feed=feed, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0]  # trains through the fused custom-vjp


def test_softmax_survives_when_probs_are_fetched(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        logits = fluid.layers.fc(x, size=5)
        probs = fluid.layers.softmax(logits)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(probs, y))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    opt = maybe_optimize(main, (loss.name, probs.name), fluid.global_scope())
    # the loss still fuses on the logits, but the fetched probs keep their op
    assert _count_ops(opt, "softmax") == 1
    assert _count_ops(opt, "softmax_with_cross_entropy") == 1
    feed = {"x": rng.randn(4, 8).astype("float32"),
            "y": rng.randint(0, 5, (4, 1)).astype("int64")}
    lv, pv = exe.run(main, feed=feed, fetch_list=[loss, probs])
    np.testing.assert_allclose(pv.sum(axis=-1), np.ones(4), rtol=1e-5)


def _unfused_attention_program(dropout=0.0, with_bias=True):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        q = fluid.layers.data("q", shape=[2, 8, 4])
        k = fluid.layers.data("k", shape=[2, 8, 4])
        v = fluid.layers.data("v", shape=[2, 8, 4])
        scores = fluid.layers.matmul(q, k, transpose_y=True, alpha=0.5)
        if with_bias:
            bias = fluid.layers.data("bias", shape=[2, 8, 8])
            scores = fluid.layers.elementwise_add(scores, bias)
        probs = fluid.layers.softmax(scores)
        if dropout:
            probs = fluid.layers.dropout(
                probs, dropout, dropout_implementation="upscale_in_train")
        out = fluid.layers.matmul(probs, v)
        red = fluid.layers.mean(out)
    return main, startup, red, probs


@pytest.mark.parametrize("with_bias", [True, False])
def test_flash_attention_rewrite_matches(rng, with_bias, monkeypatch):
    main, startup, red, _ = _unfused_attention_program(with_bias=with_bias)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {n: rng.randn(3, 2, 8, 4).astype("float32") for n in "qkv"}
    if with_bias:
        feed["bias"] = rng.randn(3, 2, 8, 8).astype("float32") * 0.1
    monkeypatch.setenv("PADDLE_TPU_OPT_LEVEL", "0")
    (want,) = exe.run(main, feed=feed, fetch_list=[red])
    monkeypatch.setenv("PADDLE_TPU_OPT_LEVEL", "1")
    opt = maybe_optimize(main, (red.name,), fluid.global_scope())
    assert _count_ops(opt, "scaled_dot_product_attention") == 1
    assert _count_ops(opt, "matmul") == 0
    assert _count_ops(opt, "softmax") == 0
    sdpa = next(o for o in opt.global_block.ops
                if o.type == "scaled_dot_product_attention")
    assert sdpa.attr("sm_scale") == pytest.approx(0.5)
    assert bool(sdpa.inputs.get("Bias")) == with_bias
    (got,) = exe.run(main, feed=feed, fetch_list=[red])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_flash_attention_rewrite_consumes_dropout(rng):
    main, startup, red, _ = _unfused_attention_program(dropout=0.3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    opt = maybe_optimize(main, (red.name,), fluid.global_scope())
    assert _count_ops(opt, "scaled_dot_product_attention") == 1
    assert _count_ops(opt, "dropout") == 0
    sdpa = next(o for o in opt.global_block.ops
                if o.type == "scaled_dot_product_attention")
    assert sdpa.attr("dropout_rate") == pytest.approx(0.3)
    # the absorbed dropout's PRNG slot rides along (determinism across
    # repeated optimizations of the same source program)
    assert sdpa.attr("__rng_slot__") is not None


def test_flash_attention_rewrite_skips_fetched_probs(rng):
    main, startup, red, probs = _unfused_attention_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    opt = maybe_optimize(main, (red.name, probs.name), fluid.global_scope())
    assert _count_ops(opt, "scaled_dot_product_attention") == 0
    assert _count_ops(opt, "softmax") == 1


def test_unfused_attention_flag_roundtrip(rng):
    """FLAGS_unfused_attention emits primitives; the default pipeline fuses
    them back; numerics match the directly-fused layer."""
    from paddle_tpu.layers import attention as attn

    def build(unfused):
        main, startup = fluid.Program(), fluid.Program()
        fluid.set_flag("unfused_attention", unfused)
        try:
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("x", shape=[8, 16])
                out = attn.multi_head_attention(
                    x, None, None, None, 4, 4, 16, 4, dropout_rate=0.0)
                red = fluid.layers.mean(out)
        finally:
            fluid.set_flag("unfused_attention", False)
        return main, startup, red

    xs = rng.randn(2, 8, 16).astype("float32")
    outs = {}
    for unfused in (False, True):
        with fluid.unique_name.guard():
            with fluid.scope_guard(fluid.Scope()):
                main, startup, red = build(unfused)
                if unfused:
                    assert _count_ops(main, "matmul") >= 2
                    opt = maybe_optimize(main, (red.name,),
                                         fluid.global_scope())
                    assert _count_ops(opt, "scaled_dot_product_attention") == 1
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                (outs[unfused],) = exe.run(main, feed={"x": xs},
                                           fetch_list=[red])
    np.testing.assert_allclose(outs[True], outs[False], rtol=1e-5, atol=1e-6)


# -- pipeline contract: idempotence, cache interaction, bit-identity ----------


def _program_signature(program):
    return [(op.type, sorted(op.inputs.items()), sorted(op.outputs.items()),
             sorted((k, repr(v)) for k, v in op.attrs.items()))
            for op in program.global_block.ops]


def test_pipeline_idempotent_and_source_untouched(rng):
    main, startup, loss = _mlp_with_baggage(dropout=0.2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    n_src = len(main.global_block.ops)
    opt1 = optimize_program(main, (loss.name,), fluid.global_scope())
    opt2 = optimize_program(opt1, (loss.name,), fluid.global_scope())
    assert _program_signature(opt1) == _program_signature(opt2)
    assert len(main.global_block.ops) == n_src


def test_optimized_program_bit_identical_with_dropout(rng, monkeypatch):
    """ISSUE 3 satellite: losses bit-identical to PADDLE_TPU_OPT_LEVEL=0,
    dropout RNG included — even though DCE removes ops positioned BEFORE
    the dropout op (the RNG-slot stamp keeps the key stream pinned)."""

    def run_level(level):
        monkeypatch.setenv("PADDLE_TPU_OPT_LEVEL", str(level))
        with fluid.unique_name.guard():
            with fluid.scope_guard(fluid.Scope()):
                main, startup = fluid.Program(), fluid.Program()
                with fluid.program_guard(main, startup):
                    x = fluid.layers.data("x", shape=[16])
                    y = fluid.layers.data("y", shape=[1], dtype="int64")
                    # dead baggage BEFORE the dropout: removal shifts every
                    # later op index unless slots are stamped
                    c = fluid.layers.fill_constant([1], "float32", 2.0)
                    fluid.layers.scale(c, scale=3.0)
                    h = fluid.layers.fc(x, size=24, act="relu")
                    h = fluid.layers.dropout(
                        h, 0.4, dropout_implementation="upscale_in_train")
                    logits = fluid.layers.fc(h, size=10)
                    loss = fluid.layers.mean(
                        fluid.layers.softmax_with_cross_entropy(logits, y))
                    fluid.optimizer.Adam(1e-3).minimize(loss)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                data = _feed(np.random.RandomState(7))
                out = []
                for _ in range(5):
                    lv, = exe.run(main, feed=data, fetch_list=[loss])
                    out.append(lv.copy())
                if level:
                    opt = exe._maybe_optimize(main, (loss.name,),
                                              fluid.global_scope())
                    assert len(opt.global_block.ops) < len(main.global_block.ops)
                return out

    l0 = run_level(0)
    l1 = run_level(1)
    for a, b in zip(l0, l1):
        np.testing.assert_array_equal(a, b)


def test_optimization_memoized_and_plan_cache_hits(rng):
    """Two runs reuse ONE optimized clone (re-running a pass on a cache hit
    is a bug) and the second run is a dispatch-plan hit."""
    main, startup, loss = _mlp_with_baggage()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = _feed(rng)
    runs0 = _counter("passes/pipeline/runs")
    exe.run(main, feed=feed, fetch_list=[loss])
    opt_a = next(iter(main._opt_cache[1].values()))[1]
    runs1 = _counter("passes/pipeline/runs")
    hits_before = _counter("executor/plan_hit")
    exe.run(main, feed=feed, fetch_list=[loss])
    runs2 = _counter("passes/pipeline/runs")
    hits_after = _counter("executor/plan_hit")
    opt_b = next(iter(main._opt_cache[1].values()))[1]
    assert opt_a is opt_b
    if monitor.enabled():
        assert runs1 > runs0          # first run paid one pipeline
        assert runs2 == runs1         # second run re-entered NO pass
        assert hits_after > hits_before


def test_opt_level_zero_disables_everything(rng, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_OPT_LEVEL", "0")
    main, startup, loss = _mlp_with_baggage()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    opt = exe._maybe_optimize(main, (loss.name,), fluid.global_scope())
    assert opt is main


def test_per_pass_env_gate(rng, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_PASS_DEAD_CODE_ELIMINATION", "0")
    main, startup, loss = _mlp_with_baggage()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    opt = optimize_program(main, (loss.name,), fluid.global_scope())
    # DCE off: the accuracy branch survives (CSE may still have merged the
    # duplicate softmax feeding it)
    assert _count_ops(opt, "accuracy") == 1
    monkeypatch.delenv("PADDLE_TPU_PASS_DEAD_CODE_ELIMINATION")
    opt2 = optimize_program(main, (loss.name,), fluid.global_scope())
    assert _count_ops(opt2, "accuracy") == 0


# -- PassBuilder error path (satellite) ---------------------------------------


def test_apply_all_propagates_failing_pass_name():
    from paddle_tpu.core.pass_framework import FunctionPass

    def boom(program, p):
        raise ValueError("kaboom")

    builder = PassBuilder([FunctionPass("fine_pass", lambda prog, p: None),
                           FunctionPass("exploding_pass", boom)])
    with pytest.raises(PassError, match="exploding_pass"):
        builder.apply_all(fluid.Program())


def test_compiled_program_left_untouched_on_pass_failure(rng):
    from paddle_tpu.core.pass_framework import FunctionPass, Pass

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        loss = fluid.layers.mean(fluid.layers.fc(x, size=3))

    class Mutator(Pass):
        name = "mutating_pass"

        def apply_impl(self, program):
            program.global_block.append_op(
                "scale", inputs={"X": loss.name}, outputs={"Out": loss.name},
                attrs={"scale": 1.0})

    def boom(program, p):
        raise RuntimeError("mid-pipeline failure")

    bs = fluid.compiler.BuildStrategy()
    bs.pass_builder().append_pass(Mutator())
    bs.pass_builder().append_pass(FunctionPass("late_boom", boom))
    compiled = fluid.compiler.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, build_strategy=bs)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    n_ops = len(main.global_block.ops)
    version = main._version
    with pytest.raises(PassError, match="late_boom"):
        exe.run(compiled, feed={"x": rng.randn(2, 4).astype("float32")},
                fetch_list=[loss])
    # transactional clone: the user's program is untouched by the half-run
    # pipeline (the Mutator ran on the clone only)
    assert len(main.global_block.ops) == n_ops
    assert main._version == version


# -- conv_bn_fuse_pass satellites ---------------------------------------------


def _conv_bn_inference(rng, bias=True):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 8, 8])
        c = fluid.layers.conv2d(img, num_filters=5, filter_size=3,
                                bias_attr=None if bias else False)
        out = fluid.layers.batch_norm(c, is_test=True)
        out = fluid.layers.relu(out)
    return main, startup, out


def test_conv_bn_fuse_idempotent_second_apply_noop(rng):
    main, startup, out = _conv_bn_inference(rng)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    scope = fluid.global_scope()
    p = get_pass("conv_bn_fuse_pass").set_attr("scope", scope)
    p.apply(main)
    assert p.attr("fused_count") == 1
    sig = _program_signature(main)
    p2 = get_pass("conv_bn_fuse_pass").set_attr("scope", scope)
    p2.apply(main)
    assert p2.attr("fused_count") == 0
    assert _program_signature(main) == sig


def test_conv_bn_fuse_reapply_from_original_is_safe(rng):
    """The default pipeline re-clones the ORIGINAL program per fetch set;
    folding must read pristine inputs each time, not compound."""
    main, startup, out = _conv_bn_inference(rng)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    scope = fluid.global_scope()
    for p in main.list_vars():
        if p.name.endswith(".mean"):
            scope.set_var(p.name, rng.randn(5).astype("float32") * 0.1)
        if p.name.endswith(".var"):
            scope.set_var(p.name, np.abs(rng.randn(5)).astype("float32") + 0.5)
    main._version += 1  # stats changed under the cache
    xs = rng.randn(2, 3, 8, 8).astype("float32")
    clone_a = optimize_program(main, (out.name,), scope)
    clone_b = optimize_program(main, (out.name,), scope)  # second fold
    assert _count_ops(clone_a, "batch_norm") == 0
    assert _count_ops(clone_b, "batch_norm") == 0
    (got,) = exe.run(main, feed={"img": xs}, fetch_list=[out])
    # reference: unfused numerics from a fresh un-optimized run
    import os
    prev = os.environ.get("PADDLE_TPU_OPT_LEVEL")
    os.environ["PADDLE_TPU_OPT_LEVEL"] = "0"
    try:
        main._version += 1  # force past cached plans
        (want,) = exe.run(main, feed={"img": xs}, fetch_list=[out])
    finally:
        if prev is None:
            os.environ.pop("PADDLE_TPU_OPT_LEVEL", None)
        else:
            os.environ["PADDLE_TPU_OPT_LEVEL"] = prev
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_conv_bn_fused_by_default_inference_pipeline(rng):
    """Satellite: the fuse pass is part of the default opt-level>=1 pipeline
    for is_test programs — no BuildStrategy wiring needed."""
    main, startup, out = _conv_bn_inference(rng)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(2, 3, 8, 8).astype("float32")
    before = _counter("passes/conv_bn_fuse_pass/rewrites_matched")
    (got,) = exe.run(main, feed={"img": xs}, fetch_list=[out])
    opt = exe._maybe_optimize(main, (out.name,), fluid.global_scope())
    assert _count_ops(opt, "batch_norm") == 0
    assert _count_ops(main, "batch_norm") == 1  # source untouched
    if monitor.enabled():
        assert _counter("passes/conv_bn_fuse_pass/rewrites_matched") > before
    # numerics match the unfused program
    import os
    os.environ["PADDLE_TPU_OPT_LEVEL"] = "0"
    try:
        main._version += 1
        (want,) = exe.run(main, feed={"img": xs}, fetch_list=[out])
    finally:
        os.environ.pop("PADDLE_TPU_OPT_LEVEL", None)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


# -- tooling ------------------------------------------------------------------


def test_dump_program_selftest_runs():
    import subprocess
    import sys as _sys

    r = subprocess.run(
        [_sys.executable, "-m", "tools.dump_program", "--selftest"],
        capture_output=True, text=True, timeout=120,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout
