"""Layer-surface parity sweep (VERDICT round-2 item 4).

Asserts every public def in the reference's ``layers/nn.py`` (155 names) and
``layers/ops.py`` resolves in ``paddle_tpu.layers``, minus an explicit
deny-list, and exercises the round-3 additions numerically.
"""

import os
import re

import numpy as np
import pytest

import paddle_tpu as fluid

REF = "/root/reference/python/paddle/fluid/layers"

# Names intentionally absent, each with a justification.
DENY_LIST = {
    # nn.py / ops.py: none — the full surface resolves.
    # control_flow.py:
    "reorder_lod_tensor_by_rank": "LoD rank-table machinery; the padded+"
        "Length representation never reorders by rank (SURVEY §2 tensor stack)",
    # io.py — the graph file-reader op stack (open_files + decorated reader
    # Variables) is replaced by py_reader/AsyncExecutor + host-side reader
    # decorators (reader/decorator.py); layers.shuffle/batch delegate there:
    "open_files": "file-reader ops replaced by py_reader + reader decorators",
    "random_data_generator": "use numpy readers + py_reader",
    "Preprocessor": "host-side reader decorators replace the graph preprocessor",
}


def _ref_all(fname):
    path = os.path.join(REF, fname)
    if not os.path.exists(path):
        pytest.skip("reference not available")
    src = open(path).read()
    block = re.search(r"__all__ = \[(.*?)\]", src, re.S).group(1)
    return re.findall(r"'([a-zA-Z0-9_]+)'", block)


@pytest.mark.parametrize("fname", ["nn.py", "ops.py", "tensor.py",
                                   "control_flow.py", "detection.py", "io.py",
                                   "metric_op.py",
                                   "learning_rate_scheduler.py"])
def test_reference_layer_surface_resolves(fname):
    names = _ref_all(fname)
    assert len(names) > 50 if fname == "nn.py" else True
    missing = [n for n in names
               if n not in DENY_LIST and not hasattr(fluid.layers, n)]
    assert not missing, "reference %s layers unresolved: %s" % (fname, missing)


def _run(main, startup, feed, fetch):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=fetch)


def test_generated_loss_wrappers(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[5])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        flabel = fluid.layers.data("flabel", shape=[1])
        left = fluid.layers.data("left", shape=[1])
        right = fluid.layers.data("right", shape=[1])
        bpr = fluid.layers.bpr_loss(fluid.layers.softmax(x), label)
        rl = fluid.layers.rank_loss(flabel, left, right)
        mrl = fluid.layers.margin_rank_loss(flabel, left, right, margin=0.2)
    n = 4
    xs = rng.randn(n, 5).astype("float32")
    ys = rng.randint(0, 5, (n, 1)).astype("int64")
    fl = rng.randint(0, 2, (n, 1)).astype("float32")
    l, r = rng.randn(n, 1).astype("float32"), rng.randn(n, 1).astype("float32")
    b, rk, m = _run(main, startup,
                    {"x": xs, "label": ys, "flabel": fl, "left": l, "right": r},
                    [bpr, rl, mrl])
    assert b.shape == (n, 1) and np.isfinite(b).all()
    np.testing.assert_allclose(rk, np.log1p(np.exp(l - r)) - fl * (l - r), rtol=1e-5)
    np.testing.assert_allclose(m, np.maximum(-fl * (l - r) + 0.2, 0.0), rtol=1e-5)


def test_generated_misc_wrappers(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3, 8, 8])
        pe_in = fluid.layers.data("pe", shape=[4, 6])
        scale = fluid.layers.data("scale", shape=[3])
        bias = fluid.layers.data("bias", shape=[3])
        ac = fluid.layers.affine_channel(x, scale=scale, bias=bias)
        pe = fluid.layers.add_position_encoding(pe_in, alpha=1.0, beta=1.0)
        cropped = fluid.layers.crop(x, shape=[2, 3, 4, 4], offsets=[0, 0, 2, 2])
        rc = fluid.layers.random_crop(x, shape=[4, 4])
        probs = fluid.layers.data("probs", shape=[6])
        sid = fluid.layers.sampling_id(probs, dtype="int64")
        bx = fluid.layers.data("bx", shape=[2], dtype="bool")
        by = fluid.layers.data("by", shape=[2], dtype="bool")
        lx = fluid.layers.logical_xor(bx, by)
    n = 2
    xs = rng.randn(n, 3, 8, 8).astype("float32")
    sc = np.array([1.0, 2.0, 3.0], "float32")
    bi = np.array([0.5, -0.5, 0.0], "float32")
    pev = rng.randn(n, 4, 6).astype("float32")
    pr = np.abs(rng.rand(n, 6)).astype("float32")
    pr /= pr.sum(-1, keepdims=True)
    bxv = np.array([[True, False], [False, False]])
    byv = np.array([[True, True], [False, True]])
    a, p, c, r, s, x_ = _run(
        main, startup,
        {"x": xs, "scale": sc, "bias": bi, "pe": pev, "probs": pr,
         "bx": bxv, "by": byv},
        [ac, pe, cropped, rc, sid, lx])
    np.testing.assert_allclose(
        a, xs * sc.reshape(1, 3, 1, 1) + bi.reshape(1, 3, 1, 1), rtol=1e-5)
    assert p.shape == pev.shape
    np.testing.assert_allclose(c, xs[:2, :3, 2:6, 2:6], rtol=1e-6)
    assert r.shape == (n, 3, 4, 4)
    assert s.shape == (n,) and (s >= 0).all() and (s < 6).all()
    np.testing.assert_array_equal(x_, bxv ^ byv)


def test_pad_constant_like_and_lod_reset(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4, 5])
        y = fluid.layers.data("y", shape=[2, 3])
        padded = fluid.layers.pad_constant_like(x, y, pad_value=7.0)
        lr, lr_len = fluid.layers.lod_reset(x, target_lod=[0, 2, 3])
    xs = rng.randn(3, 4, 5).astype("float32")
    ys = rng.randn(3, 2, 3).astype("float32")
    p, out = _run(main, startup, {"x": xs, "y": ys}, [padded, lr])
    assert p.shape == xs.shape
    np.testing.assert_allclose(p[:, :2, :3], ys, rtol=1e-6)
    assert (p[:, 2:, :] == 7.0).all()
    np.testing.assert_allclose(out, xs, rtol=1e-6)


def test_adaptive_pools(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2, 8, 6])
        v = fluid.layers.data("v", shape=[2, 4, 6, 5])
        avg = fluid.layers.adaptive_pool2d(x, pool_size=[4, 3], pool_type="avg")
        mx = fluid.layers.adaptive_pool2d(x, pool_size=[3, 5], pool_type="max")
        p3 = fluid.layers.adaptive_pool3d(v, pool_size=[2, 3, 5], pool_type="avg")
    xs = rng.randn(2, 2, 8, 6).astype("float32")
    vs = rng.randn(2, 2, 4, 6, 5).astype("float32")
    a, m, p = _run(main, startup, {"x": xs, "v": vs}, [avg, mx, p3])
    # divisible dims: reshape-reduce parity with numpy
    np.testing.assert_allclose(
        a, xs.reshape(2, 2, 4, 2, 3, 2).mean(axis=(3, 5)), rtol=1e-5)
    assert m.shape == (2, 2, 3, 5)
    # ragged windows: [floor(i*in/out), ceil((i+1)*in/out))
    for i in range(3):
        s, e = (i * 8) // 3, -((-(i + 1) * 8) // 3)
        np.testing.assert_allclose(
            m[:, :, i, :],
            np.stack([xs[:, :, s:e, (j * 6) // 5: -((-(j + 1) * 6) // 5)]
                      .max(axis=(2, 3)) for j in range(5)], axis=-1),
            rtol=1e-5)
    assert p.shape == (2, 2, 2, 3, 5)


def test_dice_loss_and_image_resize_short(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        pred = fluid.layers.data("pred", shape=[4])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        dl = fluid.layers.dice_loss(fluid.layers.softmax(pred), label)
        img = fluid.layers.data("img", shape=[3, 12, 24])
        short = fluid.layers.image_resize_short(img, out_short_len=6)
    ps = rng.randn(5, 4).astype("float32")
    ls = rng.randint(0, 4, (5, 1)).astype("int64")
    ims = rng.randn(2, 3, 12, 24).astype("float32")
    d, s = _run(main, startup, {"pred": ps, "label": ls, "img": ims}, [dl, short])
    assert 0.0 <= float(d) <= 1.0 + 1e-5
    assert s.shape == (2, 3, 6, 12)


def test_sampled_softmax_trains(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        logits = fluid.layers.fc(x, size=64)
        loss = fluid.layers.mean(
            fluid.layers.sampled_softmax_with_cross_entropy(
                logits, label, num_samples=8))
        fluid.optimizer.Adam(5e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    centers = rng.randn(4, 16).astype("float32") * 2
    first = last = None
    for i in range(60):
        ys = rng.randint(0, 4, 32)
        xs = centers[ys] + 0.3 * rng.randn(32, 16).astype("float32")
        (lv,) = exe.run(main, feed={"x": xs.astype("float32"),
                                    "label": ys.reshape(-1, 1).astype("int64")},
                        fetch_list=[loss])
        if first is None:
            first = float(lv)
        last = float(lv)
    assert last < first, "sampled softmax did not reduce loss (%s -> %s)" % (first, last)


def test_hash_layer(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[2], dtype="int64")
        h = fluid.layers.hash(ids, hash_size=1000, num_hash=3)
    v = rng.randint(0, 10**6, (8, 2)).astype("int64")
    (out,) = _run(main, startup, {"ids": v}, [h])
    assert out.shape == (8, 3, 1)
    assert (out >= 0).all() and (out < 1000).all()
    # deterministic + different seeds give different streams
    (out2,) = _run(main, startup, {"ids": v}, [h])
    np.testing.assert_array_equal(out, out2)
    assert not (out[:, 0] == out[:, 1]).all()


def test_selected_rows_helpers():
    import jax.numpy as jnp

    from paddle_tpu.core.registry import get_op_impl, OpContext
    from paddle_tpu.core.sparse import SparseGrad

    class _Op:
        def __init__(self, type_, inputs, outputs):
            self.type, self.inputs, self.outputs, self.attrs = (
                type_, inputs, outputs, {})

    ids = jnp.array([3, 1, 3, 2], jnp.int32)
    rows = jnp.arange(8, dtype=jnp.float32).reshape(4, 2)
    env = {"x": SparseGrad(ids, rows)}
    get_op_impl("merge_selected_rows")(OpContext(
        _Op("merge_selected_rows", {"X": ["x"]}, {"Out": ["m"]}), env, None))
    m = env["m"]
    # id 3 appears twice: rows 0 and 2 summed
    got = {int(i): np.asarray(m.rows)[j] for j, i in enumerate(m.ids) if i < 2**31 - 1}
    np.testing.assert_allclose(got[3], np.asarray(rows[0] + rows[2]))
    np.testing.assert_allclose(got[1], np.asarray(rows[1]))
    np.testing.assert_allclose(got[2], np.asarray(rows[3]))
    get_op_impl("get_tensor_from_selected_rows")(OpContext(
        _Op("get_tensor_from_selected_rows", {"X": ["x"]}, {"Out": ["t"]}),
        env, None))
    np.testing.assert_allclose(np.asarray(env["t"]), np.asarray(rows))


def test_spectral_norm_normalizes(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        w = fluid.layers.data("w", shape=[6, 4], append_batch_size=False)
        sn = fluid.layers.spectral_norm(w, dim=0, power_iters=20)
    ws = rng.randn(3, 6, 4).astype("float32")[0]  # op expects the raw matrix
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (out,) = exe.run(main, feed={"w": ws}, fetch_list=[sn])
    sigma = np.linalg.svd(ws, compute_uv=False)[0]
    np.testing.assert_allclose(out, ws / sigma, rtol=1e-3, atol=1e-4)


def test_sequence_conv_and_reshape(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[6, 4])
        length = fluid.layers.data("len", shape=[], dtype="int32")
        out = fluid.layers.sequence_conv(x, num_filters=8, filter_size=3,
                                         length=length, bias_attr=False)
        rs = fluid.layers.sequence_reshape(x, new_dim=2)
    xs = rng.randn(2, 6, 4).astype("float32")
    ln = np.array([6, 3], "int32")
    o, r = _run(main, startup, {"x": xs, "len": ln}, [out, rs])
    assert o.shape == (2, 6, 8)
    assert r.shape == (2, 12, 2)
    np.testing.assert_allclose(r.reshape(2, 6, 4), xs, rtol=1e-6)


def test_conv3d_transpose_and_tree_conv_build(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        v = fluid.layers.data("v", shape=[2, 4, 4, 4])
        up = fluid.layers.conv3d_transpose(v, num_filters=3, filter_size=2,
                                           stride=2, bias_attr=False)
        nodes = fluid.layers.data("nodes", shape=[5, 6])
        edges = fluid.layers.data("edges", shape=[4, 2], dtype="int32")
        tc = fluid.layers.tree_conv(nodes, edges, output_size=7,
                                    num_filters=2, bias_attr=False)
    vs = rng.randn(1, 2, 4, 4, 4).astype("float32")
    ns = rng.randn(1, 5, 6).astype("float32")
    es = np.array([[[1, 2], [1, 3], [2, 4], [0, 0]]], "int32")
    u, t = _run(main, startup, {"v": vs, "nodes": ns, "edges": es}, [up, tc])
    assert u.shape == (1, 3, 8, 8, 8)
    assert t.shape == (1, 5, 7, 2)


def test_affine_grid_and_similarity_focus(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        theta = fluid.layers.data("theta", shape=[2, 3])
        grid = fluid.layers.affine_grid(theta, out_shape=[2, 1, 4, 5])
        x = fluid.layers.data("x", shape=[3, 4, 4])
        sf = fluid.layers.similarity_focus(x, axis=1, indexes=[0, 2])
    th = np.tile(np.array([[[1, 0, 0], [0, 1, 0]]], "float32"), (2, 1, 1))
    xs = np.abs(rng.randn(2, 3, 4, 4)).astype("float32")
    g, s = _run(main, startup, {"theta": th, "x": xs}, [grid, sf])
    assert g.shape == (2, 4, 5, 2)
    assert s.shape == xs.shape and set(np.unique(s)).issubset({0.0, 1.0})


def test_teacher_student_loss_runs(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1])
        label = fluid.layers.data("label", shape=[1])
        loss = fluid.layers.teacher_student_sigmoid_loss(x, label)
    xs = rng.randn(6, 1).astype("float32")
    ls = rng.rand(6, 1).astype("float32")
    (out,) = _run(main, startup, {"x": xs, "label": ls}, [loss])
    assert np.isfinite(out).all()


def test_tensor_array_to_tensor_and_is_empty(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2, 3])
        arr = fluid.layers.create_array("float32")
        i0 = fluid.layers.fill_constant([1], "int64", 0)
        i1 = fluid.layers.fill_constant([1], "int64", 1)
        fluid.layers.array_write(x, i0, array=arr)
        fluid.layers.array_write(x * 2.0, i1, array=arr)
        out, idx = fluid.layers.tensor_array_to_tensor(arr, axis=0)
        empty = fluid.layers.is_empty(x)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(4, 2, 3).astype("float32")
    o, ix, em = exe.run(main, feed={"x": xs}, fetch_list=[out, idx, empty])
    # entries (each [4,2,3]) concatenated along entry axis 0
    assert o.shape[1:] == (2, 3) and o.shape[0] % 4 == 0
    np.testing.assert_allclose(o[:8], np.concatenate([xs, xs * 2.0], 0), rtol=1e-6)
    # Length convention: written entries report their extent, pad slots 0
    assert (ix[:2] == 4).all() and (ix[2:] == 0).all()
    assert em == False  # noqa: E712


def test_layers_load_roundtrip(rng, tmp_path):
    import os
    val = rng.randn(3, 4).astype("float32")
    np.save(os.path.join(tmp_path, "w.npy"), val)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        out = fluid.layers.create_tensor("float32", name="loaded")
        fluid.layers.load(out, os.path.join(tmp_path, "w.npy"))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    got, = exe.run(main, feed={}, fetch_list=[out])
    np.testing.assert_allclose(got, val, rtol=1e-6)


def test_detection_map_layer(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        det = fluid.layers.data("det", shape=[4, 6])
        gt = fluid.layers.data("gt", shape=[2, 5])
        m = fluid.layers.detection_map(det, gt)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    # one perfect detection for class 1, one gt -> AP 1.0
    det_np = np.full((1, 4, 6), -1.0, "float32")
    det_np[0, 0] = [1, 0.9, 0.1, 0.1, 0.5, 0.5]
    gt_np = np.zeros((1, 2, 5), "float32")
    gt_np[0, 0] = [1, 0.1, 0.1, 0.5, 0.5]
    val, = exe.run(main, feed={"det": det_np, "gt": gt_np}, fetch_list=[m])
    assert 0.99 < float(val) <= 1.0, val


def test_weight_norm_param_attr(rng):
    """WeightNormParamAttr: w = g*v/||v|| trains; g initialized to ||v|| so
    training starts at w == v (reference param_attr.py:178 semantics)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        y = fluid.layers.data("y", shape=[1])
        h = fluid.layers.fc(x, size=16, act="relu",
                            param_attr=fluid.WeightNormParamAttr(dim=1))
        out = fluid.layers.fc(h, size=1,
                              param_attr=fluid.WeightNormParamAttr(dim=None))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(out, y))
        fluid.optimizer.Adam(5e-2).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    scope = fluid.global_scope()
    # g == ||v|| at init (per-column for dim=1)
    v0 = scope.as_numpy("fc_0.w_0.w_v")
    g0 = scope.as_numpy("fc_0.w_0.w_g")
    np.testing.assert_allclose(g0, np.sqrt((v0 ** 2).sum(axis=0)), rtol=1e-5)
    xs = rng.randn(64, 8).astype("float32")
    ys = (xs[:, :1] * 0.5 + 0.2).astype("float32")
    losses = [float(exe.run(main, feed={"x": xs, "y": ys},
                            fetch_list=[loss])[0]) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])
    # both g and v moved (trainable reparameterization)
    assert not np.allclose(g0, scope.as_numpy("fc_0.w_0.w_g"))


def test_chunk_evaluator_and_evaluator_namespace():
    from paddle_tpu import evaluator, metrics

    m = metrics.ChunkEvaluator()
    m.update(np.array([10]), np.array([8]), np.array([6]))
    m.update(2, 4, 2)
    p, r, f1 = m.eval()
    assert abs(p - 8 / 12) < 1e-9 and abs(r - 8 / 12) < 1e-9
    assert abs(f1 - 8 / 12) < 1e-9
    assert evaluator.ChunkEvaluator is metrics.ChunkEvaluator
    assert evaluator.DetectionMAP is metrics.DetectionMAP
    with fluid.initializer.init_on_cpu():
        assert fluid.initializer.force_init_on_cpu()
    assert not fluid.initializer.force_init_on_cpu()
    fluid.profiler.reset_profiler()
