"""API surface freeze (reference: paddle/fluid/API.spec enforced by
tools/diff_api.py in CI): the committed API.spec must match the live
signatures — an intentional change regenerates it via
``python tools/print_signatures.py > API.spec`` in the same commit."""

import difflib
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_api_spec_is_current():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import print_signatures
    finally:
        sys.path.pop(0)
    live = print_signatures.collect()
    with open(os.path.join(REPO, "API.spec")) as f:
        committed = [l.rstrip("\n") for l in f if l.strip()]
    if live != committed:
        diff = "\n".join(difflib.unified_diff(
            committed, live, "API.spec (committed)", "API.spec (live)", lineterm=""))
        raise AssertionError(
            "Public API surface changed without updating API.spec.\n"
            "If intentional: python tools/print_signatures.py > API.spec\n" + diff)


def test_core_api_presence():
    """A few load-bearing names that must never silently vanish."""
    with open(os.path.join(REPO, "API.spec")) as f:
        spec = f.read()
    for needle in [
        "paddle_tpu.Executor",
        "paddle_tpu.layers.fc ",
        "paddle_tpu.layers.ssd_loss ",
        "paddle_tpu.optimizer.AdamOptimizer",
        "paddle_tpu.imperative.guard ",
        "paddle_tpu.io.save_inference_model ",
    ]:
        assert needle in spec, "missing from API.spec: %r" % needle
