"""Subprocess entry for the multi-process distributed test
(the role of the reference's dist_mnist.py run under test_dist_base.py).

Each process joins the jax.distributed cluster, builds the same program,
and trains over the GLOBAL mesh spanning all processes — the TPU-native
analog of the reference's multi-trainer NCCL2 mode.

Modes (DIST_MODE env):
  dp     — pure data parallel over a 1-axis mesh (default)
  dp_tp  — 2-D mesh {'data': n, 'model': 2} with column+row-parallel FC,
           composing data parallelism ACROSS processes with tensor
           parallelism (the reference has no TP at all; SURVEY §2.3).
  crash  — the multi-process CRASH DRILL: every rank trains the same
           replicated program independently (no cross-process collectives
           — the CPU backend cannot run them, and the drill's subject is
           the failure-handling fabric, not the math), coordinated through
           heartbeat/done marker files. Rank DIST_KILL_RANK SIGKILLs
           itself before step DIST_KILL_AT_STEP (a hard preemption);
           surviving ranks detect the lost peer at the end-of-run barrier
           (stale heartbeat, no done marker) and exit EXIT_PEER_LOST=43
           with a DIST_PEER_LOST diagnostic instead of hanging. Rank 0
           writes a rotating checkpoint after every step (DIST_CKPT_DIR);
           a restart-all with the same dir resumes from the last published
           serial, and per-step DIST_STEP:<rank>:<step>:<loss-hex> lines
           let the parent assert bit-exact loss parity with an
           uninterrupted run.

The task is learnable by construction: a fixed batch whose labels come from
a fixed random linear teacher, trained repeatedly — so the loss-decrease
assertion in the parent test is satisfiable (unlike round 1's fresh random
noise per step).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")

import jax

jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge as _xb

if _xb.backends_are_initialized():
    _xb._clear_backends()

import numpy as np


def make_batch(batch=8, dim=8, classes=4, seed=7):
    """Fixed learnable batch: labels from a fixed linear teacher of x."""
    rng = np.random.RandomState(seed)
    xs = rng.randn(batch, dim).astype("float32")
    teacher = rng.randn(dim, classes).astype("float32")
    ys = np.argmax(xs @ teacher, axis=1).astype("int64")[:, None]
    return xs, ys


EXIT_PEER_LOST = 43


def _build_crash_model(fluid):
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 11
    with fluid.program_guard(main_prog, startup):
        x = fluid.layers.data("x", shape=[8])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
    return main_prog, startup, loss


def crash_drill_main(pid: int, n: int, steps: int) -> None:
    """The crash-drill rank body: train, heartbeat, checkpoint (rank 0),
    self-kill on schedule, and hold a detection barrier at the end."""
    import signal
    import time

    import paddle_tpu as fluid

    ckpt_dir = os.environ["DIST_CKPT_DIR"]
    hb_dir = os.environ.get("DIST_HB_DIR", ckpt_dir)
    kill_rank = int(os.environ.get("DIST_KILL_RANK", "-1"))
    kill_at = int(os.environ.get("DIST_KILL_AT_STEP", "-1"))
    hb_timeout = float(os.environ.get("DIST_HB_TIMEOUT", "10"))
    os.makedirs(hb_dir, exist_ok=True)

    def mark(kind, payload=""):
        path = os.path.join(hb_dir, "%s_%d" % (kind, pid))
        with open(path, "w") as f:
            f.write(payload)

    main_prog, startup, loss = _build_crash_model(fluid)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    # resume ONLY when the restart policy says so (DIST_RESUME=1): a first
    # launch must not pick up a concurrent rank-0 save as its own past
    start = 0
    if os.environ.get("DIST_RESUME") == "1":
        args = fluid.io.load_checkpoint(exe, ckpt_dir, main_prog)
        if args is not None:
            start = int(args.get("step", 0))
            main_prog._tpu_step_counter = start
            print("DIST_RESUMED:%d:%d" % (pid, start), flush=True)
    mark("loaded")
    if pid == 0:
        # bootstrap barrier before the FIRST save: a slow-starting peer
        # must not restore a serial rank 0 published after racing ahead —
        # every rank resumes from the SAME step. Bounded wait; a peer that
        # never loads is caught by the end-of-run barrier below.
        deadline = time.monotonic() + hb_timeout
        waiting = set(range(n)) - {pid}
        while waiting and time.monotonic() < deadline:
            waiting = {p for p in waiting if not os.path.isfile(
                os.path.join(hb_dir, "loaded_%d" % p))}
            time.sleep(0.02)

    xs, ys = make_batch()
    for step in range(start, steps):
        if pid == kill_rank and step == kill_at:
            # hard preemption: no cleanup, no goodbye — the peers must
            # find out on their own
            os.kill(os.getpid(), signal.SIGKILL)
        l, = exe.run(main_prog, feed={"x": xs, "y": ys}, fetch_list=[loss])
        lv = np.float32(np.asarray(l).ravel()[0])
        print("DIST_STEP:%d:%d:%s" % (pid, step, lv.tobytes().hex()),
              flush=True)
        mark("hb", str(step))
        if pid == 0:
            # step+1 = "resume here"; rotation is rank 0's alone
            fluid.io.save_checkpoint(
                exe, ckpt_dir, main_prog, trainer_id=0,
                trainer_args={"step": step + 1}, max_num_checkpoints=3)
    mark("done")

    # End-of-run barrier with peer-loss detection: a real job would sit in
    # its final collective forever when a peer died — here the wait is
    # bounded, and a lost peer produces a CLEAN diagnostic + marked exit.
    deadline = time.monotonic() + hb_timeout
    missing = set(range(n)) - {pid}
    while missing and time.monotonic() < deadline:
        for peer in sorted(missing):
            if os.path.isfile(os.path.join(hb_dir, "done_%d" % peer)):
                missing.discard(peer)
        time.sleep(0.05)
    if missing:
        for peer in sorted(missing):
            hb = os.path.join(hb_dir, "hb_%d" % peer)
            last = "never-heartbeat"
            if os.path.isfile(hb):
                with open(hb) as f:
                    last = "last_step=%s" % (f.read().strip() or "?")
            print("DIST_PEER_LOST:rank=%d:lost=%d:%s:waited=%.1fs"
                  % (pid, peer, last, hb_timeout), flush=True)
        os._exit(EXIT_PEER_LOST)


def main():
    pid = int(os.environ["PADDLE_TRAINER_ID"])
    n = int(os.environ["PADDLE_TRAINERS_NUM"])
    mode = os.environ.get("DIST_MODE", "dp")
    steps = int(os.environ.get("DIST_STEPS", "5"))

    if mode == "crash":
        crash_drill_main(pid, n, steps)
        return

    import paddle_tpu as fluid

    if n > 1:
        fluid.parallel.init_distributed()
        assert jax.process_count() == n

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 11
    with fluid.program_guard(main_prog, startup):
        x = fluid.layers.data("x", shape=[8])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        if mode == "dp_tp":
            h = fluid.parallel.column_parallel_fc(x, size=16, act="relu")
            h = fluid.parallel.row_parallel_fc(h, size=16, act="relu")
            logits = fluid.layers.fc(h, size=4)
        else:
            h = fluid.layers.fc(x, size=16, act="relu")
            logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)

    # NCCL2-style transpile is a no-op but must keep the script contract
    t = fluid.transpiler.DistributeTranspiler()
    t.transpile(trainer_id=pid, program=main_prog, trainers=os.environ.get(
        "PADDLE_TRAINER_ENDPOINTS", str(n)))
    main_prog = t.get_trainer_program()

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    if mode == "dp_tp":
        ndev = len(jax.devices())
        assert ndev % 2 == 0, ndev
        prog = fluid.CompiledProgram(main_prog).with_mesh(
            {"data": ndev // 2, "model": 2}, loss_name=loss.name)
    else:
        prog = fluid.CompiledProgram(main_prog).with_data_parallel(
            loss_name=loss.name)

    xs, ys = make_batch()
    losses = []
    for step in range(steps):
        l, = exe.run(prog, feed={"x": xs, "y": ys}, fetch_list=[loss])
        losses.append(round(float(np.asarray(l)), 6))
    print("DIST_LOSSES:%d:%s" % (pid, ",".join(map(str, losses))), flush=True)


if __name__ == "__main__":
    main()
    sys.exit(0)
