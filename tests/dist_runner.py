"""Subprocess entry for the multi-process distributed test
(the role of the reference's dist_mnist.py run under test_dist_base.py).

Each process joins the jax.distributed cluster, builds the same program,
and trains over the GLOBAL mesh spanning all processes — the TPU-native
analog of the reference's multi-trainer NCCL2 mode.

Modes (DIST_MODE env):
  dp     — pure data parallel over a 1-axis mesh (default)
  dp_tp  — 2-D mesh {'data': n, 'model': 2} with column+row-parallel FC,
           composing data parallelism ACROSS processes with tensor
           parallelism (the reference has no TP at all; SURVEY §2.3).

The task is learnable by construction: a fixed batch whose labels come from
a fixed random linear teacher, trained repeatedly — so the loss-decrease
assertion in the parent test is satisfiable (unlike round 1's fresh random
noise per step).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")

import jax

jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge as _xb

if _xb.backends_are_initialized():
    _xb._clear_backends()

import numpy as np


def make_batch(batch=8, dim=8, classes=4, seed=7):
    """Fixed learnable batch: labels from a fixed linear teacher of x."""
    rng = np.random.RandomState(seed)
    xs = rng.randn(batch, dim).astype("float32")
    teacher = rng.randn(dim, classes).astype("float32")
    ys = np.argmax(xs @ teacher, axis=1).astype("int64")[:, None]
    return xs, ys


def main():
    pid = int(os.environ["PADDLE_TRAINER_ID"])
    n = int(os.environ["PADDLE_TRAINERS_NUM"])
    mode = os.environ.get("DIST_MODE", "dp")
    steps = int(os.environ.get("DIST_STEPS", "5"))

    import paddle_tpu as fluid

    if n > 1:
        fluid.parallel.init_distributed()
        assert jax.process_count() == n

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 11
    with fluid.program_guard(main_prog, startup):
        x = fluid.layers.data("x", shape=[8])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        if mode == "dp_tp":
            h = fluid.parallel.column_parallel_fc(x, size=16, act="relu")
            h = fluid.parallel.row_parallel_fc(h, size=16, act="relu")
            logits = fluid.layers.fc(h, size=4)
        else:
            h = fluid.layers.fc(x, size=16, act="relu")
            logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)

    # NCCL2-style transpile is a no-op but must keep the script contract
    t = fluid.transpiler.DistributeTranspiler()
    t.transpile(trainer_id=pid, program=main_prog, trainers=os.environ.get(
        "PADDLE_TRAINER_ENDPOINTS", str(n)))
    main_prog = t.get_trainer_program()

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    if mode == "dp_tp":
        ndev = len(jax.devices())
        assert ndev % 2 == 0, ndev
        prog = fluid.CompiledProgram(main_prog).with_mesh(
            {"data": ndev // 2, "model": 2}, loss_name=loss.name)
    else:
        prog = fluid.CompiledProgram(main_prog).with_data_parallel(
            loss_name=loss.name)

    xs, ys = make_batch()
    losses = []
    for step in range(steps):
        l, = exe.run(prog, feed={"x": xs, "y": ys}, fetch_list=[loss])
        losses.append(round(float(np.asarray(l)), 6))
    print("DIST_LOSSES:%d:%s" % (pid, ",".join(map(str, losses))), flush=True)


if __name__ == "__main__":
    main()
    sys.exit(0)
