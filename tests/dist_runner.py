"""Subprocess entry for the multi-process distributed test
(the role of the reference's dist_mnist.py run under test_dist_base.py).

Each process joins the jax.distributed cluster, builds the same program,
and trains data-parallel over the GLOBAL mesh spanning both processes —
the TPU-native analog of the reference's 2-trainer NCCL2 mode.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")

import jax

jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge as _xb

if _xb.backends_are_initialized():
    _xb._clear_backends()

import numpy as np


def main():
    pid = int(os.environ["PADDLE_TRAINER_ID"])
    n = int(os.environ["PADDLE_TRAINERS_NUM"])

    import paddle_tpu as fluid

    if n > 1:
        fluid.parallel.init_distributed()
        assert jax.process_count() == n

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 11
    with fluid.program_guard(main_prog, startup):
        x = fluid.layers.data("x", shape=[8])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)

    # NCCL2-style transpile is a no-op but must keep the script contract
    t = fluid.transpiler.DistributeTranspiler()
    t.transpile(trainer_id=pid, program=main_prog, trainers=os.environ.get(
        "PADDLE_TRAINER_ENDPOINTS", str(n)))
    main_prog = t.get_trainer_program()

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    prog = fluid.CompiledProgram(main_prog).with_data_parallel(loss_name=loss.name)
    rng = np.random.RandomState(0)  # same global data on every process
    losses = []
    for step in range(5):
        xs = rng.randn(8, 8).astype("float32")
        ys = rng.randint(0, 4, (8, 1)).astype("int64")
        l, = exe.run(prog, feed={"x": xs, "y": ys}, fetch_list=[loss])
        losses.append(round(float(np.asarray(l)), 6))
    print("DIST_LOSSES:%d:%s" % (pid, ",".join(map(str, losses))), flush=True)


if __name__ == "__main__":
    main()
    sys.exit(0)
