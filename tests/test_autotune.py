"""paddle_tpu.tune — the autotuning subsystem (ISSUE 9).

Covers the tentpole contracts: table persistence round-trip from a cold
cache dir, same-input determinism of the search result, corrupt-table
fallback that never crashes a training path, shipped v5e seed lookups on
CPU, the rerouted ``_tuned_block_sizes``/``_block_size``/softmax-xent tile
lookups, interpret-mode parity of every candidate the sweeps emit for
flash and sparse-adam (reusing the existing parity harness style), the
end-to-end-measured pass-gate tunable, and the serving ``decode_fuse``
knob + serve_bench provenance reporting.
"""

import json
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import tune
from paddle_tpu.tune import table as tt


class _Toy(tune.Tunable):
    """Deterministic synthetic tunable (no device timing)."""

    kernel = "test.toy"

    def default_shapes(self):
        return [{"n": 32}]

    def bucket(self, shape):
        return "n%d" % shape["n"]

    def candidates(self, shape):
        return [{"x": x} for x in (1, 2, 3, 4)]

    def default_config(self, shape):
        return {"x": 1}

    def cost(self, shape, config):
        return {"vmem_bytes": 1 << 40} if config["x"] == 4 else {}

    def build(self, shape, config):
        return (lambda: config["x"]), ()


def _toy_measure(fn, args, config=None, **kw):
    return float(abs(config["x"] - 2) + 1)  # best at x=2


@pytest.fixture
def tuned_table(tmp_path, monkeypatch):
    """Point the runtime table at a fresh per-test file."""
    path = str(tmp_path / "autotune_table.json")
    monkeypatch.setenv("PADDLE_TPU_TUNE_TABLE", path)
    return path


# -- table layer --------------------------------------------------------------


def test_shipped_v5e_seeds(tuned_table):
    """The checked-in shipped.json reproduces the hand-tuned v5e entries
    as the lookup result for tpu-v5e on any backend (acceptance). The
    tuned_table fixture points the runtime layer at an absent file so a
    developer's own tuned table can't shadow the shipped assertion."""
    for bucket in (tt.bucket_seq(8192, 8192), tt.bucket_seq(2048, 2048),
                   tt.bucket_seq(1024, 1024)):  # 1024 hits the wildcard
        cfg, src = tune.lookup("flash_attention", bucket, device="tpu-v5e")
        assert src == "shipped", (bucket, src)
        assert cfg == {"block_q": 512, "block_k": 512}
    cfg, src = tune.lookup("sparse_adam", tt.bucket_rows(4096, 64),
                           device="tpu-v5e")
    assert src == "shipped" and cfg == {"block": 128}


def test_default_on_unknown_device(tuned_table):
    cfg, src = tune.lookup("flash_attention", tt.bucket_seq(8192, 8192),
                           device="never-built-chip")
    assert cfg is None and src == "default"


def test_table_round_trip_cold_cache_dir(tmp_path, monkeypatch):
    """With only PADDLE_TPU_COMPILE_CACHE set (no explicit table env), the
    table lands next to the compile cache and survives a 'restart'
    (fresh read through the mtime-invalidated cache)."""
    monkeypatch.delenv("PADDLE_TPU_TUNE_TABLE", raising=False)
    monkeypatch.setenv("PADDLE_TPU_COMPILE_CACHE", str(tmp_path / "cc"))
    path = tune.table_path()
    assert path == os.path.join(str(tmp_path / "cc"), "autotune_table.json")
    assert not os.path.exists(path)  # cold
    written = tune.record("test.kern", "s512x512", {"block_q": 256},
                          device="cpu", median_ms=1.25)
    assert written == path and os.path.exists(path)
    cfg, src = tune.lookup("test.kern", "s512x512", device="cpu")
    assert src == "tuned" and cfg == {"block_q": 256}
    # the on-disk document is the versioned format with a complete entry
    with open(path) as f:
        doc = json.load(f)
    assert doc["format"] == tt.FORMAT
    ent = doc["entries"]["test.kern|s512x512|cpu"]
    assert ent["config"] == {"block_q": 256} and ent["median_ms"] == 1.25
    # record() merges — a second kernel must not clobber the first
    tune.record("other.kern", "*", {"z": 1}, device="cpu")
    assert tune.lookup("test.kern", "s512x512", device="cpu")[1] == "tuned"


def test_search_determinism_fixed_candidates(tuned_table):
    """Same fixed candidate list + deterministic measure => identical
    result AND byte-identical table entries (acceptance)."""
    toy = _Toy()
    r1 = tune.search(toy, measure=_toy_measure)
    e1 = tt.read_entries(tuned_table)
    r2 = tune.search(toy, measure=_toy_measure)
    e2 = tt.read_entries(tuned_table)
    assert r1.best == r2.best == {"x": 2}
    assert r1.best_ms == 1.0 and r1.default_ms == 2.0
    assert e1 == e2 and "test.toy|n32|%s" % tune.device_kind() in e1
    # the blown candidate was pruned, not timed
    pruned = [r for r in r1.rows if "pruned" in r]
    assert len(pruned) == 1 and pruned[0]["config"] == {"x": 4}


def test_corrupt_table_logs_once_and_falls_back(tuned_table, caplog):
    with open(tuned_table, "w") as f:
        f.write('{"format": "paddle_tpu.tune/1", "entries": {broken')
    with caplog.at_level(logging.WARNING, logger="paddle_tpu"):
        for _ in range(3):
            cfg, src = tune.lookup("flash_attention",
                                   tt.bucket_seq(512, 512), device="cpu")
            assert cfg is None and src == "default"
    warns = [r for r in caplog.records if "corrupt" in r.getMessage()]
    assert len(warns) == 1, "corrupt table must log exactly once"
    # a rebuilt table clears the failure and serves again
    tune.record("k", "b", {"v": 7}, device="cpu")
    assert tune.lookup("k", "b", device="cpu") == ({"v": 7}, "tuned")


def test_partially_written_table_falls_back(tuned_table):
    """Valid JSON that is not a complete table document (the shape a torn
    write or foreign file produces) must also degrade, not crash."""
    for payload in ('{"entries": {"a|b|c": {"config": {}}}}',   # no format
                    '{"format": "paddle_tpu.tune/1", "entries": '
                    '{"a|b": {"config": {}}}}',                  # bad key
                    '{"format": "paddle_tpu.tune/1", "entries": '
                    '{"a|b|c": {"config": 5}}}',                 # bad config
                    '[]'):
        with open(tuned_table, "w") as f:
            f.write(payload)
        tt._file_cache.pop(tuned_table, None)  # force re-parse
        cfg, src = tune.lookup("a", "b", device="c")
        assert cfg is None and src == "default", payload


def test_provenance_snapshot(tuned_table):
    tune.reset_provenance()
    tune.record("flash_attention", "s512x512", {"block_q": 256,
                                                "block_k": 128})
    tune.lookup("flash_attention", "s512x512")
    prov = tune.provenance_snapshot()
    assert prov["flash_attention"]["source"] == "tuned"
    assert prov["flash_attention"]["config"]["block_q"] == 256


# -- rerouted lookups ---------------------------------------------------------


def test_tuned_block_sizes_reroute(tuned_table):
    """_tuned_block_sizes consults the table first; tuned tiles clamp to
    the shape's divisors; no table => the hardcoded v5e fallback. The
    sweep's own make_block_sizes must agree with the serving-side mapping
    (one shared _block_sizes_for definition)."""
    from paddle_tpu.ops import attention_ops as ao

    tun = tune.get_tunable("flash_attention")
    assert tun.make_block_sizes({"block_q": 256, "block_k": 128},
                                512, 512) == ao._block_sizes_for(256, 128)

    # pure fallback (cold table): unchanged hand-tuned behavior
    bs = ao._tuned_block_sizes(8192, 8192)
    assert bs.block_q == 512 and bs.block_k == 512
    # tuned entry wins...
    tune.record("flash_attention", tt.bucket_seq(512, 512),
                {"block_q": 256, "block_k": 128})
    bs = ao._tuned_block_sizes(512, 512)
    assert bs.block_q == 256 and bs.block_k == 128
    assert bs.block_q_dkv == 256 and bs.block_k_major_dq == 128
    # ...and a tuned 512 serving a non-multiple length clamps to a divisor
    tune.record("flash_attention", tt.bucket_seq(384, 384),
                {"block_q": 512, "block_k": 512})
    bs = ao._tuned_block_sizes(384, 384)
    assert bs.block_q == 128 and bs.block_k == 128  # 384 = 3*128


def test_sparse_block_size_reroute(tuned_table):
    from paddle_tpu.ops.pallas_kernels.sparse_adam import _BLOCK, _block_size

    # pure fallback: the hardcoded default, rounded/shrunk as before
    assert _block_size(None, 1024, 16) == _BLOCK
    assert _block_size(None, 20, 16) == 24  # shrunk + rounded to 8
    assert _block_size(64, 1024, 16) == 64  # explicit int honored verbatim
    tune.record("sparse_adam", tt.bucket_rows(1024, 16), {"block": 32})
    assert _block_size(None, 1024, 16) == 32
    # explicit block still bypasses the table (the sweep's own calls)
    assert _block_size(64, 1024, 16) == 64


def test_softmax_xent_tile_reroute(tuned_table):
    from paddle_tpu.ops.pallas_kernels import softmax_xent as sx

    assert sx._tile_sizes(4096, 32768) == (sx._BN, sx._BV)  # fallback
    tune.record("softmax_xent", tt.bucket_nv(4096, 32768),
                {"block_n": 64, "block_v": 1024})
    assert sx._tile_sizes(4096, 32768) == (64, 1024)
    # insane tuned values sanitize to legal sublane/lane multiples
    tune.record("softmax_xent", tt.bucket_nv(4096, 32768),
                {"block_n": 3, "block_v": 100})
    assert sx._tile_sizes(4096, 32768) == (8, 128)


# -- candidate parity (interpret mode, real kernel bodies) --------------------


def _composed_attention(q, k, v, causal, sm_scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm_scale
    if causal:
        mask = jnp.tril(jnp.ones(s.shape[-2:], bool))
        s = jnp.where(mask, s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)


def test_flash_candidates_parity(rng):
    """EVERY candidate the flash sweep emits at its CPU shape must run the
    real kernel body (interpret mode) and match composed attention — a
    tuned config may only change speed, never numerics."""
    tun = tune.get_tunable("flash_attention")
    shape = tun.default_shapes()[0]
    cands = tun.candidates(shape)
    assert len(cands) >= 4
    from paddle_tpu.ops.pallas_kernels import flash_attention as fa

    b, h, s, d = shape["b"], shape["h"], shape["s"], shape["d"]
    q, k, v = (jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
               for _ in range(3))
    sm = 1.0 / d ** 0.5
    ref = _composed_attention(q, k, v, shape["causal"], sm)
    prev = fa.INTERPRET
    fa.INTERPRET = True
    try:
        for cfg in cands:
            bs = tun.make_block_sizes(cfg, s, s)
            out = fa.flash_attention(q, k, v, causal=shape["causal"],
                                     sm_scale=sm, block_sizes=bs)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
                err_msg="flash candidate %r diverged" % (cfg,))
    finally:
        fa.INTERPRET = prev


def test_sparse_adam_candidates_parity(rng):
    """EVERY candidate block size the sparse sweep emits must match the
    XLA scatter formulation (the test_sparse_kernel harness math) on
    duplicate-bearing ids."""
    from paddle_tpu.core.sparse import merge_rows
    from paddle_tpu.ops.pallas_kernels.sparse_adam import sparse_adam_rows

    tun = tune.get_tunable("sparse_adam")
    shape = tun.default_shapes()[0]
    vocab, dim, n = shape["vocab"], shape["dim"], shape["n"]
    ids = rng.randint(0, vocab, (n,)).astype(np.int32)
    ids[: n // 4] = ids[n // 4: n // 2]  # duplicates
    uniq, merged = merge_rows(jnp.asarray(ids),
                              jnp.asarray(rng.randn(n, dim).astype("float32")),
                              vocab)
    p = jnp.asarray(rng.randn(vocab, dim).astype("float32"))
    m = jnp.asarray(rng.randn(vocab, dim).astype("float32") * 0.1)
    v = jnp.asarray(np.abs(rng.randn(vocab, dim)).astype("float32"))
    b1, b2, eps, lr_t = 0.9, 0.999, 1e-8, 0.01
    m_rows = b1 * m[uniq] + (1 - b1) * merged
    v_rows = b2 * v[uniq] + (1 - b2) * jnp.square(merged)
    ref_p = p.at[uniq].add(-(lr_t * m_rows / (jnp.sqrt(v_rows) + eps)))
    cands = tun.candidates(shape)
    assert len(cands) >= 4
    for cfg in cands:
        k_p, k_m, k_v = sparse_adam_rows(p, m, v, uniq, merged, lr_t,
                                         b1, b2, eps, interpret=True,
                                         block=int(cfg["block"]))
        np.testing.assert_allclose(
            np.asarray(k_p), np.asarray(ref_p), rtol=1e-6, atol=1e-6,
            err_msg="sparse-adam candidate %r diverged" % (cfg,))


def test_softmax_xent_candidates_parity(rng):
    """Every (block_n, block_v) tile candidate computes the same loss as
    the XLA log_softmax reference."""
    from paddle_tpu.ops.pallas_kernels import softmax_xent as sx

    tun = tune.get_tunable("softmax_xent")
    shape = dict(n=32, v=512)  # smaller than the sweep point: fast + odd
    logits = jnp.asarray(rng.randn(shape["n"], shape["v"]).astype("float32"))
    labels = jnp.asarray(
        rng.randint(0, shape["v"], (shape["n"], 1)).astype(np.int32))
    ref = -jnp.take_along_axis(jax.nn.log_softmax(logits, axis=-1),
                               labels, axis=1)
    for cfg in tun.candidates(shape):
        bn, bv = sx._shrink_tiles(shape["n"], shape["v"],
                                  cfg["block_n"], cfg["block_v"])
        plog, plab, n_pad, v_pad = sx._pad_to(logits, labels, bn, bv)
        loss, lse = sx._call_fwd(plog, plab, bn, bv, True, 0.0, shape["v"])
        np.testing.assert_allclose(
            np.asarray(loss[:shape["n"]]), np.asarray(ref),
            rtol=2e-5, atol=2e-5,
            err_msg="softmax-xent tile %r diverged" % (cfg,))


# -- search driver ------------------------------------------------------------


def test_search_real_sparse_sweep_picks_within_noise(tuned_table):
    """A real (interpret-mode) micro-sweep must persist a winner whose
    measured time is the minimum of its candidate rows — 'within noise of
    the best candidate in its space' is exact here because the winner IS
    the measured min (acceptance)."""
    tun = tune.get_tunable("sparse_adam")
    shape = dict(vocab=64, dim=8, n=24)
    res = tune.search(tun, shape, candidates=[{"block": 8}, {"block": 16}],
                      reps=1, warmup=1)
    timed = [r for r in res.rows if "median_ms" in r]
    assert round(res.best_ms, 6) == min(r["median_ms"] for r in timed)
    assert res.written_path == tuned_table
    cfg, src = tune.lookup("sparse_adam", res.bucket)
    assert src == "tuned" and cfg == res.best


def test_search_failed_candidate_recorded_not_fatal(tuned_table):
    class _Flaky(_Toy):
        def build(self, shape, config):
            if config["x"] == 1:
                raise RuntimeError("boom")
            return super().build(shape, config)

    res = tune.search(_Flaky(), measure=_toy_measure)
    errs = [r for r in res.rows if "error" in r]
    assert len(errs) == 1 and "boom" in errs[0]["error"]
    assert res.best == {"x": 2} and res.default_ms is None


def test_pass_gates_tunable_end_to_end(tuned_table):
    """The pass-gate tunable measures REAL end-to-end step time on the
    optimized clone per gate set and persists a winner keyed on the
    program fingerprint."""
    from paddle_tpu.passes.pipeline import DEFAULT_PASS_NAMES

    tun = tune.get_tunable("pass_gates")
    try:
        shape = tun.default_shapes()[0]
        cands = tun.candidates(shape)
        assert cands[0] == {"disable": []}
        assert len(cands) == 1 + len(DEFAULT_PASS_NAMES)
        # 3 candidates keeps the test fast; each compiles its own clone
        res = tune.search(tun, shape, candidates=cands[:3], reps=2,
                          warmup=1)
        assert res.bucket.startswith("prog")
        assert all("median_ms" in r for r in res.rows)
        assert res.best in cands[:3]
        cfg, src = tune.lookup("pass_gates", res.bucket)
        assert src == "tuned" and cfg == res.best
    finally:
        tun.cleanup()


# -- serving knob -------------------------------------------------------------


def test_decode_fuse_auto_consults_table(tuned_table):
    from paddle_tpu import serving

    cfg = serving.ServingConfig(slots=4, page_size=8, max_seq=64,
                                decode_fuse="auto")
    assert cfg.decode_fuse == 1 and cfg.decode_fuse_source == "default"
    tune.record("serving.decode_fuse", tt.bucket_slots(4), {"decode_fuse": 2})
    cfg = serving.ServingConfig(slots=4, page_size=8, max_seq=64,
                                decode_fuse="auto")
    assert cfg.decode_fuse == 2 and cfg.decode_fuse_source == "tuned"
    # explicit ints keep bypassing the table
    cfg = serving.ServingConfig(slots=4, page_size=8, max_seq=64,
                                decode_fuse=3)
    assert cfg.decode_fuse == 3 and cfg.decode_fuse_source == "explicit"


def test_serve_bench_reports_decode_fuse_source(tuned_table):
    from tools.serve_bench import resolve_decode_fuse

    assert resolve_decode_fuse(2, 8) == (2, "explicit")
    assert resolve_decode_fuse(None, 8) == (1, "default")
    tune.record("serving.decode_fuse", tt.bucket_slots(8), {"decode_fuse": 4})
    assert resolve_decode_fuse(None, 8) == (4, "tuned")


def test_decode_fuse_tunable_space():
    tun = tune.get_tunable("serving.decode_fuse")
    shape = tun.default_shapes()[0]
    assert tun.default_config(shape) == {"decode_fuse": 1}
    assert {c["decode_fuse"] for c in tun.candidates(shape)} == {1, 2, 4}
    assert tun.bucket(shape) == "slots4"
