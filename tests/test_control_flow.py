"""Control-flow tests (mirrors reference test_while_op.py, test_cond.py-era
ifelse tests, test_recurrent_op.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def test_while_loop_sums(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.fill_constant([1], "int32", 0)
        n = fluid.layers.fill_constant([1], "int32", 10)
        s = fluid.layers.fill_constant([1], "float32", 0.0)
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond)
        with w.block():
            fluid.layers.assign(fluid.layers.cast(i, "float32") + s, s)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(i, n, cond=cond)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    out, iv = exe.run(main, feed={}, fetch_list=[s, i])
    assert float(out.item()) == sum(range(10))
    assert int(iv.item()) == 10


def test_while_requires_condition_update():
    main = fluid.Program()
    with fluid.program_guard(main):
        i = fluid.layers.fill_constant([1], "int32", 0)
        n = fluid.layers.fill_constant([1], "int32", 10)
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond)
        with pytest.raises(ValueError, match="infinite loop"):
            with w.block():
                fluid.layers.increment(i, in_place=True)


def test_cond_branches(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2])
        flag = fluid.layers.data("flag", shape=[], dtype="bool",
                                 append_batch_size=False)
        out = fluid.layers.cond(
            flag,
            lambda: fluid.layers.scale(x, scale=2.0),
            lambda: fluid.layers.scale(x, scale=-1.0),
        )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(3, 2).astype("float32")
    t, = exe.run(main, feed={"x": xs, "flag": np.array(True)}, fetch_list=[out])
    f, = exe.run(main, feed={"x": xs, "flag": np.array(False)}, fetch_list=[out])
    np.testing.assert_allclose(t, 2 * xs, rtol=1e-6)
    np.testing.assert_allclose(f, -xs, rtol=1e-6)


def test_static_rnn_accumulates(rng):
    T, B, D = 5, 3, 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[B, D], append_batch_size=False)
        # time-major input built by stacking the same row T times via feed
        x_tm = fluid.layers.data("x_tm", shape=[T, B, D], append_batch_size=False)
        h0 = fluid.layers.fill_constant([B, D], "float32", 0.0)
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            w = rnn.step_input(x_tm)
            prev = rnn.memory(init=h0)
            nxt = fluid.layers.elementwise_add(w, prev)
            rnn.update_memory(prev, nxt)
            rnn.step_output(nxt)
        outs = rnn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(T, B, D).astype("float32")
    got, = exe.run(main, feed={"x": xs[0], "x_tm": xs}, fetch_list=[outs])
    want = np.cumsum(xs, axis=0)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_static_rnn_trains(rng):
    """RNN through lax.scan must be differentiable end-to-end."""
    T, B, D, H = 4, 8, 6, 5
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x_tm = fluid.layers.data("x", shape=[T, B, D], append_batch_size=False)
        y = fluid.layers.data("y", shape=[B, 1], dtype="int64", append_batch_size=False)
        h0 = fluid.layers.fill_constant([B, H], "float32", 0.0)
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            w = rnn.step_input(x_tm)
            prev = rnn.memory(init=h0)
            h = fluid.layers.fc([w, prev], size=H, act="tanh")
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        outs = rnn()  # [T, B, H]
        last = fluid.layers.slice(outs, axes=[0], starts=[T - 1], ends=[T])
        last = fluid.layers.squeeze(last, axes=[0])
        logits = fluid.layers.fc(last, size=3)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(5e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(T, B, D).astype("float32")
    ys = rng.randint(0, 3, (B, 1)).astype("int64")
    losses = [float(exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])[0])
              for _ in range(30)]
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])
