"""Control-flow tests (mirrors reference test_while_op.py, test_cond.py-era
ifelse tests, test_recurrent_op.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def test_while_loop_sums(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.fill_constant([1], "int32", 0)
        n = fluid.layers.fill_constant([1], "int32", 10)
        s = fluid.layers.fill_constant([1], "float32", 0.0)
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond)
        with w.block():
            fluid.layers.assign(fluid.layers.cast(i, "float32") + s, s)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(i, n, cond=cond)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    out, iv = exe.run(main, feed={}, fetch_list=[s, i])
    assert float(out.item()) == sum(range(10))
    assert int(iv.item()) == 10


def test_while_requires_condition_update():
    main = fluid.Program()
    with fluid.program_guard(main):
        i = fluid.layers.fill_constant([1], "int32", 0)
        n = fluid.layers.fill_constant([1], "int32", 10)
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond)
        with pytest.raises(ValueError, match="infinite loop"):
            with w.block():
                fluid.layers.increment(i, in_place=True)


def test_cond_branches(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2])
        flag = fluid.layers.data("flag", shape=[], dtype="bool",
                                 append_batch_size=False)
        out = fluid.layers.cond(
            flag,
            lambda: fluid.layers.scale(x, scale=2.0),
            lambda: fluid.layers.scale(x, scale=-1.0),
        )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(3, 2).astype("float32")
    t, = exe.run(main, feed={"x": xs, "flag": np.array(True)}, fetch_list=[out])
    f, = exe.run(main, feed={"x": xs, "flag": np.array(False)}, fetch_list=[out])
    np.testing.assert_allclose(t, 2 * xs, rtol=1e-6)
    np.testing.assert_allclose(f, -xs, rtol=1e-6)


def test_static_rnn_accumulates(rng):
    T, B, D = 5, 3, 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[B, D], append_batch_size=False)
        # time-major input built by stacking the same row T times via feed
        x_tm = fluid.layers.data("x_tm", shape=[T, B, D], append_batch_size=False)
        h0 = fluid.layers.fill_constant([B, D], "float32", 0.0)
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            w = rnn.step_input(x_tm)
            prev = rnn.memory(init=h0)
            nxt = fluid.layers.elementwise_add(w, prev)
            rnn.update_memory(prev, nxt)
            rnn.step_output(nxt)
        outs = rnn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(T, B, D).astype("float32")
    got, = exe.run(main, feed={"x": xs[0], "x_tm": xs}, fetch_list=[outs])
    want = np.cumsum(xs, axis=0)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_static_rnn_trains(rng):
    """RNN through lax.scan must be differentiable end-to-end."""
    T, B, D, H = 4, 8, 6, 5
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x_tm = fluid.layers.data("x", shape=[T, B, D], append_batch_size=False)
        y = fluid.layers.data("y", shape=[B, 1], dtype="int64", append_batch_size=False)
        h0 = fluid.layers.fill_constant([B, H], "float32", 0.0)
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            w = rnn.step_input(x_tm)
            prev = rnn.memory(init=h0)
            h = fluid.layers.fc([w, prev], size=H, act="tanh")
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        outs = rnn()  # [T, B, H]
        last = fluid.layers.slice(outs, axes=[0], starts=[T - 1], ends=[T])
        last = fluid.layers.squeeze(last, axes=[0])
        logits = fluid.layers.fc(last, size=3)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(5e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(T, B, D).astype("float32")
    ys = rng.randint(0, 3, (B, 1)).astype("int64")
    losses = [float(exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])[0])
              for _ in range(30)]
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_ifelse_row_routing(rng):
    """IfElse routes rows by mask: rows with label<5 through branch A,
    others through branch B (reference: control_flow.py:1264 contract)."""
    import paddle_tpu as fluid

    x_np = rng.randn(8, 4).astype("float32")
    lab_np = rng.randint(0, 10, (8, 1)).astype("int64")
    x = fluid.layers.data("x", shape=[4])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    limit = fluid.layers.fill_constant([1], "int64", 5)
    cond_v = fluid.layers.less_than(label, limit)
    ie = fluid.layers.IfElse(cond_v)
    with ie.true_block():
        ie.output(fluid.layers.scale(ie.input(x), scale=2.0))
    with ie.false_block():
        ie.output(fluid.layers.scale(ie.input(x), scale=-1.0))
    out, = ie()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    o, = exe.run(feed={"x": x_np, "label": lab_np}, fetch_list=[out])
    exp = np.where(lab_np < 5, x_np * 2.0, x_np * -1.0)
    np.testing.assert_allclose(o, exp, rtol=1e-6)


def test_switch_first_match_wins(rng):
    """Piecewise-LR-style Switch: first true case assigns, later cases and
    default are suppressed."""
    import paddle_tpu as fluid

    step = fluid.layers.data("step", shape=[1], dtype="float32",
                             append_batch_size=False)
    lr = fluid.layers.tensor.create_global_var(
        [1], 0.0, "float32", persistable=True, name="sw_lr")
    b1 = fluid.layers.fill_constant([1], "float32", 10.0)
    b2 = fluid.layers.fill_constant([1], "float32", 20.0)
    with fluid.layers.Switch() as switch:
        with switch.case(fluid.layers.less_than(step, b1)):
            fluid.layers.tensor.assign(
                fluid.layers.fill_constant([1], "float32", 0.1), lr)
        with switch.case(fluid.layers.less_than(step, b2)):
            fluid.layers.tensor.assign(
                fluid.layers.fill_constant([1], "float32", 0.01), lr)
        with switch.default():
            fluid.layers.tensor.assign(
                fluid.layers.fill_constant([1], "float32", 0.001), lr)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    for s, expect in [(5.0, 0.1), (15.0, 0.01), (25.0, 0.001)]:
        o, = exe.run(feed={"step": np.asarray([s], "float32")}, fetch_list=[lr])
        assert abs(float(o[0]) - expect) < 1e-7, (s, float(o[0]))
