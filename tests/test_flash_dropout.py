"""In-kernel flash-attention dropout parity (r5).

The vendored kernels drop the NORMALIZED probabilities with a keep-mask
that is a pure coordinate hash (flash_attention._dropout_keep_tile), so a
composed reference can regenerate the identical mask outside the kernel
and the full forward AND backward must agree elementwise — executed here
through the real kernel bodies in Pallas interpret mode on CPU.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import attention_ops as ao
from paddle_tpu.ops.pallas_kernels import flash_attention as fa


@pytest.fixture(autouse=True)
def _interpret_kernels():
    fa.INTERPRET = True
    yield
    fa.INTERPRET = False


def _full_keep_mask(rate, seed, b, h, sq, sk):
    """The mask the kernels generate, computed in one shot per (b, h)."""
    rows = []
    for bi in range(b):
        heads = []
        for hi in range(h):
            heads.append(fa._dropout_keep_tile(rate, seed, bi, hi, 0, 0,
                                               (sq, sk)))
        rows.append(jnp.stack(heads))
    return jnp.stack(rows)  # [b, h, sq, sk] bool


def _composed(q, k, v, keep, causal, sm_scale, rate):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        sq, sk = s.shape[-2:]
        cm = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(cm, s, fa.DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    pd = jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", pd, v.astype(jnp.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_dropout_forward_matches_composed(rng, causal):
    b, h, s, d = 1, 2, 256, 64
    rate, sm_scale = 0.2, 0.125
    q = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    k = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    v = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    seed = jnp.asarray([1234], jnp.int32)
    o = ao._flash_dropout(q, k, v, seed, causal, sm_scale, rate)
    keep = _full_keep_mask(rate, 1234, b, h, s, s)
    ref = _composed(q, k, v, keep, causal, sm_scale, rate)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_dropout_grads_match_composed(rng, causal):
    """Drive the custom-vjp backward EAGERLY (interpret-mode pallas_calls
    cannot be traced on CPU — same constraint as test_ring_flash_parity)
    and compare against jax.grad of the composed reference."""
    b, h, s, d = 1, 2, 256, 64
    rate, sm_scale = 0.15, 0.125
    q = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    k = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    v = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    seed = jnp.asarray([77], jnp.int32)
    do = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))

    _, res = ao._flash_dropout_fwd(q, k, v, seed, causal, sm_scale, rate)
    dq, dk, dv, _ = ao._flash_dropout_bwd(causal, sm_scale, rate, res, do)

    keep = _full_keep_mask(rate, 77, b, h, s, s)

    def f_ref(q, k, v):
        return jnp.sum(_composed(q, k, v, keep, causal, sm_scale, rate) * do)

    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_, nm in zip((dq, dk, dv), g2, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=3e-4, atol=3e-4, err_msg=nm)


def test_flash_dropout_mask_properties(rng):
    """Keep-rate ~= 1-rate; masks differ across seeds and (b, h)."""
    rate = 0.25
    m1 = np.asarray(fa._dropout_keep_tile(rate, 1, 0, 0, 0, 0, (512, 512)))
    m2 = np.asarray(fa._dropout_keep_tile(rate, 2, 0, 0, 0, 0, (512, 512)))
    m3 = np.asarray(fa._dropout_keep_tile(rate, 1, 0, 1, 0, 0, (512, 512)))
    assert abs(m1.mean() - 0.75) < 0.01
    assert (m1 != m2).mean() > 0.2
    assert (m1 != m3).mean() > 0.2
    # tile-partition independence: quarter-tiles reassemble the full mask
    q1 = np.asarray(fa._dropout_keep_tile(rate, 1, 0, 0, 0, 256, (512, 256)))
    np.testing.assert_array_equal(m1[:, 256:], q1)


def test_flash_dropout_rate_zero_matches_plain(rng):
    b, h, s, d = 1, 1, 256, 64
    q = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    k = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    v = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    o_plain, _, _ = fa._flash_attention_impl(
        q, k, v, None, None, True, False, 0.125, 1, 128, 128, 128, False)
    keep = _full_keep_mask(0.0, 9, b, h, s, s)
    ref = _composed(q, k, v, keep, False, 0.125, 0.0)
    np.testing.assert_allclose(np.asarray(o_plain), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_dropout_multi_tile_parity(rng, causal):
    """Multi-tile coverage (2x2 q/k blocks, b=2, h=2): an offset mistake in
    any kernel's _dropout_keep_tile call would only show on non-first tiles
    or non-zero batch/head — drive the impl/bwd entries directly with block
    128 over s=256 so every coordinate term is nonzero somewhere."""
    b, h, s, d = 2, 2, 256, 64
    rate, sm_scale, blk = 0.2, 0.125, 128
    q = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    k = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    v = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    seed_arr = jnp.asarray([991], jnp.int32)
    o, l, m = fa._flash_attention_impl(
        q, k, v, None, None, True, causal, sm_scale, 1, blk, blk, blk, False,
        dropout_rate=rate, dropout_seed=seed_arr)
    keep = _full_keep_mask(rate, 991, b, h, s, s)
    ref = _composed(q, k, v, keep, causal, sm_scale, rate)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    do = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    di = jnp.sum(o.astype(jnp.float32) * do, axis=-1)
    dk_f, dv_f = fa._flash_attention_bwd_dkv(
        q, k, v, None, None, l, m, do, di,
        block_q_major=blk, block_q=blk, block_k_major=blk, block_k=blk,
        sm_scale=sm_scale, causal=causal,
        mask_value=fa.DEFAULT_MASK_VALUE, debug=False,
        dropout_rate=rate, dropout_seed=seed_arr)
    dq_f, _ = fa._flash_attention_bwd_dq(
        q, k, v, None, None, l, m, do, di,
        block_q_major=blk, block_k_major=blk, block_k=blk,
        sm_scale=sm_scale, causal=causal,
        mask_value=fa.DEFAULT_MASK_VALUE, debug=False,
        dropout_rate=rate, dropout_seed=seed_arr)

    def f_ref(q, k, v):
        return jnp.sum(_composed(q, k, v, keep, causal, sm_scale, rate) * do)

    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_, nm in zip((dq_f, dk_f, dv_f), g2, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=3e-4, atol=3e-4, err_msg=nm)
