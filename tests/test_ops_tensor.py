"""Per-op checks: tensor manipulation, fill/random, optimizer update ops
(mirrors test_reshape_op.py, test_concat_op.py, test_sgd_op.py,
test_adam_op.py, ...)."""

import numpy as np
import pytest

from paddle_tpu.testing import check_output, run_op


@pytest.fixture
def r():
    return np.random.RandomState(2)


def test_reshape_family(r):
    x = r.randn(2, 3, 4).astype("float32")
    check_output("reshape", {"X": x}, {"Out": x.reshape(6, 4)}, attrs={"shape": [6, 4]})
    check_output("reshape2", {"X": x}, {"Out": x.reshape(2, 12)}, attrs={"shape": [0, -1]})
    check_output("flatten", {"X": x}, {"Out": x.reshape(2, 12)}, attrs={"axis": 1})
    check_output("squeeze", {"X": x[:, :1]}, {"Out": x[:, 0]}, attrs={"axes": [1]})
    check_output("unsqueeze", {"X": x}, {"Out": x[:, None]}, attrs={"axes": [1]})
    check_output("transpose", {"X": x}, {"Out": x.transpose(2, 0, 1)},
                 attrs={"axis": [2, 0, 1]})


def test_concat_split_stack(r):
    a = r.randn(2, 3).astype("float32")
    b = r.randn(2, 5).astype("float32")
    check_output("concat", {"X": [("a", a), ("b", b)]},
                 {"Out": np.concatenate([a, b], 1)}, attrs={"axis": 1})
    x = r.randn(2, 6).astype("float32")
    got = run_op("split", {"X": x}, ["Out"], attrs={"num": 3, "axis": 1})
    # split writes multiple outputs under one slot; run_op returns the first
    s = run_op("split", {"X": x}, ["Out"], attrs={"sections": [2, 4], "axis": 1})
    np.testing.assert_allclose(np.asarray(s["Out"]), x[:, :2])
    c, d = r.randn(3).astype("float32"), r.randn(3).astype("float32")
    check_output("stack", {"X": [("c", c), ("d", d)]},
                 {"Y": np.stack([c, d])}, attrs={"axis": 0})


def test_slice_gather_scatter_pad(r):
    x = r.randn(4, 5).astype("float32")
    check_output("slice", {"Input": x}, {"Out": x[1:3, :2]},
                 attrs={"axes": [0, 1], "starts": [1, 0], "ends": [3, 2]})
    check_output("slice", {"Input": x}, {"Out": x[:, -2:]},
                 attrs={"axes": [1], "starts": [-2], "ends": [5]})
    idx = np.array([2, 0], dtype="int64")
    check_output("gather", {"X": x, "Index": idx}, {"Out": x[[2, 0]]})
    upd = r.randn(2, 5).astype("float32")
    want = x.copy(); want[[1, 3]] = upd
    check_output("scatter", {"X": x, "Ids": np.array([1, 3], "int64"), "Updates": upd},
                 {"Out": want})
    want_add = x.copy(); want_add[[1, 3]] += upd
    check_output("scatter", {"X": x, "Ids": np.array([1, 3], "int64"), "Updates": upd},
                 {"Out": want_add}, attrs={"overwrite": False}, atol=1e-5)
    check_output("pad", {"X": x}, {"Out": np.pad(x, [(1, 0), (0, 2)], constant_values=9.0)},
                 attrs={"paddings": [1, 0, 0, 2], "pad_value": 9.0})
    check_output("expand", {"X": x}, {"Out": np.tile(x, (2, 1))},
                 attrs={"expand_times": [2, 1]})


def test_fill_and_random_ops(r):
    check_output("fill_constant", {}, {"Out": np.full((2, 3), 7.0, "float32")},
                 attrs={"shape": [2, 3], "dtype": "float32", "value": 7.0})
    x = r.randn(5, 2).astype("float32")
    check_output("fill_zeros_like", {"X": x}, {"Out": np.zeros_like(x)})
    check_output("fill_constant_batch_size_like", {"Input": x},
                 {"Out": np.ones((5, 4), "float32")},
                 attrs={"shape": [1, 4], "dtype": "float32", "value": 1.0})
    u = np.asarray(run_op("uniform_random", {}, ["Out"],
                          attrs={"shape": [1000], "min": -2.0, "max": 2.0, "seed": 1})["Out"])
    assert -2.0 <= u.min() and u.max() <= 2.0 and abs(u.mean()) < 0.2
    g = np.asarray(run_op("gaussian_random", {}, ["Out"],
                          attrs={"shape": [2000], "mean": 1.0, "std": 2.0, "seed": 1})["Out"])
    assert abs(g.mean() - 1.0) < 0.2 and abs(g.std() - 2.0) < 0.2
    # determinism: same seed → same draw
    u2 = np.asarray(run_op("uniform_random", {}, ["Out"],
                           attrs={"shape": [1000], "min": -2.0, "max": 2.0, "seed": 1})["Out"])
    np.testing.assert_array_equal(u, u2)


def test_sgd_momentum_adam_updates(r):
    p = r.randn(4).astype("float32")
    g = r.randn(4).astype("float32")
    lr = np.array([0.1], "float32")
    check_output("sgd", {"Param": p, "Grad": g, "LearningRate": lr},
                 {"ParamOut": p - 0.1 * g}, atol=1e-6)

    v = r.randn(4).astype("float32")
    v_new = 0.9 * v + g
    check_output("momentum",
                 {"Param": p, "Grad": g, "Velocity": v, "LearningRate": lr},
                 {"ParamOut": p - 0.1 * v_new, "VelocityOut": v_new},
                 attrs={"mu": 0.9}, atol=1e-6)

    m = np.zeros(4, "float32"); vv = np.zeros(4, "float32")
    b1p = np.array([0.9], "float32"); b2p = np.array([0.999], "float32")
    m_new = 0.1 * g
    v_new2 = 0.001 * g * g
    lr_t = 0.1 * np.sqrt(1 - 0.999) / (1 - 0.9)
    want_p = p - lr_t * m_new / (np.sqrt(v_new2) + 1e-8)
    out = run_op("adam", {"Param": p, "Grad": g, "Moment1": m, "Moment2": vv,
                          "Beta1Pow": b1p, "Beta2Pow": b2p, "LearningRate": lr},
                 ["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut"],
                 attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8})
    np.testing.assert_allclose(np.asarray(out["ParamOut"]), want_p, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out["Beta1PowOut"]), [0.81], rtol=1e-5)


def test_rmsprop_adagrad_updates(r):
    p = r.randn(3).astype("float32")
    g = r.randn(3).astype("float32")
    lr = np.array([0.01], "float32")
    moment = np.abs(r.randn(3)).astype("float32")
    m_new = moment + g * g
    check_output("adagrad", {"Param": p, "Grad": g, "Moment": moment, "LearningRate": lr},
                 {"ParamOut": p - 0.01 * g / (np.sqrt(m_new) + 1e-6), "MomentOut": m_new},
                 attrs={"epsilon": 1e-6}, atol=1e-5)
    ms = np.abs(r.randn(3)).astype("float32")
    mom = np.zeros(3, "float32")
    ms_new = 0.9 * ms + 0.1 * g * g
    mom_new = 0.01 * g / np.sqrt(ms_new + 1e-10)
    check_output("rmsprop", {"Param": p, "Grad": g, "MeanSquare": ms, "Moment": mom,
                             "LearningRate": lr},
                 {"ParamOut": p - mom_new, "MeanSquareOut": ms_new},
                 attrs={"decay": 0.9, "epsilon": 1e-10, "momentum": 0.0}, atol=1e-5)


def test_compare_and_logical(r):
    x = np.array([1.0, 2.0, 3.0], "float32")
    y = np.array([2.0, 2.0, 2.0], "float32")
    check_output("less_than", {"X": x, "Y": y}, {"Out": x < y})
    check_output("equal", {"X": x, "Y": y}, {"Out": x == y})
    check_output("greater_equal", {"X": x, "Y": y}, {"Out": x >= y})
    a = np.array([True, False, True])
    b = np.array([True, True, False])
    check_output("logical_and", {"X": a, "Y": b}, {"Out": a & b})
    check_output("logical_not", {"X": a}, {"Out": ~a})


def test_where_label_smooth_interp(r):
    c = np.array([True, False])
    x = np.array([1.0, 2.0], "float32")
    y = np.array([9.0, 8.0], "float32")
    check_output("where", {"Condition": c, "X": x, "Y": y},
                 {"Out": np.where(c, x, y)})
    oh = np.eye(4, dtype="float32")[[0, 2]]
    want = 0.9 * oh + 0.1 / 4
    check_output("label_smooth", {"X": oh}, {"Out": want}, attrs={"epsilon": 0.1},
                 atol=1e-6)
    img = r.randn(1, 1, 2, 2).astype("float32")
    out = np.asarray(run_op("nearest_interp", {"X": img}, ["Out"],
                            attrs={"out_h": 4, "out_w": 4})["Out"])
    assert out.shape == (1, 1, 4, 4)
