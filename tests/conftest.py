"""Test config: force an 8-device virtual CPU mesh (SURVEY.md §4 implication c).

Tests never require real TPU hardware; sharding/collective tests use the
virtual devices, numeric tests run on CPU. Set before jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The axon sitecustomize pre-imports jax (capturing JAX_PLATFORMS=axon into
# jax.config) and may have initialized the TPU backend already — override the
# config and drop any initialized backends so the settings above take effect.
import jax as _jax

_jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge as _xb

if _xb.backends_are_initialized():
    _xb._clear_backends()

import numpy as np
import pytest


def pytest_configure(config):
    # tier-1 CI runs `-m 'not slow'`; slow marks the opt-out extras
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` run")


@pytest.fixture(autouse=True)
def _fresh_programs():
    """Each test gets fresh default programs + scope + name generator."""
    import paddle_tpu as fluid
    from paddle_tpu.core import unique_name
    from paddle_tpu.core.framework import switch_main_program, switch_startup_program
    from paddle_tpu.core.scope import Scope, scope_guard

    prev_main = switch_main_program(fluid.Program())
    prev_startup = switch_startup_program(fluid.Program())
    with unique_name.guard():
        with scope_guard(Scope()):
            yield
    switch_main_program(prev_main)
    switch_startup_program(prev_startup)


@pytest.fixture
def rng():
    return np.random.RandomState(42)
