"""Device-side observability (monitor/device.py): per-op named-scope
attribution in lowered HLO, cost/memory gauges from the AOT path, the
PADDLE_TPU_CHECK_NUMERICS=2 in-graph watchdog (run + run_steps, OPT_LEVEL
0 and 1), collective byte accounting on the 8-device CPU mesh, and the
flight-recorder crash-dump round-trip."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.enforce import EnforceNotMet
from paddle_tpu.monitor import device as dev
from paddle_tpu.monitor import metrics as mx


def _mlp_train(dim=8, hidden=16, classes=4):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[dim])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=hidden, act="relu")
        logits = fluid.layers.fc(h, size=classes)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _prepare_mlp(batch=4):
    main, startup, loss = _mlp_train()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    compiled = exe.prepare(
        main, feed={"x": ((batch, 8), "float32"),
                    "y": ((batch, 1), "int64")},
        fetch_list=[loss])
    return exe, main, loss, compiled


# -- 1. per-op attribution ----------------------------------------------------

def test_named_scopes_in_lowered_hlo():
    """Every Program op's <slot>:<type> scope survives into the lowered
    module's debug locations (fwd ops additionally under jvp(...))."""
    _, main, _, compiled = _prepare_mlp()
    txt = dev.lowered_scope_text(compiled._lowered)
    cov = dev.op_scope_coverage(txt)
    assert cov, "no named scopes in lowered HLO"
    types = {k.split(":", 1)[1] for k in cov}
    assert "mul" in types, cov          # fwd matmul (under jvp scope)
    assert "sgd" in types, cov          # optimizer op (plain scope)
    # labels are <source-op-index>:<type> — slot must be a valid op index
    n_ops = len(main.global_block.ops)
    assert all(0 <= int(k.split(":")[0]) < n_ops for k in cov), cov


def test_scopes_disabled_by_env(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_OP_SCOPES", "0")
    _, _, _, compiled = _prepare_mlp()
    cov = dev.op_scope_coverage(dev.lowered_scope_text(compiled._lowered))
    assert not cov, "PADDLE_TPU_OP_SCOPES=0 left scopes in HLO: %s" % cov


def test_cost_memory_gauges_populated_on_cpu():
    mx.enable()
    mx.reset()
    exe, main, loss, compiled = _prepare_mlp()
    snap = mx.snapshot()
    assert snap["device_profile/flops"]["value"] > 0
    assert snap["device_profile/bytes_accessed"]["value"] > 0
    assert snap["device_profile/peak_hbm_bytes"]["value"] > 0
    assert snap["device_profile/analyses"]["value"] >= 1
    # the full report: measured totals + analytic rows with stable slots
    rep = dev.step_report(compiled.program, compiled._aot, batch_size=4)
    assert rep["cost"]["flops"] > 0
    assert rep["memory"]["peak_hbm_bytes"] > 0
    rows = rep["op_costs"]
    assert rows and rows[0]["flops"] >= rows[-1]["flops"]  # sorted desc
    assert any(r["type"] == "mul" and r["intensity"] > 0 for r in rows)


def test_memory_report_pre_run():
    """Executor.memory_report: the authoritative pre-run figure
    (contrib.utils.memory_usage's docstring defers to it)."""
    main, startup, loss = _mlp_train()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rep = exe.memory_report(
        main, feed={"x": ((4, 8), "float32"), "y": ((4, 1), "int64")},
        fetch_list=[loss])
    assert rep["peak_hbm_bytes"] > 0
    assert rep["argument_bytes"] > 0
    for k in ("output_bytes", "temp_bytes"):
        assert k in rep


# -- 2. numerics watchdog -----------------------------------------------------

def _nan_prog():
    """log(x) at a known op position; feeding zeros makes THAT op the
    first non-finite producer (mean propagates downstream)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        # baggage BEFORE the faulting op, removed by OPT_LEVEL=1 DCE:
        # positional renumbering would shift the log op's index
        dead = fluid.layers.fc(x, size=8)
        bad = fluid.layers.log(x)
        out = fluid.layers.mean(bad)
    log_idx = [i for i, op in enumerate(main.global_block.ops)
               if op.type == "log"]
    assert len(log_idx) == 1
    return main, startup, out, log_idx[0]


@pytest.mark.parametrize("opt_level", ["0", "1"])
def test_watchdog_names_originating_op_run(monkeypatch, opt_level):
    monkeypatch.setenv("PADDLE_TPU_CHECK_NUMERICS", "2")
    monkeypatch.setenv("PADDLE_TPU_OPT_LEVEL", opt_level)
    main, startup, out, log_idx = _nan_prog()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    with pytest.raises(EnforceNotMet) as ei:
        exe.run(main, feed={"x": np.zeros((2, 4), "float32")},
                fetch_list=[out])
    msg = str(ei.value)
    # attributed to the SOURCE program's op index even after DCE deleted
    # the dead fc ops ahead of it (slot stamping, passes/analysis.py)
    assert "%d:log" % log_idx in msg, msg
    assert "CHECK_NUMERICS" in msg


@pytest.mark.parametrize("opt_level", ["0", "1"])
def test_watchdog_under_run_steps_fused_chunk(monkeypatch, opt_level):
    """The packed mask rides the fused chunk per step: a NaN planted in
    step 1 of a 4-step chunk is attributed to op AND step (the legacy
    post-step scan only ever saw the last fetch)."""
    monkeypatch.setenv("PADDLE_TPU_CHECK_NUMERICS", "2")
    monkeypatch.setenv("PADDLE_TPU_OPT_LEVEL", opt_level)
    main, startup, out, log_idx = _nan_prog()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    ones = np.ones((2, 4), "float32")
    feeds = iter([{"x": ones}, {"x": np.zeros((2, 4), "float32")},
                  {"x": ones}, {"x": ones}])
    with pytest.raises(EnforceNotMet) as ei:
        exe.run_steps(main, feeds, steps=4, fetch_list=[out], fetch_every=4)
    msg = str(ei.value)
    assert "%d:log" % log_idx in msg, msg
    assert "step 1 of the fused chunk" in msg, msg
    assert "run_steps" in msg


def test_watchdog_and_stats_exclude_sub_blocks(monkeypatch):
    """Regression: CHECK_NUMERICS=2 (and armed streaming stats) over a
    While sub-block must compile and run — a watchdog bit or stat row
    born inside a lax.while body cannot be stacked outside it, so the
    interpreter gates both collectors on the sub-block offset. Top-level
    ops keep full attribution; sub-block ops contribute nothing."""
    from paddle_tpu.monitor import numerics as num

    monkeypatch.setenv("PADDLE_TPU_CHECK_NUMERICS", "2")
    monkeypatch.setenv("PADDLE_TPU_NUMERICS", "1")
    monkeypatch.setenv("PADDLE_TPU_NUMERICS_EVERY", "1")
    num.reset()
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4])
            i = fluid.layers.fill_constant([1], "int32", 0)
            n = fluid.layers.fill_constant([1], "int32", 4)
            s = fluid.layers.fill_constant([1], "float32", 0.0)
            cond = fluid.layers.less_than(i, n)
            w = fluid.layers.While(cond)
            with w.block():
                fluid.layers.assign(fluid.layers.cast(i, "float32") + s, s)
                fluid.layers.increment(i, value=1, in_place=True)
                fluid.layers.less_than(i, n, cond=cond)
            bad = fluid.layers.log(x)
            out = fluid.layers.mean(bad)
        log_idx = [k for k, op in enumerate(main.global_block.ops)
                   if op.type == "log"][0]
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        ones = np.ones((2, 4), "float32")
        sv, ov = exe.run(main, feed={"x": ones}, fetch_list=[s, out])
        assert float(np.asarray(sv).item()) == sum(range(4))
        assert np.isfinite(np.asarray(ov)).all()
        # streaming stats saw only top-level ops: every recorded label's
        # slot sits below the 10_000 sub-block offset, and none of the
        # loop body's op types appear
        snap = num.snapshot()
        assert snap, "armed run folded no stats"
        for label in snap:
            slot, _, typ = label.partition(":")
            assert int(slot) < 10_000, label
            assert typ not in ("increment", "assign"), label
        # the watchdog still attributes a top-level NaN by source slot
        with pytest.raises(EnforceNotMet) as ei:
            exe.run(main, feed={"x": np.zeros((2, 4), "float32")},
                    fetch_list=[s, out])
        assert "%d:log" % log_idx in str(ei.value)
    finally:
        num.reset()


def test_watchdog_silent_on_finite_and_cache_keyed(monkeypatch):
    """Level 2 on finite data: no raise; flipping the env var re-plans
    (guarded/unguarded variants must not share a cache entry)."""
    main, startup, out, _ = _nan_prog()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    ones = np.ones((2, 4), "float32")
    monkeypatch.setenv("PADDLE_TPU_CHECK_NUMERICS", "0")
    r0, = exe.run(main, feed={"x": ones}, fetch_list=[out])
    monkeypatch.setenv("PADDLE_TPU_CHECK_NUMERICS", "2")
    r2, = exe.run(main, feed={"x": ones}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(r0), np.asarray(r2), rtol=1e-6)
    # and the guarded variant still catches after the unguarded ran
    with pytest.raises(EnforceNotMet):
        exe.run(main, feed={"x": np.zeros((2, 4), "float32")},
                fetch_list=[out])


def test_level1_fused_reduction_backstop(monkeypatch):
    """Level 1 (and legacy FLAGS_check_nan_inf): ONE fused device-side
    isfinite reduction, legacy error message naming the offending fetch."""
    monkeypatch.setenv("PADDLE_TPU_CHECK_NUMERICS", "1")
    main, startup, out, _ = _nan_prog()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    with pytest.raises(RuntimeError) as ei:
        exe.run(main, feed={"x": np.zeros((2, 4), "float32")},
                fetch_list=[out])
    assert "FLAGS_check_nan_inf" in str(ei.value)


def test_check_numerics_mask_helper():
    layout = [("0:mul", ("a",)), ("1:log", ("b",)), ("2:mean", ("c",))]
    dev.check_numerics_mask(np.ones(3, bool), layout)  # all finite: no-op
    with pytest.raises(EnforceNotMet) as ei:
        dev.check_numerics_mask(np.array([True, False, False]), layout)
    msg = str(ei.value)
    assert "1:log" in msg and "2:mean" in msg  # first + propagation
    # stacked [steps, K] chunk: step index reported
    m = np.ones((3, 3), bool)
    m[2, 1] = False
    with pytest.raises(EnforceNotMet) as ei:
        dev.check_numerics_mask(m, layout, driver="run_steps")
    assert "step 2 of the fused chunk" in str(ei.value)


def test_watchdog_attributes_early_microbatch_under_accumulation(monkeypatch):
    """Gradient accumulation scans microbatches; the watchdog bits must be
    ANDed across the chain — a NaN born in microbatch 0 of 4 is attributed
    to the originating forward op, not to the optimizer update it poisons."""
    monkeypatch.setenv("PADDLE_TPU_CHECK_NUMERICS", "2")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        bad = fluid.layers.log(x)
        loss = fluid.layers.mean(bad)
        fluid.optimizer.SGD(0.1).minimize(loss)
    log_idx = [i for i, op in enumerate(main.global_block.ops)
               if op.type == "log"][0]
    bs = fluid.BuildStrategy()
    bs.gradient_accumulation_steps = 4
    compiled = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, build_strategy=bs)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = np.ones((32, 4), "float32")
    feed[:8] = 0.0  # only microbatch 0 of 4 hits log(0)
    with pytest.raises(EnforceNotMet) as ei:
        exe.run(compiled, feed={"x": feed}, fetch_list=[loss])
    msg = str(ei.value)
    assert "%d:log" % log_idx in msg, msg


# -- 3. collective traffic accounting -----------------------------------------

def test_ring_attention_ppermute_bytes_counted():
    import jax.numpy as jnp

    from paddle_tpu.parallel.mesh import create_mesh
    from paddle_tpu.parallel.ring_attention import ring_attention

    mx.enable()
    mx.reset()
    sp = 4
    mesh = create_mesh({"sp": sp})
    b, h, s, d = 2, 2, 8 * sp, 8
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    with mesh:
        out = ring_attention(q, q + 0.1, q + 0.2, mesh, axis_name="sp")
    assert np.isfinite(np.asarray(out)).all()
    snap = dev.collectives_snapshot()
    # fwd records K and V rotations: 2 buffers x sp hops of the local
    # [b, h, s/sp, d] f32 block, per device
    blk = b * h * (s // sp) * d * 4
    assert snap.get("collectives/ppermute/bytes") == 2 * sp * blk, snap
    assert snap.get("collectives/ppermute/sp/bytes") == 2 * sp * blk
    assert snap.get("collectives/ppermute/calls") == 2 * sp


def test_all_to_all_bytes_counted_in_row_routing():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.sparse import route_rows_to_shards
    from paddle_tpu.parallel._compat import shard_map
    from paddle_tpu.parallel.mesh import create_mesh
    from jax.sharding import PartitionSpec as P

    mx.enable()
    mx.reset()
    n, d, nsh = 16, 4, 8
    mesh = create_mesh({"model": nsh})
    ids = np.arange(n * nsh, dtype=np.int64) % (nsh * 10)
    rows = np.ones((n * nsh, d), np.float32)

    def body(i, r):
        return route_rows_to_shards(i, r, nsh, 10, "model",
                                    invalid_index=nsh * 10)

    with mesh:
        rid, rrow = shard_map(
            body, mesh=mesh, in_specs=(P("model"), P("model", None)),
            out_specs=(P("model"), P("model", None)))(ids, rows)
    snap = dev.collectives_snapshot()
    assert snap.get("collectives/all_to_all/bytes", 0) > 0, snap
    assert snap.get("collectives/all_to_all/model/bytes", 0) > 0


def test_record_collective_shapes_and_gating(monkeypatch):
    mx.enable()
    mx.reset()
    arr = np.zeros((4, 8), np.float32)
    dev.record_collective("psum", "data", arr, per_step_calls=3)
    snap = dev.collectives_snapshot()
    assert snap["collectives/psum/bytes"] == 4 * 8 * 4 * 3
    assert snap["collectives/psum/calls"] == 3
    assert snap["collectives/psum/data/bytes"] == 4 * 8 * 4 * 3
    # disabled registry: inert
    mx.reset()
    mx.disable()
    try:
        dev.record_collective("psum", "data", arr)
        assert not dev.collectives_snapshot()
    finally:
        mx.enable()


# -- 4. flight recorder -------------------------------------------------------

def test_flight_recorder_dump_roundtrip(monkeypatch, tmp_path):
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_TPU_CHECK_NUMERICS", "2")
    main, startup, out, log_idx = _nan_prog()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    ones = np.ones((2, 4), "float32")
    exe.run(main, feed={"x": ones}, fetch_list=[out])  # a good step first
    with pytest.raises(EnforceNotMet):
        exe.run(main, feed={"x": np.zeros((2, 4), "float32")},
                fetch_list=[out])
    dumps = sorted(tmp_path.glob("flight_*.json"))
    assert dumps, "no flight-recorder dump on crash"
    with open(dumps[-1]) as f:
        doc = json.load(f)
    assert doc["reason"] == "executor.run"
    assert "%d:log" % log_idx in doc["exception"]
    steps = [e for e in doc["entries"] if e.get("driver") == "run"]
    assert len(steps) >= 2  # the good step AND the crashing step
    last = steps[-1]
    assert last["feed"] == [["x", "float32", [2, 4]]]
    assert last["fetch"] == [out.name]
    assert last["program"] == dev.program_fingerprint(main)
    assert "opt_level" in last and "metrics" in last
    assert doc["env"].get("PADDLE_TPU_CHECK_NUMERICS") == "2"


def test_flight_recorder_ring_capacity(tmp_path):
    fr = dev.FlightRecorder(str(tmp_path), capacity=3)
    main, startup, out, _ = _nan_prog()
    for i in range(7):
        fr.record_step("run", main, [("x", "float32", (2, 4))], ("out",),
                       extra={"i": i})
    path = fr.dump("test")
    with open(path) as f:
        doc = json.load(f)
    assert len(doc["entries"]) == 3
    assert [e["i"] for e in doc["entries"]] == [4, 5, 6]  # last N kept


def test_flight_recorder_unwritable_dir_preserves_original_error(
        monkeypatch, tmp_path):
    """A failing crash-dump (unwritable PADDLE_TPU_FLIGHT_DIR) must never
    replace the step error it was meant to explain."""
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("")
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(blocker / "sub"))
    monkeypatch.setenv("PADDLE_TPU_CHECK_NUMERICS", "2")
    main, startup, out, log_idx = _nan_prog()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    with pytest.raises(EnforceNotMet) as ei:  # NOT the dump's OSError
        exe.run(main, feed={"x": np.zeros((2, 4), "float32")},
                fetch_list=[out])
    assert "%d:log" % log_idx in str(ei.value)


def test_flight_recorder_off_by_default(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_FLIGHT_DIR", raising=False)
    assert dev.flight_recorder() is None


def test_program_fingerprint_tracks_structure():
    main, startup, out, _ = _nan_prog()
    fp1 = dev.program_fingerprint(main)
    assert fp1 == dev.program_fingerprint(main)  # memoized, stable
    with fluid.program_guard(main, startup):
        fluid.layers.mean(main.global_block.var(out.name))
    assert dev.program_fingerprint(main) != fp1  # structure changed


# -- run_steps + device profile compose ---------------------------------------

def test_run_steps_finite_with_watchdog(monkeypatch):
    """Guarded run_steps on finite data matches the unguarded driver.
    Fresh programs per mode (param init and the per-step RNG ride the
    program's step counter, so re-running startup on ONE program would
    draw different weights, not expose a watchdog difference)."""
    rng = np.random.RandomState(0)
    batches = [{"x": rng.randn(4, 8).astype("float32"),
                "y": rng.randint(0, 4, (4, 1)).astype("int64")}
               for _ in range(4)]

    def losses():
        with fluid.unique_name.guard():
            with fluid.scope_guard(fluid.Scope()):
                main, startup, loss = _mlp_train()
                main.random_seed = startup.random_seed = 11
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                return [r[0] for r in exe.run_steps(
                    main, iter(batches), steps=4, fetch_list=[loss],
                    fetch_every=2)]

    monkeypatch.setenv("PADDLE_TPU_CHECK_NUMERICS", "0")
    plain = losses()
    monkeypatch.setenv("PADDLE_TPU_CHECK_NUMERICS", "2")
    guarded = losses()
    np.testing.assert_allclose(plain, guarded, rtol=1e-6)
