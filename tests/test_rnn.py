"""RNN family tests: dynamic_lstm/dynamic_gru vs numpy references,
unit-step ops, stacked lstm, DynamicRNN, and the two reference book
workloads these ops gate (label_semantic_roles- and machine_translation-
style models training to decreasing loss).

Reference test model: python/paddle/fluid/tests/unittests/test_lstm_op.py,
test_gru_op.py, test_dynrnn_*.py, tests/book/test_label_semantic_roles.py,
tests/book/test_machine_translation.py.
"""

import numpy as np
import pytest

import paddle_tpu as fluid


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def np_lstm(x, w, b, length, hidden, peephole=False):
    """Numpy reference: gate order [i, f, c̃, o], padded+length semantics."""
    B, T, _ = x.shape
    h = np.zeros((B, hidden), "float64")
    c = np.zeros((B, hidden), "float64")
    hs = np.zeros((B, T, hidden), "float64")
    cs = np.zeros((B, T, hidden), "float64")
    gate_b = b[: 4 * hidden]
    if peephole:
        w_ic, w_fc, w_oc = np.split(b[4 * hidden:], 3)
    for t in range(T):
        gates = x[:, t] + gate_b + h @ w
        gi, gf, gc, go = np.split(gates, 4, axis=1)
        if peephole:
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        i, f = _sigmoid(gi), _sigmoid(gf)
        c_new = f * c + i * np.tanh(gc)
        if peephole:
            go = go + c_new * w_oc
        o = _sigmoid(go)
        h_new = o * np.tanh(c_new)
        alive = (t < length)[:, None]
        h = np.where(alive, h_new, h)
        c = np.where(alive, c_new, c)
        hs[:, t] = np.where(alive, h_new, 0.0)
        cs[:, t] = np.where(alive, c_new, 0.0)
    return hs, cs


def np_gru(x, w, b, length, hidden, origin_mode=False):
    B, T, _ = x.shape
    h = np.zeros((B, hidden), "float64")
    hs = np.zeros((B, T, hidden), "float64")
    w_ur, w_c = w[:, : 2 * hidden], w[:, 2 * hidden:]
    for t in range(T):
        xt = x[:, t] + b
        ur = _sigmoid(xt[:, : 2 * hidden] + h @ w_ur)
        u, r = np.split(ur, 2, axis=1)
        cand = np.tanh(xt[:, 2 * hidden:] + (r * h) @ w_c)
        h_new = (1 - u) * cand + u * h if origin_mode else u * cand + (1 - u) * h
        alive = (t < length)[:, None]
        h = np.where(alive, h_new, h)
        hs[:, t] = np.where(alive, h_new, 0.0)
    return hs


@pytest.mark.parametrize("peephole", [False, True])
def test_dynamic_lstm_matches_numpy(rng, peephole):
    B, T, H = 4, 6, 8
    x_np = rng.randn(B, T, 4 * H).astype("float32") * 0.5
    length_np = np.array([6, 3, 5, 1], "int64")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[T, 4 * H])
        length = fluid.layers.data("length", shape=[], dtype="int64")
        h, c = fluid.layers.dynamic_lstm(x, size=4 * H, length=length,
                                         use_peepholes=peephole)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        hv, cv = exe.run(main, feed={"x": x_np, "length": length_np},
                         fetch_list=[h, c])
        w = np.asarray(fluid.global_scope().find_var(
            [p.name for p in main.all_parameters() if ".w" in p.name][0]))
        b = np.asarray(fluid.global_scope().find_var(
            [p.name for p in main.all_parameters() if ".b" in p.name][0])).reshape(-1)
    ref_h, ref_c = np_lstm(x_np.astype("float64"), w.astype("float64"),
                           b.astype("float64"), length_np, H, peephole)
    np.testing.assert_allclose(hv, ref_h, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(cv, ref_c, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("origin_mode", [False, True])
def test_dynamic_gru_matches_numpy(rng, origin_mode):
    B, T, H = 3, 5, 6
    x_np = rng.randn(B, T, 3 * H).astype("float32") * 0.5
    length_np = np.array([5, 2, 4], "int64")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[T, 3 * H])
        length = fluid.layers.data("length", shape=[], dtype="int64")
        h = fluid.layers.dynamic_gru(x, size=H, length=length,
                                     origin_mode=origin_mode)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        hv, = exe.run(main, feed={"x": x_np, "length": length_np},
                      fetch_list=[h])
        w = np.asarray(fluid.global_scope().find_var(
            [p.name for p in main.all_parameters() if ".w" in p.name][0]))
        b = np.asarray(fluid.global_scope().find_var(
            [p.name for p in main.all_parameters() if ".b" in p.name][0])).reshape(-1)
    ref = np_gru(x_np.astype("float64"), w.astype("float64"),
                 b.astype("float64"), length_np, H, origin_mode)
    np.testing.assert_allclose(hv, ref, rtol=1e-4, atol=1e-5)


def test_lstm_unit_and_gru_unit(rng):
    B, H = 4, 5
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3])
        h_prev = fluid.layers.data("h_prev", shape=[H])
        c_prev = fluid.layers.data("c_prev", shape=[H])
        h, c = fluid.layers.lstm_unit(x, h_prev, c_prev, forget_bias=1.0)
        xg = fluid.layers.data("xg", shape=[3 * H])
        hg, _, _ = fluid.layers.gru_unit(xg, h_prev, size=3 * H)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {"x": rng.randn(B, 3).astype("float32"),
                "h_prev": rng.randn(B, H).astype("float32"),
                "c_prev": rng.randn(B, H).astype("float32"),
                "xg": rng.randn(B, 3 * H).astype("float32")}
        hv, cv, hgv = exe.run(main, feed=feed, fetch_list=[h, c, hg])
    assert hv.shape == (B, H) and cv.shape == (B, H) and hgv.shape == (B, H)
    assert np.isfinite(hv).all() and np.isfinite(hgv).all()


def test_stacked_bidirectional_lstm_shapes_and_masking(rng):
    B, T, D, H = 4, 7, 5, 6
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[T, D])
        length = fluid.layers.data("length", shape=[], dtype="int64")
        out, last_h, last_c = fluid.layers.lstm(
            x, hidden_size=H, num_layers=2, is_bidirec=True, length=length)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        length_np = np.array([7, 4, 2, 6], "int64")
        ov, hv, cv = exe.run(
            main, feed={"x": rng.randn(B, T, D).astype("float32"),
                        "length": length_np},
            fetch_list=[out, last_h, last_c])
    assert ov.shape == (B, T, 2 * H)
    assert hv.shape == (4, B, H) and cv.shape == (4, B, H)
    # padded positions are zeroed
    for b_i, L in enumerate(length_np):
        assert np.all(ov[b_i, L:] == 0.0)
        if L < T:
            assert np.any(ov[b_i, :L] != 0.0)


def test_dynamic_rnn_matches_dynamic_gru(rng):
    """A DynamicRNN whose body is a gru_unit must reproduce dynamic_gru."""
    B, T, H = 3, 5, 4
    x_np = rng.randn(B, T, 3 * H).astype("float32") * 0.5
    length_np = np.array([5, 3, 1], "int64")

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[T, 3 * H])
        length = fluid.layers.data("length", shape=[], dtype="int64")
        h_ref = fluid.layers.dynamic_gru(
            x, size=H, length=length,
            param_attr=fluid.ParamAttr(name="shared_w"),
            bias_attr=fluid.ParamAttr(name="shared_b"))

        drnn = fluid.layers.DynamicRNN()
        with drnn.block():
            x_t = drnn.step_input(x, length=length)
            prev = drnn.memory(shape=[H], value=0.0)
            h_t, _, _ = fluid.layers.gru_unit(
                x_t, prev, size=3 * H,
                param_attr=fluid.ParamAttr(name="shared_w"),
                bias_attr=fluid.ParamAttr(name="shared_b"))
            drnn.update_memory(prev, h_t)
            drnn.output(h_t)
        h_drnn = drnn()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        ref, got = exe.run(main, feed={"x": x_np, "length": length_np},
                           fetch_list=[h_ref, h_drnn])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_label_semantic_roles_style_model_trains(rng):
    """Stacked bidirectional dynamic_lstm token tagger (the book's
    label_semantic_roles workload shape, tests/book/test_label_semantic_roles.py)."""
    B, T, V, E, H, NTAG = 8, 10, 50, 16, 16, 5
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 1
    with fluid.program_guard(main, startup):
        words = fluid.layers.data("words", shape=[T], dtype="int64")
        length = fluid.layers.data("length", shape=[], dtype="int64")
        tags = fluid.layers.data("tags", shape=[T, 1], dtype="int64")
        emb = fluid.layers.embedding(words, size=[V, E])
        proj_f = fluid.layers.fc(emb, size=4 * H, num_flatten_dims=2)
        h_f, _ = fluid.layers.dynamic_lstm(proj_f, size=4 * H, length=length)
        proj_b = fluid.layers.fc(emb, size=4 * H, num_flatten_dims=2)
        h_b, _ = fluid.layers.dynamic_lstm(proj_b, size=4 * H, length=length,
                                           is_reverse=True)
        feat = fluid.layers.concat([h_f, h_b], axis=2)
        logits = fluid.layers.fc(feat, size=NTAG, num_flatten_dims=2)
        ce = fluid.layers.softmax_with_cross_entropy(logits, tags)
        mask = fluid.layers.unsqueeze(
            fluid.layers.sequence_mask(length, maxlen=T, dtype="float32"), axes=[2])
        loss = fluid.layers.elementwise_div(
            fluid.layers.reduce_sum(fluid.layers.elementwise_mul(ce, mask)),
            fluid.layers.reduce_sum(mask))
        fluid.optimizer.Adam(5e-3).minimize(loss)

    words_np = rng.randint(0, V, (B, T)).astype("int64")
    length_np = rng.randint(3, T + 1, (B,)).astype("int64")
    tags_np = (words_np % NTAG)[..., None].astype("int64")  # learnable mapping
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = []
        for _ in range(60):
            lv, = exe.run(main, feed={"words": words_np, "length": length_np,
                                      "tags": tags_np}, fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_machine_translation_style_model_trains(rng):
    """GRU encoder + attention DynamicRNN decoder (the book's
    machine_translation workload shape, tests/book/test_machine_translation.py)."""
    B, TS, TT, V, E, H = 6, 8, 7, 40, 12, 12
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 2
    with fluid.program_guard(main, startup):
        src = fluid.layers.data("src", shape=[TS], dtype="int64")
        src_len = fluid.layers.data("src_len", shape=[], dtype="int64")
        trg = fluid.layers.data("trg", shape=[TT], dtype="int64")
        trg_len = fluid.layers.data("trg_len", shape=[], dtype="int64")
        labels = fluid.layers.data("labels", shape=[TT, 1], dtype="int64")

        src_emb = fluid.layers.embedding(src, size=[V, E])
        enc_proj = fluid.layers.fc(src_emb, size=3 * H, num_flatten_dims=2)
        enc_out = fluid.layers.dynamic_gru(enc_proj, size=H, length=src_len)

        trg_emb = fluid.layers.embedding(trg, size=[V, E])
        drnn = fluid.layers.DynamicRNN()
        with drnn.block():
            y_t = drnn.step_input(trg_emb, length=trg_len)
            enc = drnn.static_input(enc_out)
            prev = drnn.memory(shape=[H], value=0.0)
            # dot-product attention over encoder states
            query = fluid.layers.fc(prev, size=H, bias_attr=False)
            scores = fluid.layers.matmul(
                enc, fluid.layers.unsqueeze(query, axes=[2]))  # [B,TS,1]
            att = fluid.layers.softmax(
                fluid.layers.squeeze(scores, axes=[2]))
            ctx_vec = fluid.layers.squeeze(
                fluid.layers.matmul(fluid.layers.unsqueeze(att, axes=[1]), enc),
                axes=[1])
            gates = fluid.layers.fc([y_t, ctx_vec], size=3 * H)
            h_t, _, _ = fluid.layers.gru_unit(gates, prev, size=3 * H)
            drnn.update_memory(prev, h_t)
            drnn.output(h_t)
        dec_out = drnn()
        logits = fluid.layers.fc(dec_out, size=V, num_flatten_dims=2)
        ce = fluid.layers.softmax_with_cross_entropy(logits, labels)
        mask = fluid.layers.unsqueeze(
            fluid.layers.sequence_mask(trg_len, maxlen=TT, dtype="float32"),
            axes=[2])
        loss = fluid.layers.elementwise_div(
            fluid.layers.reduce_sum(fluid.layers.elementwise_mul(ce, mask)),
            fluid.layers.reduce_sum(mask))
        fluid.optimizer.Adam(5e-3).minimize(loss)

    src_np = rng.randint(0, V, (B, TS)).astype("int64")
    src_len_np = rng.randint(3, TS + 1, (B,)).astype("int64")
    trg_np = rng.randint(0, V, (B, TT)).astype("int64")
    trg_len_np = rng.randint(2, TT + 1, (B,)).astype("int64")
    labels_np = np.roll(trg_np, -1, axis=1)[..., None].astype("int64")
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = []
        for _ in range(80):
            lv, = exe.run(main, feed={
                "src": src_np, "src_len": src_len_np, "trg": trg_np,
                "trg_len": trg_len_np, "labels": labels_np}, fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
