"""Builder-written Pallas kernel tests (interpret mode on CPU) + fused-path
gating and the no-silent-fallback contract for flash attention."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import attention_ops
from paddle_tpu.ops.pallas_kernels import fused_softmax_xent


def _ref_loss(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, labels.astype(jnp.int32), axis=-1)


@pytest.mark.parametrize("n,v", [(32, 1000), (64, 4096), (17, 300), (8, 128)])
def test_fused_softmax_xent_forward_parity(rng, n, v):
    logits = jnp.asarray(rng.randn(n, v).astype("float32") * 3)
    labels = jnp.asarray(rng.randint(0, v, (n, 1)).astype("int32"))
    loss = fused_softmax_xent(logits, labels, True)
    np.testing.assert_allclose(loss, _ref_loss(logits, labels), rtol=2e-5, atol=2e-5)


def test_fused_softmax_xent_grad_parity(rng):
    n, v = 24, 1536
    logits = jnp.asarray(rng.randn(n, v).astype("float32"))
    labels = jnp.asarray(rng.randint(0, v, (n, 1)).astype("int32"))
    w = jnp.asarray(rng.randn(n, 1).astype("float32"))  # non-uniform cotangent
    g1 = jax.grad(lambda x: (fused_softmax_xent(x, labels, True) * w).sum())(logits)
    g2 = jax.grad(lambda x: (_ref_loss(x, labels) * w).sum())(logits)
    np.testing.assert_allclose(g1, g2, rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("n,v", [(24, 1536), (17, 300)])
def test_fused_softmax_xent_label_smoothing(rng, n, v):
    """Smoothed loss/grad must match the composed formula (incl. v-padding)."""
    eps = 0.1
    logits = jnp.asarray(rng.randn(n, v).astype("float32") * 2)
    labels = jnp.asarray(rng.randint(0, v, (n, 1)).astype("int32"))

    def ref(x):
        logp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, labels.astype(jnp.int32), axis=-1)
        return (1 - eps) * nll + (eps / v) * (-logp.sum(-1, keepdims=True))

    loss = fused_softmax_xent(logits, labels, True, eps)
    np.testing.assert_allclose(loss, ref(logits), rtol=2e-5, atol=2e-5)
    w = jnp.asarray(rng.randn(n, 1).astype("float32"))
    g1 = jax.grad(lambda x: (fused_softmax_xent(x, labels, True, eps) * w).sum())(logits)
    g2 = jax.grad(lambda x: (ref(x) * w).sum())(logits)
    np.testing.assert_allclose(g1, g2, rtol=2e-4, atol=1e-5)


def test_fused_softmax_xent_bf16(rng):
    n, v = 16, 512
    logits = jnp.asarray(rng.randn(n, v).astype("float32")).astype(jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, v, (n, 1)).astype("int32"))
    loss = fused_softmax_xent(logits, labels, True)
    ref = _ref_loss(logits, labels)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref), rtol=2e-2, atol=2e-2)
    g = jax.grad(lambda x: fused_softmax_xent(x, labels, True).sum())(logits)
    assert g.dtype == jnp.bfloat16


def test_fused_gate_is_tpu_only():
    """On CPU the op must keep the composed XLA path (interpret-mode pallas
    would crawl); the gate also rejects tiny vocabs."""
    from paddle_tpu.ops.nn_ops import _fused_xent_ok

    assert jax.default_backend() == "cpu"
    assert not _fused_xent_ok(jnp.zeros((32, 32768)))


# -- flash-attention fallback contract ---------------------------------------


def _mk_qkv(rng, s=256, d=64):
    q = jnp.asarray(rng.randn(2, 4, s, d).astype("float32"))
    return q, q + 0.1, q + 0.2


@pytest.fixture
def _flash_any_seq():
    """Lower the profitability threshold so small test shapes take flash."""
    from paddle_tpu.flags import get_flag, set_flag

    old = get_flag("flash_attention_min_seq")
    set_flag("flash_attention_min_seq", 128)
    yield
    set_flag("flash_attention_min_seq", old)


def test_flash_failure_warns_not_silent(rng, monkeypatch, _flash_any_seq):
    """A failing Pallas flash call must emit a RuntimeWarning, not vanish."""
    q, k, v = _mk_qkv(rng)

    def boom(*a, **kw):
        raise ValueError("synthetic pallas failure")

    monkeypatch.setattr(attention_ops, "_on_tpu", lambda: True)
    monkeypatch.setattr(attention_ops, "_flash_fn", lambda: (boom, None))
    with pytest.warns(RuntimeWarning, match="falling back"):
        out = attention_ops.sdpa(q, k, v)
    assert out.shape == q.shape


def test_flash_failure_strict_mode_raises(rng, monkeypatch, _flash_any_seq):
    from paddle_tpu.flags import set_flag

    q, k, v = _mk_qkv(rng)

    def boom(*a, **kw):
        raise ValueError("synthetic pallas failure")

    monkeypatch.setattr(attention_ops, "_on_tpu", lambda: True)
    monkeypatch.setattr(attention_ops, "_flash_fn", lambda: (boom, None))
    set_flag("strict_fused_attention", True)
    try:
        with pytest.raises(RuntimeError, match="flash-attention failed"):
            attention_ops.sdpa(q, k, v)
    finally:
        set_flag("strict_fused_attention", False)


def test_flash_path_taken_when_gates_pass(rng, monkeypatch, _flash_any_seq):
    """When on 'TPU' with clean shapes, sdpa must call the flash kernel."""
    q, k, v = _mk_qkv(rng)
    called = {}

    def fake_flash(q, k, v, ab=None, segment_ids=None, causal=False,
                   sm_scale=1.0, block_sizes=None):
        called["yes"] = True
        called["block_sizes"] = block_sizes
        return q

    monkeypatch.setattr(attention_ops, "_on_tpu", lambda: True)
    monkeypatch.setattr(attention_ops, "_flash_fn", lambda: (fake_flash, None))
    attention_ops.sdpa(q, k, v, causal=True)
    assert called.get("yes"), "flash path not taken despite passing gates"


def test_flash_gate_rejects_causal_rectangular(rng, monkeypatch, _flash_any_seq):
    monkeypatch.setattr(attention_ops, "_on_tpu", lambda: True)
    q = jnp.zeros((2, 4, 128, 64))
    k = jnp.zeros((2, 4, 256, 64))
    assert not attention_ops._flash_ok(q, k, causal=True)
    assert attention_ops._flash_ok(q, k, causal=False) or attention_ops._flash_fn()[0] is None


def test_flash_gate_profitability_threshold(rng, monkeypatch):
    """Below the measured crossover (S=2048 with v5e-tuned BlockSizes, r4
    sweep) the composed path must win the gate; at/above it flash must."""
    monkeypatch.setattr(attention_ops, "_on_tpu", lambda: True)
    monkeypatch.setattr(attention_ops, "_flash_fn", lambda: (lambda *a, **k: None, None))
    q = jnp.zeros((2, 4, 1024, 64))
    assert not attention_ops._flash_ok(q, q, causal=False)
    q2 = jnp.zeros((2, 4, 2048, 64))
    assert attention_ops._flash_ok(q2, q2, causal=False)
    q8 = jnp.zeros((1, 4, 8192, 64))
    assert attention_ops._flash_ok(q8, q8, causal=False)


def test_tuned_block_sizes():
    """v5e tuning: 512x512 tiles when the sequence allows, largest divisor
    otherwise (blocks must divide the sequence lengths)."""
    bs = attention_ops._tuned_block_sizes(8192, 8192)
    assert bs.block_q == 512 and bs.block_k == 512
    assert bs.block_q_dkv == 512 and bs.block_k_major_dq == 512
    bs = attention_ops._tuned_block_sizes(2048, 2048)
    assert bs.block_q == 512
    bs = attention_ops._tuned_block_sizes(384, 2048)
    assert bs.block_q == 128 and bs.block_k == 512  # 384 = 3*128
