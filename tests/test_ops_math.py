"""Per-op numeric + grad checks: math / elementwise / reduce ops
(mirrors reference tests: test_elementwise_add_op.py, test_matmul_op.py,
test_reduce_op.py, ... via the OpTest harness)."""

import numpy as np
import pytest

from paddle_tpu.testing import check_grad, check_output, run_op


@pytest.fixture
def r():
    return np.random.RandomState(0)


def test_elementwise_add_broadcast_axis(r):
    x = r.randn(2, 3, 4).astype("float32")
    y = r.randn(3).astype("float32")
    check_output("elementwise_add", {"X": x, "Y": y}, {"Out": x + y.reshape(1, 3, 1)},
                 attrs={"axis": 1})
    y2 = r.randn(4).astype("float32")
    check_output("elementwise_add", {"X": x, "Y": y2}, {"Out": x + y2}, attrs={"axis": -1})


def test_elementwise_family(r):
    x = r.rand(3, 4).astype("float32") + 0.5
    y = r.rand(3, 4).astype("float32") + 0.5
    for op, fn in [("elementwise_add", np.add), ("elementwise_sub", np.subtract),
                   ("elementwise_mul", np.multiply), ("elementwise_div", np.divide),
                   ("elementwise_max", np.maximum), ("elementwise_min", np.minimum),
                   ("elementwise_pow", np.power)]:
        check_output(op, {"X": x, "Y": y}, {"Out": fn(x, y)}, atol=1e-5)
    check_grad("elementwise_mul", {"X": x, "Y": y}, ["X", "Y"], "Out")
    check_grad("elementwise_div", {"X": x, "Y": y}, ["X", "Y"], "Out", max_relative_error=1e-2)


def test_matmul_and_mul(r):
    x = r.randn(4, 5).astype("float32")
    y = r.randn(5, 3).astype("float32")
    check_output("matmul", {"X": x, "Y": y}, {"Out": x @ y}, atol=1e-4)
    check_output("matmul", {"X": x.T, "Y": y}, {"Out": x @ y},
                 attrs={"transpose_X": True}, atol=1e-4)
    check_output("matmul", {"X": x, "Y": y}, {"Out": 2.5 * (x @ y)},
                 attrs={"alpha": 2.5}, atol=1e-4)
    check_grad("matmul", {"X": x, "Y": y}, ["X", "Y"], "Out", max_relative_error=1e-2)

    x3 = r.randn(2, 3, 4).astype("float32")
    w = r.randn(12, 6).astype("float32")
    got = run_op("mul", {"X": x3, "Y": w}, ["Out"],
                 attrs={"x_num_col_dims": 1, "y_num_col_dims": 1})["Out"]
    np.testing.assert_allclose(np.asarray(got), x3.reshape(2, 12) @ w, atol=1e-4)


def test_batched_matmul(r):
    x = r.randn(3, 4, 5).astype("float32")
    y = r.randn(3, 5, 6).astype("float32")
    check_output("matmul", {"X": x, "Y": y}, {"Out": np.matmul(x, y)}, atol=1e-4)


def test_scale_sum_mean_sign_clip(r):
    x = r.randn(3, 4).astype("float32")
    check_output("scale", {"X": x}, {"Out": 2 * x + 1}, attrs={"scale": 2.0, "bias": 1.0})
    check_output("scale", {"X": x}, {"Out": 2 * (x + 1)},
                 attrs={"scale": 2.0, "bias": 1.0, "bias_after_scale": False})
    a, b = r.randn(3).astype("float32"), r.randn(3).astype("float32")
    check_output("sum", {"X": [("a", a), ("b", b)]}, {"Out": a + b})
    check_output("mean", {"X": x}, {"Out": np.mean(x)})
    check_output("sign", {"X": x}, {"Out": np.sign(x)})
    check_output("clip", {"X": x}, {"Out": np.clip(x, -0.5, 0.5)},
                 attrs={"min": -0.5, "max": 0.5})
    check_grad("mean", {"X": x}, ["X"], "Out")


def test_clip_by_norm(r):
    x = (r.randn(4, 4) * 10).astype("float32")
    norm = np.sqrt((x ** 2).sum())
    check_output("clip_by_norm", {"X": x}, {"Out": x * (1.0 / norm)},
                 attrs={"max_norm": 1.0}, rtol=1e-4)
    small = x * 0.001
    check_output("clip_by_norm", {"X": small}, {"Out": small}, attrs={"max_norm": 1.0})


def test_reduce_ops(r):
    x = r.randn(2, 3, 4).astype("float32")
    check_output("reduce_sum", {"X": x}, {"Out": x.sum(1)}, attrs={"dim": [1]}, atol=1e-5)
    check_output("reduce_mean", {"X": x}, {"Out": x.mean((0, 2), keepdims=True)},
                 attrs={"dim": [0, 2], "keep_dim": True}, atol=1e-5)
    check_output("reduce_max", {"X": x}, {"Out": x.max()}, attrs={"reduce_all": True})
    check_output("reduce_min", {"X": x}, {"Out": x.min(-1)}, attrs={"dim": [-1]})
    check_output("reduce_prod", {"X": x}, {"Out": x.prod(2)}, attrs={"dim": [2]}, rtol=1e-4)
    check_grad("reduce_sum", {"X": x}, ["X"], "Out", max_relative_error=1e-2)


def test_cumsum_and_norm(r):
    x = r.randn(3, 5).astype("float32")
    check_output("cumsum", {"X": x}, {"Out": np.cumsum(x, 1)}, attrs={"axis": 1}, atol=1e-5)
    rev = np.flip(np.cumsum(np.flip(x, 1), 1), 1)
    check_output("cumsum", {"X": x}, {"Out": rev}, attrs={"axis": 1, "reverse": True}, atol=1e-5)
    n = np.sqrt((x ** 2).sum(1, keepdims=True) + 1e-10)
    check_output("norm", {"X": x}, {"Out": x / n, "Norm": n}, attrs={"axis": 1}, atol=1e-5)
    check_output("squared_l2_norm", {"X": x}, {"Out": (x ** 2).sum()}, rtol=1e-5)
    check_output("l1_norm", {"X": x}, {"Out": np.abs(x).sum()}, rtol=1e-5)


def test_cast_increment_isfinite(r):
    x = r.randn(3).astype("float32")
    got = run_op("cast", {"X": x}, ["Out"], attrs={"out_dtype": "int32"})["Out"]
    np.testing.assert_array_equal(np.asarray(got), x.astype("int32"))
    check_output("increment", {"X": np.array([3.0], "float32")},
                 {"Out": np.array([5.0], "float32")}, attrs={"step": 2.0})
    assert bool(run_op("isfinite", {"X": np.array([1.0, np.inf])}, ["Out"])["Out"]) is False
    assert bool(run_op("has_nan", {"X": np.array([1.0, np.nan])}, ["Out"])["Out"]) is True
    assert bool(run_op("has_inf", {"X": np.array([1.0, np.nan])}, ["Out"])["Out"]) is False
