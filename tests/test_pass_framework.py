"""Program-pass framework (reference: ir/pass.h:32, REGISTER_PASS pass.h:207,
PassBuilder pybind.cc:981-1003; tester pattern: ir/fc_fuse_pass_tester.cc —
build a tiny program, apply, assert fused node counts)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.pass_framework import (
    FunctionPass, Pass, PassBuilder, get_pass, has_pass, register_pass)


def _count_ops(program, op_type):
    return sum(1 for op in program.global_block.ops if op.type == op_type)


def test_registry_and_builder_order():
    calls = []

    @register_pass("test_pass_a")
    def pass_a(program, p):
        calls.append("a")

    @register_pass("test_pass_b")
    class PassB(Pass):
        def apply_impl(self, program):
            calls.append("b")

    assert has_pass("test_pass_a") and has_pass("test_pass_b")
    with pytest.raises(ValueError, match="registered twice"):
        register_pass("test_pass_a")(lambda program, p: None)
    with pytest.raises(KeyError, match="not registered"):
        get_pass("no_such_pass")

    builder = PassBuilder(["test_pass_b"])
    builder.insert_pass(0, "test_pass_a")
    builder.append_pass(FunctionPass("inline", lambda prog, p: calls.append("c")))
    assert [p.name for p in builder.all_passes()] == [
        "test_pass_a", "test_pass_b", "inline"]
    builder.remove_pass(2)
    prog = fluid.Program()
    builder.apply_all(prog)
    assert calls == ["a", "b"]


def test_user_pass_runs_in_compiled_program_build(rng):
    """A user-registered custom pass plugged into BuildStrategy's
    PassBuilder runs during CompiledProgram's build step (VERDICT item 5's
    'done' criterion)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.fc(x, size=3, act="relu")
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    seen = {}

    class CountOpsPass(Pass):
        name = "count_ops_pass"

        def apply_impl(self, program):
            seen["ops"] = len(program.global_block.ops)
            seen["scope_is_set"] = self.attr("scope") is not None

    bs = fluid.compiler.BuildStrategy()
    bs.pass_builder().append_pass(CountOpsPass())
    compiled = fluid.compiler.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, build_strategy=bs)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(8, 4).astype("float32")
    exe.run(compiled, feed={"x": xs}, fetch_list=[loss])
    assert seen["ops"] > 0 and seen["scope_is_set"]
    # passes run once per compiled program, not once per step
    seen.clear()
    exe.run(compiled, feed={"x": xs}, fetch_list=[loss])
    assert seen == {}


def _build_conv_bn(bias):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 8, 8])
        c = fluid.layers.conv2d(img, num_filters=5, filter_size=3,
                                bias_attr=None if bias else False)
        out = fluid.layers.batch_norm(c, is_test=True)
        out = fluid.layers.relu(out)
    return main, startup, out


@pytest.mark.parametrize("bias", [True, False])
def test_conv_bn_fuse_numeric_parity(rng, bias):
    main, startup, out = _build_conv_bn(bias)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.global_scope()
    exe.run(startup)
    # make BN stats non-trivial so the fold actually moves numbers
    for p in main.list_vars():
        if p.name.endswith(".mean"):
            scope.set_var(p.name, rng.randn(5).astype("float32") * 0.1)
        if p.name.endswith(".var"):
            scope.set_var(p.name, np.abs(rng.randn(5)).astype("float32") + 0.5)
    xs = rng.randn(2, 3, 8, 8).astype("float32")
    (before,) = exe.run(main, feed={"img": xs}, fetch_list=[out])

    p = get_pass("conv_bn_fuse_pass").set_attr("scope", scope)
    p.apply(main)
    assert p.attr("fused_count") == 1
    assert _count_ops(main, "batch_norm") == 0
    assert _count_ops(main, "conv2d") == 1
    (after,) = exe.run(main, feed={"img": xs}, fetch_list=[out])
    np.testing.assert_allclose(after, before, rtol=2e-4, atol=2e-5)


def test_conv_bn_fuse_skips_training_bn(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, 8, 8])
        c = fluid.layers.conv2d(img, num_filters=4, filter_size=3)
        fluid.layers.batch_norm(c)  # training-mode BN: batch stats, no fold
    p = get_pass("conv_bn_fuse_pass").set_attr("scope", fluid.global_scope())
    p.apply(main)
    assert p.attr("fused_count") == 0
    assert _count_ops(main, "batch_norm") == 1


def test_conv_bn_fuse_skips_residual_add(rng):
    # conv → add(shortcut activation) → bn must NOT be treated as conv+bias
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[4, 8, 8])
        c = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                padding=1, bias_attr=False)
        summed = fluid.layers.elementwise_add(c, img)  # residual, not bias
        fluid.layers.batch_norm(summed, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    p = get_pass("conv_bn_fuse_pass").set_attr("scope", fluid.global_scope())
    p.apply(main)
    assert p.attr("fused_count") == 0
    assert _count_ops(main, "batch_norm") == 1


def test_fuse_pass_before_startup_is_noop(rng):
    # params not materialized yet → candidates are skipped, not crashed on
    main, startup, out = _build_conv_bn(bias=True)
    with fluid.scope_guard(fluid.Scope()):
        p = get_pass("conv_bn_fuse_pass").set_attr("scope", fluid.global_scope())
        p.apply(main)
        assert p.attr("fused_count") == 0
    assert _count_ops(main, "batch_norm") == 1


def test_inference_transpiler_uses_fuse_pass(rng):
    main, startup, out = _build_conv_bn(bias=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    t = fluid.transpiler.InferenceTranspiler()
    t.transpile(main, scope=fluid.global_scope())
    assert _count_ops(main, "batch_norm") == 0


def test_quant_passes_are_registered():
    import paddle_tpu.contrib.slim.quantization  # noqa: F401 — registers

    for name in ("quantization_transform_pass", "quantization_freeze_pass",
                 "convert_to_int8_pass", "conv_bn_fuse_pass"):
        assert has_pass(name), name
