"""Dual-executor loss-parity tests on the 8-device virtual CPU mesh.

Mirrors the reference's test_parallel_executor_mnist.py pattern
(parallel_executor_test_base.py): run the same program single-device and
data-parallel and assert losses match.
"""

import jax
import numpy as np
import pytest

import paddle_tpu as fluid


def _build_and_init(seed=1234):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=32, act="relu")
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9).minimize(loss)
    return main, startup, loss


def test_data_parallel_matches_single_device(rng):
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    xs = rng.randn(20 * 16, 16).astype("float32")
    ys = rng.randint(0, 4, (20 * 16, 1)).astype("int64")

    def run(parallel):
        with fluid.unique_name.guard():
            with fluid.scope_guard(fluid.Scope()):
                main, startup, loss = _build_and_init()
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                prog = main
                if parallel:
                    prog = fluid.CompiledProgram(main).with_data_parallel(loss_name=loss.name)
                losses = []
                for i in range(0, len(xs), 16):
                    l, = exe.run(prog, feed={"x": xs[i:i+16], "y": ys[i:i+16]},
                                 fetch_list=[loss])
                    losses.append(float(l))
                return losses

    single = run(parallel=False)
    parallel = run(parallel=True)
    np.testing.assert_allclose(single, parallel, rtol=1e-4, atol=1e-5)
    assert parallel[-1] < parallel[0]


def test_data_parallel_feed_actually_sharded(rng):
    """The feed batch must land sharded over the data axis (ICI-ready)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        out = fluid.layers.fc(x, size=4)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    prog = fluid.CompiledProgram(main).with_data_parallel()
    xs = rng.randn(16, 8).astype("float32")
    vals = exe.run(prog, feed={"x": xs}, fetch_list=[out], return_numpy=False)
    # output stays sharded on the batch axis across all 8 devices
    assert len(vals[0].sharding.device_set) == 8


def test_bench_scaling_harness_path():
    """The 1→N scaling harness (bench.py --mesh data=N) must run end-to-end
    on the virtual mesh: program compiles over the data mesh, feed shards,
    and the efficiency arithmetic is well-formed. (CPU numbers are labeled
    cpu-dryrun and are not performance evidence.)"""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        import bench
    finally:
        sys.path.pop(0)

    eps1, sps1 = bench.bench_transformer(batch=2, seq=16, vocab=64,
                                         n_devices=1, skip=1, iters=2)
    epsn, spsn = bench.bench_transformer(batch=8, seq=16, vocab=64,
                                         n_devices=4, skip=1, iters=2)
    assert eps1 > 0 and epsn > 0
    assert np.isfinite(epsn / (4 * eps1))
