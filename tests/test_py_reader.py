"""py_reader tests (reference contract: layers/io.py:636 py_reader +
test_py_reader_using_executor.py): reader-fed training matches feed-dict
training exactly, EOF/reset cycles work, and errors in the source propagate."""

import numpy as np
import pytest

import paddle_tpu as fluid


def _build(dim=16, classes=4, via_reader=False):
    if via_reader:
        reader = fluid.layers.py_reader(
            capacity=8, shapes=[[-1, dim], [-1, 1]], dtypes=["float32", "int64"],
            name="train_reader")
        img, label = fluid.layers.read_file(reader)
    else:
        reader = None
        img = fluid.layers.data("img", shape=[dim])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
    h = fluid.layers.fc(img, size=32, act="relu",
                        param_attr=fluid.ParamAttr(name="w1"), bias_attr=fluid.ParamAttr(name="b1"))
    logits = fluid.layers.fc(h, size=classes,
                             param_attr=fluid.ParamAttr(name="w2"), bias_attr=fluid.ParamAttr(name="b2"))
    loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return reader, loss


def _data(rng, n=64, dim=16, classes=4):
    xs = rng.randn(n, dim).astype("float32")
    ys = rng.randint(0, classes, (n, 1)).astype("int64")
    return xs, ys


def test_py_reader_matches_feed_dict(rng):
    xs, ys = _data(rng)
    batches = [(xs[i:i + 16], ys[i:i + 16]) for i in range(0, 64, 16)]

    # feed-dict run
    main1, startup1 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main1, startup1):
        _, loss1 = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup1)
        feed_losses = [float(exe.run(main1, feed={"img": bx, "label": by},
                                     fetch_list=[loss1])[0])
                       for bx, by in batches]

    # py_reader run (same param names → same init under same seed programs)
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        reader, loss2 = _build(via_reader=True)
    reader.decorate_tensor_provider(lambda: iter(batches))
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup2)
        reader.start()
        reader_losses = []
        with pytest.raises(fluid.core.EOFException):
            while True:
                reader_losses.append(
                    float(exe.run(main2, fetch_list=[loss2])[0]))
        reader.reset()

    np.testing.assert_allclose(reader_losses, feed_losses, rtol=1e-5)


def test_py_reader_epoch_restart(rng):
    xs, ys = _data(rng, n=32)
    batches = [(xs[i:i + 16], ys[i:i + 16]) for i in range(0, 32, 16)]
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader, loss = _build(via_reader=True)
    reader.decorate_tensor_provider(lambda: iter(batches))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    seen = 0
    for _epoch in range(3):
        reader.start()
        try:
            while True:
                exe.run(main, fetch_list=[loss])
                seen += 1
        except fluid.core.EOFException:
            reader.reset()
    assert seen == 6


def test_py_reader_paddle_reader_decoration(rng):
    """decorate_paddle_reader stacks per-sample tuples like a DataFeeder."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader, loss = _build(via_reader=True)

    def batched_samples():
        r = np.random.RandomState(0)
        for _ in range(3):
            yield [(r.randn(16).astype("float32"),
                    r.randint(0, 4, (1,)).astype("int64")) for _ in range(8)]

    reader.decorate_paddle_reader(batched_samples)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    reader.start()
    n = 0
    try:
        while True:
            exe.run(main, fetch_list=[loss])
            n += 1
    except fluid.core.EOFException:
        reader.reset()
    assert n == 3


def test_py_reader_source_error_propagates(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader, loss = _build(via_reader=True)

    def bad():
        yield (np.zeros((4, 16), "float32"), np.zeros((4, 1), "int64"))
        raise ValueError("synthetic reader failure")

    reader.decorate_tensor_provider(bad)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    reader.start()
    exe.run(main, fetch_list=[loss])  # first batch fine
    with pytest.raises(ValueError, match="synthetic reader failure"):
        while True:
            exe.run(main, fetch_list=[loss])


def test_explicit_feed_wins_over_reader(rng):
    """A FULL explicit feed bypasses the queue; a PARTIAL one raises (mixing
    queue arrays with caller rows would silently pair unrelated batches —
    round-2 advisor finding)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader, loss = _build(via_reader=True)
    img_name, lab_name = reader.var_names
    queue_x = np.zeros((4, 16), "float32")
    queue_y = np.zeros((4, 1), "int64")
    reader.decorate_tensor_provider(lambda: iter([(queue_x, queue_y)] * 2))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    reader.start()
    custom_x = rng.randn(4, 16).astype("float32")
    custom_y = np.ones((4, 1), "int64")
    lab_val, = exe.run(main, feed={img_name: custom_x, lab_name: custom_y},
                       fetch_list=[lab_name])
    np.testing.assert_array_equal(
        lab_val, custom_y), "explicit feed was clobbered by the reader queue"
    with pytest.raises(ValueError, match="feed all of them or none"):
        exe.run(main, feed={lab_name: custom_y}, fetch_list=[lab_name])


def test_py_reader_requires_start(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader, loss = _build(via_reader=True)
    reader.decorate_tensor_provider(lambda: iter([]))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    # not started → vars simply aren't fed → context-rich tracing error
    from paddle_tpu.core import EnforceNotMet

    with pytest.raises(EnforceNotMet, match="not materialized"):
        exe.run(main, fetch_list=[loss])
