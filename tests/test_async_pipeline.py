"""The async step pipeline: FetchHandle, run_steps, dispatch-plan cache,
prefetcher lifecycle, AOT prepare.

The load-bearing guarantee is numeric: the fused ``run_steps(fetch_every=k)``
driver and the non-blocking ``FetchHandle`` path must be BIT-IDENTICAL to
the plain per-step ``run()`` loop — same RNG stream (the step counter
carried through the scan), same optimizer state trajectory, same losses.
"""

import threading
import time
import traceback

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.executor import FetchHandle
from paddle_tpu.monitor import metrics as mx
from paddle_tpu.reader import DevicePrefetcher


def _mlp_program(with_dropout=True):
    """Tiny trainable MLP; dropout makes the per-step RNG stream observable
    so any counter drift between drivers breaks bit-for-bit parity."""
    x = fluid.layers.data("x", shape=[8])
    y = fluid.layers.data("y", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=8, act="relu")
    if with_dropout:
        h = fluid.layers.dropout(h, dropout_prob=0.3)
    logits = fluid.layers.fc(h, size=3)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, y))
    fluid.optimizer.Adam(1e-2).minimize(loss)
    return loss


def _feeds(rng, n, batch=4):
    return [{"x": rng.randn(batch, 8).astype("float32"),
             "y": rng.randint(0, 3, (batch, 1)).astype("int64")}
            for _ in range(n)]


def _fresh(build=_mlp_program):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return exe, main, loss


# -- FetchHandle --------------------------------------------------------------

def test_fetch_handle_matches_sync_run(rng):
    exe, main, loss = _fresh()
    feeds = _feeds(rng, 4)
    sync = [exe.run(main, feed=f, fetch_list=[loss])[0] for f in feeds[:2]]

    handle = exe.run(main, feed=feeds[2], fetch_list=[loss],
                     return_numpy=False)
    assert isinstance(handle, FetchHandle)
    assert len(handle) == 1 and handle.names == (loss.name,)
    # sequence protocol: raw device arrays, unpacking keeps working
    lv, = handle
    resolved, = handle.numpy()
    assert np.array_equal(resolved, np.asarray(lv))
    # numpy() is cached and stable
    again, = handle.numpy()
    assert np.array_equal(resolved, again)
    handle.block()
    assert handle.done()

    # the async path sits on the same trajectory as the sync one
    sync.append(resolved)
    exe2, main2, loss2 = _fresh()
    ref = [exe2.run(main2, feed=f, fetch_list=[loss2])[0] for f in feeds[:3]]
    for a, b in zip(ref, sync):
        assert np.array_equal(a, b)


def test_fetch_bytes_accounting_is_deferred_to_resolve(rng):
    exe, main, loss = _fresh()
    feed = _feeds(rng, 1)[0]
    exe.run(main, feed=feed, fetch_list=[loss])  # compile outside the probe
    mx.reset()
    h = exe.run(main, feed=feed, fetch_list=[loss], return_numpy=False)
    assert mx.snapshot()["executor/fetch_bytes"]["value"] == 0
    out, = h.numpy()
    assert mx.snapshot()["executor/fetch_bytes"]["value"] == out.nbytes


# -- run_steps ----------------------------------------------------------------

def test_run_steps_bitwise_matches_per_step_run(rng):
    feeds = _feeds(rng, 10)

    exe, main, loss = _fresh()
    ref = [exe.run(main, feed=f, fetch_list=[loss])[0] for f in feeds]

    exe2, main2, loss2 = _fresh()
    mx.reset()
    rows = exe2.run_steps(main2, iter(feeds), steps=10, fetch_list=[loss2],
                          fetch_every=4)  # chunks of 4, 4, 2
    assert len(rows) == 10
    for a, row in zip(ref, rows):
        assert np.array_equal(a, row[0])

    snap = mx.snapshot()
    assert snap["executor/run_steps_steps"]["value"] == 10
    # 10 steps in 3 fused dispatches (4+4+2)
    assert snap["executor/run_steps_dispatches"]["value"] == 3


def test_run_steps_dispatch_reduction_8x(rng):
    """The acceptance-criteria shape: fetch_every=8 → dispatches/step ÷ 8,
    losses bit-identical to the per-step loop."""
    feeds = _feeds(rng, 16)

    exe, main, loss = _fresh()
    ref = [exe.run(main, feed=f, fetch_list=[loss])[0] for f in feeds]

    exe2, main2, loss2 = _fresh()
    mx.reset()
    rows = exe2.run_steps(main2, iter(feeds), steps=16, fetch_list=[loss2],
                          fetch_every=8)
    snap = mx.snapshot()
    assert snap["executor/run_steps_dispatches"]["value"] == 2  # 16 steps / 8
    for a, row in zip(ref, rows):
        assert np.array_equal(a, row[0])


def test_run_steps_interleaves_with_run(rng):
    """run() → run_steps() → run() shares one step-counter stream and one
    scope state; the combined trajectory equals a pure run() loop."""
    feeds = _feeds(rng, 8)

    exe, main, loss = _fresh()
    ref = [exe.run(main, feed=f, fetch_list=[loss])[0] for f in feeds]

    exe2, main2, loss2 = _fresh()
    got = [exe2.run(main2, feed=feeds[0], fetch_list=[loss2])[0]]
    rows = exe2.run_steps(main2, iter(feeds[1:7]), steps=6,
                          fetch_list=[loss2], fetch_every=3)
    got += [r[0] for r in rows]
    got.append(exe2.run(main2, feed=feeds[7], fetch_list=[loss2])[0])
    for a, b in zip(ref, got):
        assert np.array_equal(a, b)


def test_run_steps_return_handles(rng):
    feeds = _feeds(rng, 6)
    exe, main, loss = _fresh()
    ref = [exe.run(main, feed=f, fetch_list=[loss])[0] for f in feeds]

    exe2, main2, loss2 = _fresh()
    handles = exe2.run_steps(main2, iter(feeds), steps=6, fetch_list=[loss2],
                             fetch_every=3, return_numpy=False)
    assert len(handles) == 2 and all(isinstance(h, FetchHandle)
                                     for h in handles)
    stacked = [h.numpy()[0] for h in handles]
    assert stacked[0].shape[0] == 3  # leading axis = chunk length
    flat = [row for s in stacked for row in s]
    for a, b in zip(ref, flat):
        assert np.array_equal(a, b)


def test_run_steps_drains_device_prefetcher(rng):
    feeds = _feeds(rng, 6)
    exe, main, loss = _fresh()
    ref = [exe.run(main, feed=f, fetch_list=[loss])[0] for f in feeds]

    exe2, main2, loss2 = _fresh()
    with DevicePrefetcher(iter(feeds), capacity=2) as pf:
        rows = exe2.run_steps(main2, pf, steps=6, fetch_list=[loss2],
                              fetch_every=2)
    for a, row in zip(ref, rows):
        assert np.array_equal(a, row[0])


def test_run_steps_stops_at_feed_exhaustion(rng):
    feeds = _feeds(rng, 5)
    exe, main, loss = _fresh()
    rows = exe.run_steps(main, iter(feeds), steps=None, fetch_list=[loss],
                         fetch_every=4)  # 4 + 1, steps unbounded
    assert len(rows) == 5


def test_run_steps_partial_final_batch_re_resolves(rng):
    """The last batch of a real epoch is smaller — run_steps must re-plan
    for the new shape mid-stream (like run()'s per-shape plans), matching
    the run()-per-step trajectory bit-for-bit."""
    feeds = _feeds(rng, 5, batch=4) + _feeds(rng, 1, batch=2)

    exe, main, loss = _fresh()
    ref = [exe.run(main, feed=f, fetch_list=[loss])[0] for f in feeds]

    exe2, main2, loss2 = _fresh()
    mx.reset()
    rows = exe2.run_steps(main2, iter(feeds), steps=6, fetch_list=[loss2],
                          fetch_every=4)
    # the collector cuts chunks at shape boundaries: 4@b4 | 1@b4 | 1@b2
    assert mx.snapshot()["executor/run_steps_dispatches"]["value"] == 3
    assert len(rows) == 6
    for a, row in zip(ref, rows):
        assert np.array_equal(a, row[0])


def test_run_steps_stops_owned_prefetcher_on_early_exit(rng):
    def endless():
        r = np.random.RandomState(0)
        while True:
            yield {"x": r.randn(4, 8).astype("float32"),
                   "y": r.randint(0, 3, (4, 1)).astype("int64")}

    # run_steps starts it -> run_steps stops it at steps
    exe, main, loss = _fresh()
    pf = DevicePrefetcher(endless(), capacity=2)
    rows = exe.run_steps(main, pf, steps=4, fetch_list=[loss], fetch_every=2)
    assert len(rows) == 4
    deadline = time.time() + 2.0
    while pf._thread.is_alive() and time.time() < deadline:
        time.sleep(0.01)
    assert not pf._thread.is_alive(), "run_steps abandoned its prefetcher"

    # caller-started prefetcher stays the caller's to stop
    exe2, main2, loss2 = _fresh()
    pf2 = DevicePrefetcher(endless(), capacity=2).start()
    exe2.run_steps(main2, pf2, steps=4, fetch_list=[loss2], fetch_every=2)
    assert pf2._thread.is_alive()
    pf2.stop()


def test_run_steps_grad_norm_gauge(rng, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_GRAD_NORM", "1")
    exe, main, loss = _fresh()
    assert monitor.GRAD_NORM_VAR in main.global_block.vars
    feeds = _feeds(rng, 4, batch=8)
    mx.reset()
    rows = exe.run_steps(main, iter(feeds), steps=4, fetch_list=[loss],
                         fetch_every=4)
    assert len(rows) == 4 and rows[0][0].size == 1  # hidden fetch stripped
    assert mx.snapshot()["optimizer/grad_global_norm"]["value"] > 0


def test_grad_norm_gauge_defers_to_handle_resolve(rng, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_GRAD_NORM", "1")
    exe, main, loss = _fresh()
    feed = _feeds(rng, 1, batch=8)[0]
    exe.run(main, feed=feed, fetch_list=[loss])
    mx.reset()
    h = exe.run(main, feed=feed, fetch_list=[loss], return_numpy=False)
    assert mx.snapshot()["optimizer/grad_global_norm"]["value"] == 0
    h.numpy()
    assert mx.snapshot()["optimizer/grad_global_norm"]["value"] > 0


# -- dispatch-plan cache ------------------------------------------------------

def test_dispatch_plan_cache_hits_and_invalidates_on_version_bump(rng):
    exe, main, loss = _fresh()
    feed = _feeds(rng, 1)[0]
    exe.run(main, feed=feed, fetch_list=[loss])
    mx.reset()
    exe.run(main, feed=feed, fetch_list=[loss])
    snap = mx.snapshot()
    assert snap["executor/plan_hit"]["value"] == 1
    assert snap["executor/plan_miss"]["value"] == 0
    assert snap["executor/cache_hit"]["value"] == 1

    # a program mutation bumps _version -> every cached plan is dropped
    v0 = main._version
    main.random_seed = 1234  # bumps version (seed is baked into the step)
    assert main._version > v0
    mx.reset()
    out, = exe.run(main, feed=feed, fetch_list=[loss])
    snap = mx.snapshot()
    assert snap["executor/plan_miss"]["value"] == 1
    assert snap["executor/cache_miss"]["value"] == 1  # new specialization too
    assert np.isfinite(out).all()


def test_dispatch_plan_misses_on_shape_change(rng):
    exe, main, loss = _fresh()
    exe.run(main, feed=_feeds(rng, 1, batch=4)[0], fetch_list=[loss])
    mx.reset()
    exe.run(main, feed=_feeds(rng, 1, batch=6)[0], fetch_list=[loss])
    snap = mx.snapshot()
    assert snap["executor/plan_hit"]["value"] == 0
    assert snap["executor/plan_miss"]["value"] == 1
    # and back: the original plan still hits
    mx.reset()
    exe.run(main, feed=_feeds(rng, 1, batch=4)[0], fetch_list=[loss])
    assert mx.snapshot()["executor/plan_hit"]["value"] == 1


def test_close_clears_caches_and_counter_dies_with_program(rng):
    exe, main, loss = _fresh()
    feed = _feeds(rng, 1)[0]
    exe.run(main, feed=feed, fetch_list=[loss])
    assert exe._cache
    assert getattr(main, "_tpu_step_counter", 0) > 0
    exe.close()
    assert not exe._cache
    # no executor-held per-program dict left to leak (the old bug)
    assert not hasattr(exe, "_step_counters")
    # plans + counters live on Program objects -> freed with them. Since the
    # default trace-time optimizer (PADDLE_TPU_OPT_LEVEL>=1), plans attach
    # to the optimized clone, which the SOURCE program owns via _opt_cache —
    # the chain still dies with `main`.
    optimized = exe._maybe_optimize(main, (loss.name,), fluid.global_scope())
    assert hasattr(optimized, "_dispatch_plans")
    if optimized is not main:
        assert any(optimized is p for _, p in main._opt_cache[1].values())


# -- prefetcher lifecycle -----------------------------------------------------

def test_prefetcher_propagates_worker_traceback(rng):
    def bad_source():
        yield {"x": np.ones((2, 2), "float32")}
        raise ValueError("exploding reader")

    pf = DevicePrefetcher(bad_source(), capacity=2)
    with pytest.raises(ValueError, match="exploding reader") as ei:
        for _ in pf:
            pass
    tb = "".join(traceback.format_tb(ei.value.__traceback__))
    assert "bad_source" in tb  # the worker's original frame survived


def test_prefetcher_stop_unblocks_worker(rng):
    def endless():
        i = 0
        while True:
            yield {"x": np.full((4,), i, "float32")}
            i += 1

    pf = DevicePrefetcher(endless(), capacity=2)
    it = iter(pf)
    next(it), next(it)
    assert pf._thread.is_alive()
    pf.stop()
    deadline = time.time() + 2.0
    while pf._thread.is_alive() and time.time() < deadline:
        time.sleep(0.01)
    assert not pf._thread.is_alive(), "stop() left the worker blocked"
    with pytest.raises(RuntimeError):
        pf.start()  # one-shot: no silent restart on a drained source


def test_prefetcher_reiterate_after_exhaustion_terminates(rng):
    """A second epoch loop over a drained prefetcher must terminate
    immediately (one worker per prefetcher now), not block in q.get()."""
    pf = DevicePrefetcher(iter([{"x": np.ones((2,), "float32")}]), capacity=2)
    assert len(list(pf)) == 1
    assert list(pf) == []  # immediate, no hang


def test_prefetcher_context_manager(rng):
    def endless():
        while True:
            yield {"x": np.zeros((4,), "float32")}

    with DevicePrefetcher(endless(), capacity=2) as pf:
        for i, feed in enumerate(pf):
            assert feed["x"].shape == (4,)
            if i >= 2:
                break
    t = pf._thread
    deadline = time.time() + 2.0
    while t.is_alive() and time.time() < deadline:
        time.sleep(0.01)
    assert not t.is_alive()


# -- AOT prepare --------------------------------------------------------------

def test_prepare_shares_cache_entry_with_run(rng):
    import jax

    exe, main, loss = _fresh()
    exe.prepare(main, feed={"x": jax.ShapeDtypeStruct((4, 8), np.float32),
                            "y": ((4, 1), "int64")}, fetch_list=[loss])
    mx.reset()
    out, = exe.run(main, feed=_feeds(rng, 1)[0], fetch_list=[loss])
    snap = mx.snapshot()
    assert snap["executor/cache_miss"]["value"] == 0  # prepare pre-built it
    assert np.isfinite(out).all()


def test_compile_cache_counters_registered():
    snap = mx.snapshot()
    assert "compile_cache/hit" in snap
    assert "compile_cache/miss" in snap
