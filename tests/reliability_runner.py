"""Subprocess entry for the kill/resume drill in test_reliability.py.

Runs ``run_supervised`` over a deterministic dropout model. Usage::

    python reliability_runner.py <checkpoint_dir> <total_steps>

Environment:
  PADDLE_TPU_FAULT_PLAN  e.g. ``executor.dispatch@3=preempt`` — the fault
                         framework SIGTERMs this process mid-run through
                         the real OS signal path, making the drill's kill
                         point deterministic (the parent still observes a
                         genuine SIGTERM-triggered checkpoint-and-exit).

Prints one ``SUP_STEP:<global_step>:<loss-bits-hex>`` line per executed
step (bit-exact comparison fodder), ``SUP_RESUMED:<start>`` when a
checkpoint was restored, and exits with ``EXIT_PREEMPTED`` (42) when the
run was preempted, 0 on completion.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np


def feed_source(start):
    def gen():
        s = start
        while True:
            r = np.random.RandomState(7000 + s)
            yield {"x": r.randn(8, 8).astype("float32"),
                   "y": r.randint(0, 4, (8, 1)).astype("int64")}
            s += 1
    return gen()


def main():
    ckpt_dir, total = sys.argv[1], int(sys.argv[2])

    import paddle_tpu as fluid
    from paddle_tpu.reliability import EXIT_PREEMPTED, run_supervised

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 4242
    with fluid.program_guard(main_prog, startup):
        x = fluid.layers.data("x", shape=[8])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        # dropout: the drill must prove the per-step RNG stream resumes too
        h = fluid.layers.dropout(h, dropout_prob=0.25)
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    res = run_supervised(
        exe, main_prog, feed_source, total, [loss],
        checkpoint_dir=ckpt_dir, fetch_every=2, checkpoint_every_steps=2,
        backoff_s=0.0, exit_on_preempt=False)
    if res.resumed:
        print("SUP_RESUMED:%d" % res.start_step, flush=True)
    for i, row in enumerate(res.losses):
        bits = np.float32(np.asarray(row[0]).ravel()[0]).tobytes().hex()
        print("SUP_STEP:%d:%s" % (res.start_step + i, bits), flush=True)
    sys.exit(EXIT_PREEMPTED if res.preempted else 0)


if __name__ == "__main__":
    main()
