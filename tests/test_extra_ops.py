"""Tests for the op-parity sweep batch (ops/extra_ops.py) + ModelAverage."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.layers.nn import LayerHelper


def _op(op_type, inputs, attrs=None, out_slots=("Out",), dtypes=None):
    helper = LayerHelper(op_type)
    outs = {}
    for i, s in enumerate(out_slots):
        outs[s] = helper.create_variable_for_type_inference(
            (dtypes or {}).get(s, "float32"))
    helper.append_op(op_type, inputs=inputs, outputs=outs, attrs=attrs or {})
    vals = [outs[s] for s in out_slots]
    return vals[0] if len(vals) == 1 else vals


def _run(fetch, feed):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feed, fetch_list=fetch if isinstance(fetch, list) else [fetch])


def test_add_position_encoding(rng):
    x_np = np.zeros((2, 6, 8), "float32")
    x = fluid.layers.data("x", shape=[6, 8])
    out = _op("add_position_encoding", {"X": x}, {"alpha": 1.0, "beta": 1.0})
    o, = _run(out, {"x": x_np})
    half = 4
    div = 10000.0 ** (np.arange(half) / half)
    pos = np.arange(6)[:, None]
    pe = np.concatenate([np.sin(pos / div), np.cos(pos / div)], 1)
    np.testing.assert_allclose(o[0], pe, rtol=1e-5, atol=1e-6)


def test_affine_grid_identity_pairs_with_grid_sampler(rng):
    """Identity theta → identity grid → grid_sampler returns the input."""
    x_np = rng.randn(1, 2, 5, 5).astype("float32")
    theta_np = np.array([[[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]], "float32")
    x = fluid.layers.data("x", shape=[2, 5, 5])
    th = fluid.layers.data("th", shape=[2, 3])
    grid = _op("affine_grid", {"Theta": th},
               {"output_shape": [1, 2, 5, 5]}, out_slots=("Output",))
    out = fluid.layers.grid_sampler(x, grid)
    o, = _run(out, {"x": x_np, "th": theta_np})
    np.testing.assert_allclose(o, x_np, rtol=1e-5, atol=1e-5)


def test_modified_huber_loss(rng):
    x_np = np.array([[-2.0], [-0.5], [0.5], [2.0]], "float32")
    y_np = np.array([[1.0], [1.0], [1.0], [1.0]], "float32")
    x = fluid.layers.data("x", shape=[1])
    y = fluid.layers.data("y", shape=[1])
    out = _op("modified_huber_loss", {"X": x, "Y": y},
              out_slots=("IntermediateVal", "Out"))[1]
    o, = _run(out, {"x": x_np, "y": y_np})
    np.testing.assert_allclose(o[:, 0], [8.0, 2.25, 0.25, 0.0], rtol=1e-5)


def test_teacher_student_sigmoid_loss(rng):
    x_np = rng.randn(6, 1).astype("float32")
    labels = np.array([[-2.0], [-1.0], [0.3], [0.9], [1.2], [2.0]], "float32")
    x = fluid.layers.data("x", shape=[1])
    y = fluid.layers.data("y", shape=[1])
    out = _op("teacher_student_sigmoid_loss", {"X": x, "Label": y},
              out_slots=("Y",))
    o, = _run(out, {"x": x_np, "y": labels})

    def ref(xv, lv):
        r = max(xv, 0.0)
        sp = np.log1p(np.exp(-abs(xv)))
        if lv < -1:
            return r + sp
        if lv < 0:
            return r - xv + sp
        if lv < 1:
            return (r + sp) + (r - xv * lv + sp)
        return (r - xv + sp) + (r - xv * (lv - 1.0) + sp)

    exp = [ref(float(x_np[i, 0]), float(labels[i, 0])) for i in range(6)]
    np.testing.assert_allclose(o[:, 0], exp, rtol=1e-5)


def test_sampling_id_distribution(rng):
    probs = np.tile(np.array([[0.0, 0.0, 1.0, 0.0]], "float32"), (64, 1))
    x = fluid.layers.data("x", shape=[4])
    out = _op("sampling_id", {"X": x}, dtypes={"Out": "int64"})
    o, = _run(out, {"x": probs})
    np.testing.assert_array_equal(o, np.full(64, 2))


def test_random_crop_shapes_and_determinism_in_test_mode(rng):
    x_np = rng.randn(2, 3, 10, 10).astype("float32")
    x = fluid.layers.data("x", shape=[3, 10, 10])
    out = _op("random_crop", {"X": x}, {"shape": [8, 8]})
    o, = _run(out, {"x": x_np})
    assert o.shape == (2, 3, 8, 8)
    # crop content must be a contiguous window of the input
    found = any(np.allclose(o[0, 0], x_np[0, 0, i:i + 8, j:j + 8])
                for i in range(3) for j in range(3))
    assert found


def test_sequence_conv_window(rng):
    b, t, d, f = 2, 6, 4, 5
    x_np = rng.randn(b, t, d).astype("float32")
    w_np = rng.randn(3 * d, f).astype("float32")
    lens = np.array([6, 4], "int64")
    x = fluid.layers.data("x", shape=[t, d])
    w = fluid.layers.data("w", shape=[3 * d, f], append_batch_size=False)
    ln = fluid.layers.data("ln", shape=[], dtype="int64")
    out = _op("sequence_conv", {"X": x, "Filter": w, "Length": ln},
              {"contextLength": 3, "contextStart": -1})
    o, = _run(out, {"x": x_np, "w": w_np, "ln": lens})
    # manual: row t = [x[t-1], x[t], x[t+1]] @ w with zero pad + length mask
    xm = x_np.copy()
    xm[1, 4:] = 0.0
    for bi in range(b):
        for ti in range(t):
            ctx = np.concatenate([
                xm[bi, ti - 1] if ti - 1 >= 0 else np.zeros(d),
                xm[bi, ti],
                xm[bi, ti + 1] if ti + 1 < t else np.zeros(d)])
            np.testing.assert_allclose(o[bi, ti], ctx @ w_np, rtol=1e-4, atol=1e-5)


def test_sequence_reshape(rng):
    x_np = rng.randn(2, 4, 6).astype("float32")
    x = fluid.layers.data("x", shape=[4, 6])
    out = _op("sequence_reshape", {"X": x}, {"new_dim": 3})
    o, = _run(out, {"x": x_np})
    np.testing.assert_allclose(o, x_np.reshape(2, 8, 3))


def test_spectral_norm_normalizes(rng):
    w_np = rng.randn(6, 8).astype("float32") * 3
    u0 = rng.randn(6).astype("float32")
    v0 = rng.randn(8).astype("float32")
    w = fluid.layers.data("w", shape=[6, 8], append_batch_size=False)
    u = fluid.layers.data("u", shape=[6], append_batch_size=False)
    v = fluid.layers.data("v", shape=[8], append_batch_size=False)
    out = _op("spectral_norm", {"Weight": w, "U": u, "V": v},
              {"power_iters": 20}, out_slots=("Out", "UOut", "VOut"))[0]
    o, = _run(out, {"w": w_np, "u": u0, "v": v0})
    sigma = np.linalg.svd(w_np, compute_uv=False)[0]
    np.testing.assert_allclose(np.linalg.svd(o, compute_uv=False)[0], 1.0, rtol=1e-3)
    np.testing.assert_allclose(o * sigma, w_np, rtol=1e-2, atol=1e-2)


def test_conv_shift_circular(rng):
    x_np = rng.randn(2, 8).astype("float32")
    y_np = rng.randn(2, 3).astype("float32")
    x = fluid.layers.data("x", shape=[8])
    y = fluid.layers.data("y", shape=[3])
    out = _op("conv_shift", {"X": x, "Y": y})
    o, = _run(out, {"x": x_np, "y": y_np})
    exp = np.zeros_like(x_np)
    for j in range(3):
        exp += np.roll(x_np, 1 - j, axis=1) * y_np[:, j:j + 1]
    np.testing.assert_allclose(o, exp, rtol=1e-5)


def test_fused_embedding_seq_pool(rng):
    w_np = rng.randn(20, 4).astype("float32")
    ids = np.array([[1, 2, 3], [4, 5, 0]], "int64")
    lens = np.array([3, 2], "int64")
    w = fluid.layers.data("w", shape=[20, 4], append_batch_size=False)
    i = fluid.layers.data("i", shape=[3], dtype="int64")
    ln = fluid.layers.data("ln", shape=[], dtype="int64")
    out = _op("fused_embedding_seq_pool", {"W": w, "Ids": i, "Length": ln})
    o, = _run(out, {"w": w_np, "i": ids, "ln": lens})
    np.testing.assert_allclose(o[0], w_np[[1, 2, 3]].sum(0), rtol=1e-5)
    np.testing.assert_allclose(o[1], w_np[[4, 5]].sum(0), rtol=1e-5)


def test_max_pool3d_with_index(rng):
    x_np = rng.randn(1, 2, 4, 4, 4).astype("float32")
    x = fluid.layers.data("x", shape=[2, 4, 4, 4])
    out, mask = _op("max_pool3d_with_index", {"X": x}, {"ksize": [2, 2, 2]},
                    out_slots=("Out", "Mask"), dtypes={"Mask": "int32"})
    o, m = _run([out, mask], {"x": x_np})
    exp = x_np.reshape(1, 2, 2, 2, 2, 2, 2, 2).max(axis=(3, 5, 7))
    np.testing.assert_allclose(o, exp)
    flat = x_np.reshape(1, 2, -1)
    got_via_mask = np.take_along_axis(flat, m.reshape(1, 2, -1), axis=2)
    np.testing.assert_allclose(got_via_mask.reshape(o.shape), o)


def test_fill_op():
    out = _op("fill", {}, {"shape": [2, 3], "dtype": "float32",
                           "value": [1, 2, 3, 4, 5, 6]})
    o, = _run(out, {})
    np.testing.assert_allclose(o, [[1, 2, 3], [4, 5, 6]])


def test_model_average_apply_restore(rng):
    dim = 4
    xs = rng.randn(32, dim).astype("float32")
    ys = (xs @ rng.randn(dim, 1)).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[dim])
        y = fluid.layers.data("y", shape=[1])
        pred = fluid.layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="w"))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.05).minimize(loss)
        ma = fluid.optimizer.ModelAverage(
            0.5, min_average_window=2, max_average_window=100)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    snaps = []
    for _ in range(6):
        exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        snaps.append(fluid.global_scope().as_numpy("w").copy())
    current = fluid.global_scope().as_numpy("w").copy()
    with ma.apply(exe):
        averaged = fluid.global_scope().as_numpy("w").copy()
    # restored afterwards
    np.testing.assert_allclose(fluid.global_scope().as_numpy("w"), current)
    # the average differs from the endpoint and lies inside the visited range
    assert not np.allclose(averaged, current)
    lo = np.minimum.reduce(snaps)
    hi = np.maximum.reduce(snaps)
    assert (averaged >= lo - 1e-5).all() and (averaged <= hi + 1e-5).all()


def test_tree_conv_matches_manual(rng):
    """3-node tree (1-2, 1-3), max_depth 2: patches from each root with the
    reference eta coefficients (tree2col.cc)."""
    f, out_sz, k, nmax = 4, 3, 2, 3
    nodes = rng.randn(1, nmax, f).astype("float32")
    edges = np.array([[[1, 2], [1, 3], [0, 0]]], "int32")
    filt = rng.randn(f, 3, out_sz, k).astype("float32")

    nv = fluid.layers.data("nv", shape=[nmax, f])
    ev = fluid.layers.data("ev", shape=[3, 2], dtype="int32")
    fv = fluid.layers.data("fv", shape=[f, 3, out_sz, k], append_batch_size=False)
    out = _op("tree_conv", {"NodesVector": nv, "EdgeSet": ev, "Filter": fv},
              {"max_depth": 2})
    o, = _run(out, {"nv": nodes, "ev": edges, "fv": filt})
    assert o.shape == (1, nmax, out_sz, k)

    # manual: DIRECTED tree 1→{2,3} (reference construct_tree); patches:
    # root 1 = {1, 2, 3}; roots 2/3 have no children = {self}
    def eta(idx, pclen, depth, d=2.0):
        et = (d - depth) / d
        tmp = 0.5 if pclen == 1 else (idx - 1.0) / (pclen - 1.0)
        el = (1.0 - et) * tmp
        return el, (1.0 - et) * (1.0 - el), et

    def patch_row(members):
        col = np.zeros((3, f))
        for node, idx, pclen, depth in members:
            el, er, et = eta(idx, pclen, depth)
            col[0] += el * nodes[0, node - 1]
            col[1] += er * nodes[0, node - 1]
            col[2] += et * nodes[0, node - 1]
        return np.einsum("df,fdok->ok", col, filt)

    exp1 = patch_row([(1, 1, 1, 0), (2, 1, 2, 1), (3, 2, 2, 1)])
    exp2 = patch_row([(2, 1, 1, 0)])
    exp3 = patch_row([(3, 1, 1, 0)])
    np.testing.assert_allclose(o[0, 0], exp1, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(o[0, 1], exp2, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(o[0, 2], exp3, rtol=1e-4, atol=1e-5)


def test_tree_conv_half_zero_edge_terminates(rng):
    """A row with one zero endpoint ends the edge list (reference
    construct_tree breaks) — later edges and node 0 must not leak."""
    f, out_sz, k, nmax = 3, 2, 2, 4
    nodes = rng.randn(1, nmax, f).astype("float32")
    filt = rng.randn(f, 3, out_sz, k).astype("float32")
    # (0,3) terminates: the (1,2) edge after it is ignored too
    edges_a = np.array([[[1, 2], [0, 3], [1, 4]]], "int32")
    edges_b = np.array([[[1, 2], [0, 0], [0, 0]]], "int32")
    nv = fluid.layers.data("nv", shape=[nmax, f])
    ev = fluid.layers.data("ev", shape=[3, 2], dtype="int32")
    fv = fluid.layers.data("fv", shape=[f, 3, out_sz, k], append_batch_size=False)
    out = _op("tree_conv", {"NodesVector": nv, "EdgeSet": ev, "Filter": fv},
              {"max_depth": 2})
    oa, = _run(out, {"nv": nodes, "ev": edges_a, "fv": filt})
    with fluid.scope_guard(fluid.Scope()):
        ob, = _run(out, {"nv": nodes, "ev": edges_b, "fv": filt})
    np.testing.assert_allclose(oa, ob, rtol=1e-6)
