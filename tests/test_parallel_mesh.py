"""Model-parallel + gradient-accumulation tests on the 8-device CPU mesh."""

import jax
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import parallel


def _data(rng, n=32, d=16, classes=4):
    xs = rng.randn(n, d).astype("float32")
    ys = rng.randint(0, classes, (n, 1)).astype("int64")
    return xs, ys


def _run_steps(build_fn, compiled_factory, xs, ys, steps=4):
    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 7
            with fluid.program_guard(main, startup):
                loss = build_fn()
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            prog = compiled_factory(main, loss) if compiled_factory else main
            out = []
            for _ in range(steps):
                l, = exe.run(prog, feed={"x": xs, "y": ys}, fetch_list=[loss])
                out.append(float(l))
            return out


def _tp_model():
    x = fluid.layers.data("x", shape=[16])
    y = fluid.layers.data("y", shape=[1], dtype="int64")
    h = parallel.column_parallel_fc(x, 32, act="relu")
    h = parallel.row_parallel_fc(h, 16, act="relu")
    logits = fluid.layers.fc(h, size=4)
    return fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))


def test_tensor_parallel_fc_matches_single_device(rng):
    xs, ys = _data(rng)
    single = _run_steps(_tp_model, None, xs, ys)

    def factory(main, loss):
        return fluid.CompiledProgram(main).with_mesh(
            {"data": 2, "model": 4}, loss_name=loss.name)

    def build_with_opt():
        loss = _tp_model()
        fluid.optimizer.SGD(0.1).minimize(loss)
        return loss

    single = _run_steps(build_with_opt, None, xs, ys)
    meshed = _run_steps(build_with_opt, factory, xs, ys)
    np.testing.assert_allclose(single, meshed, rtol=1e-4, atol=1e-5)
    assert meshed[-1] < meshed[0]


def test_sharded_embedding_matches_single_device(rng):
    V, D = 64, 8
    ids_np = rng.randint(0, V, (16, 4)).astype("int64")
    ys = rng.randint(0, 4, (16, 1)).astype("int64")

    def build():
        ids = fluid.layers.data("x", shape=[4], dtype="int64")
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        emb = parallel.sharded_embedding(ids, size=[V, D], mesh_axis="model")
        flat = fluid.layers.reshape(emb, [-1, 4 * D])
        logits = fluid.layers.fc(flat, size=4)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(1e-2).minimize(loss)
        return loss

    def factory(main, loss):
        return fluid.CompiledProgram(main).with_mesh(
            {"data": 2, "model": 4}, loss_name=loss.name)

    single = _run_steps(build, None, ids_np, ys)
    meshed = _run_steps(build, factory, ids_np, ys)
    np.testing.assert_allclose(single, meshed, rtol=1e-4, atol=1e-5)


def test_sharded_embedding_table_actually_sharded(rng):
    V, D = 64, 8
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.unique_name.guard():
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                ids = fluid.layers.data("x", shape=[4], dtype="int64")
                emb = parallel.sharded_embedding(ids, size=[V, D], mesh_axis="model")
                out = fluid.layers.reduce_sum(emb)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            prog = fluid.CompiledProgram(main).with_mesh({"data": 2, "model": 4})
            exe.run(prog, feed={"x": rng.randint(0, V, (8, 4)).astype("int64")},
                    fetch_list=[out])
            # after the run, the table in scope must be laid out row-sharded
            w = [v for n, v in scope.vars.items() if n.startswith("sharded_embedding")
                 or "emb" in n.lower() or n.endswith(".w_0")]
            table = [v for n, v in scope.vars.items()
                     if getattr(v, "shape", None) == (V, D)][0]
            assert len(table.sharding.device_set) == 8
            # row-sharded over 'model' (4-way): each shard holds V/4 rows
            shard_shape = table.sharding.shard_shape(table.shape)
            assert shard_shape[0] == V // 4


def test_gradient_accumulation_matches_full_batch(rng):
    xs, ys = _data(rng, n=32)

    def build():
        x = fluid.layers.data("x", shape=[16])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=32, act="relu")
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
        return loss

    def factory_accum(main, loss):
        bs = fluid.BuildStrategy()
        bs.gradient_accumulation_steps = 4
        return fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, build_strategy=bs)

    def factory_plain(main, loss):
        return fluid.CompiledProgram(main).with_data_parallel(loss_name=loss.name)

    plain = _run_steps(build, factory_plain, xs, ys)
    accum = _run_steps(build, factory_accum, xs, ys)
    np.testing.assert_allclose(plain, accum, rtol=1e-4, atol=1e-5)
