"""C++ train demo: compile train/demo_trainer.cc and run the full
Python-free training loop (reference: train/demo's CI build+run)."""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")
def test_cpp_train_demo_compiles_and_converges(tmp_path):
    prog_dir = str(tmp_path / "demo_program")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.run([sys.executable, os.path.join(REPO, "train", "save_program.py"),
                    prog_dir], check=True, env=env)

    cfg = "python3-config"
    inc = subprocess.check_output([cfg, "--includes"], text=True).split()
    ld = subprocess.check_output([cfg, "--ldflags", "--embed"], text=True).split()
    exe = str(tmp_path / "demo_trainer")
    subprocess.run(["g++", "-O2", os.path.join(REPO, "train", "demo_trainer.cc"),
                    *inc, *ld, "-o", exe], check=True)

    r = subprocess.run([exe, prog_dir], env=env, capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == 0, "demo failed:\n%s\n%s" % (r.stdout, r.stderr)
    assert "C++ train demo: PASS" in r.stdout
