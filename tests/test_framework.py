"""Core IR construction tests (mirrors reference framework tests:
python/paddle/fluid/tests/unittests/test_program.py, test_operator_desc.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.framework import Program


def test_program_blocks_and_vars():
    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        assert x.shape == (-1, 4)
        assert prog.global_block.has_var("x")


def test_append_op_and_version_bump():
    prog = fluid.Program()
    v0 = prog._version
    blk = prog.global_block
    a = blk.create_var(name="a", shape=[2], dtype="float32")
    b = blk.create_var(name="b", shape=[2], dtype="float32")
    op = blk.append_op("elementwise_add", inputs={"X": a, "Y": a}, outputs={"Out": b})
    assert prog._version > v0
    assert op.input("X") == ["a"]
    assert op.output("Out") == ["b"]
    assert blk.ops[-1] is op


def test_default_programs_and_guard():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        assert fluid.default_main_program() is main
        assert fluid.default_startup_program() is startup
    assert fluid.default_main_program() is not main


def test_parameter_creation_appends_init_op():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        out = fluid.layers.fc(x, size=3)
        params = main.all_parameters()
        assert len(params) == 2  # weight + bias
        # init ops live in the startup program
        assert len(startup.global_block.ops) == 2
        init_types = {op.type for op in startup.global_block.ops}
        assert "uniform_random" in init_types  # Xavier default
        assert "fill_constant" in init_types  # bias zero-fill


def test_clone_for_test_strips_optimizer_ops():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        out = fluid.layers.fc(x, size=3, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(out, y))
        test_prog = main.clone(for_test=True)
        fluid.optimizer.SGD(0.1).minimize(loss)
    main_types = [op.type for op in main.global_block.ops]
    test_types = [op.type for op in test_prog.global_block.ops]
    assert "sgd" in main_types
    assert "backward_marker" in main_types
    assert "sgd" not in test_types
    assert "backward_marker" not in test_types


def test_variable_operator_overloading():
    main = fluid.Program()
    with fluid.program_guard(main):
        x = fluid.layers.data("x", shape=[4])
        z = x * 2.0 + 1.0
        types = [op.type for op in main.global_block.ops]
        assert "scale" in types


def test_unknown_op_reports_cleanly():
    from paddle_tpu.core.registry import get_op_impl

    with pytest.raises(NotImplementedError, match="no TPU implementation"):
        get_op_impl("definitely_not_an_op")


def test_minimize_outside_guard_updates_loss_program():
    """Regression: optimize ops must land in the loss's program even when
    minimize() is called outside the program_guard."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        out = fluid.layers.fc(x, size=3)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(out, y)
        )
    fluid.optimizer.SGD(0.1).minimize(loss)  # outside the guard
    assert "sgd" in [op.type for op in main.global_block.ops]
    assert "sgd" not in [op.type for op in fluid.default_main_program().global_block.ops]


def test_clone_for_test_with_regularizer_runs():
    """Regression: clone(for_test) must drop post-marker clip/regularizer ops."""
    import paddle_tpu.regularizer as reg

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        out = fluid.layers.fc(x, size=3)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(out, y))
        fluid.optimizer.Adam(1e-3, regularization=reg.L2Decay(1e-4)).minimize(loss)
        test_prog = main.clone(for_test=True)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = np.zeros((4, 4), "float32")
    ys = np.zeros((4, 1), "int64")
    (train_l,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    (test_l,) = exe.run(test_prog, feed={"x": xs, "y": ys}, fetch_list=[loss])
    assert np.isfinite(test_l).all()
