"""contrib/slim prune+distill scaffolding and the legacy ParallelExecutor
wrapper (VERDICT round-2 missing items 7 & 8)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.contrib import slim


def test_ratio_pruner_masks():
    v = np.array([[0.1, -0.9], [0.5, -0.05]], "float32")
    mask = slim.RatioPruner({"*": 0.5}).prune(v, name="w")
    assert mask.sum() == 2  # keep top-50% by |w|
    assert mask[0, 1] == 1 and mask[1, 0] == 1
    t = slim.MagnitudePruner(0.4).prune(v)
    np.testing.assert_array_equal(t, (np.abs(v) >= 0.4).astype("float32"))


def test_prune_strategy_in_compressor(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        y = fluid.layers.fc(x, size=4, bias_attr=False)
        loss = fluid.layers.mean(fluid.layers.square(y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    w_name = main.all_parameters()[0].name

    feeds = [{"x": rng.randn(4, 8).astype("float32")} for _ in range(3)]
    compressor = slim.build_compressor(
        data_reader=lambda: iter(feeds), epoch=2, program_exe=exe,
        strategies=[slim.PruneStrategy(slim.RatioPruner({"*": 0.25}),
                                       start_epoch=0, end_epoch=10)])
    ctx = compressor.apply(main)
    assert ctx.epoch_id == 1 and ctx.batch_id == 3
    w = np.asarray(fluid.global_scope().find_var(w_name))
    sparsity = (w == 0).mean()
    assert sparsity >= 0.70, "RatioPruner(0.25) should zero ~75%% (got %.2f)" % sparsity


def test_distill_losses_build_and_match_numpy(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        t = fluid.layers.data("t", shape=[6])
        s = fluid.layers.data("s", shape=[6])
        soft = slim.distillation.soft_label_loss(t, s, temperature=2.0)
        l2 = slim.distillation.l2_distill_loss(t, s)
        ta = fluid.layers.data("ta", shape=[3, 4, 4])
        tb = fluid.layers.data("tb", shape=[5, 4, 4])
        sa = fluid.layers.data("sa", shape=[3, 4, 4])
        sb = fluid.layers.data("sb", shape=[5, 4, 4])
        fsp = slim.distillation.fsp_loss(ta, tb, sa, sb)
    n = 3
    tv = rng.randn(n, 6).astype("float32")
    sv = rng.randn(n, 6).astype("float32")
    fa = rng.randn(n, 3, 4, 4).astype("float32")
    fb = rng.randn(n, 5, 4, 4).astype("float32")
    ga = rng.randn(n, 3, 4, 4).astype("float32")
    gb = rng.randn(n, 5, 4, 4).astype("float32")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    so, l2o, fo = exe.run(main, feed={"t": tv, "s": sv, "ta": fa, "tb": fb,
                                      "sa": ga, "sb": gb},
                          fetch_list=[soft, l2, fsp])

    def softmax(z):
        e = np.exp(z - z.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    T = 2.0
    p = softmax(tv / T)
    logq = np.log(softmax(sv / T))
    np.testing.assert_allclose(
        so, -(T * T) * (p * logq).sum(-1).mean(), rtol=1e-4)
    np.testing.assert_allclose(l2o, ((tv - sv) ** 2).mean(), rtol=1e-5)

    def fsp_mat(a, b):
        n_, ca, h, w = a.shape
        return np.einsum("nchw,ndhw->ncd", a, b) / (h * w)

    np.testing.assert_allclose(
        fo, ((fsp_mat(fa, fb) - fsp_mat(ga, gb)) ** 2).mean(), rtol=1e-4)


def test_parallel_executor_legacy_wrapper(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name,
                                main_program=main)
    assert pe.device_count == 8  # virtual CPU mesh from conftest
    first = last = None
    for i in range(12):
        xs = rng.randn(16, 8).astype("float32")
        ys = (np.abs(xs).sum(1) % 4).astype("int64").reshape(-1, 1)
        (lv,) = pe.run(fetch_list=[loss], feed={"x": xs, "label": ys})
        first = first if first is not None else float(lv)
        last = float(lv)
    assert last < first
