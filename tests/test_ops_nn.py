"""Per-op checks: activations, softmax/losses, normalization, conv/pool,
embedding, attention (mirrors test_activation_op.py, test_softmax_op.py,
test_batch_norm_op.py, test_conv2d_op.py, test_pool2d_op.py,
test_lookup_table_op.py)."""

import numpy as np
import pytest

from paddle_tpu.testing import check_grad, check_output, run_op


@pytest.fixture
def r():
    return np.random.RandomState(1)


def test_activations_numeric(r):
    x = (r.randn(3, 4) * 2).astype("float32")
    cases = {
        "sigmoid": 1 / (1 + np.exp(-x)),
        "relu": np.maximum(x, 0),
        "tanh": np.tanh(x),
        "exp": np.exp(x),
        "square": x * x,
        "abs": np.abs(x),
        "softsign": x / (1 + np.abs(x)),
        "reciprocal": 1 / x,
        "leaky_relu": np.where(x >= 0, x, 0.02 * x),
    }
    for op, want in cases.items():
        attrs = {"alpha": 0.02} if op == "leaky_relu" else {}
        check_output(op, {"X": x}, {"Out": want.astype("float32")}, attrs=attrs,
                     atol=1e-5, rtol=1e-4)
    xp = np.abs(x) + 0.1
    check_output("sqrt", {"X": xp}, {"Out": np.sqrt(xp)}, atol=1e-5)
    check_output("log", {"X": xp}, {"Out": np.log(xp)}, atol=1e-5)


def test_activation_grads(r):
    x = (r.randn(2, 3) + 0.1).astype("float32")
    for op in ("sigmoid", "tanh", "softplus", "swish", "gelu"):
        check_grad(op, {"X": x}, ["X"], "Out", max_relative_error=2e-2)


def test_softmax_and_cross_entropy(r):
    x = r.randn(4, 7).astype("float32")
    e = np.exp(x - x.max(-1, keepdims=True))
    sm = e / e.sum(-1, keepdims=True)
    check_output("softmax", {"X": x}, {"Out": sm}, atol=1e-5)

    label = r.randint(0, 7, (4, 1)).astype("int64")
    want_loss = -np.log(sm[np.arange(4), label.ravel()]).reshape(4, 1)
    check_output("softmax_with_cross_entropy", {"Logits": x, "Label": label},
                 {"Loss": want_loss.astype("float32"), "Softmax": sm}, atol=1e-5)
    check_output("cross_entropy", {"X": sm.astype("float32"), "Label": label},
                 {"Y": want_loss.astype("float32")}, atol=1e-5)
    # soft labels
    soft = np.abs(r.rand(4, 7)).astype("float32")
    soft /= soft.sum(-1, keepdims=True)
    want_soft = -(soft * np.log(sm)).sum(-1, keepdims=True)
    check_output("softmax_with_cross_entropy", {"Logits": x, "Label": soft},
                 {"Loss": want_soft.astype("float32")}, attrs={"soft_label": True},
                 atol=1e-5)
    check_grad("softmax_with_cross_entropy", {"Logits": x, "Label": label},
               ["Logits"], "Loss", max_relative_error=1e-2)


def test_sigmoid_xent_and_losses(r):
    x = r.randn(4, 3).astype("float32")
    lbl = r.randint(0, 2, (4, 3)).astype("float32")
    want = np.maximum(x, 0) - x * lbl + np.log1p(np.exp(-np.abs(x)))
    check_output("sigmoid_cross_entropy_with_logits", {"X": x, "Label": lbl},
                 {"Out": want}, atol=1e-5)
    p = np.clip(r.rand(4, 1).astype("float32"), 0.1, 0.9)
    y = r.randint(0, 2, (4, 1)).astype("float32")
    eps = 1e-4
    check_output("log_loss", {"Predicted": p, "Labels": y},
                 {"Loss": (-y * np.log(p + eps) - (1 - y) * np.log(1 - p + eps))},
                 atol=1e-5)
    check_output("huber_loss", {"X": x[:, :1], "Y": x[:, 1:2]},
                 {"Out": np.where(np.abs(x[:, 1:2] - x[:, :1]) <= 1.0,
                                  0.5 * (x[:, 1:2] - x[:, :1]) ** 2,
                                  np.abs(x[:, 1:2] - x[:, :1]) - 0.5)},
                 attrs={"delta": 1.0}, atol=1e-5)


def test_layer_norm_numeric(r):
    x = r.randn(4, 6).astype("float32")
    scale = r.rand(6).astype("float32")
    bias = r.rand(6).astype("float32")
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    want = (x - mean) / np.sqrt(var + 1e-5) * scale + bias
    check_output("layer_norm", {"X": x, "Scale": scale, "Bias": bias},
                 {"Y": want, "Mean": mean.ravel(), "Variance": var.ravel()},
                 attrs={"begin_norm_axis": 1}, atol=1e-4)
    check_grad("layer_norm", {"X": x, "Scale": scale, "Bias": bias},
               ["X", "Scale"], "Y", max_relative_error=2e-2)


def test_batch_norm_train_and_infer(r):
    x = r.randn(4, 3, 2, 2).astype("float32")
    scale = np.ones(3, "float32")
    bias = np.zeros(3, "float32")
    mean = np.zeros(3, "float32")
    var = np.ones(3, "float32")
    bmean = x.mean((0, 2, 3))
    bvar = x.var((0, 2, 3))
    want = (x - bmean.reshape(1, 3, 1, 1)) / np.sqrt(bvar.reshape(1, 3, 1, 1) + 1e-5)
    out = run_op("batch_norm",
                 {"X": x, "Scale": scale, "Bias": bias, "Mean": mean, "Variance": var},
                 ["Y", "MeanOut", "VarianceOut"],
                 attrs={"momentum": 0.9, "epsilon": 1e-5})
    np.testing.assert_allclose(np.asarray(out["Y"]), want, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out["MeanOut"]), 0.9 * mean + 0.1 * bmean, atol=1e-5)
    # inference path uses running stats
    out_t = run_op("batch_norm",
                   {"X": x, "Scale": scale, "Bias": bias, "Mean": bmean, "Variance": bvar},
                   ["Y"], attrs={"is_test": True, "epsilon": 1e-5}, is_test=True)
    np.testing.assert_allclose(np.asarray(out_t["Y"]), want, atol=1e-4)


def test_conv2d_numeric_small(r):
    # hand-check a 1-channel 3x3 conv against explicit correlation
    x = r.randn(1, 1, 4, 4).astype("float32")
    w = r.randn(1, 1, 3, 3).astype("float32")
    want = np.zeros((1, 1, 2, 2), "float32")
    for i in range(2):
        for j in range(2):
            want[0, 0, i, j] = (x[0, 0, i:i+3, j:j+3] * w[0, 0]).sum()
    check_output("conv2d", {"Input": x, "Filter": w}, {"Output": want},
                 attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
                        "groups": 1}, atol=1e-4)
    check_grad("conv2d", {"Input": x, "Filter": w}, ["Input", "Filter"], "Output",
               attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
                      "groups": 1}, max_relative_error=2e-2)


def test_depthwise_and_grouped_conv(r):
    x = r.randn(2, 4, 5, 5).astype("float32")
    w = r.randn(4, 1, 3, 3).astype("float32")  # groups=4 depthwise
    out = run_op("depthwise_conv2d", {"Input": x, "Filter": w}, ["Output"],
                 attrs={"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
                        "groups": 4})["Output"]
    assert np.asarray(out).shape == (2, 4, 5, 5)
    # each output channel depends only on its input channel
    x2 = x.copy(); x2[:, 0] += 100.0
    out2 = run_op("depthwise_conv2d", {"Input": x2, "Filter": w}, ["Output"],
                  attrs={"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
                         "groups": 4})["Output"]
    diff = np.abs(np.asarray(out2) - np.asarray(out))
    assert diff[:, 0].max() > 1 and diff[:, 1:].max() < 1e-3


def test_pool2d_numeric(r):
    x = r.randn(1, 1, 4, 4).astype("float32")
    want_max = x.reshape(1, 1, 2, 2, 2, 2).max((3, 5))
    check_output("pool2d", {"X": x}, {"Out": want_max},
                 attrs={"pooling_type": "max", "ksize": [2, 2], "strides": [2, 2],
                        "paddings": [0, 0]})
    want_avg = x.reshape(1, 1, 2, 2, 2, 2).mean((3, 5))
    check_output("pool2d", {"X": x}, {"Out": want_avg},
                 attrs={"pooling_type": "avg", "ksize": [2, 2], "strides": [2, 2],
                        "paddings": [0, 0]}, atol=1e-5)
    check_output("pool2d", {"X": x}, {"Out": x.max((2, 3), keepdims=True)},
                 attrs={"pooling_type": "max", "global_pooling": True, "ksize": [1, 1],
                        "strides": [1, 1], "paddings": [0, 0]})


def test_lookup_table(r):
    w = r.randn(10, 4).astype("float32")
    ids = np.array([[1], [3], [0]], dtype="int64")
    check_output("lookup_table", {"W": w, "Ids": ids}, {"Out": w[[1, 3, 0]]})
    # padding_idx zeroes that row
    out = run_op("lookup_table", {"W": w, "Ids": ids}, ["Out"],
                 attrs={"padding_idx": 3})["Out"]
    got = np.asarray(out)
    assert np.allclose(got[1], 0) and np.allclose(got[0], w[1])
    check_grad("lookup_table", {"W": w, "Ids": ids}, ["W"], "Out",
               max_relative_error=1e-2)


def test_dropout_modes(r):
    x = np.ones((64, 64), "float32")
    out = np.asarray(run_op("dropout", {"X": x}, ["Out"],
                            attrs={"dropout_prob": 0.3, "seed": 5})["Out"])
    keep = (out != 0).mean()
    assert 0.6 < keep < 0.8
    assert set(np.unique(out)).issubset({0.0, 1.0})
    up = np.asarray(run_op("dropout", {"X": x}, ["Out"],
                           attrs={"dropout_prob": 0.3, "seed": 5,
                                  "dropout_implementation": "upscale_in_train"})["Out"])
    nz = np.unique(up[up != 0])
    np.testing.assert_allclose(nz, np.full_like(nz, 1 / 0.7), rtol=1e-5)
    # inference: downgrade scales by (1-p); upscale passes through
    inf = np.asarray(run_op("dropout", {"X": x}, ["Out"],
                            attrs={"dropout_prob": 0.3, "is_test": True}, is_test=True)["Out"])
    np.testing.assert_allclose(inf, x * 0.7, rtol=1e-6)


def test_attention_matches_reference_composition(r):
    b, h, s, d = 2, 2, 8, 4
    q = r.randn(b, h, s, d).astype("float32")
    k = r.randn(b, h, s, d).astype("float32")
    v = r.randn(b, h, s, d).astype("float32")
    scores = np.einsum("bhqd,bhkd->bhqk", q, k) * (d ** -0.5)
    e = np.exp(scores - scores.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, v)
    check_output("scaled_dot_product_attention", {"Q": q, "K": k, "V": v},
                 {"Out": want}, attrs={"sm_scale": d ** -0.5}, atol=1e-4)
    # causal: position 0 attends only to itself
    causal = np.asarray(run_op("scaled_dot_product_attention",
                               {"Q": q, "K": k, "V": v}, ["Out"],
                               attrs={"causal": True, "sm_scale": d ** -0.5})["Out"])
    np.testing.assert_allclose(causal[:, :, 0], v[:, :, 0], atol=1e-4)
    check_grad("scaled_dot_product_attention", {"Q": q, "K": k, "V": v},
               ["Q", "K", "V"], "Out", attrs={"sm_scale": d ** -0.5},
               max_relative_error=2e-2)


def test_one_hot_topk_argsort(r):
    ids = np.array([[1], [0], [3]], dtype="int64")
    want = np.zeros((3, 4), "float32")
    want[[0, 1, 2], [1, 0, 3]] = 1
    check_output("one_hot", {"X": ids}, {"Out": want}, attrs={"depth": 4})
    x = r.randn(3, 5).astype("float32")
    got = run_op("top_k", {"X": x}, ["Out", "Indices"], attrs={"k": 2})
    np.testing.assert_allclose(np.asarray(got["Out"]), np.sort(x, -1)[:, ::-1][:, :2], atol=1e-6)
    got = run_op("argsort", {"X": x}, ["Out", "Indices"], attrs={"axis": -1})
    np.testing.assert_allclose(np.asarray(got["Out"]), np.sort(x, -1), atol=1e-6)
