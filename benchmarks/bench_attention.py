"""Attention microbenchmark on real TPU: the Pallas flash kernel vs the
XLA-composed O(S²) path, fwd+bwd, bf16 causal. Chained-loop difference
timing (k-vs-1 iterations inside one jit) cancels the axon tunnel's
per-call round trip.

Measured 2026-07-30 on v5e (loop-difference timing, causal fwd+bwd):
  r2 (f32 softmax): S=2048 flash 5.22 vs composed 3.32 ms; S=8192 13.41 vs 16.39
  r3 (bf16 softmax): S=8192 flash 11.53 vs composed 4.03 ms;
                     S=16384 flash 96.64 vs composed 59.45 ms
  r4 (v5e-tuned BlockSizes 512x512): S=2048 flash 1.24 vs composed 2.00 ms
     (1.61x); S=4096 1.85 vs 6.40 (3.46x); S=8192 3.12 vs 12.93 (4.15x);
     S=16384 12.07 vs 39.20 (3.25x). Sweeps: sweep_flash_blocks.py,
     sweep_flash_crossover.py.
The stock all-128 BlockSizes were the r3 loss; with 512x512 tiles flash wins
everywhere above S~2048, so FLAGS_flash_attention_min_seq (default 2048) is
a PERF crossover, and flash's O(S) memory additionally rescues shapes where
composed OOMs (~24k single-chip).
"""

import json
import time

import jax
import jax.numpy as jnp

from paddle_tpu.ops.attention_ops import sdpa


def composed(q, k, v, causal):
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (q.shape[-1] ** -0.5)
    if causal:
        m = jnp.tril(jnp.ones((q.shape[2], k.shape[2]), bool))
        scores = jnp.where(m, scores, jnp.asarray(-1e9, scores.dtype))
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _per_iter_ms(fn, q, k, v, lo=1, hi=5, reps=4):
    def make(iters):
        def body(i, carry):
            qq, acc = carry

            def loss(t):
                return jnp.sum(fn(t, k, v).astype(jnp.float32) ** 2)

            l, g = jax.value_and_grad(loss)(qq)
            return qq + 1e-6 * g.astype(qq.dtype), acc + l

        return jax.jit(lambda: jax.lax.fori_loop(0, iters, body, (q, 0.0))[1])

    def tmin(f):
        float(f())
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(f())
            ts.append(time.perf_counter() - t0)
        return min(ts)

    return (tmin(make(hi)) - tmin(make(lo))) / (hi - lo) * 1e3


def main():
    from paddle_tpu.flags import get_flag, set_flag

    old_gate = get_flag("flash_attention_min_seq")
    for b, h, s, d in [(4, 8, 2048, 64), (1, 8, 8192, 64)]:
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(k1, (b, h, s, d), jnp.bfloat16)
        k = jax.random.normal(k2, (b, h, s, d), jnp.bfloat16)
        v = jax.random.normal(k3, (b, h, s, d), jnp.bfloat16)
        set_flag("flash_attention_min_seq", 128)  # force flash for the A side
        tf = _per_iter_ms(lambda t, kk, vv: sdpa(t, kk, vv, causal=True,
                                                 sm_scale=d ** -0.5), q, k, v)
        set_flag("flash_attention_min_seq", old_gate)  # restore the default
        # B side calls the local composed() directly — no gate involved
        tc = _per_iter_ms(lambda t, kk, vv: composed(t, kk, vv, True), q, k, v)
        print(json.dumps({"bench": "attention_fwd_bwd_bf16_causal",
                          "b": b, "h": h, "s": s, "d": d,
                          "flash_ms": round(tf, 2), "composed_ms": round(tc, 2),
                          "flash_speedup": round(tc / tf, 3)}))


if __name__ == "__main__":
    main()
