"""Count ENTRY-computation kernels (launches) in paddle vs raw BERT HLO.

The per-kernel launch latency on this chip is ~140 us (TRANSFORMER_PROFILE
.md §2), so entry instruction count is the first-order model of the
optimizer-tax gap. Prints per-opcode entry counts and dumps both HLOs.

Usage: python benchmarks/diag_bert_kernels.py
"""
import collections
import os
import re
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def entry_counts(hlo, tag):
    entry = hlo[hlo.index("\nENTRY "):]
    entry = entry[:entry.index("\n}")]
    per = collections.Counter()
    for line in entry.split("\n"):
        m = re.match(r"\s+(?:ROOT )?%[\w.\-]+ = \S+ ([a-z][a-z\-]*)\(", line)
        if m:
            per[m.group(1)] += 1
    sync = {k: v for k, v in per.items()
            if k not in ("parameter", "get-tuple-element", "tuple", "constant",
                         "bitcast", "after-all", "copy-start", "copy-done",
                         "slice-start", "slice-done")}
    print("%s: sync entry instrs=%d  %s" % (
        tag, sum(sync.values()),
        sorted(sync.items(), key=lambda kv: -kv[1])[:12]))
    return per


def main():
    import bench
    import paddle_tpu as fluid
    from paddle_tpu.models import bert

    batch, seq, n_mask = 32, 128, 20
    with fluid.unique_name.guard(), fluid.scope_guard(fluid.Scope()):
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            ids = fluid.layers.data("ids", shape=[seq], dtype="int64")
            pos = fluid.layers.data("pos", shape=[seq], dtype="int64")
            sent = fluid.layers.data("sent", shape=[seq], dtype="int64")
            mask = fluid.layers.data("mask", shape=[seq], dtype="float32")
            mpos = fluid.layers.data("mpos", shape=[n_mask], dtype="int64")
            mlbl = fluid.layers.data("mlbl", shape=[1], dtype="int64")
            nsp = fluid.layers.data("nsp", shape=[1], dtype="int64")
            loss, _, _ = bert.bert_pretrain(ids, pos, sent, mask, mpos, mlbl,
                                            nsp, **bert.BERT_BASE_CONFIG)
            opt = fluid.amp.decorate(fluid.optimizer.Adam(learning_rate=1e-4))
            opt.minimize(loss)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        rng = np.random.RandomState(0)
        mpos_np = (np.arange(batch)[:, None] * seq
                   + rng.randint(0, seq, (batch, n_mask))).astype("int64")
        feed = {
            "ids": rng.randint(0, 30522, (batch, seq)).astype("int64"),
            "pos": np.tile(np.arange(seq), (batch, 1)).astype("int64"),
            "sent": np.zeros((batch, seq), "int64"),
            "mask": np.ones((batch, seq), "float32"),
            "mpos": mpos_np,
            "mlbl": rng.randint(0, 30522, (batch * n_mask, 1)).astype("int64"),
            "nsp": rng.randint(0, 2, (batch, 1)).astype("int64"),
        }
        exe.run(main_prog, feed=feed, fetch_list=[loss], return_numpy=False)
        compiled = next(c for c in exe._cache.values() if c.fetch_names)
        scope = fluid.global_scope()
        state = {n: scope.vars[n] for n in compiled.state_names
                 if n in scope.vars}
        comp = compiled.fn.lower(state, feed, np.uint32(0)).compile()
        hlo_p = comp.as_text()
        with open("/tmp/hlo_bert_paddle.txt", "w") as f:
            f.write(hlo_p)

    diag = {}
    bench.bench_raw_jax_bert.__wrapped__ if hasattr(
        bench.bench_raw_jax_bert, "__wrapped__") else None
    # lower-only: reuse the _diag hook
    orig_timeit = bench._timeit
    bench._timeit = lambda step, b, **kw: (0.0, 0.0)
    try:
        bench.bench_raw_jax_bert(batch, seq, n_mask, _diag=diag)
    finally:
        bench._timeit = orig_timeit
    rcomp = diag["lowered"].compile()
    hlo_r = rcomp.as_text()
    with open("/tmp/hlo_bert_raw.txt", "w") as f:
        f.write(hlo_r)

    entry_counts(hlo_p, "paddle")
    entry_counts(hlo_r, "raw   ")


if __name__ == "__main__":
    main()
