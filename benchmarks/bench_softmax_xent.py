"""Microbenchmark: Pallas fused softmax-xent vs the XLA-composed lowering.

Run on real TPU: ``PYTHONPATH=/root/repo:/root/.axon_site python
benchmarks/bench_softmax_xent.py``. Prints one JSON line per config with the
fwd+bwd wall time of both paths and the speedup.

Measured 2026-07-29 on the axon v5e chip (8192×32000 fp32 fwd+bwd, min of
20 per-call scalar-fetch timings): pallas 107.1 ms vs XLA 131.7 ms →
**1.23× speedup lower bound** — the axon tunnel adds a fixed per-call
round-trip (~tens of ms) to BOTH numbers, so the on-chip ratio is higher.
block_until_ready is unreliable through the tunnel; timing forces a scalar
device→host fetch instead.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.pallas_kernels import fused_softmax_xent


def composed(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, labels.astype(jnp.int32), axis=-1)


def timeit(fn, *args, iters=20):
    """Min-of-N per-call latency; scalar fetch defeats lazy tunnels."""
    warm = fn(*args)
    float((warm[0] if isinstance(warm, tuple) else warm).sum())
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        s = out[0] if isinstance(out, tuple) else out
        float(s.sum())
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main():
    for n, v, dtype in [(8192, 32000, "float32"), (8192, 32000, "bfloat16")]:
        k1, k2 = jax.random.split(jax.random.PRNGKey(0))
        logits = jax.random.normal(k1, (n, v), jnp.float32).astype(dtype)
        labels = jax.random.randint(k2, (n, 1), 0, v, jnp.int32)

        def step_fused(lg, lb):
            def f(x):
                return fused_softmax_xent(x, lb).sum()
            l, g = jax.value_and_grad(f)(lg)
            return l, g

        def step_composed(lg, lb):
            def f(x):
                return composed(x, lb).sum()
            l, g = jax.value_and_grad(f)(lg)
            return l, g

        jf = jax.jit(step_fused)
        jc = jax.jit(step_composed)
        # numerics parity on-device
        lf, gf = jf(logits, labels)
        lc, gc = jc(logits, labels)
        np.testing.assert_allclose(float(lf), float(lc), rtol=2e-3)
        np.testing.assert_allclose(np.asarray(gf, dtype="float32"),
                                   np.asarray(gc, dtype="float32"),
                                   rtol=5e-2, atol=5e-3)
        tf = timeit(jf, logits, labels)
        tc = timeit(jc, logits, labels)
        print(json.dumps({
            "bench": "softmax_xent_fwd_bwd", "n": n, "v": v, "dtype": dtype,
            "pallas_ms": round(tf * 1e3, 3), "xla_ms": round(tc * 1e3, 3),
            "speedup": round(tc / tf, 3),
        }))


if __name__ == "__main__":
    main()
