"""Per-op TPU busy-time diff of the paddle vs raw BERT-base train steps.

Same method as profile_xplane.py (which profiles the Transformer config):
trace 3 steps of each, bucket device-lane events by fusion name, diff.

Usage: python benchmarks/profile_bert.py  (on axon TPU)
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))); sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

from profile_xplane import parse_xplane, profile_step  # noqa: E402


def main():
    import bench
    import paddle_tpu as fluid
    from paddle_tpu.models import bert

    batch, seq, n_mask = 32, 128, 20
    with fluid.unique_name.guard(), fluid.scope_guard(fluid.Scope()):
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            ids = fluid.layers.data("ids", shape=[seq], dtype="int64")
            pos = fluid.layers.data("pos", shape=[seq], dtype="int64")
            sent = fluid.layers.data("sent", shape=[seq], dtype="int64")
            mask = fluid.layers.data("mask", shape=[seq], dtype="float32")
            mpos = fluid.layers.data("mpos", shape=[n_mask], dtype="int64")
            mlbl = fluid.layers.data("mlbl", shape=[1], dtype="int64")
            nsp = fluid.layers.data("nsp", shape=[1], dtype="int64")
            loss, _, _ = bert.bert_pretrain(ids, pos, sent, mask, mpos, mlbl,
                                            nsp, **bert.BERT_BASE_CONFIG)
            opt = fluid.amp.decorate(fluid.optimizer.Adam(learning_rate=1e-4))
            opt.minimize(loss)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        rng = np.random.RandomState(0)
        mpos_np = (np.arange(batch)[:, None] * seq
                   + rng.randint(0, seq, (batch, n_mask))).astype("int64")
        feed = bench._device_feed({
            "ids": rng.randint(0, 30522, (batch, seq)).astype("int64"),
            "pos": np.tile(np.arange(seq), (batch, 1)).astype("int64"),
            "sent": np.zeros((batch, seq), "int64"),
            "mask": np.ones((batch, seq), "float32"),
            "mpos": mpos_np,
            "mlbl": rng.randint(0, 30522, (batch * n_mask, 1)).astype("int64"),
            "nsp": rng.randint(0, 2, (batch, 1)).astype("int64"),
        })

        def pstep():
            lv, = exe.run(main_prog, feed=feed, fetch_list=[loss],
                          return_numpy=False)
            return lv

        profile_step(pstep, "/tmp/prof_bert_paddle")
    t_p = parse_xplane("/tmp/prof_bert_paddle")

    # raw twin: rebuild the pieces of bench_raw_jax_bert with a profile loop
    import jax

    diag = {}
    # reuse the bench function but only to build; easiest is to re-run its
    # step under the profiler via a tiny monkeypatch of _timeit
    orig_timeit = bench._timeit
    captured = {}

    def grab(step, batch_, skip=3, iters=12):
        captured["step"] = step
        return orig_timeit(step, batch_, skip=2, iters=4)

    bench._timeit = grab
    try:
        bench.bench_raw_jax_bert(batch, seq, n_mask)
    finally:
        bench._timeit = orig_timeit
    profile_step(captured["step"], "/tmp/prof_bert_raw")
    t_r = parse_xplane("/tmp/prof_bert_raw")

    sp, sr = sum(t_p.values()), sum(t_r.values())
    print("device busy: paddle %.2f ms  raw %.2f ms (3 profiled steps)"
          % (sp, sr))
    keys = sorted(set(t_p) | set(t_r),
                  key=lambda k: -abs(t_p.get(k, 0) - t_r.get(k, 0)))
    print("%-40s %9s %9s %9s" % ("op bucket", "paddle ms", "raw ms", "delta"))
    for k in keys[:30]:
        d = t_p.get(k, 0) - t_r.get(k, 0)
        if abs(d) < 0.05:
            continue
        print("%-40s %9.2f %9.2f %+9.2f" % (k[:40], t_p.get(k, 0),
                                            t_r.get(k, 0), d))


if __name__ == "__main__":
    main()
