"""Per-op TPU busy-time profile of the paddle vs raw Transformer steps.

jax.profiler trace -> parse <run>/plugins/profile/*/​*.xplane.pb with
tensorflow's xplane proto (PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python),
aggregate device-lane event durations by fusion-name bucket, and diff.

Usage: PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python python
       benchmarks/profile_xplane.py  (on axon TPU)
"""
import collections
import glob
import os
import re
import sys

import numpy as np

sys.path.insert(0, ".")
os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")


def profile_step(run_step, outdir, steps=3):
    import jax

    np.asarray(run_step())  # ensure compiled
    with jax.profiler.trace(outdir):
        for _ in range(steps):
            out = run_step()
        np.asarray(out)


def parse_xplane(outdir):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"),
                      recursive=True)
    per_op = collections.Counter()
    for p in paths:
        xs = xplane_pb2.XSpace()
        with open(p, "rb") as f:
            xs.ParseFromString(f.read())
        for plane in xs.planes:
            if "TPU" not in plane.name or "XLA" in plane.name:
                continue
            ev_meta = {m.id: m.name for m in plane.event_metadata.values()}
            # exact match: "Async XLA Ops" (overlapped DMA spans) also
            # contains the substring "XLA Ops" and must NOT be summed as
            # busy time — that double-count inflated the r4 bucket numbers
            op_lines = [l for l in plane.lines if l.name == "XLA Ops"]
            if not op_lines:
                # fallback for other profiler line layouts: never re-admit
                # the async spans the exact-match filter exists to exclude
                import warnings

                warnings.warn("no 'XLA Ops' line in %s; summing non-async "
                              "lines" % plane.name)
                op_lines = [l for l in plane.lines if "Async" not in l.name]
            for line in op_lines:
                for ev in line.events:
                    nm = ev_meta.get(ev.metadata_id, "?")
                    per_op[_bucket(nm)] += ev.duration_ps / 1e9  # ms
    return per_op


def _bucket(name):
    """'%divide_subtract_fusion.2 = (f32[...' -> 'divide_subtract_fusion'.
    Async copy-start/done spans overlap compute — bucket them apart."""
    tok = name.split(" = ")[0].split("/")[-1].lstrip("%")
    tok = re.sub(r"[.\d]+$", "", tok)
    if tok.startswith(("copy-start", "copy-done")):
        return "(async copies)"
    return tok


def main():
    import jax

    import bench
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tfm

    batch, seq, vocab = 64, 256, 30000
    with fluid.unique_name.guard(), fluid.scope_guard(fluid.Scope()):
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            src = fluid.layers.data("src", shape=[seq], dtype="int64")
            trg = fluid.layers.data("trg", shape=[seq], dtype="int64")
            lbl = fluid.layers.data("lbl", shape=[seq, 1], dtype="int64")
            smask = fluid.layers.data("smask", shape=[seq], dtype="float32")
            tmask = fluid.layers.data("tmask", shape=[seq], dtype="float32")
            logits, loss = tfm.transformer_base(
                src, trg, lbl, smask, tmask, src_vocab_size=vocab,
                trg_vocab_size=vocab, max_length=seq, dropout_rate=0.1)
            opt = fluid.amp.decorate(fluid.optimizer.Adam(learning_rate=1e-4))
            opt.minimize(loss)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = bench._device_feed({
            "src": rng.randint(2, vocab, (batch, seq)).astype("int64"),
            "trg": rng.randint(2, vocab, (batch, seq)).astype("int64"),
            "lbl": rng.randint(2, vocab, (batch, seq, 1)).astype("int64"),
            "smask": np.ones((batch, seq), "float32"),
            "tmask": np.ones((batch, seq), "float32"),
        })

        def pstep():
            lv, = exe.run(main_prog, feed=feed, fetch_list=[loss],
                          return_numpy=False)
            return lv

        profile_step(pstep, "/tmp/prof_paddle")
    t_p = parse_xplane("/tmp/prof_paddle")

    diag = {}
    bench.bench_raw_jax_transformer(batch, seq, vocab, _diag=diag,
                                    _profile_dir="/tmp/prof_raw")
    t_r = parse_xplane("/tmp/prof_raw")

    sp, sr = sum(t_p.values()), sum(t_r.values())
    print("device busy: paddle %.2f ms  raw %.2f ms (over profiled steps)"
          % (sp, sr))
    keys = sorted(set(t_p) | set(t_r),
                  key=lambda k: -abs(t_p.get(k, 0) - t_r.get(k, 0)))
    print("%-40s %9s %9s %9s" % ("op bucket", "paddle ms", "raw ms", "delta"))
    for k in keys[:25]:
        d = t_p.get(k, 0) - t_r.get(k, 0)
        if abs(d) < 0.05:
            continue
        print("%-40s %9.2f %9.2f %+9.2f" % (k[:40], t_p.get(k, 0),
                                            t_r.get(k, 0), d))


if __name__ == "__main__":
    main()
