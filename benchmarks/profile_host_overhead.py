"""Host-path profile: where does Executor.run's per-step Python time go?

Runs the bench transformer config at tiny dims on CPU (compute ~free, op/var
counts identical to the real bench) and cProfiles N steps of exe.run. The
per-step framework tax measured here is device-independent — it is the same
Python that runs in front of the TPU step.

Usage: JAX_PLATFORMS=cpu python benchmarks/profile_host_overhead.py [steps]
"""

from __future__ import annotations

import cProfile
import pstats
import sys
import time

import numpy as np


def build(batch=8, seq=32, vocab=1000, d_model=64, d_inner=128):
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tfm

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        src = fluid.layers.data("src", shape=[seq], dtype="int64")
        trg = fluid.layers.data("trg", shape=[seq], dtype="int64")
        lbl = fluid.layers.data("lbl", shape=[seq, 1], dtype="int64")
        smask = fluid.layers.data("smask", shape=[seq], dtype="float32")
        tmask = fluid.layers.data("tmask", shape=[seq], dtype="float32")
        logits, loss = tfm.transformer(
            src, trg, lbl, smask, tmask, src_vocab_size=vocab,
            trg_vocab_size=vocab, max_length=seq, n_layer=6, n_head=8,
            d_model=d_model, d_inner=d_inner, dropout_rate=0.1)
        opt = fluid.optimizer.Adam(learning_rate=1e-4)
        opt = fluid.amp.decorate(opt)
        opt.minimize(loss)

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    rng = np.random.RandomState(0)
    import jax

    feed = {k: jax.device_put(v) for k, v in {
        "src": rng.randint(2, vocab, (batch, seq)).astype("int64"),
        "trg": rng.randint(2, vocab, (batch, seq)).astype("int64"),
        "lbl": rng.randint(2, vocab, (batch, seq, 1)).astype("int64"),
        "smask": np.ones((batch, seq), "float32"),
        "tmask": np.ones((batch, seq), "float32"),
    }.items()}
    return exe, main_prog, feed, loss


def main():
    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    exe, prog, feed, loss = build()

    def step():
        return exe.run(prog, feed=feed, fetch_list=[loss], return_numpy=False)

    # warmup / compile
    for _ in range(3):
        np.asarray(step()[0])

    t0 = time.perf_counter()
    for _ in range(steps):
        out = step()
    np.asarray(out[0])
    wall = time.perf_counter() - t0
    print("exe.run:     %.3f ms/step (incl. tiny compute)" % (1e3 * wall / steps))

    # the same compiled step called directly with pre-gathered args — the
    # difference vs exe.run is the framework's per-step host tax
    compiled = next(c for c in exe._cache.values() if c.fetch_names)
    import paddle_tpu as fluid

    scope = fluid.global_scope()
    state = {n: scope.vars[n] for n in compiled.state_names if n in scope.vars}
    t0 = time.perf_counter()
    idx = np.uint32(0)
    for _ in range(steps):
        state, fetches = compiled(state, feed, idx)
    np.asarray(fetches[0])
    wall = time.perf_counter() - t0
    print("compiled.fn: %.3f ms/step (incl. tiny compute)" % (1e3 * wall / steps))

    pr = cProfile.Profile()
    pr.enable()
    for _ in range(steps):
        out = step()
    pr.disable()
    np.asarray(out[0])
    st = pstats.Stats(pr)
    st.sort_stats("cumulative").print_stats(30)


if __name__ == "__main__":
    main()
