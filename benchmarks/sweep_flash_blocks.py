"""Sweep Pallas flash-attention BlockSizes on the real TPU.

Round-3 verdict: stock defaults (all-128 blocks) lose 0.627x to XLA-composed
attention at S=8192 (b1 h8 d64 causal bf16, fwd+bwd). This sweep finds the
v5e-optimal tiling. Timing is loop-difference (lo vs hi chained iterations)
per the established methodology in benchmarks/RESNET50_PROFILE.md.
"""
import functools
import itertools
import json
import sys
import time

import jax
import jax.numpy as jnp
from jax.experimental.pallas.ops.tpu import flash_attention as fa

B, H, S, D = 1, 8, 8192, 64
CAUSAL = True
DTYPE = jnp.bfloat16

key = jax.random.PRNGKey(0)
kq, kk, kv = jax.random.split(key, 3)
q = jax.random.normal(kq, (B, H, S, D), DTYPE)
k = jax.random.normal(kk, (B, H, S, D), DTYPE)
v = jax.random.normal(kv, (B, H, S, D), DTYPE)


def timeit(fn, *args, lo=2, hi=12):
    """Loop-difference timing of fn chained n times; returns ms/call."""
    def chain(n):
        @jax.jit
        def run(q, k, v):
            def body(c, _):
                qq, kk2, vv = c
                o, g = fn(qq, kk2, vv)
                # real data dependence so XLA cannot hoist the body out of
                # the loop (a *0 perturbation gets constant-folded)
                return (qq + 1e-6 * g[0].astype(qq.dtype), kk2, vv), o[0][0, 0, 0, 0]
            (c, outs) = jax.lax.scan(body, (q, k, v), None, length=n)
            return outs
        return run
    import numpy as np
    r_lo, r_hi = chain(lo), chain(hi)
    # np.asarray (fetching bytes) is the only reliable sync through the
    # axon tunnel; block_until_ready returns early (round-3 finding).
    np.asarray(r_lo(q, k, v)); np.asarray(r_hi(q, k, v))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter(); np.asarray(r_lo(q, k, v)); t_lo = time.perf_counter() - t0
        t0 = time.perf_counter(); np.asarray(r_hi(q, k, v)); t_hi = time.perf_counter() - t0
        best = min(best, (t_hi - t_lo) / (hi - lo))
    return best * 1e3


def fwd_bwd(attn):
    def loss(q, k, v):
        return jnp.sum(attn(q, k, v).astype(jnp.float32))
    def run(q, k, v):
        l, g = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        return (g[0],), g
    return run


def composed(q, k, v):
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (1.0 / D ** 0.5)
    cm = jnp.tril(jnp.ones((S, S), bool))
    scores = jnp.where(cm, scores, jnp.full_like(scores, -1e9))
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def flash_with(bs):
    def attn(q, k, v):
        return fa.flash_attention(q, k, v, causal=CAUSAL, sm_scale=1.0 / D ** 0.5,
                                  block_sizes=bs)
    return attn


results = {}
t = timeit(fwd_bwd(composed))
results["composed"] = t
print(f"composed: {t:.2f} ms", flush=True)

configs = []
# (block_q, block_k_major=block_k, block_q_dkv=block_k_dkv, block_q_dq=block_k_dq)
for bq in (128, 256, 512, 1024):
    for bk in (128, 256, 512, 1024, 2048):
        configs.append((bq, bk))

for bq, bk in configs:
    name = f"q{bq}_k{bk}"
    try:
        bs = fa.BlockSizes(
            block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
            block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
            block_q_dkv=bq,
            block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
        )
        t = timeit(fwd_bwd(flash_with(bs)))
        results[name] = t
        print(f"{name}: {t:.2f} ms", flush=True)
    except Exception as e:
        print(f"{name}: FAIL {type(e).__name__}: {str(e)[:120]}", flush=True)

if len(results) > 1:
    best = min((v, k) for k, v in results.items() if k != "composed")
    print(json.dumps({"composed_ms": results["composed"], "best": best[1],
                      "best_ms": best[0],
                      "speedup": results["composed"] / best[0]}))
else:
    print(json.dumps({"composed_ms": results.get("composed"),
                      "best": None, "note": "every block config failed"}))
