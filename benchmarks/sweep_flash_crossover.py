"""Find the flash-vs-composed crossover with v5e-tuned BlockSizes.

Sweeps sequence length at the long-context shape (b1 h8 d64 causal) and the
Transformer-base bench shape (b64 h8 d64 s256), fwd+bwd, bf16.
"""
import json
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental.pallas.ops.tpu import flash_attention as fa


from paddle_tpu.ops.attention_ops import _tuned_block_sizes as tuned_blocks


def timeit(fn, args, lo=2, hi=12):
    def chain(n):
        @jax.jit
        def run(q, k, v):
            def body(c, _):
                qq, kk2, vv = c
                g = fn(qq, kk2, vv)
                return (qq + 1e-6 * g[0].astype(qq.dtype), kk2, vv), g[0][0, 0, 0, 0]
            _, outs = jax.lax.scan(body, (q, k, v), None, length=n)
            return outs
        return run
    r_lo, r_hi = chain(lo), chain(hi)
    np.asarray(r_lo(*args)); np.asarray(r_hi(*args))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter(); np.asarray(r_lo(*args)); t_lo = time.perf_counter() - t0
        t0 = time.perf_counter(); np.asarray(r_hi(*args)); t_hi = time.perf_counter() - t0
        best = min(best, (t_hi - t_lo) / (hi - lo))
    return best * 1e3


def grad_of(attn):
    def loss(q, k, v):
        return jnp.sum(attn(q, k, v).astype(jnp.float32))
    return jax.grad(loss, argnums=(0, 1, 2))


def make_composed(S, causal):
    def composed(q, k, v):
        d = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (1.0 / d ** 0.5)
        if causal:
            cm = jnp.tril(jnp.ones((S, S), bool))
            s = jnp.where(cm, s, jnp.full_like(s, -1e9))
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return composed


def make_flash(S, causal):
    bs = tuned_blocks(S, S)
    def flash(q, k, v):
        d = q.shape[-1]
        return fa.flash_attention(q, k, v, causal=causal, sm_scale=1.0 / d ** 0.5,
                                  block_sizes=bs)
    return flash


out = {}
shapes = [
    # (B, H, S, D, causal, label)
    (64, 8, 256, 64, True, "bench_transformer_b64_s256"),
    (64, 8, 256, 64, False, "b64_s256_noncausal"),
    (8, 8, 1024, 64, True, "b8_s1024"),
    (4, 8, 2048, 64, True, "b4_s2048"),
    (2, 8, 4096, 64, True, "b2_s4096"),
    (1, 8, 8192, 64, True, "b1_s8192"),
    (1, 8, 16384, 64, True, "b1_s16384"),
    (32, 16, 512, 64, True, "bertish_b32_h16_s512"),
    (1, 8, 512, 128, True, "b1_s512_d128"),
]
for B, H, S, D, causal, label in shapes:
    q = jax.random.normal(jax.random.PRNGKey(0), (B, H, S, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, H, S, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, H, S, D), jnp.bfloat16)
    try:
        t_c = timeit(grad_of(make_composed(S, causal)), (q, k, v))
    except Exception as e:
        t_c = float("nan"); print(label, "composed FAIL", str(e)[:80])
    try:
        t_f = timeit(grad_of(make_flash(S, causal)), (q, k, v))
    except Exception as e:
        t_f = float("nan"); print(label, "flash FAIL", str(e)[:80])
    ok = t_c == t_c and t_f == t_f
    out[label] = {"composed_ms": round(t_c, 3) if t_c == t_c else None,
                  "flash_ms": round(t_f, 3) if t_f == t_f else None,
                  "speedup": round(t_c / t_f, 3) if ok else None}
    print(label, out[label], flush=True)

print(json.dumps(out))
