"""ResNet-50 perf diagnosis: framework step vs a raw-JAX twin (NCHW + NHWC).

Prints XLA cost analysis (flops / bytes accessed) and measured step time for
(a) the paddle_tpu ResNet-50 bench step, (b) a hand-written JAX ResNet-50
train step in NCHW, and (c) the same in NHWC — separating framework tax from
layout effects.

Usage: python benchmarks/diag_resnet.py  (on axon TPU)
"""

from __future__ import annotations

import functools
import sys
import time

import numpy as np


def fmt(ca):
    return {k: ca.get(k) for k in ("flops", "bytes accessed", "transcendentals")
            if k in ca}


def _timeit(step, batch, skip=3, iters=10):
    for _ in range(skip):
        np.asarray(step())
    t0 = time.time()
    for _ in range(iters):
        out = step()
    assert np.isfinite(np.asarray(out)).all()
    dt = time.time() - t0
    return batch * iters / dt, iters / dt


def framework(batch=64, image=224, classes=1000):
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.models import resnet as rn

    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main_prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main_prog, startup):
                img = fluid.layers.data("img", shape=[3, image, image])
                label = fluid.layers.data("label", shape=[1], dtype="int64")
                logits, loss, acc = rn.resnet50(img, label, class_num=classes)
                opt = fluid.optimizer.Momentum(0.1, 0.9)
                opt = fluid.amp.decorate(opt)
                opt.minimize(loss)
            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(startup)
            rng = np.random.RandomState(0)
            feed = {k: jax.device_put(v) for k, v in {
                "img": rng.randn(batch, 3, image, image).astype("float32"),
                "label": rng.randint(0, classes, (batch, 1)).astype("int64"),
            }.items()}
            exe.run(main_prog, feed=feed, fetch_list=[loss], return_numpy=False)
            compiled = next(c for c in exe._cache.values() if c.fetch_names)
            scope = fluid.global_scope()
            state = {n: scope.vars[n] for n in compiled.state_names
                     if n in scope.vars}
            comp = compiled.fn.lower(state, feed, np.uint32(0)).compile()
            print("paddle_tpu :", fmt(comp.cost_analysis()))
            with open("/tmp/hlo_resnet_paddle.txt", "w") as f:
                f.write(comp.as_text())

            def step():
                lv, = exe.run(main_prog, feed=feed, fetch_list=[loss],
                              return_numpy=False)
                return lv

            eps, sps = _timeit(step, batch)
            print("paddle_tpu : %.1f ex/s  %.2f ms/step" % (eps, 1e3 / sps))


def raw(layout="NCHW", batch=64, image=224, classes=1000):
    import jax
    import jax.numpy as jnp

    nhwc = layout == "NHWC"
    dn = ("NHWC", "HWIO", "NHWC") if nhwc else ("NCHW", "OIHW", "NCHW")

    cfg = [(64, 256, 3, 1), (128, 512, 4, 2), (256, 1024, 6, 2), (512, 2048, 3, 2)]
    keys = iter(jax.random.split(jax.random.PRNGKey(0), 200))

    def conv_p(cin, cout, k):
        shape = (k, k, cin, cout) if nhwc else (cout, cin, k, k)
        fan = cin * k * k
        return jax.random.normal(next(keys), shape, jnp.float32) * (2.0 / fan) ** 0.5

    def bn_p(c):
        return {"g": jnp.ones((c,)), "b": jnp.zeros((c,)),
                "m": jnp.zeros((c,)), "v": jnp.ones((c,))}

    params = {"stem": conv_p(3, 64, 7), "stem_bn": bn_p(64)}
    cin = 64
    for si, (mid, cout, n, stride) in enumerate(cfg):
        for bi in range(n):
            p = {}
            p["c1"], p["bn1"] = conv_p(cin, mid, 1), bn_p(mid)
            p["c2"], p["bn2"] = conv_p(mid, mid, 3), bn_p(mid)
            p["c3"], p["bn3"] = conv_p(mid, cout, 1), bn_p(cout)
            if bi == 0:
                p["sc"], p["sbn"] = conv_p(cin, cout, 1), bn_p(cout)
            params["s%d_%d" % (si, bi)] = p
            cin = cout
    params["fc_w"] = jax.random.normal(next(keys), (2048, classes)) * 0.01
    params["fc_b"] = jnp.zeros((classes,))

    def conv(x, w, stride):
        k = w.shape[0] if nhwc else w.shape[2]
        pad = (k - 1) // 2
        return jax.lax.conv_general_dilated(
            x, w, (stride, stride), [(pad, pad)] * 2,
            dimension_numbers=dn)

    def bn(x, p):
        ax = (0, 1, 2) if nhwc else (0, 2, 3)
        sh = (1, 1, 1, -1) if nhwc else (1, -1, 1, 1)
        xf = x.astype(jnp.float32)
        m = xf.mean(ax)
        v = (xf ** 2).mean(ax) - m ** 2
        inv = jax.lax.rsqrt(v + 1e-5).astype(x.dtype)
        return ((x - m.astype(x.dtype).reshape(sh)) * inv.reshape(sh)
                * p["g"].astype(x.dtype).reshape(sh)
                + p["b"].astype(x.dtype).reshape(sh))

    def block(x, p, stride):
        h = jax.nn.relu(bn(conv(x, p["c1"], 1), p["bn1"]))
        h = jax.nn.relu(bn(conv(h, p["c2"], stride), p["bn2"]))
        h = bn(conv(h, p["c3"], 1), p["bn3"])
        if "sc" in p:
            x = bn(conv(x, p["sc"], stride), p["sbn"])
        return jax.nn.relu(x + h)

    def loss_fn(params32, img, lbl):
        p = jax.tree_util.tree_map(
            lambda t: t.astype(jnp.bfloat16) if t.dtype == jnp.float32 else t,
            params32)
        x = img.astype(jnp.bfloat16)
        x = jax.nn.relu(bn(conv(x, p["stem"], 2), p["stem_bn"]))
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            (1, 1, 3, 3) if not nhwc else (1, 3, 3, 1),
            (1, 1, 2, 2) if not nhwc else (1, 2, 2, 1),
            [(0, 0), (0, 0), (1, 1), (1, 1)] if not nhwc
            else [(0, 0), (1, 1), (1, 1), (0, 0)])
        for si, (mid, cout, n, stride) in enumerate(cfg):
            for bi in range(n):
                x = block(x, p["s%d_%d" % (si, bi)], stride if bi == 0 else 1)
        ax = (1, 2) if nhwc else (2, 3)
        x = x.mean(ax)
        logits = (x @ p["fc_w"] + p["fc_b"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, lbl, axis=-1).mean()

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, mom, img, lbl):
        loss, g = jax.value_and_grad(loss_fn)(params, img, lbl)
        mom = jax.tree_util.tree_map(lambda m, gg: 0.9 * m + gg, mom, g)
        params = jax.tree_util.tree_map(lambda p_, m: p_ - 0.1 * m, params, mom)
        return params, mom, loss

    rng = np.random.RandomState(0)
    img = rng.randn(batch, 3, image, image).astype("float32")
    if nhwc:
        img = img.transpose(0, 2, 3, 1)
    img = jax.device_put(jnp.asarray(img))
    lbl = jax.device_put(jnp.asarray(rng.randint(0, classes, (batch, 1))))
    mom = jax.tree_util.tree_map(jnp.zeros_like, params)
    comp = train_step.lower(params, mom, img, lbl).compile()
    print("raw %s  :" % layout, fmt(comp.cost_analysis()))
    with open("/tmp/hlo_resnet_raw_%s.txt" % layout, "w") as f:
        f.write(comp.as_text())

    state = {"p": params, "m": mom}

    def step():
        state["p"], state["m"], loss = train_step(state["p"], state["m"], img, lbl)
        return loss

    eps, sps = _timeit(step, batch)
    print("raw %s  : %.1f ex/s  %.2f ms/step" % (layout, eps, 1e3 / sps))


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "fw"):
        framework()
    if which in ("all", "nchw"):
        raw("NCHW")
    if which in ("all", "nhwc"):
        raw("NHWC")
