"""Sparse-update implementation probe: XLA scatter path vs the Pallas
row-DMA kernel on the DeepFM-shape [V, 10] table.

The three scatter fusions are the whole sparse-over-dense gap at V=1e6
(SPARSE_PROFILE.md §1: ~30 GB/s effective, one VMEM-resident table out of
three). This probe times ONE sparse-Adam update — gather + row math +
writeback over merged (ids, rows) — both ways, isolated from the rest of
the DeepFM step.

    python benchmarks/diag_sparse.py                # [1e6, 10], 26624 ids
    python benchmarks/diag_sparse.py --vocab 1e7

On TPU the kernel path is the compiled Mosaic kernel and the numbers are
the real before/after for SPARSE_PROFILE.md §4. On CPU the kernel runs in
the Pallas *interpreter* — a correctness vehicle, orders of magnitude slow
— so the probe shrinks the id count and labels the result cpu-interpret;
only the scatter number is meaningful there.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")  # run from the repo root, like the other diags


def _timeit(fn, iters=20, skip=3):
    for _ in range(skip):
        jax.block_until_ready(fn())
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    vocab = int(1e6)
    n_ids = 26624  # b1024 × 26 fields
    for i, a in enumerate(sys.argv):
        if a == "--vocab":
            vocab = int(float(sys.argv[i + 1]))
        if a == "--ids":
            n_ids = int(sys.argv[i + 1])
    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        n_ids = min(n_ids, 512)  # interpret mode: keep the probe finite

    from paddle_tpu.core.sparse import merge_rows
    from paddle_tpu.ops.pallas_kernels.sparse_adam import sparse_adam_rows

    rng = np.random.RandomState(0)
    dim = 10
    ids = jnp.asarray(rng.randint(0, vocab, (n_ids,)).astype(np.int32))
    raw = jnp.asarray(rng.randn(n_ids, dim).astype(np.float32))
    p = jnp.asarray(rng.randn(vocab, dim).astype(np.float32))
    m = jnp.zeros((vocab, dim), jnp.float32)
    v = jnp.zeros((vocab, dim), jnp.float32)
    b1, b2, eps, lr_t = 0.9, 0.999, 1e-8, 1e-3

    @jax.jit
    def scatter_update(p, m, v, ids, raw):
        uniq, merged = merge_rows(ids, raw, vocab)
        m_rows = b1 * m[uniq] + (1 - b1) * merged
        v_rows = b2 * v[uniq] + (1 - b2) * jnp.square(merged)
        step = lr_t * m_rows / (jnp.sqrt(v_rows) + eps)
        return (p.at[uniq].add(-step),
                m.at[uniq].add(m_rows - m[uniq]),
                v.at[uniq].add(v_rows - v[uniq]))

    @jax.jit
    def kernel_update(p, m, v, ids, raw):
        uniq, merged = merge_rows(ids, raw, vocab)
        return sparse_adam_rows(p, m, v, uniq, merged, lr_t, b1, b2, eps,
                                interpret=not on_tpu)

    scatter_ms = _timeit(lambda: scatter_update(p, m, v, ids, raw))
    kernel_ms = _timeit(lambda: kernel_update(p, m, v, ids, raw),
                        iters=20 if on_tpu else 3, skip=3 if on_tpu else 1)

    a, b = scatter_update(p, m, v, ids, raw), kernel_update(p, m, v, ids, raw)
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-5, atol=1e-6)

    print(json.dumps({
        "mode": "tpu" if on_tpu else "cpu-interpret",
        "vocab": vocab, "n_ids": n_ids,
        "scatter_update_ms": round(scatter_ms, 3),
        "kernel_update_ms": round(kernel_ms, 3),
        "kernel_over_scatter": round(kernel_ms / scatter_ms, 3),
        "note": ("kernel compiled (Mosaic); numbers are the SPARSE_PROFILE "
                 "§4 before/after" if on_tpu else
                 "kernel INTERPRETED on CPU — parity only, timing not "
                 "meaningful; run on TPU for the real comparison"),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
