"""Histogram large tensors in the compiled paddle vs raw-JAX Transformer
steps, localizing the bytes-accessed gap from diag_overhead.py (which dumps
/tmp/hlo_paddle.txt and /tmp/hlo_raw.txt — run it first on axon TPU).
"""
import collections
import re
import sys

import numpy as np

DTYPE_BYTES = {"f32": 4, "bf16": 2, "s32": 4, "u32": 4, "pred": 1,
               "s64": 8, "u64": 8, "f16": 2, "s8": 1, "u8": 1}

SHAPE_RE = re.compile(r"(f32|bf16|f16|s32|u32|s64|u64|pred|s8|u8)\[([\d,]+)\]")


def big_shapes(path, min_mb=64):
    counts = collections.Counter()
    with open(path) as f:
        for line in f:
            # only instruction definitions (lhs shape), not operand uses
            head = line.split("=", 1)
            if len(head) != 2:
                continue
            m = SHAPE_RE.search(head[1].strip())
            if not m or not head[1].strip().startswith(("f32[", "bf16[", "f16[",
                                                        "s32[", "u32[", "s64[",
                                                        "u64[", "pred[", "s8[",
                                                        "u8[", "(")):
                continue
            for m in SHAPE_RE.finditer(head[1].split(")", 1)[0]
                                       if head[1].strip().startswith("(")
                                       else m.group(0)):
                dt, dims = m.group(1), m.group(2)
                n = int(np.prod([int(d) for d in dims.split(",")]))
                mb = n * DTYPE_BYTES[dt] / 1e6
                if mb >= min_mb:
                    counts["%s[%s] %.0fMB" % (dt, dims, mb)] += 1
    return counts


def main(min_mb=64):
    pc = big_shapes("/tmp/hlo_paddle.txt", min_mb)
    rc = big_shapes("/tmp/hlo_raw.txt", min_mb)
    keys = sorted(set(pc) | set(rc),
                  key=lambda k: -(pc.get(k, 0) + rc.get(k, 0)))
    print("%-44s %8s %8s" % ("shape (instruction outputs)", "paddle", "raw"))
    for k in keys:
        if pc.get(k, 0) != rc.get(k, 0):
            print("%-44s %8d %8d" % (k, pc.get(k, 0), rc.get(k, 0)))


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 64)
