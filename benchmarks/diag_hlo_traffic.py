"""Approximate per-instruction HBM traffic from an XLA HLO dump.

Parses the ENTRY computation (fusion boundaries = HBM traffic: each
top-level instruction reads its operands and writes its output), attributing
bytes to instruction names. Diffing two dumps localizes a bytes-accessed gap
reported by cost_analysis (run benchmarks/diag_overhead.py first to produce
/tmp/hlo_paddle.txt and /tmp/hlo_raw.txt).
"""
import collections
import re
import sys

import numpy as np

DTYPE_BYTES = {"f32": 4, "bf16": 2, "s32": 4, "u32": 4, "pred": 1,
               "s64": 8, "u64": 8, "f16": 2, "s8": 1, "u8": 1, "f64": 8,
               "c64": 8, "c128": 16, "s16": 2, "u16": 2}

SHAPE_RE = re.compile(r"\b(%s)\[([\d,]*)\]" % "|".join(DTYPE_BYTES))
DEF_RE = re.compile(r"^\s*(?:ROOT )?([%\w.\-]+) = ")
OPERAND_RE = re.compile(r"%([\w.\-]+)")


def shape_bytes(sig):
    total = 0
    for m in SHAPE_RE.finditer(sig):
        dims = m.group(2)
        n = int(np.prod([int(d) for d in dims.split(",")])) if dims else 1
        total += n * DTYPE_BYTES[m.group(1)]
    return total


def parse(path):
    """-> (def_shapes: name->bytes, entry_lines: [line])."""
    def_shapes = {}
    entry_lines = []
    in_entry = False
    with open(path) as f:
        for line in f:
            if line.startswith("ENTRY "):
                in_entry = True
                continue
            if in_entry and line.startswith("}"):
                in_entry = False
            m = DEF_RE.match(line)
            if m:
                name = m.group(1).lstrip("%")
                rhs = line.split("=", 1)[1]
                # bytes of the defined value: shapes before the opcode's "("
                head = rhs.split("(", 1)[0] if "(" in rhs else rhs
                def_shapes[name] = shape_bytes(head)
                if in_entry:
                    entry_lines.append(line)
    return def_shapes, entry_lines


SKIP_OPS = {"parameter", "get-tuple-element", "tuple", "constant", "bitcast",
            "after-all"}


def traffic(path):
    def_shapes, entry_lines = parse(path)
    per_op = collections.Counter()
    for line in entry_lines:
        m = DEF_RE.match(line)
        rhs = line.split("=", 1)[1].strip()
        opcode = re.match(r"[\w\[\]{},.:()\s]*?(\w[\w\-]*)\(", rhs)
        opcode = opcode.group(1) if opcode else "?"
        if opcode in SKIP_OPS:
            continue
        name = m.group(1).lstrip("%")
        out_b = def_shapes.get(name, 0)
        # operand reads: resolve %refs in the argument list
        args = rhs.split("(", 1)[1] if "(" in rhs else ""
        args = args.split("calls=")[0].split("to_apply=")[0]
        in_b = sum(def_shapes.get(r, 0) for r in OPERAND_RE.findall(args))
        per_op[_bucket(name)] += out_b + in_b
    return per_op


def _bucket(name):
    """fusion.123 -> fusion; keep distinctive names."""
    return re.sub(r"[.\d]+$", "", name)


def main():
    t_p = traffic("/tmp/hlo_paddle.txt")
    t_r = traffic("/tmp/hlo_raw.txt")
    print("total paddle %.2f GB   raw %.2f GB" %
          (sum(t_p.values()) / 1e9, sum(t_r.values()) / 1e9))
    keys = sorted(set(t_p) | set(t_r),
                  key=lambda k: -abs(t_p.get(k, 0) - t_r.get(k, 0)))
    print("%-28s %10s %10s %10s" % ("op", "paddle GB", "raw GB", "delta GB"))
    for k in keys[:20]:
        d = (t_p.get(k, 0) - t_r.get(k, 0)) / 1e9
        if abs(d) < 0.05:
            continue
        print("%-28s %10.2f %10.2f %+10.2f"
              % (k, t_p.get(k, 0) / 1e9, t_r.get(k, 0) / 1e9, d))


if __name__ == "__main__":
    main()
