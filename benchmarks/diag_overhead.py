"""Diagnose the framework-vs-raw-JAX gap at the XLA level.

Lowers both the paddle_tpu transformer train step and bench.py's raw-JAX
twin at identical shapes, compiles, and prints XLA cost analysis (flops,
bytes accessed) plus a measured per-step time for each. The delta in flops
or bytes names the part of the traced program that raw JAX doesn't have.

Usage: python benchmarks/diag_overhead.py          (on axon TPU)
       python benchmarks/diag_overhead.py --host   (any backend, incl. CPU)
       python benchmarks/diag_overhead.py --opt    (any backend, incl. CPU)

``--host`` measures pure HOST dispatch overhead on a tiny MLP where device
compute is negligible: per-step wall time of the cache-hit ``run()`` path
(the dispatch-plan cache's hot path) and of the fused
``run_steps(fetch_every=8)`` driver, plus dispatches-per-step from the
monitor counters — the number the async-pipeline work optimizes.

``--opt`` is the CPU MLP probe for the default trace-time optimizer
(paddle_tpu.passes): builds the same MLP with a metrics side branch and a
constant chain, runs it at ``PADDLE_TPU_OPT_LEVEL=0`` and ``=1``, and
reports traced-op count, trace+compile wall time of the first step, and a
bit-identity check on the losses (dropout RNG included). Exits non-zero if
level 1 fails to shrink the program or perturbs a loss bit.

``--numerics`` is the CPU MLP probe for the streaming tensor-statistics
layer (paddle_tpu.monitor.numerics): cache-hit steady-state ms/step with
``PADDLE_TPU_NUMERICS`` off vs armed (level 1), the measured overhead
ratio, and a bit-identity check of the off-mode losses against a build
that never armed stats. Exits non-zero if the armed overhead exceeds the
documented <=15% contract or level 0 perturbs a loss bit.
"""

from __future__ import annotations

import sys
import time

import numpy as np


def fmt(ca):
    return {k: ca.get(k) for k in ("flops", "bytes accessed", "transcendentals")
            if k in ca}


def host_mode(steps=300, fetch_every=8):
    """CPU-friendly per-step host dispatch cost: cache-hit run() vs the
    fused run_steps driver. Prints one machine-greppable line per driver."""
    sys.path.insert(0, ".")
    import jax

    import paddle_tpu as fluid
    from paddle_tpu import monitor

    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main_prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main_prog, startup):
                x = fluid.layers.data("x", shape=[64])
                y = fluid.layers.data("y", shape=[1], dtype="int64")
                h = fluid.layers.fc(x, size=64, act="relu")
                logits = fluid.layers.fc(h, size=10)
                loss = fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(logits, y))
                fluid.optimizer.Adam(1e-3).minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            rng = np.random.RandomState(0)
            feed = {"x": jax.device_put(rng.randn(32, 64).astype("float32")),
                    "y": jax.device_put(
                        rng.randint(0, 10, (32, 1)).astype("int64"))}

            # steps divisible by fetch_every: no partial-chunk compile
            # inside a timed region
            steps = (steps // fetch_every) * fetch_every

            for _ in range(10):  # compile + warm the dispatch plan
                exe.run(main_prog, feed=feed, fetch_list=[loss],
                        return_numpy=False)
            t0 = time.perf_counter()
            for _ in range(steps):
                out = exe.run(main_prog, feed=feed, fetch_list=[loss],
                              return_numpy=False)
            run_ms = (time.perf_counter() - t0) / steps * 1e3
            np.asarray(out[0])
            print("host_dispatch_ms run()      : %.4f  (cache-hit, "
                  "return_numpy=False)" % run_ms)

            def rep(n):
                return (feed for _ in range(n))

            exe.run_steps(main_prog, rep(2 * fetch_every),
                          steps=2 * fetch_every, fetch_list=[loss],
                          fetch_every=fetch_every, return_numpy=False)
            monitor.metrics.reset()
            t0 = time.perf_counter()
            hs = exe.run_steps(main_prog, rep(steps), steps=steps,
                               fetch_list=[loss], fetch_every=fetch_every,
                               return_numpy=False)
            rs_ms = (time.perf_counter() - t0) / steps * 1e3
            hs[-1].block()
            snap = monitor.snapshot()
            n_disp = snap["executor/run_steps_dispatches"]["value"]
            n_steps = snap["executor/run_steps_steps"]["value"]
            print("host_dispatch_ms run_steps(): %.4f  (fetch_every=%d, "
                  "dispatches/step=%.3f)"
                  % (rs_ms, fetch_every, n_disp / max(n_steps, 1)))
            print("dispatch_reduction          : %.1fx fewer dispatched "
                  "calls" % (n_steps / max(n_disp, 1)))


def opt_mode(steps=6):
    """CPU probe for PADDLE_TPU_OPT_LEVEL: op count + trace/compile time +
    loss bit-identity, level 1 vs level 0 (ISSUE 3 acceptance gate)."""
    import os

    sys.path.insert(0, ".")

    def run_level(level):
        os.environ["PADDLE_TPU_OPT_LEVEL"] = str(level)
        import paddle_tpu as fluid

        with fluid.unique_name.guard():
            with fluid.scope_guard(fluid.Scope()):
                main_prog, startup = fluid.Program(), fluid.Program()
                with fluid.program_guard(main_prog, startup):
                    x = fluid.layers.data("x", shape=[64])
                    y = fluid.layers.data("y", shape=[1], dtype="int64")
                    h = fluid.layers.fc(x, size=64, act="relu")
                    h = fluid.layers.dropout(
                        h, 0.2, dropout_implementation="upscale_in_train")
                    logits = fluid.layers.fc(h, size=10)
                    loss = fluid.layers.mean(
                        fluid.layers.softmax_with_cross_entropy(logits, y))
                    # train-loop baggage the optimizer should shed when only
                    # the loss is fetched: a metrics branch and a dead
                    # constant chain (lr-schedule-style host arithmetic)
                    fluid.layers.accuracy(fluid.layers.softmax(logits), y)
                    c = fluid.layers.fill_constant([1], "float32", 2.0)
                    fluid.layers.scale(c, scale=0.5)
                    fluid.optimizer.Adam(1e-3).minimize(loss)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                rng = np.random.RandomState(0)
                feed = {"x": rng.randn(32, 64).astype("float32"),
                        "y": rng.randint(0, 10, (32, 1)).astype("int64")}
                t0 = time.perf_counter()
                first, = exe.run(main_prog, feed=feed, fetch_list=[loss])
                compile_ms = (time.perf_counter() - t0) * 1e3
                losses = [first.copy()]
                for _ in range(steps - 1):
                    lv, = exe.run(main_prog, feed=feed, fetch_list=[loss])
                    losses.append(lv.copy())
                traced = exe._maybe_optimize(
                    main_prog, (loss.name,), fluid.global_scope())
                return (len(main_prog.global_block.ops),
                        len(traced.global_block.ops), compile_ms, losses)

    src0, traced0, ms0, losses0 = run_level(0)
    src1, traced1, ms1, losses1 = run_level(1)
    identical = all(np.array_equal(a, b)
                    for a, b in zip(losses0, losses1))
    print("opt_probe op_count    : src=%d  traced@0=%d  traced@1=%d"
          % (src0, traced0, traced1))
    print("opt_probe compile_ms  : level0=%.1f  level1=%.1f  (first step, "
          "trace+XLA)" % (ms0, ms1))
    print("opt_probe loss_parity : bit_identical=%s  (%d steps, dropout on)"
          % (identical, len(losses0)))
    ok = traced1 < traced0 and identical and ms1 <= ms0 * 1.05
    print("opt_probe verdict     : %s" % ("OK" if ok else "FAIL"))
    return 0 if ok else 1


def numerics_mode(steps=40, reps=3):
    """CPU MLP probe for PADDLE_TPU_NUMERICS: steady-state cache-hit
    ms/step off vs armed (level 1) and the overhead ratio vs the <=15%
    contract, plus loss bit-identity for the off path (ISSUE 14
    acceptance gate). Two things make the armed path cheap enough: the
    per-op stat reductions are single fused kernels, and armed runs only
    fold stats every PADDLE_TPU_NUMERICS_EVERY-th chunk (default 4) —
    the probe measures the honest steady-state mean over both kinds of
    step. The MLP uses a 1024-wide hidden layer at batch 512 so the
    matmuls carry realistic arithmetic intensity (a toy 512-wide net at
    batch 256 makes ANY per-op observation look like ~50% because its
    matmuls are nearly as memory-bound as the stats themselves)."""
    import os

    sys.path.insert(0, ".")

    def run_mode(level):
        if level is None:
            os.environ.pop("PADDLE_TPU_NUMERICS", None)
        else:
            os.environ["PADDLE_TPU_NUMERICS"] = str(level)
        import paddle_tpu as fluid

        with fluid.unique_name.guard():
            with fluid.scope_guard(fluid.Scope()):
                main_prog, startup = fluid.Program(), fluid.Program()
                with fluid.program_guard(main_prog, startup):
                    x = fluid.layers.data("x", shape=[1024])
                    y = fluid.layers.data("y", shape=[1], dtype="int64")
                    h = fluid.layers.fc(x, size=1024, act="relu")
                    logits = fluid.layers.fc(h, size=10)
                    loss = fluid.layers.mean(
                        fluid.layers.softmax_with_cross_entropy(logits, y))
                    fluid.optimizer.Adam(1e-3).minimize(loss)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                rng = np.random.RandomState(0)
                feed = {"x": rng.randn(512, 1024).astype("float32"),
                        "y": rng.randint(0, 10, (512, 1)).astype("int64")}
                losses = []
                for _ in range(3):  # compile + settle the caches
                    lv, = exe.run(main_prog, feed=feed, fetch_list=[loss])
                    losses.append(lv.copy())
                best = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    for _ in range(steps):
                        exe.run(main_prog, feed=feed, fetch_list=[loss])
                    best.append((time.perf_counter() - t0) / steps * 1e3)
                best.sort()
                # mean of the fastest half: cross-process stable where a
                # bare median wobbles (perf_gate --record discipline)
                half = best[:max(1, len(best) // 2)]
                return sum(half) / len(half), losses

    base_ms, base_losses = run_mode(None)
    off_ms, off_losses = run_mode(0)
    armed_ms, armed_losses = run_mode(1)
    os.environ.pop("PADDLE_TPU_NUMERICS", None)
    off_identical = all(np.array_equal(a, b)
                        for a, b in zip(base_losses, off_losses))
    armed_close = all(np.allclose(a, b, rtol=1e-6)
                      for a, b in zip(base_losses, armed_losses))
    overhead = armed_ms / off_ms - 1.0
    print("numerics_probe ms/step   : unset=%.3f  level0=%.3f  armed=%.3f"
          % (base_ms, off_ms, armed_ms))
    from paddle_tpu.monitor import numerics as _num

    print("numerics_probe overhead  : %+.1f%%  (armed level 1 vs off, "
          "stats every %d chunks; contract <=15%%)"
          % (100.0 * overhead, _num.stats_every()))
    print("numerics_probe loss_parity: level0_bit_identical=%s  "
          "armed_allclose=%s" % (off_identical, armed_close))
    ok = overhead <= 0.15 and off_identical and armed_close
    print("numerics_probe verdict   : %s" % ("OK" if ok else "FAIL"))
    return 0 if ok else 1


def main():
    sys.path.insert(0, ".")
    import jax

    import bench

    batch, seq, vocab = 64, 256, 30000

    # -- framework step ------------------------------------------------------
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tfm

    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main_prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main_prog, startup):
                src = fluid.layers.data("src", shape=[seq], dtype="int64")
                trg = fluid.layers.data("trg", shape=[seq], dtype="int64")
                lbl = fluid.layers.data("lbl", shape=[seq, 1], dtype="int64")
                smask = fluid.layers.data("smask", shape=[seq], dtype="float32")
                tmask = fluid.layers.data("tmask", shape=[seq], dtype="float32")
                logits, loss = tfm.transformer_base(
                    src, trg, lbl, smask, tmask, src_vocab_size=vocab,
                    trg_vocab_size=vocab, max_length=seq, dropout_rate=0.1)
                opt = fluid.optimizer.Adam(learning_rate=1e-4)
                opt = fluid.amp.decorate(opt)
                opt.minimize(loss)

            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(startup)
            rng = np.random.RandomState(0)
            feed = bench._device_feed({
                "src": rng.randint(2, vocab, (batch, seq)).astype("int64"),
                "trg": rng.randint(2, vocab, (batch, seq)).astype("int64"),
                "lbl": rng.randint(2, vocab, (batch, seq, 1)).astype("int64"),
                "smask": np.ones((batch, seq), "float32"),
                "tmask": np.ones((batch, seq), "float32"),
            })
            # trigger compile + grab the cached step
            exe.run(main_prog, feed=feed, fetch_list=[loss], return_numpy=False)
            compiled = next(c for c in exe._cache.values() if c.fetch_names)
            scope = fluid.global_scope()
            state = {n: scope.vars[n] for n in compiled.state_names
                     if n in scope.vars}
            comp = compiled.fn.lower(state, feed, np.uint32(0)).compile()
            ca = comp.cost_analysis()
            print("paddle_tpu :", fmt(ca))
            print("paddle_tpu mem:", comp.memory_analysis())
            with open("/tmp/hlo_paddle.txt", "w") as f:
                f.write(comp.as_text())

            def fw_step():
                lv, = exe.run(main_prog, feed=feed, fetch_list=[loss],
                              return_numpy=False)
                return lv

            eps, sps = bench._timeit(fw_step, batch)
            print("paddle_tpu : %.1f ex/s  %.2f ms/step" % (eps, 1e3 / sps))

    # -- raw JAX twin --------------------------------------------------------
    # rebuild raw bench pieces with lowering access
    import functools

    import jax.numpy as jnp  # noqa

    diag = {}
    eps_raw, sps_raw = bench.bench_raw_jax_transformer(batch, seq, vocab,
                                                       _diag=diag)
    if "lowered" in diag:
        rcomp = diag["lowered"].compile()
        print("raw jax    :", fmt(rcomp.cost_analysis()))
        print("raw jax mem:", rcomp.memory_analysis())
        with open("/tmp/hlo_raw.txt", "w") as f:
            f.write(rcomp.as_text())
    print("raw jax    : %.1f ex/s  %.2f ms/step" % (eps_raw, 1e3 / sps_raw))
    print("overhead   : %.4f" % (eps_raw / eps))


if __name__ == "__main__":
    if "--host" in sys.argv:
        host_mode()
    elif "--opt" in sys.argv:
        sys.exit(opt_mode())
    elif "--numerics" in sys.argv:
        sys.exit(numerics_mode())
    else:
        main()
