"""Diagnose the framework-vs-raw-JAX gap at the XLA level.

Lowers both the paddle_tpu transformer train step and bench.py's raw-JAX
twin at identical shapes, compiles, and prints XLA cost analysis (flops,
bytes accessed) plus a measured per-step time for each. The delta in flops
or bytes names the part of the traced program that raw JAX doesn't have.

Usage: python benchmarks/diag_overhead.py  (on axon TPU)
"""

from __future__ import annotations

import sys
import time

import numpy as np


def fmt(ca):
    return {k: ca.get(k) for k in ("flops", "bytes accessed", "transcendentals")
            if k in ca}


def main():
    sys.path.insert(0, ".")
    import jax

    import bench

    batch, seq, vocab = 64, 256, 30000

    # -- framework step ------------------------------------------------------
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tfm

    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main_prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main_prog, startup):
                src = fluid.layers.data("src", shape=[seq], dtype="int64")
                trg = fluid.layers.data("trg", shape=[seq], dtype="int64")
                lbl = fluid.layers.data("lbl", shape=[seq, 1], dtype="int64")
                smask = fluid.layers.data("smask", shape=[seq], dtype="float32")
                tmask = fluid.layers.data("tmask", shape=[seq], dtype="float32")
                logits, loss = tfm.transformer_base(
                    src, trg, lbl, smask, tmask, src_vocab_size=vocab,
                    trg_vocab_size=vocab, max_length=seq, dropout_rate=0.1)
                opt = fluid.optimizer.Adam(learning_rate=1e-4)
                opt = fluid.amp.decorate(opt)
                opt.minimize(loss)

            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(startup)
            rng = np.random.RandomState(0)
            feed = bench._device_feed({
                "src": rng.randint(2, vocab, (batch, seq)).astype("int64"),
                "trg": rng.randint(2, vocab, (batch, seq)).astype("int64"),
                "lbl": rng.randint(2, vocab, (batch, seq, 1)).astype("int64"),
                "smask": np.ones((batch, seq), "float32"),
                "tmask": np.ones((batch, seq), "float32"),
            })
            # trigger compile + grab the cached step
            exe.run(main_prog, feed=feed, fetch_list=[loss], return_numpy=False)
            compiled = next(c for c in exe._cache.values() if c.fetch_names)
            scope = fluid.global_scope()
            state = {n: scope.vars[n] for n in compiled.state_names
                     if n in scope.vars}
            comp = compiled.fn.lower(state, feed, np.uint32(0)).compile()
            ca = comp.cost_analysis()
            print("paddle_tpu :", fmt(ca))
            print("paddle_tpu mem:", comp.memory_analysis())
            with open("/tmp/hlo_paddle.txt", "w") as f:
                f.write(comp.as_text())

            def fw_step():
                lv, = exe.run(main_prog, feed=feed, fetch_list=[loss],
                              return_numpy=False)
                return lv

            eps, sps = bench._timeit(fw_step, batch)
            print("paddle_tpu : %.1f ex/s  %.2f ms/step" % (eps, 1e3 / sps))

    # -- raw JAX twin --------------------------------------------------------
    # rebuild raw bench pieces with lowering access
    import functools

    import jax.numpy as jnp  # noqa

    diag = {}
    eps_raw, sps_raw = bench.bench_raw_jax_transformer(batch, seq, vocab,
                                                       _diag=diag)
    if "lowered" in diag:
        rcomp = diag["lowered"].compile()
        print("raw jax    :", fmt(rcomp.cost_analysis()))
        print("raw jax mem:", rcomp.memory_analysis())
        with open("/tmp/hlo_raw.txt", "w") as f:
            f.write(rcomp.as_text())
    print("raw jax    : %.1f ex/s  %.2f ms/step" % (eps_raw, 1e3 / sps_raw))
    print("overhead   : %.4f" % (eps_raw / eps))


if __name__ == "__main__":
    main()
