"""Why don't our post-value_and_grad Adam updates fuse into the grad dots?

Bisects the BERT train config feature by feature on a small MLP: each
--with-* flag moves the repro one step toward bench_bert's setup. After
compiling on the TPU we count, over fusion computations whose divide comes
from optimizer_ops.py (the Adam update), how many also contain the weight-
grad matmul (`convolution` on this backend) — vertically fused — vs stand
alone.

Usage: python benchmarks/diag_adam_fusion.py [--amp] [--dropout] [--ln]
         [--emb] [--gelu] [--layers N] [--d N]
"""
from __future__ import annotations

import re
import sys

import numpy as np

sys.path.insert(0, ".")


def adam_fusion_stats(hlo: str, tag: str):
    comps = hlo.split("\n\n")
    fused = alone = 0
    for c in comps:
        if "optimizer_ops.py" not in c or " divide(" not in c:
            continue
        if not c.lstrip().startswith("%fused_computation"):
            continue
        if " convolution(" in c:
            fused += 1
        else:
            alone += 1
    print("%s: adam fusions WITH grad-matmul=%d  standalone=%d"
          % (tag, fused, alone))
    return fused, alone


def adam_fusion_params(hlo: str):
    """For every standalone adam fusion, print the output tuple shape sig."""
    comps = hlo.split("\n\n")
    for c in comps:
        if "optimizer_ops.py" not in c or " divide(" not in c:
            continue
        if not c.lstrip().startswith("%fused_computation"):
            continue
        if " convolution(" in c:
            continue
        head = c.lstrip().split("\n", 1)[0]
        sig = head.split("->", 1)[1] if "->" in head else head
        print("  standalone:", sig.strip()[:100])


def run_bert(args):
    import paddle_tpu as fluid
    from paddle_tpu.models import bert

    n_layer = 2
    batch, seq, n_mask = 32, 128, 20
    cfg = dict(bert.BERT_BASE_CONFIG, n_layer=n_layer)
    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main_prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main_prog, startup):
                ids = fluid.layers.data("ids", shape=[seq], dtype="int64")
                pos = fluid.layers.data("pos", shape=[seq], dtype="int64")
                sent = fluid.layers.data("sent", shape=[seq], dtype="int64")
                mask = fluid.layers.data("mask", shape=[seq], dtype="float32")
                mpos = fluid.layers.data("mpos", shape=[n_mask], dtype="int64")
                mlbl = fluid.layers.data("mlbl", shape=[1], dtype="int64")
                nsp = fluid.layers.data("nsp", shape=[1], dtype="int64")
                loss, _, _ = bert.bert_pretrain(ids, pos, sent, mask, mpos,
                                                mlbl, nsp, **cfg)
                opt = fluid.optimizer.Adam(learning_rate=1e-4)
                if "--amp" in args:
                    opt = fluid.amp.decorate(opt)
                opt.minimize(loss)
            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(startup)
            rng = np.random.RandomState(0)
            mpos_np = (np.arange(batch)[:, None] * seq
                       + rng.randint(0, seq, (batch, n_mask))).astype("int64")
            feed = {
                "ids": rng.randint(0, 30522, (batch, seq)).astype("int64"),
                "pos": np.tile(np.arange(seq), (batch, 1)).astype("int64"),
                "sent": np.zeros((batch, seq), "int64"),
                "mask": np.ones((batch, seq), "float32"),
                "mpos": mpos_np,
                "mlbl": rng.randint(0, 30522, (batch * n_mask, 1)).astype("int64"),
                "nsp": rng.randint(0, 2, (batch, 1)).astype("int64"),
            }
            exe.run(main_prog, feed=feed, fetch_list=[loss],
                    return_numpy=False)
            compiled = next(c for c in exe._cache.values() if c.fetch_names)
            scope = fluid.global_scope()
            state = {n: scope.vars[n] for n in compiled.state_names
                     if n in scope.vars}
            comp = compiled.fn.lower(state, feed, np.uint32(0)).compile()
            hlo_p = comp.as_text()
            with open("/tmp/hlo_adam_bert.txt", "w") as f:
                f.write(hlo_p)
    adam_fusion_stats(hlo_p, "bert2[%s]" % " ".join(sorted(args)))
    adam_fusion_params(hlo_p)


def main():
    args = set(sys.argv[1:])

    def intarg(name, default):
        for a in sys.argv[1:]:
            if a.startswith(name + "="):
                return int(a.split("=")[1])
        return default

    if "--bert" in args:
        run_bert(args)
        return

    n_layer = intarg("--layers", 4)
    d = intarg("--d", 512)
    batch = 64

    import paddle_tpu as fluid

    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main_prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main_prog, startup):
                if "--emb" in args:
                    tok = fluid.layers.data("tok", shape=[1], dtype="int64")
                    x = fluid.layers.embedding(tok, size=[1000, d])
                    x = fluid.layers.reshape(x, [-1, d])
                else:
                    x = fluid.layers.data("x", shape=[d], dtype="float32")
                y = fluid.layers.data("y", shape=[1], dtype="int64")
                h = x
                act = "gelu" if "--gelu" in args else "relu"
                for _ in range(n_layer):
                    h = fluid.layers.fc(h, size=d, act=act)
                    if "--ln" in args:
                        h = fluid.layers.layer_norm(h)
                    if "--dropout" in args:
                        h = fluid.layers.dropout(h, dropout_prob=0.1)
                logits = fluid.layers.fc(h, size=10)
                loss = fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(logits, y))
                opt = fluid.optimizer.Adam(learning_rate=1e-4)
                if "--amp" in args:
                    opt = fluid.amp.decorate(opt)
                opt.minimize(loss)
            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(startup)
            rng = np.random.RandomState(0)
            feed = {"y": rng.randint(0, 10, (batch, 1)).astype("int64")}
            if "--emb" in args:
                feed["tok"] = rng.randint(0, 1000, (batch, 1)).astype("int64")
            else:
                feed["x"] = rng.randn(batch, d).astype("float32")
            exe.run(main_prog, feed=feed, fetch_list=[loss],
                    return_numpy=False)
            compiled = next(c for c in exe._cache.values() if c.fetch_names)
            scope = fluid.global_scope()
            state = {n: scope.vars[n] for n in compiled.state_names
                     if n in scope.vars}
            comp = compiled.fn.lower(state, feed, np.uint32(0)).compile()
            hlo_p = comp.as_text()
            with open("/tmp/hlo_adam_paddle.txt", "w") as f:
                f.write(hlo_p)
    adam_fusion_stats(hlo_p, "paddle[%s]" % " ".join(sorted(args)))


if __name__ == "__main__":
    main()
