"""Benchmark harness (reference: benchmark/fluid/fluid_benchmark.py).

Reports the reference harness's metric — train ``examples/sec`` with warmup
exclusion (``--skip_batch_num`` semantics, args.py:40) — for the flagship
Transformer-base training step on the available accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline: the reference repo publishes no numeric tables
(BASELINE.md — "published: {}"), so the ratio is against the round-1
measurement of this framework recorded below once available.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# Round-1 reference point (examples/sec on a single TPU v5e chip), filled in
# after the first recorded run so later rounds report progress against it.
ROUND1_BASELINE_EXAMPLES_PER_SEC = 204.15  # 2026-07-29, single TPU v5e chip, fp32


def main():
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tfm

    batch, seq, vocab = 64, 256, 30000
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        src = fluid.layers.data("src", shape=[seq], dtype="int64")
        trg = fluid.layers.data("trg", shape=[seq], dtype="int64")
        lbl = fluid.layers.data("lbl", shape=[seq, 1], dtype="int64")
        smask = fluid.layers.data("smask", shape=[seq], dtype="float32")
        tmask = fluid.layers.data("tmask", shape=[seq], dtype="float32")
        logits, loss = tfm.transformer_base(
            src, trg, lbl, smask, tmask, src_vocab_size=vocab,
            trg_vocab_size=vocab, max_length=seq, dropout_rate=0.1)
        fluid.optimizer.Adam(learning_rate=1e-4).minimize(loss)

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)

    rng = np.random.RandomState(0)
    feed = {
        "src": rng.randint(2, vocab, (batch, seq)).astype("int64"),
        "trg": rng.randint(2, vocab, (batch, seq)).astype("int64"),
        "lbl": rng.randint(2, vocab, (batch, seq, 1)).astype("int64"),
        "smask": np.ones((batch, seq), "float32"),
        "tmask": np.ones((batch, seq), "float32"),
    }

    skip_batch_num, num_batches = 3, 10
    for _ in range(skip_batch_num):  # warmup incl. compile
        exe.run(main_prog, feed=feed, fetch_list=[loss])
    t0 = time.time()
    for _ in range(num_batches):
        lv, = exe.run(main_prog, feed=feed, fetch_list=[loss])
    elapsed = time.time() - t0
    examples_per_sec = batch * num_batches / elapsed

    vs = (examples_per_sec / ROUND1_BASELINE_EXAMPLES_PER_SEC
          if ROUND1_BASELINE_EXAMPLES_PER_SEC else 1.0)
    print(json.dumps({
        "metric": "transformer_base_train_examples_per_sec_b%d_s%d" % (batch, seq),
        "value": round(examples_per_sec, 2),
        "unit": "examples/sec",
        "vs_baseline": round(vs, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
