"""Benchmark harness (reference: benchmark/fluid/fluid_benchmark.py).

Reports the reference harness's metric — train ``examples/sec`` with warmup
exclusion (``--skip_batch_num`` semantics, args.py:40) — for:

  * Transformer-base training (bf16 AMP, the TPU-native float16 story)
  * ResNet-50 ImageNet-shape training (bf16 AMP)
  * a raw-JAX Transformer-base step of identical shape/precision — the
    framework-overhead yardstick (paddle_tpu should be within a few % of it)

plus derived step/sec and estimated MFU against the chip's bf16 peak.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

vs_baseline: the reference repo publishes no numeric tables (BASELINE.md —
"published: {}"), so the ratio is against the round-1 measurement of this
framework (fp32, same chip class) recorded below.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np

# Round-1 recorded measurement (examples/sec, single TPU v5e chip, fp32,
# Transformer-base b64 s256) — the cross-round progress denominator.
ROUND1_BASELINE_EXAMPLES_PER_SEC = 197.84

# bf16 peak FLOP/s per chip by device kind (public spec sheets).
_PEAK_BF16 = {
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5e": 197e12,
    "TPU v5 lite": 197e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
}


def _device_peak_flops():
    import jax

    kind = jax.devices()[0].device_kind
    for k, v in _PEAK_BF16.items():
        if k.lower() in kind.lower():
            return v, kind
    return None, kind


def _transformer_train_flops_per_example(seq, vocab, n_layer=6, d_model=512,
                                         d_inner=2048):
    """Analytic fwd FLOPs ×3 for fwd+bwd (MFU estimate, not a measurement)."""
    s, d, di, L, V = seq, d_model, d_inner, n_layer, vocab
    enc = L * (8 * s * d * d + 4 * s * s * d + 4 * s * d * di)
    dec = L * (16 * s * d * d + 8 * s * s * d + 4 * s * d * di)
    proj = 2 * s * d * V
    return 3 * (enc + dec + proj)


_RESNET50_TRAIN_FLOPS_PER_IMAGE = 3 * 4.1e9  # ~4.1 GFLOP fwd @224²


def _mesh_prog(fluid, main_prog, loss, n_devices, model_devices=1):
    """(program-to-run, mesh) — CompiledProgram over a data(/model) mesh.

    ``model_devices > 1`` adds a TP axis: embedding tables row-sharded and
    the softmax projection column-sharded over ``model`` (same annotations
    as __graft_entry__.dryrun_multichip's dp x tp leg)."""
    if not n_devices:
        if model_devices and model_devices > 1:
            raise ValueError(
                "model_devices=%d requires n_devices (a data axis); without "
                "a mesh the run would silently measure a 1-chip program"
                % model_devices)
        return main_prog, None
    from paddle_tpu.parallel.mesh import create_mesh

    axes = {"data": n_devices}
    if model_devices and model_devices > 1:
        axes["model"] = model_devices
        from paddle_tpu.parallel import annotate_sharding

        for v in main_prog.all_parameters():
            if v.name in ("src_emb", "trg_emb"):
                annotate_sharding(v, ("model", None))
            elif v.name.startswith("predict") and len(v.shape) == 2:
                annotate_sharding(v, (None, "model"))
    mesh = create_mesh(axes)
    prog = fluid.CompiledProgram(main_prog).with_mesh(mesh, loss_name=loss.name)
    return prog, mesh


def _device_feed(feed, mesh=None):
    """Pre-place feed arrays in HBM once — the benchmark measures the train
    step, not host→device (or tunnel) transfer of identical data every
    iteration. The executor keeps jax.Arrays as-is (no host round-trip).
    With ``mesh``, arrays are pre-sharded batch-major over the ``data`` axis
    so the N-device run doesn't pay a growing H2D transfer per step either
    (which would systematically understate scaling efficiency)."""
    import jax

    if mesh is None:
        return {k: jax.device_put(v) for k, v in feed.items()}
    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(v):
        spec = P("data", *([None] * (v.ndim - 1)))
        return jax.device_put(v, NamedSharding(mesh, spec))

    return {k: put(v) for k, v in feed.items()}


def _timeit(run_step, batch, skip=5, iters=20, epochs=3):
    """Dispatch ``iters`` chained steps per epoch, ``epochs`` epochs, then
    report throughput from the MEDIAN epoch. Each step's state feeds the
    next, so the end-of-epoch value fetch transitively executes the whole
    chain; fetching bytes (np.asarray) is the only reliable sync through a
    remote-device tunnel (block_until_ready can return early there), and
    doing it once per epoch amortizes the round-trip latency.

    Tunnel epochs carry ~±10% jitter (r4: the 0.44-0.49 MFU band), so a
    single epoch is soft — the median is the reported number and the raw
    per-epoch times are stashed on ``_timeit.last`` for error bars
    (read via _last_spread() right after the call).

    A monitor.StepLogger rides along: one progress line per epoch on
    stderr, and its summary() lands in ``_timeit.last["step_logger"]`` for
    the bench-JSON metrics section. NOTE: the steps here chain async device
    work (return_numpy=False, one fetch per epoch), so the logger's
    per-step intervals are HOST DISPATCH gaps, not device step time — the
    epoch-boundary sample absorbs the real compute. They are published as
    ``host_dispatch_ms`` (a host-overhead/pipeline-stall signal); the
    truthful throughput numbers remain the eps_* fields."""
    from paddle_tpu.monitor import StepLogger

    for _ in range(skip):  # warmup incl. compile — fetch to really finish
        np.asarray(run_step())
    slog = StepLogger(every_n=iters, name="bench")
    times = []
    for _ in range(max(1, epochs)):
        t0 = time.time()
        for _ in range(iters):
            out = run_step()
            slog.step(examples=batch)
        assert np.isfinite(np.asarray(out)).all()
        times.append(time.time() - t0)
    dt = sorted(times)[len(times) // 2]
    _timeit.last = {
        "epoch_sec": [round(t, 4) for t in times],
        "eps_median": batch * iters / dt,
        "eps_max": batch * iters / min(times),
        "eps_min": batch * iters / max(times),
        "step_logger": slog.summary(),
    }
    return batch * iters / dt, iters / dt


def _timeit_pipeline(exe, prog, feed, fetch_list, batch, skip=5, iters=20,
                     epochs=3, fetch_every=8):
    """Async-driver twin of _timeit: each epoch is ``iters`` steps driven by
    ``Executor.run_steps`` with ``fetch_every`` steps fused per dispatch
    (1/``fetch_every`` the host dispatches of the run()-per-step loop).

    Two numbers per epoch land in the bench JSON: ``host_dispatch_ms_per_
    step`` — wall time until every chunk is dispatched, fetches unresolved
    (the pipeline-headroom signal: how far the host runs ahead of the
    device) — and ``synced_step_ms`` — dispatch + resolving the final
    handle, which transitively waits for the whole chain (the truthful
    throughput number; eps_* derive from it)."""

    def rep(n):
        return (feed for _ in range(n))

    # warm with the full epoch step count so BOTH chain lengths (the
    # fetch_every-chunk and the final partial chunk) compile outside the
    # timed region
    warm = max(iters, skip)
    hs = exe.run_steps(prog, rep(warm), steps=warm, fetch_list=fetch_list,
                       fetch_every=fetch_every, return_numpy=False)
    np.asarray(hs[-1][0])
    times, dispatch_times, n_dispatches = [], [], 0
    for _ in range(max(1, epochs)):
        t0 = time.time()
        hs = exe.run_steps(prog, rep(iters), steps=iters,
                           fetch_list=fetch_list, fetch_every=fetch_every,
                           return_numpy=False)
        dispatch_times.append(time.time() - t0)
        out = np.asarray(hs[-1][0])  # sync: resolves the whole chain
        assert np.isfinite(out).all()
        times.append(time.time() - t0)
        n_dispatches = len(hs)
    dt = sorted(times)[len(times) // 2]
    _timeit.last = {
        "epoch_sec": [round(t, 4) for t in times],
        "eps_median": batch * iters / dt,
        "eps_max": batch * iters / min(times),
        "eps_min": batch * iters / max(times),
        "pipeline": {
            "fetch_every": fetch_every,
            "dispatches_per_epoch": n_dispatches,
            "steps_per_dispatch": round(iters / max(n_dispatches, 1), 2),
            "host_dispatch_ms_per_step": round(
                sorted(dispatch_times)[len(dispatch_times) // 2]
                / iters * 1e3, 4),
            "synced_step_ms": round(dt / iters * 1e3, 4),
        },
    }
    return batch * iters / dt, iters / dt


def _last_spread():
    """Per-epoch spread of the most recent _timeit call, for bench JSON."""
    last = getattr(_timeit, "last", None)
    if not last:
        return {}
    out = {"eps_min": round(last["eps_min"], 2),
           "eps_max": round(last["eps_max"], 2),
           "n_epochs": len(last["epoch_sec"])}
    sl = last.get("step_logger") or {}
    if "step_time_ms" in sl:
        # honest name: chained async steps make these host dispatch gaps
        # (see _timeit docstring), not device step time
        out["host_dispatch_ms"] = sl["step_time_ms"]
    if "pipeline" in last:
        out["pipeline"] = last["pipeline"]
    return out


# -- paddle_tpu benches -------------------------------------------------------


def bench_transformer(batch=64, seq=256, vocab=30000, use_amp=True,
                      n_devices=None, skip=5, iters=20, model_devices=1,
                      epochs=3, pipeline=False, fetch_every=8):
    """``n_devices``: run through CompiledProgram.with_mesh({'data': n}) —
    the GSPMD data-parallel path — with ``batch`` as the GLOBAL batch.
    ``model_devices``: add a TP axis (dp x tp mesh, see _mesh_prog).
    ``pipeline``: drive with the fused async Executor.run_steps driver."""
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tfm

    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main_prog, startup = fluid.Program(), fluid.Program()
            # build attention from primitives (the reference dist_transformer
            # composition): the default trace-time optimizer's
            # flash_attention_rewrite (PADDLE_TPU_OPT_LEVEL>=1) fuses the
            # non-causal sites back onto the fused-attention op at prepare
            # time — this config is the standing proof that primitive-built
            # programs reach the Pallas kernel without opting in
            prev_unfused = fluid.get_flag("unfused_attention")
            fluid.set_flag("unfused_attention", True)
            try:
                with fluid.program_guard(main_prog, startup):
                    src = fluid.layers.data("src", shape=[seq], dtype="int64")
                    trg = fluid.layers.data("trg", shape=[seq], dtype="int64")
                    lbl = fluid.layers.data("lbl", shape=[seq, 1], dtype="int64")
                    smask = fluid.layers.data("smask", shape=[seq], dtype="float32")
                    tmask = fluid.layers.data("tmask", shape=[seq], dtype="float32")
                    logits, loss = tfm.transformer_base(
                        src, trg, lbl, smask, tmask, src_vocab_size=vocab,
                        trg_vocab_size=vocab, max_length=seq, dropout_rate=0.1)
                    opt = fluid.optimizer.Adam(learning_rate=1e-4)
                    if use_amp:
                        opt = fluid.amp.decorate(opt)
                    opt.minimize(loss)
            finally:
                fluid.set_flag("unfused_attention", prev_unfused)

            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(startup)

            prog, mesh = _mesh_prog(fluid, main_prog, loss, n_devices,
                                    model_devices)

            rng = np.random.RandomState(0)
            feed = {
                "src": rng.randint(2, vocab, (batch, seq)).astype("int64"),
                "trg": rng.randint(2, vocab, (batch, seq)).astype("int64"),
                "lbl": rng.randint(2, vocab, (batch, seq, 1)).astype("int64"),
                "smask": np.ones((batch, seq), "float32"),
                "tmask": np.ones((batch, seq), "float32"),
            }
            feed = _device_feed(feed, mesh)

            if pipeline:
                return _timeit_pipeline(exe, prog, feed, [loss], batch,
                                        skip=skip, iters=iters, epochs=epochs,
                                        fetch_every=fetch_every)

            def step():
                lv, = exe.run(prog, feed=feed, fetch_list=[loss],
                              return_numpy=False)
                return lv

            return _timeit(step, batch, skip=skip, iters=iters,
                           epochs=epochs)


def bench_resnet50(batch=64, image=224, classes=1000, use_amp=True,
                   n_devices=None, skip=5, iters=20, epochs=3,
                   pipeline=False, fetch_every=8):
    import paddle_tpu as fluid
    from paddle_tpu.models import resnet as rn

    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main_prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main_prog, startup):
                img = fluid.layers.data("img", shape=[3, image, image])
                label = fluid.layers.data("label", shape=[1], dtype="int64")
                logits, loss, acc = rn.resnet50(img, label, class_num=classes)
                opt = fluid.optimizer.Momentum(0.1, 0.9)
                if use_amp:
                    opt = fluid.amp.decorate(opt)
                opt.minimize(loss)

            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(startup)

            prog, mesh = _mesh_prog(fluid, main_prog, loss, n_devices)

            rng = np.random.RandomState(0)
            feed = {
                "img": rng.randn(batch, 3, image, image).astype("float32"),
                "label": rng.randint(0, classes, (batch, 1)).astype("int64"),
            }
            feed = _device_feed(feed, mesh)

            if pipeline:
                return _timeit_pipeline(exe, prog, feed, [loss], batch,
                                        skip=skip, iters=iters, epochs=epochs,
                                        fetch_every=fetch_every)

            def step():
                lv, = exe.run(prog, feed=feed, fetch_list=[loss],
                              return_numpy=False)
                return lv

            return _timeit(step, batch, skip=skip, iters=iters)


# -- raw-JAX yardsticks -------------------------------------------------------


def bench_raw_jax_resnet50(batch=64, image=224, classes=1000):
    """Hand-written JAX ResNet-50 train step, same shapes/precision as the
    paddle_tpu bench (bf16 forward, fp32 master, Momentum). ResNet-50 at this
    batch is HBM-bandwidth-bound on TPU (see benchmarks/RESNET50_PROFILE.md);
    this yardstick proves the framework sits at XLA's own ceiling."""
    import jax
    import jax.numpy as jnp

    dn = ("NCHW", "OIHW", "NCHW")
    cfg = [(64, 256, 3, 1), (128, 512, 4, 2), (256, 1024, 6, 2), (512, 2048, 3, 2)]
    keys = iter(jax.random.split(jax.random.PRNGKey(0), 200))

    def conv_p(cin, cout, k):
        fan = cin * k * k
        return jax.random.normal(next(keys), (cout, cin, k, k), jnp.float32) * (2.0 / fan) ** 0.5

    def bn_p(c):
        # running mean/var included so the yardstick does the SAME work as
        # the framework step (EMA updates ride along in the state)
        return {"g": jnp.ones((c,)), "b": jnp.zeros((c,)),
                "rm": jnp.zeros((c,)), "rv": jnp.ones((c,))}

    params = {"stem": conv_p(3, 64, 7), "stem_bn": bn_p(64)}
    cin = 64
    for si, (mid, cout, n, stride) in enumerate(cfg):
        for bi in range(n):
            p = {"c1": conv_p(cin, mid, 1), "bn1": bn_p(mid),
                 "c2": conv_p(mid, mid, 3), "bn2": bn_p(mid),
                 "c3": conv_p(mid, cout, 1), "bn3": bn_p(cout)}
            if bi == 0:
                p["sc"], p["sbn"] = conv_p(cin, cout, 1), bn_p(cout)
            params["s%d_%d" % (si, bi)] = p
            cin = cout
    params["fc_w"] = jax.random.normal(next(keys), (2048, classes)) * 0.01
    params["fc_b"] = jnp.zeros((classes,))

    def conv(x, w, stride):
        k = w.shape[2]
        pad = (k - 1) // 2
        return jax.lax.conv_general_dilated(
            x, w, (stride, stride), [(pad, pad)] * 2, dimension_numbers=dn)

    def bn(x, p, stats, nm):
        n_el = x.shape[0] * x.shape[2] * x.shape[3]
        m = jnp.sum(x, (0, 2, 3), dtype=jnp.float32) / n_el
        v = (jnp.sum(jnp.square(x.astype(jnp.float32)), (0, 2, 3),
                     dtype=jnp.float32) / n_el - m ** 2)
        stats[nm] = (0.9 * p["rm"].astype(jnp.float32) + 0.1 * m,
                     0.9 * p["rv"].astype(jnp.float32) + 0.1 * v)
        inv = jax.lax.rsqrt(v + 1e-5).astype(x.dtype)
        sh = (1, -1, 1, 1)
        return ((x - m.astype(x.dtype).reshape(sh)) * inv.reshape(sh)
                * p["g"].astype(x.dtype).reshape(sh)
                + p["b"].astype(x.dtype).reshape(sh))

    def block(x, p, stride, stats, nm):
        h = jax.nn.relu(bn(conv(x, p["c1"], 1), p["bn1"], stats, nm + "/bn1"))
        h = jax.nn.relu(bn(conv(h, p["c2"], stride), p["bn2"], stats, nm + "/bn2"))
        h = bn(conv(h, p["c3"], 1), p["bn3"], stats, nm + "/bn3")
        if "sc" in p:
            x = bn(conv(x, p["sc"], stride), p["sbn"], stats, nm + "/sbn")
        return jax.nn.relu(x + h)

    def loss_fn(params32, img, lbl):
        p = jax.tree_util.tree_map(
            lambda t: t.astype(jnp.bfloat16) if t.dtype == jnp.float32 else t,
            params32)
        stats = {}
        x = img.astype(jnp.bfloat16)
        x = jax.nn.relu(bn(conv(x, p["stem"], 2), p["stem_bn"], stats, "stem_bn"))
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 1, 3, 3), (1, 1, 2, 2),
            [(0, 0), (0, 0), (1, 1), (1, 1)])
        for si, (mid, cout, n, stride) in enumerate(cfg):
            for bi in range(n):
                nm = "s%d_%d" % (si, bi)
                x = block(x, p[nm], stride if bi == 0 else 1, stats, nm)
        x = x.mean((2, 3))
        logits = (x @ p["fc_w"] + p["fc_b"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        acc = (logits.argmax(-1) == lbl[:, 0]).mean()  # framework fetches acc-able graph
        loss = -jnp.take_along_axis(logp, lbl, axis=-1).mean()
        return loss, (stats, acc)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, mom, img, lbl):
        (loss, (stats, _acc)), g = jax.value_and_grad(
            loss_fn, has_aux=True)(params, img, lbl)
        mom = jax.tree_util.tree_map(lambda m, gg: 0.9 * m + gg, mom, g)
        params = jax.tree_util.tree_map(lambda p_, m: p_ - 0.1 * m, params, mom)
        # write back running stats (EMA) by name, matching the framework's BN
        params = dict(params)
        for nm, (rm, rv) in stats.items():
            tree = params
            *path, leaf = nm.split("/")
            for kk in path:
                tree[kk] = dict(tree[kk])
                tree = tree[kk]
            tree[leaf] = dict(tree[leaf], rm=rm, rv=rv)
        return params, mom, loss

    import jax as _jax

    rng = np.random.RandomState(0)
    img = _jax.device_put(rng.randn(batch, 3, image, image).astype("float32"))
    lbl = _jax.device_put(rng.randint(0, classes, (batch, 1)))
    mom = jax.tree_util.tree_map(jnp.zeros_like, params)
    state = {"p": params, "m": mom}

    def step():
        state["p"], state["m"], loss = train_step(state["p"], state["m"], img, lbl)
        return loss

    return _timeit(step, batch)


def bench_raw_jax_transformer(batch=64, seq=256, vocab=30000, n_layer=6,
                              n_head=8, d_model=512, d_inner=2048, _diag=None,
                              _profile_dir=None):
    """A hand-written JAX Transformer-base train step with the same shapes,
    label smoothing, Adam, dropout, and bf16-forward/fp32-master semantics as
    the paddle_tpu bench — measures what the framework layer costs."""
    import jax
    import jax.numpy as jnp

    dk = d_model // n_head
    k0 = jax.random.PRNGKey(0)

    def dense_init(key, fan_in, shape):
        bound = (6.0 / (fan_in + shape[-1])) ** 0.5
        return jax.random.uniform(key, shape, jnp.float32, -bound, bound)

    params = {}
    keys = iter(jax.random.split(k0, 200))
    params["src_emb"] = jax.random.normal(next(keys), (vocab, d_model)) * d_model ** -0.5
    params["trg_emb"] = jax.random.normal(next(keys), (vocab, d_model)) * d_model ** -0.5
    for side, L in (("enc", n_layer), ("dec", n_layer)):
        for i in range(L):
            p = {}
            n_attn = 1 if side == "enc" else 2
            for a in range(n_attn):
                p["qkv_%d" % a] = dense_init(next(keys), d_model, (d_model, 3 * d_model))
                p["o_%d" % a] = dense_init(next(keys), d_model, (d_model, d_model))
                p["ln_a%d_g" % a] = jnp.ones((d_model,))
                p["ln_a%d_b" % a] = jnp.zeros((d_model,))
            p["fc1"] = dense_init(next(keys), d_model, (d_model, d_inner))
            p["fc2"] = dense_init(next(keys), d_inner, (d_inner, d_model))
            p["ln_f_g"] = jnp.ones((d_model,))
            p["ln_f_b"] = jnp.zeros((d_model,))
            params["%s_%d" % (side, i)] = p
    params["ln_enc_g"] = jnp.ones((d_model,))
    params["ln_enc_b"] = jnp.zeros((d_model,))
    params["ln_dec_g"] = jnp.ones((d_model,))
    params["ln_dec_b"] = jnp.zeros((d_model,))
    params["proj"] = dense_init(next(keys), d_model, (d_model, vocab))

    pos = np.arange(seq)[:, None] / np.power(
        10000, 2 * (np.arange(d_model)[None, :] // 2) / d_model)
    pos_table = np.zeros((seq, d_model), "float32")
    pos_table[:, 0::2] = np.sin(pos[:, 0::2])
    pos_table[:, 1::2] = np.cos(pos[:, 1::2])
    pos_table = jnp.asarray(pos_table)

    def ln(x, g, b):
        m = x.mean(-1, keepdims=True)
        v = ((x - m) ** 2).mean(-1, keepdims=True)
        return (x - m) / jnp.sqrt(v + 1e-5) * g + b

    def mha(x, kv, qkvw, ow, causal, key):
        q, k, v = jnp.split(x @ qkvw if kv is None else
                            jnp.concatenate([x @ qkvw[:, :d_model],
                                             kv @ qkvw[:, d_model:]], -1),
                            [d_model, 2 * d_model], axis=-1)

        def heads(t):
            return t.reshape(t.shape[0], t.shape[1], n_head, dk).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        scores = (q @ k.transpose(0, 1, 3, 2)) * (dk ** -0.5)
        if causal:
            mask = jnp.tril(jnp.ones((q.shape[2], k.shape[2]), bool))
            scores = jnp.where(mask, scores, jnp.asarray(-1e9, scores.dtype))
        att = jax.nn.softmax(scores, axis=-1)
        att = drop(att, key)
        out = (att @ v).transpose(0, 2, 1, 3).reshape(x.shape[0], x.shape[1], d_model)
        return out @ ow

    rate = 0.1

    def drop(x, key):
        keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
        return jnp.where(keep, x / (1.0 - rate), 0).astype(x.dtype)

    def layer(p, x, enc_out, causal, key):
        ks = jax.random.split(key, 6)
        h = mha(ln(x, p["ln_a0_g"], p["ln_a0_b"]), None, p["qkv_0"], p["o_0"],
                causal, ks[0])
        x = x + drop(h, ks[1])
        if enc_out is not None:
            h = mha(ln(x, p["ln_a1_g"], p["ln_a1_b"]), enc_out, p["qkv_1"],
                    p["o_1"], False, ks[2])
            x = x + drop(h, ks[3])
        h = ln(x, p["ln_f_g"], p["ln_f_b"])
        h = jax.nn.relu(h @ p["fc1"])
        h = drop(h, ks[4])
        return x + drop(h @ p["fc2"], ks[5])

    eps = 0.1

    def loss_fn(params32, src, trg, lbl, key):
        p = jax.tree_util.tree_map(
            lambda t: t.astype(jnp.bfloat16) if t.dtype == jnp.float32 else t,
            params32)
        ks = jax.random.split(key, 2 * n_layer + 2)
        x = p["src_emb"][src] * d_model ** 0.5 + pos_table.astype(jnp.bfloat16)
        x = drop(x, ks[-1])
        for i in range(n_layer):
            x = layer(p["enc_%d" % i], x, None, False, ks[i])
        enc_out = ln(x, p["ln_enc_g"], p["ln_enc_b"])
        y = p["trg_emb"][trg] * d_model ** 0.5 + pos_table.astype(jnp.bfloat16)
        y = drop(y, ks[-2])
        for i in range(n_layer):
            y = layer(p["dec_%d" % i], y, enc_out, True, ks[n_layer + i])
        y = ln(y, p["ln_dec_g"], p["ln_dec_b"])
        logits = (y @ p["proj"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, lbl[..., None], axis=-1)[..., 0]
        smooth = -logp.sum(-1)
        per_tok = (1 - eps) * nll + (eps / vocab) * smooth
        return per_tok.mean()

    import optax

    opt = optax.adam(1e-4)
    opt_state = opt.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, src, trg, lbl, key):
        loss, grads = jax.value_and_grad(loss_fn)(params, src, trg, lbl, key)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.RandomState(0)
    src = jnp.asarray(rng.randint(2, vocab, (batch, seq)))
    trg = jnp.asarray(rng.randint(2, vocab, (batch, seq)))
    lbl = jnp.asarray(rng.randint(2, vocab, (batch, seq)))
    state = {"p": params, "o": opt_state, "k": k0}
    if _diag is not None:  # benchmarks/diag_overhead.py: expose the lowering
        _diag["lowered"] = train_step.lower(params, opt_state, src, trg, lbl, k0)

    def step():
        state["k"], sub = jax.random.split(state["k"])
        state["p"], state["o"], loss = train_step(state["p"], state["o"],
                                                  src, trg, lbl, sub)
        return loss

    if _profile_dir is not None:  # benchmarks/profile_xplane.py
        np.asarray(step())
        with jax.profiler.trace(_profile_dir):
            for _ in range(3):
                out = step()
            np.asarray(out)
    return _timeit(step, batch)


def _bert_train_flops_per_example(seq, n_mask, vocab=30522, n_layer=12,
                                  d_model=768, d_inner=3072):
    """Analytic fwd FLOPs ×3 (same convention as the Transformer's)."""
    s, d, di, L, V = seq, d_model, d_inner, n_layer, vocab
    enc = L * (8 * s * d * d + 4 * s * s * d + 4 * s * d * di)
    heads = n_mask * (2 * d * d + 2 * d * V)
    return 3 * (enc + heads)


def bench_bert(batch=32, seq=128, n_mask=20, use_amp=True, skip=5, iters=20,
               epochs=3, pipeline=False, fetch_every=8):
    """BERT-base pretraining step (MLM+NSP) — the 4th north-star config
    (BASELINE.json; ref inference/tests/api/analyzer_bert_tester.cc names the
    model, its train config lives in models/bert.py here). Exercises
    layer_norm/gelu/AMP at d_model=768."""
    import paddle_tpu as fluid
    from paddle_tpu.models import bert

    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main_prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main_prog, startup):
                ids = fluid.layers.data("ids", shape=[seq], dtype="int64")
                pos = fluid.layers.data("pos", shape=[seq], dtype="int64")
                sent = fluid.layers.data("sent", shape=[seq], dtype="int64")
                mask = fluid.layers.data("mask", shape=[seq], dtype="float32")
                mpos = fluid.layers.data("mpos", shape=[n_mask], dtype="int64")
                mlbl = fluid.layers.data("mlbl", shape=[1], dtype="int64")
                nsp = fluid.layers.data("nsp", shape=[1], dtype="int64")
                loss, _, _ = bert.bert_pretrain(
                    ids, pos, sent, mask, mpos, mlbl, nsp,
                    **bert.BERT_BASE_CONFIG)
                opt = fluid.optimizer.Adam(learning_rate=1e-4)
                if use_amp:
                    opt = fluid.amp.decorate(opt)
                opt.minimize(loss)

            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(startup)
            rng = np.random.RandomState(0)
            # mask positions are FLAT indices into [b*s] (models/transformer.py)
            mpos_np = (np.arange(batch)[:, None] * seq
                       + rng.randint(0, seq, (batch, n_mask))).astype("int64")
            feed = _device_feed({
                "ids": rng.randint(0, 30522, (batch, seq)).astype("int64"),
                "pos": np.tile(np.arange(seq), (batch, 1)).astype("int64"),
                "sent": np.zeros((batch, seq), "int64"),
                "mask": np.ones((batch, seq), "float32"),
                "mpos": mpos_np,
                "mlbl": rng.randint(0, 30522, (batch * n_mask, 1)).astype("int64"),
                "nsp": rng.randint(0, 2, (batch, 1)).astype("int64"),
            })

            if pipeline:
                return _timeit_pipeline(exe, main_prog, feed, [loss], batch,
                                        skip=skip, iters=iters, epochs=epochs,
                                        fetch_every=fetch_every)

            def step():
                lv, = exe.run(main_prog, feed=feed, fetch_list=[loss],
                              return_numpy=False)
                return lv

            return _timeit(step, batch, skip=skip, iters=iters)


def bench_raw_jax_bert(batch=32, seq=128, n_mask=20, vocab=30522, n_layer=12,
                       n_head=12, d_model=768, d_inner=3072, _diag=None):
    """Hand-written JAX BERT-base pretrain step, same shapes/precision
    (bf16 forward / f32 master, Adam, dropout 0.1) — the overhead yardstick."""
    import functools

    import jax
    import jax.numpy as jnp
    import optax

    dk = d_model // n_head
    keys = iter(jax.random.split(jax.random.PRNGKey(0), 400))

    def dense(din, dout):
        return {"w": jax.random.normal(next(keys), (din, dout)) * 0.02,
                "b": jnp.zeros((dout,))}

    params = {
        "word": jax.random.normal(next(keys), (vocab, d_model)) * 0.02,
        "pos_emb": jax.random.normal(next(keys), (512, d_model)) * 0.02,
        "sent": jax.random.normal(next(keys), (2, d_model)) * 0.02,
        "ln0_g": jnp.ones((d_model,)), "ln0_b": jnp.zeros((d_model,)),
        "mlm_t": dense(d_model, d_model),
        "ln_m_g": jnp.ones((d_model,)), "ln_m_b": jnp.zeros((d_model,)),
        "mlm_o": dense(d_model, vocab),
        "pool": dense(d_model, d_model),
        "nsp": dense(d_model, 2),
    }
    for i in range(n_layer):
        params["l%d" % i] = {
            "qkv": dense(d_model, 3 * d_model), "o": dense(d_model, d_model),
            "ln1_g": jnp.ones((d_model,)), "ln1_b": jnp.zeros((d_model,)),
            "fc1": dense(d_model, d_inner), "fc2": dense(d_inner, d_model),
            "ln2_g": jnp.ones((d_model,)), "ln2_b": jnp.zeros((d_model,)),
        }

    def ln(x, g, b):
        m = x.mean(-1, keepdims=True)
        v = ((x - m) ** 2).mean(-1, keepdims=True)
        return (x - m) / jnp.sqrt(v + 1e-5) * g + b

    rate = 0.1

    def drop(x, key):
        keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
        return jnp.where(keep, x / (1.0 - rate), 0).astype(x.dtype)

    def layer(p, x, key):
        ks = jax.random.split(key, 3)
        q, k, v = jnp.split(x @ p["qkv"]["w"] + p["qkv"]["b"].astype(x.dtype),
                            3, axis=-1)

        def heads(t):
            return t.reshape(t.shape[0], t.shape[1], n_head, dk).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        sc = (q @ k.transpose(0, 1, 3, 2)) * (dk ** -0.5)
        att = jax.nn.softmax(sc, axis=-1)
        att = drop(att, ks[0])
        o = (att @ v).transpose(0, 2, 1, 3).reshape(x.shape[0], x.shape[1], d_model)
        o = o @ p["o"]["w"] + p["o"]["b"].astype(x.dtype)
        x = ln(x + drop(o, ks[1]), p["ln1_g"], p["ln1_b"])
        h = jax.nn.gelu(x @ p["fc1"]["w"] + p["fc1"]["b"].astype(x.dtype))
        h = h @ p["fc2"]["w"] + p["fc2"]["b"].astype(x.dtype)
        return ln(x + drop(h, ks[2]), p["ln2_g"], p["ln2_b"])

    def loss_fn(p32, ids, pos, sent, mpos, mlbl, nsp_l, key):
        p = jax.tree_util.tree_map(
            lambda t: t.astype(jnp.bfloat16) if t.dtype == jnp.float32 else t,
            p32)
        ks = jax.random.split(key, n_layer + 1)
        x = p["word"][ids] + p["pos_emb"][pos] + p["sent"][sent]
        x = drop(ln(x, p["ln0_g"], p["ln0_b"]), ks[-1])
        for i in range(n_layer):
            x = layer(p["l%d" % i], x, ks[i])
        flat = x.reshape(-1, d_model)
        picked = flat[mpos.reshape(-1)]
        h = jax.nn.gelu(picked @ p["mlm_t"]["w"] + p["mlm_t"]["b"].astype(x.dtype))
        h = ln(h, p["ln_m_g"], p["ln_m_b"])
        logits = (h @ p["mlm_o"]["w"] + p["mlm_o"]["b"].astype(x.dtype)).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        mlm = -jnp.take_along_axis(logp, mlbl.reshape(-1)[:, None], axis=-1).mean()
        pooled = jnp.tanh(x[:, 0] @ p["pool"]["w"] + p["pool"]["b"].astype(x.dtype))
        nlog = (pooled @ p["nsp"]["w"] + p["nsp"]["b"].astype(x.dtype)).astype(jnp.float32)
        nsp = -jnp.take_along_axis(jax.nn.log_softmax(nlog),
                                   nsp_l.reshape(-1)[:, None], axis=-1).mean()
        return mlm + nsp

    opt = optax.adam(1e-4)
    opt_state = opt.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(p, o, ids, pos, sent, mpos, mlbl, nsp_l, key):
        loss, g = jax.value_and_grad(loss_fn)(p, ids, pos, sent, mpos, mlbl,
                                              nsp_l, key)
        up, o = opt.update(g, o)
        return optax.apply_updates(p, up), o, loss

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, vocab, (batch, seq)))
    pos = jnp.asarray(np.tile(np.arange(seq), (batch, 1)))
    sent = jnp.zeros((batch, seq), jnp.int32)
    mpos = jnp.asarray(np.arange(batch)[:, None] * seq
                       + rng.randint(0, seq, (batch, n_mask)))
    mlbl = jnp.asarray(rng.randint(0, vocab, (batch * n_mask,)))
    nsp_l = jnp.asarray(rng.randint(0, 2, (batch,)))
    state = {"p": params, "o": opt_state, "k": jax.random.PRNGKey(1)}
    if _diag is not None:
        _diag["lowered"] = train_step.lower(params, opt_state, ids, pos, sent,
                                            mpos, mlbl, nsp_l, state["k"])

    def step():
        state["k"], sub = jax.random.split(state["k"])
        state["p"], state["o"], loss = train_step(
            state["p"], state["o"], ids, pos, sent, mpos, mlbl, nsp_l, sub)
        return loss

    return _timeit(step, batch)


def bench_bert_infer(batch=64, seq=256, use_amp=True, skip=3, iters=15,
                     epochs=3):
    """BERT-base FORWARD (inference) — the compute-bound headline
    (benchmarks/TRANSFORMER_PROFILE.md): matmul-dense, no optimizer small
    kernels, bf16 on the MXU. Measured 0.44-0.49 MFU on v5e across tunnel
    epochs (r4, benchmarks/TRANSFORMER_PROFILE.md); the training configs
    sit at ~21% because per-parameter optimizer updates and VPU ops cap
    them, not because the framework's compute path is slow."""
    import paddle_tpu as fluid
    from paddle_tpu.models import bert

    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main_prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main_prog, startup):
                ids = fluid.layers.data("ids", shape=[seq], dtype="int64")
                pos = fluid.layers.data("pos", shape=[seq], dtype="int64")
                sent = fluid.layers.data("sent", shape=[seq], dtype="int64")
                mask = fluid.layers.data("mask", shape=[seq], dtype="float32")
                # inference steps are independent, so _timeit's end-of-loop
                # sync wouldn't transitively force them — chain each step on
                # the previous pooled output via an in-GRAPH zero coupling
                # (any eager per-step op would serialize on the tunnel)
                chain = fluid.layers.data("chain", shape=[768])
                zero = fluid.layers.cast(
                    fluid.layers.scale(fluid.layers.reduce_sum(chain), scale=0.0),
                    "int64")
                ids2 = fluid.layers.elementwise_add(ids, zero)
                seq_out, pooled = bert.bert_base(ids2, pos, sent, mask,
                                                 dropout_rate=0.0,
                                                 is_test=True)
                # fetch f32 so the chained feed needs no eager per-step
                # dtype canon under AMP (pooled itself is bf16 there)
                pooled_f32 = fluid.layers.cast(pooled, "float32")
            # the program is already built is_test/dropout-free — no
            # backward to prune, so run it directly
            if use_amp:
                fluid.amp.enable(main_prog, "bfloat16")
            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(startup)
            rng = np.random.RandomState(0)
            feed = _device_feed({
                "ids": rng.randint(0, 30522, (batch, seq)).astype("int64"),
                "pos": np.tile(np.arange(seq), (batch, 1)).astype("int64"),
                "sent": np.zeros((batch, seq), "int64"),
                "mask": np.ones((batch, seq), "float32"),
                "chain": np.zeros((batch, 768), "float32"),
            })
            carry = {"prev": feed["chain"]}

            def step():
                f = dict(feed)
                f["chain"] = carry["prev"]
                out, = exe.run(main_prog, feed=f, fetch_list=[pooled_f32],
                               return_numpy=False)
                carry["prev"] = out
                return out

            return _timeit(step, batch, skip=skip, iters=iters,
                           epochs=epochs)


def _bert_fwd_flops_per_example(seq, n_layer=12, d_model=768, d_inner=3072):
    s, d, di, L = seq, d_model, d_inner, n_layer
    return L * (8 * s * d * d + 4 * s * s * d + 4 * s * d * di)


def _lm_train_flops_per_example(seq, vocab=32000, n_layer=12, d_model=1024,
                                d_inner=4096):
    """Analytic fwd FLOPs x3 for the causal LM (same convention as the
    Transformer's; the 4*s*s*d attention term is what flash carries)."""
    s, d, di, L, V = seq, d_model, d_inner, n_layer, vocab
    return 3 * (L * (8 * s * d * d + 4 * s * s * d + 4 * s * d * di)
                + 2 * s * d * V)


def bench_longseq_train(batch=8, seq=2048, vocab=32000, skip=3, iters=10,
                        epochs=3):
    """Long-sequence causal-LM training — the compute-bound TRAINING
    headline (VERDICT r4 #3): d_model=1024 and S=2048 push arithmetic
    intensity past v5e's ~240 FLOP/byte balance point, and the v5e-tuned
    Pallas flash kernel carries the S^2 attention. Attention-probs dropout
    is 0 here (the modern long-context recipe); the r5 in-kernel dropout
    path supports it at ~7% step cost (22.5 vs 24.2 ex/s measured) where
    the composed path would need a 12.9 GB probs materialization. Measured
    r5: 0.37 MFU (vs 0.30 bar; benchmarks/TRANSFORMER_PROFILE.md §5)."""
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tfm

    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main_prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main_prog, startup):
                ids = fluid.layers.data("ids", shape=[seq], dtype="int64")
                lbl = fluid.layers.data("lbl", shape=[seq, 1], dtype="int64")
                logits, loss = tfm.causal_lm(ids, lbl, vocab_size=vocab,
                                             max_length=seq)
                opt = fluid.amp.decorate(fluid.optimizer.Adam(learning_rate=1e-4))
                opt.minimize(loss)
            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(startup)
            rng = np.random.RandomState(0)
            feed = _device_feed({
                "ids": rng.randint(0, vocab, (batch, seq)).astype("int64"),
                "lbl": rng.randint(0, vocab, (batch, seq, 1)).astype("int64"),
            })

            def step():
                lv, = exe.run(main_prog, feed=feed, fetch_list=[loss],
                              return_numpy=False)
                return lv

            return _timeit(step, batch, skip=skip, iters=iters, epochs=epochs)


def bench_deepfm(batch=1024, vocab=int(1e6), num_fields=26, emb_dim=10,
                 is_sparse=True, skip=5, iters=20, _diag=None,
                 shard_axes=None):
    """``is_sparse=True`` is the SelectedRows-equivalent rows-only path
    (V-independent step cost); ``False`` is the dense gather+scatter path
    (faster at small V/batch where the sparse machinery's fixed cost isn't
    yet amortized, but scales with V like the raw-JAX twin)."""
    """DeepFM CTR — the 5th north-star config (ref tests/unittests/
    dist_ctr.py, operators/reader/ctr_reader.cc). Exercises the
    sparse-embedding + SparseGrad path end-to-end at V=1e6: the embedding
    update must touch only looked-up rows, never the dense table."""
    import paddle_tpu as fluid
    from paddle_tpu.models import deepfm as dfm

    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main_prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main_prog, startup):
                ids = fluid.layers.data("ids", shape=[num_fields], dtype="int64")
                dense = fluid.layers.data("dense", shape=[13])
                label = fluid.layers.data("label", shape=[1], dtype="int64")
                _, loss, _ = dfm.deepfm(
                    ids, dense, label,
                    sparse_feature_dim=vocab,
                    embedding_size=emb_dim,
                    num_fields=num_fields,
                    is_sparse=is_sparse,
                    sharding_axis="model" if shard_axes else None)
                fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

            exe = fluid.Executor(fluid.TPUPlace(0))
            if shard_axes:
                # tables + Adam moments row-sharded over ``model``; the
                # startup init materializes them shard-by-shard (V=1e8
                # single-chip init RESOURCE_EXHAUSTs — BENCH_r05)
                from paddle_tpu import parallel

                mesh = parallel.create_mesh(dict(shard_axes))
                with parallel.mesh_guard(mesh):
                    exe.run(startup)
                main_prog = fluid.CompiledProgram(main_prog).with_mesh(
                    dict(shard_axes), loss_name=loss.name)
            else:
                exe.run(startup)
            rng = np.random.RandomState(0)
            feed = _device_feed({
                "ids": rng.randint(0, vocab, (batch, num_fields)).astype("int64"),
                "dense": rng.rand(batch, 13).astype("float32"),
                "label": rng.randint(0, 2, (batch, 1)).astype("int64"),
            })

            if _diag is not None:
                exe.run(main_prog, feed=feed, fetch_list=[loss],
                        return_numpy=False)
                compiled = next(c for c in exe._cache.values() if c.fetch_names)
                scope = fluid.global_scope()
                state = {n: scope.vars[n] for n in compiled.state_names
                         if n in scope.vars}
                comp = compiled.fn.lower(state, feed, np.uint32(0)).compile()
                _diag["cost"] = comp.cost_analysis()

            def step():
                lv, = exe.run(main_prog, feed=feed, fetch_list=[loss],
                              return_numpy=False)
                return lv

            return _timeit(step, batch, skip=skip, iters=iters)


def bench_deepfm_stream(batch=1024, vocab=int(1e6), num_fields=26,
                        emb_dim=10, steps=12, skip=4, fetch_every=4):
    """Streaming-ingest DeepFM leg (ROADMAP item 5's host side): the
    AsyncExecutor MultiSlot text format parsed shard-by-shard by
    ``data.CTRMultiSlotReader`` (exactly-once checkpointable position,
    corrupt-record quarantine), parse-ahead on its bounded prefetch queue,
    composed with ``DevicePrefetcher`` for the H2D overlap, driving the
    fused ``run_steps`` path. Returns a detail dict: sustained
    examples/s over the steady window plus the host-side parse rate."""
    import tempfile

    import paddle_tpu as fluid
    from paddle_tpu import data as pdata
    from paddle_tpu.models import deepfm as dfm
    from paddle_tpu.reader import DevicePrefetcher

    with tempfile.TemporaryDirectory() as td:
        t0 = time.perf_counter()
        paths = pdata.write_ctr_shards(
            td, (steps + skip) * batch, n_shards=4, num_fields=num_fields,
            dense_dim=13, vocab=vocab, seed=0)
        gen_s = time.perf_counter() - t0
        with fluid.unique_name.guard():
            with fluid.scope_guard(fluid.Scope()):
                main_prog, startup = fluid.Program(), fluid.Program()
                with fluid.program_guard(main_prog, startup):
                    ids = fluid.layers.data("ids", shape=[num_fields],
                                            dtype="int64")
                    dense = fluid.layers.data("dense", shape=[13])
                    label = fluid.layers.data("label", shape=[1],
                                              dtype="int64")
                    _, loss, _ = dfm.deepfm(
                        ids, dense, label, sparse_feature_dim=vocab,
                        embedding_size=emb_dim, num_fields=num_fields,
                        is_sparse=True)
                    fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
                exe = fluid.Executor(fluid.TPUPlace(0))
                exe.run(startup)
                reader = pdata.CTRMultiSlotReader(
                    paths, batch_size=batch, num_fields=num_fields,
                    dense_dim=13, vocab=vocab, epochs=1)
                with DevicePrefetcher(reader.prefetch(4),
                                      capacity=2) as feeds:
                    it = iter(feeds)
                    # warmup chunk: compile + fill the prefetch pipeline
                    exe.run_steps(main_prog, it, steps=skip,
                                  fetch_list=[loss], fetch_every=fetch_every)
                    t1 = time.perf_counter()
                    rows = exe.run_steps(main_prog, it, steps=steps,
                                         fetch_list=[loss],
                                         fetch_every=fetch_every)
                    np.asarray(rows[-1][0])  # sync
                    wall = time.perf_counter() - t1
        return {
            "examples_per_sec": round(steps * batch / wall, 2),
            "steps": steps, "batch": batch, "fetch_every": fetch_every,
            "records_parsed": reader.records_read,
            "shard_gen_s": round(gen_s, 3),
            "mode": "CTRMultiSlotReader -> prefetch -> DevicePrefetcher "
                    "-> run_steps (AsyncExecutor MultiSlot format)",
        }


def bench_raw_jax_deepfm(batch=1024, vocab=int(1e6), num_fields=26,
                         emb_dim=10, _diag=None):
    """Natural raw-JAX DeepFM: gather + autodiff (dense scatter-add grads,
    optax adam over the FULL table — what you get without a sparse-update
    framework). The paddle_tpu sparse path should beat this, and the gap IS
    the never-densify story."""
    import functools

    import jax
    import jax.numpy as jnp
    import optax

    keys = iter(jax.random.split(jax.random.PRNGKey(0), 16))
    params = {
        "emb": jax.random.normal(next(keys), (vocab, emb_dim)) * (emb_dim ** -0.5),
        "w1": jax.random.normal(next(keys), (vocab, 1)) * 1e-4,
    }
    sizes = (26 * emb_dim + 13, 400, 400, 400)
    for i in range(3):
        params["fc%d" % i] = {
            "w": jax.random.normal(next(keys), (sizes[i], sizes[i + 1]))
                 * (sizes[i + 1] ** -0.5),
            "b": jnp.zeros((sizes[i + 1],))}
    params["out"] = {"w": jax.random.normal(next(keys), (400, 1)) * 0.05,
                     "b": jnp.zeros((1,))}

    def loss_fn(p, ids, dense, label):
        e = p["emb"][ids]                       # [b, f, e]
        w1 = p["w1"][ids][..., 0]               # [b, f]
        first = w1.sum(-1, keepdims=True)
        se = e.sum(1)
        second = 0.5 * (se ** 2 - (e ** 2).sum(1)).sum(-1, keepdims=True)
        h = jnp.concatenate([e.reshape(ids.shape[0], -1), dense], axis=-1)
        for i in range(3):
            h = jax.nn.relu(h @ p["fc%d" % i]["w"] + p["fc%d" % i]["b"])
        logit = first + second + h @ p["out"]["w"] + p["out"]["b"]
        z = jnp.concatenate([jnp.zeros_like(logit), logit], axis=-1)
        logp = jax.nn.log_softmax(z)
        return -jnp.take_along_axis(logp, label, axis=-1).mean()

    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(p, o, ids, dense, label):
        loss, g = jax.value_and_grad(loss_fn)(p, ids, dense, label)
        up, o = opt.update(g, o)
        return optax.apply_updates(p, up), o, loss

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, vocab, (batch, num_fields)))
    dense = jnp.asarray(rng.rand(batch, 13).astype("float32"))
    label = jnp.asarray(rng.randint(0, 2, (batch, 1)))
    if _diag is not None:
        _diag["cost"] = train_step.lower(params, opt_state, ids, dense,
                                         label).compile().cost_analysis()
    state = {"p": params, "o": opt_state}

    def step():
        state["p"], state["o"], loss = train_step(state["p"], state["o"],
                                                  ids, dense, label)
        return loss

    return _timeit(step, batch)


def bench_long_context(b=1, h=8, s=8192, d=64):
    """The long-context story on hardware (VERDICT r2 weak #6): (a) the
    Pallas flash kernel vs XLA-composed attention at S=8192 bf16 causal
    fwd+bwd — the gate's claimed crossover — and (b) the ring-attention
    machinery at sp=1 vs plain attention (its overhead must be ~nil so the
    sp>1 memory scaling comes free). Chained-loop difference timing cancels
    the axon tunnel round-trip."""
    import time

    import jax
    import jax.numpy as jnp

    from paddle_tpu.flags import set_flag
    from paddle_tpu.ops.attention_ops import sdpa
    from paddle_tpu.parallel import ring_attention

    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (b, h, s, d), jnp.float32).astype(jnp.bfloat16)
    kk = jax.random.normal(k2, (b, h, s, d), jnp.float32).astype(jnp.bfloat16)
    v = jax.random.normal(k3, (b, h, s, d), jnp.float32).astype(jnp.bfloat16)

    def per_iter_ms(fn, lo=8, hi=64, reps=3):
        # wide spread: ~4ms/iter kernels need the hi-chain to run ~0.25s or
        # the axon tunnel's per-call jitter (~±10ms) swamps the difference
        def make(iters):
            @jax.jit
            def run(qq0):
                def body(c, _):
                    g = jax.grad(
                        lambda t: jnp.sum(fn(t, kk, v).astype(jnp.float32) ** 2))(c)
                    return c + 1e-6 * g.astype(c.dtype), g[0, 0, 0, 0]

                _, o = jax.lax.scan(body, qq0, None, length=iters)
                return o

            return run

        def tmin(f):
            np.asarray(f(q))
            ts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                np.asarray(f(q))
                ts.append(time.perf_counter() - t0)
            return min(ts)

        return (tmin(make(hi)) - tmin(make(lo))) / (hi - lo) * 1e3

    out = {"shape": "b%d h%d s%d d%d bf16 causal" % (b, h, s, d),
           "note": "gate is a PERF crossover at S=2048: v5e-tuned BlockSizes "
                   "(512x512, r4 sweep) make flash beat composed above it; "
                   "flash is also O(S) memory where composed OOMs ~24k "
                   "(FLAGS_flash_attention_min_seq)"}
    from paddle_tpu.flags import get_flag

    old_gate = get_flag("flash_attention_min_seq")
    set_flag("flash_attention_min_seq", 1)       # force the Pallas kernel
    out["flash_ms"] = round(per_iter_ms(
        lambda t, k_, v_: sdpa(t, k_, v_, causal=True, sm_scale=d ** -0.5)), 2)
    set_flag("flash_attention_min_seq", 10 ** 9)  # force the composed path
    out["composed_ms"] = round(per_iter_ms(
        lambda t, k_, v_: sdpa(t, k_, v_, causal=True, sm_scale=d ** -0.5)), 2)
    set_flag("flash_attention_min_seq", old_gate)  # restore the tuned gate
    out["flash_speedup"] = round(out["composed_ms"] / out["flash_ms"], 3)

    # ring attention, sp=1 (single chip): the ring machinery's overhead vs
    # the plain composed softmax at the same (non-causal) shape
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("sp",))
    with mesh:
        out["ring_sp1_ms"] = round(per_iter_ms(
            lambda t, k_, v_: ring_attention(t, k_, v_, mesh=mesh,
                                             axis_name="sp")), 2)
    out["plain_ms"] = round(per_iter_ms(
        lambda t, k_, v_: sdpa(t, k_, v_, causal=False, sm_scale=1.0)), 2)
    return out


def bench_scaling(axes_str="data=8"):
    """1→N chip scaling harness — the BASELINE.json north-star metric
    ("train step/sec + scaling eff 1→8 chips") as one command:

        python bench.py --mesh data=8

    Runs the SAME per-chip workload on a 1-device and an N-device ``data``
    mesh through CompiledProgram.with_mesh (the GSPMD path: feeds shard over
    the data axis, XLA inserts the gradient all-reduce over ICI) and reports
    per-chip examples/sec + scaling efficiency = eps_N / (N * eps_1).

    On CPU — the only multi-device option in this environment — it validates
    the identical code path with tiny shapes and labels results
    ``cpu-dryrun``; numbers there measure host contention, not ICI, and are
    NOT performance evidence. On a real v5e-8 the same command is the
    production measurement.
    """
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the axon sitecustomize pre-imports jax with the TPU plugin; drop
        # any initialized backend so the CPU dryrun settings take effect
        # (same dance as tests/conftest.py), and make sure the virtual
        # device count is set BEFORE the backend re-initializes
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        jax.config.update("jax_platforms", "cpu")
        from jax._src import xla_bridge as _xb

        if _xb.backends_are_initialized():
            _xb._clear_backends()

    axes = {}
    for part in axes_str.split(","):
        k, v = part.split("=")
        axes[k.strip()] = int(v)
    if (not axes or set(axes) - {"data", "model"}
            or any(v < 1 for v in axes.values())):
        # pp/sp/ep live in dryrun_multichip, not here
        return {"error": "only --mesh data=N[,model=M] is supported, got %r"
                % axes_str}
    dp = axes.get("data", 1)
    tp = axes.get("model", 1)
    n = dp * tp
    avail = len(jax.devices())
    if avail < n:
        return {"error": "mesh %s needs %d devices, have %d" % (axes, n, avail)}
    dryrun = jax.default_backend() == "cpu"
    if dryrun:
        tfm_kw = dict(seq=64, vocab=1000, skip=2, iters=5, epochs=1)
        rn_kw = dict(image=64, classes=100, skip=2, iters=5, epochs=1)
        tb, rb = 4, 4          # per-chip batches
    else:
        tfm_kw = dict(seq=256, vocab=30000)
        rn_kw = dict(image=224, classes=1000)
        tb, rb = 64, 64

    out = {"mode": "cpu-dryrun" if dryrun else "tpu", "mesh": axes,
           "n_devices": n}
    # expected-on-real-hardware efficiencies from the ICI arithmetic
    # (benchmarks/COLLECTIVES.md §1 dp, §6 tp) — recorded next to each
    # measurement so real-v5e-8 numbers have a target to land against
    if tp == 1:
        out["expected_efficiency_real_hw"] = {
            "transformer": ">=0.95 (COLLECTIVES.md §1: <0.5% grad "
                           "all-reduce fraction)",
            "resnet50": ">=0.93 (COLLECTIVES.md §1: ~1%)"}
    else:
        out["expected_efficiency_real_hw"] = {
            "transformer": ">=0.90 (COLLECTIVES.md §6: vocab-sharded "
                           "softmax all-reduce + dp grad all-reduce)"}
    benches = [("transformer", bench_transformer, tb, tfm_kw)]
    if tp == 1:
        # the TP annotations are transformer-specific; resnet runs dp-only
        benches.append(("resnet50", bench_resnet50, rb, rn_kw))
    for name, fn, b, kw in benches:
        if name == "transformer" and tp > 1:
            kw = dict(kw, model_devices=tp)
        eps1, _ = fn(batch=b, n_devices=1, **{k: v for k, v in kw.items()
                                              if k != "model_devices"})
        epsn, _ = fn(batch=b * dp, n_devices=dp, **kw)
        out[name] = {
            "per_chip_batch": b,
            "examples_per_sec_1dev": round(eps1, 2),
            "examples_per_sec_%ddev" % n: round(epsn, 2),
            "per_chip_examples_per_sec": round(epsn / n, 2),
            "scaling_efficiency": round(epsn / (n * eps1), 4),
        }
    return out


def _run_ledger_section(kind, configs, extra=None):
    """Append one provenance-stamped record to the run ledger (armed via
    PADDLE_TPU_RUN_LEDGER — see monitor.runlog) and return the tail keys
    (run_id, ledger path) every summary carries so ledger, telemetry ring
    and trace artifacts cross-link on one id. Must never sink the bench."""
    try:
        from paddle_tpu.monitor import runlog

        runlog.record_run(kind, configs, extra=extra)
        return runlog.tail_info()
    except Exception as e:
        return {"run_id": None, "run_ledger_error": repr(e)[:80]}


def main():
    # --pipeline: drive the transformer/ResNet/BERT benches with the fused
    # async run_steps driver (fetch_every=8) instead of run()-per-step; the
    # JSON detail gains a "pipeline" block (host dispatch gap vs synced step
    # time) and the metrics section the executor/run_steps_* instruments.
    pipeline = "--pipeline" in sys.argv
    if pipeline:
        sys.argv.remove("--pipeline")
    if len(sys.argv) > 1 and sys.argv[1] == "--quick":
        # ~1s CPU probe through tools/perf_gate's tiny MLP train loop:
        # the cheap way to grow the run ledger a baseline point per
        # commit; same summary-tail shape as the full bench.
        from tools import perf_gate as _pg

        configs, breakdowns = _pg.run_probe()
        summary = dict(configs)
        summary["autotune"] = _autotune_summary()
        summary.update(_run_ledger_section("bench", configs,
                                           extra={"stepstats": breakdowns}))
        print(json.dumps({"summary": summary}))
        return 0
    if len(sys.argv) > 1 and sys.argv[1] == "--serve":
        # serving-stack leg (paddle_tpu.serving): ragged continuous batching
        # + paged KV-cache vs the padded static-batch baseline on one
        # synthetic mixed-length stream. CPU-sim OK; the compact summary
        # (p50/p99 latency + sustained QPS) rides the truncation-proof tail.
        from tools import serve_bench as _sb

        res = _sb.serve_bench()
        cont = res["continuous_paged"]
        print(json.dumps({
            "metric": "serving_sustained_qps_mixed_stream",
            "value": cont["qps"],
            "unit": "requests/sec",
            "vs_baseline": res["qps_ratio_vs_padded"],
            "detail": res,
            "metrics": _monitor_metrics_section(),
        }))
        serve_summary = {
            "qps": cont["qps"],
            "latency_p50_ms": cont["latency_p50_ms"],
            "latency_p99_ms": cont["latency_p99_ms"],
            "tokens_per_sec": cont["tokens_per_sec"],
            "qps_ratio_vs_padded": res["qps_ratio_vs_padded"],
            "decode_fuse": "%s(%s)" % (res["config"]["decode_fuse"],
                                       res["config"]["decode_fuse_source"]),
            # which decode-attention inner loop the headline leg ran +
            # the tune-table layer that supplied its block config
            "decode_kernel": "%s(%s)" % (cont["decode_kernel"],
                                         cont["decode_kernel_source"]),
        }
        # the paged-kernel A/B leg (present when the kernel compiled, i.e.
        # --kernel paged or auto-on-TPU): kernel:gather ratios + the
        # kernel leg's own provenance ride the tail
        kleg = res.get("continuous_paged_kernel")
        if isinstance(kleg, dict) and "error" not in kleg:
            serve_summary["kernel_qps_ratio"] = (
                res["kernel_vs_gather"]["qps_ratio"])
            serve_summary["kernel_tokens_per_sec_ratio"] = (
                res["kernel_vs_gather"]["tokens_per_sec_ratio"])
            serve_summary["kernel_leg"] = "%s(%s)" % (
                kleg["decode_kernel"], kleg["decode_kernel_source"])
        # observability artifacts (armed via PADDLE_TPU_TRACE_FILE /
        # PADDLE_TPU_TELEMETRY_DIR) surface in the truncation-proof tail
        for key in ("trace_file", "telemetry_dir"):
            if key in res:
                serve_summary[key] = res[key]
        tail = {"serve": serve_summary, "autotune": _autotune_summary()}
        tail.update(_run_ledger_section(
            "serve_bench", {"serve_mixed_stream": {
                k: v for k, v in serve_summary.items()
                if isinstance(v, (int, float))}}))
        print(json.dumps({"summary": tail}))
        return 0

    if len(sys.argv) > 1 and sys.argv[1] == "--mesh":
        if len(sys.argv) < 3:
            print(json.dumps({"error": "usage: bench.py --mesh data=8"}))
            sys.exit(2)
        res = bench_scaling(sys.argv[2])
        if "error" in res:
            print(json.dumps(res))
            sys.exit(1)
        eff = res.get("transformer", {}).get("scaling_efficiency")
        from paddle_tpu.monitor import device as _dev

        print(json.dumps({
            "metric": "scaling_efficiency_1_to_%d" % res.get("n_devices", 0),
            "value": eff, "unit": "ratio", "vs_baseline": eff,
            "detail": res,
            # per-device bytes the explicit collective sites move per step
            # (trace-time accounting; GSPMD-inserted collectives excluded)
            "collectives": _dev.collectives_snapshot(),
            "metrics": _monitor_metrics_section()}))
        return

    peak, kind = _device_peak_flops()
    detail = {"device": kind, "pipeline_mode": pipeline}

    batch, seq, vocab = 64, 256, 30000
    # the axon compile tunnel occasionally drops a connection mid-compile;
    # one retry keeps that transient flake from sinking the whole headline
    # metric — but ONLY for connection-type failures, so a real numeric or
    # compile regression still fails loudly instead of being healed
    try:
        tfm_eps, tfm_sps = bench_transformer(batch, seq, vocab, use_amp=True,
                                             pipeline=pipeline)
    except Exception as first_err:
        msg = repr(first_err)
        if not any(s in msg for s in ("response body closed", "remote_compile",
                                      "Connection", "DEADLINE")):
            raise
        sys.stderr.write("transformer bench hit a tunnel flake (%r); "
                         "retrying once\n" % (first_err,))
        time.sleep(20)
        tfm_eps, tfm_sps = bench_transformer(batch, seq, vocab, use_amp=True,
                                             pipeline=pipeline)
    detail["transformer_bf16"] = {
        "examples_per_sec": round(tfm_eps, 2), "steps_per_sec": round(tfm_sps, 3),
        **_last_spread(), **_graph_opt_section()}
    if peak:
        fl = _transformer_train_flops_per_example(seq, vocab)
        detail["transformer_bf16"]["mfu_est"] = round(tfm_eps * fl / peak, 4)

    try:
        raw_eps, raw_sps = bench_raw_jax_transformer(batch, seq, vocab)
        detail["raw_jax_transformer_bf16"] = {
            "examples_per_sec": round(raw_eps, 2), "steps_per_sec": round(raw_sps, 3)}
        detail["overhead_vs_raw_jax"] = round(raw_eps / tfm_eps, 4)
    except Exception as e:  # the yardstick must never sink the bench
        detail["raw_jax_transformer_bf16"] = {"error": repr(e)[:200]}

    try:
        rn_eps, rn_sps = bench_resnet50(pipeline=pipeline)
        detail["resnet50_bf16"] = {
            "examples_per_sec": round(rn_eps, 2), "steps_per_sec": round(rn_sps, 3),
            **_last_spread()}
        if peak:
            detail["resnet50_bf16"]["mfu_est"] = round(
                rn_eps * _RESNET50_TRAIN_FLOPS_PER_IMAGE / peak, 4)
        try:
            rr_eps, _ = bench_raw_jax_resnet50()
            detail["raw_jax_resnet50_bf16"] = {"examples_per_sec": round(rr_eps, 2)}
            detail["resnet50_bf16"]["overhead_vs_raw_jax"] = round(rr_eps / rn_eps, 4)
        except Exception as e:
            detail["raw_jax_resnet50_bf16"] = {"error": repr(e)[:200]}
    except Exception as e:
        detail["resnet50_bf16"] = {"error": repr(e)[:200]}

    try:
        bb, bs, bm = 32, 128, 20
        bert_eps, bert_sps = bench_bert(bb, bs, bm, pipeline=pipeline)
        detail["bert_base_bf16"] = {
            "examples_per_sec": round(bert_eps, 2),
            "steps_per_sec": round(bert_sps, 3), "batch": bb, "seq": bs,
            **_last_spread()}
        if peak:
            detail["bert_base_bf16"]["mfu_est"] = round(
                bert_eps * _bert_train_flops_per_example(bs, bm) / peak, 4)
        try:
            br_eps, _ = bench_raw_jax_bert(bb, bs, bm)
            detail["raw_jax_bert_base_bf16"] = {
                "examples_per_sec": round(br_eps, 2)}
            detail["bert_base_bf16"]["overhead_vs_raw_jax"] = round(
                br_eps / bert_eps, 4)
        except Exception as e:
            detail["raw_jax_bert_base_bf16"] = {"error": repr(e)[:200]}
    except Exception as e:
        detail["bert_base_bf16"] = {"error": repr(e)[:200]}

    try:
        bi_b, bi_s = 64, 256
        # 5 epochs for the compute-bound headline: report the median, not a
        # cherry-pickable band (VERDICT r4 weak #7)
        bi_eps, bi_sps = bench_bert_infer(bi_b, bi_s, epochs=5)
        detail["bert_base_infer_bf16"] = {
            "examples_per_sec": round(bi_eps, 2),
            "steps_per_sec": round(bi_sps, 3), "batch": bi_b, "seq": bi_s,
            **_last_spread()}
        if peak:
            detail["bert_base_infer_bf16"]["mfu_est"] = round(
                bi_eps * _bert_fwd_flops_per_example(bi_s) / peak, 4)
    except Exception as e:
        detail["bert_base_infer_bf16"] = {"error": repr(e)[:200]}

    try:
        detail["long_context_s8192"] = bench_long_context()
    except Exception as e:
        detail["long_context_s8192"] = {"error": repr(e)[:200]}

    try:
        ls_b, ls_s = 8, 2048
        ls_eps, ls_sps = bench_longseq_train(ls_b, ls_s)
        detail["longseq_lm_train_bf16"] = {
            "examples_per_sec": round(ls_eps, 2),
            "steps_per_sec": round(ls_sps, 3), "batch": ls_b, "seq": ls_s,
            **_last_spread()}
        if peak:
            detail["longseq_lm_train_bf16"]["mfu_est"] = round(
                ls_eps * _lm_train_flops_per_example(ls_s) / peak, 4)
    except Exception as e:
        detail["longseq_lm_train_bf16"] = {"error": repr(e)[:200]}

    try:
        dv = int(1e6)
        df_eps, df_sps = bench_deepfm(vocab=dv)
        detail["deepfm_ctr"] = {
            "examples_per_sec": round(df_eps, 2),
            "steps_per_sec": round(df_sps, 3), "vocab": dv, "batch": 1024,
            "mode": "is_sparse (SelectedRows rows-only grads)"}
        try:
            # the never-densify evidence: step FLOPs must not scale with V
            d6, d7 = {}, {}
            bench_deepfm(vocab=dv, skip=1, iters=2, _diag=d6)
            bench_deepfm(vocab=10 * dv, skip=1, iters=2, _diag=d7)
            f6 = d6["cost"].get("flops", 0)
            f7 = d7["cost"].get("flops", 0)
            detail["deepfm_ctr"]["embedding_update"] = {
                "step_flops_V1e6": f6, "step_flops_V1e7": f7,
                "flops_ratio_10x_vocab": round(f7 / max(f6, 1), 4),
                "note": "ratio ~1.0 = grads/optimizer never densify over V",
            }
        except Exception as e:
            detail["deepfm_ctr"]["embedding_update"] = {"error": repr(e)[:200]}
        try:
            # host-side streaming ingestion (AsyncExecutor MultiSlot parity
            # through the checkpointable reader): sustained eps should sit
            # near the in-memory feed number — the gap IS the parse cost
            # the prefetch pipeline must hide
            st = bench_deepfm_stream(vocab=dv)
            st["ingest_overhead_vs_in_memory"] = round(
                df_eps / max(st["examples_per_sec"], 1e-9), 4)
            detail["deepfm_ctr"]["stream_ingest"] = st
        except Exception as e:
            detail["deepfm_ctr"]["stream_ingest"] = {"error": repr(e)[:200]}
        try:
            dd_eps, _ = bench_deepfm(vocab=dv, is_sparse=False)
            detail["deepfm_ctr_dense"] = {
                "examples_per_sec": round(dd_eps, 2),
                "note": "dense gather/scatter mode — the apples-to-apples "
                        "twin of the raw-JAX dense yardstick; sparse mode "
                        "trades fixed per-step cost for V-independence"}
        except Exception as e:
            detail["deepfm_ctr_dense"] = {"error": repr(e)[:200]}
        try:
            dr_eps, _ = bench_raw_jax_deepfm(vocab=dv)
            detail["raw_jax_deepfm_dense"] = {
                "examples_per_sec": round(dr_eps, 2),
                "note": "natural raw JAX: dense scatter grads + full-table "
                        "adam — scales with V where the sparse path doesn't"}
            # named for what it measures (VERDICT demand 8): the raw-JAX twin
            # is DENSE (full-table scatter+adam), so against the sparse
            # framework path this is a cross-mode ratio, not framework
            # overhead — deepfm_ctr_dense.overhead_vs_raw_jax is the
            # apples-to-apples framework-overhead number
            detail["deepfm_ctr"]["overhead_vs_dense_raw_jax"] = round(
                dr_eps / df_eps, 4)
            if "examples_per_sec" in detail.get("deepfm_ctr_dense", {}):
                detail["deepfm_ctr_dense"]["overhead_vs_raw_jax"] = round(
                    dr_eps / detail["deepfm_ctr_dense"]["examples_per_sec"], 4)
        except Exception as e:
            detail["raw_jax_deepfm_dense"] = {"error": repr(e)[:200]}
        try:
            # wall-clock sparse-vs-dense crossover over V (VERDICT r4 #2):
            # dense pays full-table Adam traffic that grows with V (and
            # eventually cannot fit); the rows-only sparse path holds flat.
            # measured r5 (this chip, one process): V=1e6 dense 1.50x
            # faster; V=1e7 1.09x; V=5e7 sparse WINS 1.54x (dense pays
            # full-table Adam traffic); V=1e8 exceeds single-chip HBM for
            # p+m+v in either mode (the sharded-embedding multi-chip path
            # is the capacity story there). benchmarks/SPARSE_PROFILE.md.
            sweep = {}
            from paddle_tpu.ops.optimizer_ops import _sparse_kernel_mode

            # which sparse-update implementation this sweep measured: the
            # row-DMA Pallas kernel (pallas_kernels/sparse_adam.py, auto on
            # TPU via FLAGS_sparse_update_kernel) or the XLA scatter path
            sweep["update_impl"] = _sparse_kernel_mode() or "xla_scatter"
            for vv in (int(1e6), int(1e7), int(5e7), int(1e8)):
                ent = {}
                import gc

                for is_sp, lbl in ((True, "sparse"), (False, "dense")):
                    # drop the previous run's tables BEFORE each compile —
                    # one V=5e7 mode holds ~12 GB of p/m/v state
                    gc.collect()
                    try:
                        e_, _ = bench_deepfm(vocab=vv, is_sparse=is_sp,
                                             skip=3, iters=10)
                        ent[lbl + "_eps"] = round(e_, 2)
                    except Exception as ex:
                        ent[lbl + "_eps"] = None
                        ent[lbl + "_error"] = repr(ex)[:120]
                if ent.get("sparse_eps") and ent.get("dense_eps"):
                    ent["sparse_over_dense"] = round(
                        ent["dense_eps"] / ent["sparse_eps"], 4)
                sweep["V=%.0e" % vv] = ent
            import gc

            gc.collect()
            import jax as _jax

            if len(_jax.devices()) >= 2:
                # the capacity leg: V=1e8 runs ONLY with the table (and its
                # Adam moments) row-sharded over the mesh — 13.2 GB of CTR
                # state at ~1.65 GB/chip on 8 devices
                nd = len(_jax.devices())
                try:
                    e_, _ = bench_deepfm(
                        vocab=int(1e8), is_sparse=True, skip=2, iters=5,
                        shard_axes={"data": 1, "model": nd})
                    sweep["V=1e+08_sharded_model=%d" % nd] = {
                        "sparse_eps": round(e_, 2)}
                except Exception as ex:
                    sweep["V=1e+08_sharded_model=%d" % nd] = {
                        "error": repr(ex)[:120]}
            detail["deepfm_v_sweep"] = sweep
        except Exception as e:
            detail["deepfm_v_sweep"] = {"error": repr(e)[:200]}
    except Exception as e:
        detail["deepfm_ctr"] = {"error": repr(e)[:200]}

    try:
        device_profile = _device_profile_section()
    except Exception as e:
        device_profile = {"error": repr(e)[:200]}

    vs = (tfm_eps / ROUND1_BASELINE_EXAMPLES_PER_SEC
          if ROUND1_BASELINE_EXAMPLES_PER_SEC else 1.0)
    print(json.dumps({
        "metric": "transformer_base_train_examples_per_sec_b%d_s%d_bf16" % (batch, seq),
        "value": round(tfm_eps, 2),
        "unit": "examples/sec",
        "vs_baseline": round(vs, 3),
        "detail": detail,
        "device_profile": device_profile,
        "metrics": _monitor_metrics_section(),
    }))
    # the compact per-config digest is the LAST line on purpose: a log tail
    # (drivers keep ~2,000 chars) always carries the headline numbers even
    # when the full detail JSON above is truncated (VERDICT "do this" #5)
    summary = _compact_summary(detail)
    summary["autotune"] = _autotune_summary()
    # run-ledger record + run_id cross-link key, last so a truncated log
    # still says which ledger record this tail corresponds to
    summary.update(_run_ledger_section(
        "bench", {cfg: row for cfg, row in summary.items()
                  if isinstance(row, dict) and "error" not in row
                  and cfg != "autotune"}))
    print(json.dumps({"summary": summary}))
    return 0


def _autotune_summary():
    """Per-kernel config provenance (tuned/shipped/default) + the active
    table path — rides the truncation-proof tail so every bench JSON says
    which configs its hot kernels actually ran with. Kernels the bench
    exercised report their REAL lookup; the canonical probes below fill in
    any kernel no leg reached (so the tail is always complete)."""
    try:
        from paddle_tpu import tune

        probes = (
            ("flash_attention", tune.bucket_seq(8192, 8192)),
            ("sparse_adam", tune.bucket_rows(1024, 64)),
            ("softmax_xent", tune.bucket_nv(4096, 32768)),
            ("serving.decode_fuse", tune.bucket_slots(8)),
        )
        prov = tune.provenance_snapshot()
        for kern, bucket in probes:
            if kern not in prov:
                tune.lookup(kern, bucket)
        out = {"table": tune.table_path()}
        for kern, p in sorted(tune.provenance_snapshot().items()):
            cfg = p.get("config")
            out[kern] = (p["source"] if not cfg else "%s:%s" % (
                p["source"], json.dumps(cfg, sort_keys=True,
                                        separators=(",", ":"))))
        return out
    except Exception as e:  # the tail must always print
        return {"error": repr(e)[:80]}


def _compact_summary(detail):
    """{config: {eps_median, mfu, overhead}} — one short row per benched
    config, plus the deepfm sweep's sparse_over_dense ratios."""
    out = {}
    for name, ent in detail.items():
        if not isinstance(ent, dict):
            continue
        if "examples_per_sec" not in ent:
            if "error" in ent:
                out[name] = {"error": str(ent["error"])[:60]}
            continue
        row = {"eps_median": ent["examples_per_sec"]}
        if "mfu_est" in ent:
            row["mfu"] = ent["mfu_est"]
        if "overhead_vs_raw_jax" in ent:
            row["overhead"] = ent["overhead_vs_raw_jax"]
        elif "overhead_vs_dense_raw_jax" in ent:
            # deepfm_ctr's cross-mode ratio keeps its honest name in the
            # tail too (sparse framework vs dense raw ≠ framework overhead)
            row["overhead_vs_dense"] = ent["overhead_vs_dense_raw_jax"]
        out[name] = row
    sweep = detail.get("deepfm_v_sweep")
    if isinstance(sweep, dict) and "error" not in sweep:
        row = {}
        for k, ent in sweep.items():
            if isinstance(ent, dict) and ent.get("sparse_over_dense"):
                row[k] = ent["sparse_over_dense"]
            elif isinstance(ent, dict) and "sharded" in k:
                row[k] = ent.get("sparse_eps") or str(
                    ent.get("error", ""))[:40]
        if "update_impl" in sweep:
            row["update_impl"] = sweep["update_impl"]
        out["deepfm_sparse_over_dense"] = row
    return out


def _graph_opt_section():
    """Trace-time optimizer evidence for the bench just run: global-block
    op count entering/leaving the default pipeline (the gauges hold the
    most recent pipeline application — i.e. this bench's program) and the
    cumulative fused-pattern match counters. Trace/compile-time deltas vs
    PADDLE_TPU_OPT_LEVEL=0 are measured by ``benchmarks/diag_overhead.py
    --opt``; here the absolute trace+compile histograms land in the
    ``metrics`` section."""
    from paddle_tpu import monitor

    snap = monitor.snapshot()

    def val(name):
        s = snap.get(name)
        return int(s["value"]) if s and s.get("value") is not None else 0

    before = val("passes/pipeline/op_count_before")
    if not before:
        return {}
    from paddle_tpu.passes import opt_level

    return {"graph_opt": {
        "opt_level": opt_level(),
        "op_count_before": before,
        "op_count_after": val("passes/pipeline/op_count_after"),
        "flash_attention_rewrites": val(
            "passes/flash_attention_rewrite/rewrites_matched"),
        "softmax_xent_rewrites": val(
            "passes/softmax_xent_fuse_pass/rewrites_matched"),
    }}


def _device_profile_section(batch=64):
    """The ``device_profile`` section: per-op flops/bytes attribution +
    measured XLA cost/memory analysis for the canonical MLP train config
    (tools/profile_report's demo shape at bench batch). AOT-compiled via
    ``Executor.prepare`` — one extra small compile, no step execution —
    so every bench JSON carries a roofline table whose ``slot`` ids match
    the ``<slot>:<type>`` named scopes in any xprof trace taken alongside.
    Render it with ``python -m tools.profile_report <bench.json>``."""
    import paddle_tpu as fluid
    from paddle_tpu.monitor import device as _dev

    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("x", shape=[32])
                y = fluid.layers.data("y", shape=[1], dtype="int64")
                h = fluid.layers.fc(x, size=64, act="relu")
                logits = fluid.layers.fc(h, size=10)
                loss = fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(logits, y))
                fluid.optimizer.SGD(0.1).minimize(loss)
            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(startup)
            compiled = exe.prepare(
                main, feed={"x": ((batch, 32), "float32"),
                            "y": ((batch, 1), "int64")},
                fetch_list=[loss])
    rep = _dev.step_report(compiled.program,
                           getattr(compiled, "_aot", None),
                           batch_size=batch, top=12)
    rep["config"] = "mlp_train_b%d" % batch
    return rep


def _monitor_metrics_section():
    """In-framework counters backing the throughput numbers (cache
    hit/miss, step-time histograms, feed/fetch bytes, HBM gauges) — the
    monitor.snapshot() of the whole bench process, zero-valued instruments
    dropped for signal."""
    from paddle_tpu import monitor

    out = {}
    for name, snap in monitor.snapshot().items():
        if snap["type"] == "histogram" and snap["count"] == 0:
            continue
        if snap["type"] == "counter" and not snap.get("value"):
            continue
        # gauges keep explicitly-written zeros (a queue depth pinned at 0 IS
        # the input-bound signal); only never-written gauges are noise
        if snap["type"] == "gauge" and not snap.get("set"):
            continue
        out[name] = snap
    return out


if __name__ == "__main__":
    sys.exit(main())
