"""Build + serialize the train program the C++ demo consumes (reference:
paddle/fluid/train/demo/README.md step 1 — a python script saves the
ProgramDesc that demo_trainer.cc loads)."""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.core import serialization  # noqa: E402

DIM, CLASSES = 16, 4


def main(out_dir):
    main_p, startup_p = fluid.Program(), fluid.Program()
    main_p.random_seed = startup_p.random_seed = 7
    with fluid.program_guard(main_p, startup_p):
        x = fluid.layers.data("x", shape=[DIM])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=32, act="relu")
        logits = fluid.layers.fc(h, size=CLASSES)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(1e-2).minimize(loss)
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "startup.json"), "w") as f:
        f.write(serialization.dumps(startup_p))
    with open(os.path.join(out_dir, "main.json"), "w") as f:
        f.write(serialization.dumps(main_p))
    with open(os.path.join(out_dir, "meta.txt"), "w") as f:
        f.write("%s\n%s\n%d %d\n" % (REPO, loss.name, DIM, CLASSES))
    print("saved to", out_dir)


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "demo_program")
