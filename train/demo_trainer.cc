// C++ training demo — Python-free user code driving paddle_tpu
// (reference: paddle/fluid/train/demo/demo_trainer.cc, which links
// libpaddle_fluid and drives Executor::Run from C++).
//
// The TPU build's runtime IS the embedded CPython+JAX/XLA stack, so this
// demo links libpython the way the reference links libpaddle_fluid: all
// orchestration — program loading, the train loop, synthetic data
// generation, feed construction, loss extraction, the convergence check —
// is C++; no Python source is executed beyond the framework itself.
//
// Build & run (see train/README.md):
//   g++ -O2 demo_trainer.cc $(python3-config --includes) \
//       $(python3-config --ldflags --embed) -o demo_trainer
//   ./demo_trainer <dir with startup.json/main.json/meta.txt>

#include <Python.h>

#include <cstdio>
#include <cstdlib>
#include <fstream>
#include <random>
#include <sstream>
#include <string>
#include <vector>

namespace {

std::string ReadFile(const std::string& path) {
  std::ifstream f(path);
  if (!f) {
    std::fprintf(stderr, "cannot open %s\n", path.c_str());
    std::exit(2);
  }
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

PyObject* Check(PyObject* obj, const char* what) {
  if (obj == nullptr) {
    std::fprintf(stderr, "python error at: %s\n", what);
    PyErr_Print();
    std::exit(3);
  }
  return obj;
}

// Wrap a C++ buffer as a numpy array [rows, cols] of `dtype`.
PyObject* MakeArray(PyObject* np, void* data, Py_ssize_t bytes,
                    const char* dtype, int rows, int cols) {
  PyObject* mv = Check(
      PyMemoryView_FromMemory(static_cast<char*>(data), bytes, PyBUF_READ),
      "memoryview");
  PyObject* flat =
      Check(PyObject_CallMethod(np, "frombuffer", "Os", mv, dtype), "frombuffer");
  PyObject* arr =
      Check(PyObject_CallMethod(flat, "reshape", "(ii)", rows, cols), "reshape");
  Py_DECREF(mv);
  Py_DECREF(flat);
  return arr;
}

}  // namespace

int main(int argc, char** argv) {
  const std::string dir = argc > 1 ? argv[1] : "demo_program";

  Py_Initialize();

  // meta.txt: line 1 = repo path, line 2 = loss var name, line 3 = feature dim
  std::istringstream meta(ReadFile(dir + "/meta.txt"));
  std::string repo, loss_name;
  int dim = 0, classes = 0;
  std::getline(meta, repo);
  std::getline(meta, loss_name);
  meta >> dim >> classes;

  {  // sys.path.insert(0, repo)
    PyObject* sys_path = Check(PySys_GetObject("path"), "sys.path");
    PyObject* p = Check(PyUnicode_FromString(repo.c_str()), "repo str");
    PyList_Insert(sys_path, 0, p);
    Py_DECREF(p);
  }

  PyObject* fluid = Check(PyImport_ImportModule("paddle_tpu"), "import paddle_tpu");
  PyObject* serial = Check(PyImport_ImportModule("paddle_tpu.core.serialization"),
                           "import serialization");
  PyObject* np = Check(PyImport_ImportModule("numpy"), "import numpy");

  const std::string startup_json = ReadFile(dir + "/startup.json");
  const std::string main_json = ReadFile(dir + "/main.json");
  PyObject* startup = Check(
      PyObject_CallMethod(serial, "loads", "s", startup_json.c_str()), "loads startup");
  PyObject* main_prog = Check(
      PyObject_CallMethod(serial, "loads", "s", main_json.c_str()), "loads main");

  PyObject* place = Check(PyObject_CallMethod(fluid, "CPUPlace", nullptr), "CPUPlace");
  PyObject* exe = Check(PyObject_CallMethod(fluid, "Executor", "O", place), "Executor");
  Py_DECREF(Check(PyObject_CallMethod(exe, "run", "O", startup), "run startup"));

  // synthetic separable data, generated in C++ (reference demo feeds
  // constant fake data; we want a real convergence check)
  std::mt19937 gen(42);
  std::normal_distribution<float> noise(0.f, 0.3f);
  std::vector<float> centers(static_cast<size_t>(classes) * dim);
  for (auto& c : centers) c = noise(gen) * 10.f;

  const int batch = 32;
  std::vector<float> xbuf(static_cast<size_t>(batch) * dim);
  std::vector<long long> ybuf(batch);
  std::uniform_int_distribution<int> pick(0, classes - 1);

  PyObject* run_name = Check(PyUnicode_FromString("run"), "run name");
  double first_loss = -1.0, last_loss = -1.0;
  const int steps = 40;
  for (int step = 0; step < steps; ++step) {
    for (int i = 0; i < batch; ++i) {
      int y = pick(gen);
      ybuf[i] = y;
      for (int j = 0; j < dim; ++j)
        xbuf[static_cast<size_t>(i) * dim + j] =
            centers[static_cast<size_t>(y) * dim + j] + noise(gen);
    }
    PyObject* x_arr = MakeArray(np, xbuf.data(),
                                static_cast<Py_ssize_t>(xbuf.size() * sizeof(float)),
                                "float32", batch, dim);
    PyObject* y_arr = MakeArray(np, ybuf.data(),
                                static_cast<Py_ssize_t>(ybuf.size() * sizeof(long long)),
                                "int64", batch, 1);
    PyObject* feed = Check(PyDict_New(), "feed dict");
    PyDict_SetItemString(feed, "x", x_arr);
    PyDict_SetItemString(feed, "y", y_arr);
    PyObject* fetch = Check(Py_BuildValue("[s]", loss_name.c_str()), "fetch list");

    PyObject* args = Check(Py_BuildValue("(O)", main_prog), "args");
    PyObject* kwargs = Check(PyDict_New(), "kwargs");
    PyDict_SetItemString(kwargs, "feed", feed);
    PyDict_SetItemString(kwargs, "fetch_list", fetch);
    PyObject* run_m = Check(PyObject_GetAttr(exe, run_name), "exe.run attr");
    PyObject* result = Check(PyObject_Call(run_m, args, kwargs), "exe.run");

    PyObject* loss0 = Check(PySequence_GetItem(result, 0), "result[0]");
    PyObject* item = Check(PyObject_CallMethod(loss0, "item", nullptr), "loss.item()");
    last_loss = PyFloat_AsDouble(item);
    if (step == 0) first_loss = last_loss;
    if (step % 10 == 0 || step == steps - 1)
      std::printf("step %d loss %.6f\n", step, last_loss);

    for (PyObject* o : {x_arr, y_arr, feed, fetch, args, kwargs, run_m, result,
                        loss0, item})
      Py_DECREF(o);
  }

  std::printf("first=%.6f last=%.6f\n", first_loss, last_loss);
  const bool ok = last_loss < first_loss * 0.5;
  std::printf(ok ? "C++ train demo: PASS\n" : "C++ train demo: FAIL\n");
  Py_FinalizeEx();
  return ok ? 0 : 1;
}
