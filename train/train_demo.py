"""Python twin of demo_trainer.cc, with monitor wiring: load the programs
saved by ``save_program.py`` and train them with a ``monitor.StepLogger``
emitting periodic throughput/step-time/loss lines, then dump the metrics
snapshot (cache hits, step-time histogram) at the end.

    python train/save_program.py /tmp/demo_program
    python train/train_demo.py /tmp/demo_program [steps]

Runs on CPU (``JAX_PLATFORMS=cpu``) or TPU alike; set
``PADDLE_TPU_TRACE_FILE=/tmp/trace.json`` to also get a Chrome trace of
the host timeline.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import monitor  # noqa: E402
from paddle_tpu.core import serialization  # noqa: E402


def main(prog_dir, steps=200, batch=64, log_every=20):
    with open(os.path.join(prog_dir, "startup.json")) as f:
        startup = serialization.loads(f.read())
    with open(os.path.join(prog_dir, "main.json")) as f:
        main_p = serialization.loads(f.read())
    with open(os.path.join(prog_dir, "meta.txt")) as f:
        _repo, loss_name, dims = f.read().splitlines()[:3]
    dim, classes = (int(t) for t in dims.split())

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    # separable synthetic data so the loss visibly falls (demo_trainer.cc's
    # convergence check)
    rng = np.random.RandomState(0)
    centers = rng.randn(classes, dim).astype("float32") * 2.0

    slog = monitor.StepLogger(every_n=log_every, name="train_demo")
    last = None
    for _ in range(int(steps)):
        y = rng.randint(0, classes, (batch, 1)).astype("int64")
        x = (centers[y[:, 0]] + rng.randn(batch, dim).astype("float32") * 0.5)
        last, = exe.run(main_p, feed={"x": x, "y": y},
                        fetch_list=[loss_name])
        slog.step(loss=last, examples=batch)

    summary = slog.summary()
    print("final loss %.4f after %d steps" % (float(last), summary["steps"]))
    print(monitor.to_text())
    if float(last) > 1.0:
        print("WARNING: loss did not converge", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "demo_program",
                  *(int(a) for a in sys.argv[2:3])))
