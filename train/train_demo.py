"""Python twin of demo_trainer.cc, with monitor wiring: load the programs
saved by ``save_program.py`` and train them with a ``monitor.StepLogger``
emitting periodic throughput/step-time/loss lines, then dump the metrics
snapshot (cache hits, step-time histogram) at the end.

    python train/save_program.py /tmp/demo_program
    python train/train_demo.py /tmp/demo_program [steps] [--pipeline]

Runs on CPU (``JAX_PLATFORMS=cpu``) or TPU alike; set
``PADDLE_TPU_TRACE_FILE=/tmp/trace.json`` to also get a Chrome trace of
the host timeline. ``--pipeline`` swaps the run()-per-step loop for the
fused async driver (``Executor.run_steps``, ``log_every`` steps per
dispatch) — same losses bit-for-bit, 1/log_every the host dispatches.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import monitor  # noqa: E402
from paddle_tpu.core import serialization  # noqa: E402


def main(prog_dir, steps=200, batch=64, log_every=20, pipeline=False):
    with open(os.path.join(prog_dir, "startup.json")) as f:
        startup = serialization.loads(f.read())
    with open(os.path.join(prog_dir, "main.json")) as f:
        main_p = serialization.loads(f.read())
    with open(os.path.join(prog_dir, "meta.txt")) as f:
        _repo, loss_name, dims = f.read().splitlines()[:3]
    dim, classes = (int(t) for t in dims.split())

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    # separable synthetic data so the loss visibly falls (demo_trainer.cc's
    # convergence check)
    rng = np.random.RandomState(0)
    centers = rng.randn(classes, dim).astype("float32") * 2.0

    slog = monitor.StepLogger(every_n=log_every, name="train_demo")
    last = None

    def batches(n):
        for _ in range(n):
            y = rng.randint(0, classes, (batch, 1)).astype("int64")
            x = (centers[y[:, 0]]
                 + rng.randn(batch, dim).astype("float32") * 0.5)
            yield {"x": x, "y": y}

    if pipeline:
        # fused async driver: log_every steps per dispatched call. The
        # per-step losses come back in one burst, so replaying them through
        # StepLogger would fabricate absurd throughput lines — report one
        # honest wall-clock number instead.
        import time

        t0 = time.time()
        rows = exe.run_steps(main_p, batches(int(steps)), steps=int(steps),
                             fetch_list=[loss_name], fetch_every=log_every)
        dt = max(time.time() - t0, 1e-9)
        last = rows[-1][0]
        n_steps = len(rows)
        print("pipeline: %d steps in %.2fs (%.1f steps/s, %.1f ex/s, "
              "%d steps/dispatch)" % (n_steps, dt, n_steps / dt,
                                      n_steps * batch / dt, log_every))
    else:
        for feed in batches(int(steps)):
            last, = exe.run(main_p, feed=feed, fetch_list=[loss_name])
            slog.step(loss=last, examples=batch)
        n_steps = slog.summary()["steps"]

    print("final loss %.4f after %d steps" % (float(last), n_steps))
    print(monitor.to_text())
    if float(last) > 1.0:
        print("WARNING: loss did not converge", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    argv = list(sys.argv[1:])
    use_pipeline = "--pipeline" in argv
    if use_pipeline:
        argv.remove("--pipeline")
    sys.exit(main(argv[0] if argv else "demo_program",
                  *(int(a) for a in argv[1:2]), pipeline=use_pipeline))
