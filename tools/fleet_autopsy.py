"""Request autopsy CLI: phase waterfalls, budgets, and breach verdicts.

    python -m tools.fleet_autopsy <trace_dir> --trace-id ID
        Replay one request out of a finished traced fleet run: print its
        phase waterfall (every attributed interval in start order —
        queue/admission/prefill/ship/decode/verify/retry/tail with cause,
        replica and attempt), the per-phase totals, and the TTFT
        decomposition checked against the engine-measured ``ttft_ms`` the
        terminal instant carries.

    python -m tools.fleet_autopsy <trace_dir> [--window] [--event-log F]
                                  [--telemetry-base D] [--json]
        Aggregate table over every request of the run: per-phase
        per-replica p50/p99/total budgets (the same fold the router
        publishes as ``fleet/phase/<name>/ms`` histograms and snapshot
        ``phases`` blocks). With --event-log, recorded ``slo_breach``
        events are joined against the ledger and one ``BreachAutopsy``
        verdict per distinct breach is printed (dominant phase, offending
        replica(s), exemplar trace_ids, actionable hint) — the offline
        twin of the verdicts the router journals at close.

    python -m tools.fleet_autopsy --selftest
        <10s, JAX_PLATFORMS=cpu: runs a traced+SLO-armed 2-replica
        process-mode sim fleet with a decode-latency fault injected into
        replica 0 only, and asserts the breach autopsy names the decode
        phase and replica 0 (exemplar trace_ids present in the merged
        timeline, verdict journaled in the event log under the run's
        run_id); that every finished request's TTFT decomposition sums to
        the engine-measured ``serving/ttft_ms`` within tolerance; and
        that the same fleet WITHOUT the fault emits zero autopsies. The
        smoke-gate entry (ROADMAP).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


# -- rendering ----------------------------------------------------------------

def _fmt_ms(v) -> str:
    return "%.2f" % v if v is not None else "-"


def waterfall(trace_dir: str, trace_id: str) -> dict:
    """Single-request phase waterfall; returns the ledger doc."""
    from paddle_tpu.fleet import autopsy

    res = autopsy.run_autopsy(trace_dir)
    led = res["ledgers"].get(trace_id)
    if led is None:
        raise SystemExit("trace_id %r not found (have %d requests; e.g. %s)"
                         % (trace_id, len(res["ledgers"]),
                            sorted(res["ledgers"])[:4]))
    t0 = min((iv.t0_us for iv in led.intervals),
             default=led.submitted_us or 0)
    if led.submitted_us is not None:
        t0 = min(t0, led.submitted_us)
    print("request %s  state=%s attempts=%d replicas=%s"
          % (led.trace_id, led.state, led.attempts, led.replicas))
    print("%10s %10s %9s  %-9s %-10s %-7s %s"
          % ("start_ms", "end_ms", "ms", "phase", "cause", "replica",
             "attempt"))
    for iv in led.intervals:
        print("%10.2f %10.2f %9.2f  %-9s %-10s %-7s %s"
              % ((iv.t0_us - t0) / 1e3, (iv.t1_us - t0) / 1e3, iv.ms,
                 iv.phase, iv.cause or "-",
                 iv.replica if iv.replica is not None else "-",
                 iv.attempt if iv.attempt is not None else "-"))
    print("phase totals: %s" % json.dumps(
        {k: round(v, 2) for k, v in led.phase_ms().items() if v > 0}))
    ttft = led.ttft_decomposition()
    print("ttft: explained=%sms (queue=%s admission=%s prefill=%s) "
          "measured=%sms  e2e=%sms"
          % (_fmt_ms(ttft["explained_ms"]), _fmt_ms(ttft["queue_ms"]),
             _fmt_ms(ttft["admission_ms"]), _fmt_ms(ttft["prefill_ms"]),
             _fmt_ms(ttft.get("measured_ttft_ms")), _fmt_ms(led.e2e_ms())))
    return led.to_doc()


def window(trace_dir: str, event_log: str = None, telemetry_base: str = None,
           as_json: bool = False) -> dict:
    """Aggregate per-phase budgets (+ breach verdicts when an event log
    is given); returns the printable doc."""
    from paddle_tpu.fleet import autopsy

    res = autopsy.run_autopsy(trace_dir, event_log=event_log,
                              telemetry_base=telemetry_base)
    stats = res["stats"]
    doc = {"requests": stats["requests"],
           "run_id": (res["manifest"] or {}).get("run_id"),
           "fleet": stats["fleet"], "replicas": stats["replicas"],
           "autopsies": [a.to_doc() for a in res["autopsies"]],
           "problems": res["problems"]}
    if as_json:
        print(json.dumps(doc, indent=1, default=str))
        return doc
    print("run %s: %d requests, %d trace problem(s)"
          % (doc["run_id"], doc["requests"], len(doc["problems"])))
    print("%-10s %-9s %6s %10s %10s %12s"
          % ("scope", "phase", "count", "p50_ms", "p99_ms", "total_ms"))
    scopes = [("fleet", stats["fleet"])]
    scopes += [("replica %s" % r, v)
               for r, v in sorted(stats["replicas"].items())]
    for scope, folds in scopes:
        for phase, st in folds.items():
            print("%-10s %-9s %6d %10.2f %10.2f %12.2f"
                  % (scope, phase, st["count"], st["p50_ms"], st["p99_ms"],
                     st["total_ms"]))
    for a in doc["autopsies"]:
        print("BREACH %s [%s%s]: dominant=%s (%.0f%% of attributed time) "
              "offenders=%s exemplars=%s\n  hint: %s"
              % (a["slo"], a["scope"],
                 "" if a["replica"] is None else ":%s" % a["replica"],
                 a["dominant_phase"], a["dominant_share"] * 100.0,
                 [o.get("replica") for o in a["offenders"]],
                 a["exemplars"], a["hint"]))
    if not doc["autopsies"]:
        print("no SLO breaches recorded%s"
              % ("" if event_log else " (no --event-log given)"))
    return doc


# -- selftest -----------------------------------------------------------------

def _drill(td: str, faulted: bool) -> dict:
    """One traced, SLO-armed 2-replica sim fleet run; with ``faulted``,
    replica 0 decodes with a 60ms injected step latency."""
    from paddle_tpu.fleet import FleetConfig, Router
    from paddle_tpu.monitor.slo import parse_slos

    tag = "faulted" if faulted else "clean"
    trace_dir = os.path.join(td, "trace_%s" % tag)
    base = os.path.join(td, "tele_%s" % tag)
    elog = os.path.join(td, "events_%s.jsonl" % tag)
    overrides = {}
    if faulted:
        overrides = {0: {"fault_plan": "serving.decode@1=latency:999:60"}}
    router = Router(FleetConfig(
        replicas=2, mode="process", affinity="round_robin",
        engine_spec={"engine": "sim", "sim": {"slots": 4, "step_ms": 2.0}},
        max_outstanding=16, trace_dir=trace_dir, telemetry_base=base,
        event_log=elog,
        slos=parse_slos("serving/request_latency_ms:p99<=150"),
        spec_overrides=overrides))
    try:
        frs = [router.submit([3, i], 8) for i in range(8)]
        assert router.wait_all(60.0), router.accounting()
        assert all(f.state == "finished" for f in frs), router.accounting()
    finally:
        router.close()  # workers flush samples -> SLO pass -> autopsy
    return {"trace_dir": trace_dir, "event_log": elog,
            "telemetry_base": base, "router": router,
            "trace_ids": [f.trace_id for f in frs]}


def selftest() -> int:
    t0 = time.perf_counter()
    from paddle_tpu.fleet import autopsy
    from paddle_tpu.fleet.events import (KIND_BREACH_AUTOPSY,
                                         KIND_SLO_BREACH, read_events)
    from paddle_tpu.monitor import metrics as mx

    mx.enable()
    # pin the workers' export interval above the run length: one final
    # flushed sample per worker -> the close()-time SLO pass judges the
    # whole run deterministically (same recipe as fleet_bench's SLO leg)
    prev = os.environ.get("PADDLE_TPU_TELEMETRY_INTERVAL_S")
    os.environ["PADDLE_TPU_TELEMETRY_INTERVAL_S"] = "60"
    try:
        with tempfile.TemporaryDirectory() as td:
            run = _drill(td, faulted=True)

            # 1. the breach fired and the router journaled a typed
            # autopsy verdict under the same run_id
            evs = read_events(run["event_log"])
            rids = {e["run_id"] for e in evs}
            assert len(rids) == 1, rids
            breaches = [e for e in evs if e["kind"] == KIND_SLO_BREACH]
            assert breaches, "faulted run recorded no slo_breach"
            verdicts = [e for e in evs if e["kind"] == KIND_BREACH_AUTOPSY]
            assert verdicts, "no breach_autopsy journaled at close"

            # 2. every verdict names the decode phase; the replica-scope
            # verdict (and every offender ranking) names replica 0
            for v in verdicts:
                assert v["dominant_phase"] == "decode", v
                assert v["offenders"], v
                assert v["offenders"][0]["replica"] == 0, v["offenders"]
                assert "decode" in v["hint"], v["hint"]
            rep_scoped = [v for v in verdicts if v["scope"] == "replica"]
            assert rep_scoped and all(v["replica"] == 0
                                      for v in rep_scoped), verdicts

            # 3. exemplar trace_ids exist in the merged timeline's
            # request set (and on the offending replica)
            res = autopsy.run_autopsy(run["trace_dir"],
                                      event_log=run["event_log"],
                                      telemetry_base=run["telemetry_base"])
            for v in verdicts:
                assert v["exemplars"], v
                for tid in v["exemplars"]:
                    led = res["ledgers"].get(tid)
                    assert led is not None, (tid, sorted(res["ledgers"]))
                    assert 0 in led.replicas, (tid, led.replicas)

            # 4. TTFT decomposition: queue+admission+prefill explains the
            # engine-measured serving/ttft_ms for EVERY finished request
            finished = [led for led in res["ledgers"].values()
                        if led.state == "finished"]
            assert len(finished) == 8, len(finished)
            for led in finished:
                ttft = led.ttft_decomposition()
                m = ttft["measured_ttft_ms"]
                assert m is not None, led.trace_id
                tol = max(1.0, 0.05 * m)
                assert abs(ttft["explained_ms"] - m) <= tol, \
                    "request %s: explained %.3fms vs measured %.3fms" \
                    % (led.trace_id, ttft["explained_ms"], m)

            # 5. the decomposition is on the ordinary metrics surfaces:
            # fleet/phase/* histograms observed per request, and the
            # snapshot carries per-replica phase budgets with replica 0's
            # decode p50 past the injected 60ms step latency
            assert mx.histogram("fleet/phase/decode/ms").count >= 8
            snap = run["router"].snapshot()
            assert "phases" in snap and "decode" in snap["phases"], \
                sorted(snap.get("phases", {}))
            r0 = next(r for r in snap["replicas"]
                      if r["name"] == "replica-0")
            r1 = next(r for r in snap["replicas"]
                      if r["name"] == "replica-1")
            d0 = r0["phases"]["decode"]
            d1 = r1["phases"]["decode"]
            assert d0["p50_ms"] >= 60.0 > d1["p50_ms"], (d0, d1)
            assert snap.get("autopsies"), "snapshot lost the verdicts"

            # 6. the CLI renders both views without error
            waterfall(run["trace_dir"], run["trace_ids"][0])
            window(run["trace_dir"], event_log=run["event_log"],
                   telemetry_base=run["telemetry_base"])

            # 7. a clean run (same shape, no fault) emits ZERO autopsies
            clean = _drill(td, faulted=False)
            evs_clean = read_events(clean["event_log"])
            assert not [e for e in evs_clean
                        if e["kind"] == KIND_BREACH_AUTOPSY], \
                "clean run produced autopsy verdicts"
            assert not [e for e in evs_clean
                        if e["kind"] == KIND_SLO_BREACH], \
                "clean run breached"
    finally:
        if prev is None:
            os.environ.pop("PADDLE_TPU_TELEMETRY_INTERVAL_S", None)
        else:
            os.environ["PADDLE_TPU_TELEMETRY_INTERVAL_S"] = prev

    print("fleet_autopsy selftest: OK (%.1fs)  %d breach(es) -> %d "
          "verdict(s), dominant=decode@replica0 (r0 decode p50 %.0fms vs "
          "r1 %.1fms), TTFT explained within tolerance on %d requests, "
          "clean run: 0 autopsies"
          % (time.perf_counter() - t0, len(breaches), len(verdicts),
             d0["p50_ms"], d1["p50_ms"], len(finished)))
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0
    if argv and argv[0] == "--selftest":
        return selftest()

    def opt(name, default=None):
        if name in argv:
            i = argv.index(name)
            argv.pop(i)
            return argv.pop(i)
        return default

    trace_id = opt("--trace-id")
    event_log = opt("--event-log")
    telemetry_base = opt("--telemetry-base")
    as_json = "--json" in argv
    if as_json:
        argv.remove("--json")
    if "--window" in argv:
        argv.remove("--window")
    if len(argv) != 1:
        print("usage: python -m tools.fleet_autopsy <trace_dir> "
              "[--trace-id ID | --window] [--event-log F] "
              "[--telemetry-base D] [--json]", file=sys.stderr)
        return 2
    trace_dir = argv[0]
    if trace_id:
        doc = waterfall(trace_dir, trace_id)
        if as_json:
            print(json.dumps(doc, indent=1, default=str))
        return 0
    window(trace_dir, event_log=event_log, telemetry_base=telemetry_base,
           as_json=as_json)
    return 0


if __name__ == "__main__":
    sys.exit(main())
