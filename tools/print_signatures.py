"""Dump the public API surface as a stable, diffable spec (reference:
tools/print_signatures.py + paddle/fluid/API.spec + tools/diff_api.py).

Usage:  python tools/print_signatures.py > API.spec

Every public function/class in the listed modules is emitted as
``qualified.name (signature)``; classes additionally list their public
methods. The committed API.spec is enforced by tests/test_api_spec.py — an
intentional API change must regenerate the spec in the same commit.
"""

from __future__ import annotations

import inspect
import sys

MODULES = [
    "paddle_tpu",
    "paddle_tpu.compile_cache",
    "paddle_tpu.layers",
    "paddle_tpu.layers.detection",
    "paddle_tpu.layers.control_flow",
    "paddle_tpu.layers.io",
    "paddle_tpu.layers.tensor",
    "paddle_tpu.layers.learning_rate_scheduler",
    "paddle_tpu.optimizer",
    "paddle_tpu.initializer",
    "paddle_tpu.regularizer",
    "paddle_tpu.clip",
    "paddle_tpu.io",
    "paddle_tpu.metrics",
    "paddle_tpu.monitor",
    "paddle_tpu.monitor.budgets",
    "paddle_tpu.monitor.device",
    "paddle_tpu.monitor.metrics",
    "paddle_tpu.monitor.numerics",
    "paddle_tpu.monitor.regress",
    "paddle_tpu.monitor.runlog",
    "paddle_tpu.monitor.slo",
    "paddle_tpu.monitor.stepstats",
    "paddle_tpu.monitor.telemetry",
    "paddle_tpu.monitor.tracer",
    "paddle_tpu.nets",
    "paddle_tpu.reader",
    "paddle_tpu.backward",
    "paddle_tpu.amp",
    "paddle_tpu.imperative",
    "paddle_tpu.parallel",
    "paddle_tpu.passes",
    "paddle_tpu.profiler",
    "paddle_tpu.transpiler",
    "paddle_tpu.contrib",
    "paddle_tpu.inference",
    "paddle_tpu.serving",
    "paddle_tpu.serving.phases",
    "paddle_tpu.fleet",
    "paddle_tpu.fleet.autopsy",
    "paddle_tpu.fleet.prefix_cache",
    "paddle_tpu.fleet.protocol",
    "paddle_tpu.fleet.replica",
    "paddle_tpu.fleet.router",
    "paddle_tpu.fleet.trace",
    "paddle_tpu.fleet.slo",
    "paddle_tpu.fleet.events",
    "paddle_tpu.reliability",
    "paddle_tpu.reliability.faults",
    "paddle_tpu.reliability.supervisor",
    "paddle_tpu.reliability.sentinel",
    "paddle_tpu.data",
    "paddle_tpu.data.reader",
    "paddle_tpu.data.multislot",
    "paddle_tpu.tune",
    "paddle_tpu.tune.table",
    "paddle_tpu.tune.search",
    "paddle_tpu.tune.tunables",
    "paddle_tpu.dataset",
]


def _sig(obj) -> str:
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(*args, **kwargs)"


def _public_names(mod):
    names = getattr(mod, "__all__", None)
    if names is None:
        names = [n for n in dir(mod) if not n.startswith("_")]
    return sorted(set(names))


def collect():
    import importlib

    lines = []
    for mod_name in MODULES:
        mod = importlib.import_module(mod_name)
        for name in _public_names(mod):
            obj = getattr(mod, name, None)
            if obj is None or inspect.ismodule(obj):
                continue
            qual = "%s.%s" % (mod_name, name)
            if inspect.isclass(obj):
                lines.append("%s %s" % (qual, _sig(obj.__init__)))
                for mname, meth in sorted(vars(obj).items()):
                    if mname.startswith("_"):
                        continue
                    if callable(meth) or isinstance(meth, (staticmethod, classmethod)):
                        fn = meth.__func__ if isinstance(meth, (staticmethod, classmethod)) else meth
                        if callable(fn):
                            lines.append("%s.%s %s" % (qual, mname, _sig(fn)))
            elif callable(obj):
                lines.append("%s %s" % (qual, _sig(obj)))
    return sorted(set(lines))


if __name__ == "__main__":
    sys.stdout.write("\n".join(collect()) + "\n")
