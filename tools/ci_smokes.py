"""Run every ROADMAP smoke gate sequentially — the pre-tier-1 CI entry.

    python -m tools.ci_smokes [--only FRAGMENT] [--timeout SECONDS]

Each gate is one ``JAX_PLATFORMS=cpu python -m <module> --selftest``
subprocess (a fresh interpreter per gate, exactly how CI and a human run
them — no shared registry state between gates). Prints one PASS/FAIL
line per gate with its wall time, a failing gate's last output lines,
and exits nonzero iff any gate failed.

Each gate also has a wall-time BUDGET (the ROADMAP's per-gate bound): a
passing gate that runs over budget prints a visible ``SLOW`` warning —
never a failure, so a loaded CI host cannot flake the gate, but drift
shows up in the log the day it starts, not the day the suite times out.

The gate list mirrors ROADMAP.md's "fast smokes" — keep both in sync.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (label, module) — ROADMAP.md order
GATES = (
    ("dump_metrics", "tools.dump_metrics"),
    ("dump_program", "tools.dump_program"),
    ("sparse_adam", "paddle_tpu.ops.pallas_kernels.sparse_adam"),
    ("paged_attention", "paddle_tpu.ops.pallas_kernels.paged_attention"),
    ("profile_report", "tools.profile_report"),
    ("serve_bench", "tools.serve_bench"),
    ("fleet_bench", "tools.fleet_bench"),
    ("chaos_drill", "tools.chaos_drill"),
    ("fleet_trace", "tools.fleet_trace"),
    ("fleet_autopsy", "tools.fleet_autopsy"),
    ("autotune", "tools.autotune"),
    ("check_budgets", "tools.check_budgets"),
    ("perf_gate", "tools.perf_gate"),
    ("numerics_report", "tools.numerics_report"),
)

# label -> wall-time budget in seconds (the ROADMAP per-gate bounds).
# Exceeding a budget WARNS (visibly, in the gate line) but never fails:
# budgets catch drift, timeouts catch hangs.
BUDGETS = {
    "dump_metrics": 10.0,
    "dump_program": 10.0,
    "sparse_adam": 15.0,
    "paged_attention": 15.0,
    "profile_report": 15.0,
    "serve_bench": 75.0,   # speculative leg + its repetitive-stream drill
    "fleet_bench": 75.0,  # + disagg QPS, remote-hit, and kill-migration legs
    "chaos_drill": 30.0,
    "fleet_trace": 10.0,
    "fleet_autopsy": 10.0,
    "autotune": 15.0,
    "check_budgets": 10.0,
    "perf_gate": 10.0,
    "numerics_report": 15.0,
}


def run_gate(module: str, timeout: float = 120.0):
    """One smoke gate in a clean subprocess; returns (rc, seconds, tail)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, "-m", module, "--selftest"],
            cwd=_REPO, env=env, timeout=timeout,
            capture_output=True, text=True)
        rc, out = proc.returncode, proc.stdout + proc.stderr
    except subprocess.TimeoutExpired as e:
        rc = 124
        out = "%s%s\nTIMEOUT after %.0fs" % (
            (e.stdout or b"").decode("utf-8", "replace") if
            isinstance(e.stdout, bytes) else (e.stdout or ""),
            (e.stderr or b"").decode("utf-8", "replace") if
            isinstance(e.stderr, bytes) else (e.stderr or ""), timeout)
    dt = time.perf_counter() - t0
    tail = "\n".join(out.strip().splitlines()[-12:])
    return rc, dt, tail


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0

    def opt(name, default=None):
        if name in argv:
            i = argv.index(name)
            argv.pop(i)
            return argv.pop(i)
        return default

    only = opt("--only")
    timeout = float(opt("--timeout", "120"))
    if argv:
        print("unknown arguments: %s" % " ".join(argv), file=sys.stderr)
        return 2
    gates = [(lbl, mod) for lbl, mod in GATES
             if only is None or only in lbl]
    if not gates:
        print("no gate matches --only %r" % only, file=sys.stderr)
        return 2
    failed = []
    slow = []
    t0 = time.perf_counter()
    for label, module in gates:
        rc, dt, tail = run_gate(module, timeout=timeout)
        status = "PASS" if rc == 0 else "FAIL(rc=%d)" % rc
        budget = BUDGETS.get(label)
        drift = ""
        if rc == 0 and budget is not None and dt > budget:
            slow.append(label)
            drift = "  SLOW: %.1fs > %.0fs budget" % (dt, budget)
        print("%-16s %-10s %6.1fs   python -m %s --selftest%s"
              % (label, status, dt, module, drift))
        if rc != 0:
            failed.append(label)
            print("  | " + tail.replace("\n", "\n  | "), file=sys.stderr)
    total = time.perf_counter() - t0
    print("-" * 60)
    if slow:
        print("ci_smokes: WARNING %d gate(s) over wall-time budget (%s) — "
              "not fatal, but the drift is real; re-budget or re-tighten"
              % (len(slow), ", ".join(slow)))
    if failed:
        print("ci_smokes: %d/%d gates FAILED (%s) in %.1fs"
              % (len(failed), len(gates), ", ".join(failed), total))
        return 1
    print("ci_smokes: all %d gates passed in %.1fs" % (len(gates), total))
    return 0


if __name__ == "__main__":
    sys.exit(main())
