"""Live fleet view: per-replica QPS/p99/queue/health from on-disk state.

    python -m tools.fleet_top <telemetry_base> [--events FILE]
                              [--watch [SECONDS]] [--ticks N]
        Render the fleet observability plane with NO control channel to
        the router — everything comes off disk: ``snapshot.json`` (the
        router drops it atomically under its telemetry base every
        observation tick), the per-replica telemetry rings
        (``replica_<i>/``), and optionally the fleet event log tail.
        ``--watch`` redraws every SECONDS (default 2.0) until ^C;
        ``--ticks`` bounds the redraws (for drivers/tests). One replica
        per row, in NUMERIC index order (replica_10 after replica_9),
        with ring freshness and degradation flags inline. When the run
        was traced, each replica row carries a ``phases:`` sub-line with
        the per-phase p50/p99 latency budgets (the ``fleet/phase/*``
        decomposition the router folds into the snapshot at close).

    python -m tools.fleet_top --selftest
        <10s: drives a tiny process-mode sim fleet with telemetry + an
        event log, then asserts the rendered view carries the replica
        rows, states, SLO section and event tail, and that watch mode
        ticks without a router alive (the files are the interface).
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def load_view(base: str, events_path: str = None,
              events_tail: int = 5) -> dict:
    """Everything one render needs, read fresh from disk. Tolerant of
    every partial state: no snapshot yet, no rings yet, no event log —
    the view says what is missing instead of failing."""
    from paddle_tpu.fleet.events import read_events
    from paddle_tpu.fleet.router import aggregate_telemetry

    view = {"base": base, "snapshot": None, "telemetry": {}, "events": []}
    snap_path = os.path.join(base, "snapshot.json")
    try:
        with open(snap_path) as f:
            view["snapshot"] = json.load(f)
    except (OSError, ValueError):
        pass
    view["telemetry"] = aggregate_telemetry(base)
    if events_path is None and view["snapshot"]:
        events_path = view["snapshot"].get("event_log")
    if events_path:
        view["events"] = read_events(events_path)[-events_tail:]
    return view


def _ring_row(entry: dict) -> str:
    if not entry:
        return "ring: none"
    if entry.get("flag"):
        return "ring: %s" % entry["flag"]
    last = entry.get("last") or {}
    age = max(0.0, time.time() - float(last.get("t", 0.0)))
    retired = ((last.get("metrics") or {})
               .get("serving/requests_retired") or {}).get("value")
    return "ring: %d samples, %.1fs old%s" % (
        entry.get("samples", 0), age,
        ", retired=%d" % retired if retired is not None else "")


def render(view: dict) -> str:
    lines = []
    snap = view.get("snapshot")
    if snap:
        states = " ".join("%s=%d" % kv
                          for kv in sorted((snap.get("states") or {})
                                           .items()))
        lines.append("fleet %s  up %.1fs  queue=%d  requests=%d  %s"
                     % (snap.get("run_id", "?"),
                        snap.get("uptime_s", 0.0),
                        snap.get("queue_depth", 0),
                        snap.get("requests", 0), states))
        slo = snap.get("slo")
        if slo:
            lines.append(
                "slo: %s  breached_replicas=%s  fleet_breaches=%d%s"
                % (",".join(slo.get("specs") or []) or "-",
                   slo.get("breached_replicas") or [],
                   slo.get("fleet_breaches", 0),
                   "  LAST: %s" % (slo.get("fleet_breach") or {}).get("slo")
                   if slo.get("fleet_breach") else ""))
    else:
        lines.append("fleet <no snapshot.json under %s>" % view["base"])
    lines.append("%-12s %-6s %-9s %8s %9s %8s %9s  %s"
                 % ("replica", "alive", "status", "inflight", "completed",
                    "qps", "p99_ms", "telemetry"))
    rows = {r["name"]: r for r in (snap or {}).get("replicas") or []}
    names = list(rows)
    for tname in view.get("telemetry") or {}:
        rname = tname.replace("replica_", "replica-")
        if rname not in names:
            names.append(rname)
    for name in names:
        r = rows.get(name, {})
        h = r.get("health") or {}
        status = h.get("status", "?")
        if h.get("slo_breached"):
            status += "(slo)"
        ring = (view.get("telemetry") or {}).get(
            name.replace("replica-", "replica_"))
        qps = r.get("qps", "-")
        p99 = r.get("p99_ms", "-")
        lines.append("%-12s %-6s %-9s %8s %9s %8s %9s  %s"
                     % (name, r.get("alive", "?"), status,
                        r.get("inflight", "-"), r.get("completed", "-"),
                        "%.2f" % qps if isinstance(qps, float) else qps,
                        "%.1f" % p99 if isinstance(p99, float) else p99,
                        _ring_row(ring)))
        ph = r.get("phases") or {}
        cells = " ".join("%s %.1f/%.1f" % (p, st["p50_ms"], st["p99_ms"])
                         for p, st in ph.items() if st.get("count"))
        if cells:
            lines.append("  phases(p50/p99 ms): %s" % cells)
    for ev in view.get("events") or []:
        extra = ev.get("replica")
        lines.append("event %-14s %s%s"
                     % (ev.get("kind"),
                        "replica=%s " % extra if extra is not None else "",
                        ev.get("trace_id") or ev.get("why") or ""))
    return "\n".join(lines)


def watch(base: str, interval_s: float = 2.0, events_path: str = None,
          max_ticks: int = None) -> int:
    ticks = 0
    try:
        while max_ticks is None or ticks < max_ticks:
            out = render(load_view(base, events_path))
            if ticks:
                sys.stdout.write("\x1b[2J\x1b[H")
            print(out)
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
            time.sleep(interval_s)
    except KeyboardInterrupt:
        pass
    return 0


# -- selftest -----------------------------------------------------------------

def selftest() -> int:
    import tempfile

    t0 = time.perf_counter()
    from paddle_tpu.fleet import FleetConfig, Router

    with tempfile.TemporaryDirectory() as td:
        base = os.path.join(td, "tele")
        elog = os.path.join(td, "events.jsonl")
        router = Router(FleetConfig(
            replicas=2, mode="process", affinity="round_robin",
            engine_spec={"engine": "sim",
                         "sim": {"slots": 2, "step_ms": 2.0}},
            telemetry_base=base, event_log=elog,
            trace_dir=os.path.join(td, "trace"),
            slos=[]))
        try:
            for i in range(6):
                router.submit([1, i], 8)
            assert router.wait_all(30.0)
        finally:
            router.close()   # workers flush final samples; snapshot drops

        view = load_view(base, elog)
        assert view["snapshot"] is not None, "router left no snapshot.json"
        assert len(view["snapshot"]["replicas"]) == 2
        assert view["telemetry"], "no replica rings under %s" % base
        out = render(view)
        assert "replica-0" in out and "replica-1" in out, out
        assert "finished=6" in out, out
        assert "fleet_stop" in out or "event" in out, out
        # traced run -> the close-time snapshot carries per-replica phase
        # budgets and the rows grow a phases sub-line
        assert "phases(p50/p99 ms):" in out, out
        assert "decode" in out and "prefill" in out, out

        # numeric ordering: a fabricated replica_10 ring must sort after
        # replica_2, not between replica_1 and replica_2
        from paddle_tpu.fleet.router import aggregate_telemetry

        for idx in (2, 10):
            os.makedirs(os.path.join(base, "replica_%d" % idx),
                        exist_ok=True)
        order = [n for n in aggregate_telemetry(base)]
        assert order.index("replica_2") < order.index("replica_10"), order

        # watch mode ticks off disk with no router alive
        assert watch(base, interval_s=0.01, events_path=elog,
                     max_ticks=2) == 0

    print("fleet_top selftest: OK (%.1fs)" % (time.perf_counter() - t0))
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0
    if argv and argv[0] == "--selftest":
        return selftest()

    def opt(name, default=None):
        if name in argv:
            i = argv.index(name)
            argv.pop(i)
            return argv.pop(i)
        return default

    events_path = opt("--events")
    ticks = opt("--ticks")
    interval = None
    if "--watch" in argv:
        i = argv.index("--watch")
        argv.pop(i)
        interval = 2.0
        if i < len(argv) and not argv[i].startswith("-") \
                and not os.path.isdir(argv[i]):
            try:
                interval = float(argv[i])
                argv.pop(i)
            except ValueError:
                pass
    if len(argv) != 1:
        print("usage: python -m tools.fleet_top <telemetry_base> "
              "[--events FILE] [--watch [SECONDS]] [--ticks N]",
              file=sys.stderr)
        return 2
    base = argv[0]
    if interval is not None:
        return watch(base, interval, events_path,
                     max_ticks=int(ticks) if ticks else None)
    print(render(load_view(base, events_path)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
