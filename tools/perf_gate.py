"""Perf regression gate over the run ledger (paddle_tpu.monitor.runlog).

    python -m tools.perf_gate --record [--ledger FILE] [--steps N]
        Run the quick CPU probe (tiny MLP train loop, ~1s) and append one
        provenance-stamped record to the ledger (PADDLE_TPU_RUN_LEDGER or
        --ledger). On TPU rounds this is the command that extends the
        measured bench trajectory past BENCH_r05 — every probe becomes a
        durable (config, context, time) baseline point.

    python -m tools.perf_gate --check [--ledger FILE] [--rel-threshold F]
                              [--min-samples N] [--window N]
        Compare the newest ledger record against the trailing
        per-(config, metric) baseline window (median + MAD noise band,
        direction-aware — see monitor.regress). Exit 1 on any REGRESSED
        verdict, naming the offending (config, metric); NEUTRAL /
        IMPROVED / INSUFFICIENT_DATA exit 0.

    python -m tools.perf_gate --report [--ledger FILE]
        Trend table per (config, metric): n, median, MAD, last value.

    python -m tools.perf_gate --explain [--ledger FILE]
        Step-time decomposition of the newest record (compute / comms /
        host / input attribution with the dominant term + hint).

    python -m tools.perf_gate --selftest
        <5s, CPU, synthetic ledger: write/rotate/torn-tail read-back with
        provenance round-trip, injected-regression drill (exit nonzero),
        noisy-flat pass, min-sample gating, and a deliberately
        feed-starved probe labeled input-bound. The CI smoke gate.
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


# -- the quick probe ----------------------------------------------------------

def run_probe(steps=24, batch=32, starve_ms=0.0, seed=0):
    """Tiny MLP train loop (profile_report's demo shape): compile once,
    time ``steps`` steps, return ({config: metrics}, stepstats breakdown).

    ``starve_ms`` makes the feed source deliberately slow — each step's
    batch "arrives" after that long, with the measured wait observed into
    the real ``data/prefetch_wait_ms`` instrument — the input-bound drill
    the selftest asserts on. Step wall time includes the feed wait (the
    wall clock a user sees)."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.data import metrics as dmx
    from paddle_tpu.monitor import stepstats

    rng = np.random.RandomState(seed)
    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("x", shape=[32])
                y = fluid.layers.data("y", shape=[1], dtype="int64")
                h = fluid.layers.fc(x, size=64, act="relu")
                logits = fluid.layers.fc(h, size=10)
                loss = fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(logits, y))
                fluid.optimizer.SGD(0.1).minimize(loss)
            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(startup)
            feed = {"x": rng.rand(batch, 32).astype("float32"),
                    "y": rng.randint(0, 10, (batch, 1)).astype("int64")}
            for _ in range(3):  # compile + post-compile settle, untimed
                exe.run(main, feed=feed, fetch_list=[loss])
            iter_ms = []
            for _ in range(int(steps)):
                t0 = time.perf_counter()
                if starve_ms:
                    time.sleep(starve_ms / 1e3)
                    dmx.PREFETCH_WAIT_MS.observe(
                        (time.perf_counter() - t0) * 1e3)
                exe.run(main, feed=feed, fetch_list=[loss])
                iter_ms.append((time.perf_counter() - t0) * 1e3)
    st = sorted(iter_ms)
    # the gate statistic is the mean of the fastest half, not the median:
    # a sub-ms CPU probe's upper half is scheduler jitter, and across
    # fresh processes the median wobbles ~20% while the fast-half mean
    # stays within a few percent — the difference between NEUTRAL and
    # noise-triggered verdicts on back-to-back runs
    lo = st[:max(1, len(st) // 2)]
    step_ms = sum(lo) / len(lo)
    config = "mlp_train_b%d" % batch + ("_starved" if starve_ms else "")
    metrics = {
        "step_ms": round(step_ms, 4),
        "examples_per_sec": round(batch * 1e3 / max(step_ms, 1e-9), 2),
    }
    breakdown = stepstats.decompose(step_ms=step_ms)
    return {config: metrics}, {config: breakdown}


def record_probes(steps=24, batch=32, starve_ms=0.0):
    """--record: probe, append one ledger record, print the tail."""
    from paddle_tpu.monitor import runlog

    configs, breakdowns = run_probe(steps=steps, batch=batch,
                                    starve_ms=starve_ms)
    record = runlog.record_run("perf_gate", configs,
                               extra={"stepstats": breakdowns})
    tail = dict(runlog.tail_info())
    tail["configs"] = configs
    tail["ledger_path"] = record["ledger_path"]
    print(json.dumps({"perf_gate": tail}))
    if record["ledger_path"] is None:
        print("# ledger NOT armed — set PADDLE_TPU_RUN_LEDGER (or pass "
              "--ledger) to persist this probe", file=sys.stderr)
    return record


# -- check / report / explain -------------------------------------------------

def check_ledger(path=None, rel_threshold=0.10, mad_mult=4.0,
                 min_samples=4, window=20, quiet=False):
    """Newest record vs trailing baselines; returns (exit_code, verdicts)."""
    from paddle_tpu.monitor import regress, runlog

    records = runlog.read_ledger(path)
    if not records:
        if not quiet:
            print("perf_gate --check: ledger is empty (%r)"
                  % (path or runlog.ledger_path()), file=sys.stderr)
        return 2, []
    head, history = records[-1], records[:-1]
    verdicts = regress.compare_run(
        head, history, rel_threshold=rel_threshold, mad_mult=mad_mult,
        min_samples=min_samples, window=window)
    regressed = regress.check_verdicts(verdicts)
    if not quiet:
        print("perf_gate --check: run %s (%s) vs %d prior records"
              % (head.get("run_id"), head.get("kind"), len(history)))
        if verdicts:
            print(regress.report(verdicts))
        else:
            print("no comparable (config, metric) pairs")
        for v in regressed:
            print("REGRESSION: (%s, %s) %.4g vs baseline median %.4g"
                  % (v.config, v.metric, v.current, v.baseline_median),
                  file=sys.stderr)
    return (1 if regressed else 0), verdicts


def report_ledger(path=None):
    """--report: one trend row per (config, metric)."""
    from paddle_tpu.monitor import regress, runlog

    records = runlog.read_ledger(path)
    series = {}
    for rec in records:
        for config, metrics in sorted((rec.get("configs") or {}).items()):
            if not isinstance(metrics, dict):
                continue
            for metric, v in sorted(metrics.items()):
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    series.setdefault((config, metric), []).append(float(v))
    header = ("config", "metric", "n", "median", "mad", "min", "max", "last")
    rows = []
    for (config, metric), vals in sorted(series.items()):
        med = regress._median(vals)
        rows.append((config[:36], metric, "%d" % len(vals), "%.4g" % med,
                     "%.3g" % regress._mad(vals, med), "%.4g" % min(vals),
                     "%.4g" % max(vals), "%.4g" % vals[-1]))
    widths = [max(len(header[i]), *(len(r[i]) for r in rows)) if rows
              else len(header[i]) for i in range(len(header))]
    line = "  ".join(h.ljust(w) for h, w in zip(header, widths))
    print(line)
    print("-" * len(line))
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    print("\n%d records in %s" % (len(records), path or "ledger"))
    return 0


def explain_ledger(path=None):
    """--explain: stepstats attribution of the newest record (stored at
    record time when present; otherwise computed from the live registry)."""
    from paddle_tpu.monitor import runlog, stepstats

    records = runlog.read_ledger(path)
    if not records:
        print("perf_gate --explain: ledger is empty", file=sys.stderr)
        return 2
    head = records[-1]
    print("run %s (%s):" % (head.get("run_id"), head.get("kind")))
    stored = (head.get("extra") or {}).get("stepstats") or {}
    if stored:
        for config, breakdown in sorted(stored.items()):
            print(stepstats.render(breakdown, config=config))
    else:
        print(stepstats.render(stepstats.decompose(), config="live"))
    return 0


# -- selftest -----------------------------------------------------------------

def _synthetic_record(config, metrics, seq):
    from paddle_tpu.monitor.runlog import RUN_SCHEMA

    return {"schema": RUN_SCHEMA, "run_id": "rsynthetic-%d" % seq,
            "t": float(seq), "kind": "perf_gate",
            "configs": {config: metrics}}


def selftest() -> int:
    import tempfile

    from paddle_tpu.monitor import metrics as mx
    from paddle_tpu.monitor import regress, runlog, stepstats

    t0 = time.time()
    mx.enable()
    mx.reset()
    saved = {k: os.environ.get(k) for k in
             ("PADDLE_TPU_RUN_LEDGER", "PADDLE_TPU_RUN_LEDGER_ROTATE",
              "PADDLE_TPU_RUN_LEDGER_KEEP")}
    with tempfile.TemporaryDirectory() as td:
        try:
            # 1. ledger discipline: rotate every 3 records, keep 2 files
            lpath = os.path.join(td, "ledger.jsonl")
            os.environ["PADDLE_TPU_RUN_LEDGER"] = lpath
            os.environ["PADDLE_TPU_RUN_LEDGER_ROTATE"] = "3"
            os.environ["PADDLE_TPU_RUN_LEDGER_KEEP"] = "2"
            runlog._ledger = None  # fresh ledger for the overridden knobs
            first = runlog.record_run("perf_gate",
                                      {"probe": {"step_ms_p50": 10.0}})
            assert first["ledger_path"] == lpath, first["ledger_path"]
            for i in range(7):
                runlog.record_run("perf_gate",
                                  {"probe": {"step_ms_p50": 10.0 + i}})
            back = runlog.read_ledger(lpath)
            # 8 appends, rotate@3 keep@2: shard(3) + live(2) survive
            assert len(back) == 5, len(back)
            assert mx.snapshot()["runlog/rotations"]["value"] >= 2
            assert os.path.exists(lpath + ".2")
            # provenance round-trip on the first (full) record
            prov = first["provenance"]
            assert first["run_id"] == runlog.run_id()
            assert "sha" in prov["git"] and "device_kind" in prov
            assert "opt_level" in prov and "jax" in prov
            assert prov["env"].get("PADDLE_TPU_RUN_LEDGER") == lpath
            assert back[-1]["configs"]["probe"]["step_ms_p50"] == 16.0
            assert back[-1]["provenance"]["device_kind"] == \
                prov["device_kind"]

            # 2. torn tail + foreign schema lines are skipped, not fatal
            with open(lpath, "a") as f:
                f.write('{"schema": "other/v9", "configs": {}}\n')
                f.write('{"schema": "paddle_tpu.runlog/v1", "tor')
            assert len(runlog.read_ledger(lpath)) == 5

            # 3. injected 1.3x step-time regression -> --check exits 1
            #    naming the (config, metric)
            rpath = os.path.join(td, "regress.jsonl")
            led = runlog.RunLedger(rpath, rotate_records=1000)
            base = [10.0, 10.2, 9.8, 10.1, 9.9, 10.0, 10.3, 9.7]
            for i, v in enumerate(base):
                led.append(_synthetic_record(
                    "synthetic", {"step_ms_p50": v}, i))
            led.append(_synthetic_record(
                "synthetic", {"step_ms_p50": 13.0}, 99))
            rc, verdicts = check_ledger(rpath, quiet=True)
            assert rc == 1, rc
            bad = [v for v in verdicts if v.verdict == regress.REGRESSED]
            assert len(bad) == 1 and bad[0].config == "synthetic" \
                and bad[0].metric == "step_ms_p50", [v.to_doc()
                                                    for v in verdicts]
            assert bad[0].n_baseline == 8 and "synthetic" in bad[0].describe()
            assert mx.snapshot()["perf/regressions"]["value"] >= 1

            # throughput direction: eps DOWN 1.3x must also regress
            epath = os.path.join(td, "eps.jsonl")
            led = runlog.RunLedger(epath, rotate_records=1000)
            for i, v in enumerate(base):
                led.append(_synthetic_record(
                    "synthetic", {"examples_per_sec": 100 * v}, i))
            led.append(_synthetic_record(
                "synthetic", {"examples_per_sec": 770.0}, 99))
            rc, verdicts = check_ledger(epath, quiet=True)
            assert rc == 1 and verdicts[0].verdict == regress.REGRESSED

            # 4. seeded noisy-but-flat series stays NEUTRAL (exit 0)
            npath = os.path.join(td, "noisy.jsonl")
            led = runlog.RunLedger(npath, rotate_records=1000)
            noisy = [9.6, 10.4, 9.8, 10.2, 10.0, 9.7, 10.3, 10.1]
            for i, v in enumerate(noisy):
                led.append(_synthetic_record("noisy", {"step_ms_p50": v}, i))
            led.append(_synthetic_record("noisy", {"step_ms_p50": 10.05}, 99))
            rc, verdicts = check_ledger(npath, quiet=True)
            assert rc == 0 and verdicts[0].verdict == regress.NEUTRAL, \
                [v.to_doc() for v in verdicts]

            # 5. min-sample gating: a 3-sample ledger cannot call a
            #    regression — INSUFFICIENT_DATA, exit 0
            spath = os.path.join(td, "small.jsonl")
            led = runlog.RunLedger(spath, rotate_records=1000)
            for i, v in enumerate([10.0, 10.1, 9.9]):
                led.append(_synthetic_record("small", {"step_ms_p50": v}, i))
            led.append(_synthetic_record("small", {"step_ms_p50": 13.0}, 99))
            rc, verdicts = check_ledger(spath, quiet=True)
            assert rc == 0 and verdicts[0].verdict == \
                regress.INSUFFICIENT_DATA, [v.to_doc() for v in verdicts]

            # 6. decomposition: a deliberately feed-starved probe is
            #    input-bound with the feed wait dominant
            mx.reset()
            configs, breakdowns = run_probe(steps=6, starve_ms=8.0)
            (config, bd), = breakdowns.items()
            assert config.endswith("_starved"), config
            assert bd["bound"] == "input" and bd["dominant"] == "input_ms", bd
            assert bd["terms"]["input_ms"] >= 7.0, bd
            assert "prefetch" in bd["hint"] or "feed" in bd["hint"]
            # and the un-starved probe is NOT input-bound
            mx.reset()
            _, breakdowns = run_probe(steps=6)
            (_, bd2), = breakdowns.items()
            assert bd2["bound"] != "input", bd2

            # 7. --report and --explain render without raising
            report_ledger(rpath)
            runlog._ledger = None
            os.environ["PADDLE_TPU_RUN_LEDGER"] = lpath
            record_probes(steps=4)
            assert explain_ledger(lpath) == 0
            assert stepstats.render(bd).splitlines()[0].endswith(
                "(dominant: input_ms)")
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            runlog._ledger = None
    dt = time.time() - t0
    assert dt < 5.0, "selftest too slow: %.1fs" % dt
    print("perf_gate selftest: OK (%.1fs): ledger fsync/rotate/torn-tail + "
          "provenance round-trip, 1.3x regression drill exits 1, noisy-flat "
          "NEUTRAL, 3-sample INSUFFICIENT_DATA, starved probe input-bound"
          % dt)
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0
    if "--selftest" in argv:
        return selftest()

    def opt(name, default=None):
        if name in argv:
            i = argv.index(name)
            if i + 1 >= len(argv):
                print("%s requires a value" % name, file=sys.stderr)
                raise SystemExit(2)
            argv.pop(i)
            return argv.pop(i)
        return default

    ledger = opt("--ledger")
    if ledger:
        os.environ["PADDLE_TPU_RUN_LEDGER"] = ledger
    steps = int(opt("--steps", "24"))
    rel_threshold = float(opt("--rel-threshold", "0.10"))
    min_samples = int(opt("--min-samples", "4"))
    window = int(opt("--window", "20"))
    modes = [a for a in argv if a in ("--record", "--check", "--report",
                                     "--explain")]
    unknown = [a for a in argv if a not in modes]
    if unknown:
        print("unknown arguments: %s" % " ".join(unknown), file=sys.stderr)
        return 2
    if not modes:
        print("pick one of --record / --check / --report / --explain / "
              "--selftest", file=sys.stderr)
        return 2
    rc = 0
    for mode in modes:
        if mode == "--record":
            record_probes(steps=steps)
        elif mode == "--check":
            code, _ = check_ledger(ledger, rel_threshold=rel_threshold,
                                   min_samples=min_samples, window=window)
            rc = max(rc, code)
        elif mode == "--report":
            rc = max(rc, report_ledger(ledger))
        elif mode == "--explain":
            rc = max(rc, explain_ledger(ledger))
    return rc


if __name__ == "__main__":
    sys.exit(main())
