"""Inspect paddle_tpu.monitor artifacts from the command line.

The tools/timeline.py of this stack, plus a metrics pretty-printer:

    python -m tools.dump_metrics snapshot.json
        Pretty-print a metrics snapshot (the ``monitor.to_json()`` /
        bench-JSON ``metrics`` format) as an aligned table.

    python -m tools.dump_metrics --to-chrome spans.json trace.json
        Convert a raw host-span file (``monitor.tracer.save_spans``) to a
        chrome://tracing / Perfetto-loadable Chrome trace. Accepts an
        existing Chrome trace too (idempotent), so the conversion
        round-trips.

    python -m tools.dump_metrics --watch <interval_s>
        Tail the LIVE in-process registry as interval deltas: every tick
        print counters that moved (as +delta and rate/s), gauges that
        changed, and histogram activity. Ctrl-C exits. (Most useful from
        code: ``from tools.dump_metrics import watch; watch(1.0)`` in a
        thread next to a running engine — a separate process sees its own
        registry, so there it tails a telemetry ring dir instead:
        ``--watch <interval_s> <PADDLE_TPU_TELEMETRY_DIR>``.) Multiple
        dirs — ``--watch 1 dir1 dir2`` or ``dir1,dir2`` — tail N rings
        into one merged view (lines labeled by source dir); a fleet
        ``telemetry_base`` holding ``replica_*/`` subdirs expands to all
        of its replicas' rings.

    python -m tools.dump_metrics --selftest
        Exercise registry + tracer + the Chrome-trace round-trip +
        telemetry ring write/rotate/read-back + SLO counters in-process
        and exit 0/1. Needs no TPU (run under ``JAX_PLATFORMS=cpu``); the
        CI smoke check.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from paddle_tpu.monitor import metrics, tracer  # noqa: E402


def format_snapshot(snap: dict) -> str:
    """Aligned table for a ``monitor.snapshot()``-format dict."""
    lines = ["%-40s %-9s %s" % ("metric", "type", "value"),
             "-" * 72]
    for name in sorted(snap):
        s = snap[name]
        t = s.get("type", "?")
        if t == "histogram":
            detail = ("count=%d mean=%.3f p50=%.3f p95=%.3f p99=%.3f "
                      "min=%.3f max=%.3f"
                      % (s.get("count", 0), s.get("mean", 0.0),
                         s.get("p50", 0.0), s.get("p95", 0.0),
                         s.get("p99", 0.0),
                         s.get("min", 0.0), s.get("max", 0.0)))
        else:
            v = s.get("value", 0)
            detail = ("%d" % v) if float(v).is_integer() else ("%.6g" % v)
        lines.append("%-40s %-9s %s" % (name, t, detail))
    return "\n".join(lines)


def dump_snapshot(path: str) -> int:
    with open(path) as f:
        doc = json.load(f)
    # accept a whole bench JSON ({"detail": ..., "metrics": {...}}) too
    if "metrics" in doc and all(
            not isinstance(v, dict) or "type" not in v for v in doc.values()):
        doc = doc["metrics"]
    print(format_snapshot(doc))
    return 0


def to_chrome(src: str, dst: str) -> int:
    spans = tracer.load_spans(src)
    tracer.save_chrome_trace(dst, spans)
    print("wrote %d span(s) -> %s" % (len(spans), dst))
    return 0


def _delta_lines(sample) -> list:
    """One human line per instrument that moved this interval (the
    exporter's own ``telemetry/*`` bookkeeping is excluded — every tick
    moves it, which would bury real deltas and make idle look busy)."""
    lines = []
    for name, d in sorted(sample.deltas.get("counters", {}).items()):
        if name.startswith("telemetry/"):
            continue
        lines.append("%-44s +%-10g %8.2f/s"
                     % (name, d, d / sample.dt_s if sample.dt_s else 0.0))
    for name, v in sorted(sample.deltas.get("gauges", {}).items()):
        if name.startswith("telemetry/"):
            continue
        lines.append("%-44s -> %g" % (name, v))
    for name, h in sorted(sample.deltas.get("histograms", {}).items()):
        p99 = sample.histogram_interval_percentile(name, 99) or 0.0
        lines.append("%-44s n=%-6d mean=%.3f p99=%.3f"
                     % (name, h["count"],
                        (h["sum"] / h["count"]) if h["count"] else 0.0, p99))
    return lines


def _expand_watch_dirs(telemetry_dir) -> list:
    """Normalize the --watch dir argument: a single dir, a comma-joined
    list, or a Python list — plus one level of fleet expansion: a dir
    containing ``replica_*/`` subdirs (the router's ``telemetry_base``)
    tails every replica's ring, merged — in NUMERIC replica order
    (replica_10 after replica_9, not between replica_1 and replica_2)."""
    from paddle_tpu.fleet.router import _replica_index

    if telemetry_dir is None:
        return []
    dirs = (list(telemetry_dir) if isinstance(telemetry_dir, (list, tuple))
            else [d for d in str(telemetry_dir).split(",") if d])
    out = []
    for d in dirs:
        subs = sorted(
            (name for name in
             (os.listdir(d) if os.path.isdir(d) else [])
             if name.startswith("replica_")
             and os.path.isdir(os.path.join(d, name))),
            key=_replica_index)
        out.extend([os.path.join(d, name) for name in subs] or [d])
    return out


def watch(interval_s: float, telemetry_dir=None,
          max_ticks: int = None) -> int:
    """Print interval deltas every ``interval_s``. With ``telemetry_dir``
    set, tail other processes' JSONL telemetry rings (exporter output
    dirs) instead of the local registry; otherwise run a private
    in-process exporter with no disk ring. ``telemetry_dir`` may be one
    dir, a comma-joined list ("dir1,dir2"), a Python list, or a fleet
    ``telemetry_base`` containing ``replica_*/`` subdirs — N rings tail
    into one merged view, each line group labeled by its source dir.
    ``max_ticks`` bounds the loop (tests); None = until KeyboardInterrupt.
    The ring tail re-parses the whole (bounded: rotate × keep samples)
    ring each interval and filters by per-(dir, writer) seq — simple over
    fast, this is an ops tool."""
    import time

    from paddle_tpu.monitor import telemetry
    from paddle_tpu.monitor.telemetry import TelemetrySample

    ticks = 0
    dirs = _expand_watch_dirs(telemetry_dir)
    try:
        if dirs:
            # track the monotone per-writer seq, NOT the list index: a
            # ring rotation prunes old files, shrinking the list without
            # un-publishing samples (index tracking would go blind for a
            # whole rotation's worth of samples after each prune). Keyed
            # (dir, pid): two replicas' rings never shadow each other.
            last_seq = {}
            label = len(dirs) > 1
            while max_ticks is None or ticks < max_ticks:
                for d in dirs:
                    try:
                        series = telemetry.read_series(d)
                    except Exception:
                        continue
                    for doc in series:
                        key = (d, doc.get("pid", 0))
                        if doc.get("seq", 0) <= last_seq.get(key, -1):
                            continue
                        last_seq[key] = doc.get("seq", 0)
                        sample = TelemetrySample(
                            doc.get("seq", 0), doc.get("t", 0.0),
                            doc.get("dt_s", 0.0), doc.get("metrics", {}),
                            doc.get("deltas", {}))
                        body = _delta_lines(sample)
                        src = (" [%s]" % os.path.basename(d.rstrip("/"))
                               if label else "")
                        print("-- seq %d (dt %.2fs)%s"
                              % (sample.seq, sample.dt_s, src))
                        for line in body:
                            print(line)
                ticks += 1
                time.sleep(interval_s)
            return 0
        exp = telemetry.TelemetryExporter(
            "", interval_s=interval_s, prometheus_file=False)
        exp.disabled = True  # live tail only — never writes a ring
        while max_ticks is None or ticks < max_ticks:
            time.sleep(interval_s)
            sample = exp.tick()
            body = _delta_lines(sample)
            print("-- %s (dt %.2fs)"
                  % (time.strftime("%H:%M:%S"), sample.dt_s))
            for line in (body or ["(no activity)"]):
                print(line)
            ticks += 1
    except KeyboardInterrupt:
        pass
    return 0


def validate_chrome_trace(doc: dict) -> None:
    """Raise AssertionError unless ``doc`` is a loadable Chrome trace."""
    assert isinstance(doc, dict) and "traceEvents" in doc, "missing traceEvents"
    assert isinstance(doc["traceEvents"], list), "traceEvents must be a list"
    for ev in doc["traceEvents"]:
        assert "ph" in ev and "pid" in ev, "event missing ph/pid: %r" % (ev,)
        if ev["ph"] == "X":
            assert {"name", "ts", "dur", "tid"} <= set(ev), \
                "complete event missing fields: %r" % (ev,)


def selftest() -> int:
    # 1. registry: counter/gauge/histogram + snapshot/reset
    metrics.enable()
    c = metrics.counter("selftest/count")
    c.inc(3)
    metrics.gauge("selftest/gauge").set(1.5)
    h = metrics.histogram("selftest/hist")
    for v in (0.2, 2.0, 40.0):
        h.observe(v)
    snap = metrics.snapshot()
    assert snap["selftest/count"]["value"] == 3
    assert snap["selftest/hist"]["count"] == 3
    assert "p95" in snap["selftest/hist"]
    assert "p99" in snap["selftest/hist"]
    assert "p99=" in format_snapshot(snap)  # table carries the P99 column
    # disabled = inert
    metrics.disable()
    c.inc(100)
    metrics.enable()
    assert c.value == 3
    # 2. tracer: nested spans -> raw file -> CLI conversion -> valid Chrome
    tracer.start_tracing()
    with tracer.span("selftest/outer"):
        with tracer.span("selftest/inner", args={"k": 1}):
            pass
    spans = tracer.stop_tracing()
    mine = [s for s in spans if s["name"].startswith("selftest/")]
    assert {s["name"] for s in mine} == {"selftest/outer", "selftest/inner"}
    inner = next(s for s in mine if s["name"] == "selftest/inner")
    outer = next(s for s in mine if s["name"] == "selftest/outer")
    assert inner["depth"] == outer["depth"] + 1, "span nesting lost"
    with tempfile.TemporaryDirectory() as td:
        raw = os.path.join(td, "spans.json")
        chrome = os.path.join(td, "trace.json")
        tracer.save_spans(raw, mine)
        to_chrome(raw, chrome)
        with open(chrome) as f:
            doc = json.load(f)
        validate_chrome_trace(doc)
        # round-trip: chrome trace back to spans, names/durations preserved
        back = tracer.load_spans(chrome)
        assert {s["name"] for s in back} == {s["name"] for s in mine}
        assert sorted(s["dur_us"] for s in back) == sorted(
            s["dur_us"] for s in mine)
    # 3. async pipeline: the compile-cache counter pair must exist and a
    #    tiny fused run_steps loop must execute + instrument (CPU, ~1s)
    import numpy as np

    import paddle_tpu as fluid

    snap = metrics.snapshot()
    assert "compile_cache/hit" in snap, "compile-cache counters not registered"
    assert "compile_cache/miss" in snap
    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main_prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main_prog, startup):
                x = fluid.layers.data("x", shape=[4])
                y = fluid.layers.data("y", shape=[1], dtype="int64")
                logits = fluid.layers.fc(x, size=2)
                loss = fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(logits, y))
                fluid.optimizer.SGD(0.1).minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            rng = np.random.RandomState(0)
            feeds = ({"x": rng.randn(2, 4).astype("float32"),
                      "y": rng.randint(0, 2, (2, 1)).astype("int64")}
                     for _ in range(4))
            rows = exe.run_steps(main_prog, feeds, steps=4,
                                 fetch_list=[loss], fetch_every=2)
            assert len(rows) == 4 and np.isfinite(rows[-1][0]).all()
            snap = metrics.snapshot()
            assert snap["executor/run_steps_dispatches"]["value"] == 2
            assert snap["executor/run_steps_steps"]["value"] == 4
            # 4a. device-profile gauges: prepare() AOT-compiles and must
            #     mirror the XLA cost/memory analyses into the gauges
            exe.prepare(main_prog,
                        feed={"x": ((2, 4), "float32"),
                              "y": ((2, 1), "int64")},
                        fetch_list=[loss])
            snap = metrics.snapshot()
            assert snap["device_profile/flops"]["value"] > 0, \
                "prepare() did not publish cost_analysis"
            assert snap["device_profile/peak_hbm_bytes"]["value"] > 0
    # 4b. numerics watchdog packed-mask path: PADDLE_TPU_CHECK_NUMERICS=2
    #     compiles the guarded step variant; a planted NaN must be
    #     attributed to the ORIGINATING op by <slot>:<type>, not a fetch
    from paddle_tpu.core.enforce import EnforceNotMet

    prev = os.environ.get("PADDLE_TPU_CHECK_NUMERICS")
    os.environ["PADDLE_TPU_CHECK_NUMERICS"] = "2"
    try:
        with fluid.unique_name.guard():
            with fluid.scope_guard(fluid.Scope()):
                m2, s2 = fluid.Program(), fluid.Program()
                with fluid.program_guard(m2, s2):
                    x = fluid.layers.data("x", shape=[4])
                    bad = fluid.layers.log(x)  # log(0) -> -inf at THIS op
                    out = fluid.layers.mean(bad)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(s2)
                try:
                    exe.run(m2, feed={"x": np.zeros((2, 4), "float32")},
                            fetch_list=[out])
                    raise AssertionError("watchdog missed the planted NaN")
                except EnforceNotMet as e:
                    assert ":log" in str(e), str(e)
    finally:
        if prev is None:
            os.environ.pop("PADDLE_TPU_CHECK_NUMERICS", None)
        else:
            os.environ["PADDLE_TPU_CHECK_NUMERICS"] = prev
    # 5. serving/* counters: the multiplexer's host-side bookkeeping
    #    (scheduler + page pool) must feed the registry; the full compiled
    #    prefill->decode->retire path has its own gate (tools/serve_bench
    #    --selftest)
    from paddle_tpu.serving import (PagePool, PagePoolExhausted, Request,
                                    Scheduler)

    metrics.reset()
    sched = Scheduler(n_slots=2, max_queue=4)
    pool = PagePool(num_pages=4, page_size=8)
    r1 = sched.submit(Request([1, 2, 3], max_new_tokens=4))
    r2 = sched.submit(Request([4, 5], max_new_tokens=2))
    r1.pages = pool.alloc(pool.pages_needed(3 + 4))
    sched.admit(0)
    try:
        pool.alloc(99)
        raise AssertionError("page pool did not backpressure")
    except PagePoolExhausted:
        sched.requeue_head_blocked()
    snap = metrics.snapshot()
    assert snap["serving/requests_submitted"]["value"] == 2
    assert snap["serving/requests_admitted"]["value"] == 1
    assert snap["serving/queue_depth"]["value"] == 1
    assert snap["serving/slot_occupancy"]["value"] == 1
    assert snap["serving/page_pool_pages_in_use"]["value"] == 1
    assert snap["serving/admission_blocked_on_pages"]["value"] == 1
    pool.free(r1.pages)
    sched.retire(0)
    snap = metrics.snapshot()
    assert snap["serving/requests_retired"]["value"] == 1
    assert snap["serving/slot_occupancy"]["value"] == 0
    assert snap["serving/page_pool_utilization"]["value"] == 0
    assert r2.state == "queued"  # blocked head stays FIFO-first
    metrics.reset()

    # 6. reliability instruments + the fault framework's registry feed:
    #    an armed plan firing must tick reliability/faults_injected (the
    #    full recovery drills have their own gate, tools/chaos_drill
    #    --selftest)
    from paddle_tpu.reliability import (FaultPlan, TransientFault, faults,
                                        run_supervised)  # noqa: F401
    # (run_supervised imported for its side effect: loading the supervisor
    # registers the reliability/preemptions|checkpoints|... instruments)

    with FaultPlan.parse("executor.compile@1=transient"):
        try:
            faults.fire("executor.compile")
            raise AssertionError("armed fault did not fire")
        except TransientFault:
            pass
    snap = metrics.snapshot()
    assert snap["reliability/faults_injected"]["value"] == 1
    for name in ("reliability/preemptions", "reliability/retries",
                 "reliability/checkpoints_written", "reliability/resumes",
                 "reliability/feed_errors",
                 "serving/faults", "serving/retries", "serving/timeouts",
                 "serving/requests_failed", "serving/drains",
                 "serving/drained_requests", "serving/drain_rejected",
                 "serving/spec_proposed_tokens",
                 "serving/spec_accepted_tokens",
                 "serving/spec_rejected_tokens", "serving/spec_drafts",
                 "serving/spec_verify_dispatches",
                 "serving/spec_accept_rate"):
        assert name in snap, "missing instrument %s" % name
    metrics.reset()

    # 6b. data/* + sentinel/* registries: the ingestion pipeline's counters
    #     must feed the registry from a real (tiny) reader pass — one good
    #     record, one corrupt, one quarantine-skip on the second epoch —
    #     and loading the sentinel registers its trip/rollback instruments
    #     (the full self-heal/exactly-once recovery drills have their own
    #     gate, tools/chaos_drill --selftest)
    import numpy as np

    from paddle_tpu import data as pdata
    from paddle_tpu.reliability import sentinel as _sentinel  # noqa: F401

    metrics.reset()
    with tempfile.TemporaryDirectory() as td:
        shard = os.path.join(td, "rows.txt")
        with open(shard, "w") as f:
            f.write("1.0 2.0\nbad record\n3.0 4.0\n")
        qfile = os.path.join(td, "quarantine.jsonl")

        def parse(line):
            vals = [float(t) for t in line.split()]
            return {"x": np.asarray(vals, np.float32)}

        reader = pdata.CheckpointableReader(
            [shard], parse, batch_size=2,
            schema=[pdata.FieldSpec("x", (2,), np.float32)],
            epochs=2, quarantine_path=qfile,
            max_corrupt_rate=0.9, corrupt_check_min=1)
        batches = list(reader)
        assert len(batches) == 2 and batches[0]["x"].shape == (2, 2)
        qrows = [json.loads(ln) for ln in open(qfile)]
        assert len(qrows) == 1 and qrows[0]["id"] == "rows.txt#1", qrows
        assert "parse" in qrows[0]["reason"]
        snap = metrics.snapshot()
        assert snap["data/records_read"]["value"] == 4
        assert snap["data/records_corrupt"]["value"] == 1
        assert snap["data/records_quarantined"]["value"] == 1
        assert snap["data/records_skipped"]["value"] == 1  # epoch-2 skip
        assert snap["data/batches"]["value"] == 2
        assert snap["data/epochs_completed"]["value"] == 2
        assert snap["data/bytes_read"]["value"] > 0
        for name in ("data/prefetch_depth", "data/prefetch_wait_ms",
                     "sentinel/trips", "sentinel/rollbacks",
                     "sentinel/records_quarantined", "sentinel/lr_backoffs",
                     "sentinel/fatals", "sentinel/trips_nan",
                     "sentinel/trips_spike", "sentinel/trips_plateau",
                     "sentinel/trips_grad_norm", "sentinel/trips_drift"):
            assert name in snap, "missing instrument %s" % name
    metrics.reset()

    # 6c. numerics/* registry: the streaming-stats layer must feed per-op
    #     gauges, the chunks counter and the LOG-BUCKETED absmax histogram
    #     from a real armed step, render through the table/Prometheus/
    #     --watch formatters, and leave zero registry residue when off
    from paddle_tpu.monitor import numerics as _numerics
    from paddle_tpu.monitor import telemetry as _tele

    metrics.reset()
    _numerics.reset()
    prev_num = os.environ.get("PADDLE_TPU_NUMERICS")
    os.environ["PADDLE_TPU_NUMERICS"] = "1"
    try:
        exp = _tele.TelemetryExporter("", interval_s=999.0,
                                      prometheus_file=False)
        exp.disabled = True
        exp.tick()  # baseline so the next tick's deltas cover the run
        with fluid.unique_name.guard():
            with fluid.scope_guard(fluid.Scope()):
                m3, s3 = fluid.Program(), fluid.Program()
                with fluid.program_guard(m3, s3):
                    x = fluid.layers.data("x", shape=[4])
                    h3 = fluid.layers.fc(x, size=4, act="relu")
                    out3 = fluid.layers.mean(h3)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(s3)
                exe.run(m3, feed={"x": np.ones((2, 4), "float32")},
                        fetch_list=[out3])
        snap = metrics.snapshot()
        assert snap["numerics/chunks"]["value"] >= 1, "no stats chunk landed"
        assert any(k.startswith("numerics/") and k.endswith("/absmax")
                   for k in snap), "per-op numerics gauges missing"
        hsnap = snap["numerics/absmax"]
        assert hsnap["type"] == "histogram" and hsnap["count"] >= 1
        assert "le_1e-08" in hsnap["buckets"], "log buckets missing"
        assert _numerics.snapshot(), "host per-op registry empty"
        # the log-bucketed histogram must survive every renderer
        assert "numerics/absmax" in format_snapshot(snap)
        assert 'numerics_absmax_bucket{le="1e-08"}' in metrics.to_prometheus()
        sample = exp.tick()
        assert any(line.startswith("numerics/absmax")
                   for line in _delta_lines(sample)), \
            "--watch formatter dropped the log-bucketed histogram"
        exp.stop()
    finally:
        if prev_num is None:
            os.environ.pop("PADDLE_TPU_NUMERICS", None)
        else:
            os.environ["PADDLE_TPU_NUMERICS"] = prev_num
    _numerics.reset()
    metrics.reset()

    # 7. continuous telemetry: JSONL ring write/rotate/read-back, interval
    #    deltas, the watch formatter, Prometheus rendering, and the slo/*
    #    counters breaching + clearing on synthetic ticks
    from paddle_tpu.monitor import slo, telemetry

    metrics.reset()
    with tempfile.TemporaryDirectory() as td:
        exp = telemetry.TelemetryExporter(td, interval_s=999.0,
                                          rotate_samples=2, keep_files=2)
        h = metrics.histogram("selftest/lat_ms")
        mon = slo.SLOMonitor([slo.SLO("selftest/lat_ms", p=99, max_ms=10.0)])
        exp.add_listener(mon.on_sample)
        for i in range(5):
            h.observe(100.0 if i < 2 else 1.0)  # breach 2 ticks, then clear
            sample = exp.tick()
            assert sample.histogram_delta("selftest/lat_ms")["count"] == 1
            assert _delta_lines(sample)  # the --watch formatter must render
        exp.stop()  # final flush = one more (empty-delta) sample
        series = telemetry.read_series(td, pid=os.getpid())
        assert len(series) >= 2, "ring rotation lost everything: %d" % len(series)
        assert all(s["schema"] == telemetry.SAMPLE_SCHEMA for s in series)
        seqs = [s["seq"] for s in series]
        assert seqs == sorted(seqs) and seqs[-1] == 6, seqs
        files = [f for f in os.listdir(td) if f.endswith(".jsonl")]
        assert len(files) <= 2, "rotation did not prune: %s" % files
        assert os.path.exists(os.path.join(td, "metrics.prom"))
        snap = metrics.snapshot()
        assert snap["slo/breaches"]["value"] == 2, snap["slo/breaches"]
        assert snap["telemetry/samples"]["value"] == 6
        assert snap["telemetry/rotations"]["value"] >= 1
        assert "slo/selftest/lat_ms:p99/breaches" in snap
    # prometheus exposition must carry the histogram triplet, sanitized
    prom = metrics.to_prometheus()
    assert "selftest_lat_ms_bucket{le=\"+Inf\"}" in prom, prom[-400:]
    assert "selftest_lat_ms_count 5" in prom
    assert "selftest_lat_ms_sum" in prom
    metrics.reset()

    # 8. autotune/* counters + the tuned-config lookup ladder (the sweep
    #    mechanism has its own gate, tools/autotune --selftest). Point the
    #    runtime table at a guaranteed-absent file so a developer's own
    #    tuned table can't change what this CI assertion sees.
    from paddle_tpu import tune

    prev_tbl = os.environ.get("PADDLE_TPU_TUNE_TABLE")
    with tempfile.TemporaryDirectory() as td:
        os.environ["PADDLE_TPU_TUNE_TABLE"] = os.path.join(td, "none.json")
        try:
            cfg, src = tune.lookup("flash_attention",
                                   tune.bucket_seq(8192, 8192),
                                   device="tpu-v5e")
            assert src == "shipped" and cfg["block_q"] == 512, (cfg, src)
            cfg, src = tune.lookup("sparse_adam", tune.bucket_rows(1024, 64),
                                   device="tpu-v5e")
            assert src == "shipped" and cfg["block"] == 128, (cfg, src)
            cfg, src = tune.lookup("flash_attention",
                                   tune.bucket_seq(128, 128),
                                   device="made-up-chip")
            assert cfg is None and src == "default"
        finally:
            if prev_tbl is None:
                os.environ.pop("PADDLE_TPU_TUNE_TABLE", None)
            else:
                os.environ["PADDLE_TPU_TUNE_TABLE"] = prev_tbl
    snap = metrics.snapshot()
    assert snap["autotune/lookups"]["value"] >= 3
    assert snap["autotune/lookup_shipped"]["value"] >= 2
    assert snap["autotune/lookup_default"]["value"] >= 1
    for name in ("autotune/sweeps", "autotune/candidates_timed",
                 "autotune/candidates_pruned", "autotune/candidates_failed",
                 "autotune/table_writes", "autotune/table_errors",
                 "autotune/measure_ms"):
        assert name in snap, "missing instrument %s" % name
    metrics.reset()

    # 9. fleet/* registry + multi-dir watch aggregation: importing the
    #    fleet metrics module must register the full router + prefix-cache
    #    instrument set, and --watch must merge N replica ring dirs with
    #    per-(dir, pid) cursors (the fleet's N-replica tail view)
    import contextlib
    import io

    import paddle_tpu.fleet.metrics  # noqa: F401  (registers fleet/*)

    snap = metrics.snapshot()
    for name in ("fleet/submitted", "fleet/routed", "fleet/requeued",
                 "fleet/completed", "fleet/rejected",
                 "fleet/duplicate_results", "fleet/queue_depth",
                 "fleet/replicas_alive", "fleet/replica_restarts",
                 "fleet/rolling_restarts", "fleet/no_healthy_replica",
                 "fleet/rerouted",
                 "fleet/prefix_cache/hits", "fleet/prefix_cache/misses",
                 "fleet/prefix_cache/inserts",
                 "fleet/prefix_cache/evictions",
                 "fleet/prefix_cache/entries",
                 "fleet/prefix_cache/pages_held",
                 "fleet/prefix_cache/tokens_reused",
                 "fleet/prefix_cache/poisoned_skipped",
                 "fleet/migrations_started", "fleet/migrations_completed",
                 "fleet/migrations_failed", "fleet/migrated_pages",
                 "fleet/migration_ms",
                 "fleet/prefix_cache/remote_hits",
                 "fleet/prefix_cache/remote_misses",
                 "fleet/prefix_cache/remote_ships"):
        assert name in snap, "missing fleet instrument %s" % name
    with tempfile.TemporaryDirectory() as td:
        base = os.path.join(td, "fleet")
        for i in range(2):
            d = os.path.join(base, "replica_%d" % i)
            os.makedirs(d)
            exp = telemetry.TelemetryExporter(d, interval_s=999.0)
            metrics.counter("selftest/fleet_tick").inc(i + 1)
            exp.tick()
            exp.stop()
        assert _expand_watch_dirs(base) == [
            os.path.join(base, "replica_0"), os.path.join(base, "replica_1")]
        # numeric, not lexicographic: replica_10 tails AFTER replica_2
        for i in (2, 10):
            os.makedirs(os.path.join(base, "replica_%d" % i))
        assert _expand_watch_dirs(base) == [
            os.path.join(base, "replica_%d" % i) for i in (0, 1, 2, 10)]
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            watch(0.0, base, max_ticks=1)
        out = buf.getvalue()
        assert "[replica_0]" in out and "[replica_1]" in out, out
    metrics.reset()
    print("dump_metrics selftest: OK")
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0 if argv else 2
    if argv[0] == "--selftest":
        return selftest()
    if argv[0] == "--to-chrome":
        if len(argv) != 3:
            print("usage: dump_metrics --to-chrome spans.json trace.json",
                  file=sys.stderr)
            return 2
        return to_chrome(argv[1], argv[2])
    if argv[0] == "--watch":
        if len(argv) < 2:
            print("usage: dump_metrics --watch <interval_s> "
                  "[telemetry_dir ...]", file=sys.stderr)
            return 2
        return watch(float(argv[1]), argv[2:] if len(argv) > 2 else None)
    if len(argv) != 1:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    return dump_snapshot(argv[0])


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # e.g. piped into head
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
