"""Render and gate the paddle_tpu.monitor.numerics observatory.

The CLI face of the streaming tensor-statistics layer
(``PADDLE_TPU_NUMERICS``, paddle_tpu/monitor/numerics.py): per-op range
stats, drift early-warnings, and the persisted amax/scale calibration
tables the int8 KV-page path is gated behind.

    python -m tools.numerics_report --selftest
        <5s, JAX_PLATFORMS=cpu — the ROADMAP/ci_smokes gate:
        (1) armed-stats parity: per-op absmax/mean/rms/zero-fraction from
            the packed device-side fetch match a numpy reference computed
            from the SAME step's fetched tensors on a canned MLP;
        (2) drift drill: an injected activation-scale ramp raises the
            typed :class:`NumericsDriftWarning` (and the
            ``numerics_drift`` flight event naming the ``<slot>:<type>``
            op) at least 2 chunks BEFORE the CHECK_NUMERICS=2 watchdog
            trips on the same ramp;
        (3) calibration round-trip: record/lookup amax+scale through the
            tune-table discipline (atomic publish, running max merge,
            corrupt-table lookups degrade to None, never raise);
        (4) int8 KV decode parity: quantized pages decode within the
            symmetric-int8 tolerance of fp pages at ragged lengths, and
            2x the pages fit under the fp byte budget (the capacity win
            serve_bench asserts end-to-end).

    python -m tools.numerics_report --probe
        Run a tiny armed MLP step in-process and print the per-op stats
        table (what an armed trainer's registries look like).

    python -m tools.numerics_report --table [PATH]
        Render the calibration table at PATH (default: the active
        ``numerics.table_path()`` location).

    python -m tools.numerics_report --flight DUMP.json
        Render the ``numerics_last`` section of a flight-recorder dump —
        the per-op range history embedded next to a NaN trip.
"""

from __future__ import annotations

import json
import math
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

_COLS = ("absmax", "mean", "rms", "zero_frac", "subnormal_frac",
         "overflow_frac", "count", "chunks")


def render_stats(snap: dict) -> str:
    """Fixed-width per-op table of a ``numerics.snapshot()`` dict (also
    accepts the ``numerics_last`` section of a flight dump)."""
    if not snap:
        return "(no numerics stats accumulated — is PADDLE_TPU_NUMERICS " \
               "armed?)"
    rows = [("op",) + _COLS]
    for label in sorted(snap, key=lambda s: (len(s.split(":")[0]), s)):
        st = snap[label]
        rows.append((label,) + tuple(
            "%.4g" % st[c] if isinstance(st.get(c), float)
            else str(st.get(c, "-")) for c in _COLS))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    return "\n".join("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
                     for r in rows)


def render_table(path=None) -> str:
    """Render the calibration table: one line per (fingerprint, slot,
    type) with amax / scale / bits."""
    from paddle_tpu.monitor import numerics

    path = path or numerics.table_path()
    if not path:
        return "(no calibration table configured: set " \
               "PADDLE_TPU_NUMERICS_TABLE or PADDLE_TPU_COMPILE_CACHE)"
    entries = numerics.read_calibration(path)
    if not entries:
        return "%s: absent, corrupt or empty" % path
    lines = ["calibration table %s (%d entries):" % (path, len(entries))]
    for key in sorted(entries):
        cfg = entries[key].get("config", {})
        lines.append("  %-48s amax=%-12.6g scale=%-12.6g bits=%s"
                     % (key, cfg.get("amax", float("nan")),
                        cfg.get("scale", float("nan")), cfg.get("bits", "?")))
    return "\n".join(lines)


def _probe_once(scale_pow: float = 0.0):
    """One armed MLP train step; returns (numerics snapshot, {var name:
    fetched numpy array}) — the parity leg's two sides come from the SAME
    dispatch, so there is nothing scheduling-dependent to tolerate."""
    import numpy as np

    import paddle_tpu as fluid

    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("x", shape=[8])
                h = fluid.layers.fc(x, size=8, act="relu")
                out = fluid.layers.mean(h)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            rng = np.random.RandomState(0)
            feed = {"x": (2.0 ** scale_pow
                          * rng.randn(4, 8)).astype("float32")}
            fetched = exe.run(main, feed=feed,
                              fetch_list=[out.name, h.name])
            from paddle_tpu.monitor import numerics

            return numerics.snapshot(), dict(zip((out.name, h.name), fetched))


def probe() -> int:
    os.environ.setdefault("PADDLE_TPU_NUMERICS", "1")
    snap, _ = _probe_once()
    print(render_stats(snap))
    return 0


# -- selftest ------------------------------------------------------------------


def _np_reference(arr):
    """The numpy twin of one packed stat row's derived fields."""
    import numpy as np

    a = np.asarray(arr, np.float64)
    av = np.abs(a)
    return {
        "absmax": float(av.max()),
        "mean": float(a.mean()),
        "rms": float(np.sqrt((a * a).mean())),
        "zero_frac": float((a == 0).mean()),
    }


def _selftest_parity():
    """Device-side packed stats == numpy reference on the fetched tensors
    of the same canned MLP step."""
    from paddle_tpu.monitor import numerics

    numerics.reset()
    snap, fetched = _probe_once()
    assert snap, "armed step accumulated no stats"
    relu = [l for l in snap if l.endswith(":relu")]
    assert len(relu) == 1, "expected one relu entry, got %r" % (sorted(snap),)
    got = snap[relu[0]]
    h = next(v for v in fetched.values() if v.size > 1)
    want = _np_reference(h)
    for fld, ref in want.items():
        assert math.isclose(got[fld], ref, rel_tol=1e-5, abs_tol=1e-7), (
            "stats parity: %s %s=%.8g, numpy reference %.8g"
            % (relu[0], fld, got[fld], ref))
    assert got["count"] == h.size, (got["count"], h.size)
    mean = [l for l in snap if l.endswith(":mean")]
    assert len(mean) == 1
    loss = next(v for v in fetched.values() if v.size == 1)
    assert math.isclose(snap[mean[0]]["absmax"], abs(float(loss)),
                        rel_tol=1e-5), "mean-op absmax != fetched loss"
    return len(snap)


def _selftest_drift(tmp):
    """The acceptance drill: an activation-scale ramp raises the typed
    drift warning (flight event carries the named op) >= 2 chunks before
    the CHECK_NUMERICS=2 watchdog trips on the same ramp."""
    import warnings

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.core.enforce import EnforceNotMet
    from paddle_tpu.monitor import device as dev, numerics

    numerics.reset()
    os.environ["PADDLE_TPU_CHECK_NUMERICS"] = "2"
    os.environ["PADDLE_TPU_FLIGHT_DIR"] = tmp
    try:
        with fluid.unique_name.guard():
            with fluid.scope_guard(fluid.Scope()):
                main, startup = fluid.Program(), fluid.Program()
                with fluid.program_guard(main, startup):
                    x = fluid.layers.data("x", shape=[4])
                    h = fluid.layers.scale(x, scale=2.0)
                    out = fluid.layers.mean(h)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                warn_chunk = trip_chunk = None
                events = []
                with warnings.catch_warnings(record=True) as wlog:
                    warnings.simplefilter("always")
                    for i in range(40):
                        feed = {"x": np.full((2, 4), 2.0 ** (16 * i),
                                             "float32")}
                        try:
                            exe.run(main, feed=feed, fetch_list=[out])
                        except EnforceNotMet:
                            trip_chunk = i
                            break
                        if warn_chunk is None and any(
                                isinstance(w.message,
                                           numerics.NumericsDriftWarning)
                                for w in wlog):
                            warn_chunk = i
                            events = numerics.drain_drift_events()
        assert warn_chunk is not None, "ramp never raised a drift warning"
        assert trip_chunk is not None, "ramp never tripped the watchdog"
        assert warn_chunk <= trip_chunk - 2, (
            "drift warning must lead the watchdog by >= 2 chunks: "
            "warned at %d, tripped at %d" % (warn_chunk, trip_chunk))
        scale_evs = [e for e in events if e["op"].endswith(":scale")]
        assert scale_evs, "no drift event named the scale op: %r" % events
        assert scale_evs[0]["kind"] == "trending-toward-overflow"
        # the same event landed in the flight ring with the named op
        fr = dev.flight_recorder()
        assert fr is not None
        ring = [e for e in fr._entries
                if e.get("event") == "numerics_drift"
                and e.get("op", "").endswith(":scale")]
        assert ring, "numerics_drift flight event missing the named op"
        assert ring[0]["drift_kind"] == "trending-toward-overflow"
        return warn_chunk, trip_chunk
    finally:
        os.environ.pop("PADDLE_TPU_CHECK_NUMERICS", None)
        os.environ.pop("PADDLE_TPU_FLIGHT_DIR", None)


def _selftest_calibration(tmp):
    """Round-trip + corruption tolerance of the calibration table."""
    from paddle_tpu.monitor import numerics

    path = os.path.join(tmp, "calib.json")
    assert numerics.lookup_amax("fp0", "3", "matmul", path=path) is None
    numerics.record_calibration("fp0", "3", "matmul", 7.5, path=path)
    got = numerics.lookup_amax("fp0", "3", "matmul", path=path)
    assert got == 7.5, got
    scale = numerics.lookup_scale("fp0", "3", "matmul", path=path)
    assert math.isclose(scale, 7.5 / 127.0), scale
    # merge is a running max: a smaller later amax must not shrink it
    numerics.record_calibration("fp0", "3", "matmul", 2.0, path=path)
    assert numerics.lookup_amax("fp0", "3", "matmul", path=path) == 7.5
    numerics.record_calibration("fp0", "3", "matmul", 9.0, path=path)
    assert numerics.lookup_amax("fp0", "3", "matmul", path=path) == 9.0
    # the KV pair helpers the serving int8 gate consults
    fp = numerics.kv_fingerprint(2, 4, 16, "float32")
    assert numerics.kv_scale(fp, path=path) is None
    numerics.record_kv_calibration(fp, 3.0, 4.0, path=path)
    ks, vs = numerics.kv_scale(fp, path=path)
    assert math.isclose(ks, 3.0 / 127.0) and math.isclose(vs, 4.0 / 127.0)
    # the report renderer covers every entry
    txt = render_table(path)
    assert "amax=9" in txt and str(len(
        numerics.read_calibration(path))) in txt
    # corruption: truncated JSON degrades every lookup to None, no raise
    with open(path, "w") as f:
        f.write('{"format": "paddle_tpu.numerics/1", "entr')
    assert numerics.lookup_amax("fp0", "3", "matmul", path=path) is None
    assert numerics.kv_scale(fp, path=path) is None
    # foreign format tag is corruption too (a tune table is NOT a
    # calibration table, even though the file machinery is shared)
    from paddle_tpu.tune import table as tbl

    tbl.write_entries(path, {tbl.entry_key("k", "b", "d"): {"config": {}}})
    assert numerics.read_calibration(path) is None


def _selftest_int8_kv():
    """Quantized pages decode within the symmetric-int8 tolerance of fp
    pages at ragged lengths; double the pages fit under the fp budget."""
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.serving.kv_cache import Int8PagedKVCache, PagedKVCache

    n_layer, n_head, d_head = 2, 2, 8
    slots, max_ctx, ps, npg = 2, 32, 8, 8
    amax = 3.0
    rng = np.random.RandomState(0)
    fp = PagedKVCache(n_layer, n_head, d_head, slots, max_ctx, ps, npg)
    q8 = Int8PagedKVCache(n_layer, n_head, d_head, slots, max_ctx, ps, npg,
                          k_scale=amax / 127.0, v_scale=amax / 127.0)
    sf, si = fp.init_state(), q8.init_state()
    pt = np.arange(slots * (max_ctx // ps), dtype=np.int32).reshape(
        slots, max_ctx // ps)
    sf = {**sf, "pt": jnp.array(pt)}
    si = {**si, "pt": jnp.array(pt)}
    lens = (13, 5)  # ragged, page-straddling
    for slot, plen in enumerate(lens):
        dest = jnp.array(pt[slot])
        for layer in range(n_layer):
            k = jnp.array(rng.uniform(-amax, amax, (plen, n_head, d_head)),
                          jnp.float32)
            v = jnp.array(rng.uniform(-amax, amax, (plen, n_head, d_head)),
                          jnp.float32)
            sf = fp.write_prompt(sf, layer, k, v, dest, jnp.int32(plen))
            si = q8.write_prompt(si, layer, k, v, dest, jnp.int32(plen))
    # per-element context error bounded by half a quantization step
    step_tol = amax / 127.0 * 0.51
    for layer in range(n_layer):
        kf, vf = fp.context(sf, layer)
        ki, vi = q8.context(si, layer)
        assert float(jnp.abs(kf - ki).max()) <= step_tol
        assert float(jnp.abs(vf - vi).max()) <= step_tol
    # decode parity within tolerance on BOTH paths (gather context above,
    # fused decode_attention here)
    q = jnp.array(rng.randn(slots, n_head, d_head), jnp.float32)
    ctx_len = jnp.array(lens, jnp.int32)
    of = fp.decode_attention(sf, 0, q, ctx_len, sm_scale=0.3)
    oi = q8.decode_attention(si, 0, q, ctx_len, sm_scale=0.3)
    err = float(jnp.abs(of - oi).max())
    assert err < 0.05, "int8 decode attention error %.4g" % err
    # the capacity win: int8 at 2x the pages still fits under the fp
    # byte budget (half the bf16 page bytes, a quarter of fp32)
    q8x2 = Int8PagedKVCache(n_layer, n_head, d_head, slots, max_ctx, ps,
                            2 * npg, k_scale=0.1, v_scale=0.1)
    fp_bytes = fp.cache_bytes(fp.init_state())
    i8x2_bytes = q8x2.cache_bytes(q8x2.init_state())
    assert i8x2_bytes <= fp_bytes, (i8x2_bytes, fp_bytes)
    assert q8x2.num_pages == 2 * fp.num_pages
    # uncalibrated scales are a hard constructor error (the gate that
    # keeps an uncalibrated grid from silently clipping)
    try:
        Int8PagedKVCache(n_layer, n_head, d_head, slots, max_ctx, ps, npg,
                         k_scale=0.0, v_scale=1.0)
        raise AssertionError("zero scale accepted")
    except ValueError:
        pass
    return err, i8x2_bytes, fp_bytes


def selftest() -> int:
    import tempfile
    import time

    t0 = time.time()
    os.environ["PADDLE_TPU_NUMERICS"] = "1"
    # The drills assert per-chunk behaviour (EMA ticks, parity over every
    # run) — disable the default every-4-chunks sampling cadence.
    os.environ["PADDLE_TPU_NUMERICS_EVERY"] = "1"
    os.environ.pop("PADDLE_TPU_NUMERICS_TABLE", None)
    try:
        n_ops = _selftest_parity()
        with tempfile.TemporaryDirectory(prefix="numerics_drift_") as tmp:
            warn_chunk, trip_chunk = _selftest_drift(tmp)
        with tempfile.TemporaryDirectory(prefix="numerics_calib_") as tmp:
            _selftest_calibration(tmp)
        err, i8x2, fpb = _selftest_int8_kv()
    finally:
        os.environ.pop("PADDLE_TPU_NUMERICS", None)
        os.environ.pop("PADDLE_TPU_NUMERICS_EVERY", None)
        from paddle_tpu.monitor import numerics

        numerics.reset()
    print("numerics_report selftest: OK (%.1fs)  stats parity over %d ops; "
          "drift warned chunk %d vs watchdog trip %d; calibration "
          "round-trip; int8 KV err %.4g with 2x pages %dB <= fp %dB"
          % (time.time() - t0, n_ops, warn_chunk, trip_chunk, err,
             i8x2, fpb))
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0 if argv else 2
    if argv[0] == "--selftest":
        return selftest()
    if argv[0] == "--probe":
        return probe()
    if argv[0] == "--table":
        print(render_table(argv[1] if len(argv) > 1 else None))
        return 0
    if argv[0] == "--flight":
        if len(argv) < 2:
            print("--flight needs a dump path", file=sys.stderr)
            return 2
        with open(argv[1]) as f:
            doc = json.load(f)
        snap = doc.get("numerics_last")
        if not snap:
            print("%s: no numerics_last section (dump written without "
                  "PADDLE_TPU_NUMERICS armed)" % argv[1])
            return 1
        print(render_stats(snap))
        return 0
    print("unknown flag %r" % argv[0], file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
