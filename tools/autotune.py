"""Measured autotuning CLI (paddle_tpu.tune).

    python -m tools.autotune --all [--reps K] [--table FILE] [--dry-run]
        Sweep every registered tunable (flash-attention BlockSizes,
        sparse-adam row blocks, softmax-xent tiles, per-program pass
        gates, serving decode_fuse) over its default shape points on the
        CURRENT backend, write the winners into the persistent config
        table (PADDLE_TPU_TUNE_TABLE, or autotune_table.json next to
        PADDLE_TPU_COMPILE_CACHE), and print a before/after table.

    python -m tools.autotune --kernel flash_attention
        Sweep one tunable (see --list for names).

    python -m tools.autotune --model DIR
        Pass-gate selection measured end-to-end on a saved inference
        model directory (io.save_inference_model layout).

    python -m tools.autotune --selftest
        <10s, CPU: table round-trip from a cold dir, determinism of the
        table produced from a fixed candidate list, corrupt-table
        fallback, shipped v5e seed lookup, real (interpret-mode)
        sparse-adam + paged-attention micro-sweeps, and the autotune/*
        counters. The CI smoke gate (ROADMAP).

On CPU the sweeps run the same code path as on TPU (Pallas interpret /
XLA:CPU timing) — mechanism numbers, not shipping numbers; run the same
commands on real hardware to populate the table with TPU medians.
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _fmt_ms(v):
    return "-" if v is None else ("%.3f" % v)


def print_results(results) -> None:
    """Human before/after table: one row per (kernel, shape) sweep."""
    from paddle_tpu import tune

    header = ("kernel", "shape", "bucket", "cands", "pruned",
              "default_ms", "best_ms", "speedup", "best_config")
    rows = []
    for res in results:
        n_pruned = sum(1 for r in res.rows if "pruned" in r)
        shape_lbl = ",".join("%s=%s" % (k, res.shape[k])
                             for k in sorted(res.shape)
                             if not isinstance(res.shape[k], (dict, list)))
        sp = res.speedup_vs_default
        rows.append((res.kernel, shape_lbl[:38], res.bucket,
                     str(len(res.rows)), str(n_pruned),
                     _fmt_ms(res.default_ms), _fmt_ms(res.best_ms),
                     "-" if sp is None else "%.2fx" % sp,
                     json.dumps(res.best, sort_keys=True)))
    widths = [max(len(header[i]), *(len(r[i]) for r in rows)) if rows
              else len(header[i]) for i in range(len(header))]
    line = "  ".join(h.ljust(w) for h, w in zip(header, widths))
    print(line)
    print("-" * len(line))
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    path = tune.table_path()
    written = [r.written_path for r in results if r.written_path]
    if written:
        print("\ntable: %s (%d entries written, device=%s)"
              % (written[-1], len(written), tune.device_kind()))
    elif path is None:
        print("\ntable: NOT WRITTEN — set PADDLE_TPU_TUNE_TABLE or "
              "PADDLE_TPU_COMPILE_CACHE to persist tuned configs")
    else:
        print("\ntable: %s (dry run — nothing written)" % path)


def run_sweeps(kernels, *, reps=5, warmup=1, persist=True, table_file=None,
               model_dir=None):
    from paddle_tpu import tune

    results, failures = [], []
    for name in kernels:
        t = tune.get_tunable(name)
        try:
            shapes = t.default_shapes()
            if name == "pass_gates" and model_dir:
                shapes = [dict(workload="model", model_dir=model_dir,
                               batch=16)]
            for shape in shapes:
                t0 = time.perf_counter()
                try:
                    res = tune.search(t, shape, reps=reps, warmup=warmup,
                                      persist=persist, table_file=table_file)
                except Exception as e:
                    # one broken tunable must not sink the report for the
                    # kernels that already swept (their entries ARE written)
                    failures.append((name, shape, e))
                    print("# SWEEP FAILED %s %r: %s: %s"
                          % (name, shape, type(e).__name__, e),
                          file=sys.stderr)
                    continue
                print("# swept %s %s in %.1fs -> %s"
                      % (name, res.bucket, time.perf_counter() - t0,
                         json.dumps(res.best, sort_keys=True)),
                      file=sys.stderr)
                results.append(res)
        finally:
            t.cleanup()
    return results, failures


# -- selftest -----------------------------------------------------------------


class _ToyTunable:
    """Deterministic synthetic tunable: cost is a pure function of the
    config, so the search machinery (pruning, ranking, persistence) can be
    asserted bit-for-bit without device timing noise."""

    kernel = "selftest.toy"

    def default_shapes(self):
        return [{"n": 64}]

    def bucket(self, shape):
        return "n%d" % shape["n"]

    def candidates(self, shape):
        return [{"x": x} for x in (1, 2, 3, 4, 5)]

    def default_config(self, shape):
        return {"x": 1}

    def cost(self, shape, config):
        # x=5 is "memory-blown": the prune path must fire deterministically
        return {"vmem_bytes": 1 << 40} if config["x"] == 5 else {}

    def build(self, shape, config):
        return (lambda: config["x"]), ()

    def cleanup(self):
        pass


def _toy_measure(fn, args, config=None, **_kw):
    # deterministic "measurement": best at x=3, tie between 2 and 4
    return float(abs(config["x"] - 3) + 1)


def selftest() -> int:
    import tempfile

    t0 = time.time()
    from paddle_tpu import tune
    from paddle_tpu.monitor import metrics as mx
    from paddle_tpu.tune import table as tt

    mx.enable()
    mx.reset()
    with tempfile.TemporaryDirectory() as td:
        tpath = os.path.join(td, "autotune_table.json")
        prev = os.environ.get("PADDLE_TPU_TUNE_TABLE")
        os.environ["PADDLE_TPU_TUNE_TABLE"] = tpath
        try:
            # 1. shipped seeds: the hand-tuned v5e entries answer cold
            cfg, src = tune.lookup("flash_attention",
                                   tune.bucket_seq(8192, 8192),
                                   device="tpu-v5e")
            assert src == "shipped" and cfg["block_q"] == 512 \
                and cfg["block_k"] == 512, (cfg, src)
            cfg, src = tune.lookup("sparse_adam", tune.bucket_rows(4096, 64),
                                   device="tpu-v5e")
            assert src == "shipped" and cfg["block"] == 128, (cfg, src)
            cfg, src = tune.lookup("paged_attention",
                                   tune.bucket_ctx(2048, 512),
                                   device="tpu-v5e")
            assert src == "shipped" and cfg["block_pages"] == 8, (cfg, src)
            # unknown device -> default (hardcoded fallbacks stay in charge)
            cfg, src = tune.lookup("flash_attention",
                                   tune.bucket_seq(8192, 8192),
                                   device="made-up-chip")
            assert cfg is None and src == "default"

            # 2. determinism: same fixed candidate list + deterministic
            #    measure twice -> byte-identical table entries, best=x3,
            #    the blown candidate pruned not timed
            toy = _ToyTunable()
            r1 = tune.search(toy, reps=3, measure=_toy_measure)
            e1 = tt.read_entries(tpath)
            r2 = tune.search(toy, reps=3, measure=_toy_measure)
            e2 = tt.read_entries(tpath)
            assert r1.best == r2.best == {"x": 3}, (r1.best, r2.best)
            assert e1 == e2 and e1, "table not deterministic"
            assert any("pruned" in row for row in r1.rows), r1.rows
            assert r1.default_ms == 3.0 and r1.best_ms == 1.0

            # 3. round-trip: the tuned entry answers lookups (and wins
            #    over shipped/default)
            cfg, src = tune.lookup("selftest.toy", "n64")
            assert src == "tuned" and cfg == {"x": 3}, (cfg, src)

            # 4. a REAL micro-sweep through the Pallas interpreter: tiny
            #    sparse-adam candidate space, then the rerouted
            #    _block_size picks the tuned winner up
            sa = tune.get_tunable("sparse_adam")
            shape = dict(vocab=64, dim=8, n=24)
            res = tune.search(sa, shape,
                              candidates=[{"block": 8}, {"block": 16}],
                              reps=1, warmup=1)
            # search() appends the default config (block 24 here) so every
            # sweep carries a before/after — any of the three may win
            assert res.best["block"] in (8, 16, 24) and res.written_path
            from paddle_tpu.ops.pallas_kernels.sparse_adam import _block_size

            got = _block_size(None, shape["n"], shape["dim"])
            assert got == res.best["block"], (got, res.best)

            # 4b. same mechanism for the paged-attention wave width: a
            #     tiny interpret-mode sweep, then the kernel's trace-time
            #     _block_pages serves the tuned winner
            pa = tune.get_tunable("paged_attention")
            pshape = dict(slots=2, max_ctx=32, page_size=8, n_head=2,
                          d_head=8)
            pres = tune.search(pa, pshape,
                               candidates=[{"block_pages": 1},
                                           {"block_pages": 2}],
                               reps=1, warmup=1)
            assert pres.best["block_pages"] in (1, 2, 4) \
                and pres.written_path, pres.best
            from paddle_tpu.ops.pallas_kernels.paged_attention import \
                _block_pages

            got = _block_pages(None, 8, 4, 32, 16)
            assert got == pres.best["block_pages"], (got, pres.best)

            # 5. corrupt table: logs once, falls back — never raises
            with open(tpath, "w") as f:
                f.write('{"format": "paddle_tpu.tune/1", "entries": {tor')
            cfg, src = tune.lookup("selftest.toy", "n64")
            assert cfg is None and src == "default", (cfg, src)
            from paddle_tpu.ops.attention_ops import _tuned_block_sizes

            bs = _tuned_block_sizes(8192, 8192)  # must not raise
            assert bs.block_q == 512  # hardcoded fallback preserved
            # ...and the paged-attention lookup ladder degrades the same
            # way: corrupt table -> the analytic VMEM-budget default
            got = _block_pages(None, 8, 4, 32, 16)
            assert got == 4, got  # _default_block_pages(8, 4, 16)

            # 6. the autotune/* instruments all exist and counted the above
            snap = mx.snapshot()
            for name in ("autotune/lookups", "autotune/lookup_tuned",
                         "autotune/lookup_shipped", "autotune/lookup_default",
                         "autotune/sweeps", "autotune/candidates_timed",
                         "autotune/candidates_pruned",
                         "autotune/candidates_failed",
                         "autotune/table_writes", "autotune/table_errors"):
                assert name in snap, "missing instrument %s" % name
            assert snap["autotune/sweeps"]["value"] == 4
            assert snap["autotune/lookup_shipped"]["value"] >= 2
            assert snap["autotune/lookup_tuned"]["value"] >= 2
            assert snap["autotune/candidates_pruned"]["value"] >= 2
            assert snap["autotune/table_errors"]["value"] >= 1
            assert snap["autotune/table_writes"]["value"] >= 3
        finally:
            if prev is None:
                os.environ.pop("PADDLE_TPU_TUNE_TABLE", None)
            else:
                os.environ["PADDLE_TPU_TUNE_TABLE"] = prev
    dt = time.time() - t0
    # two interpret-mode kernel micro-sweeps (sparse_adam, paged_attention)
    # dominate; the Pallas interpreter traces slowly but honestly
    assert dt < 10.0, "selftest too slow: %.1fs" % dt
    print("autotune selftest: OK (%.1fs): shipped v5e seeds, deterministic "
          "search, tuned-table round-trip + reroute (sparse_adam + "
          "paged_attention), corrupt-table fallback, autotune/* counters"
          % dt)
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0
    if "--selftest" in argv:
        return selftest()
    from paddle_tpu import tune

    if "--list" in argv:
        for name in tune.registered_tunables():
            print(name)
        return 0

    def opt(name, default=None):
        if name in argv:
            i = argv.index(name)
            if i + 1 >= len(argv):
                print("%s requires a value" % name, file=sys.stderr)
                raise SystemExit(2)
            argv.pop(i)
            return argv.pop(i)
        return default

    reps = int(opt("--reps", "5"))
    warmup = int(opt("--warmup", "1"))
    table_file = opt("--table")
    model_dir = opt("--model")
    kernel = opt("--kernel")
    persist = "--dry-run" not in argv
    argv = [a for a in argv if a not in ("--all", "--dry-run")]
    if argv:
        print("unknown arguments: %s" % " ".join(argv), file=sys.stderr)
        return 2
    if kernel:
        kernels = [kernel]
    elif model_dir:
        kernels = ["pass_gates"]
    else:
        kernels = tune.registered_tunables()
    results, failures = run_sweeps(kernels, reps=reps, warmup=warmup,
                                   persist=persist, table_file=table_file,
                                   model_dir=model_dir)
    print_results(results)
    for name, shape, e in failures:
        print("SWEEP FAILED %s %r: %s: %s"
              % (name, shape, type(e).__name__, e), file=sys.stderr)
    # machine tail: the sweep digest as one JSON line (bench-style),
    # carrying the run_id (+ ledger record when PADDLE_TPU_RUN_LEDGER is
    # armed) so tuned-table provenance joins the perf trend data
    tail = {
        "autotune": [r.to_dict() for r in results],
        "failures": ["%s %r: %r" % (n, s, str(e)[:120])
                     for n, s, e in failures],
    }
    try:
        from paddle_tpu.monitor import runlog

        configs = {}
        for r in results:
            row = {}
            if r.best_ms is not None:
                row["best_ms"] = r.best_ms
            if r.speedup_vs_default is not None:
                row["speedup_vs_default"] = r.speedup_vs_default
            if row:
                configs["%s/%s" % (r.kernel, r.bucket)] = row
        runlog.record_run("autotune", configs,
                          extra={"n_failures": len(failures)})
        tail.update(runlog.tail_info())
    except Exception as e:
        tail["run_ledger_error"] = repr(e)[:80]
    print(json.dumps(tail, default=str))
    return 1 if failures and not results else 0


if __name__ == "__main__":
    sys.exit(main())
