"""Merge a fleet trace dir into ONE clock-aligned Perfetto timeline.

    python -m tools.fleet_trace <trace_dir> [--out FILE] [--validate]
                                [--slack-us N]
        Load ``fleet_manifest.json`` + every fragment a traced fleet run
        left behind (the router's spans plus one fragment per worker
        spawn), shift each worker's timestamps by its handshake-measured
        clock offset, and write one Chrome trace with per-process tracks
        (``fleet router``, ``fleet worker replica <i>``) — open it in
        Perfetto and a request's queued wait, dispatch, worker-side
        serve/prefill/decode, kill, and requeued replay all line up on
        one ruler. Default --out: ``<trace_dir>/merged.json``.

        ``--validate`` additionally runs the fleet-level invariant
        checker (the cross-process analogue of serving.trace.
        validate_request_spans): every traced request must join into one
        well-nested tree — >=1 queued span, exactly one terminal,
        non-overlapping ordered attempts, every worker span inside its
        attempt window within ``--slack-us`` (default 20000; this is the
        clock-correction error bound, so an unaligned merge fails here).
        Orphans a SIGKILL left open are closed synthetically and tagged.

    python -m tools.fleet_trace --selftest
        <10s, JAX_PLATFORMS=cpu: spins a 2-replica process-mode sim
        fleet with tracing + event log armed and a 3s clock skew
        injected into the workers (PADDLE_TPU_TRACE_CLOCK_SKEW_US),
        SIGKILLs one worker mid-traffic, then asserts: the handshake
        recovered the injected offset; the merge + --validate pass; the
        killed attempt 1 and requeued attempt 2 join on one trace_id;
        the SIGKILLed worker's missing fragment is a flagged problem,
        not a failure; the merged doc round-trips through
        tracer.load_spans; and the fleet event log carries
        spawn/kill_detected/requeue joined on one run_id. The
        smoke-gate entry (ROADMAP).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def merge(trace_dir: str, out_path: str = None) -> dict:
    """Merge + write; returns a digest (span/fragment/problem counts)."""
    from paddle_tpu.fleet import trace as ftrace
    from paddle_tpu.monitor import tracer

    spans, manifest, problems = ftrace.load_fragments(trace_dir)
    if out_path is None:
        out_path = os.path.join(trace_dir, "merged.json")
    doc = tracer.to_chrome_trace(spans,
                                 process_names=ftrace.process_names(manifest))
    with open(out_path, "w") as f:
        json.dump(doc, f)
    return {"out": out_path, "spans": len(spans),
            "fragments": (1 if (manifest.get("router") or {}).get("file")
                          else 0) + len(manifest.get("workers") or []),
            "run_id": manifest.get("run_id"),
            "problems": problems,
            "offsets_us": {
                "r%(replica)s_g%(gen)s" % e: e.get("offset_us")
                for e in manifest.get("workers") or []}}


def validate(trace_dir: str, slack_us: int = 20000) -> dict:
    """Merge in memory and run the fleet invariant checker; returns
    {trace_id: digest} plus the ``_meta`` entry."""
    from paddle_tpu.fleet import trace as ftrace

    spans, _, _ = ftrace.load_fragments(trace_dir)
    return ftrace.validate_fleet_spans(spans, slack_us=slack_us)


# -- selftest -----------------------------------------------------------------

_SKEW_US = 3_000_000


def _drill(td: str) -> dict:
    """One traced process-mode fleet run with a mid-traffic SIGKILL;
    returns paths + the router's replica clock measurements."""
    from paddle_tpu.fleet import FleetConfig, Router

    trace_dir = os.path.join(td, "trace")
    event_log = os.path.join(td, "fleet_events.jsonl")
    router = Router(FleetConfig(
        replicas=2, mode="process", affinity="round_robin",
        engine_spec={"engine": "sim", "sim": {"slots": 2, "step_ms": 3.0}},
        max_outstanding=4, trace_dir=trace_dir, event_log=event_log))
    offsets = {rep.index: rep.clock_offset_us for rep in router._replicas}
    rtts = {rep.index: rep.clock_rtt_us for rep in router._replicas}
    try:
        frs = [router.submit([1, 2, i], 16) for i in range(8)]
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline \
                and not router._replicas[0].inflight:
            router.pump()
            time.sleep(0.002)
        assert router._replicas[0].inflight, "no traffic reached the victim"
        router._replicas[0].kill()
        assert router.wait_all(30.0), router.accounting()
        acc = router.accounting()
        assert set(acc.values()) == {"finished"}, acc
        assert all(f.tokens for f in frs)
    finally:
        router.close()
    return {"trace_dir": trace_dir, "event_log": event_log,
            "offsets": offsets, "rtts": rtts}


def selftest() -> int:
    t0 = time.perf_counter()
    # import the tracer BEFORE arming the skew: the skew knob is read at
    # tracer import, so only the worker processes (fresh interpreters
    # inheriting the env) run 3s fast — exactly the cross-host clock
    # disagreement the handshake + merge must correct
    from paddle_tpu.monitor import tracer  # noqa: F401
    from paddle_tpu.fleet.events import read_events

    prev = os.environ.get("PADDLE_TPU_TRACE_CLOCK_SKEW_US")
    os.environ["PADDLE_TPU_TRACE_CLOCK_SKEW_US"] = str(_SKEW_US)
    try:
        with tempfile.TemporaryDirectory() as td:
            run = _drill(td)

            # 1. the handshake recovered the injected skew (tolerance is
            # generous vs the ~1ms observed RTTs; the merge slack below
            # is the bound that actually matters)
            for idx, off in run["offsets"].items():
                assert abs(off - _SKEW_US) < 250_000, \
                    "replica %d offset %dus vs injected %dus (rtt %dus)" \
                    % (idx, off, _SKEW_US, run["rtts"][idx])

            # 2. merge: one timeline, the SIGKILLed worker's fragment is
            # a flagged hole, everything else loads
            digest = merge(run["trace_dir"])
            assert digest["spans"] > 0 and digest["fragments"] >= 3
            missing = [p for p in digest["problems"]
                       if p["problem"] == "missing"]
            assert len(missing) == 1 and missing[0]["replica"] == 0, \
                digest["problems"]

            # 3. validate: well-nested cross-process trees; the killed
            # attempt 1 is closed+tagged and attempt 2 of the SAME
            # trace_id finished. Worker spans sit inside their attempt
            # windows within the default slack — with a 3s injected skew
            # this only holds because the offsets were applied.
            digests = validate(run["trace_dir"])
            meta = digests.pop("_meta")
            assert meta["requests"] == 8, meta
            replayed = {t: d for t, d in digests.items() if d["killed"]}
            assert replayed, "SIGKILL mid-traffic produced no killed attempt"
            for tid, d in replayed.items():
                assert d["state"] == "finished", (tid, d)
                assert d["killed"][0] == 1 and d["attempts"][-1] >= 2, \
                    (tid, d)
                assert d["outcomes"][d["attempts"][-1]] == "finished", \
                    (tid, d)
            joined = [d for d in digests.values() if d["worker_spans"] > 0]
            assert joined, "no worker-side spans joined the merged tree"

            # 4. the merged artifact is a loadable Chrome trace that
            # round-trips through the tracer's reader
            from tools.dump_metrics import validate_chrome_trace

            with open(digest["out"]) as f:
                doc = json.load(f)
            validate_chrome_trace(doc)
            spans_back = tracer.load_spans(digest["out"])
            assert len(spans_back) >= digest["spans"]

            # 5. event log: lifecycle story joined on one run_id
            evs = read_events(run["event_log"])
            kinds = {e["kind"] for e in evs}
            assert {"fleet_start", "spawn", "kill_detected", "requeue",
                    "restart", "fleet_stop"} <= kinds, kinds
            assert len({e["run_id"] for e in evs}) == 1
            kill = next(e for e in evs if e["kind"] == "kill_detected")
            assert kill["replica"] == 0 and kill["lost"] >= 1, kill
    finally:
        if prev is None:
            os.environ.pop("PADDLE_TPU_TRACE_CLOCK_SKEW_US", None)
        else:
            os.environ["PADDLE_TPU_TRACE_CLOCK_SKEW_US"] = prev

    print("fleet_trace selftest: OK (%.1fs)  offsets %s (injected %dus), "
          "%d spans merged, killed attempt 1 -> finished attempt 2 on %d "
          "request(s)"
          % (time.perf_counter() - t0,
             {i: o for i, o in run["offsets"].items()}, _SKEW_US,
             digest["spans"], len(replayed)))
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0
    if argv and argv[0] == "--selftest":
        return selftest()

    def opt(name, default=None):
        if name in argv:
            i = argv.index(name)
            argv.pop(i)
            return argv.pop(i)
        return default

    out = opt("--out")
    slack_us = int(opt("--slack-us", "20000"))
    do_validate = "--validate" in argv
    if do_validate:
        argv.remove("--validate")
    if len(argv) != 1:
        print("usage: python -m tools.fleet_trace <trace_dir> [--out FILE] "
              "[--validate] [--slack-us N]", file=sys.stderr)
        return 2
    trace_dir = argv[0]
    digest = merge(trace_dir, out)
    if do_validate:
        digests = validate(trace_dir, slack_us=slack_us)
        meta = digests.pop("_meta")
        digest["validated"] = {
            "requests": meta["requests"],
            "synthetic_closures": meta["synthetic_closures"],
            "states": {},
            "replayed": sorted(t for t, d in digests.items()
                               if len(d["attempts"]) > 1),
        }
        for d in digests.values():
            st = digest["validated"]["states"]
            st[d["state"]] = st.get(d["state"], 0) + 1
    print(json.dumps(digest, indent=1, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
