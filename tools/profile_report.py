"""Per-op roofline report for a compiled paddle_tpu step.

The device-profile twin of tools/dump_metrics.py / tools/dump_program.py
(monitor/device.py is the library; this renders it):

    python -m tools.profile_report
        AOT-compile the canned MLP train step (the diag_overhead.py probe
        shape) and print the per-op flops/bytes/%-of-step roofline table
        plus the compiled step's measured cost_analysis/memory_analysis
        totals.

    python -m tools.profile_report --model DIR [--batch N]
        Same, over a saved inference model (io.load_inference_model).

    python -m tools.profile_report bench.json
        Render the ``device_profile`` section a bench.py run embedded in
        its JSON (no recompilation, works off-host).

    python -m tools.profile_report --selftest
        Exercise the whole path in-process on CPU (<5s) and exit 0/1 —
        a CI smoke gate alongside the dump_metrics/dump_program selftests.

Reading the table: ``flops``/``bytes`` are ANALYTIC first-order rows from
static Program shapes — attribution weights that apportion the step, not a
simulator. The measured truth is the compiled totals up top (XLA fuses
across op boundaries). ``intensity`` = flops/byte decides which side of
the roofline an op lives on: below the device's flops/byte ridge point it
is HBM-bound (optimize traffic), above it compute-bound (optimize flops).
``slot`` is the op's position in the SOURCE program — identical to the
``<slot>:<type>`` named scopes in HLO/xprof and to numerics-watchdog
reports, stable under the trace-time optimizer's DCE/CSE renumbering.
"""

from __future__ import annotations

import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _fmt_si(v: float) -> str:
    for div, suf in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(v) >= div:
            return "%.2f%s" % (v / div, suf)
    return "%.0f" % v


def render(report: dict, top: int = 0) -> str:
    """Text table for a ``monitor.device.step_report`` dict (also the
    bench-JSON ``device_profile`` section)."""
    lines = []
    cost = report.get("cost") or {}
    mem = report.get("memory") or {}
    if cost:
        lines.append("measured (XLA cost_analysis, whole compiled step):")
        for k in ("flops", "bytes_accessed", "transcendentals"):
            if k in cost:
                lines.append("  %-22s %s" % (k, _fmt_si(cost[k])))
    if mem:
        lines.append("measured (XLA memory_analysis):")
        for k in ("argument_bytes", "output_bytes", "temp_bytes",
                  "peak_hbm_bytes"):
            if k in mem:
                lines.append("  %-22s %s" % (k, _fmt_si(mem[k])))
    rows = report.get("op_costs") or []
    if top:
        rows = rows[:top]
    lines.append("analytic per-op attribution (%d op(s), total %s flops):"
                 % (report.get("n_ops", len(rows)),
                    _fmt_si(report.get("analytic_total_flops", 0.0))))
    lines.append("%5s %-28s %10s %10s %10s %7s %7s  %s"
                 % ("slot", "type", "flops", "bytes", "flops/B",
                    "%step", "cum%", "out"))
    cum = 0.0
    for r in rows:
        cum += r.get("flops_frac", 0.0)
        lines.append("%5d %-28s %10s %10s %10.2f %6.1f%% %6.1f%%  %s"
                     % (r["slot"], r["type"], _fmt_si(r["flops"]),
                        _fmt_si(r["bytes"]), r["intensity"],
                        100 * r.get("flops_frac", 0.0), 100 * cum,
                        r.get("out", "")))
    return "\n".join(lines)


def _demo_mlp(fluid):
    """The canned MLP train step (same family as diag_overhead.py's
    probe): fc/relu x2 + softmax_with_cross_entropy + SGD."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[32])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=64, act="relu")
        logits = fluid.layers.fc(h, size=10)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def report_program(main, startup, loss_name, feed_spec, batch: int) -> dict:
    """AOT-compile the (program, feed-spec) step and build the full
    device-profile report (measured totals + analytic rows + scope
    coverage of the lowered HLO)."""
    import paddle_tpu as fluid
    from paddle_tpu.monitor import device as dev

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        if startup is not None:
            exe.run(startup)
        compiled = exe.prepare(main, feed=feed_spec,
                               fetch_list=[loss_name] if loss_name else [])
    aot = getattr(compiled, "_aot", None)
    rep = dev.step_report(compiled.program, aot, batch_size=batch)
    lowered = getattr(compiled, "_lowered", None)
    try:
        if lowered is not None:
            rep["scope_coverage"] = dev.op_scope_coverage(
                dev.lowered_scope_text(lowered))
        elif aot is not None:
            rep["scope_coverage"] = dev.op_scope_coverage(aot.as_text())
    except Exception:
        pass
    return rep


def _run_demo(batch: int = 16) -> dict:
    import paddle_tpu as fluid

    main, startup, loss = _demo_mlp(fluid)
    return report_program(
        main, startup, loss.name,
        {"x": ((batch, 32), "float32"), "y": ((batch, 1), "int64")}, batch)


def _run_model(model_dir: str, batch: int) -> dict:
    import paddle_tpu as fluid
    from paddle_tpu import io

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        prog, feed_names, fetch_targets = io.load_inference_model(
            model_dir, exe)
        block = prog.global_block
        feed_spec = {}
        for n in feed_names:
            v = block.var(n)
            shape = tuple(batch if (d or 0) < 0 else d
                          for d in (v.shape or ()))
            feed_spec[n] = (shape, str(v.dtype))
        compiled = exe.prepare(
            prog, feed=feed_spec,
            fetch_list=[t.name for t in fetch_targets])
    from paddle_tpu.monitor import device as dev

    return dev.step_report(compiled.program, getattr(compiled, "_aot", None),
                           batch_size=batch)


def selftest() -> int:
    import time

    t0 = time.time()
    from paddle_tpu.monitor import device as dev, metrics as mx

    mx.enable()
    rep = _run_demo(batch=8)
    # analytic rows exist and the matmuls dominate as they must in an MLP
    rows = rep["op_costs"]
    assert rows, "no analytic op rows"
    assert any(r["type"] in ("mul", "matmul") and r["flops"] > 0
               for r in rows), "matmul rows missing flops"
    # fracs are rounded to 4 decimals in the report, so sum within ~n*5e-5
    assert abs(sum(r["flops_frac"] for r in rows) - 1.0) < 1e-2
    # measured compiled totals came back on CPU
    assert rep.get("cost", {}).get("flops", 0) > 0, "cost_analysis empty"
    assert rep.get("memory", {}).get("peak_hbm_bytes", 0) > 0
    # the <slot>:<type> named scopes survived into the lowered HLO
    cov = rep.get("scope_coverage") or {}
    assert cov, "no named scopes in compiled HLO"
    assert any(k.split(":", 1)[1] in ("mul", "matmul") for k in cov), cov
    # gauges mirrored by the prepare() path
    snap = mx.snapshot()
    assert snap.get("device_profile/flops", {}).get("value", 0) > 0
    assert snap.get("device_profile/peak_hbm_bytes", {}).get("value", 0) > 0
    txt = render(rep, top=12)
    assert "slot" in txt and "%step" in txt
    # renders from a bench-JSON-shaped dict too (round-trip through json)
    render(json.loads(json.dumps(rep)))
    dt = time.time() - t0
    assert dt < 5.0, "selftest too slow: %.1fs" % dt
    print("profile_report selftest: OK (%d rows, %.1fs)" % (len(rows), dt))
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0
    if "--selftest" in argv:
        return selftest()
    batch = 16
    if "--batch" in argv:
        i = argv.index("--batch")
        batch = int(argv[i + 1])
        del argv[i:i + 2]
    top = 0
    if "--top" in argv:
        i = argv.index("--top")
        top = int(argv[i + 1])
        del argv[i:i + 2]
    if "--model" in argv:
        rep = _run_model(argv[argv.index("--model") + 1], batch)
    elif argv and os.path.isfile(argv[0]):
        with open(argv[0]) as f:
            doc = json.load(f)
        rep = doc.get("device_profile", doc)
        if not rep.get("op_costs"):
            print("no device_profile section in %s" % argv[0],
                  file=sys.stderr)
            return 1
    else:
        rep = _run_demo(batch)
    print(render(rep, top=top))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # e.g. piped into head
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
