"""AOT compile-cache warmup (the TVM "compile once, deploy many" leg).

Builds a named model's train program and ahead-of-time compiles its step
via ``Executor.prepare`` — ``jax.jit(...).lower().compile()`` — WITHOUT
running a single step. With ``PADDLE_TPU_COMPILE_CACHE=<dir>`` set (see
``paddle_tpu/compile_cache.py``), the XLA executable lands in the
persistent on-disk cache, so the real training/bench job that follows (same
program, same shapes, same jaxlib) starts with a cache hit instead of a
multi-minute compile.

    PADDLE_TPU_COMPILE_CACHE=/var/cache/xla \\
        python -m tools.warmup --model transformer --batch 64 --seq 256

    python -m tools.warmup --model mlp          # CPU smoke (<5s)

Exits 0 on success and prints the compile wall time plus the process's
``compile_cache/hit|miss`` counters — run it twice to see the second
invocation flip to a hit.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _specs(**shapes):
    """name -> (shape, dtype) feed spec dict for Executor.prepare."""
    return {n: (tuple(shape), dtype) for n, (shape, dtype) in shapes.items()}


def build_mlp(args):
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[64])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=64, act="relu")
        logits = fluid.layers.fc(h, size=10)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(1e-3).minimize(loss)
    b = args.batch
    return main, startup, loss, _specs(
        x=((b, 64), "float32"), y=((b, 1), "int64"))


def build_transformer(args):
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tfm

    b, s, v = args.batch, args.seq, args.vocab
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data("src", shape=[s], dtype="int64")
        trg = fluid.layers.data("trg", shape=[s], dtype="int64")
        lbl = fluid.layers.data("lbl", shape=[s, 1], dtype="int64")
        smask = fluid.layers.data("smask", shape=[s], dtype="float32")
        tmask = fluid.layers.data("tmask", shape=[s], dtype="float32")
        _, loss = tfm.transformer_base(
            src, trg, lbl, smask, tmask, src_vocab_size=v, trg_vocab_size=v,
            max_length=s, dropout_rate=0.1)
        opt = fluid.optimizer.Adam(learning_rate=1e-4)
        if args.amp:
            opt = fluid.amp.decorate(opt)
        opt.minimize(loss)
    return main, startup, loss, _specs(
        src=((b, s), "int64"), trg=((b, s), "int64"), lbl=((b, s, 1), "int64"),
        smask=((b, s), "float32"), tmask=((b, s), "float32"))


def build_resnet50(args):
    import paddle_tpu as fluid
    from paddle_tpu.models import resnet as rn

    b, im = args.batch, args.image
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[3, im, im])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        _, loss, _ = rn.resnet50(img, label, class_num=1000)
        opt = fluid.optimizer.Momentum(0.1, 0.9)
        if args.amp:
            opt = fluid.amp.decorate(opt)
        opt.minimize(loss)
    return main, startup, loss, _specs(
        img=((b, 3, im, im), "float32"), label=((b, 1), "int64"))


def build_bert(args):
    import paddle_tpu as fluid
    from paddle_tpu.models import bert

    b, s, m = args.batch, args.seq, args.n_mask
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[s], dtype="int64")
        pos = fluid.layers.data("pos", shape=[s], dtype="int64")
        sent = fluid.layers.data("sent", shape=[s], dtype="int64")
        mask = fluid.layers.data("mask", shape=[s], dtype="float32")
        mpos = fluid.layers.data("mpos", shape=[m], dtype="int64")
        mlbl = fluid.layers.data("mlbl", shape=[1], dtype="int64")
        nsp = fluid.layers.data("nsp", shape=[1], dtype="int64")
        loss, _, _ = bert.bert_pretrain(ids, pos, sent, mask, mpos, mlbl, nsp,
                                        **bert.BERT_BASE_CONFIG)
        opt = fluid.optimizer.Adam(learning_rate=1e-4)
        if args.amp:
            opt = fluid.amp.decorate(opt)
        opt.minimize(loss)
    return main, startup, loss, _specs(
        ids=((b, s), "int64"), pos=((b, s), "int64"), sent=((b, s), "int64"),
        mask=((b, s), "float32"), mpos=((b, m), "int64"),
        mlbl=((b * m, 1), "int64"), nsp=((b, 1), "int64"))


BUILDERS = {
    "mlp": build_mlp,
    "transformer": build_transformer,
    "resnet50": build_resnet50,
    "bert": build_bert,
}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools.warmup",
        description="AOT-compile a model's train step into the persistent "
                    "XLA compile cache (PADDLE_TPU_COMPILE_CACHE).")
    p.add_argument("--model", choices=sorted(BUILDERS), default="mlp")
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--seq", type=int, default=256)
    p.add_argument("--vocab", type=int, default=30000)
    p.add_argument("--image", type=int, default=224)
    p.add_argument("--n-mask", type=int, default=20)
    p.add_argument("--no-amp", dest="amp", action="store_false",
                   help="skip bf16 AMP decoration (default: on, matching "
                        "bench.py shapes so the bench gets the cache hit)")
    args = p.parse_args(argv)

    import paddle_tpu as fluid
    from paddle_tpu import compile_cache, monitor

    if not compile_cache.is_configured():
        print("warning: PADDLE_TPU_COMPILE_CACHE is not set — compiling "
              "without a persistent cache (warmup is then pointless)",
              file=sys.stderr)

    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main_prog, startup, loss, feed_specs = BUILDERS[args.model](args)
            exe = fluid.Executor(fluid.TPUPlace(0)
                                 if fluid.is_compiled_with_tpu()
                                 else fluid.CPUPlace())
            exe.run(startup)
            t0 = time.perf_counter()
            exe.prepare(main_prog, feed=feed_specs, fetch_list=[loss])
            dt = time.perf_counter() - t0

    snap = monitor.snapshot()
    hits = int(snap["compile_cache/hit"]["value"])
    misses = int(snap["compile_cache/miss"]["value"])
    print("warmup[%s]: AOT compile %.2fs  compile_cache hit=%d miss=%d%s"
          % (args.model, dt, hits, misses,
             "" if compile_cache.is_configured() else "  (cache OFF)"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
