"""Assert measured collective traffic against the checked-in budgets.

The enforcement face of ``paddle_tpu.monitor.budgets``: drives the three
explicitly-accounted collective legs — the gpipe ppermute schedule, the
ring-attention K/V rotation (forward AND backward, accumulators included)
and the CTR sparse-row all_to_all exchange — on an 8-device virtual CPU
mesh, reads the ``collectives/*`` counters they record at trace time, and
asserts each against its closed-form bytes-per-step budget.

    python -m tools.check_budgets --selftest
        <5s, no TPU: run all legs, assert measured == budget exactly
        (trace-time accounting is shape math — any drift is a regression),
        and prove a deliberately tightened budget fails loudly. The
        ROADMAP smoke gate closing item 4's "collective-traffic budgets"
        residue.

    python -m tools.check_budgets --table
        Print the budget table (legs, counters, closed forms).

``dryrun_multichip`` runs the same asserts inline against its own legs, so
the MULTICHIP JSON's collective volumes are budget-checked, not just
printed.
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

N_DEV = 8


def _ensure_virtual_devices(n: int = N_DEV) -> None:
    """Force an n-device virtual CPU platform — must run BEFORE any jax
    backend initializes (XLA parses XLA_FLAGS once per process). An
    existing smaller device-count flag is REPLACED, not kept — keeping it
    would leave the selftest under-provisioned."""
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    want = "--xla_force_host_platform_device_count=%d" % n
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--?xla_force_host_platform_device_count=\d+",
                       want, flags)
    else:
        flags += " " + want
    os.environ["XLA_FLAGS"] = flags.strip()
    os.environ["JAX_PLATFORMS"] = "cpu"


def _coll_bytes(op: str) -> int:
    from paddle_tpu.monitor import metrics as mx

    snap = mx.snapshot().get("collectives/%s/bytes" % op)
    return int(snap["value"]) if snap else 0


def run_gpipe_leg() -> dict:
    """Trace one gpipe training step (4 stages × 4 microbatches) and
    check the forward ppermute schedule against gpipe.fwd."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from paddle_tpu.monitor import budgets
    from paddle_tpu.parallel import pipeline_step, stack_stage_params

    s, mb, d_model = 4, 2, 16
    m = 4
    mesh = Mesh(np.array(jax.devices()[:s]), ("pipe",))
    rng = np.random.RandomState(0)

    def stage(params, x):
        w, b = params
        return jnp.tanh(x @ w + b)

    stages = [(jnp.asarray(rng.randn(d_model, d_model).astype("float32") * .3),
               jnp.zeros((d_model,), jnp.float32)) for _ in range(s)]
    stacked = stack_stage_params(stages)
    xs = jnp.asarray(rng.randn(m, mb, d_model).astype("float32"))
    ys = jnp.asarray(rng.randn(m, mb, d_model).astype("float32") * .1)
    step = jax.jit(pipeline_step(stage, lambda o, l: jnp.mean((o - l) ** 2),
                                 mesh, "pipe"))
    before = _coll_bytes("ppermute")
    loss, _ = step(stacked, xs, ys)
    assert np.isfinite(float(loss))
    measured = _coll_bytes("ppermute") - before
    act_bytes = mb * d_model * 4
    return budgets.check_budget("gpipe.fwd", measured,
                                microbatches=m, stages=s,
                                activation_bytes=act_bytes)


def run_ring_attention_leg() -> dict:
    """Forward-only then fwd+bwd ring attention; check fwd and bwd
    rotation volumes (f32 dK/dV accumulators included)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from paddle_tpu.monitor import budgets
    from paddle_tpu.parallel import ring_attention

    sp, b, h, s_loc, d = 4, 2, 2, 8, 8
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, h, s_loc * sp, d).astype("float32"))
    k, v = q + 0.1, q + 0.2
    block_elems = b * h * s_loc * d
    block_bytes = block_elems * 4

    before = _coll_bytes("ppermute")
    with mesh:
        out = ring_attention(q, k, v, mesh=mesh, axis_name="sp")
    assert np.isfinite(np.asarray(out)).all()
    fwd_rec = budgets.check_budget(
        "ring_attention.fwd", _coll_bytes("ppermute") - before,
        n_devices=sp, block_bytes=block_bytes)

    before = _coll_bytes("ppermute")
    with mesh:
        g = jax.grad(
            lambda q_, k_, v_: ring_attention(
                q_, k_, v_, mesh=mesh, axis_name="sp").sum())(q, k, v)
    assert np.isfinite(np.asarray(g)).all()
    # grad traces the custom-vjp fwd AND bwd: the measured delta covers both
    fwd_plus_bwd = _coll_bytes("ppermute") - before
    bwd_budget = budgets.budget_bytes("ring_attention.bwd", n_devices=sp,
                                      block_bytes=block_bytes,
                                      block_elems=block_elems)
    bwd_rec = budgets.check_budget(
        "ring_attention.bwd", fwd_plus_bwd - fwd_rec["budget_bytes"],
        n_devices=sp, block_bytes=block_bytes, block_elems=block_elems)
    assert bwd_rec["budget_bytes"] == bwd_budget
    return {"fwd": fwd_rec, "bwd": bwd_rec}


def run_ctr_routing_leg() -> dict:
    """One route_rows_to_shards exchange over the full 8-device axis."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.core.sparse import route_rows_to_shards
    from paddle_tpu.monitor import budgets
    from paddle_tpu.parallel._compat import shard_map

    n_shards, n_loc, dim = N_DEV, 16, 8
    V = 1024
    mesh = Mesh(np.array(jax.devices()[:n_shards]), ("model",))
    rng = np.random.RandomState(2)
    ids = jnp.asarray(rng.randint(0, V, n_shards * n_loc).astype("int32"))
    rows = jnp.asarray(
        rng.randn(n_shards * n_loc, dim).astype("float32"))

    def body(ids_loc, rows_loc):
        return route_rows_to_shards(ids_loc, rows_loc, n_shards,
                                    V // n_shards, "model", V)

    before = _coll_bytes("all_to_all")
    rid, rrows = shard_map(
        body, mesh=mesh, in_specs=(P("model"), P("model", None)),
        out_specs=(P("model"), P("model", None)))(ids, rows)
    assert np.asarray(rid).shape[0] == n_shards * n_shards * n_loc
    measured = _coll_bytes("all_to_all") - before
    return budgets.check_budget("ctr.row_routing", measured,
                                n_shards=n_shards, n_local=n_loc, dim=dim,
                                id_itemsize=4, row_itemsize=4)


def selftest() -> int:
    import time

    t0 = time.time()
    import jax

    if len(jax.devices()) < N_DEV:
        # backend initialized too small in-process: re-exec clean. The
        # child env gets the count flag force-replaced; the marker makes a
        # still-too-small child FAIL instead of recursing forever.
        if os.environ.get("_PADDLE_TPU_CHECK_BUDGETS_CHILD"):
            print("check_budgets: child still sees %d < %d devices — "
                  "XLA_FLAGS not honored; aborting"
                  % (len(jax.devices()), N_DEV), file=sys.stderr)
            return 1
        import subprocess

        env = dict(os.environ)
        env["_PADDLE_TPU_CHECK_BUDGETS_CHILD"] = "1"
        r = subprocess.run([sys.executable, "-m", "tools.check_budgets",
                            "--selftest"], env=env, cwd=_REPO)
        return r.returncode

    from paddle_tpu.monitor import budgets

    records = {
        "gpipe.fwd": run_gpipe_leg(),
        "ring_attention": run_ring_attention_leg(),
        "ctr.row_routing": run_ctr_routing_leg(),
    }
    flat = [records["gpipe.fwd"], records["ring_attention"]["fwd"],
            records["ring_attention"]["bwd"], records["ctr.row_routing"]]
    for rec in flat:
        # trace-time accounting is pure shape math: anything but EXACT
        # equality means an emission site or budget formula drifted
        assert rec["measured_bytes"] == rec["budget_bytes"], rec
        print("budget OK  %-20s %8d B == budget (%s)"
              % (rec["leg"], rec["measured_bytes"], rec["counter"]))

    # a deliberately tightened budget must fail LOUDLY, naming the leg
    rec = records["ctr.row_routing"]
    try:
        budgets.check_budget("ctr.row_routing", rec["measured_bytes"],
                             budget=rec["budget_bytes"] - 1)
        raise AssertionError("tightened budget did not trip")
    except budgets.CollectiveBudgetExceeded as e:
        assert "ctr.row_routing" in str(e), e
    print("check_budgets selftest: OK (%.1fs)" % (time.time() - t0))
    return 0


def print_table() -> int:
    from paddle_tpu.monitor.budgets import COLLECTIVE_BUDGETS

    for leg in sorted(COLLECTIVE_BUDGETS):
        spec = COLLECTIVE_BUDGETS[leg]
        print("%-20s %-32s params=%s\n  %s"
              % (leg, spec["counter"], ",".join(spec["params"]), spec["doc"]))
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0 if argv else 2
    if argv[0] == "--table":
        return print_table()
    if argv[0] == "--selftest":
        _ensure_virtual_devices()
        return selftest()
    print(__doc__.strip(), file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
