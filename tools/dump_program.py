"""Inspect a Program before/after the default trace-time optimizer.

The graph-pass twin of tools/dump_metrics.py:

    python -m tools.dump_program
        Print the canned demo program's op list (the same MLP-with-baggage
        probe benchmarks/diag_overhead.py --opt uses).

    python -m tools.dump_program --diff
        Run the default pipeline (PADDLE_TPU_OPT_LEVEL, default 1) pass by
        pass over the demo program and print, for each pass, the op-list
        delta it is responsible for — per-pass attribution of every removed,
        inserted, and rewritten op.

    python -m tools.dump_program --diff --model DIR
        Same, over a saved inference model (io.load_inference_model) instead
        of the canned demo.

    python -m tools.dump_program --selftest
        Assert the canned MLP program shrinks under the default pipeline
        (<2s, JAX_PLATFORMS=cpu) and exit 0/1 — a CI smoke gate alongside
        ``tools/dump_metrics --selftest``.
"""

from __future__ import annotations

import os
import sys
from collections import Counter

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def format_ops(program, prefix="  "):
    lines = []
    for i, op in enumerate(program.global_block.ops):
        ins = sorted(set(op.input_arg_names))
        outs = sorted(set(op.output_arg_names))
        lines.append("%s%3d: %-28s (%s) -> (%s)"
                     % (prefix, i, op.type, ", ".join(ins), ", ".join(outs)))
    return "\n".join(lines)


def _demo_program(fluid):
    """Canned MLP with typical optimizer fodder: an unfetched metrics
    branch (DCE), a constant chain (folding), a duplicated subexpression
    (CSE) and a primitive softmax+cross_entropy composition (pattern
    rewrite)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[32])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=32, act="relu")
        logits = fluid.layers.fc(h, size=10)
        probs = fluid.layers.softmax(logits)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(probs, y))
        fluid.layers.accuracy(fluid.layers.softmax(logits), y)  # dead branch
        c = fluid.layers.fill_constant([1], "float32", 4.0)
        c = fluid.layers.scale(c, scale=0.25)                   # folds to 1.0
        dup_a = fluid.layers.scale(h, scale=2.0)                # CSE pair...
        dup_b = fluid.layers.scale(h, scale=2.0)
        fluid.layers.elementwise_add(dup_a, dup_b)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _diff_counts(before_ops, after_ops):
    b, a = Counter(o.type for o in before_ops), Counter(o.type for o in after_ops)
    removed = {t: n for t, n in (b - a).items()}
    added = {t: n for t, n in (a - b).items()}
    return removed, added


def run_diff(program, scope, fetch_names, fluid) -> int:
    from paddle_tpu.core.pass_framework import get_pass
    from paddle_tpu.passes import analysis as A
    from paddle_tpu.passes.pipeline import (DEFAULT_PASS_NAMES, opt_level,
                                            pass_enabled)

    level = opt_level()
    print("PADDLE_TPU_OPT_LEVEL=%d" % level)
    print("before (%d ops):" % len(program.global_block.ops))
    print(format_ops(program))
    if level <= 0:
        print("\nopt level 0: pipeline disabled, nothing to diff")
        return 0

    work = program.clone()
    work._rng_table_n = getattr(program, "_rng_table_n",
                                len(program.global_block.ops) + 8)
    A.stamp_rng_slots(work)
    protected = A.protected_names(work, fetch_names)
    for name in DEFAULT_PASS_NAMES:
        if not pass_enabled(name):
            print("\n== %s: disabled via env gate" % name)
            continue
        if name == "conv_bn_fuse_pass" and scope is None:
            print("\n== %s: skipped (no scope)" % name)
            continue
        p = get_pass(name)
        p.set_attr("scope", scope)
        p.set_attr("fetch_names", tuple(fetch_names))
        p.set_attr("protected", set(protected))
        n_before = len(work.global_block.ops)
        before_ops = list(work.global_block.ops)
        work = p.apply(work)
        removed, added = _diff_counts(before_ops, work.global_block.ops)
        delta = len(work.global_block.ops) - n_before
        print("\n== %s: %d -> %d ops (%+d)"
              % (name, n_before, len(work.global_block.ops), delta))
        for t, n in sorted(removed.items()):
            print("   - %dx %s" % (n, t))
        for t, n in sorted(added.items()):
            print("   + %dx %s" % (n, t))
        if not removed and not added:
            print("   (no-op)")
    print("\nafter (%d ops):" % len(work.global_block.ops))
    print(format_ops(work))
    return 0


def selftest() -> int:
    os.environ.setdefault("PADDLE_TPU_OPT_LEVEL", "1")
    import paddle_tpu as fluid
    from paddle_tpu.passes.pipeline import optimize_program

    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            main, startup, loss = _demo_program(fluid)
            n_before = len(main.global_block.ops)
            opt = optimize_program(main, (loss.name,), fluid.global_scope())
            n_after = len(opt.global_block.ops)
            assert n_after < n_before, \
                "pipeline failed to shrink the canned MLP (%d -> %d)" % (
                    n_before, n_after)
            # the pipeline must be idempotent: a second application of the
            # default passes to its own output changes nothing
            opt2 = optimize_program(opt, (loss.name,), fluid.global_scope())
            sig = [(o.type, sorted(o.input_arg_names),
                    sorted(o.output_arg_names)) for o in opt.global_block.ops]
            sig2 = [(o.type, sorted(o.input_arg_names),
                     sorted(o.output_arg_names)) for o in opt2.global_block.ops]
            assert sig == sig2, "default pipeline is not idempotent"
            # source program untouched
            assert len(main.global_block.ops) == n_before
    print("dump_program selftest: OK (%d -> %d ops, idempotent)"
          % (n_before, n_after))
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--selftest" in argv:
        return selftest()

    import paddle_tpu as fluid

    model_dir = None
    if "--model" in argv:
        model_dir = argv[argv.index("--model") + 1]
    with fluid.unique_name.guard():
        with fluid.scope_guard(fluid.Scope()):
            if model_dir:
                exe = fluid.Executor(fluid.CPUPlace())
                program, feed_names, fetched = fluid.io.load_inference_model(
                    model_dir, exe)
                fetch_names = tuple(
                    f.name if hasattr(f, "name") else str(f) for f in fetched)
            else:
                program, startup, loss = _demo_program(fluid)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                fetch_names = (loss.name,)
            if "--diff" in argv:
                return run_diff(program, fluid.global_scope(), fetch_names,
                                fluid)
            print("%d ops:" % len(program.global_block.ops))
            print(format_ops(program))
    return 0


if __name__ == "__main__":
    sys.exit(main())
